"""Benchmark: admission throughput on the reference's baseline scenario.

Mirrors test/performance/scheduler/configs/baseline/generator.yaml from the
reference (kubernetes-sigs/kueue): 5 cohorts x 6 ClusterQueues, nominal 20
cpu + borrowingLimit 100 per CQ, reclaimWithinCohort=Any +
withinClusterQueue=LowerPriority, and per CQ 350 small (req 1, prio 50),
100 medium (req 5, prio 100), 50 large (req 20, prio 200) workloads with
200/500/1000 ms runtimes.

Differences from the reference harness, by design: all workloads are
submitted upfront and execution is simulated on a virtual clock (completion
is instantaneous when the scheduler is otherwise stuck), so the measured
wall time is pure scheduling compute — the framework's sustainable
admission throughput. The reference's derived number on this config is
~42.7 admissions/s (BASELINE.md); vs_baseline = ours / 42.7.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import argparse
import heapq
import json
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def build_scenario(scale: float):
    from kueue_tpu.api.constants import PreemptionPolicy
    from kueue_tpu.api.types import (
        ClusterQueue,
        ClusterQueuePreemption,
        Cohort,
        FlavorQuotas,
        LocalQueue,
        PodSet,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
        Workload,
    )
    from kueue_tpu.cache.cache import Cache
    from kueue_tpu.queue.manager import QueueManager

    cache = Cache()
    queues = QueueManager()
    cache.add_or_update_resource_flavor(ResourceFlavor(name="default"))

    classes = [
        ("small", int(350 * scale), 1000, 50, 0.2),
        ("medium", int(100 * scale), 5000, 100, 0.5),
        ("large", int(50 * scale), 20000, 200, 1.0),
    ]

    workloads = []
    t = 0.0
    for ci in range(5):
        cache.add_or_update_cohort(Cohort(name=f"cohort-{ci}"))
        for qi in range(6):
            cq_name = f"cq-{ci}-{qi}"
            cq = ClusterQueue(
                name=cq_name,
                cohort=f"cohort-{ci}",
                resource_groups=[
                    ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[
                            FlavorQuotas(
                                name="default",
                                resources={
                                    "cpu": ResourceQuota(
                                        nominal=20_000,
                                        borrowing_limit=100_000,
                                    )
                                },
                            )
                        ],
                    )
                ],
                preemption=ClusterQueuePreemption(
                    reclaim_within_cohort=PreemptionPolicy.ANY,
                    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                ),
            )
            cache.add_or_update_cluster_queue(cq)
            queues.add_cluster_queue(cq)
            lq = LocalQueue(name=f"lq-{cq_name}", cluster_queue=cq_name)
            cache.add_or_update_local_queue(lq)
            queues.add_local_queue(lq)
            for cls_name, count, req, prio, runtime_s in classes:
                for i in range(count):
                    t += 1.0
                    workloads.append(
                        (
                            Workload(
                                name=f"{cq_name}-{cls_name}-{i}",
                                queue_name=f"lq-{cq_name}",
                                pod_sets=[
                                    PodSet(
                                        name="main", count=1,
                                        requests={"cpu": req},
                                    )
                                ],
                                priority=prio,
                                creation_time=t,
                            ),
                            runtime_s,
                        )
                    )
    return cache, queues, workloads


def run(kind: str, scale: float) -> dict:
    from kueue_tpu.core.workload_info import is_evicted

    cache, queues, workloads = build_scenario(scale)
    if kind == "device":
        from kueue_tpu.models.driver import DeviceScheduler

        sched = DeviceScheduler(cache, queues)
    else:
        from kueue_tpu.scheduler.scheduler import Scheduler

        sched = Scheduler(cache, queues)

    runtime_of = {}
    for wl, runtime_s in workloads:
        assert queues.add_or_update_workload(wl)
        runtime_of[wl.key] = runtime_s

    n_total = len(workloads)
    vclock = 0.0
    completions = []  # (completes_at, key)
    running = {}
    finished = 0
    cycles = 0
    t_start = time.monotonic()

    while finished < n_total:
        result = sched.schedule()
        cycles += 1
        for key in result.admitted:
            heapq.heappush(completions, (vclock + runtime_of[key], key))
            running[key] = True
        for key in result.preempted:
            running.pop(key, None)

        if not result.admitted and not result.preempted:
            # Scheduler stuck: advance virtual time to the next completion.
            while completions and completions[0][1] not in running:
                heapq.heappop(completions)  # evicted; stale entry
            if not completions:
                if not result.head_keys:
                    log(f"DEADLOCK: finished={finished}/{n_total}")
                    break
                # heads exist but nothing runs/admits: keep cycling guard
                log(f"stall: finished={finished}/{n_total}")
                break
            vclock, key = heapq.heappop(completions)
            batch = [key]
            while completions and completions[0][0] <= vclock:
                _, k2 = heapq.heappop(completions)
                if k2 in running:
                    batch.append(k2)
            for k in batch:
                if k in running:
                    del running[k]
                    info = cache.workloads.get(k)
                    cache.delete_workload(k)
                    finished += 1
            queues.queue_inadmissible_workloads()
        else:
            # Opportunistically complete anything already due.
            while completions and completions[0][0] <= vclock:
                _, k = heapq.heappop(completions)
                if k in running:
                    del running[k]
                    cache.delete_workload(k)
                    finished += 1
                    queues.queue_inadmissible_workloads()

    wall = time.monotonic() - t_start
    return {
        "n": n_total,
        "finished": finished,
        "wall_s": wall,
        "cycles": cycles,
        "throughput": finished / wall if wall > 0 else 0.0,
        "device_time_s": getattr(sched, "device_time_s", 0.0),
    }


def device_mega_cycle_probe():
    """Secondary metric (stderr): one batched scheduling cycle at the
    north-star scale — 50k pending workloads x 2000 CQs (50 cohorts) x 32
    flavors — as a single compiled program on the attached accelerator."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from kueue_tpu.models import batch_scheduler as bs
    from kueue_tpu.models.encode import CycleArrays
    from kueue_tpu.ops.quota_ops import QuotaTreeArrays, compute_subtree
    from kueue_tpu.ops.tree_encode import GroupLayout

    W, C, F, R, CO = 50_000, 2000, 32, 2, 50
    rng = np.random.default_rng(0)
    N = C + CO
    parent = np.full(N, -1, np.int32)
    depth = np.zeros(N, np.int32)
    height = np.zeros(N, np.int32)
    for i in range(CO, N):
        parent[i] = rng.integers(0, CO)
        depth[i] = 1
    height[:CO] = 1
    is_cq = np.zeros(N, bool)
    is_cq[CO:] = True
    nominal = np.zeros((N, F, R), np.int64)
    nominal[CO:] = rng.integers(0, 50, (C, F, R)) * 1000
    CAPV = 1 << 62
    tree = QuotaTreeArrays(
        parent=jnp.asarray(parent), active=jnp.ones(N, bool),
        depth=jnp.asarray(depth), height=jnp.asarray(height),
        nominal=jnp.asarray(nominal),
        borrow_limit=jnp.full((N, F, R), CAPV, jnp.int64),
        has_borrow_limit=jnp.zeros((N, F, R), bool),
        lend_limit=jnp.full((N, F, R), CAPV, jnp.int64),
        has_lend_limit=jnp.zeros((N, F, R), bool),
        subtree_quota=jnp.zeros((N, F, R), jnp.int64),
    )
    usage0 = jnp.zeros((N, F, R), jnp.int64)
    subtree, usage = compute_subtree(tree, usage0, jnp.asarray(is_cq))
    tree = tree._replace(subtree_quota=subtree)
    arrays = CycleArrays(
        tree=tree, usage=usage,
        flavor_at=jnp.asarray(np.tile(np.arange(F, dtype=np.int32), (N, 1))),
        n_flavors=jnp.full(N, F, jnp.int32),
        covered=jnp.ones((N, R), bool),
        when_can_borrow_try_next=jnp.zeros(N, bool),
        when_can_preempt_try_next=jnp.ones(N, bool),
        pref_preempt_over_borrow=jnp.zeros(N, bool),
        can_preempt_while_borrowing=jnp.zeros(N, bool),
        never_preempts=jnp.ones(N, bool),
        can_always_reclaim=jnp.zeros(N, bool),
        usage_by_prio=jnp.zeros((N, F, R, 8), jnp.int64),
        prio_cuts=jnp.full(8, (1 << 62), jnp.int64),
        prefilter_valid=jnp.asarray(False),
        policy_within=jnp.zeros(N, jnp.int32),
        policy_reclaim=jnp.zeros(N, jnp.int32),
        nominal_cq=tree.nominal,
        w_cq=jnp.asarray(rng.integers(CO, N, W).astype(np.int32)),
        w_req=jnp.asarray(rng.integers(1, 20, (W, R)) * 500),
        w_elig=jnp.asarray(rng.random((W, F)) < 0.9),
        w_active=jnp.ones(W, bool),
        w_priority=jnp.asarray(rng.integers(0, 3, W) * 100),
        w_timestamp=jnp.asarray(np.arange(W, dtype=np.float64)),
        w_quota_reserved=jnp.zeros(W, bool),
        w_start_flavor=jnp.zeros(W, np.int32),
    )
    layout = GroupLayout(parent, np.ones(N, bool))
    ga = bs.GroupArrays(*layout.as_jax())
    for name, fn in (
        ("fixed-point", jax.jit(bs.make_fixedpoint_cycle())),
        ("grouped-scan", jax.jit(
            bs.make_grouped_cycle(2 * W // layout.n_groups))),
    ):
        out = fn(arrays, ga)
        out.outcome.block_until_ready()  # compile
        t0 = time.monotonic()
        out = fn(arrays, ga)
        out.outcome.block_until_ready()
        dt = time.monotonic() - t0
        admitted = int((np.asarray(out.outcome) == 4).sum())
        log(
            f"device mega-cycle[{name}] (50k wl x 2000 CQ x 32 flavors, "
            f"{jax.devices()[0].platform}): {dt*1000:.0f} ms, "
            f"{admitted} admitted, equivalent {admitted/dt:.0f} admissions/s"
        )
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kind", default="host", choices=["device", "host"])
    ap.add_argument("--scale", type=float, default=1.0,
                    help="fraction of the 15k baseline workload count")
    ap.add_argument("--with-mega", action="store_true")
    args = ap.parse_args()

    stats = run(args.kind, args.scale)
    log(f"stats: {stats}")
    if args.with_mega:
        try:
            device_mega_cycle_probe()
        except Exception as exc:  # pragma: no cover
            log(f"device mega-cycle probe failed: {exc}")
    baseline_throughput = 42.7  # BASELINE.md derived admissions/s
    value = round(stats["throughput"], 2)
    print(json.dumps({
        "metric": "baseline_admission_throughput",
        "value": value,
        "unit": "workloads/s",
        "vs_baseline": round(value / baseline_throughput, 2),
    }), flush=True)
    # Skip interpreter teardown: a wedged accelerator transport can hang
    # JAX's backend finalizers, and the result is already on stdout.
    import os as _os

    _os._exit(0)


if __name__ == "__main__":
    main()
