"""Benchmark: admission throughput on the reference's baseline scenario.

Mirrors test/performance/scheduler/configs/baseline/generator.yaml from the
reference (kubernetes-sigs/kueue): 5 cohorts x 6 ClusterQueues, nominal 20
cpu + borrowingLimit 100 per CQ, reclaimWithinCohort=Any +
withinClusterQueue=LowerPriority, and per CQ 350 small (req 1, prio 50),
100 medium (req 5, prio 100), 50 large (req 20, prio 200) workloads with
200/500/1000 ms runtimes.

Measurements emitted (one JSON line on stdout):
  * value / vs_baseline — the host control-plane's sustainable admission
    throughput on the full 15k-workload scenario (virtual clock; pure
    scheduling compute). The reference's derived number on this config is
    ~42.7 admissions/s (BASELINE.md).
  * device.sim — the SAME scenario simulated END TO END ON THE DEVICE:
    one compiled XLA dispatch running every scheduling round + virtual-time
    completion until all workloads finish (models/sim_loop.py).
  * device.mega — one batched scheduling cycle at the north-star scale
    (50k pending workloads x 2000 CQs x 32 flavors) for both admission
    kernels (grouped scan / fixed point).

Device probes run in /usr/bin/timeout-guarded subprocesses: a wedged
accelerator transport (observed with the remote-TPU tunnel) then costs a
bounded timeout instead of hanging the bench; the JSON line reports
device.ok=false in that case.
"""

from __future__ import annotations

import argparse
import heapq
import json
import os
import subprocess
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# Set by main() from --out. Probes write their record here (atomically)
# so the parent bench / harness can read a complete file even when the
# probe process dies after measuring (e.g. the jaxlib serialize()
# segfault); stdout then carries exactly one final JSON line per run.
_OUT_PATH = None


def _write_probe_record(doc: dict) -> None:
    """Persist an (interim or final) probe record without touching
    stdout: atomic write to --out when given, stderr otherwise."""
    if _OUT_PATH:
        try:
            tmp = _OUT_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, _OUT_PATH)
            return
        except OSError as exc:
            log(f"--out unwritable: {exc!r}")
    log(json.dumps(doc))


def build_scenario(scale: float, n_cohorts: int = 5, n_cqs: int = 6,
                   classes=None, fair: bool = False,
                   nominal: int = 20_000, borrowing_limit: int = 100_000):
    from kueue_tpu.api.constants import PreemptionPolicy
    from kueue_tpu.api.types import (
        ClusterQueue,
        ClusterQueuePreemption,
        Cohort,
        FairSharing,
        FlavorQuotas,
        LocalQueue,
        PodSet,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
        Workload,
    )
    from kueue_tpu.cache.cache import Cache
    from kueue_tpu.queue.manager import QueueManager

    cache = Cache()
    queues = QueueManager()
    cache.add_or_update_resource_flavor(ResourceFlavor(name="default"))

    if classes is None:
        classes = [
            ("small", int(350 * scale), 1000, 50, 0.2),
            ("medium", int(100 * scale), 5000, 100, 0.5),
            ("large", int(50 * scale), 20000, 200, 1.0),
        ]

    workloads = []
    t = 0.0
    for ci in range(n_cohorts):
        cache.add_or_update_cohort(Cohort(name=f"cohort-{ci}"))
        for qi in range(n_cqs):
            cq_name = f"cq-{ci}-{qi}"
            cq = ClusterQueue(
                name=cq_name,
                cohort=f"cohort-{ci}",
                resource_groups=[
                    ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[
                            FlavorQuotas(
                                name="default",
                                resources={
                                    "cpu": ResourceQuota(
                                        nominal=nominal,
                                        borrowing_limit=borrowing_limit,
                                    )
                                },
                            )
                        ],
                    )
                ],
                preemption=ClusterQueuePreemption(
                    reclaim_within_cohort=PreemptionPolicy.ANY,
                    within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                ),
                fair_sharing=FairSharing(weight=1.0) if fair else None,
            )
            cache.add_or_update_cluster_queue(cq)
            queues.add_cluster_queue(cq)
            lq = LocalQueue(name=f"lq-{cq_name}", cluster_queue=cq_name)
            cache.add_or_update_local_queue(lq)
            queues.add_local_queue(lq)
            for cls_name, count, req, prio, runtime_s in classes:
                for i in range(count):
                    t += 1.0
                    workloads.append(
                        (
                            Workload(
                                name=f"{cq_name}-{cls_name}-{i}",
                                queue_name=f"lq-{cq_name}",
                                pod_sets=[
                                    PodSet(
                                        name="main", count=1,
                                        requests={"cpu": req},
                                    )
                                ],
                                priority=prio,
                                creation_time=t,
                            ),
                            runtime_s,
                        )
                    )
    return cache, queues, workloads


def run(kind: str, scale: float) -> dict:
    cache, queues, workloads = build_scenario(scale)
    if kind == "device":
        from kueue_tpu.models.driver import DeviceScheduler

        sched = DeviceScheduler(cache, queues)
    else:
        from kueue_tpu.scheduler.scheduler import Scheduler

        sched = Scheduler(cache, queues)

    runtime_of = {}
    for wl, runtime_s in workloads:
        assert queues.add_or_update_workload(wl)
        runtime_of[wl.key] = runtime_s

    n_total = len(workloads)
    vclock = 0.0
    completions = []  # (completes_at, key)
    running = {}
    finished = 0
    cycles = 0
    t_start = time.monotonic()

    while finished < n_total:
        result = sched.schedule()
        cycles += 1
        for key in result.admitted:
            heapq.heappush(completions, (vclock + runtime_of[key], key))
            running[key] = True
        for key in result.preempted:
            running.pop(key, None)

        if not result.admitted and not result.preempted:
            # Scheduler stuck: advance virtual time to the next completion.
            while completions and completions[0][1] not in running:
                heapq.heappop(completions)  # evicted; stale entry
            if not completions:
                if not result.head_keys:
                    log(f"DEADLOCK: finished={finished}/{n_total}")
                    break
                log(f"stall: finished={finished}/{n_total}")
                break
            vclock, key = heapq.heappop(completions)
            batch = [key]
            while completions and completions[0][0] <= vclock:
                _, k2 = heapq.heappop(completions)
                if k2 in running:
                    batch.append(k2)
            for k in batch:
                if k in running:
                    del running[k]
                    cache.delete_workload(k)
                    finished += 1
            queues.queue_inadmissible_workloads()
        else:
            # Opportunistically complete anything already due.
            while completions and completions[0][0] <= vclock:
                _, k = heapq.heappop(completions)
                if k in running:
                    del running[k]
                    cache.delete_workload(k)
                    finished += 1
                    queues.queue_inadmissible_workloads()

    wall = time.monotonic() - t_start
    return {
        "n": n_total,
        "finished": finished,
        "wall_s": wall,
        "cycles": cycles,
        "throughput": finished / wall if wall > 0 else 0.0,
        "device_time_s": getattr(sched, "device_time_s", 0.0),
    }


# ---------------------------------------------------------------------------
# Device probes (run in timeout-guarded subprocesses; each prints one JSON
# line on stdout and exits via os._exit so a half-wedged transport cannot
# hang interpreter teardown).
# ---------------------------------------------------------------------------


def probe_sim(scale: float):
    """The full baseline scenario as ONE device dispatch: every scheduling
    round + virtual-clock completion runs inside a compiled while_loop
    (models/sim_loop.py)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from kueue_tpu.core.workload_info import WorkloadInfo
    from kueue_tpu.models.encode import encode_cycle
    from kueue_tpu.models.sim_loop import make_sim_loop

    cache, queues, workloads = build_scenario(scale)
    infos = []
    runtimes = []
    for wl, runtime_s in workloads:
        lq = cache.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        infos.append(WorkloadInfo(wl, lq.cluster_queue))
        runtimes.append(int(runtime_s * 1000))
    snapshot = cache.snapshot()
    t_enc = time.monotonic()
    arrays, idx = encode_cycle(snapshot, infos, snapshot.resource_flavors)
    encode_s = time.monotonic() - t_enc
    w_pad = arrays.w_cq.shape[0]
    runtime_ms = jnp.asarray(
        np.pad(np.asarray(runtimes, np.int64), (0, w_pad - len(runtimes)))
    )
    # Exactness needs the per-round scan depth >= the largest per-tree
    # entry bucket, not the full W (trees scan in parallel).
    group_of = np.asarray(idx.group_arrays.flat_to_group)[
        np.asarray(arrays.w_cq)
    ]
    s_max = int(np.bincount(group_of).max())
    # Lending-limit-free trees take the fixed-point admission pass: a
    # handful of fully-parallel rounds per cycle instead of a sequential
    # per-tree scan (identical decisions; see models/batch_scheduler.py).
    kernel = (
        "grouped" if bool(np.asarray(arrays.tree.has_lend_limit).any())
        else "fixedpoint"
    )
    n_levels = int(np.asarray(arrays.tree.depth).max()) + 1
    platform = jax.devices()[0].platform
    from kueue_tpu.models import pallas_scan as ps

    kernels = [kernel]
    # Pallas is retired to opt-in (docs/perf.md "Pallas scan"): the live
    # TPU variant only dispatches under KUEUE_TPU_ENABLE_PALLAS=1.
    if platform == "tpu" and ps.opt_in() and ps.fits_int32(arrays):
        kernels.append("pallas")
    stats = {
        "probe": "sim",
        "ok": True,
        "platform": platform,
        "n": len(infos),
        "encode_s": round(encode_s, 3),
    }
    best = None
    for k in kernels:
        # Per-kernel isolation: a kernel that fails to compile or run on
        # the hardware (e.g. a TPU-only lowering limit) must not discard
        # the measurements already captured for the others.
        try:
            sim = jax.jit(make_sim_loop(s_max=s_max, kernel=k,
                                        n_levels=n_levels))
            t0 = time.monotonic()
            out = sim(arrays, idx.group_arrays, runtime_ms)
            out.rounds.block_until_ready()
            compile_s = time.monotonic() - t0
            t0 = time.monotonic()
            out = sim(arrays, idx.group_arrays, runtime_ms)
            out.rounds.block_until_ready()
            dt = time.monotonic() - t0
            admitted = int((np.asarray(out.admitted_at) >= 0).sum())
        except Exception as exc:  # noqa: BLE001 - record and continue
            stats[f"{k}_error"] = repr(exc)[:300]
            continue
        stats[f"{k}_wall_s"] = round(dt, 3)
        stats[f"{k}_compile_s"] = round(compile_s, 1)
        stats[f"{k}_admitted"] = admitted
        if best is None or dt < best[0]:
            best = (dt, k, admitted, int(out.rounds))
    if best is None:
        stats["ok"] = False
        return stats
    dt, k, admitted, rounds = best
    stats.update({
        "admitted": admitted,
        "rounds": rounds,
        "kernel": k,
        "compile_s": stats[f"{k}_compile_s"],
        "device_wall_s": round(dt, 3),
        "admissions_per_s": round(admitted / dt, 1) if dt > 0 else 0.0,
        # Honest end-to-end number for the host-vs-device crossover:
        # encode + dispatch (compile amortizes via the persistent cache).
        "end_to_end_s": round(encode_s + dt, 3),
        "end_to_end_adm_per_s": round(
            admitted / (encode_s + dt), 1
        ) if encode_s + dt > 0 else 0.0,
    })
    return stats


def probe_fair(scale: float):
    """The flagship fair-sharing configuration (BASELINE.json config #3 /
    perf_configs/fair-sharing: 50 cohorts x 40 CQs = 2,000 CQs, 25
    workloads per CQ = 50k at scale 1.0) simulated end to end on the
    device with the DRS-tournament kernel (models/fair_kernel.py) —
    the fair analog of the sim probe, because the host fair tournament
    is the slowest host path and the device kernel is its replacement."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from kueue_tpu.core.workload_info import WorkloadInfo
    from kueue_tpu.models.encode import encode_cycle
    from kueue_tpu.models.sim_loop import make_sim_loop

    # Linear scaling contract (like probe_sim): per-CQ class counts are
    # fixed; only the cohort count scales, so workload count tracks
    # ``scale`` linearly and cross-scale adm/s numbers stay comparable.
    classes = [
        ("small", 18, 1000, 50, 0.15),
        ("medium", 5, 5000, 100, 0.35),
        ("large", 2, 20000, 200, 0.7),
    ]
    n_cohorts = max(int(50 * scale), 1)
    cache, queues, workloads = build_scenario(
        1.0, n_cohorts=n_cohorts, n_cqs=40, classes=classes, fair=True
    )
    infos = []
    runtimes = []
    for wl, runtime_s in workloads:
        lq = cache.local_queues.get(f"{wl.namespace}/{wl.queue_name}")
        infos.append(WorkloadInfo(wl, lq.cluster_queue))
        runtimes.append(int(runtime_s * 1000))
    snapshot = cache.snapshot()
    t_enc = time.monotonic()
    arrays, idx = encode_cycle(
        snapshot, infos, snapshot.resource_flavors, fair_sharing=True
    )
    encode_s = time.monotonic() - t_enc
    w_pad = arrays.w_cq.shape[0]
    runtime_ms = jnp.asarray(
        np.pad(np.asarray(runtimes, np.int64), (0, w_pad - len(runtimes)))
    )
    # Exact tournament bound: one entry per CQ participates per scan
    # (last-entry shadowing), so a root can produce at most
    # #participating-CQs winners — NOT #entries (26x fewer steps at the
    # flagship's 25 workloads/CQ).
    s_max = int(idx.fair_s_bound) or arrays.w_cq.shape[0]
    n_levels = int(np.asarray(arrays.tree.depth).max()) + 1
    stats = {
        "probe": "fair",
        "ok": True,
        "platform": jax.devices()[0].platform,
        "n": len(infos),
        "cqs": n_cohorts * 40,
        "encode_s": round(encode_s, 3),
    }
    try:
        sim = jax.jit(make_sim_loop(s_max=s_max, kernel="fair",
                                    n_levels=n_levels))
        t0 = time.monotonic()
        out = sim(arrays, idx.group_arrays, runtime_ms)
        out.rounds.block_until_ready()
        compile_s = time.monotonic() - t0
        t0 = time.monotonic()
        out = sim(arrays, idx.group_arrays, runtime_ms)
        out.rounds.block_until_ready()
        dt = time.monotonic() - t0
        admitted = int((np.asarray(out.admitted_at) >= 0).sum())
    except Exception as exc:  # noqa: BLE001 - record and report
        stats["ok"] = False
        stats["error"] = repr(exc)[:300]
        return stats
    stats.update({
        "admitted": admitted,
        "rounds": int(out.rounds),
        "compile_s": round(compile_s, 1),
        "device_wall_s": round(dt, 3),
        "admissions_per_s": round(admitted / dt, 1) if dt > 0 else 0.0,
        "end_to_end_s": round(encode_s + dt, 3),
        "end_to_end_adm_per_s": round(
            admitted / (encode_s + dt), 1
        ) if encode_s + dt > 0 else 0.0,
    })
    return stats


def probe_ping():
    """Cheap device-aliveness check: backend init + one tiny computation."""
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    x = (jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()
    return {"probe": "ping", "ok": True, "platform": d.platform,
            "check": float(x[0, 0])}


def build_mega(W=50_000, C=2000, F=32, R=2, CO=50):
    """Dense north-star-scale cycle arrays (50k pending workloads x 2000
    CQs in 50 cohorts x 32 flavors by default). Shared by the mega probe
    and the offline tuning sweep (tools/tune_mega.py)."""
    import numpy as np
    import jax.numpy as jnp

    from kueue_tpu.models.encode import CycleArrays
    from kueue_tpu.ops.quota_ops import QuotaTreeArrays, compute_subtree
    from kueue_tpu.ops.tree_encode import GroupLayout
    rng = np.random.default_rng(0)
    N = C + CO
    parent = np.full(N, -1, np.int32)
    depth = np.zeros(N, np.int32)
    height = np.zeros(N, np.int32)
    for i in range(CO, N):
        parent[i] = rng.integers(0, CO)
        depth[i] = 1
    height[:CO] = 1
    is_cq = np.zeros(N, bool)
    is_cq[CO:] = True
    nominal = np.zeros((N, F, R), np.int64)
    nominal[CO:] = rng.integers(0, 50, (C, F, R)) * 1000
    CAPV = 1 << 62
    tree = QuotaTreeArrays(
        parent=jnp.asarray(parent), active=jnp.ones(N, bool),
        depth=jnp.asarray(depth), height=jnp.asarray(height),
        nominal=jnp.asarray(nominal),
        borrow_limit=jnp.full((N, F, R), CAPV, jnp.int64),
        has_borrow_limit=jnp.zeros((N, F, R), bool),
        lend_limit=jnp.full((N, F, R), CAPV, jnp.int64),
        has_lend_limit=jnp.zeros((N, F, R), bool),
        subtree_quota=jnp.zeros((N, F, R), jnp.int64),
    )
    usage0 = jnp.zeros((N, F, R), jnp.int64)
    subtree, usage = compute_subtree(tree, usage0, jnp.asarray(is_cq))
    tree = tree._replace(subtree_quota=subtree)
    from kueue_tpu.models.encode import _order_rank

    # Draw order matches the original generator so results stay comparable.
    w_cq_np = rng.integers(CO, N, W).astype(np.int32)
    w_req_np = rng.integers(1, 20, (W, R)) * 500
    w_elig_np = rng.random((W, F)) < 0.9
    w_prio = rng.integers(0, 3, W) * 100
    w_ts = np.arange(W, dtype=np.float64)
    arrays = CycleArrays(
        tree=tree, usage=usage,
        flavor_at=jnp.asarray(np.tile(np.arange(F, dtype=np.int32), (N, 1))),
        n_flavors=jnp.full(N, F, jnp.int32),
        covered=jnp.ones((N, R), bool),
        when_can_borrow_try_next=jnp.zeros(N, bool),
        when_can_preempt_try_next=jnp.ones(N, bool),
        pref_preempt_over_borrow=jnp.zeros(N, bool),
        can_preempt_while_borrowing=jnp.zeros(N, bool),
        never_preempts=jnp.ones(N, bool),
        can_always_reclaim=jnp.zeros(N, bool),
        usage_by_prio=jnp.zeros((N, F, R, 8), jnp.int64),
        prio_cuts=jnp.full(8, (1 << 62), jnp.int64),
        prefilter_valid=jnp.asarray(False),
        policy_within=jnp.zeros(N, jnp.int32),
        policy_reclaim=jnp.zeros(N, jnp.int32),
        nominal_cq=tree.nominal,
        w_cq=jnp.asarray(w_cq_np),
        w_req=jnp.asarray(w_req_np),
        w_elig=jnp.asarray(w_elig_np),
        w_active=jnp.ones(W, bool),
        w_priority=jnp.asarray(w_prio),
        w_timestamp=jnp.asarray(w_ts),
        w_quota_reserved=jnp.zeros(W, bool),
        w_start_flavor=jnp.zeros(W, np.int32),
        w_order_rank=jnp.asarray(_order_rank(w_prio, w_ts)),
    )
    layout = GroupLayout(parent, np.ones(N, bool))
    return arrays, layout


def probe_mega():
    """One batched scheduling cycle at the north-star scale — 50k pending
    workloads x 2000 CQs (50 cohorts) x 32 flavors — as a single compiled
    program on the attached accelerator.

    Timing discipline on the tunneled (axon) device: async dispatch FAKES
    completion until the first device->host readback in the process —
    ``block_until_ready`` returns early, so pre-readback timings are
    meaningless (a 1-TFLOP matmul "completes" in 60 us). After one
    readback every dispatch is honestly synchronous but pays the tunnel's
    ~65 ms round-trip latency. This probe therefore (a) anchors sync mode
    with an explicit readback before any timing, (b) reports the
    single-dispatch wall (includes the round trip — the number a remote
    caller sees) AND the chained per-cycle compute ((T_k - T_1)/(k - 1)
    with k cycles data-dependent inside one dispatch) — the number a
    locally-attached TPU would see and the honest kernel cost."""
    import numpy as np
    import jax

    from kueue_tpu.models import batch_scheduler as bs

    W = 50_000
    arrays, layout = build_mega(W=W)
    ga = bs.GroupArrays(*layout.as_jax())
    n_levels = int(np.asarray(arrays.tree.depth).max()) + 1
    group_of = np.asarray(layout.flat_to_group)[np.asarray(arrays.w_cq)]
    s_exact = int(np.bincount(group_of, minlength=layout.n_groups).max())
    out_stats = {"probe": "mega", "ok": True,
                 "platform": jax.devices()[0].platform}
    from kueue_tpu.models import pallas_scan as ps

    # Sync-mode anchor (see docstring): one tiny readback.
    _ = int(jax.jit(lambda a: a.max())(arrays.w_cq))

    variants = [
        ("fixedpoint", bs.make_fixedpoint_cycle(n_levels=n_levels)),
        ("grouped", bs.make_grouped_cycle(
            s_exact, unroll=4, n_levels=n_levels)),
    ]
    if ps.opt_in() and ps.fits_int32(arrays):
        variants.append(
            ("pallas", ps.make_pallas_cycle(s_exact, n_levels=n_levels)))
        # Half-width quota math for the HBM-bound nominate/order phases
        # (bs.cast_arrays_i32) — exact under the same fits_int32 gate.
        variants.append(("pallas_i32", ps.make_pallas_cycle(
            s_exact, n_levels=n_levels, i32=True)))
    elif not ps.opt_in():
        # Retired to opt-in after the BENCH_TPU_LIVE RecursionError
        # re-probe (docs/perf.md "Pallas scan"): the mega probe routes to
        # the fixed-point/grouped kernels unless explicitly re-enabled.
        out_stats["pallas"] = (
            f"retired to opt-in ({ps.PALLAS_OPT_IN_ENV}=1)"
        )
    walls = {}
    impls = dict(variants)
    for name, impl in variants:
        fn = jax.jit(impl)
        # Per-variant isolation: one kernel's hardware-only failure must
        # not lose the others' measurements.
        try:
            t0 = time.monotonic()
            out = fn(arrays, ga)
            out.outcome.block_until_ready()  # compile
            compile_s = time.monotonic() - t0
            t0 = time.monotonic()
            out = fn(arrays, ga)
            out.outcome.block_until_ready()
            dt = time.monotonic() - t0
            admitted = int((np.asarray(out.outcome) == 4).sum())
        except Exception as exc:  # noqa: BLE001 - record and continue
            out_stats[name + "_error"] = repr(exc)[:300]
            log(f"mega[{name}]: FAILED {exc!r}")
            continue
        walls[name] = dt
        out_stats[name + "_ms"] = round(dt * 1000, 1)
        out_stats[name + "_compile_s"] = round(compile_s, 1)
        out_stats["admitted"] = admitted
        log(f"mega[{name}]: {dt*1000:.0f} ms, {admitted} admitted, "
            f"~{admitted/dt:.0f} admissions/s equivalent")

    # Chained per-cycle compute for the fastest variant: k cycles with
    # usage fed forward (data-dependent, no CSE) in one dispatch. Tunnel
    # round-trip latency is noisy run to run (~±30 ms), so use a long
    # chain and best-of-3 at both endpoints: per-cycle = (T8 - T1)/7.
    if walls:
        best = min(walls, key=walls.get)
        k = 8

        def chain(a, g):
            impl = impls[best]
            out = impl(a, g)
            for _ in range(k - 1):
                a = a._replace(usage=out.usage)
                out = impl(a, g)
            return out

        try:
            fn_k = jax.jit(chain)
            fn_1 = jax.jit(impls[best])
            out = fn_k(arrays, ga)
            out.outcome.block_until_ready()
            t1 = tk = float("inf")
            for _ in range(3):
                t0 = time.monotonic()
                out = fn_1(arrays, ga)
                out.outcome.block_until_ready()
                t1 = min(t1, time.monotonic() - t0)
                t0 = time.monotonic()
                out = fn_k(arrays, ga)
                out.outcome.block_until_ready()
                tk = min(tk, time.monotonic() - t0)
            per = (tk - t1) / (k - 1)
            out_stats["percycle_kernel"] = best
            out_stats["percycle_ms"] = round(per * 1000, 1)
            out_stats["dispatch_latency_ms"] = round(
                (t1 - per) * 1000, 1
            )
            log(f"mega[{best}]: chained x{k} {tk*1000:.0f} ms vs x1 "
                f"{t1*1000:.0f} ms -> {per*1000:.1f} ms/cycle "
                "latency-free")
        except Exception as exc:  # noqa: BLE001
            out_stats["percycle_error"] = repr(exc)[:300]
    return out_stats


def probe_tiled(scale: float):
    """Tiled streaming admission vs the monolithic cycle (ROADMAP item 3:
    500k-1M pending workloads through a bounded device arena).

    The live run is scaled down for this box: a 24-tree x 4-CQ forest
    driven to completion twice — once monolithic (tileWidth=off), once
    tiled (tileWidth=16) — with per-cycle result parity asserted (the
    randomized differential lives in tests/test_tiled.py; this is the
    measured twin). Both drivers are prewarmed and the measurement is a
    second fresh-build run, so walls compare dispatch cost, not compiles.

    The 500k-class story is proven without materializing 500k rows:
    a tiled cycle at any backlog width only ever materializes
    bucket(tile width) rows, so the probe (a) AOT-lowers the production
    kernel at the auto tile bucket (8192) — the one shape a tiled 1M
    cycle dispatches — and (b) projects plane bytes linearly in W from
    two measured encodes to report what the monolithic plane WOULD cost
    at the target vs the tiled bound.

    Headline: ``tiled_peak_plane_mb`` (lower; the bound) and
    ``tiled_vs_mono_delta_pct`` (lower; honest about this CPU box, where
    tiling the same work adds per-tile dispatch + re-snapshot overhead
    and no memory pressure is relieved)."""
    import jax
    import numpy as np

    from kueue_tpu.models import batch_scheduler as bs
    from kueue_tpu.models import buckets
    from kueue_tpu.models.driver import DeviceScheduler
    from kueue_tpu.models.encode import encode_cycle, plane_nbytes

    TILE_W = 16
    TARGET_W = 500_000
    classes = [
        ("s", max(2, int(6 * scale)), 1000, 50, 0.2),
        ("l", max(1, int(2 * scale)), 15000, 100, 0.5),
    ]

    def build():
        return build_scenario(
            scale, n_cohorts=24, n_cqs=4, classes=classes
        )

    def drive(tile_width, submit_then_run=True):
        cache, queues, workloads = build()
        for wl, _rt in workloads:
            queues.add_or_update_workload(wl)
        sched = DeviceScheduler(cache, queues, tile_width=tile_width)
        sched.prewarm(max_heads=96, aot=False)
        cycles = []
        peak_plane = 0
        tiles_seen = 0
        prev_carry = None
        prev_heads = None
        t0 = time.monotonic()
        for _ in range(10_000):
            res = sched.schedule()
            carry = sched._last_tile_carry
            if carry is not None and carry is not prev_carry:
                peak_plane = max(peak_plane, carry.peak_plane_bytes)
                tiles_seen = max(tiles_seen, carry.tiles)
                prev_carry = carry
            cycles.append(
                (sorted(res.admitted), sorted(res.preempted),
                 sorted(res.skipped))
            )
            if res.admitted or res.preempted:
                prev_heads = None
                continue
            if not res.head_keys or res.head_keys == prev_heads:
                break
            prev_heads = res.head_keys
        wall = time.monotonic() - t0
        return cycles, wall, peak_plane, tiles_seen

    stats = {
        "probe": "tiled",
        "ok": True,
        "platform": jax.devices()[0].platform,
        "tile_width": TILE_W,
        "target_w": TARGET_W,
    }

    # Warmup pass (fills the in-process compile cache for both shapes),
    # then the measured pass on fresh identical builds.
    log("tiled: warmup drive (monolithic)")
    drive("off")
    log("tiled: warmup drive (tiled)")
    drive(TILE_W)
    log("tiled: measured drive (monolithic)")
    mono_cycles, mono_wall, _mono_peak, _ = drive("off")
    log("tiled: measured drive (tiled)")
    tiled_cycles, tiled_wall, tiled_peak, tiles_seen = drive(TILE_W)

    identical = mono_cycles == tiled_cycles
    stats["live_cycles"] = len(mono_cycles)
    stats["live_admitted"] = sum(len(c[0]) for c in mono_cycles)
    stats["tiles_per_cycle"] = tiles_seen
    stats["tiled_vs_mono_identical"] = identical
    if not identical:
        stats["ok"] = False
        log("tiled: DIVERGED from monolithic cycle")
    stats["mono_wall_s"] = round(mono_wall, 3)
    stats["tiled_wall_s"] = round(tiled_wall, 3)
    if mono_wall > 0:
        stats["tiled_vs_mono_delta_pct"] = round(
            100.0 * (tiled_wall - mono_wall) / mono_wall, 1
        )

    # Plane accounting on a fresh build: the monolithic first-cycle
    # plane vs the tiled peak, measured; then the linear-in-W projection
    # to the 500k-class target.
    cache, queues, workloads = build()
    for wl, _rt in workloads:
        queues.add_or_update_workload(wl)
    heads = queues.heads()
    snapshot = cache.snapshot()

    def plane_at(w_pad, hs=()):
        arrays, _idx = encode_cycle(
            snapshot, list(hs), snapshot.resource_flavors, w_pad=w_pad,
            preempt=True,
        )
        return plane_nbytes(arrays)

    mono_bucket = buckets.bucket_for(len(heads))
    mono_plane = plane_at(mono_bucket, heads)
    mb = 1024.0 * 1024.0
    stats["live_heads"] = len(heads)
    stats["mono_plane_mb"] = round(mono_plane / mb, 3)
    stats["tiled_peak_plane_mb"] = round(tiled_peak / mb, 3)
    if tiled_peak >= mono_plane:
        stats["ok"] = False
        log("tiled: peak tile plane not below the monolithic plane")

    # Per-row cost from two encode widths; fixed part = tree/policy
    # tensors that do not scale with W.
    b1, b2 = 128, 1024
    p1, p2 = plane_at(b1), plane_at(b2)
    per_row = (p2 - p1) / float(b2 - b1)
    fixed = p1 - b1 * per_row
    auto_tile_bucket = buckets.bucket_for(
        DeviceScheduler._TILE_AUTO_WIDTH
    )
    stats["plane_bytes_per_row"] = round(per_row, 1)
    stats["projected_mono_plane_mb_at_target"] = round(
        (fixed + per_row * buckets.bucket_for(TARGET_W)) / mb, 1
    )
    stats["projected_tiled_peak_plane_mb_at_target"] = round(
        (fixed + per_row * auto_tile_bucket) / mb, 1
    )

    # Full-size shape proof by AOT lowering only: the auto tile bucket
    # is the one W shape a tiled 500k-1M cycle ever dispatches.
    log("tiled: AOT-lowering the auto tile bucket shape")
    try:
        arrays, idx = encode_cycle(
            snapshot, [], snapshot.resource_flavors,
            w_pad=auto_tile_bucket, preempt=True,
        )
        t0 = time.monotonic()
        jax.jit(bs.cycle_grouped_preempt).lower(
            arrays, idx.group_arrays, idx.admitted_arrays
        )
        stats["fullsize_tile_bucket"] = auto_tile_bucket
        stats["fullsize_lowered"] = True
        stats["fullsize_lower_s"] = round(time.monotonic() - t0, 1)
    except Exception as exc:  # noqa: BLE001 - record and fail the gate
        stats["fullsize_lowered"] = False
        stats["fullsize_lower_error"] = repr(exc)[:300]
        stats["ok"] = False

    stats["fingerprint_extra"] = {
        "target_w": TARGET_W,
        "tile_width": TILE_W,
        "n_cohorts": 24,
        "n_cqs": 4,
    }
    return stats


def probe_phases():
    """Per-phase device timing at the north-star scale: nominate /
    admission-order / admit-scan measured as separately-jitted programs,
    plus data-volume accounting (bytes shipped host->device per cycle and
    the per-scan-step working set). The reference logs per-phase durations
    inside its schedule cycle (pkg/scheduler/scheduler.go:305-372); this
    is the device analog, so regressions inside the cycle are visible
    instead of hiding in one wall number."""
    import numpy as np
    import jax

    from kueue_tpu.models import batch_scheduler as bs

    W = 50_000
    arrays, layout = build_mega(W=W)
    ga = bs.GroupArrays(*layout.as_jax())
    n_levels = int(np.asarray(arrays.tree.depth).max()) + 1
    group_of = np.asarray(layout.flat_to_group)[np.asarray(arrays.w_cq)]
    s_exact = int(np.bincount(group_of, minlength=layout.n_groups).max())
    stats = {"probe": "phases", "ok": True,
             "platform": jax.devices()[0].platform}
    leaves = jax.tree_util.tree_leaves((arrays, ga))
    stats["encode_bytes"] = int(sum(x.nbytes for x in leaves))
    # Per-step working set of the grouped scan: [G, L, R] gathers of the
    # five chain tensors plus the delta scatter (i64 = 8 bytes).
    g_n = int(layout.n_groups)
    r_n = int(arrays.w_req.shape[1])
    stats["scan_step_bytes"] = int(g_n * n_levels * r_n * 8 * 6)
    stats["scan_steps"] = s_exact

    nom_fn = jax.jit(
        lambda a: bs.nominate(a, a.usage, n_levels=n_levels)
    )
    order_fn = jax.jit(lambda a, nom: bs.admission_order(a, nom))

    def scan_impl(a, g, nom, order):
        return bs.admit_scan_grouped(
            a, g, nom, a.usage, order, s_exact, unroll=4,
            n_levels=n_levels,
        )

    scan_fn = jax.jit(scan_impl)

    def timeit(name, fn, *args):
        try:
            out = fn(*args)
            jax.block_until_ready(out)
            t0 = time.monotonic()
            out = fn(*args)
            jax.block_until_ready(out)
            stats[name + "_ms"] = round((time.monotonic() - t0) * 1000, 1)
            return out
        except Exception as exc:  # noqa: BLE001 - record and continue
            stats[name + "_error"] = repr(exc)[:300]
            stats["ok"] = False
            return None

    nom = timeit("nominate", nom_fn, arrays)
    if nom is not None:
        order = timeit("order", order_fn, arrays, nom)
        if order is not None:
            timeit("scan", scan_fn, arrays, ga, nom, order)

    # Same phases on int32-cast quota tensors (exact under fits_int32):
    # the nominate/order phases are HBM-bound int64 streams, so the i32
    # numbers show how much of their cost is pure bandwidth.
    from kueue_tpu.models import pallas_scan as ps

    if ps.fits_int32(arrays):
        arrays32 = bs.cast_arrays_i32(arrays)
        nom32 = timeit("nominate_i32", nom_fn, arrays32)
        if nom32 is not None:
            timeit("order_i32", order_fn, arrays32, nom32)
    return stats


def probe_multichip():
    """Weak-scaling curve on the virtual host mesh: the north-star cycle
    timed over 1/2/4/8 devices with the workload axis sharded (nominate is
    the FLOP-parallel phase; the grouped admission scan is sequential by
    semantics and replicated). Runs on the forced-CPU host platform — the
    same compiled sharding program a real multi-chip TPU mesh would run,
    minus the interconnect speeds."""
    import numpy as np
    import jax

    from kueue_tpu.models import batch_scheduler as bs
    from kueue_tpu.parallel import sharding as par

    n_avail = len(jax.devices())
    W = 50_000
    arrays, layout = build_mega(W=W)
    ga = bs.GroupArrays(*layout.as_jax())
    n_levels = int(np.asarray(arrays.tree.depth).max()) + 1
    group_of = np.asarray(layout.flat_to_group)[np.asarray(arrays.w_cq)]
    s_exact = int(np.bincount(group_of, minlength=layout.n_groups).max())
    stats = {
        "probe": "multichip", "ok": True, "devices": n_avail, "w": W,
        "note": (
            "virtual host devices share one CPU's cores: this curve "
            "measures sharding/collective overhead and program validity, "
            "not speedup; real chips split the nominate FLOPs"
        ),
    }
    from jax.sharding import NamedSharding, PartitionSpec as P

    nom_proto = bs.NominateResult(*([0] * 8))
    for n in (1, 2, 4, 8):
        if n > n_avail or W % n:
            continue
        try:
            mesh = par.make_mesh(n)
            rep = NamedSharding(mesh, P())
            nom_fn = jax.jit(
                lambda a: bs.nominate(a, a.usage, n_levels=n_levels),
                in_shardings=(par.arrays_shardings(mesh, arrays),),
                out_shardings=jax.tree_util.tree_map(
                    lambda _: rep, nom_proto
                ),
            )
            out = nom_fn(arrays)
            jax.block_until_ready(out)
            t0 = time.monotonic()
            out = nom_fn(arrays)
            jax.block_until_ready(out)
            stats[f"nominate_{n}dev_ms"] = round(
                (time.monotonic() - t0) * 1000, 1
            )
            cyc = par.sharded_grouped_cycle(
                mesh, arrays, ga, s_max=s_exact, n_levels=n_levels,
                unroll=4,
            )
            out = cyc(arrays, ga)
            jax.block_until_ready(out.outcome)
            t0 = time.monotonic()
            out = cyc(arrays, ga)
            jax.block_until_ready(out.outcome)
            stats[f"cycle_{n}dev_ms"] = round(
                (time.monotonic() - t0) * 1000, 1
            )
            if n > 1:
                # Group-axis-sharded scan variant (VERDICT r3 #6):
                # measured for the record; see scan_floor_analysis.
                cyc_g = par.sharded_grouped_cycle(
                    mesh, arrays, ga, s_max=s_exact, n_levels=n_levels,
                    unroll=4, shard_scan_by_group=True,
                )
                out = cyc_g(arrays, ga)
                jax.block_until_ready(out.outcome)
                t0 = time.monotonic()
                out = cyc_g(arrays, ga)
                jax.block_until_ready(out.outcome)
                stats[f"cycle_gshard_{n}dev_ms"] = round(
                    (time.monotonic() - t0) * 1000, 1
                )
        except Exception as exc:  # noqa: BLE001 - record and continue
            stats[f"{n}dev_error"] = repr(exc)[:300]
    stats["scan_floor_analysis"] = (
        "The grouped admission scan is step-latency-bound, not "
        "width-bound: each of its s_max sequential steps touches "
        "O(G*Nm*F*R) ~1MB of state but costs ~0.2ms of dispatch/memory "
        "latency, so sharding the group axis (independent cohort "
        "forests; bit-identical outcomes, validated in "
        "tests/test_multichip_differential.py) removes width a device "
        "never waits on while adding SPMD partition overhead per step — "
        "XLA inserts per-step reshards (273 vs 42 all-gathers in the "
        "compiled HLO). Multi-chip speedup for the cycle therefore comes "
        "from (a) the W-sharded nominate phase (the FLOP term) and (b) "
        "eliminating the sequential scan itself — the fixed-point kernel "
        "already replaces it with a handful of fully-parallel rounds for "
        "lending-limit-free trees; a group-sharded scan would only win "
        "when per-step width work dominates per-step latency, i.e. "
        "forests far wider than the 50-cohort flagship."
    )
    return stats


def probe_incremental(scale: float):
    """Steady-state incremental-cycle probe (docs/perf.md): warm the
    CycleArena at the ~10k-workload baseline config, churn <=5% of the
    admitted rows per cycle, and report the host-encode cost of the
    incremental path vs from-scratch encode_cycle plus the device-solve
    split. Admission to steady state runs through the host-exact
    scheduler so the probe measures encoding, not kernel recompiles;
    one cycle is verified bit-identical against from-scratch."""
    import numpy as np
    import jax

    from kueue_tpu.api.types import PodSet, Workload
    from kueue_tpu.core.workload_info import WorkloadInfo
    from kueue_tpu.models import batch_scheduler as bs
    from kueue_tpu.models.arena import CycleArena, assert_cycle_equal
    from kueue_tpu.models.encode import encode_cycle
    from kueue_tpu.scheduler.scheduler import Scheduler

    # 10k-workload config on the baseline 5x6 quota tree, one homogeneous
    # class so the steady-state admitted set is large (~6000 rows: 200 x
    # 100m per 20k-nominal CQ) — the default class mix parks after one
    # 20k "large" fills each CQ's nominal and admits only 30 rows, which
    # is no test of O(admitted) encode cost.
    n_per_cq = max(1, int(333 * scale))
    cache, queues, workloads = build_scenario(
        scale, classes=[("unit", n_per_cq, 100, 50, 1.0)]
    )
    for wl, _rt in workloads:
        assert queues.add_or_update_workload(wl)
    host = Scheduler(cache, queues)
    for _ in range(400):
        res = host.schedule()
        if not res.admitted and not res.preempted:
            break
    admitted_n = len(cache.workloads)

    heads = queues.heads()
    arena = CycleArena(cache)
    snap = arena.take_snapshot()
    t0 = time.monotonic()
    arrays, idx = arena.encode(snap, heads, snap.resource_flavors,
                               preempt=True)
    cold_s = time.monotonic() - t0
    w_pad = int(np.asarray(arrays.w_cq).shape[0])

    # Steady-state churn: the newest admitted row of a few CQs completes
    # and a fresh equivalent admits in its slot (<=5% of rows per cycle).
    churn_cqs = [n for n, d in cache._cq_workloads.items() if d]
    k_churn = max(1, min(len(churn_cqs), admitted_n // 40))
    inc_s, full_s, dirty = [], [], []
    verified = False
    nonce = 0
    t_clock = float(len(workloads) + 1)
    for _ in range(12):
        for cq_name in churn_cqs[:k_churn]:
            d = cache._cq_workloads.get(cq_name)
            if not d:
                continue
            last_key = next(reversed(d))
            old = cache.workloads[last_key].obj
            cache.delete_workload(last_key)
            nonce += 1
            t_clock += 1.0
            # uid sorts adjacent to the replaced row's so the global
            # uid_rank column shifts only locally; fresh counter uids
            # land mid-order lexicographically and re-rank O(A) rows.
            repl = Workload(
                name=f"churn-{nonce}", namespace=old.namespace,
                queue_name=old.queue_name, uid=old.uid + "r",
                pod_sets=[PodSet(name="main", count=1,
                                 requests=dict(old.pod_sets[0].requests))],
                priority=old.priority, creation_time=t_clock,
            )
            cache.add_or_update_workload(WorkloadInfo(repl, cq_name))
        snap = arena.take_snapshot()
        t0 = time.monotonic()
        arrays, idx = arena.encode(snap, heads, snap.resource_flavors,
                                   w_pad=w_pad, preempt=True)
        inc_s.append(time.monotonic() - t0)
        if arena.last_stats.get("path") != "incremental":
            return {"probe": "incremental", "ok": False,
                    "error": f"fell back to full: {arena.last_stats}"}
        dirty.append(int(arena.last_stats.get("dirty_admitted", 0)))
        t0 = time.monotonic()
        ref = encode_cycle(snap, heads, snap.resource_flavors,
                           w_pad=w_pad, preempt=True)
        full_s.append(time.monotonic() - t0)
        if not verified:
            assert_cycle_equal(arrays, idx, *ref)
            verified = True

    # Device-solve side of the split: one warm grouped-kernel dispatch on
    # the arena-built arrays.
    out = bs.cycle_grouped_preempt(arrays, idx.group_arrays,
                                   idx.admitted_arrays)
    t0 = time.monotonic()
    jax.block_until_ready(out.outcome)
    compile_s = time.monotonic() - t0
    t0 = time.monotonic()
    out = bs.cycle_grouped_preempt(arrays, idx.group_arrays,
                                   idx.admitted_arrays)
    jax.block_until_ready(out.outcome)
    device_s = time.monotonic() - t0

    inc_med = sorted(inc_s)[len(inc_s) // 2]
    full_med = sorted(full_s)[len(full_s) // 2]
    return {
        "probe": "incremental", "ok": True,
        "platform": jax.devices()[0].platform,
        "n": len(workloads), "admitted": admitted_n, "heads": len(heads),
        "dirty_admitted_rows": max(dirty) if dirty else 0,
        "dirty_pct": round(
            100.0 * max(dirty) / max(admitted_n, 1), 2) if dirty else 0.0,
        "cold_encode_ms": round(cold_s * 1000, 2),
        "encode_ms": round(inc_med * 1000, 2),
        "full_encode_ms": round(full_med * 1000, 2),
        "encode_speedup": round(full_med / inc_med, 1) if inc_med else 0.0,
        "device_ms": round(device_s * 1000, 2),
        "device_compile_s": round(compile_s, 1),
        "bit_identical": verified,
    }


def probe_whatif(scale: float):
    """The what-if engine's batching claim (docs/whatif.md): answering
    K - 1 = 7 capacity questions about one live 10k-workload snapshot as
    ONE batched K=8 forecast dispatch (`WhatIfEngine.eta(scenarios=...)`,
    whatif/batched.py) vs asking them one engine call at a time — the
    operator-facing sequential alternative, which re-collects, re-encodes,
    re-uploads, and re-rolls the base world per question. Wide saturated
    topology (50 cohorts x 100 CQs, nominal fits exactly one of the two
    8000m workloads each CQ holds, so the second wave waits a full
    runtime), 10k pending workloads at scale 1.0, identical horizon and
    kernel both ways. Each question grows one CQ by a full workload's
    quota, which pulls that CQ's second workload into the first wave —
    the vs_base deltas are real, not vacuous."""
    import jax

    from kueue_tpu.whatif.engine import QuotaDelta, Scenario, WhatIfEngine

    n_questions = 7  # + the base world = K = 8 lanes per dispatch
    cache, queues, workloads = build_scenario(
        scale, n_cohorts=50, n_cqs=100,
        classes=[("probe", max(1, int(2 * scale)), 8000, 50, 1.0)],
        nominal=8000, borrowing_limit=0,
    )
    for wl, _runtime_s in workloads:
        queues.add_or_update_workload(wl)
    eng = WhatIfEngine(
        cache, queues, default_runtime_ms=1000, horizon_rounds=64
    )
    scens = [
        Scenario(
            kind="quota", label=f"grow-cq-{k}-0",
            quota_deltas=(QuotaDelta(
                node=f"cq-{k}-0", flavor="default",
                resource="cpu", delta=8000,
            ),),
            drain_node=None, workload=None, cluster_queue=None,
        )
        for k in range(n_questions)
    ]

    # Compile all three shape buckets (K=1, K=2, K=8) before timing.
    t0 = time.monotonic()
    base = eng.eta()
    eng.eta(scenarios=scens[:1])
    eng.eta(scenarios=scens)
    compile_s = time.monotonic() - t0
    if base.basis != "rollout":
        return {"probe": "whatif", "ok": False,
                "error": f"fell back: {base.reason}"}

    # Best-of-N: single-core bench boxes jitter by tens of percent and
    # the two paths are measured back to back.
    batched_s = float("inf")
    for _ in range(3):
        t0 = time.monotonic()
        rep = eng.eta(scenarios=scens)
        batched_s = min(batched_s, time.monotonic() - t0)

    # Sequential baseline: one public-API call per question. Each is a
    # K=2 dispatch (vs_base needs the base lane from the same snapshot).
    sequential_s = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        for s in scens:
            eng.eta(scenarios=[s])
        sequential_s = min(sequential_s, time.monotonic() - t0)

    base_sf = rep.scenarios[0]
    return {
        "probe": "whatif",
        "ok": rep.basis == "rollout",
        "platform": jax.devices()[0].platform,
        "n": len(workloads),
        "k": len(rep.scenarios),
        "questions": n_questions,
        "horizon_rounds": 64,
        "rounds": base_sf.rounds,
        "base_admitted": base_sf.admitted_within_horizon,
        "compile_s": round(compile_s, 1),
        "batched_wall_s": round(batched_s, 3),
        "sequential_wall_s": round(sequential_s, 3),
        "speedup_x": round(sequential_s / batched_s, 2)
        if batched_s > 0 else 0.0,
        "scenarios_per_s": round(len(rep.scenarios) / batched_s, 2)
        if batched_s > 0 else 0.0,
    }


def probe_readplane(scale: float):
    """Multi-tenant read plane (docs/whatif.md, "Multi-tenant read
    plane"): K>=64 equivalent what-if load — seven tenants' quota
    sweeps, a drain matrix, a starvation bisection, ETAs and previews —
    coalesced into shared tiled rollout dispatches against one pinned
    double-buffered snapshot generation, vs the same queries issued
    solo. Three phases: (1) coalesced-vs-sequential wall on a pinned
    generation plus the concurrent differential (three seeds; coalesced
    answers must equal solo-issued answers with plain ``==``), (2) a
    read-idle service-loop churn window, (3) the same churn window under
    concurrent read traffic — the admission-cycle p99 delta between the
    two is the "reads never block admission" headline, gated generously
    here (single-core box) and median-tracked by the perf ledger.
    ``lane_budget=15`` tiles every batch through K=16 dispatches, so the
    scenario-plane working set stays bounded no matter how many queries
    coalesce (the memory story: ``plane_reduction_x``)."""
    import random
    import threading

    import jax

    from kueue_tpu.api.constants import PreemptionPolicy
    from kueue_tpu.api.types import (
        ClusterQueue,
        ClusterQueuePreemption,
        Cohort,
        FlavorQuotas,
        LocalQueue,
        PodSet,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
        Workload,
    )
    from kueue_tpu.manager import Manager
    from kueue_tpu.metrics.registry import Histogram
    from kueue_tpu.models.buckets import bucket_for, pow2_bucket
    from kueue_tpu.readplane.queries import (
        drain_matrix_query,
        eta_query,
        expand,
        preview_query,
        starve_search_query,
        sweep_query,
    )
    from kueue_tpu.tas.snapshot import Node
    from kueue_tpu.whatif.engine import Scenario

    mgr = Manager()
    m = mgr.metrics

    def rp_cq(name: str, nominal: int = 8000) -> ClusterQueue:
        return ClusterQueue(
            name=name, cohort="rp",
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(
                    name="default",
                    resources={"cpu": ResourceQuota(nominal=nominal)},
                )],
            )],
            preemption=ClusterQueuePreemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            ),
        )

    # The "default" flavor selects the probe nodes via node_labels (no
    # topology_name, so the what-if rollout path stays supported), which
    # makes the drain-matrix lanes real proportional quota cuts instead
    # of ForecastUnsupported fallbacks.
    mgr.apply(
        ResourceFlavor(name="default", node_labels={"pool": "rp"}),
        Cohort(name="rp"),
        Cohort(name="churn"),
        # cq-rp-0 gets 9000m so the standing admitted count lands at 25
        # (9+8+8) — one past the preview path's multiple-of-8 admitted
        # axis (encode's `a`) — and cq-churn's 7000m caps churn at 7
        # concurrent admissions, so total admitted holds in (24, 32]
        # and the A axis stays 32 through every phase. At 24 standing
        # (a rung boundary) the first churn admission mid-window forced
        # a fresh preview-kernel compile into the query-p99 headline.
        rp_cq("cq-rp-0", nominal=9000),
        *[rp_cq(f"cq-rp-{i}") for i in (1, 2)],
        # Churn rides its own cohort/CQ so the open-loop arrivals below
        # admit and finish without draining the rp CQs' standing backlog
        # (which pins the rollout's W bucket for the probe's lifetime).
        ClusterQueue(
            name="cq-churn", cohort="churn",
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(
                    name="default",
                    resources={"cpu": ResourceQuota(nominal=7000)},
                )],
            )],
            preemption=ClusterQueuePreemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            ),
        ),
        *[LocalQueue(name=f"lq-cq-rp-{i}", cluster_queue=f"cq-rp-{i}")
          for i in range(3)],
        LocalQueue(name="lq-churn", cluster_queue="cq-churn"),
    )
    for i in range(4):
        mgr.cache.add_or_update_node(Node(
            name=f"node-{i}", labels={"pool": "rp"},
            capacity={"cpu": 2000},
        ))
    # Standing backlog, built to pin the rollout shape statics for the
    # probe's whole lifetime: 14 x 1000m per rp CQ. Once the service
    # loop settles, 25 admit fleet-wide (9+8+8, quota-full, and nothing
    # ever finishes them — the churn observer only tracks churn-CQ
    # admissions) and 17 stay pending forever. Three budgets ride on
    # this:
    #  - w_pad (bucket_for of pending+admitted): 42 standing + the
    #    churn CQ's 0..16 in-flight stays inside the (32, 64] rung;
    #  - s_max (_pow2 of *active pending* + hypo heads, engine.py): 17
    #    standing pending keeps every dispatch in the (16, 32] band —
    #    15 would sit at the band edge and the first churn arrival
    #    during the loaded window would flip s_max 16 -> 32, a fresh
    #    ~60s XLA compile landing squarely in the query-p99 headline;
    #  - the preview A axis pinned at 32 by the quota split above.
    # Every serving-phase query therefore reuses the executables
    # phase 0 compiled instead of paying a mid-window recompile, and
    # sweeps / cuts / drains still move real admitted-within-horizon
    # numbers (the rollout's virtual time completes admitted
    # workloads, so the blocked tail admits late-horizon).
    for ci in range(3):
        for i in range(14):
            mgr.create_workload(Workload(
                name=f"rp-{ci}-{i}", queue_name=f"lq-cq-rp-{ci}",
                pod_sets=[PodSet(name="main", count=1,
                                 requests={"cpu": 1000})],
                priority=i % 3, creation_time=float(ci * 14 + i + 1),
            ))

    # Small horizon on the shared template: the read plane inherits it
    # (and the jit-cache dict) so probe compiles stay CPU-box friendly.
    tpl = mgr.whatif()
    tpl.default_runtime_ms = 1000
    tpl.horizon_rounds = 64
    rp = mgr.readplane(window=32, coalesce_delay_s=0.01, lane_budget=15)

    # Settle BEFORE the compile warmup, not after: warmup must run in
    # the same admitted/pending regime the serving windows measure, or
    # it warms the wrong s_max band (42 active pending pre-settle vs 18
    # post-settle) and the loaded window pays the recompile instead.
    svc = mgr.service(
        tick_interval_s=0.25, slo_interval_s=0.5, idle_sleep_s=0.005,
        stall_after_s=5.0, cycles_per_iter=8,
    )
    svc.start()
    t_settle = time.monotonic() + 30.0
    while time.monotonic() < t_settle:
        live_pending = sum(
            len(mgr.queues.pending_workloads_all(name))
            for name in mgr.queues.cluster_queues)
        if live_pending <= 17 and svc.ingest_depth() == 0:
            break
        time.sleep(0.05)

    rp.publish(force=True)
    rp.start()

    def hypo(name: str, ci: int) -> Workload:
        return Workload(
            name=name, queue_name=f"lq-cq-rp-{ci}",
            pod_sets=[PodSet(name="main", count=1,
                             requests={"cpu": 1000})],
            priority=5,
        )

    def make_queries():
        """Fresh Query objects per repetition (starve_search mutates its
        bisection bracket as it folds). ~65 first-round scenario lanes —
        the K>=64 equivalent load — spread over nine tenants."""
        qs = []
        for ti in range(7):
            qs.append(sweep_query(
                f"cq-rp-{ti % 3}", "default", "cpu",
                deltas=tuple(1000 * (d + 1) for d in range(8)),
                tenant=f"tenant-{ti}",
            ))
        qs.append(drain_matrix_query(
            tuple(f"node-{i}" for i in range(4)), tenant="ops"))
        qs.append(starve_search_query(
            "cq-rp-0", "default", "cpu", max_cut=6000, points=4,
            rounds=2, tenant="ops"))
        qs.append(eta_query(cluster_queue="cq-rp-1", tenant="tenant-0"))
        qs.append(eta_query(
            scenarios=(Scenario(
                kind="submit", label="hypo-submit",
                workload=hypo("rp-hypo-eta", 2),
                cluster_queue="cq-rp-2",
            ),),
            tenant="tenant-1",
        ))
        qs.append(preview_query(hypo("rp-hypo-prev-a", 0),
                                cluster_queue="cq-rp-0",
                                tenant="tenant-2"))
        qs.append(preview_query(hypo("rp-hypo-prev-b", 1),
                                cluster_queue="cq-rp-1",
                                tenant="tenant-3"))
        return qs

    mix_lanes = sum(len(expand(q)) for q in make_queries())
    n_queries = len(make_queries())

    # Phase 0: compile warmup — solo issuance touches every dispatch
    # shape (K=1/2/8/16 rollouts + the preview path); the coalesced pass
    # then reuses the same executables via the shared jit-cache dict.
    log("readplane: compile warmup (solo shapes + one coalesced pass)")
    t0 = time.monotonic()
    warm = [rp.query_solo(q) for q in make_queries()]
    bad = [a for a in warm if not isinstance(a, dict) or not a.get("ok")]
    if bad:
        return {"probe": "readplane", "ok": False,
                "error": f"warmup failed: {str(bad[0])[:200]}"}
    basis = next((a["basis"] for a in warm if "basis" in a), None)
    if basis != "rollout":
        return {"probe": "readplane", "ok": False,
                "error": f"fell back: basis={basis}"}
    for t in [rp.submit(q) for q in make_queries()]:
        t.result(120.0)
    compile_s = time.monotonic() - t0

    # Phase 1a: coalesced vs sequential wall on the pinned generation.
    # Best-of-N both ways: single-core boxes jitter by tens of percent.
    coalesced_s = float("inf")
    answers: list = []
    for _ in range(3):
        qs = make_queries()
        t0 = time.monotonic()
        tickets = [rp.submit(q) for q in qs]
        answers = [t.result(120.0) for t in tickets]
        coalesced_s = min(coalesced_s, time.monotonic() - t0)
    if not all(a.get("ok") for a in answers):
        return {"probe": "readplane", "ok": False,
                "error": "coalesced pass returned a failed answer"}
    sequential_s = float("inf")
    for _ in range(2):
        qs = make_queries()
        t0 = time.monotonic()
        for q in qs:
            rp.query_solo(q)
        sequential_s = min(sequential_s, time.monotonic() - t0)
    speedup = sequential_s / coalesced_s if coalesced_s > 0 else 0.0
    log(f"readplane: coalesced {coalesced_s:.3f}s vs sequential "
        f"{sequential_s:.3f}s (speedup {speedup:.2f}x)")

    # Phase 1b: concurrent differential — shuffled multi-thread issuance
    # must produce answers == solo issuance against the same pinned
    # generation (the bit-identity contract of readplane/queries.py).
    diff_ok = True
    diff_detail = []
    for seed in (1, 2, 3):
        rng = random.Random(seed)
        qs = make_queries()
        solo = [rp.query_solo(q) for q in make_queries()]
        order = list(range(len(qs)))
        rng.shuffle(order)
        results: list = [None] * len(qs)

        def issue(idxs, qs=qs, results=results):
            for i in idxs:
                results[i] = rp.query(qs[i], timeout=120.0)

        threads = [threading.Thread(target=issue, args=(order[t::4],))
                   for t in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        mismatches = [i for i in range(len(qs)) if results[i] != solo[i]]
        diff_detail.append({"seed": seed, "queries": len(qs),
                            "mismatches": len(mismatches)})
        if mismatches:
            diff_ok = False
            log(f"readplane differential seed {seed}: "
                f"{len(mismatches)} mismatched (first: query "
                f"{mismatches[0]} kind={qs[mismatches[0]].kind})")

    # Phase 2/3: service-loop churn windows, read-idle then read-loaded.
    duration_s = max(2.0, 40.0 * scale)
    window_seq = [0]

    def window_q_ms(series: str, before_counts, q: float):
        buckets, counts, _n = m.histogram_totals(series)
        if not buckets:
            return None
        prev = before_counts if before_counts else [0] * (len(buckets) + 1)
        dc = [c - p for c, p in zip(counts, prev)]
        dn = sum(dc)
        if dn <= 0:
            return None
        h = Histogram(buckets=buckets)
        h.counts = dc
        h.n = dn
        v = h.quantile(q)
        if v is None or v != v or v == float("inf"):
            return None
        return round(v * 1000, 3)

    def churn_window(readers_n: int) -> dict:
        window_seq[0] += 1
        tag = window_seq[0]
        before = {}
        for series in ("admission_attempt_duration_seconds",
                       "readplane_query_seconds",
                       "readplane_snapshot_staleness_seconds"):
            _b, counts, _n = m.histogram_totals(series)
            before[series] = list(counts)
        stop_readers = threading.Event()
        reader_stats = [[0, 0] for _ in range(readers_n)]  # [queries, errs]

        def reader_loop(rix: int) -> None:
            st = reader_stats[rix]
            while not stop_readers.is_set():
                for q in make_queries():
                    if stop_readers.is_set():
                        break
                    try:
                        a = rp.query(q, timeout=120.0)
                        st[0] += 1
                        if not a.get("ok"):
                            st[1] += 1
                    except Exception:  # noqa: BLE001 - counted, not fatal
                        st[1] += 1

        readers = [threading.Thread(target=reader_loop, args=(rix,),
                                    daemon=True)
                   for rix in range(readers_n)]
        for th in readers:
            th.start()
        running: list = []
        admitted_box = [0]

        def churn(result) -> None:
            admitted_box[0] += len(result.admitted)
            # Only churn-CQ workloads cycle through completion: the rp
            # CQs' standing backlog stays put (25 admitted + 17 pending
            # fleet-wide), pinning the rollout's shape statics for the
            # whole serving phase. Finish down to 4 so churn keeps
            # turning over inside cq-churn's 7-admission cap.
            running.extend(k for k in result.admitted if "/churn-" in k)
            while len(running) > 4:
                svc.finish(running.pop(0))

        svc.on_cycle.append(churn)
        t0 = time.monotonic()
        t_end = t0 + duration_s
        submitted = 0
        next_arrival = t0
        interval = 1.0 / 8.0  # arrivals/s, open loop
        while time.monotonic() < t_end:
            now = time.monotonic()
            while next_arrival <= now and next_arrival < t_end:
                svc.submit(Workload(
                    name=f"churn-{tag}-{submitted}",
                    queue_name="lq-churn",
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={"cpu": 1000})],
                    priority=submitted % 3,
                ))
                submitted += 1
                next_arrival += interval
            time.sleep(0.01)
        stop_readers.set()
        for th in readers:
            th.join(timeout=30.0)
        # Drain this window's churn out of the system entirely (admit
        # stragglers, then finish everything tracked) so the next
        # window — and the final stats — start from the standing-
        # backlog steady state, not on top of leftover churn quota.
        t_drain = time.monotonic() + 15.0
        while time.monotonic() < t_drain:
            churn_pending = len(
                mgr.queues.pending_workloads_all("cq-churn"))
            if churn_pending == 0 and svc.ingest_depth() == 0:
                break
            time.sleep(0.02)
        while running:
            svc.finish(running.pop(0))
        t_drain = time.monotonic() + 5.0
        while svc.ingest_depth() > 0 and time.monotonic() < t_drain:
            time.sleep(0.01)
        svc.on_cycle.remove(churn)
        _b, counts, _n = m.histogram_totals(
            "admission_attempt_duration_seconds")
        cycles = sum(c - p for c, p in zip(
            counts, before["admission_attempt_duration_seconds"]))
        return {
            "duration_s": round(duration_s, 3),
            "readers": readers_n,
            "submitted": submitted,
            "admitted": admitted_box[0],
            "cycles": cycles,
            "cycle_p99_ms": window_q_ms(
                "admission_attempt_duration_seconds",
                before["admission_attempt_duration_seconds"], 0.99),
            "queries": sum(st[0] for st in reader_stats),
            "query_errors": sum(st[1] for st in reader_stats),
            "query_p99_ms": window_q_ms(
                "readplane_query_seconds",
                before["readplane_query_seconds"], 0.99),
            "staleness_p99_ms": window_q_ms(
                "readplane_snapshot_staleness_seconds",
                before["readplane_snapshot_staleness_seconds"], 0.99),
        }

    log("readplane: read-idle churn window")
    idle = churn_window(readers_n=0)
    log("readplane: read-loaded churn window")
    loaded = churn_window(readers_n=3)
    svc.flush_telemetry()
    svc.stop()
    rp.stop()
    loop_errors = int(m.counter_total("service_loop_errors_total"))

    # Bounded-memory story: the tiled scenario plane (peak padded K any
    # single dispatch used) vs the padded K one monolithic dispatch of
    # the whole mix would allocate. Per-lane estimate: the (N,F,R)
    # nominal plane in int64 plus the W-padded active/result rows.
    peak_lanes = rp.coalescer.peak_tile_lanes
    rs = rp.publisher.current()
    w_pad = bucket_for((rs.pending_total if rs is not None else 48) + 2)
    per_lane_bytes = 3 * 1 * 1 * 8 + w_pad * 9
    untiled_lanes = pow2_bucket(mix_lanes + 1, floor=1)
    peak_plane_mb = peak_lanes * per_lane_bytes / 1e6
    untiled_plane_mb = untiled_lanes * per_lane_bytes / 1e6

    idle_p99 = idle.get("cycle_p99_ms")
    loaded_p99 = loaded.get("cycle_p99_ms")
    cycle_delta = (round(loaded_p99 - idle_p99, 3)
                   if isinstance(idle_p99, float)
                   and isinstance(loaded_p99, float) else None)
    # Generous absolute/relative bound: one slow box cycle is tens of
    # ms; the ledger's rolling median gates drift across runs.
    cycle_ok = (idle_p99 is None or loaded_p99 is None
                or loaded_p99 <= max(3.0 * idle_p99, idle_p99 + 15.0))
    ok = bool(
        speedup > 1.0
        and diff_ok
        and basis == "rollout"
        and peak_lanes <= 16
        and idle["admitted"] > 0
        and loaded["admitted"] > 0
        and loaded["queries"] > 0
        and loaded["query_errors"] == 0
        and loop_errors == 0
        and cycle_ok
    )
    stats = {
        "probe": "readplane",
        "ok": ok,
        "platform": jax.devices()[0].platform,
        "queries_per_mix": n_queries,
        "mix_lanes": mix_lanes,
        "tenants": 9,
        "compile_s": round(compile_s, 1),
        "coalesced_wall_s": round(coalesced_s, 3),
        "sequential_wall_s": round(sequential_s, 3),
        "readplane_coalesced_speedup": round(speedup, 2),
        "differential": {"ok": diff_ok, "seeds": diff_detail},
        "batches": rp.coalescer.batches,
        "total_lanes": rp.coalescer.total_lanes,
        "lane_budget": rp.coalescer.lane_budget,
        "peak_tile_lanes": peak_lanes,
        "untiled_lanes": untiled_lanes,
        "readplane_peak_plane_mb": round(peak_plane_mb, 6),
        "untiled_plane_mb": round(untiled_plane_mb, 6),
        "plane_reduction_x": round(untiled_lanes / peak_lanes, 2)
        if peak_lanes else 0.0,
        "idle": idle,
        "loaded": loaded,
        "readplane_cycle_p99_delta_ms": cycle_delta,
        "readplane_query_p99_ms": loaded.get("query_p99_ms"),
        "readplane_staleness_p99_ms": loaded.get("staleness_p99_ms"),
        "publish": rp.publisher.to_doc(),
        "loop_errors": loop_errors,
        "fingerprint_extra": {"version": 2, "mix_lanes": mix_lanes,
                              "lane_budget": 15},
    }
    return stats


def probe_encode(scale: float):
    """Columnar workload plane (docs/perf.md, "Columnar workload
    plane"): the cache-maintained struct-of-arrays store
    (cache/columns.py) turns the cold full encode into column slicing +
    ``np.take`` gathers. Two phases: (1) a 3-seed columns-vs-oracle
    bit-identity differential — direct encode, verify mode, tile
    planning, a full monolithic drive, a tiled + pipelined drive
    (arena deltas and speculation ride along), and a failover
    export/restore with the bulk column warm — all hard-gating ``ok``;
    (2) the timing story at W = 50k * scale on one dense backlog:
    the row-wise oracle full encode vs the warm-columns full encode
    (headline ``encode_cold_speedup``, gated >= 10x), the absolute
    columnar wall (``encode_50k_ms``), and the per-tile gather slice
    at the auto tile width (``encode_tile_slice_ms``). The timed phase
    runs ``device_put=False`` and must record zero backend compiles."""
    import random

    import jax

    from kueue_tpu.api.types import PodSet, Workload
    from kueue_tpu.core.workload_info import WorkloadInfo
    from kueue_tpu.models.arena import assert_cycle_equal
    from kueue_tpu.models.driver import DeviceScheduler
    from kueue_tpu.models.encode import (
        columns_mode,
        encode_cycle,
        plan_tiles,
        set_columns_mode,
    )
    from kueue_tpu.perf import compile_cache as cc

    W_TARGET = max(64, int(50_000 * scale))
    TILE_W = 8192
    SEEDS = (11, 23, 47)

    stats = {
        "probe": "encode", "ok": True,
        "platform": jax.devices()[0].platform,
        "w_target": W_TARGET,
        "fingerprint_extra": {"version": 1, "w_target": W_TARGET,
                              "tile_w": TILE_W, "seeds": len(SEEDS)},
    }
    prev_mode = columns_mode()

    def small_build(seed):
        rng = random.Random(seed)
        classes = [
            ("a", 4 + rng.randrange(4), 1000 * rng.randrange(1, 4),
             rng.randrange(100), 0.2),
            ("b", 2 + rng.randrange(3), 5000, 50 + rng.randrange(100),
             0.5),
        ]
        return build_scenario(1.0, n_cohorts=4, n_cqs=3, classes=classes)

    def pending_infos(queues, workloads):
        return [
            WorkloadInfo(wl, queues.cluster_queue_for(wl))
            for wl, _rt in workloads
        ]

    def drive(seed, mode, tile_width, pipeline):
        set_columns_mode(mode)
        cache, queues, workloads = small_build(seed)
        for wl, _rt in workloads:
            queues.add_or_update_workload(wl)
        sched = DeviceScheduler(cache, queues, tile_width=tile_width,
                                pipeline_cycles=pipeline)
        cycles = []
        prev_heads = None
        for _ in range(2000):
            res = sched.schedule()
            cycles.append((sorted(res.admitted), sorted(res.preempted),
                           sorted(res.skipped)))
            if res.admitted or res.preempted:
                prev_heads = None
                continue
            if not res.head_keys or res.head_keys == prev_heads:
                break
            prev_heads = res.head_keys
        return cycles

    def restore_differential(seed):
        # Failover shape: a standby restores from the checkpoint doc,
        # bulk-warms the columnar store, and its first encode must be
        # bit-identical to the row-wise oracle on the SAME restored
        # manager (restore re-stamps wall-clock fields, so two separate
        # restores are not comparable bit-for-bit).
        from kueue_tpu.manager import Manager

        rng = random.Random(seed)
        mgr = Manager()
        from kueue_tpu.api.types import (
            ClusterQueue,
            Cohort,
            FlavorQuotas,
            LocalQueue,
            ResourceFlavor,
            ResourceGroup,
            ResourceQuota,
        )

        mgr.apply(ResourceFlavor(name="default"), Cohort(name="enc"))
        for q in range(3):
            mgr.apply(
                ClusterQueue(
                    name=f"cq{q}", cohort="enc",
                    resource_groups=[ResourceGroup(
                        covered_resources=["cpu"],
                        flavors=[FlavorQuotas(
                            name="default",
                            resources={"cpu": ResourceQuota(nominal=4000)},
                        )],
                    )],
                ),
                LocalQueue(name=f"lq{q}", cluster_queue=f"cq{q}"),
            )
        for i in range(40):
            mgr.create_workload(Workload(
                name=f"w{i}", queue_name=f"lq{rng.randrange(3)}",
                pod_sets=[PodSet(
                    name="main", count=1,
                    requests={"cpu": 100 * rng.randrange(1, 5)},
                )],
                priority=rng.randrange(100), creation_time=float(i + 1),
            ))
        doc = mgr.export_state()
        mgr2 = Manager.restore_state(doc)
        heads = []
        for name in mgr2.queues.cluster_queues:
            heads.extend(mgr2.queues.pending_workloads(name))
        snap = mgr2.cache.snapshot()
        set_columns_mode("off")
        ref = encode_cycle(snap, heads, snap.resource_flavors,
                           preempt=True, device_put=False)
        set_columns_mode("on")
        filled = mgr2.warm_workload_columns()
        got = encode_cycle(snap, heads, snap.resource_flavors,
                           preempt=True, device_put=False)
        assert filled > 0, "restore warm filled no rows"
        assert_cycle_equal(got[0], got[1], ref[0], ref[1])

    try:
        # ---- Phase 1: 3-seed columns-vs-oracle differential ----------
        for seed in SEEDS:
            log(f"encode: differential seed {seed}")
            cache, queues, workloads = small_build(seed)
            for wl, _rt in workloads:
                queues.add_or_update_workload(wl)
            infos = pending_infos(queues, workloads)
            snap = cache.snapshot()
            set_columns_mode("off")
            ref = encode_cycle(snap, infos, snap.resource_flavors,
                               preempt=True, device_put=False)
            set_columns_mode("on")
            got = encode_cycle(snap, infos, snap.resource_flavors,
                               preempt=True, device_put=False)
            assert_cycle_equal(got[0], got[1], ref[0], ref[1])
            # Warm repeat must stay identical (pure gather, no refills).
            got = encode_cycle(snap, infos, snap.resource_flavors,
                               preempt=True, device_put=False)
            assert_cycle_equal(got[0], got[1], ref[0], ref[1])
            # Verify mode runs both paths and asserts internally.
            set_columns_mode("verify")
            encode_cycle(snap, infos, snap.resource_flavors,
                         preempt=True, device_put=False)
            # Tile planning parity off the same store columns.
            set_columns_mode("off")
            t_off = [[h.key for h in t]
                     for t in plan_tiles(infos, 64, snap)]
            set_columns_mode("on")
            t_on = [[h.key for h in t]
                    for t in plan_tiles(infos, 64, snap)]
            assert t_off == t_on, "plan_tiles order diverged"

            # End-to-end drives: monolithic, then tiled + pipelined
            # (arena deltas + speculation ride these paths).
            mono_off = drive(seed, "off", "off", "off")
            mono_on = drive(seed, "on", "off", "off")
            assert mono_off == mono_on, "monolithic drive diverged"
            tiled_off = drive(seed, "off", 16, "on")
            tiled_on = drive(seed, "on", 16, "on")
            assert tiled_off == tiled_on, "tiled/pipelined drive diverged"

            # Failover restore + bulk warm.
            restore_differential(seed)
        stats["differential_seeds"] = len(SEEDS)
        stats["bit_identical"] = True

        # ---- Phase 2: timing at W_TARGET --------------------------------
        log(f"encode: building {W_TARGET}-head backlog")
        per_cq = max(1, W_TARGET // 25)
        cache, queues, workloads = build_scenario(
            1.0, n_cohorts=5, n_cqs=5,
            classes=[("u", per_cq, 1000, 50, 0.2)],
        )
        for wl, _rt in workloads:
            queues.add_or_update_workload(wl)
        infos = pending_infos(queues, workloads)
        snap = cache.snapshot()
        stats["w_actual"] = len(infos)

        cc.configure()
        c0 = int(cc.stats().get("backend_compiles", 0))

        set_columns_mode("off")
        t0 = time.monotonic()
        ref = encode_cycle(snap, infos, snap.resource_flavors,
                           preempt=True, device_put=False)
        oracle_s = time.monotonic() - t0

        set_columns_mode("on")
        t0 = time.monotonic()
        encode_cycle(snap, infos, snap.resource_flavors,
                     preempt=True, device_put=False)
        cold_fill_s = time.monotonic() - t0
        t0 = time.monotonic()
        got = encode_cycle(snap, infos, snap.resource_flavors,
                           preempt=True, device_put=False)
        warm_s = time.monotonic() - t0
        assert_cycle_equal(got[0], got[1], ref[0], ref[1])

        # Per-tile slice: the auto tile width, store already warm.
        tile = infos[:min(TILE_W, len(infos))]
        t0 = time.monotonic()
        encode_cycle(snap, tile, snap.resource_flavors,
                     w_pad=len(tile), preempt=True, device_put=False)
        tile_s = time.monotonic() - t0

        # Tile planning at full width off the warm rank columns.
        t0 = time.monotonic()
        tiles = plan_tiles(infos, TILE_W, snap)
        plan_s = time.monotonic() - t0

        stats["warmed_compiles"] = int(
            cc.stats().get("backend_compiles", 0)) - c0
        stats["encode_oracle_ms"] = round(oracle_s * 1000, 1)
        stats["encode_cold_fill_ms"] = round(cold_fill_s * 1000, 1)
        stats["encode_50k_ms"] = round(warm_s * 1000, 2)
        stats["encode_tile_slice_ms"] = round(tile_s * 1000, 2)
        stats["plan_tiles_ms"] = round(plan_s * 1000, 2)
        stats["tiles_planned"] = len(tiles)
        stats["encode_cold_speedup"] = round(
            oracle_s / warm_s, 1) if warm_s > 0 else 0.0
        # The 10x target is defined at W=50k; at reduced scales fixed
        # costs (snapshot, axis maps, pad alloc) dominate both paths and
        # the ratio is meaningless, so only correctness gates apply.
        if W_TARGET >= 50_000 and stats["encode_cold_speedup"] < 10.0:
            stats["ok"] = False
            log("encode: cold speedup below the 10x gate")
        if stats["warmed_compiles"] != 0:
            stats["ok"] = False
            log("encode: warmed probe paid backend compiles")
    except AssertionError as exc:
        stats["ok"] = False
        stats["bit_identical"] = False
        stats["error"] = f"differential: {exc}"[:300]
    finally:
        set_columns_mode(prev_mode)
    return stats


def _steady_once(scale: float, pipeline: str):
    """One open-loop churn window against the STREAMING service loop
    (docs/observability.md "Service loop & live health") driving the
    DEVICE scheduler (``deviceKernel=auto``) with the pipelined-cycle
    mode forced to ``pipeline`` ("on" | "off"). A producer paces
    arrivals into ``ServiceLoop.post`` — arrivals never wait on
    completions, so a slow loop surfaces as queue growth and burn rate
    — while an ``on_cycle`` observer posts completions beyond a target
    concurrency, and the script injects a quota edit, a HOLD_AND_DRAIN
    drain, and a resume mid-run. Reports loop-health telemetry the way
    an operator would read it: admissions/s, cycle p50/p99, ingestion
    lag, watermark peaks, per-SLO burn, the ``/healthz`` document, and
    the scheduler's pipeline health. ``scale=1`` drives >=60s of churn;
    the CI contract test runs ``scale=0.05`` (~3s)."""
    from kueue_tpu.api.constants import PreemptionPolicy, StopPolicy
    from kueue_tpu.api.types import (
        ClusterQueue,
        ClusterQueuePreemption,
        Cohort,
        FlavorQuotas,
        LocalQueue,
        PodSet,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
        Workload,
    )
    from kueue_tpu.manager import Manager

    def steady_cq(nominal: int,
                  stop_policy=StopPolicy.NONE) -> ClusterQueue:
        return ClusterQueue(
            name="cq-steady", cohort="steady",
            resource_groups=[ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(
                    name="default",
                    resources={"cpu": ResourceQuota(nominal=nominal)},
                )],
            )],
            preemption=ClusterQueuePreemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
            ),
            stop_policy=stop_policy,
        )

    mgr = Manager(use_device_scheduler=True, device_kernel="auto",
                  pipeline_cycles=pipeline)
    mgr.apply(
        ResourceFlavor(name="default"),
        Cohort(name="steady"),
        steady_cq(16000),
        LocalQueue(name="lq-steady", cluster_queue="cq-steady"),
    )
    # Warm the W=16 scan bucket before the window opens so neither mode
    # pays compile time inside its churn run (the second window's
    # prewarm hits the in-process jit cache and is ~free).
    mgr.prewarm(max_heads=16, aot=False)
    m = mgr.metrics
    svc = mgr.service(
        tick_interval_s=0.25, slo_interval_s=0.5, idle_sleep_s=0.005,
        stall_after_s=5.0, cycles_per_iter=8,
    )

    # Completion churn rides the telemetry stage: every admitted key
    # beyond the concurrency target gets a finish posted back through
    # the ingest path (never a direct manager call — the observer must
    # not touch state).
    churn_target = 12
    running: list = []
    admitted_box = [0]

    def churn(result) -> None:
        admitted_box[0] += len(result.admitted)
        running.extend(result.admitted)
        while len(running) > churn_target:
            svc.finish(running.pop(0))

    svc.on_cycle.append(churn)
    svc.start()

    duration_s = max(3.0, 60.0 * scale)
    rate = 16.0  # arrivals/s, open loop
    interval = 1.0 / rate
    t0 = time.monotonic()
    t_end = t0 + duration_s
    events = {"quota_edit": 0.35, "drain": 0.55, "resume": 0.70}
    fired = set()
    submitted = 0
    rejected = 0
    depth_peak = 0.0
    oldest_age_peak = 0.0
    next_arrival = t0
    while True:
        now = time.monotonic()
        if now >= t_end:
            break
        while next_arrival <= now and next_arrival < t_end:
            ok = svc.submit(Workload(
                name=f"steady-{submitted}",
                queue_name="lq-steady",
                pod_sets=[PodSet(name="main", count=1,
                                 requests={"cpu": 1000})],
                priority=submitted % 3,
            ))
            submitted += 1
            if not ok:
                rejected += 1
            next_arrival += interval
        frac = (now - t0) / duration_s
        for name, at in events.items():
            if frac >= at and name not in fired:
                fired.add(name)
                log(f"steady event @{frac:.2f}: {name}")
                if name == "quota_edit":
                    svc.apply(steady_cq(24000))
                elif name == "drain":
                    svc.apply(steady_cq(24000,
                                        StopPolicy.HOLD_AND_DRAIN))
                else:
                    svc.apply(steady_cq(24000))
        # Watermark peaks off the exported gauges — the operator's view.
        depth_peak = max(depth_peak, m.get(
            "service_queue_depth", {"cluster_queue": "cq-steady"}
        ))
        oldest_age_peak = max(oldest_age_peak, m.get(
            "service_oldest_pending_age_seconds",
            {"cluster_queue": "cq-steady"},
        ))
        time.sleep(min(0.02, max(0.0, next_arrival - time.monotonic())))
    # Let the loop drain the tail of the ingest queue (late submits and
    # the observer's last completions) before stopping, so the applied-op
    # accounting below sees every event.
    t_drain = time.monotonic() + 10.0
    while svc.ingest_depth() > 0 and time.monotonic() < t_drain:
        time.sleep(0.01)
    svc.flush_telemetry()
    svc.stop()
    wall = time.monotonic() - t0
    admitted_total = admitted_box[0]

    def q_ms(series: str, q: float):
        v = m.histogram_quantile(series, q)
        if v is None or v != v or v == float("inf"):
            return None
        return round(v * 1000, 3)

    statuses = mgr.slo().evaluate()
    _, _, cycles_n = m.histogram_totals(
        "admission_attempt_duration_seconds"
    )
    loop_errors = int(m.counter_total("service_loop_errors_total"))
    applies = int(m.counter_total("service_ingest_ops_total"))
    health = svc.health()
    ok = bool(
        admitted_total > 0
        and cycles_n > 0
        and loop_errors == 0
        and len(fired) == len(events)
        and applies >= submitted + len(events)
    )
    return {
        "ok": ok,
        "pipeline_mode": pipeline,
        "pipeline": mgr.scheduler.pipeline_health(),
        "duration_s": round(duration_s, 3),
        "wall_s": round(wall, 3),
        "arrival_rate_per_s": rate,
        "submitted": submitted,
        "rejected_posts": rejected,
        "admitted": admitted_total,
        "finished": int(m.get("workloads_finished_total")),
        "pending_after": mgr.queues.pending_count("cq-steady"),
        "events_fired": sorted(fired),
        "admissions_per_s": round(admitted_total / wall, 2)
        if wall > 0 else 0.0,
        "cycles": cycles_n,
        "cycle_p50_ms": q_ms("admission_attempt_duration_seconds", 0.50),
        "cycle_p99_ms": q_ms("admission_attempt_duration_seconds", 0.99),
        "ingest_lag_p50_ms": q_ms("service_ingest_lag_seconds", 0.50),
        "ingest_lag_p99_ms": q_ms("service_ingest_lag_seconds", 0.99),
        "admit_wait_p99_ms": q_ms("service_submit_to_admit_seconds",
                                  0.99),
        "queue_depth_peak": depth_peak,
        "oldest_pending_age_peak_s": round(oldest_age_peak, 3),
        "loop_iterations": int(
            m.counter_total("service_loop_iterations_total")
        ),
        "loop_errors": loop_errors,
        "health": health,
        "healthy": all(st.healthy for st in statuses),
        "slos": [st.to_dict() for st in statuses],
    }


def probe_steady(scale: float):
    """Steady v3: the v2 open-loop churn window run TWICE in one
    invocation — serialized (``pipelineCycles=off``) first, then
    pipelined (``on``) — against the device scheduler with
    ``deviceKernel=auto``, so the ledger captures both modes under one
    fingerprint. The record carries the pipelined run's loop-health
    stats at top level, a ``serialized`` mirror of the baseline window,
    and the pipeline-specific headline metrics: overlap occupancy (what
    fraction of device-dispatch wall time the speculative host encode
    filled), total abandoned speculations, and pipelined-minus-
    serialized deltas for admissions/s and cycle p99. Arrivals are
    open-loop paced, so admissions/s is arrival-bound in both modes —
    the deltas gate on "pipelining must not make the loop worse", while
    occupancy > 0 proves the overlap actually happened."""
    log("steady v3: serialized window (pipelineCycles=off)")
    base = _steady_once(scale, "off")
    log("steady v3: pipelined window (pipelineCycles=on)")
    piped = _steady_once(scale, "on")
    ph = piped.get("pipeline") or {}
    occupancy = float(ph.get("overlapOccupancyPct") or 0.0)

    def delta(key, pct=False):
        a, b = base.get(key), piped.get(key)
        if not isinstance(a, (int, float)) \
                or not isinstance(b, (int, float)):
            return None
        if pct:
            return round(100.0 * (b - a) / a, 2) if a else 0.0
        return round(b - a, 3)

    out = dict(piped)
    out["probe"] = "steady"
    # v3 runs the device scheduler and both pipeline modes in one
    # invocation: a new ledger fingerprint group, so the gate baselines
    # fresh instead of comparing across probe designs.
    out["fingerprint_extra"] = {
        "version": 3, "device_kernel": "auto",
        "modes": "serialized+pipelined",
    }
    out["serialized"] = {
        k: base.get(k) for k in (
            "ok", "admissions_per_s", "admitted", "cycles",
            "cycle_p50_ms", "cycle_p99_ms", "ingest_lag_p99_ms",
            "loop_errors", "queue_depth_peak", "pipeline",
        )
    }
    out["pipeline_overlap_occupancy_pct"] = round(occupancy, 3)
    out["pipeline_abort_total"] = int(ph.get("abortTotal") or 0)
    out["admissions_per_s_delta_pct"] = delta("admissions_per_s",
                                              pct=True)
    out["cycle_p99_delta_ms"] = delta("cycle_p99_ms")
    out["ok"] = bool(base["ok"] and piped["ok"] and occupancy > 0.0)
    return out


def probe_failover(scale: float, seed: int = 1808):
    """Warm-failover drill (docs/failover.md): a primary ServiceLoop
    with a ``Replicator`` streams crash-consistent records to a warm
    standby through a durable ``LeaseStore`` while a steady-style churn
    runs against it (paced submits, completion churn past a concurrency
    target). At a seeded mid-churn step the primary "crashes": the
    step's record is already durable (write-ahead of the ack) but its
    acks die with the process, and a torn half-record is left on the
    stream tail. The virtual clock runs the lease out, the standby
    promotes (strict final replay, torn-tail truncation, lease CAS) and
    the driver finishes the schedule against it, re-issuing every op
    that was never acked (idempotent replay). Correctness gates by
    differential against an unkilled twin run of the identical
    schedule: zero lost and zero duplicated admission acks, zero
    standby fingerprint mismatches, and the takeover window (promote +
    first post-takeover admission cycle) pays zero backend compiles —
    the standby's bucket ladder is AOT-warm from the shared store."""
    import random
    import shutil
    import tempfile
    from collections import Counter

    from kueue_tpu.api.types import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        PodSet,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
        Workload,
    )
    from kueue_tpu.controllers.ha import (
        LeaseStore,
        Replicator,
        WarmStandby,
    )
    from kueue_tpu.manager import Manager
    from kueue_tpu.perf import compile_cache as cc

    n = max(24, int(round(240 * scale)))
    batch = 4
    churn_target = 8
    lease_s = 0.5
    dt = 0.05  # virtual seconds per step
    heads = 16
    submit_steps = (n + batch - 1) // batch
    rng = random.Random(seed)
    kill_step = rng.randint(max(1, submit_steps // 3),
                            max(2, (2 * submit_steps) // 3))

    workdir = tempfile.mkdtemp(prefix="kueue_tpu_failover_")
    # Shared persistent compile cache + AOT executable store: the
    # primary's prewarm populates it, the standby's (re-)prewarm loads
    # from it — the takeover window must not compile.
    cc.configure(cache_dir=os.path.join(workdir, "xla"))
    cc.install_listeners()

    def wl_for(i: int) -> Workload:
        return Workload(
            name=f"ha-{i}", queue_name="lq-ha",
            pod_sets=[PodSet(name="main", count=1,
                             requests={"cpu": 1})],
        )

    def specs():
        # Fresh objects per manager (apply takes ownership). Quota is
        # ample — every key admits exactly once, so the differential is
        # exact set equality, never an eviction race.
        return [
            ResourceFlavor(name="default"),
            ClusterQueue(
                name="cq-ha",
                resource_groups=[ResourceGroup(
                    covered_resources=["cpu"],
                    flavors=[FlavorQuotas(
                        name="default",
                        resources={"cpu": ResourceQuota(nominal=2 * n)},
                    )],
                )],
            ),
            LocalQueue(name="lq-ha", cluster_queue="cq-ha"),
        ]

    def one_run(kill: bool) -> dict:
        clk = [0.0]
        mkw = dict(use_device_scheduler=True, device_kernel="scan",
                   clock=lambda: clk[0])
        store = LeaseStore(
            lease_duration_s=lease_s,
            dir=os.path.join(workdir, "kill" if kill else "twin"),
        )
        mgr = Manager(**mkw)
        mgr.apply(*specs())
        mgr.prewarm(max_heads=heads, aot=True)
        svc = mgr.service(tick_interval_s=None, idle_sleep_s=0.0,
                          cycles_per_iter=4, telemetry_async=False)
        rep = Replicator(store).attach(svc)
        store.try_acquire("primary", clk[0])

        standby = None
        if kill:
            standby = WarmStandby("standby", store, manager_kw=mkw)
            standby.prewarm(max_heads=heads, aot=True)

        acks: list = []      # every admission ack a client received
        running: list = []   # acked keys not yet finished (churn pool)
        cycle_box: list = []
        svc.on_cycle.append(lambda r: cycle_box.extend(r.admitted))

        submitted = 0
        step = 0
        crashed = False
        while True:
            clk[0] += dt
            store.try_acquire("primary", clk[0])
            while submitted < n and submitted < (step + 1) * batch:
                svc.submit(wl_for(submitted))
                submitted += 1
            cycle_box.clear()
            svc.step()
            step += 1
            step_acks = list(cycle_box)
            if kill and step == kill_step:
                # CRASH. The step's stream record is fsync'd
                # (write-ahead) but its acks were never delivered, and
                # the next append died mid-write: torn garbage on the
                # tail (a length the file can't satisfy).
                with open(store.stream.path, "ab") as f:
                    f.write(b"\x00\x01\x00\x00torn-half-record")
                crashed = True
                break
            acks.extend(step_acks)
            running.extend(step_acks)
            while len(running) > churn_target:
                svc.finish(running.pop(0))
            if standby is not None:
                standby.poll(clk[0])
            if submitted >= n and len(set(acks)) >= n:
                svc.step()  # drain the last finishes
                break
            if step > submit_steps + 400:
                break
        out = {
            "steps": step, "submitted": submitted,
            "records_written": rep.records_written,
            "stream_bytes": store.stream.size(),
            "acks": acks, "crashed": crashed,
        }
        if not kill:
            store.stream.close()
            return out

        # Run the lease out on the virtual clock, then let the standby
        # take over and serve the rest of the schedule.
        clk[0] += lease_s + dt
        c0 = int(cc.stats().get("backend_compiles", 0))
        t0 = time.perf_counter()
        role = standby.poll(clk[0])
        svc2 = standby.manager.service(
            tick_interval_s=None, idle_sleep_s=0.0,
            cycles_per_iter=4, telemetry_async=False,
        )
        rep2 = Replicator(store).attach(svc2)
        cycle_box2: list = []
        svc2.on_cycle.append(lambda r: cycle_box2.extend(r.admitted))

        # Client recovery: re-issue everything never acked. Keys the
        # stream already made durable are answered idempotently from
        # the standby's state (admitted -> the single ack arrives now);
        # only truly-lost ops are re-submitted for a fresh decision.
        acked = set(acks)
        for i in range(submitted):
            key = wl_for(i).key
            if key in acked:
                continue
            if key in standby.manager.workloads:
                if key in standby.manager.cache.workloads:
                    acks.append(key)
                # else: still pending — admitted by a cycle below.
            else:
                svc2.submit(wl_for(i))
        # Unconfirmed finishes (posted into the dead primary's ingest
        # queue, never applied): re-issue; finish_workload is a no-op
        # on an already-finished workload.
        for key in list(running):
            if key in standby.manager.workloads:
                svc2.finish(key)

        first_cycle = {}
        while True:
            clk[0] += dt
            store.try_acquire("standby", clk[0])
            while submitted < n and submitted < (step + 1) * batch:
                svc2.submit(wl_for(submitted))
                submitted += 1
            cycle_box2.clear()
            svc2.step()
            step += 1
            if not first_cycle:
                first_cycle = {
                    "takeover_ms": round(
                        (time.perf_counter() - t0) * 1000.0, 3),
                    "takeover_compiles": int(
                        cc.stats().get("backend_compiles", 0)) - c0,
                }
            acks.extend(cycle_box2)
            running.extend(cycle_box2)
            while len(running) > churn_target:
                svc2.finish(running.pop(0))
            if submitted >= n and len(set(acks)) >= n:
                svc2.step()
                break
            if step > submit_steps + 400:
                break
        store.stream.close()
        out.update({
            "acks": acks, "submitted": submitted, "steps": step,
            "role": role, "promoted": standby.promoted,
            "records_applied": standby.records_applied,
            "replayed_at_takeover": standby.manager.metrics.get(
                "failover_replayed_records"),
            "truncated_bytes": standby.truncated_bytes,
            "fingerprint_mismatches": standby.fingerprint_mismatches,
            "promote_ms": round(
                (standby.takeover_seconds or 0.0) * 1000.0, 3),
            "records_written_2": rep2.records_written,
            **first_cycle,
        })
        return out

    log(f"failover: twin run (n={n}, {submit_steps} submit steps)")
    twin = one_run(kill=False)
    log(f"failover: kill run (kill step {kill_step})")
    rec = one_run(kill=True)
    shutil.rmtree(workdir, ignore_errors=True)

    twin_set = set(twin["acks"])
    counts = Counter(rec["acks"])
    lost = sorted(twin_set - set(counts))
    dups = sorted(k for k, c in counts.items() if c > 1)
    ok = bool(
        rec["crashed"]
        and rec.get("promoted")
        and len(twin_set) == n
        and not lost
        and not dups
        and set(counts) == twin_set
        and rec.get("takeover_compiles") == 0
        and rec.get("truncated_bytes", 0) > 0
        and rec.get("fingerprint_mismatches") == 0
    )
    return {
        "probe": "failover", "ok": ok,
        "n_workloads": n, "seed": seed, "kill_step": kill_step,
        "failover_takeover_ms": rec.get("takeover_ms"),
        "failover_promote_ms": rec.get("promote_ms"),
        "failover_lost_admissions": len(lost),
        "failover_dup_admissions": len(dups),
        "failover_takeover_compiles": rec.get("takeover_compiles"),
        "failover_truncated_bytes": rec.get("truncated_bytes"),
        "failover_replayed_records": rec.get("replayed_at_takeover"),
        "fingerprint_mismatches": rec.get("fingerprint_mismatches"),
        "twin_admitted": len(twin_set),
        "recovered_admitted": len(set(counts)),
        "records_written": rec.get("records_written"),
        "records_applied": rec.get("records_applied"),
        "stream_bytes": rec.get("stream_bytes"),
        "twin_steps": twin["steps"], "kill_steps": rec["steps"],
        "lost_keys": lost[:8], "dup_keys": dups[:8],
        "fingerprint_extra": {"version": 1, "seed": seed},
    }


def probe_scanfloor(scale: float):
    """Scan-vs-fixed-point cycle latency + rounds-taken on tiny CPU-scale
    encoded cycles across three quota mixes (plain borrow-limits,
    lending limits, preemption). Each mix captures a REAL encoded cycle
    from a scan-mode DeviceScheduler run, then times both kernels on the
    identical arrays (best-of-N, block_until_ready) and spot-checks
    outcome equality. The point is the shape of the floor, not absolute
    numbers: the scan pays ~one sequential step per admission slot while
    the fixed point pays a handful of fully-parallel rounds (BENCH_r05
    floor analysis; docs/perf.md coverage matrix)."""
    import jax
    import numpy as np

    from kueue_tpu.api.constants import PreemptionPolicy
    from kueue_tpu.api.types import (
        ClusterQueue,
        ClusterQueuePreemption,
        Cohort,
        FlavorQuotas,
        LocalQueue,
        PodSet,
        ResourceFlavor,
        ResourceGroup,
        ResourceQuota,
        Workload,
    )
    from kueue_tpu.manager import Manager
    from kueue_tpu.models import batch_scheduler as bs
    from kueue_tpu.models.driver import DeviceScheduler
    from kueue_tpu.perf import compile_cache

    n_cq = max(4, min(12, int(8 * scale)))
    s_resid = 16  # residual-scan rung covering every probe cycle

    def build(mix):
        """One cohort forest + a wave of pending heads; returns the first
        encoded cycle the scan driver actually dispatches: (arrays, ga,
        adm) for the grouped mixes, (arrays, adm, s_max) for "fair"."""
        mgr = Manager(fair_sharing=(mix == "fair"))
        preemption = ClusterQueuePreemption()
        if mix == "preempt":
            preemption = ClusterQueuePreemption(
                within_cluster_queue=PreemptionPolicy.LOWER_PRIORITY,
                reclaim_within_cohort=PreemptionPolicy.LOWER_PRIORITY,
            )
        objs = [ResourceFlavor(name="default"),
                Cohort(name="co0"), Cohort(name="co1")]
        for i in range(n_cq):
            lend = 2000 if (mix == "lending" and i % 2 == 0) else None
            rgs = [ResourceGroup(
                covered_resources=["cpu"],
                flavors=[FlavorQuotas(
                    name="default",
                    resources={"cpu": ResourceQuota(
                        4000 + 1000 * (i % 3), 3000, lend)},
                )],
            )]
            if mix == "multislot":
                # A second resource group forces the slot layout (the
                # encoded s_req planes) — these heads now ride the
                # hybrid kernel's residual scan instead of being
                # scan-only shapes.
                rgs.append(ResourceGroup(
                    covered_resources=["gpu"],
                    flavors=[FlavorQuotas(
                        name="default",
                        resources={"gpu": ResourceQuota(4000, 2000)},
                    )],
                ))
            objs.append(ClusterQueue(
                name=f"cq{i}", cohort=f"co{i % 2}",
                resource_groups=rgs,
                preemption=preemption,
            ))
            objs.append(LocalQueue(name=f"lq{i}", cluster_queue=f"cq{i}"))
        mgr.apply(*objs)
        sched = DeviceScheduler(mgr.cache, mgr.queues,
                                fair_sharing=(mix == "fair"))
        if mix == "preempt":
            # Fillers first: admitted low-priority victims to preempt.
            for i in range(n_cq):
                mgr.create_workload(Workload(
                    name=f"fill{i}", queue_name=f"lq{i}",
                    pod_sets=[PodSet(name="main", count=1,
                                     requests={"cpu": 4000})],
                    priority=0, creation_time=float(i + 1),
                ))
            sched.schedule_all(max_cycles=20)
        for i in range(2 * n_cq):
            reqs = {"cpu": 1500 + 500 * (i % 4)}
            if mix == "multislot":
                reqs["gpu"] = 1000 + 500 * (i % 3)
            mgr.create_workload(Workload(
                name=f"w{i}", queue_name=f"lq{i % n_cq}",
                pod_sets=[PodSet(name="main", count=1, requests=reqs)],
                priority=100 + (i % 3) * 100,
                creation_time=float(100 + i),
            ))
        want = ("cycle_fair_preempt" if mix == "fair"
                else "cycle_grouped_preempt")
        captured = []
        orig = compile_cache.dispatch

        def spy(entry, fn, *a, **kw):
            if entry == want and not captured:
                captured.append((a, kw.get("static", ())))
            return orig(entry, fn, *a, **kw)

        compile_cache.dispatch = spy
        try:
            sched.schedule()
        finally:
            compile_cache.dispatch = orig
        if not captured:
            raise RuntimeError(f"mix {mix}: no device cycle dispatched")
        a, static = captured[0]
        if mix == "fair":
            return a[0], a[1], static[1]
        return a

    def best_of(fn, args, n=7):
        out = fn(*args)
        jax.block_until_ready(out.outcome)  # compile outside the clock
        best = None
        for _ in range(n):
            t = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out.outcome)
            dt = time.perf_counter() - t
            best = dt if best is None or dt < best else best
        return best, out

    mixes = {}
    ok = True
    rounds_max = 0
    fair_rounds_max = 0
    speedups = []
    fair_speedups = []
    for mix in ("plain", "lending", "preempt", "multislot", "fair"):
        built = build(mix)
        if mix == "fair":
            from kueue_tpu.models import fair_fixedpoint as ffp
            from kueue_tpu.models import fair_kernel as fkm

            arrays, adm, s_max = built
            scan_s, out_scan = best_of(
                fkm.fair_cycle_preempt_for(s_max), (arrays, adm))
            fp_s, out_fp = best_of(
                ffp.fair_fixedpoint_cycle_for(s_max), (arrays, adm))
            planes = ("outcome", "usage")
        else:
            arrays, ga, adm = built
            scan_s, out_scan = best_of(
                bs.cycle_grouped_preempt, (arrays, ga, adm))
            if mix in ("preempt", "multislot"):
                # multislot heads ride the hybrid's residual scan now
                # (the slot-tree partition), same entry as preemption.
                fp_fn = bs.fixedpoint_cycle_preempt_for(s_resid)
                fp_s, out_fp = best_of(fp_fn, (arrays, ga, adm))
                planes = ("outcome", "usage", "victims")
            else:
                fp_s, out_fp = best_of(bs.cycle_fixedpoint, (arrays, ga))
                planes = ("outcome", "usage")
        match = all(
            np.array_equal(np.asarray(getattr(out_scan, p)),
                           np.asarray(getattr(out_fp, p)))
            for p in planes
            if getattr(out_scan, p) is not None
            or getattr(out_fp, p) is not None
        )
        rounds = int(np.asarray(out_fp.fp_rounds))
        converged = bool(np.asarray(out_fp.converged))
        ok = ok and match and converged
        speedup = scan_s / fp_s if fp_s > 0 else 0.0
        if mix == "fair":
            fair_rounds_max = max(fair_rounds_max, rounds)
            fair_speedups.append(speedup)
        else:
            rounds_max = max(rounds_max, rounds)
            speedups.append(speedup)
        mixes[mix] = {
            "scan_ms": round(scan_s * 1000, 3),
            "fp_ms": round(fp_s * 1000, 3),
            "speedup": round(speedup, 2) if fp_s > 0 else None,
            "rounds": rounds,
            "heads_bucket": int(np.asarray(arrays.w_cq).shape[0]),
            "match": match,
        }
        log(f"scanfloor[{mix}]: scan={scan_s * 1e3:.2f}ms "
            f"fp={fp_s * 1e3:.2f}ms rounds={rounds} match={match}")
    return {
        "probe": "scanfloor",
        "ok": ok and rounds_max <= 8 and fair_rounds_max <= 8,
        "n_cq": n_cq,
        # fp_speedup < 1 on CPU is expected (the fixed-point rounds are
        # slower than the grouped scan under JAX CPU emulation) and is
        # exactly why deviceKernel=auto now prefers the scan on a CPU
        # backend (driver._fp_auto_ok / autoCpuKernel) — the default
        # path no longer pays this penalty; the probe keeps measuring
        # it so a kernel-side fix shows up in the ledger. The same
        # caveat applies to fair_fp_speedup (the fair rounds vs the DRS
        # tournament scan).
        "fingerprint_extra": {
            "note": "auto-on-cpu prefers scan; fp timed for the record",
            "v": 2,  # + fair / multislot mixes (fair fixed-point PR)
        },
        "fp_speedup": round(min(speedups), 2) if speedups else 0.0,
        "rounds_max": rounds_max,
        "fair_fp_speedup": (
            round(min(fair_speedups), 2) if fair_speedups else 0.0
        ),
        "fair_rounds_max": fair_rounds_max,
        "mixes": mixes,
    }


def build_tas_scenario(scale: float = 1.0):
    """Scaled-down BASELINE config #4: one 3-level TPU topology
    (block / rack / host), two ClusterQueues over a TAS flavor, and a
    wave of multi-podset gangs with mixed slot counts — 2- and 3-podset
    gangs with required levels spread across the hierarchy, every third
    gang carrying an extra plain (non-TAS) podset. This is the shape
    whose per-slot placement the batched slot pass
    (models/slot_tas.py) vectorizes; tests/test_slot_tas.py reuses the
    builder so the probe and the differential pin the same scenario.

    Returns ``(mgr, sched, workloads)`` with the gangs already created
    and pending.
    """
    from kueue_tpu.api.types import (
        ClusterQueue,
        FlavorQuotas,
        LocalQueue,
        PodSet,
        ResourceFlavor,
        ResourceGroup,
        Topology,
        TopologyRequest,
        Workload,
        quota,
    )
    from kueue_tpu.manager import Manager
    from kueue_tpu.models.driver import DeviceScheduler
    from kueue_tpu.tas.snapshot import Node

    levels = ["tpu.block", "tpu.rack", "kubernetes.io/hostname"]
    blocks = max(2, int(2 * scale))
    racks, hosts = 2, 2
    mgr = Manager()
    objs = [
        ResourceFlavor(name="tpu-v5e", topology_name="topo"),
        Topology(name="topo", levels=levels),
    ]
    for q in range(2):
        objs.append(ClusterQueue(
            name=f"cq{q}",
            resource_groups=[ResourceGroup(
                covered_resources=["tpu"],
                flavors=[FlavorQuotas(
                    name="tpu-v5e", resources={"tpu": quota(100_000)},
                )],
            )],
        ))
        objs.append(LocalQueue(name=f"lq{q}", cluster_queue=f"cq{q}"))
    mgr.apply(*objs)
    for b in range(blocks):
        for r in range(racks):
            for h in range(hosts):
                mgr.apply(Node(
                    name=f"n-{b}-{r}-{h}",
                    labels={"tpu.block": f"b{b}",
                            "tpu.rack": f"b{b}-r{r}"},
                    capacity={"tpu": 16},
                ))
    n_gangs = max(6, int(8 * scale))
    workloads = []
    for i in range(n_gangs):
        n_ps = 2 + (i % 2)  # mixed slot counts: 2- and 3-podset gangs
        pod_sets = []
        for p in range(n_ps):
            level = levels[(i + p) % len(levels)]
            pod_sets.append(PodSet(
                name=f"ps{p}", count=1 + (p % 2),
                requests={"tpu": 2 + 2 * (p % 2)},
                topology_request=TopologyRequest(required_level=level),
            ))
        if i % 3 == 2:
            pod_sets.append(PodSet(
                name="aux", count=1, requests={"tpu": 1},
            ))
        workloads.append(Workload(
            name=f"gang{i}", queue_name=f"lq{i % 2}",
            pod_sets=pod_sets,
            priority=100 * (i % 3), creation_time=float(i + 1),
        ))
    sched = DeviceScheduler(mgr.cache, mgr.queues)
    for wl in workloads:
        mgr.create_workload(wl)
    return mgr, sched, workloads


def probe_tas(scale: float):
    """Batched slot pass vs the retired per-slot loop on a REAL encoded
    multi-podset TAS cycle. Captures the first ``cycle_grouped_preempt``
    dispatch of a config-#4-shaped gang wave (build_tas_scenario), then
    times two fresh jits of the same grouped-preempt factory on the
    identical arrays: once as shipped (models/slot_tas.place_slots, the
    batched pass + bounded conflict scan) and once with the module
    attribute swapped to ``place_slots_reference`` — the sequential
    per-slot oracle that reproduces the five unrolled scans this PR
    deleted. Headlines: ``tas_slot_speedup`` (reference wall / batched
    wall per cycle) and ``tas_compile_s_delta`` (batched trace+compile
    minus reference — the unrolled loop's S-times-larger graph is the
    compile-time cost the pass removes). ``ok`` additionally requires
    bit-identical outcome/usage planes between the arms and the
    conflict-scan bound ``0 <= rounds <= S``."""
    import jax
    import numpy as np

    from kueue_tpu.models import batch_scheduler as bs
    from kueue_tpu.models import slot_tas
    from kueue_tpu.perf import compile_cache

    mgr, sched, workloads = build_tas_scenario(scale)

    captured = []
    orig = compile_cache.dispatch

    def spy(entry, fn, *a, **kw):
        if entry == "cycle_grouped_preempt" and not captured:
            captured.append(a)
        return orig(entry, fn, *a, **kw)

    compile_cache.dispatch = spy
    try:
        sched.schedule()
    finally:
        compile_cache.dispatch = orig
    if not captured:
        raise RuntimeError("no grouped TAS device cycle dispatched")
    arrays, ga, adm = captured[0]
    if getattr(arrays, "s_tas", None) is None:
        raise RuntimeError("captured cycle has no slot TAS planes")
    s_ax2 = int(arrays.s_tas.shape[1])

    def timed(tag):
        fn = jax.jit(bs.make_grouped_cycle(preempt=True))
        t0 = time.perf_counter()
        out = fn(arrays, ga, adm)
        jax.block_until_ready(out.outcome)
        compile_s = time.perf_counter() - t0
        best = None
        for _ in range(7):
            t = time.perf_counter()
            out = fn(arrays, ga, adm)
            jax.block_until_ready(out.outcome)
            dt = time.perf_counter() - t
            best = dt if best is None or dt < best else best
        log(f"tas[{tag}]: compile={compile_s:.2f}s "
            f"run={best * 1e3:.3f}ms")
        return compile_s, best, out

    compile_b, run_b, out_b = timed("batched")
    orig_pass = slot_tas.place_slots
    slot_tas.place_slots = slot_tas.place_slots_reference
    try:
        compile_r, run_r, out_r = timed("reference")
    finally:
        slot_tas.place_slots = orig_pass

    planes = ("outcome", "usage", "victims", "tas_takes", "s_tas_takes")
    match = all(
        np.array_equal(np.asarray(getattr(out_b, p)),
                       np.asarray(getattr(out_r, p)))
        for p in planes
        if getattr(out_b, p, None) is not None
        or getattr(out_r, p, None) is not None
    )
    rounds = int(np.asarray(out_b.slot_rounds))
    speedup = run_r / run_b if run_b > 0 else 0.0
    admitted = int(np.asarray(out_b.outcome > 0).sum())
    ok = match and 0 <= rounds <= s_ax2 and admitted >= 1
    return {
        "probe": "tas",
        "ok": bool(ok),
        "n_gangs": len(workloads),
        "s_bucket": s_ax2,
        "tas_slot_speedup": round(speedup, 3),
        "tas_compile_s_delta": round(compile_b - compile_r, 3),
        "batched_ms": round(run_b * 1000, 3),
        "reference_ms": round(run_r * 1000, 3),
        "batched_compile_s": round(compile_b, 3),
        "reference_compile_s": round(compile_r, 3),
        "slot_rounds": rounds,
        "admitted": admitted,
        "match": match,
        "fingerprint_extra": {"levels": 3, "version": 1},
    }


def probe_fleet(scale: float):
    """Joint fleet placement vs the sequential MultiKueue race
    (BASELINE.json config #5 shape at tiny CPU scale: 3 worker
    clusters, ~200*scale workloads). Runs the sequential dispatcher
    first (per-workload mirror-to-all + first-QuotaReserved-wins), then
    the FleetDispatcher (one batched ``cycle_fleet_assign`` solve + one
    apply per cluster lane), on identical fleets. Headlines:
    ``fleet_joint_speedup`` (sequential wall / joint wall — the
    subsystem's reason to exist) and ``fleet_dispatch_p99_ms`` (p99 of
    one joint encode+solve). Correctness is gated elsewhere (the
    differential suite); here ``ok`` requires both paths to dispatch
    every workload and the joint path to have actually used the device
    kernel."""
    from kueue_tpu.perf import multikueue_bench

    n = max(30, int(200 * scale))
    workers = 3
    log(f"fleet probe: sequential dispatch, n={n} workers={workers}")
    seq = multikueue_bench.run(n_workloads=n, n_workers=workers)
    log(f"fleet probe: joint dispatch, n={n} workers={workers}")
    joint = multikueue_bench.run_joint(
        n_workloads=n, n_workers=workers, device=True, prewarm=True
    )
    speedup = (
        seq["wall_s"] / joint["wall_s"] if joint["wall_s"] else 0.0
    )
    ok = (
        seq["dispatched"] >= n
        and joint["dispatched"] >= n
        and joint["device_solves"] >= 1
        and joint["host_solves"] == 0
    )
    return {
        "probe": "fleet",
        "ok": bool(ok),
        "n": n,
        "workers": workers,
        "fleet_joint_speedup": round(speedup, 3),
        "fleet_dispatch_p99_ms": round(joint["dispatch_p99_ms"], 3),
        "sequential_wall_s": round(seq["wall_s"], 4),
        "joint_wall_s": round(joint["wall_s"], 4),
        "sequential_throughput": round(seq["throughput"], 2),
        "joint_throughput": round(joint["throughput"], 2),
        "joint_placement": joint["placement"],
        "device_solves": joint["device_solves"],
        "host_solves": joint["host_solves"],
        "fingerprint_extra": {"workers": workers, "version": 1},
    }


def probe_coldstart_child(scale: float):
    """Child half of the cold-start probe: one fresh process, the shared
    persistent compile cache + AOT store (KUEUE_TPU_COMPILE_CACHE), one
    measurement of time-to-first-admission — scheduler construction
    through the first admitting cycle, compiles included. Run twice
    against the same cache dir by probe_coldstart; the delta is exactly
    the compile cost the cache removes."""
    import jax

    from kueue_tpu.models.driver import DeviceScheduler
    from kueue_tpu.perf import compile_cache

    configured = compile_cache.configure()
    compile_cache.install_listeners()
    cache, queues, workloads = build_scenario(
        scale, n_cohorts=1, n_cqs=2,
        classes=[("cold", max(1, int(4 * scale)), 1000, 50, 1.0)],
    )
    for wl, _runtime_s in workloads:
        queues.add_or_update_workload(wl)

    t0 = time.monotonic()
    sched = DeviceScheduler(cache, queues)
    result = sched.schedule()
    first_admission_s = time.monotonic() - t0

    stats = compile_cache.stats()
    out = {
        "probe": "coldstart-child",
        "ok": bool(result.admitted),
        "platform": jax.devices()[0].platform,
        "cache_dir": configured,
        "n": len(workloads),
        "admitted_first_cycle": len(result.admitted),
        "first_admission_s": round(first_admission_s, 3),
        "backend_compiles": stats["backend_compiles"],
        "compile_s": round(stats["compile_seconds"], 3),
        "cache_hits": stats["cache_hits"],
        "cache_misses": stats["cache_misses"],
        "aot_hits": stats["aot_hits"],
        "aot_stored": [],
    }
    # Record the measurement BEFORE the serialize step:
    # executable.serialize() can segfault on some jaxlib CPU builds — a
    # crash below must cost the AOT store for the next process, not this
    # measurement. Written to the --out sidecar (the parent prefers it
    # over stdout), NOT printed: stdout stays one-final-JSON-line.
    _write_probe_record(out)
    out["aot_stored"] = sorted(compile_cache.store_recorded())
    return out


def probe_coldstart(scale: float, platform: str = None):
    """Cold start vs warm cache (docs/perf.md): two fresh processes
    sharing one persistent compile cache + AOT executable store. The
    cold process compiles the solver cycle inside its first admission;
    the warm one deserializes it — its time-to-first-admission must be
    >= 3x faster on CPU."""
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="kueue-tpu-coldstart-")
    env = {"KUEUE_TPU_COMPILE_CACHE": cache_dir}
    cold = run_probe_subprocess(
        "coldstart-child", 420, scale, platform, env_extra=env
    )
    warm = run_probe_subprocess(
        "coldstart-child", 420, scale, platform, env_extra=env
    )
    out = {"probe": "coldstart", "cache_dir": cache_dir,
           "cold": cold, "warm": warm}
    if not (cold.get("ok") and warm.get("ok")):
        out["ok"] = False
        return out
    warm_s = warm["first_admission_s"]
    speedup = (cold["first_admission_s"] / warm_s
               if warm_s > 0 else float("inf"))
    out.update({
        "cold_first_admission_s": cold["first_admission_s"],
        "warm_first_admission_s": warm_s,
        "speedup_x": round(speedup, 2),
        "warm_aot_hits": warm["aot_hits"],
        "warm_cache_hits": warm["cache_hits"],
        "warm_backend_compiles": warm["backend_compiles"],
        "ok": speedup >= 3.0,
    })
    return out


def run_probe_subprocess(
    probe: str, timeout_s: int, scale: float, platform: str = None,
    env_extra: dict = None, compile_cache: str = None,
) -> dict:
    """Run one probe in a timeout-guarded subprocess. The child gets a
    tempfile ``--out`` sidecar, preferred over stdout parsing: a record
    the child wrote before crashing (serialize() segfault) still counts,
    and stdout formatting drift can't corrupt the result."""
    import tempfile

    fd, out_path = tempfile.mkstemp(prefix=f"kueue-tpu-{probe}-",
                                    suffix=".json")
    os.close(fd)
    os.unlink(out_path)  # child creates it atomically on write
    cmd = [
        "/usr/bin/timeout", str(timeout_s), sys.executable, __file__,
        "--probe", probe, "--scale", str(scale), "--out", out_path,
    ]
    if platform:
        cmd += ["--platform", platform]
    if compile_cache:
        cmd += ["--compile-cache", compile_cache]
    env = None
    if env_extra:
        env = dict(os.environ)
        env.update(env_extra)
    try:
        res = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s + 30,
            env=env,
        )
    except subprocess.TimeoutExpired:
        res = None
    finally:
        doc = None
        try:
            with open(out_path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            doc = None
        try:
            os.unlink(out_path)
        except OSError:
            pass
    if isinstance(doc, dict):
        return doc
    if res is None:
        return {"probe": probe, "ok": False, "error": "outer timeout"}
    for line in reversed(res.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                pass
    tail = (res.stderr or "").strip().splitlines()[-3:]
    return {
        "probe": probe, "ok": False, "rc": res.returncode,
        "error": " | ".join(tail)[-300:] or f"rc={res.returncode}",
    }


def main():
    argv = sys.argv[1:]
    if argv and argv[0] == "incremental":
        # docs/perf.md spelling: `python bench.py incremental`.
        argv = ["--probe", "incremental"] + argv[1:]
    ap = argparse.ArgumentParser()
    ap.add_argument("--kind", default="host", choices=["device", "host"])
    ap.add_argument("--scale", type=float, default=1.0,
                    help="fraction of the 15k baseline workload count")
    ap.add_argument("--probe", default=None,
                    choices=["ping", "mega", "sim", "fair", "phases",
                             "multichip", "incremental", "whatif",
                             "steady", "scanfloor", "tas", "fleet",
                             "tiled", "failover", "readplane",
                             "encode", "coldstart", "coldstart-child"],
                    help="internal: run one device probe and exit")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform inside the probe (the "
                         "JAX_PLATFORMS env var is NOT equivalent: the "
                         "environment's sitecustomize hangs on it)")
    ap.add_argument("--compile-cache", default=None,
                    help="persistent XLA compilation cache dir: amortizes "
                         "the 20-40s kernel compiles across bench runs. "
                         "Known hazard: some jaxlib CPU builds segfault in "
                         "executable.serialize(); each probe runs in its "
                         "own subprocess so a crash costs one probe, not "
                         "the bench")
    ap.add_argument("--skip-device", action="store_true")
    ap.add_argument("--out", default=None,
                    help="write the final probe record (and any interim "
                         "crash-protection record) atomically to this "
                         "path; stdout still carries the one final JSON "
                         "line")
    ap.add_argument("--ledger", default=None,
                    help="perf-ledger JSONL path (default: "
                         "PERF_LEDGER.jsonl at the repo root, or "
                         "$KUEUE_TPU_PERF_LEDGER)")
    ap.add_argument("--no-ledger", action="store_true",
                    help="skip the perf-ledger append for this run")
    args = ap.parse_args(argv)
    global _OUT_PATH
    _OUT_PATH = args.out

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.compile_cache:
        import jax

        try:
            jax.config.update("jax_enable_compilation_cache", True)
            jax.config.update(
                "jax_compilation_cache_dir", args.compile_cache
            )
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        except Exception as exc:  # noqa: BLE001
            log(f"compile cache unavailable: {exc!r}")

    if args.probe:
        try:
            stats = {
                "ping": probe_ping,
                "mega": probe_mega,
                "sim": lambda: probe_sim(args.scale),
                "fair": lambda: probe_fair(args.scale),
                "phases": probe_phases,
                "multichip": probe_multichip,
                "incremental": lambda: probe_incremental(args.scale),
                "whatif": lambda: probe_whatif(args.scale),
                "steady": lambda: probe_steady(args.scale),
                "scanfloor": lambda: probe_scanfloor(args.scale),
                "tas": lambda: probe_tas(args.scale),
                "fleet": lambda: probe_fleet(args.scale),
                "tiled": lambda: probe_tiled(args.scale),
                "failover": lambda: probe_failover(args.scale),
                "readplane": lambda: probe_readplane(args.scale),
                "encode": lambda: probe_encode(args.scale),
                "coldstart": lambda: probe_coldstart(
                    args.scale, args.platform),
                "coldstart-child": lambda: probe_coldstart_child(
                    args.scale),
            }[args.probe]()
        except Exception as exc:  # noqa: BLE001 - report, don't crash
            stats = {"probe": args.probe, "ok": False,
                     "error": repr(exc)[:300]}
        if args.out:
            _write_probe_record(stats)
        # Perf ledger: every top-level probe run leaves one JSONL record
        # (docs/observability.md#perf-ledger). coldstart-child is the
        # internal half of the coldstart probe — its cold and warm runs
        # share a fingerprint, so recording them would poison the
        # rolling median with deliberate before/after deltas.
        if not args.no_ledger and args.probe != "coldstart-child":
            try:
                from kueue_tpu.perf import ledger as perf_ledger

                rec = perf_ledger.make_record(
                    args.probe, stats, scale=args.scale,
                    platform=args.platform,
                    extra_config=stats.get("fingerprint_extra"),
                )
                path = args.ledger or perf_ledger.default_ledger_path()
                if not perf_ledger.append_record(rec, path):
                    log(f"perf ledger unwritable at {path}")
            except Exception as exc:  # noqa: BLE001 - never fail a probe
                log(f"perf ledger append failed: {exc!r}")
        print(json.dumps(stats), flush=True)
        os._exit(0)

    stats = run(args.kind, args.scale)
    log(f"host stats: {stats}")

    device = {}
    if not args.skip_device:
        # Fast aliveness gate: a wedged device tunnel costs one bounded
        # timeout here instead of one per heavy probe.
        device["ping"] = run_probe_subprocess(
            "ping", 90, args.scale, args.platform
        )
        log(f"device ping: {device['ping']}")
        if device["ping"].get("ok"):
            cc = args.compile_cache or "/tmp/kueue_tpu_xla_cache"

            def probe_with_cache_fallback(name):
                # The persistent cache is the one new variable; retry a
                # failed probe without it before giving up on the number.
                out = run_probe_subprocess(
                    name, 420, args.scale, args.platform, compile_cache=cc
                )
                log(f"device {name} probe: {out}")
                if not out.get("ok"):
                    out = run_probe_subprocess(
                        name, 420, args.scale, args.platform
                    )
                    log(f"device {name} probe (no cache): {out}")
                return out

            device["sim"] = probe_with_cache_fallback("sim")
            device["mega"] = probe_with_cache_fallback("mega")
            device["fair"] = probe_with_cache_fallback("fair")
            device["phases"] = probe_with_cache_fallback("phases")
            device["incremental"] = probe_with_cache_fallback("incremental")
            device["whatif"] = probe_with_cache_fallback("whatif")
        device["ok"] = bool(
            (device.get("sim") or {}).get("ok")
            or (device.get("mega") or {}).get("ok")
        )
        if not device["ping"].get("ok"):
            # Say it LOUDLY: with the tunnel wedged the round ships no
            # hardware numbers; the CPU crossover study is the fallback
            # evidence (VERDICT r3 #1) — device-path vs host-path on
            # identical scenarios, CPU backend, honest end-to-end.
            device["tunnel_dead_fallback"] = (
                "TPU tunnel unreachable at bench time (ping rc above). "
                "Device kernels in this round are validated on the CPU "
                "backend only; see crossover_cpu below for the "
                "device-vs-host comparison on identical scenarios and "
                "CROSSOVER_CPU.md for the study."
            )
            try:
                cx = run_probe_subprocess(
                    "sim", 900, min(args.scale, 0.3), "cpu"
                )
                log(f"crossover sim (cpu): {cx}")
                out_extra = {"sim_cpu": cx}
                fx = run_probe_subprocess(
                    "fair", 900, min(args.scale, 0.1), "cpu"
                )
                log(f"crossover fair (cpu): {fx}")
                out_extra["fair_cpu"] = fx
                device["crossover_cpu"] = out_extra
            except Exception as exc:  # noqa: BLE001
                device["crossover_cpu"] = {"error": repr(exc)[:200]}

    multichip = {}
    if not args.skip_device:
        # Weak-scaling curve on the virtual host mesh (tunnel-independent;
        # the same sharded program a real multi-chip mesh runs).
        multichip = run_probe_subprocess(
            "multichip", 900, args.scale, "cpu",
            env_extra={
                "XLA_FLAGS": os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8"
            },
        )
        log(f"multichip probe: {multichip}")

    baseline_throughput = 42.7  # BASELINE.md derived admissions/s
    value = round(stats["throughput"], 2)
    out = {
        "metric": "baseline_admission_throughput",
        "value": value,
        "unit": "workloads/s",
        "vs_baseline": round(value / baseline_throughput, 2),
    }
    if device:
        out["device"] = device
        sim = device.get("sim") or {}
        out["device_time_s"] = sim.get("device_wall_s", 0.0)
    if multichip:
        out["multichip"] = multichip
    # Full detail goes to a sidecar file: the driver records only the
    # TAIL of stdout, and the complete object (multichip curve + floor
    # analysis prose) is long enough to truncate mid-JSON (BENCH_r04's
    # official capture has parsed:null for exactly this reason). The
    # final stdout line is a compact summary that always fits.
    detail_ref = "BENCH_DETAIL.json"
    try:
        here = os.path.dirname(os.path.abspath(__file__))
        tmp = os.path.join(here, ".BENCH_DETAIL.json.tmp")
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, os.path.join(here, "BENCH_DETAIL.json"))
    except OSError as exc:
        # Never advertise a stale/partial sidecar as this run's data.
        # The full object still goes to stderr (a measurement run's data
        # must never be silently dropped) — NOT stdout, which carries
        # exactly one final JSON line (the compact summary below).
        detail_ref = f"unwritable: {exc!r}"[:120]
        log(json.dumps(out))

    def _pick(d, *keys):
        picked = {
            k: d[k] for k in keys
            if isinstance(d, dict) and d.get(k) is not None
        }
        if isinstance(d, dict) and d.get("error") and not d.get("ok"):
            picked["error"] = str(d["error"])[:80]
        return picked

    compact = {
        "metric": out["metric"],
        "value": out["value"],
        "unit": out["unit"],
        "vs_baseline": out["vs_baseline"],
        "detail": detail_ref,
    }
    if device:
        dv = {}
        for name in ("ping", "sim", "mega", "fair", "phases",
                     "incremental"):
            p = device.get(name)
            if not isinstance(p, dict):
                continue
            if not p.get("ok"):
                dv[name] = {"ok": False, "rc": p.get("rc")}
                if p.get("error"):
                    dv[name]["error"] = str(p["error"])[:80]
            elif name == "incremental":
                dv[name] = _pick(p, "ok", "encode_ms", "full_encode_ms",
                                 "encode_speedup", "device_ms",
                                 "dirty_pct", "bit_identical")
            elif name == "sim":
                dv[name] = _pick(p, "ok", "admissions_per_s",
                                 "end_to_end_adm_per_s", "kernel")
            elif name == "mega":
                dv[name] = _pick(p, "ok", "percycle_ms", "pallas_i32_ms",
                                 "grouped_ms", "dispatch_latency_ms")
            elif name == "fair":
                dv[name] = _pick(p, "ok", "admissions_per_s",
                                 "end_to_end_adm_per_s")
            else:
                dv[name] = {"ok": True}
        cx = device.get("crossover_cpu")
        if isinstance(cx, dict):
            if cx.get("error"):
                dv["crossover_cpu"] = {"error": str(cx["error"])[:80]}
            else:
                dv["crossover_cpu"] = {
                    k: _pick(v, "ok", "admissions_per_s")
                    for k, v in cx.items() if isinstance(v, dict)
                }
        compact["device"] = dv
        compact["device_time_s"] = out.get("device_time_s", 0.0)
    if multichip:
        compact["multichip"] = _pick(
            multichip, "ok", "devices", "cycle_1dev_ms", "cycle_8dev_ms",
            "nominate_1dev_ms", "nominate_8dev_ms",
        )
    print(json.dumps(compact), flush=True)
    # Skip interpreter teardown: a wedged accelerator transport can hang
    # JAX's backend finalizers, and the result is already on stdout.
    os._exit(0)


if __name__ == "__main__":
    main()
