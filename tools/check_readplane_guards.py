#!/usr/bin/env python
"""Static check: the read-plane hot-path hooks stay zero-cost when the
read plane is off (and its optional accounting stays flag-gated).

The read plane must cost a deployment that never attaches one exactly
one attribute read per service-loop step — the same contract
``tracing.ENABLED`` / ``faults.ENABLED`` carry (tools/
check_kernel_gates.py) and the pipeline hooks carry
(tools/check_pipeline_guards.py). The guarded seams:

- ``obs/service.py`` guards its per-step snapshot publish
  (``....publish_cycle(...)``) with ``if self._readplane``;
- ``readplane/publisher.py`` only captures behind its gate
  (``self._capture(...)`` under ``self._should_capture``), so demand-
  idle cycles never pay a clone;
- ``readplane/coalescer.py`` guards fault injection with
  ``faults.ENABLED`` and tenant cost attribution with
  ``costs.ENABLED``.

For every call site matching one of those patterns, this checker walks
back from the call line (at most ``MAX_WALKBACK`` lines) to the first
non-blank line at strictly lower indentation — the statement that owns
the enclosing block — and requires the guard substring on that line. It
also requires at least one site per (file, pattern): deleting a hook
without deleting its rule fails loudly instead of silently un-checking.

Run standalone (exit 1 on violations) or via tools/check_all.py.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "kueue_tpu"

MAX_WALKBACK = 40

# (file, call-site substring, required guard substring). Call patterns
# include the leading receiver dot so ``def`` lines never match.
RULES: Tuple[Tuple[Path, str, str], ...] = (
    (PACKAGE / "obs" / "service.py",
     ".publish_cycle(", "self._readplane"),
    (PACKAGE / "readplane" / "publisher.py",
     "self._capture(", "self._should_capture"),
    (PACKAGE / "readplane" / "coalescer.py",
     "faults.fire(", "faults.ENABLED"),
    (PACKAGE / "readplane" / "coalescer.py",
     "costs.charge", "costs.ENABLED"),
)


def _indent(line: str) -> int:
    return len(line) - len(line.lstrip())


def _enclosing_stmt(lines: List[str], i: int) -> Tuple[int, str]:
    """Index + text of the first non-blank line above ``lines[i]`` with
    strictly lower indentation (the owner of the enclosing block)."""
    base = _indent(lines[i])
    for j in range(i - 1, max(-1, i - 1 - MAX_WALKBACK), -1):
        line = lines[j]
        if not line.strip():
            continue
        if _indent(line) < base:
            return j, line
    return -1, ""


def run_check() -> List[str]:
    violations: List[str] = []
    for path, call, guard in RULES:
        try:
            lines = path.read_text().splitlines()
        except OSError as exc:
            violations.append(f"{path}: unreadable ({exc})")
            continue
        sites = [
            i for i, line in enumerate(lines)
            if call in line and not line.lstrip().startswith("#")
        ]
        if not sites:
            violations.append(
                f"{path}: no call site matching {call!r} — the hook was "
                f"removed; update RULES in {Path(__file__).name}"
            )
            continue
        for i in sites:
            j, stmt = _enclosing_stmt(lines, i)
            if guard not in stmt:
                where = f"{path}:{i + 1}"
                owner = (
                    f"line {j + 1}: {stmt.strip()!r}" if j >= 0
                    else "no enclosing statement found in walk-back range"
                )
                violations.append(
                    f"{where}: {call!r} is not directly guarded by "
                    f"'{guard}' (enclosing {owner}) — the read-plane hook "
                    f"must be zero-cost when the read plane is off"
                )
    return violations


def main() -> int:
    violations = run_check()
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} readplane-guard violation(s)")
        return 1
    print("readplane guard check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
