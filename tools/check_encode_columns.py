#!/usr/bin/env python
"""Static check: the encode hot paths stay columnar.

PR 20 moved the per-workload encode work into the struct-of-arrays
store (kueue_tpu/cache/columns.py): ``encode_cycle`` / ``plan_tiles``
(models/encode.py) and ``CycleArena._build_w`` (models/arena.py) now do
column slicing and ``np.take`` gathers, with the old per-row Python
walks quarantined in named oracle helpers (``_classify_heads``,
``_fill_w_rows``, ``_tile_head_views``, ``_build_w_rows``) that run only
on the ragged fallback or in verify mode. This checker keeps it that
way:

- inside the hot functions, no ``for`` loop / comprehension / generator
  may iterate a per-workload sequence (``heads``, ``device_wls``,
  ``wl_slots``, ``infos``) — that is the host-side floor coming back;
- the oracle helpers must still exist (deleting one silently un-checks
  the allowlist and orphans the differential tests);
- the hot path must still call into the columnar store (at least one
  ``.gather(`` and one ``.assemble(`` site across the two files).

Run standalone (exit 1 on violations) or via tools/check_all.py.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import Dict, List, Set

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "kueue_tpu"

# file -> functions whose bodies must not loop per workload.
HOT_FUNCS: Dict[Path, Set[str]] = {
    PACKAGE / "models" / "encode.py": {"encode_cycle", "plan_tiles"},
    PACKAGE / "models" / "arena.py": {"_build_w"},
}

# Allowlisted row-wise oracles: they must exist (anti-rot — the verify
# mode and the differential tests depend on them), and per-workload
# loops inside them are fine.
ORACLE_FUNCS: Dict[Path, Set[str]] = {
    PACKAGE / "models" / "encode.py": {
        "_classify_heads", "_fill_w_rows", "_tile_head_views",
    },
    PACKAGE / "models" / "arena.py": {"_build_w_rows"},
}

# Iterating any of these names inside a hot function is a violation.
PER_WORKLOAD_NAMES = {"heads", "device_wls", "wl_slots", "infos"}

LOOP_NODES = (ast.For, ast.ListComp, ast.SetComp, ast.DictComp,
              ast.GeneratorExp)


def _iter_exprs(node: ast.AST) -> List[ast.expr]:
    if isinstance(node, ast.For):
        return [node.iter]
    if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return [g.iter for g in node.generators]
    if isinstance(node, ast.DictComp):
        return [g.iter for g in node.generators]
    return []


def _per_workload_name(expr: ast.expr) -> str:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and sub.id in PER_WORKLOAD_NAMES:
            return sub.id
    return ""


def _functions(tree: ast.AST) -> Dict[str, ast.AST]:
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
    return out


def run_check() -> List[str]:
    violations: List[str] = []
    gather_sites = 0
    assemble_sites = 0
    for path in sorted(set(HOT_FUNCS) | set(ORACLE_FUNCS)):
        try:
            src = path.read_text()
        except OSError as exc:
            violations.append(f"{path}: unreadable ({exc})")
            continue
        tree = ast.parse(src, filename=str(path))
        funcs = _functions(tree)

        for name in sorted(ORACLE_FUNCS.get(path, ())):
            if name not in funcs:
                violations.append(
                    f"{path}: oracle helper {name}() is gone — the "
                    f"row-wise verify path must stay; update "
                    f"{Path(__file__).name} if it was renamed"
                )

        for name in sorted(HOT_FUNCS.get(path, ())):
            fn = funcs.get(name)
            if fn is None:
                violations.append(
                    f"{path}: hot function {name}() not found — update "
                    f"HOT_FUNCS in {Path(__file__).name}"
                )
                continue
            nested = {
                n for n in ast.walk(fn)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                and n is not fn
            }
            nested_bodies: Set[int] = set()
            for nf in nested:
                for sub in ast.walk(nf):
                    nested_bodies.add(id(sub))
            for node in ast.walk(fn):
                if id(node) in nested_bodies:
                    continue
                if not isinstance(node, LOOP_NODES):
                    continue
                for expr in _iter_exprs(node):
                    hit = _per_workload_name(expr)
                    if hit:
                        violations.append(
                            f"{path}:{node.lineno}: {name}() iterates "
                            f"per-workload sequence '{hit}' — the hot "
                            f"path must stay columnar; move the loop "
                            f"into an oracle helper or use the store"
                        )

        gather_sites += src.count(".gather(")
        assemble_sites += src.count(".assemble(")

    if not violations:
        if gather_sites == 0:
            violations.append(
                "no '.gather(' call site in the encode hot paths — the "
                "columnar store is no longer consulted"
            )
        if assemble_sites == 0:
            violations.append(
                "no '.assemble(' call site in the encode hot paths — "
                "the columnar store no longer fills the cycle arrays"
            )
    return violations


def main() -> int:
    violations = run_check()
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} encode-columns violation(s)")
        return 1
    print("encode columns check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
