#!/usr/bin/env python
"""Static check: the driver's kernel dispatch gate matches each kernel's
documented preconditions.

Every device cycle factory documents its entry name and the driver-side
conditions it needs to be exact as docstring markers::

    kernel-entry: cycle_fixedpoint
    gate-requires: not idx.has_partial
    gate-requires: arrays.s_req is None

``DeviceScheduler._schedule_heads`` (the single dispatch site both the
monolithic ``schedule`` path and the tiled ``_schedule_tiled`` loop
funnel through) selects a kernel by assigning
``entry = "<name>"`` inside an if/elif chain. This walker pairs each
assignment with the conditions that guard it and verifies, in both
directions, that code and docs agree:

1. every dispatched entry has a ``kernel-entry`` marker (a new kernel
   cannot ship with undocumented preconditions);
2. every marker names an entry the driver actually dispatches (a rename
   cannot orphan the docs);
3. every ``gate-requires`` condition appears as a conjunct of the gate
   guarding that entry (the driver cannot silently drop a precondition
   the kernel still needs);
4. every gate conjunct testing a known capability attribute is
   documented by that kernel (a kernel that GAINS a capability — e.g.
   lending limits — cannot leave a stale exclusion in the gate: the
   marker is deleted from the docstring, and this check then flags the
   leftover condition).

Conditions are normalized through ``ast.parse``/``ast.unparse`` so
whitespace and quoting never matter. Mode-selection conjuncts
(``self.device_kernel``, bucketing locals like ``s_resid``) are not
capability tests and are ignored by check 4.

A fifth check covers the shared batched TAS slot pass
(``models/slot_tas.py``), which has no ``entry =`` dispatch of its own:
``place_slots`` documents its consumers as docstring markers::

    slot-pass-used-by: batch_scheduler.admit_scan_grouped

and the check verifies, in both directions, that every marker names a
kernel function that really calls ``place_slots`` and that every call
site in the kernel files is documented — so a new consumer (or a
removed one) cannot silently drift from the pass's docs.

Run standalone (exit 1 on violations) or via tests/test_kernel_gates.py.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import Dict, List, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "kueue_tpu"

DRIVER = PACKAGE / "models" / "driver.py"
FLEET_DISPATCHER = PACKAGE / "fleet" / "dispatcher.py"

# Files whose factory docstrings may carry kernel-entry markers.
KERNEL_FILES = (
    PACKAGE / "models" / "batch_scheduler.py",
    PACKAGE / "models" / "fair_kernel.py",
    PACKAGE / "models" / "fair_fixedpoint.py",
    PACKAGE / "fleet" / "kernel.py",
)

# The fleet dispatcher's _select_entry() gates the joint multi-cluster
# assignment kernel; its kernel file is split out of KERNEL_FILES so the
# driver site is only checked against the cycle kernels it dispatches.
FLEET_SITE = (FLEET_DISPATCHER, "_select_entry",
              (PACKAGE / "fleet" / "kernel.py",))


def dispatch_sites():
    """Every place an ``entry = "<name>"`` dispatch gate lives: (file,
    method name holding the if/elif chain, kernel files its entries may
    document themselves in).

    Resolved from module globals at call time so the synth tests can
    repoint ``DRIVER`` / ``KERNEL_FILES`` at temporary sources.
    """
    fleet_kernels = set(FLEET_SITE[2])
    driver_kernels = tuple(f for f in KERNEL_FILES
                           if f not in fleet_kernels)
    return (
        # _schedule_heads is the one kernel-dispatch site in the driver:
        # monolithic cycles call it once, the tiled mode once per tile —
        # covering the tile dispatch path with the same gate pins.
        (DRIVER, "_schedule_heads", driver_kernels),
        FLEET_SITE,
    )

# Attribute substrings that mark a gate conjunct as a CAPABILITY test —
# something a kernel can or cannot handle — as opposed to mode selection.
# A conjunct mentioning one of these must be documented by the kernel it
# guards (check 4).
CAPABILITY_ATTRS = (
    "has_partial",
    "s_req",
    "tas_topo",
    "has_lend_limit",
    "fair_sharing",
    "s_bound",
)

_ENTRY_RE = re.compile(r"^\s*kernel-entry:\s*(\S+)\s*$", re.M)
_REQ_RE = re.compile(r"^\s*gate-requires:\s*(.+?)\s*$", re.M)

# The shared batched TAS slot pass and its used-by contract (check 5).
SLOT_PASS = PACKAGE / "models" / "slot_tas.py"
SLOT_PASS_FUNC = "place_slots"
_USED_BY_RE = re.compile(r"^\s*slot-pass-used-by:\s*(\S+)\s*$", re.M)


def _normalize(cond: str) -> str:
    """Canonical text for a boolean condition (quoting/whitespace-proof)."""
    try:
        return ast.unparse(ast.parse(cond, mode="eval").body)
    except SyntaxError:
        return " ".join(cond.split())


def documented_gates(files=KERNEL_FILES) -> Dict[str, List[str]]:
    """entry name -> normalized gate-requires conditions, harvested from
    the kernel factory docstrings."""
    out: Dict[str, List[str]] = {}
    for path in files:
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            doc = ast.get_docstring(node)
            if not doc:
                continue
            entries = _ENTRY_RE.findall(doc)
            if not entries:
                continue
            reqs = [_normalize(c) for c in _REQ_RE.findall(doc)]
            for entry in entries:
                out[entry] = reqs
    return out


def _conjuncts(test: ast.expr) -> List[ast.expr]:
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        out: List[ast.expr] = []
        for v in test.values:
            out.extend(_conjuncts(v))
        return out
    return [test]


class _GateCollector(ast.NodeVisitor):
    """Pair every ``entry = "<name>"`` assignment with the positive
    conjuncts of the if/elif tests whose BODY (not else-branch) encloses
    it."""

    def __init__(self) -> None:
        self.stack: List[ast.expr] = []
        # entry -> list of (normalized conjunct, lineno)
        self.gates: Dict[str, List[Tuple[str, int]]] = {}

    def visit_If(self, node: ast.If) -> None:
        conj = _conjuncts(node.test)
        self.stack.extend(conj)
        for child in node.body:
            self.visit(child)
        del self.stack[len(self.stack) - len(conj):]
        for child in node.orelse:
            self.visit(child)

    def visit_Assign(self, node: ast.Assign) -> None:
        if (
            len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "entry"
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            self.gates[node.value.value] = [
                (_normalize(ast.unparse(c)), c.lineno) for c in self.stack
            ]
        self.generic_visit(node)


def dispatch_gates(path: Path = DRIVER, func_name: str = "_schedule_heads"
                   ) -> Dict[str, List[Tuple[str, int]]]:
    """entry name -> gate conjuncts guarding its assignment inside
    ``func_name`` in ``path``."""
    tree = ast.parse(path.read_text(), filename=str(path))
    collector = _GateCollector()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            collector.visit(node)
    return collector.gates


def _check_site(path: Path, func_name: str, kernel_files) -> List[str]:
    violations: List[str] = []
    docs = documented_gates(kernel_files)
    gates = dispatch_gates(path, func_name)

    if not gates:
        return [f"{path}: found no entry assignments in {func_name}()"]

    for entry in sorted(gates):
        if entry not in docs:
            violations.append(
                f"{path}: dispatches {entry!r} but no kernel factory "
                f"docstring carries a 'kernel-entry: {entry}' marker"
            )
    for entry in sorted(docs):
        if entry not in gates:
            violations.append(
                f"'kernel-entry: {entry}' documented but {path.name}'s "
                f"{func_name}() never assigns entry = {entry!r}"
            )

    for entry, reqs in sorted(docs.items()):
        if entry not in gates:
            continue
        conj = gates[entry]
        conj_norm = {c for c, _ in conj}
        for req in reqs:
            if req not in conj_norm:
                violations.append(
                    f"{entry}: documented precondition "
                    f"'gate-requires: {req}' is not a conjunct of the "
                    f"{func_name}() dispatch gate "
                    f"(gate has: {sorted(conj_norm)})"
                )
        for cond, lineno in conj:
            if not any(attr in cond for attr in CAPABILITY_ATTRS):
                continue  # mode selection / bucketing, not a capability
            if cond not in reqs:
                violations.append(
                    f"{path}:{lineno}: gate condition '{cond}' guards "
                    f"{entry!r} but the kernel docstring does not list it "
                    f"as 'gate-requires:' — either the kernel gained this "
                    f"capability (delete the stale gate condition) or the "
                    f"docstring is missing the marker"
                )
    return violations


def _slot_pass_markers(path: Path = None) -> List[str]:
    """``module.function`` consumers documented in the slot pass's
    docstring (``slot-pass-used-by:`` markers on ``place_slots``)."""
    path = SLOT_PASS if path is None else path
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name == SLOT_PASS_FUNC):
            doc = ast.get_docstring(node) or ""
            return _USED_BY_RE.findall(doc)
    return []


def _slot_pass_call_sites(files=None) -> List[str]:
    """``module.function`` for every top-level kernel function whose body
    (including nested closures) calls ``place_slots``."""
    files = KERNEL_FILES if files is None else files
    out: List[str] = []
    for path in files:
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                name = (
                    fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None
                )
                if name == SLOT_PASS_FUNC:
                    out.append(f"{path.stem}.{node.name}")
                    break
    return out


def _check_slot_pass() -> List[str]:
    """Check 5: the slot pass's documented consumers match the kernel
    call sites of ``place_slots``, in both directions. No subject file
    (the synth harness repoints ``SLOT_PASS`` at a path it never
    writes) means nothing to check."""
    if not SLOT_PASS.exists():
        return []
    markers = _slot_pass_markers()
    sites = _slot_pass_call_sites()
    violations: List[str] = []
    for m in sorted(set(markers) - set(sites)):
        violations.append(
            f"{SLOT_PASS.name}: 'slot-pass-used-by: {m}' documented but "
            f"no kernel function of that name calls {SLOT_PASS_FUNC}()"
        )
    for s in sorted(set(sites) - set(markers)):
        violations.append(
            f"{s} calls {SLOT_PASS_FUNC}() but {SLOT_PASS.name}'s "
            f"{SLOT_PASS_FUNC} docstring has no "
            f"'slot-pass-used-by: {s}' marker"
        )
    return violations


def run_check() -> List[str]:
    violations: List[str] = []
    for path, func_name, kernel_files in dispatch_sites():
        violations.extend(_check_site(path, func_name, kernel_files))
    violations.extend(_check_slot_pass())
    return violations


def main() -> int:
    violations = run_check()
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} kernel-gate violation(s)")
        return 1
    print("kernel gate check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
