#!/usr/bin/env python
"""One-shot pre-commit gate: run every static checker plus an import
smoke test.

Wraps the repo checkers —

- ``check_metrics_names.py``: every emitted metric name is a literal
  from ``metrics/names.py`` and documented in docs/observability.md;
- ``check_kernel_gates.py``: zero-cost module-flag idiom holds at every
  tracing/faults call site;
- ``check_pipeline_guards.py``: the pipelined-cycle hooks in the driver
  and service loop stay behind their ``_pipeline_on`` / ``_pipeline``
  guards (zero-cost when serialized);
- ``check_ha_containment.py``: every HA state-mutation site in
  ``controllers/ha.py`` sits inside a ``_contained(...)`` scope
  (docs/failover.md recovery invariants);
- ``check_readplane_guards.py``: the read-plane publish/coalesce hooks
  stay behind their ``self._readplane`` / ``_should_capture`` /
  ``ENABLED`` guards (zero-cost when no read plane is attached);
- ``check_perf_ledger.py``: newest PERF_LEDGER.jsonl record per probe
  fingerprint has not regressed vs its rolling median —

and then imports the public entry points in a fresh CPU-pinned
subprocess so a syntax error or circular import anywhere in the facade
fails fast without waiting for the test suite. Exit status is 0 iff
every step passed. Run it before committing (see README), or via
``tools/run_isolated.py --checks``.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent

CHECKERS = (
    "check_metrics_names.py",
    "check_kernel_gates.py",
    "check_pipeline_guards.py",
    "check_ha_containment.py",
    "check_readplane_guards.py",
    "check_encode_columns.py",
    "check_perf_ledger.py",
)

# Facade modules whose import pulls in (nearly) the whole package:
# manager wires cache/queues/scheduler/solver, obs.service the loop,
# visibility the HTTP layer, cli the argparse surface, perf.ledger the
# bench bookkeeping.
SMOKE_IMPORTS = (
    "kueue_tpu.manager",
    "kueue_tpu.obs.service",
    "kueue_tpu.visibility.server",
    "kueue_tpu.cli",
    "kueue_tpu.perf.ledger",
)


def run_step(label: str, cmd: list) -> int:
    print(f"== [{label}] {' '.join(cmd)}", flush=True)
    env = dict(os.environ,
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    return subprocess.call(cmd, cwd=str(REPO_ROOT), env=env)


def main() -> int:
    failures = []
    for name in CHECKERS:
        rc = run_step(name, [sys.executable,
                             str(REPO_ROOT / "tools" / name)])
        if rc != 0:
            failures.append((name, rc))
    smoke = "import " + ", ".join(SMOKE_IMPORTS)
    rc = run_step("import-smoke", [sys.executable, "-c", smoke])
    if rc != 0:
        failures.append(("import-smoke", rc))

    print("\n== check_all summary")
    if not failures:
        print(f"all {len(CHECKERS) + 1} steps passed")
        return 0
    for label, rc in failures:
        print(f"FAILED {label} (rc={rc})")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
