#!/usr/bin/env python
"""Static check: every HA state-mutation site sits inside a containment
scope.

The warm-failover subsystem (``kueue_tpu/controllers/ha.py``,
docs/failover.md) promises that a replication, tail, or takeover failure
can never corrupt replica state — every mutation of a ``Manager`` / its
cache / its queues happens inside a ``with self._contained(<point>):``
scope whose breaker absorbs the failure (docs/fault_containment.md).
That promise is structural, so this checker enforces it structurally:
it parses ``ha.py`` and requires every ``Call`` whose attribute is one
of the known mutators to be *lexically* nested inside an ``ast.With``
whose context expression calls ``_contained``. A new execution scope
(nested ``def`` / ``lambda``) resets the containment — code defined
inside a with-block does not run under it.

Run standalone (exit 1 on violations) or via tools/check_all.py.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

REPO_ROOT = Path(__file__).resolve().parent.parent
HA_PATH = REPO_ROOT / "kueue_tpu" / "controllers" / "ha.py"

#: Method names that mutate Manager / cache / queue state when called on
#: any receiver inside ha.py. ``schedule`` is deliberately absent: the
#: leader's admission cycles are contained by the driver's own scopes
#: (models/driver.py), not by the replication layer.
MUTATORS = frozenset({
    "create_workload",
    "update_workload",
    "finish_workload",
    "delete_workload",
    "forget_workload",
    "assume_workload",
    "add_or_update_workload",
    "requeue_workload",
    "restore_state",
    "apply",
    "delete",
})


def _is_contained_ctx(expr: ast.expr) -> bool:
    """True for ``self._contained(...)`` (or any ``*._contained(...)``)
    used as a with-item context expression."""
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "_contained"
    )


def _walk(node: ast.AST, contained: bool, violations: List[str]) -> None:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        # New execution scope: a def/lambda *defined* under a with-block
        # does not *run* under it.
        contained = False
    elif isinstance(node, ast.With):
        if any(_is_contained_ctx(item.context_expr)
               for item in node.items):
            contained = True
    elif isinstance(node, ast.Call) and not contained:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in MUTATORS:
            violations.append(
                f"{HA_PATH}:{node.lineno}: mutation call "
                f"'.{func.attr}(...)' is not inside a "
                f"'with ..._contained(<point>):' scope"
            )
    for child in ast.iter_child_nodes(node):
        _walk(child, contained, violations)


def run_check() -> List[str]:
    violations: List[str] = []
    try:
        tree = ast.parse(HA_PATH.read_text(), filename=str(HA_PATH))
    except (OSError, SyntaxError) as exc:
        return [f"{HA_PATH}: unparseable ({exc})"]
    _walk(tree, False, violations)
    # Self-test: deleting every mutator from ha.py (or renaming them)
    # must fail loudly instead of silently un-checking.
    found = sum(
        1 for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in MUTATORS
    )
    if found == 0:
        violations.append(
            f"{HA_PATH}: no mutation call sites found — MUTATORS in "
            f"{Path(__file__).name} is stale"
        )
    return violations


def main() -> int:
    violations = run_check()
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} HA containment violation(s)")
        return 1
    print("HA containment check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
