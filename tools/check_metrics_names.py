#!/usr/bin/env python
"""Static check: every metric emission uses a name from the frozen
allowlist (kueue_tpu/metrics/names.py).

A typo'd series name doesn't fail at runtime — it silently forks a new
series and every dashboard reading the intended one shows zeros forever.
This walker finds each ``<metrics-ish receiver>.inc/observe/set_gauge``
call in the package and verifies the first argument is a string literal
present in ``METRIC_NAMES``.

Receivers considered metric emitters:
- the ``tracing`` module (``tracing.inc(...)``)
- a bare ``m`` (the local alias convention for a Metrics registry)
- any attribute chain containing a ``metrics`` component
  (``self.manager.metrics.inc``, ``mgr.metrics.observe``)

Other ``.observe()``-shaped calls (e.g. ``self.roletracker.observe``)
are unrelated and skipped. The registry/tracing internals are excluded:
they forward caller-supplied names by design.

Run standalone (exit 1 on violations) or via tests/test_observability.py.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "kueue_tpu"

# Forwarding layers: they pass through names owned by their callers.
EXCLUDED = {
    PACKAGE / "metrics" / "registry.py",
    PACKAGE / "metrics" / "tracing.py",
}

_EMIT_METHODS = {"inc", "observe", "set_gauge"}


def _receiver_parts(node: ast.expr) -> List[str]:
    """Flatten an attribute chain to its name components;
    ``self.manager.metrics`` -> ["self", "manager", "metrics"]."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _is_metrics_receiver(parts: List[str]) -> bool:
    if not parts:
        return False
    if parts == ["tracing"] or parts == ["m"]:
        return True
    return "metrics" in parts


def check_file(path: Path, allowlist: frozenset) -> List[Tuple[int, str]]:
    violations: List[Tuple[int, str]] = []
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in _EMIT_METHODS:
            continue
        if not _is_metrics_receiver(_receiver_parts(fn.value)):
            continue
        if not node.args:
            violations.append(
                (node.lineno, f"{fn.attr}() call without a metric name")
            )
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            violations.append((
                node.lineno,
                f"{fn.attr}() metric name is not a string literal "
                "(allowlist check impossible)",
            ))
            continue
        if first.value not in allowlist:
            violations.append((
                node.lineno,
                f"{fn.attr}({first.value!r}) not in METRIC_NAMES "
                "(kueue_tpu/metrics/names.py)",
            ))
    return violations


def collect_emitted_names(path: Path) -> set:
    """Every string-literal metric name passed to an emit method in one
    file — regardless of receiver heuristics or exclusions. Used for the
    dead-allowlist check: a name only ever forwarded (registry/tracing)
    still counts as emitted somewhere upstream of the forwarding layer."""
    emitted: set = set()
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        # Attribute form (m.inc / tracing.observe / self.metrics.set_gauge)
        # or the bare-function form used inside tracing.py itself.
        if isinstance(fn, ast.Attribute):
            name = fn.attr
        elif isinstance(fn, ast.Name):
            name = fn.id
        else:
            continue
        if name not in _EMIT_METHODS:
            continue
        if not node.args:
            continue
        # The name argument may be a conditional over literals
        # ("a" if miss else "b"); any string constant inside it is a
        # name the call can emit.
        for sub in ast.walk(node.args[0]):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                emitted.add(sub.value)
    return emitted


def check_emitted_coverage(allowlist: frozenset) -> List[str]:
    """The inverse of the typo check: an allowlisted name no call site
    ever emits is dead weight — usually a renamed series whose allowlist
    entry survived the rename. Dashboards reading it show zeros forever
    with every static check green, so the allowlist itself must stay
    honest. Scans ALL package files (including the forwarding layers the
    per-site walk excludes) plus bench.py, which owns probe-only series."""
    emitted: set = set()
    for path in sorted(PACKAGE.rglob("*.py")):
        emitted |= collect_emitted_names(path)
    bench = REPO_ROOT / "bench.py"
    if bench.exists():
        emitted |= collect_emitted_names(bench)
    return [
        f"kueue_tpu/metrics/names.py: series {name!r} is allowlisted "
        "but no call site ever emits it"
        for name in sorted(allowlist - emitted)
    ]


def check_reason_codes_documented() -> List[str]:
    """Every provenance reason code the obs layer can stamp onto a cycle
    record (obs/reasons.py) must appear in docs/observability.md — the
    explain API is only as useful as the operator's ability to look a
    code up."""
    from kueue_tpu.obs.reasons import documented_reason_codes

    doc_path = REPO_ROOT / "docs" / "observability.md"
    if not doc_path.exists():
        return [f"{doc_path.relative_to(REPO_ROOT)}: missing"]
    doc = doc_path.read_text()
    return [
        f"docs/observability.md: reason code {code!r} is in "
        "kueue_tpu/obs/reasons.py but undocumented"
        for code in sorted(documented_reason_codes())
        if code not in doc
    ]


def check_help_text_keys() -> List[str]:
    """Every HELP_TEXT key (names.py) must itself be an allowlisted
    series: a # HELP entry for a name that can never be emitted is a
    leftover from a rename, and the Prometheus exposition would carry
    documentation for a ghost."""
    from kueue_tpu.metrics.names import HELP_TEXT, METRIC_NAMES

    return [
        f"kueue_tpu/metrics/names.py: HELP_TEXT key {name!r} is not in "
        "METRIC_NAMES"
        for name in sorted(set(HELP_TEXT) - set(METRIC_NAMES))
    ]


def check_docs_coverage(allowlist: frozenset) -> List[str]:
    """Every allowlisted series must be documented: names.py's contract is
    "adding a metric means adding it here AND to docs/observability.md".
    An undocumented series is invisible to operators — dashboards are
    built from the doc, not from grepping emission sites."""
    doc_path = REPO_ROOT / "docs" / "observability.md"
    if not doc_path.exists():
        return [f"{doc_path.relative_to(REPO_ROOT)}: missing"]
    doc = doc_path.read_text()
    return [
        f"docs/observability.md: series {name!r} is in METRIC_NAMES but "
        "undocumented"
        for name in sorted(allowlist)
        if name not in doc
    ]


def check_fault_points_documented() -> List[str]:
    """Every registered fault-injection point (utils/faults.py POINTS)
    must appear in docs/fault_containment.md. An undocumented point is a
    containment surface nobody drills: the injection framework exists so
    operators rehearse failures by name."""
    from kueue_tpu.utils.faults import POINTS

    doc_path = REPO_ROOT / "docs" / "fault_containment.md"
    if not doc_path.exists():
        return [f"{doc_path.relative_to(REPO_ROOT)}: missing"]
    doc = doc_path.read_text()
    return [
        f"docs/fault_containment.md: fault point {point!r} is in "
        "utils/faults.py POINTS but undocumented"
        for point in sorted(POINTS)
        if point not in doc
    ]


def run_check() -> List[str]:
    """Returns human-readable violation lines; empty list = clean."""
    sys.path.insert(0, str(REPO_ROOT))
    from kueue_tpu.metrics.names import METRIC_NAMES

    out: List[str] = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path in EXCLUDED:
            continue
        for lineno, msg in check_file(path, METRIC_NAMES):
            rel = path.relative_to(REPO_ROOT)
            out.append(f"{rel}:{lineno}: {msg}")
    out.extend(check_docs_coverage(METRIC_NAMES))
    out.extend(check_emitted_coverage(METRIC_NAMES))
    out.extend(check_help_text_keys())
    out.extend(check_fault_points_documented())
    out.extend(check_reason_codes_documented())
    return out


def main() -> int:
    violations = run_check()
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} metric-name violation(s)")
        return 1
    print("metric names OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
