#!/usr/bin/env python
"""Static check: every metric emission uses a name from the frozen
allowlist (kueue_tpu/metrics/names.py).

A typo'd series name doesn't fail at runtime — it silently forks a new
series and every dashboard reading the intended one shows zeros forever.
This walker finds each ``<metrics-ish receiver>.inc/observe/set_gauge``
call in the package and verifies the first argument is a string literal
present in ``METRIC_NAMES``.

Receivers considered metric emitters:
- the ``tracing`` module (``tracing.inc(...)``)
- a bare ``m`` (the local alias convention for a Metrics registry)
- any attribute chain containing a ``metrics`` component
  (``self.manager.metrics.inc``, ``mgr.metrics.observe``)

Other ``.observe()``-shaped calls (e.g. ``self.roletracker.observe``)
are unrelated and skipped. The registry/tracing internals are excluded:
they forward caller-supplied names by design.

Run standalone (exit 1 on violations) or via tests/test_observability.py.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "kueue_tpu"

# Forwarding layers: they pass through names owned by their callers.
EXCLUDED = {
    PACKAGE / "metrics" / "registry.py",
    PACKAGE / "metrics" / "tracing.py",
}

_EMIT_METHODS = {"inc", "observe", "set_gauge"}


def _receiver_parts(node: ast.expr) -> List[str]:
    """Flatten an attribute chain to its name components;
    ``self.manager.metrics`` -> ["self", "manager", "metrics"]."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    parts.reverse()
    return parts


def _is_metrics_receiver(parts: List[str]) -> bool:
    if not parts:
        return False
    if parts == ["tracing"] or parts == ["m"]:
        return True
    return "metrics" in parts


def check_file(path: Path, allowlist: frozenset) -> List[Tuple[int, str]]:
    violations: List[Tuple[int, str]] = []
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not isinstance(fn, ast.Attribute) or fn.attr not in _EMIT_METHODS:
            continue
        if not _is_metrics_receiver(_receiver_parts(fn.value)):
            continue
        if not node.args:
            violations.append(
                (node.lineno, f"{fn.attr}() call without a metric name")
            )
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant)
                and isinstance(first.value, str)):
            violations.append((
                node.lineno,
                f"{fn.attr}() metric name is not a string literal "
                "(allowlist check impossible)",
            ))
            continue
        if first.value not in allowlist:
            violations.append((
                node.lineno,
                f"{fn.attr}({first.value!r}) not in METRIC_NAMES "
                "(kueue_tpu/metrics/names.py)",
            ))
    return violations


def check_docs_coverage(allowlist: frozenset) -> List[str]:
    """Every allowlisted series must be documented: names.py's contract is
    "adding a metric means adding it here AND to docs/observability.md".
    An undocumented series is invisible to operators — dashboards are
    built from the doc, not from grepping emission sites."""
    doc_path = REPO_ROOT / "docs" / "observability.md"
    if not doc_path.exists():
        return [f"{doc_path.relative_to(REPO_ROOT)}: missing"]
    doc = doc_path.read_text()
    return [
        f"docs/observability.md: series {name!r} is in METRIC_NAMES but "
        "undocumented"
        for name in sorted(allowlist)
        if name not in doc
    ]


def check_fault_points_documented() -> List[str]:
    """Every registered fault-injection point (utils/faults.py POINTS)
    must appear in docs/fault_containment.md. An undocumented point is a
    containment surface nobody drills: the injection framework exists so
    operators rehearse failures by name."""
    from kueue_tpu.utils.faults import POINTS

    doc_path = REPO_ROOT / "docs" / "fault_containment.md"
    if not doc_path.exists():
        return [f"{doc_path.relative_to(REPO_ROOT)}: missing"]
    doc = doc_path.read_text()
    return [
        f"docs/fault_containment.md: fault point {point!r} is in "
        "utils/faults.py POINTS but undocumented"
        for point in sorted(POINTS)
        if point not in doc
    ]


def run_check() -> List[str]:
    """Returns human-readable violation lines; empty list = clean."""
    sys.path.insert(0, str(REPO_ROOT))
    from kueue_tpu.metrics.names import METRIC_NAMES

    out: List[str] = []
    for path in sorted(PACKAGE.rglob("*.py")):
        if path in EXCLUDED:
            continue
        for lineno, msg in check_file(path, METRIC_NAMES):
            rel = path.relative_to(REPO_ROOT)
            out.append(f"{rel}:{lineno}: {msg}")
    out.extend(check_docs_coverage(METRIC_NAMES))
    out.extend(check_fault_points_documented())
    return out


def main() -> int:
    violations = run_check()
    for v in violations:
        print(v)
    if violations:
        print(f"{len(violations)} metric-name violation(s)")
        return 1
    print("metric names OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
