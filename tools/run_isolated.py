#!/usr/bin/env python
"""Full-suite runner that shields the bulk run from the jaxlib
cumulative-compile segfault.

VERDICT round 5: a full single-process ``pytest tests/`` run intermittently
dies with SIGSEGV inside jaxlib after enough cumulative XLA compilation —
always in one of a few compile-heavy files, each of which passes cleanly
standalone (the persistent compile cache is already disabled in
tests/conftest.py for the same reason). The fix is process isolation:

1. the bulk of the suite runs once with ``-m "(not slow) and not
   isolated"`` — the compile-heavy files are marked
   ``pytest.mark.isolated`` at module level and skipped here;
2. each isolated file then runs in its own fresh subprocess, so its
   compilation burden starts from zero and a crash kills only that
   segment;
3. any segment that dies on a *signal* (segfault, not a test failure) is
   retried once in a fresh process; if the **bulk** segment still dies,
   its member files are retried standalone, one fresh process each, so
   the crash is pinned to individual casualties instead of failing the
   whole run.

The summary reports how many segments were retried after signal deaths
and how many remained casualties (still crashing when run alone). Exit
status is 0 iff every test ultimately passed — a segfault victim whose
standalone retry is green does not fail the run. Extra pytest args after
``--`` are forwarded to every segment (e.g. ``tools/run_isolated.py --
-q``). ``--compile-cache DIR`` exports KUEUE_TPU_COMPILE_CACHE=DIR to
every segment so the fresh subprocesses share warm executables through
the persistent compile cache instead of recompiling from zero
(perf/compile_cache.py). ``--perf-gate`` additionally runs the warm-
failover drill (``bench.py --probe failover`` — the kill/recover
differential of docs/failover.md, which appends ``failover_takeover_ms``
to the ledger), the read-plane probe (``bench.py --probe readplane`` —
coalesced-vs-sequential serving speedup + bounded tiled-K memory,
docs/whatif.md) and then ``tools/check_perf_ledger.py``, so a failed
drill or a headline-metric regression recorded in PERF_LEDGER.jsonl
fails the run like a test would. ``--checks`` runs ``tools/check_all.py`` (all static checkers +
import smoke) before the suite and fails fast if any checker does.
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
TESTS = REPO_ROOT / "tests"

BASE_ARGS = [
    "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    "--continue-on-collection-errors",
]


def isolated_files() -> list:
    """Discover the isolated set from the marks themselves, so marking a
    new file is the only step (no second list to update here)."""
    out = []
    for path in sorted(TESTS.glob("test_*.py")):
        text = path.read_text()
        if "pytestmark = pytest.mark.isolated" in text:
            out.append(path)
    return out


def _is_signal_death(rc: int) -> bool:
    # Negative = killed by signal (subprocess convention); 128+sig covers
    # a shell-wrapped child reporting the same thing.
    return rc < 0 or rc > 128


def run_segment(label: str, args: list, extra: list,
                stats: dict) -> int:
    """Run one pytest segment in a fresh subprocess, streaming output.
    Returns the exit code; a signal death (rc < 0, or 128+sig from a
    shell) is retried once in another fresh process and counted in
    ``stats``."""
    cmd = [sys.executable, "-m", "pytest", *BASE_ARGS, *args, *extra]
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    for attempt in (1, 2):
        print(f"== [{label}] attempt {attempt}: {' '.join(cmd)}",
              flush=True)
        rc = subprocess.call(cmd, cwd=str(REPO_ROOT), env=env)
        if rc == 5:
            # No tests collected (e.g. every test in the segment is
            # deselected by the -m expression): vacuously green.
            return 0
        if not _is_signal_death(rc):
            return rc
        print(f"== [{label}] died on a signal (rc={rc}); retrying in a "
              "fresh process", flush=True)
        if attempt == 1:
            stats["retries"] += 1
    return rc


def bulk_files() -> list:
    isolated = set(isolated_files())
    return [p for p in sorted(TESTS.glob("test_*.py"))
            if p not in isolated]


def main(argv: list) -> int:
    extra = []
    if "--" in argv:
        split = argv.index("--")
        extra = argv[split + 1:]
        argv = argv[:split]
    if "--compile-cache" in argv:
        # Point every segment at one persistent compile cache
        # (tests/conftest.py reads KUEUE_TPU_COMPILE_CACHE): the
        # isolated segments' whole point is fresh processes, which
        # otherwise recompile everything from zero — with the cache
        # their compiles become disk hits after the first run. The
        # jaxlib serialize() segfault risk rides with the opt-in, but
        # here a crashed segment is already retried and shielded.
        i = argv.index("--compile-cache")
        if i + 1 >= len(argv):
            print("--compile-cache requires a directory argument",
                  file=sys.stderr)
            return 2
        os.environ["KUEUE_TPU_COMPILE_CACHE"] = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    perf_gate = False
    if "--perf-gate" in argv:
        perf_gate = True
        argv.remove("--perf-gate")
    if "--checks" in argv:
        # Static checkers + import smoke up front: a typo'd metric name
        # or broken facade import fails in seconds, not after the suite.
        argv.remove("--checks")
        print("== [checks] tools/check_all.py", flush=True)
        rc = subprocess.call(
            [sys.executable, str(REPO_ROOT / "tools" / "check_all.py")],
            cwd=str(REPO_ROOT),
        )
        if rc != 0:
            print("== [checks] failed; aborting before the suite",
                  file=sys.stderr)
            return rc
    if argv:
        print(f"unknown arguments {argv!r}; pass pytest args after --",
              file=sys.stderr)
        return 2

    stats = {"retries": 0}
    failures = []
    casualties = []
    rc = run_segment(
        "bulk",
        ["tests/", "-m", "(not slow) and not isolated"],
        extra, stats,
    )
    if _is_signal_death(rc):
        # The cumulative-compile crash moved into the bulk segment: pin
        # it down by retrying every member file standalone, one fresh
        # process each. Only files that fail (or keep crashing) alone
        # count against the run.
        print("== [bulk] still dying on a signal; retrying member files "
              "standalone", flush=True)
        for path in bulk_files():
            rel = str(path.relative_to(REPO_ROOT))
            stats["retries"] += 1
            frc = run_segment(
                rel, [rel, "-m", "(not slow) and not isolated"],
                extra, stats,
            )
            if _is_signal_death(frc):
                casualties.append((rel, frc))
            elif frc != 0:
                failures.append((rel, frc))
    elif rc != 0:
        failures.append(("bulk", rc))
    for path in isolated_files():
        rel = str(path.relative_to(REPO_ROOT))
        rc = run_segment(rel, [rel, "-m", "not slow"], extra, stats)
        if _is_signal_death(rc):
            casualties.append((rel, rc))
        elif rc != 0:
            failures.append((rel, rc))

    if perf_gate:
        # Failover drill first: the kill/recover differential probe
        # (docs/failover.md) appends its takeover headline to the
        # ledger, so the gate below sees this run, not just history.
        print("== [perf-gate] bench.py --probe failover", flush=True)
        rc = subprocess.call(
            [sys.executable, str(REPO_ROOT / "bench.py"),
             "--probe", "failover", "--scale", "0.05",
             "--platform", "cpu"],
            cwd=str(REPO_ROOT),
        )
        if rc != 0:
            failures.append(("perf-gate:failover", rc))
        # Read-plane probe: coalesced-vs-sequential serving speedup,
        # query p99 under concurrent load, snapshot staleness, and the
        # bounded tiled-K scenario plane (docs/whatif.md).
        print("== [perf-gate] bench.py --probe readplane", flush=True)
        rc = subprocess.call(
            [sys.executable, str(REPO_ROOT / "bench.py"),
             "--probe", "readplane", "--scale", "0.05",
             "--platform", "cpu"],
            cwd=str(REPO_ROOT),
        )
        if rc != 0:
            failures.append(("perf-gate:readplane", rc))
        # Columnar encode probe: warm-columns full-encode speedup vs the
        # row-wise oracle, plus the 3-seed bit-identity differential
        # (docs/perf.md, "Columnar workload plane").
        print("== [perf-gate] bench.py --probe encode", flush=True)
        rc = subprocess.call(
            [sys.executable, str(REPO_ROOT / "bench.py"),
             "--probe", "encode", "--scale", "0.1",
             "--platform", "cpu"],
            cwd=str(REPO_ROOT),
        )
        if rc != 0:
            failures.append(("perf-gate:encode", rc))
        # Perf-ledger gate: headline metrics in PERF_LEDGER.jsonl must
        # not regress vs their rolling median (check_perf_ledger.py).
        print("== [perf-gate] tools/check_perf_ledger.py", flush=True)
        rc = subprocess.call(
            [sys.executable, str(REPO_ROOT / "tools"
                                 / "check_perf_ledger.py")],
            cwd=str(REPO_ROOT),
        )
        if rc != 0:
            failures.append(("perf-gate", rc))

    print("\n== run_isolated summary")
    print(f"signal retries: {stats['retries']}, "
          f"casualties: {len(casualties)}")
    if not failures and not casualties:
        print("all segments passed")
        return 0
    for label, rc in casualties:
        print(f"CASUALTY segment {label} (still dying on rc={rc})")
    for label, rc in failures:
        print(f"FAILED segment {label} (rc={rc})")
    return 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
