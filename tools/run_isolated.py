#!/usr/bin/env python
"""Full-suite runner that shields the bulk run from the jaxlib
cumulative-compile segfault.

VERDICT round 5: a full single-process ``pytest tests/`` run intermittently
dies with SIGSEGV inside jaxlib after enough cumulative XLA compilation —
always in one of a few compile-heavy files, each of which passes cleanly
standalone (the persistent compile cache is already disabled in
tests/conftest.py for the same reason). The fix is process isolation:

1. the bulk of the suite runs once with ``-m "(not slow) and not
   isolated"`` — the compile-heavy files are marked
   ``pytest.mark.isolated`` at module level and skipped here;
2. each isolated file then runs in its own fresh subprocess, so its
   compilation burden starts from zero and a crash kills only that
   segment;
3. any segment that dies on a *signal* (segfault, not a test failure) is
   retried once in a fresh process before being counted as failed.

Exit status is 0 iff every segment passed. Extra pytest args after ``--``
are forwarded to every segment (e.g. ``tools/run_isolated.py -- -q``).
"""

from __future__ import annotations

import os
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
TESTS = REPO_ROOT / "tests"

BASE_ARGS = [
    "-p", "no:cacheprovider", "-p", "no:xdist", "-p", "no:randomly",
    "--continue-on-collection-errors",
]


def isolated_files() -> list:
    """Discover the isolated set from the marks themselves, so marking a
    new file is the only step (no second list to update here)."""
    out = []
    for path in sorted(TESTS.glob("test_*.py")):
        text = path.read_text()
        if "pytestmark = pytest.mark.isolated" in text:
            out.append(path)
    return out


def run_segment(label: str, args: list, extra: list) -> int:
    """Run one pytest segment in a fresh subprocess, streaming output.
    Returns the exit code; a signal death (rc < 0, or 128+sig from a
    shell) is retried once in another fresh process."""
    cmd = [sys.executable, "-m", "pytest", *BASE_ARGS, *args, *extra]
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    for attempt in (1, 2):
        print(f"== [{label}] attempt {attempt}: {' '.join(cmd)}",
              flush=True)
        rc = subprocess.call(cmd, cwd=str(REPO_ROOT), env=env)
        if rc == 5:
            # No tests collected (e.g. every test in the segment is
            # deselected by the -m expression): vacuously green.
            return 0
        if rc >= 0 and rc != 139:
            return rc
        print(f"== [{label}] died on a signal (rc={rc}); retrying in a "
              "fresh process", flush=True)
    return rc


def main(argv: list) -> int:
    extra = []
    if "--" in argv:
        split = argv.index("--")
        extra = argv[split + 1:]
        argv = argv[:split]
    if argv:
        print(f"unknown arguments {argv!r}; pass pytest args after --",
              file=sys.stderr)
        return 2

    failures = []
    rc = run_segment(
        "bulk",
        ["tests/", "-m", "(not slow) and not isolated"],
        extra,
    )
    if rc != 0:
        failures.append(("bulk", rc))
    for path in isolated_files():
        rel = path.relative_to(REPO_ROOT)
        rc = run_segment(str(rel), [str(rel), "-m", "not slow"], extra)
        if rc != 0:
            failures.append((str(rel), rc))

    print("\n== run_isolated summary")
    if not failures:
        print("all segments passed")
        return 0
    for label, rc in failures:
        print(f"FAILED segment {label} (rc={rc})")
    return 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
