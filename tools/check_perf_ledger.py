#!/usr/bin/env python
"""Perf-ledger regression gate.

Reads ``PERF_LEDGER.jsonl`` (kueue_tpu/perf/ledger.py records), groups
records by (probe, config fingerprint), and compares the NEWEST record's
headline metrics against the rolling median of up to ``--window`` prior
records in the same group. Fails (exit 1) when any headline metric is
worse than the median by more than ``--threshold`` fraction —
lower-is-better metrics regress upward, higher-is-better ones downward.

Groups with no history (a single record) pass: the first run of a new
config seeds the baseline. Records that fail schema validation fail the
gate — a ledger the checker can't read is itself a regression.

Standalone:
    python tools/check_perf_ledger.py [--ledger PATH] [--threshold 0.2]
Wired into the suite runner as ``tools/run_isolated.py --perf-gate``.
"""

from __future__ import annotations

import argparse
import statistics
import sys
from pathlib import Path
from typing import Dict, List, Tuple

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from kueue_tpu.perf import ledger  # noqa: E402


def check_ledger(records: List[dict], threshold: float = 0.2,
                 window: int = 8) -> Tuple[List[str], List[str]]:
    """Returns (problems, notes). Empty problems == gate passes."""
    problems: List[str] = []
    notes: List[str] = []
    groups: Dict[Tuple[str, str], List[dict]] = {}
    for i, rec in enumerate(records):
        errs = ledger.validate_record(rec)
        if errs:
            problems.append(f"record #{i}: " + "; ".join(errs))
            continue
        groups.setdefault(
            (rec["probe"], rec["fingerprint"]), []
        ).append(rec)

    for (probe, fp), group in sorted(groups.items()):
        newest, priors = group[-1], group[:-1][-window:]
        if not newest.get("ok"):
            problems.append(
                f"{probe}[{fp}]: newest record reports ok=false"
            )
            continue
        if not priors:
            notes.append(f"{probe}[{fp}]: no history yet (baseline run)")
            continue
        for name, h in newest.get("headline", {}).items():
            base_vals = [
                p["headline"][name]["value"] for p in priors
                if name in p.get("headline", {}) and p.get("ok")
            ]
            if not base_vals:
                notes.append(f"{probe}[{fp}].{name}: no prior values")
                continue
            base = statistics.median(base_vals)
            val = h["value"]
            if base == 0:
                continue
            if h["direction"] == "lower":
                ratio = (val - base) / abs(base)
            else:
                ratio = (base - val) / abs(base)
            if ratio > threshold:
                problems.append(
                    f"{probe}[{fp}].{name}: {val:g} vs median {base:g} "
                    f"of {len(base_vals)} prior(s) — "
                    f"{ratio * 100:.1f}% worse (> {threshold * 100:.0f}%)"
                )
            else:
                notes.append(
                    f"{probe}[{fp}].{name}: {val:g} vs median {base:g} "
                    f"({ratio * 100:+.1f}% worse-direction delta, ok)"
                )
    return problems, notes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ledger", type=Path,
                    default=ledger.default_ledger_path())
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="max worse-direction fraction vs rolling median")
    ap.add_argument("--window", type=int, default=8,
                    help="how many prior records feed the median")
    args = ap.parse_args(argv)

    records = ledger.load_records(args.ledger)
    if not records:
        print(f"perf ledger: no records at {args.ledger} — nothing to "
              "gate (pass)")
        return 0
    problems, notes = check_ledger(records, threshold=args.threshold,
                                   window=args.window)
    for n in notes:
        print(f"  {n}")
    if problems:
        print(f"perf ledger: {len(problems)} problem(s):")
        for p in problems:
            print(f"  REGRESSION {p}")
        return 1
    print(f"perf ledger: OK ({len(records)} record(s), "
          f"threshold {args.threshold * 100:.0f}%)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
