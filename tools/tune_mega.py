"""Mega-cycle tuning sweep on the attached accelerator.

Measures the north-star cycle (bench.build_mega: 50k x 2000 x 32) across
kernel variants/knobs and prints one JSON line per config:
  * grouped scan with exact s_max (max per-tree entry bucket) vs the
    conservative 2W/G, unroll 2/4/8;
  * fixed-point rounds actually taken + wall time.

Usage:  python tools/tune_mega.py [--platform tpu] [--configs a,b,...]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--configs", default="")
    ap.add_argument("--w", type=int, default=50_000)
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import numpy as np
    import jax
    import jax.numpy as jnp

    from bench import build_mega
    from kueue_tpu.models import batch_scheduler as bs

    arrays, layout = build_mega(W=args.w)
    ga = bs.GroupArrays(*layout.as_jax())
    group_of = np.asarray(layout.flat_to_group)[np.asarray(arrays.w_cq)]
    s_exact = int(np.bincount(group_of, minlength=layout.n_groups).max())
    s_cons = 2 * args.w // layout.n_groups
    log(f"platform={jax.devices()[0].platform} groups={layout.n_groups} "
        f"s_exact={s_exact} s_conservative={s_cons}")

    n_levels = int(np.asarray(arrays.tree.depth).max()) + 1
    configs = []
    for unroll in (2, 4, 8):
        configs.append((f"exact_u{unroll}",
                        lambda u=unroll: jax.jit(bs.make_grouped_cycle(
                            s_exact, unroll=u, n_levels=n_levels))))
    configs.append(("cons_u2",
                    lambda: jax.jit(bs.make_grouped_cycle(s_cons))))
    configs.append(("fixedpoint", lambda: jax.jit(
        bs.make_fixedpoint_cycle(n_levels=n_levels))))
    from kueue_tpu.models import pallas_scan as ps

    configs.append(("pallas", lambda: jax.jit(
        ps.make_pallas_cycle(s_exact, n_levels=n_levels))))
    configs.append(("pallas32", lambda: jax.jit(
        ps.make_pallas_cycle(s_exact, n_levels=n_levels, i32=True))))
    if args.configs:
        want = set(args.configs.split(","))
        configs = [(n, f) for n, f in configs if n in want]
    if any(n.startswith("pallas") for n, _ in configs) \
            and not ps.fits_int32(arrays):
        log("pallas configs skipped: fits_int32(arrays) is False")
        configs = [
            (n, f) for n, f in configs if not n.startswith("pallas")
        ]

    ref_admitted = None
    for name, mk in configs:
        fn = mk()
        t0 = time.monotonic()
        out = fn(arrays, ga)
        out.outcome.block_until_ready()
        compile_s = time.monotonic() - t0
        best = 1e9
        for _ in range(3):
            t0 = time.monotonic()
            out = fn(arrays, ga)
            out.outcome.block_until_ready()
            best = min(best, time.monotonic() - t0)
        admitted = int((np.asarray(out.outcome) == bs.OUT_ADMITTED).sum())
        if ref_admitted is None:
            ref_admitted = admitted
        rec = {"config": name, "ms": round(best * 1000, 1),
               "compile_s": round(compile_s, 1), "admitted": admitted,
               "match": admitted == ref_admitted}
        print(json.dumps(rec), flush=True)

    # Fixed-point rounds diagnostic.
    if any("fixedpoint" in n for n, _ in configs):
        usage = arrays.usage
        nom = jax.jit(bs.nominate)(arrays, usage)
        order = jax.jit(bs.admission_order)(arrays, nom)

        @jax.jit
        def fp(arrays, nom, usage, order):
            return bs.admit_fixedpoint(arrays, ga, nom, usage, order)

        _u, _a, rounds = fp(arrays, nom, usage, order)
        print(json.dumps({"config": "fixedpoint_rounds",
                          "rounds": int(rounds)}), flush=True)


if __name__ == "__main__":
    main()
