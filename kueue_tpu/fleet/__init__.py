"""Joint multi-cluster (MultiKueue fleet) placement subsystem.

See docs/multikueue.md. Encode per-cluster capacity into ``[C, ...]``
lane planes (:mod:`kueue_tpu.fleet.encode`), solve the whole pending
batch in one device dispatch (:mod:`kueue_tpu.fleet.kernel`) or one
host oracle walk (:mod:`kueue_tpu.fleet.oracle`), apply per lane
through the existing remote worker layer
(:mod:`kueue_tpu.fleet.dispatcher`).
"""

from kueue_tpu.fleet.dispatcher import FleetDispatcher, plan_from_outputs
from kueue_tpu.fleet.encode import (
    AFFINITY_ANNOTATION,
    FLEET_MAX_S,
    FleetEncoder,
    FleetSpec,
    FleetUnsupported,
    cluster_capacity,
    local_capacity,
    to_device,
)
from kueue_tpu.fleet.kernel import FleetOutputs, fleet_cycle, make_fleet_cycle
from kueue_tpu.fleet.oracle import (
    FleetPlan,
    fleet_oracle,
    plans_equal,
    validate_plan,
)

__all__ = [
    "AFFINITY_ANNOTATION",
    "FLEET_MAX_S",
    "FleetDispatcher",
    "FleetEncoder",
    "FleetOutputs",
    "FleetPlan",
    "FleetSpec",
    "FleetUnsupported",
    "cluster_capacity",
    "fleet_cycle",
    "fleet_oracle",
    "local_capacity",
    "make_fleet_cycle",
    "plan_from_outputs",
    "plans_equal",
    "to_device",
    "validate_plan",
]
