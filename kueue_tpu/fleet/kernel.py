"""``cycle_fleet_assign``: joint multi-cluster placement in one dispatch.

One jitted program places a whole admission batch across every worker
cluster at once. The scan walks candidates in admission order (priority
desc — the same order the sequential MultiKueue dispatcher visits them)
carrying per-lane state ``(avail [C,F,R], taken [C,S], placed [C])``;
each step evaluates *every* cluster lane in parallel (vectorized
feasibility over the C axis — the "vmap over clusters" of the fleet
design, fused into the scan body) and a cross-cluster argmin over
dispatch cost + spread + preemption penalties picks the lane.

Determinism contract (what the differential suite pins against the
sequential host oracle in ``fleet/oracle.py``):

- lane tie-break: lowest lane index among equal costs (``argmin`` picks
  the first minimum; lanes are sorted by cluster name at encode time);
- flavor tie-break: first feasible flavor index (``argmax`` of the
  boolean fits row picks the first ``True``);
- victim selection: the greedy eligible prefix — victims are sorted
  (priority asc, key asc) at encode time, and a preempting placement
  takes every eligible victim up to the first prefix whose cumulative
  freed capacity fits the request, exactly as a sequential preemptor
  walking that order would.

All integer planes are int32; costs are int32 so the masked argmin is
exact (no float ties). Infeasible/padded lanes are masked to ``BIG``
which no real cost can reach (encode bounds dispatch costs well below
it).
"""

from __future__ import annotations

from typing import NamedTuple

from kueue_tpu.fleet.encode import FleetArrays

#: Cost mask for infeasible lanes: any real cost is far below this, so
#: the argmin never picks a masked lane and ``min >= BIG`` means "no
#: lane can take this candidate".
BIG = 1 << 30


class FleetOutputs(NamedTuple):
    admitted: object   # [W] bool
    cluster: object    # [W] i32, -1 when not admitted
    flavor: object     # [W] i32, -1 when not admitted
    victims: object    # [W, S] bool, chosen lane's victim axis
    placed: object     # [C] i32 placements per lane
    avail: object      # [C, F, R] i32 post-placement capacity


def make_fleet_cycle():
    """Build the jitted joint fleet-assignment cycle.

    kernel-entry: cycle_fleet_assign
    gate-requires: spec.s_bound <= FLEET_MAX_S

    Returns a function ``(arrays: FleetArrays) -> FleetOutputs`` closed
    over nothing, so one compiled executable serves every fleet at the
    same padded ``(C, S, F, R, W)`` shapes.
    """
    import jax
    import jax.numpy as jnp

    def step(carry, xs):
        avail, taken, placed = carry
        req_w, elig_w, prio_w, cost_w, valid_w, pre_w, \
            flavor_ok, vict_free, vict_prio, vict_ok, \
            spread_w, pre_penalty = xs

        C, F, R = avail.shape
        S = taken.shape[1]

        okf = flavor_ok & elig_w[None, :]                       # [C, F]
        fits_free = jnp.all(
            avail >= req_w[None, None, :], axis=-1
        ) & okf                                                  # [C, F]
        free_any = jnp.any(fits_free, axis=-1)                   # [C]
        free_flavor = jnp.argmax(fits_free, axis=-1)             # [C]

        elig_v = vict_ok & ~taken & (vict_prio < prio_w)         # [C, S]
        freed_cum = jnp.cumsum(
            vict_free * elig_v[:, :, None, None].astype(jnp.int32),
            axis=1,
        )                                                        # [C,S,F,R]
        fits_pre = jnp.all(
            avail[:, None, :, :] + freed_cum >= req_w[None, None, None, :],
            axis=-1,
        ) & okf[:, None, :]                                      # [C, S, F]
        pre_any_f = jnp.any(fits_pre, axis=1)                    # [C, F]
        pre_flavor = jnp.argmax(pre_any_f, axis=-1)              # [C]
        pre_any = jnp.any(pre_any_f, axis=-1) & pre_w            # [C]

        feasible = free_any | pre_any
        use_pre = ~free_any & pre_any
        lane_cost = (
            cost_w
            + spread_w * placed
            + jnp.where(use_pre, pre_penalty, 0)
        )
        masked = jnp.where(feasible & valid_w, lane_cost, BIG)
        c_star = jnp.argmin(masked)                              # first min
        admitted = masked[c_star] < BIG

        pre_here = use_pre[c_star]
        flavor = jnp.where(pre_here, pre_flavor[c_star],
                           free_flavor[c_star])

        # Victim prefix on the chosen lane: first s whose cumulative
        # freed capacity fits at the chosen flavor; take every eligible
        # victim up to it.
        fits_row = fits_pre[c_star, :, flavor]                   # [S]
        s_first = jnp.argmax(fits_row)
        sel = (
            elig_v[c_star]
            & (jnp.arange(S) <= s_first)
            & pre_here
            & admitted
        )                                                        # [S]
        # dtype pinned: under x64 jnp.sum promotes i32 -> i64, which
        # would poison the avail scatter-add below.
        freed_sel = jnp.sum(
            vict_free[c_star] * sel[:, None, None].astype(jnp.int32),
            axis=0, dtype=jnp.int32,
        )                                                        # [F, R]
        consume = (
            jnp.zeros((F, R), dtype=jnp.int32)
            .at[flavor, :].set(req_w)
        )
        delta = jnp.where(admitted, freed_sel - consume,
                          jnp.zeros((F, R), dtype=jnp.int32))
        avail = avail.at[c_star].add(delta)
        taken = taken.at[c_star].set(taken[c_star] | sel)
        placed = placed.at[c_star].add(admitted.astype(jnp.int32))

        out = (
            admitted,
            jnp.where(admitted, c_star.astype(jnp.int32),
                      jnp.int32(-1)),
            jnp.where(admitted, flavor.astype(jnp.int32),
                      jnp.int32(-1)),
            sel,
        )
        return (avail, taken, placed), out

    def cycle(arrays: FleetArrays) -> FleetOutputs:
        C = arrays.avail.shape[0]
        S = arrays.vict_ok.shape[1]
        W = arrays.req.shape[0]
        carry = (
            arrays.avail,
            jnp.zeros((C, S), dtype=bool),
            jnp.zeros((C,), dtype=jnp.int32),
        )
        xs = (
            arrays.req, arrays.elig, arrays.prio,
            jnp.swapaxes(arrays.cost, 0, 1),     # [W, C]
            arrays.valid, arrays.preempt,
        )

        def body(carry, x):
            req_w, elig_w, prio_w, cost_w, valid_w, pre_w = x
            return step(carry, (
                req_w, elig_w, prio_w, cost_w, valid_w, pre_w,
                arrays.flavor_ok, arrays.vict_free,
                arrays.vict_prio, arrays.vict_ok,
                arrays.spread_w, arrays.pre_penalty,
            ))

        (avail, _taken, placed), (admitted, cluster, flavor, victims) = \
            jax.lax.scan(body, carry, xs, length=W)
        return FleetOutputs(
            admitted=admitted, cluster=cluster, flavor=flavor,
            victims=victims, placed=placed, avail=avail,
        )

    return jax.jit(cycle)


_CYCLE = None


def fleet_cycle():
    """Memoized jitted cycle (one program per process)."""
    global _CYCLE
    if _CYCLE is None:
        _CYCLE = make_fleet_cycle()
    return _CYCLE
