"""FleetDispatcher: one joint solve admits for the whole fleet.

The sequential MultiKueue dispatcher mirrors each workload to every
nominated worker, lets every worker race, then keeps the first
reservation — O(candidates x clusters) remote round-trips per admission
wave, and the "winner" is whichever cluster answered first, not the
cheapest feasible one. The fleet dispatcher replaces that loop: encode
every reachable worker's capacity into lane planes (``fleet/encode``),
solve placement for the *entire* pending batch in one device dispatch
(``cycle_fleet_assign``) or one host oracle walk, then apply each lane's
placements with one mirror + one ``schedule_all`` per cluster.

Containment ladder (never corrupt local state):

- device solve faults/invalid plan -> host oracle, counted under
  ``solver_fallback_cycles_total{reason="fleet"}``;
- a lane shape the flat planes can't model (``FleetUnsupported``) or an
  encode crash -> return ``False`` so the controller's sequential path
  handles the workload exactly as before this subsystem existed;
- a lane that fails during *apply* (transport down, worker crash) ->
  that lane's placements stay PENDING and retry next tick, counted in
  ``fleet_apply_failures_total``; other lanes' applies are unaffected.

With a :class:`~kueue_tpu.obs.service.ServiceLoop` attached, per-lane
apply results are streamed through the loop's ingestion queue
(``service.call``) so remote confirmations serialize with admission
cycles instead of racing them.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from kueue_tpu.api.constants import CheckState
from kueue_tpu.api.types import Workload
from kueue_tpu.core.workload_info import (
    has_quota_reservation,
    is_finished,
)
from kueue_tpu.fleet.encode import (
    FLEET_MAX_S,
    FleetEncoder,
    FleetSpec,
    FleetUnsupported,
    to_device,
)
from kueue_tpu.fleet.oracle import FleetPlan, fleet_oracle, validate_plan
from kueue_tpu.utils import faults

import numpy as np


def plan_from_outputs(spec: FleetSpec, out) -> FleetPlan:
    """Slice padded device outputs back to the spec's real extents."""
    C = spec.c
    W = spec.w
    S = spec.vict_ok.shape[1]
    return FleetPlan(
        admitted=np.asarray(out.admitted)[:W].astype(bool),
        cluster=np.asarray(out.cluster)[:W].astype(np.int32),
        flavor=np.asarray(out.flavor)[:W].astype(np.int32),
        victims=np.asarray(out.victims)[:W, :S].astype(bool),
        placed=np.asarray(out.placed)[:C].astype(np.int32),
        avail=np.asarray(out.avail)[:C].astype(np.int64),
    )


class FleetDispatcher:
    """Joint placement front-end for :class:`MultiKueueController`."""

    def __init__(
        self,
        device: bool = True,
        preemption: bool = False,
        spread_weight: int = 1,
        preempt_penalty: int = 64,
        affinity_penalty: int = 8,
        dispatch_costs: Optional[Dict[str, int]] = None,
        service=None,
    ) -> None:
        self.device = device
        self.preemption = preemption
        self.spread_weight = spread_weight
        self.preempt_penalty = preempt_penalty
        self.affinity_penalty = affinity_penalty
        self.dispatch_costs = dict(dispatch_costs or {})
        self.service = service
        self.encoder = FleetEncoder()
        self.controller = None
        self._last_fp: Optional[Tuple] = None

    @classmethod
    def from_settings(cls, settings, service=None) -> "FleetDispatcher":
        """Build from config ``MultiKueueSettings`` (fleet_* fields)."""
        return cls(
            device=getattr(settings, "fleet_device", True),
            preemption=getattr(settings, "fleet_preemption", False),
            spread_weight=getattr(settings, "fleet_spread_weight", 1),
            preempt_penalty=getattr(settings, "fleet_preempt_penalty", 64),
            affinity_penalty=getattr(settings, "fleet_affinity_penalty", 8),
            dispatch_costs=getattr(settings, "fleet_dispatch_costs", None),
            service=service,
        )

    def bind(self, controller) -> "FleetDispatcher":
        self.controller = controller
        return self

    # -- candidate collection -------------------------------------------

    def _collect(self, manager, check_name: str) -> List[Workload]:
        out: List[Workload] = []
        for wl in manager.workloads.values():
            if not wl.active or is_finished(wl):
                continue
            if not has_quota_reservation(wl):
                continue
            if wl.status.cluster_name:
                continue
            for acs in wl.status.admission_checks:
                if acs.name == check_name \
                        and acs.state == CheckState.PENDING:
                    out.append(wl)
                    break
        return out

    def _capacity_token(self) -> Optional[Tuple]:
        """Stable token over every worker's cache generations, or None
        when any worker (remote clients) can't provide one — meaning
        the no-change fast path must not be taken."""
        ctrl = self.controller
        if ctrl is None:
            return None
        parts = []
        for name in sorted(ctrl.workers):
            cache = getattr(ctrl.workers[name], "cache", None)
            if cache is None:
                return None
            parts.append((name, cache.generation,
                          cache.workload_generation))
        return tuple(parts)

    # -- the joint solve -------------------------------------------------

    def sync(self, manager, wl: Workload, check_name: str) -> bool:
        """Fleet entry point, called per-workload from the controller's
        ``sync``. The *first* pending workload of a tick triggers the
        joint solve for every candidate; later candidates' checks are
        already resolved (or the fingerprint guard makes their call a
        no-op). Returns ``False`` to hand the workload to the
        controller's sequential path."""
        if self.controller is None or not self.controller.workers:
            return False
        return self.run(manager, check_name)

    def run(self, manager, check_name: str) -> bool:
        ctrl = self.controller
        candidates = self._collect(manager, check_name)
        if not candidates:
            return True
        token = self._capacity_token()
        fp = (frozenset(w.key for w in candidates), token)
        if token is not None and fp == self._last_fp:
            # Same pending set against unchanged capacity: the previous
            # solve's outcome still stands, nothing to recompute.
            return True

        t0 = time.perf_counter()
        try:
            spec = self.encoder.encode(
                ctrl.workers, candidates,
                preemption=self.preemption,
                spread_weight=self.spread_weight,
                preempt_penalty=self.preempt_penalty,
                affinity_penalty=self.affinity_penalty,
                dispatch_costs=self.dispatch_costs,
            )
        except FleetUnsupported:
            return False
        except Exception:  # noqa: BLE001 - encode crash: sequential path
            manager.metrics.inc(
                "solver_fallback_cycles_total", {"reason": "fleet"}
            )
            return False

        for lane in spec.skipped:
            manager.metrics.inc(
                "fleet_lane_unavailable_total", {"cluster": lane}
            )
        manager.metrics.set_gauge("fleet_lanes", spec.c)
        manager.metrics.set_gauge("fleet_candidates", spec.w)
        if spec.c == 0:
            # Whole fleet unreachable: nothing to place against; retry
            # next tick (transport breakers own the backoff).
            self._last_fp = fp
            return True

        plan, path = self._solve(manager, spec)
        manager.metrics.inc("fleet_dispatches_total", {"path": path})
        manager.metrics.observe(
            "fleet_dispatch_seconds", time.perf_counter() - t0
        )
        clean = self._apply(manager, spec, plan, candidates, check_name)
        # A lane that failed during apply must retry next tick even if
        # nothing else changed — only a clean apply arms the
        # unchanged-fingerprint fast path.
        self._last_fp = (fp[0], self._capacity_token()) if clean else None
        return True

    def _select_entry(self, spec: FleetSpec) -> Optional[str]:
        entry = None
        if self.device and spec.s_bound <= FLEET_MAX_S:
            entry = "cycle_fleet_assign"
        return entry

    def _solve(self, manager, spec: FleetSpec) -> Tuple[FleetPlan, str]:
        entry = self._select_entry(spec)
        if entry is not None:
            try:
                if faults.ENABLED:
                    faults.fire(faults.FLEET_DISPATCH)
                from kueue_tpu.fleet.kernel import fleet_cycle
                from kueue_tpu.perf import compile_cache

                arrays = to_device(spec)
                out = compile_cache.dispatch(entry, fleet_cycle(), arrays)
                plan = plan_from_outputs(spec, out)
                errs = validate_plan(spec, plan)
                if errs:
                    raise RuntimeError(
                        f"fleet plan validation failed: {errs[:3]}"
                    )
                return plan, "device"
            except Exception:  # noqa: BLE001 - contained: host oracle
                manager.metrics.inc(
                    "solver_fallback_cycles_total", {"reason": "fleet"}
                )
        return fleet_oracle(spec), "host"

    # -- per-lane apply ---------------------------------------------------

    def _apply(self, manager, spec: FleetSpec, plan: FleetPlan,
               candidates: List[Workload], check_name: str) -> bool:
        """Apply per lane; returns True only if every lane applied
        without a contained failure."""
        by_key = {w.key: w for w in candidates}
        lanes: Dict[str, List[Tuple[Workload, List[str]]]] = {}
        for wi, key in enumerate(spec.candidates):
            if not plan.admitted[wi]:
                continue
            wl = by_key.get(key)
            if wl is None:
                continue
            ci = int(plan.cluster[wi])
            cname = spec.clusters[ci]
            vkeys = [
                spec.vict_keys[ci][si]
                for si in np.nonzero(plan.victims[wi])[0]
                if si < len(spec.vict_keys[ci])
            ]
            lanes.setdefault(cname, []).append((wl, vkeys))
        clean = True
        for cname, rows in lanes.items():
            clean = self._apply_lane(manager, cname, rows, check_name) \
                and clean
        return clean

    def _apply_lane(self, manager, cname: str,
                    rows: List[Tuple[Workload, List[str]]],
                    check_name: str) -> bool:
        ctrl = self.controller
        worker = ctrl.workers[cname]
        try:
            if faults.ENABLED:
                faults.fire(faults.FLEET_APPLY)
            victim_keys: List[str] = []
            seen = set()
            for _wl, vkeys in rows:
                for vk in vkeys:
                    if vk not in seen:
                        seen.add(vk)
                        victim_keys.append(vk)
            for vk in victim_keys:
                remote_v = worker.workloads.get(vk)
                if remote_v is not None:
                    worker.delete_workload(remote_v)
                manager.metrics.inc(
                    "fleet_preemptions_total", {"cluster": cname}
                )
                local_v = manager.workloads.get(vk)
                if local_v is not None \
                        and local_v.status.cluster_name == cname:
                    ctrl._redispatch(manager, local_v)
            for wl, _vkeys in rows:
                if wl.key not in worker.workloads:
                    copy = wl.clone()
                    copy.status = type(copy.status)()
                    try:
                        worker.create_workload(copy)
                    except ValueError:
                        pass  # raced into existence: fine
            schedule_all = getattr(worker, "schedule_all", None)
            if schedule_all is not None:
                schedule_all()
            else:
                worker.schedule()
            for wl, _vkeys in rows:
                remote = worker.workloads.get(wl.key)
                if remote is None or not has_quota_reservation(remote):
                    continue  # lane disagreed: stays PENDING, retries
                self._finalize(manager, wl, cname, check_name)
            return True
        except ConnectionError:
            manager.metrics.inc(
                "fleet_apply_failures_total", {"cluster": cname}
            )
        except Exception:  # noqa: BLE001 - lane contained, others proceed
            manager.metrics.inc(
                "fleet_apply_failures_total", {"cluster": cname}
            )
        return False

    def _finalize(self, manager, wl: Workload, cname: str,
                  check_name: str) -> None:
        """Record the placement on the manager side. Streamed through
        the service ingest queue when one is attached (and we are not
        already on the loop thread), so confirmations serialize with
        admission cycles."""
        svc = self.service

        def fin(mgr) -> None:
            self._finalize_inline(mgr, wl, cname, check_name)

        if svc is not None:
            import threading

            on_loop = (
                getattr(svc, "_thread", None) is threading.current_thread()
            )
            if not on_loop and svc.post(("fleet_apply", fin,
                                         manager.clock())):
                return
        fin(manager)

    def _finalize_inline(self, manager, wl: Workload, cname: str,
                         check_name: str) -> None:
        ctrl = self.controller
        worker = ctrl.workers.get(cname)
        if worker is None:
            return
        try:
            remote = worker.workloads.get(wl.key)
        except ConnectionError:
            remote = None
        if remote is None or not has_quota_reservation(remote):
            return
        st = ctrl.state.get(wl.key)
        if st is None:
            st = _group_state()
            ctrl.state[wl.key] = st
        st.winner = cname
        if cname not in st.nominated:
            st.nominated.append(cname)
        wl.status.cluster_name = cname
        ctrl._mirror_topology(wl, remote)
        acs = next(
            (a for a in wl.status.admission_checks
             if a.name == check_name),
            None,
        )
        if acs is not None:
            acs.state = CheckState.READY
            acs.message = (
                f'The workload got reservation on "{cname}" (fleet)'
            )
            acs.last_transition_time = manager.clock()
        manager.metrics.inc(
            "multikueue_dispatches_total", {"cluster": cname}
        )
        manager.metrics.inc(
            "fleet_placements_total", {"cluster": cname}
        )

    # -- prewarm -----------------------------------------------------------

    def prewarm(self, max_heads: int = 16, aot: bool = True) -> dict:
        """Compile the fleet cycle for the current worker shapes so the
        first joint dispatch hits a warm executable. Zero-candidate
        planes at the real (C, S, F, R) extents and the W ladder up to
        ``max_heads`` — the same shapes runtime solves pad to."""
        ctrl = self.controller
        if ctrl is None or not ctrl.workers or not self.device:
            return {"entries": 0}
        from kueue_tpu.models import buckets
        from kueue_tpu.fleet.kernel import fleet_cycle
        from kueue_tpu.perf import compile_cache

        try:
            spec = self.encoder.encode(
                ctrl.workers, [],
                preemption=self.preemption,
                spread_weight=self.spread_weight,
                preempt_penalty=self.preempt_penalty,
                affinity_penalty=self.affinity_penalty,
                dispatch_costs=self.dispatch_costs,
            )
        except Exception:  # noqa: BLE001 - incl. FleetUnsupported
            return {"entries": 0}
        if spec.c == 0 or self._select_entry(spec) is None:
            return {"entries": 0}
        entries = 0
        for rung in buckets.ladder(max_heads):
            try:
                arrays = to_device(spec, w_bucket=rung)
                compile_cache.prewarm_entry(
                    "cycle_fleet_assign", fleet_cycle(), (arrays,),
                    aot=aot,
                )
                entries += 1
            except Exception:  # noqa: BLE001 - prewarm is best-effort
                break
        return {"entries": entries, "clusters": spec.c,
                "s_bound": spec.s_bound}


def _group_state():
    from kueue_tpu.controllers.multikueue import _GroupState

    return _GroupState()
