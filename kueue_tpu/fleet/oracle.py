"""Sequential host oracle for joint fleet placement.

This is the *specification* of ``cycle_fleet_assign``: a plain-python
walk over candidates in admission order, evaluating each cluster lane
the way the sequential per-cluster MultiKueue dispatcher would (can the
lane fit the request on free capacity? failing that, can a prefix of
its lower-priority victims free enough?), then picking the cheapest
lane under the same dispatch-cost + spread + preemption penalty model
and the same tie-breaks (lowest lane index, first feasible flavor,
greedy eligible victim prefix).

The differential suite pins the device kernel bit-identical-in-outcome
to this function; the dispatcher also uses it directly as the contained
host fallback when the device path faults — so a fleet under fault
injection still produces *correct* placements, just slower.
"""

from __future__ import annotations

from typing import List, NamedTuple

import numpy as np

from kueue_tpu.fleet.encode import FleetSpec


class FleetPlan(NamedTuple):
    """Joint placement result on host (unpadded, numpy)."""

    admitted: np.ndarray   # [W] bool
    cluster: np.ndarray    # [W] int32, -1 when not admitted
    flavor: np.ndarray     # [W] int32, -1 when not admitted
    victims: np.ndarray    # [W, S] bool (chosen lane's victim axis)
    placed: np.ndarray     # [C] int32
    avail: np.ndarray      # [C, F, R] int64 post-placement


def fleet_oracle(spec: FleetSpec) -> FleetPlan:
    C, F, R = spec.avail.shape
    W = spec.req.shape[0]
    S = spec.vict_ok.shape[1]

    avail = spec.avail.astype(np.int64).copy()
    taken = np.zeros((C, S), dtype=bool)
    placed = np.zeros((C,), dtype=np.int64)

    admitted = np.zeros((W,), dtype=bool)
    cluster = np.full((W,), -1, dtype=np.int32)
    flavor_out = np.full((W,), -1, dtype=np.int32)
    victims = np.zeros((W, S), dtype=bool)

    for w in range(W):
        req = spec.req[w]
        best_cost = None
        best = None  # (c, flavor, sel_row, use_pre)
        for c in range(C):
            okf = spec.flavor_ok[c] & spec.elig[w]
            # Free-capacity path: first flavor that fits outright.
            free_flavor = -1
            for f in range(F):
                if okf[f] and np.all(avail[c, f] >= req):
                    free_flavor = f
                    break
            use_pre = False
            sel_row = np.zeros((S,), dtype=bool)
            flavor = free_flavor
            if free_flavor < 0:
                if not spec.preempt[w]:
                    continue
                # Preemption path: greedy eligible victim prefix, first
                # flavor whose cumulative freed capacity ever fits.
                elig_v = (
                    spec.vict_ok[c] & ~taken[c]
                    & (spec.vict_prio[c] < spec.prio[w])
                )
                freed = np.zeros((F, R), dtype=np.int64)
                pre_flavor = -1
                s_first = -1
                fits_at = np.full((F,), -1, dtype=np.int64)
                cum = np.zeros((S, F, R), dtype=np.int64)
                run = np.zeros((F, R), dtype=np.int64)
                for s in range(S):
                    if elig_v[s]:
                        run = run + spec.vict_free[c, s]
                    cum[s] = run
                    for f in range(F):
                        if fits_at[f] < 0 and okf[f] \
                                and np.all(avail[c, f] + run[f] >= req):
                            fits_at[f] = s
                for f in range(F):
                    if fits_at[f] >= 0:
                        pre_flavor = f
                        break
                if pre_flavor < 0:
                    continue
                flavor = pre_flavor
                s_first = int(fits_at[pre_flavor])
                sel_row = elig_v & (np.arange(S) <= s_first)
                freed = cum[s_first]
                use_pre = True
            cost = int(spec.cost[c, w]) + spec.spread_weight * int(placed[c])
            if use_pre:
                cost += spec.preempt_penalty
            if best_cost is None or cost < best_cost:
                best_cost = cost
                best = (c, flavor, sel_row, use_pre)
        if best is None:
            continue
        c, flavor, sel_row, use_pre = best
        admitted[w] = True
        cluster[w] = c
        flavor_out[w] = flavor
        victims[w] = sel_row
        if use_pre:
            for s in np.nonzero(sel_row)[0]:
                avail[c] += spec.vict_free[c, s]
            taken[c] |= sel_row
        avail[c, flavor] -= req
        placed[c] += 1

    return FleetPlan(
        admitted=admitted, cluster=cluster, flavor=flavor_out,
        victims=victims, placed=placed.astype(np.int32), avail=avail,
    )


def validate_plan(spec: FleetSpec, plan: FleetPlan) -> List[str]:
    """Bounds/consistency checks on a (possibly device-produced) plan.
    Returns problems; empty means the plan is safe to apply."""
    errs: List[str] = []
    C, F, _R = spec.avail.shape
    W = spec.req.shape[0]
    S = spec.vict_ok.shape[1]
    if plan.admitted.shape != (W,) or plan.cluster.shape != (W,):
        return ["plan shape mismatch"]
    if plan.victims.shape != (W, S):
        return ["victim plane shape mismatch"]
    for w in range(W):
        if not plan.admitted[w]:
            if plan.cluster[w] != -1 or plan.victims[w].any():
                errs.append(f"w={w}: placement data on unadmitted row")
            continue
        c = int(plan.cluster[w])
        f = int(plan.flavor[w])
        if not (0 <= c < C):
            errs.append(f"w={w}: cluster index {c} out of range")
            continue
        if not (0 <= f < F) or not spec.flavor_ok[c, f]:
            errs.append(f"w={w}: flavor {f} not offered by lane {c}")
        bad = plan.victims[w] & ~spec.vict_ok[c]
        if bad.any():
            errs.append(f"w={w}: selects padded/absent victims on lane {c}")
        if plan.victims[w].any() and not spec.preempt[w]:
            errs.append(f"w={w}: victims selected with preemption off")
    if plan.avail is not None and np.asarray(plan.avail).min() < 0:
        errs.append("negative post-placement capacity")
    return errs


def plans_equal(a: FleetPlan, b: FleetPlan) -> List[str]:
    """Differential comparison; returns mismatch descriptions."""
    errs: List[str] = []
    if not np.array_equal(a.admitted, b.admitted):
        errs.append(
            f"admitted differs: {np.nonzero(a.admitted != b.admitted)[0]}"
        )
    mask = a.admitted & b.admitted
    if not np.array_equal(a.cluster[mask], b.cluster[mask]):
        errs.append("cluster choice differs on jointly admitted rows")
    if not np.array_equal(a.flavor[mask], b.flavor[mask]):
        errs.append("flavor choice differs on jointly admitted rows")
    if not np.array_equal(a.victims[mask], b.victims[mask]):
        errs.append("victim sets differ on jointly admitted rows")
    return errs
