"""Fleet encoding: stack per-cluster capacity planes into ``[C, ...]``.

The joint multi-cluster placement kernel (``fleet/kernel.py``) consumes
one batched tensor set with a leading cluster axis: per-lane available
capacity ``[C, F, R]``, per-lane running-workload (victim) planes
``[C, S, F, R]``, and per-candidate request/eligibility planes
``[W, ...]`` shared across lanes. This module builds those planes from
live worker clusters — in-process :class:`kueue_tpu.manager.Manager`
instances or remote worker clients speaking the ``capacity`` op
(``remote/worker.py``) — mirroring how ``models/encode.py`` builds the
single-cluster cycle tensors.

Incremental lane reuse (the CycleArena idea applied per cluster lane):
:class:`FleetEncoder` caches each lane's capacity doc keyed by the
worker's cache generations and rebuilds only lanes whose worker state
changed since the previous solve; unchanged lanes are reused verbatim.

A lane the flat planes cannot represent (multiple ClusterQueues, a
cohort, or lending limits — shapes where admission depends on the quota
*tree*, not one per-CQ cell) raises :class:`FleetUnsupported`; the
dispatcher then leaves the whole fleet to the sequential per-workload
MultiKueue path rather than solve against a wrong model. An
*unreachable* lane (transport down) is merely skipped and counted —
placement proceeds across the reachable lanes.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from kueue_tpu.api.types import Workload
from kueue_tpu.core.workload_info import (
    has_quota_reservation,
    is_finished,
)
from kueue_tpu.models import buckets

#: Workload annotation naming the preferred worker cluster; other lanes
#: pay the dispatcher's affinity penalty for this candidate.
AFFINITY_ANNOTATION = "kueue.x-k8s.io/preferred-cluster"

#: Victim-axis cap for the device kernel: a lane with more running
#: workloads than this solves on the host oracle instead (the padded
#: cumulative-free planes grow with S; past this rung the scan's
#: compile/memory cost outweighs one joint dispatch).
FLEET_MAX_S = 512


class FleetUnsupported(Exception):
    """A reachable worker's quota shape cannot be modeled by flat
    per-lane planes (multi-CQ / cohort / lending); use the sequential
    dispatch path."""


class FleetSpec(NamedTuple):
    """Host-side (numpy, unpadded) joint-placement problem."""

    clusters: Tuple[str, ...]            # lane -> cluster name
    flavors: Tuple[str, ...]             # flavor universe
    resources: Tuple[str, ...]           # resource universe
    candidates: Tuple[str, ...]          # workload keys, admission order
    vict_keys: Tuple[Tuple[str, ...], ...]  # per lane, victim-axis order
    avail: np.ndarray                    # [C, F, R] int64
    flavor_ok: np.ndarray                # [C, F] bool
    vict_free: np.ndarray                # [C, S, F, R] int64
    vict_prio: np.ndarray                # [C, S] int64
    vict_ok: np.ndarray                  # [C, S] bool
    req: np.ndarray                      # [W, R] int64
    elig: np.ndarray                     # [W, F] bool
    prio: np.ndarray                     # [W] int64
    cost: np.ndarray                     # [C, W] int64
    preempt: np.ndarray                  # [W] bool
    spread_weight: int
    preempt_penalty: int
    s_bound: int                         # padded victim-axis length
    skipped: Tuple[str, ...]             # unreachable cluster names

    @property
    def c(self) -> int:
        return len(self.clusters)

    @property
    def w(self) -> int:
        return len(self.candidates)


class FleetArrays(NamedTuple):
    """Device-side padded planes consumed by ``cycle_fleet_assign``."""

    avail: object        # [Cp, F, R] i32
    flavor_ok: object    # [Cp, F] bool
    vict_free: object    # [Cp, Sp, F, R] i32
    vict_prio: object    # [Cp, Sp] i32
    vict_ok: object      # [Cp, Sp] bool
    req: object          # [Wp, R] i32
    elig: object         # [Wp, F] bool
    prio: object         # [Wp] i32
    cost: object         # [Cp, Wp] i32
    valid: object        # [Wp] bool
    preempt: object      # [Wp] bool
    spread_w: object     # scalar i32
    pre_penalty: object  # scalar i32


# --------------------------------------------------------------------------
# per-cluster capacity docs
# --------------------------------------------------------------------------

def local_capacity(mgr) -> dict:
    """Capacity doc for an in-process worker Manager — also the payload
    of the remote ``capacity`` op (JSON-serializable by construction)."""
    snap = mgr.cache.snapshot()
    cqs = list(snap.cluster_queues.values())
    has_cohort = any(cq.spec.cohort for cq in cqs)
    has_lend = False
    flavors: Dict[str, Dict[str, int]] = {}
    for cq in cqs:
        for rg in cq.spec.resource_groups:
            for fq in rg.flavors:
                row = flavors.setdefault(fq.name, {})
                for res, q in fq.resources.items():
                    if q.lending_limit is not None:
                        has_lend = True
                    avail = cq.available((fq.name, res))
                    row[res] = row.get(res, 0) + max(0, int(avail))
    running: List[dict] = []
    for wl in mgr.workloads.values():
        if not has_quota_reservation(wl) or is_finished(wl):
            continue
        adm = wl.status.admission
        if adm is None:
            continue
        usage: Dict[str, Dict[str, int]] = {}
        for psa in adm.pod_set_assignments:
            for res, amount in psa.resource_usage.items():
                fl = psa.flavors.get(res)
                if fl is None:
                    continue
                row = usage.setdefault(fl, {})
                row[res] = row.get(res, 0) + int(amount)
        running.append({
            "key": wl.key,
            "priority": int(wl.priority),
            "usage": usage,
        })
    return {
        "flavors": flavors,
        "cq_count": len(cqs),
        "has_cohort": bool(has_cohort),
        "has_lend": bool(has_lend),
        "running": running,
    }


def cluster_capacity(worker) -> Optional[dict]:
    """Capacity doc for one worker; ``None`` when unreachable."""
    try:
        if hasattr(worker, "cache"):
            return local_capacity(worker)
        cap = getattr(worker, "capacity", None)
        if cap is None:
            raise FleetUnsupported(
                f"worker {worker!r} exposes neither a cache nor a "
                "capacity op"
            )
        return cap()
    except ConnectionError:
        return None


# --------------------------------------------------------------------------
# encoding
# --------------------------------------------------------------------------

def _candidate_requests(wl: Workload) -> Dict[str, int]:
    req: Dict[str, int] = {}
    for ps in wl.pod_sets:
        for res, v in ps.requests.items():
            req[res] = req.get(res, 0) + int(v) * int(ps.count)
    return req


class FleetEncoder:
    """Builds :class:`FleetSpec` instances, reusing unchanged lanes.

    Lane cache key: in-process workers expose
    ``(cache.generation, cache.workload_generation)``; any worker
    without those (remote clients) is re-read every solve.
    """

    def __init__(self) -> None:
        self._lane_docs: Dict[str, Tuple[object, dict]] = {}
        self.lane_reuses = 0
        self.lane_rebuilds = 0

    def _lane_doc(self, name: str, worker) -> Optional[dict]:
        token = None
        cache = getattr(worker, "cache", None)
        if cache is not None:
            token = (cache.generation, cache.workload_generation)
        if token is not None:
            hit = self._lane_docs.get(name)
            if hit is not None and hit[0] == token:
                self.lane_reuses += 1
                return hit[1]
        doc = cluster_capacity(worker)
        if doc is not None and token is not None:
            self._lane_docs[name] = (token, doc)
            self.lane_rebuilds += 1
        return doc

    def encode(
        self,
        workers: Dict[str, object],
        candidates: List[Workload],
        *,
        preemption: bool = False,
        spread_weight: int = 1,
        preempt_penalty: int = 64,
        affinity_penalty: int = 8,
        dispatch_costs: Optional[Dict[str, int]] = None,
    ) -> FleetSpec:
        docs: Dict[str, dict] = {}
        skipped: List[str] = []
        for name in sorted(workers):
            doc = self._lane_doc(name, workers[name])
            if doc is None:
                skipped.append(name)
                continue
            if doc["cq_count"] != 1 or doc["has_cohort"] or doc["has_lend"]:
                raise FleetUnsupported(
                    f"cluster {name!r}: flat lane planes cannot model "
                    f"cq_count={doc['cq_count']} "
                    f"cohort={doc['has_cohort']} lend={doc['has_lend']}"
                )
            docs[name] = doc

        clusters = tuple(sorted(docs))
        flavor_set: set = set()
        resource_set: set = set()
        for doc in docs.values():
            for fname, row in doc["flavors"].items():
                flavor_set.add(fname)
                resource_set.update(row)
            for vic in doc["running"]:
                for fname, row in vic["usage"].items():
                    flavor_set.add(fname)
                    resource_set.update(row)
        for wl in candidates:
            resource_set.update(_candidate_requests(wl))
        flavors = tuple(sorted(flavor_set))
        resources = tuple(sorted(resource_set))
        fi = {f: i for i, f in enumerate(flavors)}
        ri = {r: i for i, r in enumerate(resources)}

        C, F, R = len(clusters), len(flavors), len(resources)
        # Admission order: priority desc, creation asc, key asc — the
        # same order the sequential dispatcher sees workloads in.
        cands = sorted(
            candidates,
            key=lambda w: (-w.priority, w.creation_time, w.key),
        )
        W = len(cands)

        avail = np.zeros((C, F, R), dtype=np.int64)
        flavor_ok = np.zeros((C, F), dtype=bool)
        vict_lists: List[List[dict]] = []
        s_real = 0
        for ci, name in enumerate(clusters):
            doc = docs[name]
            for fname, row in doc["flavors"].items():
                flavor_ok[ci, fi[fname]] = True
                for res, v in row.items():
                    avail[ci, fi[fname], ri[res]] = v
            vics = sorted(
                doc["running"], key=lambda v: (v["priority"], v["key"])
            ) if preemption else []
            vict_lists.append(vics)
            s_real = max(s_real, len(vics))
        # With preemption off the victim planes are dead weight — pin
        # S to 1 so the compiled shape never churns as the running set
        # grows (the zero-compile-after-prewarm pin depends on this).
        s_bound = buckets.pow2_bucket(s_real, floor=4) if preemption else 1

        vict_free = np.zeros((C, s_bound, F, R), dtype=np.int64)
        vict_prio = np.zeros((C, s_bound), dtype=np.int64)
        vict_ok = np.zeros((C, s_bound), dtype=bool)
        vict_keys: List[Tuple[str, ...]] = []
        for ci, vics in enumerate(vict_lists):
            keys = []
            for si, vic in enumerate(vics[:s_bound]):
                keys.append(vic["key"])
                vict_prio[ci, si] = vic["priority"]
                vict_ok[ci, si] = True
                for fname, row in vic["usage"].items():
                    for res, v in row.items():
                        vict_free[ci, si, fi[fname], ri[res]] = v
            vict_keys.append(tuple(keys))

        req = np.zeros((W, R), dtype=np.int64)
        elig = np.ones((W, F), dtype=bool)
        prio = np.zeros((W,), dtype=np.int64)
        cost = np.zeros((C, W), dtype=np.int64)
        preempt_row = np.full((W,), bool(preemption))
        base_costs = dispatch_costs or {}
        for wi, wl in enumerate(cands):
            for res, v in _candidate_requests(wl).items():
                req[wi, ri[res]] = v
            prio[wi] = wl.priority
            preferred = (wl.annotations or {}).get(AFFINITY_ANNOTATION)
            for ci, name in enumerate(clusters):
                cost[ci, wi] = int(base_costs.get(name, 0))
                if preferred is not None and preferred != name:
                    cost[ci, wi] += int(affinity_penalty)

        return FleetSpec(
            clusters=clusters, flavors=flavors, resources=resources,
            candidates=tuple(w.key for w in cands),
            vict_keys=tuple(vict_keys),
            avail=avail, flavor_ok=flavor_ok, vict_free=vict_free,
            vict_prio=vict_prio, vict_ok=vict_ok, req=req, elig=elig,
            prio=prio, cost=cost, preempt=preempt_row,
            spread_weight=int(spread_weight),
            preempt_penalty=int(preempt_penalty),
            s_bound=s_bound, skipped=tuple(skipped),
        )


def to_device(spec: FleetSpec, w_bucket: Optional[int] = None
              ) -> FleetArrays:
    """Pad the host spec onto the device plane shapes: cluster lanes to
    the next power of two (padded lanes carry no flavors, so they can
    never win), candidates to the W bucket ladder, victims already at
    ``s_bound``."""
    import jax.numpy as jnp

    C, F, R = spec.avail.shape
    W = spec.req.shape[0]
    Cp = buckets.pow2_bucket(max(1, C), floor=2)
    Wp = w_bucket if w_bucket is not None else buckets.bucket_for(W)
    Sp = spec.s_bound

    def pad(a: np.ndarray, shape: Tuple[int, ...]) -> np.ndarray:
        out = np.zeros(shape, dtype=a.dtype)
        out[tuple(slice(0, n) for n in a.shape)] = a
        return out

    i32 = np.int32
    return FleetArrays(
        avail=jnp.asarray(pad(spec.avail, (Cp, F, R)).astype(i32)),
        flavor_ok=jnp.asarray(pad(spec.flavor_ok, (Cp, F))),
        vict_free=jnp.asarray(
            pad(spec.vict_free, (Cp, Sp, F, R)).astype(i32)
        ),
        vict_prio=jnp.asarray(pad(spec.vict_prio, (Cp, Sp)).astype(i32)),
        vict_ok=jnp.asarray(pad(spec.vict_ok, (Cp, Sp))),
        req=jnp.asarray(pad(spec.req, (Wp, R)).astype(i32)),
        elig=jnp.asarray(pad(spec.elig, (Wp, F))),
        prio=jnp.asarray(pad(spec.prio, (Wp,)).astype(i32)),
        cost=jnp.asarray(pad(spec.cost, (Cp, Wp)).astype(i32)),
        valid=jnp.asarray(
            pad(np.ones((W,), dtype=bool), (Wp,))
        ),
        preempt=jnp.asarray(pad(spec.preempt, (Wp,))),
        spread_w=jnp.int32(spec.spread_weight),
        pre_penalty=jnp.int32(spec.preempt_penalty),
    )
