"""Metrics registry.

Behavioral surface: reference pkg/metrics/metrics.go — the ~50 Prometheus
series become counters/gauges/histograms in a dependency-free registry with
a Prometheus text exposition dump (so operators can scrape or log it).
"""

from __future__ import annotations

import bisect
import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

_DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 300.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _lk(labels: Optional[Dict[str, str]]) -> LabelKey:
    return tuple(sorted((labels or {}).items()))


class Histogram:
    def __init__(self, buckets=_DEFAULT_BUCKETS) -> None:
        self.buckets = list(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float) -> None:
        self.counts[bisect.bisect_left(self.buckets, v)] += 1
        self.total += v
        self.n += 1

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (the Prometheus histogram_quantile
        estimator): locate the winning bucket, then interpolate linearly
        between its bounds instead of returning the coarse upper bound."""
        if self.n == 0:
            return 0.0
        target = q * self.n
        acc = 0
        for i, c in enumerate(self.counts):
            prev_acc = acc
            acc += c
            if acc >= target:
                if i >= len(self.buckets):
                    return float("inf")
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i]
                if c == 0:
                    return hi
                return lo + (hi - lo) * (target - prev_acc) / c
        return float("inf")


class Metrics:
    """Counters, gauges and histograms with labels. Series names follow the
    reference (pkg/metrics/metrics.go:354-966) so dashboards carry over."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.counters: Dict[str, Dict[LabelKey, float]] = defaultdict(
            lambda: defaultdict(float)
        )
        self.gauges: Dict[str, Dict[LabelKey, float]] = defaultdict(
            lambda: defaultdict(float)
        )
        self.histograms: Dict[str, Dict[LabelKey, Histogram]] = defaultdict(
            dict
        )

    def inc(self, name: str, labels: Optional[Dict[str, str]] = None,
            value: float = 1.0) -> None:
        with self._lock:
            self.counters[name][_lk(labels)] += value

    def set_gauge(self, name: str, value: float,
                  labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self.gauges[name][_lk(labels)] = value

    def observe(self, name: str, value: float,
                labels: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            h = self.histograms[name].get(_lk(labels))
            if h is None:
                h = self.histograms[name][_lk(labels)] = Histogram()
            h.observe(value)

    # -- locked aggregate readers ---------------------------------------
    #
    # Concurrent readers (SLO engine, service-loop watermarks, /metrics
    # scrapes) must never iterate live histogram/counter cells while a
    # writer thread mutates them: Histogram.observe updates counts/n/total
    # non-atomically, so an unlocked read can see n != sum(counts) (a torn
    # read). These helpers snapshot under the registry lock.

    def counter_total(self, name: str) -> float:
        """Sum of one counter series across all label children."""
        with self._lock:
            return float(sum(self.counters.get(name, {}).values()))

    def histogram_totals(self, name: str):
        """Aggregate one histogram series across label children into
        ``(buckets, counts, n)``, read atomically. Children share the
        default bucket layout per series; a child with a different layout
        is skipped (mixed layouts fall back to the first child's)."""
        with self._lock:
            children = self.histograms.get(name, {})
            buckets: Optional[Tuple[float, ...]] = None
            counts: List[int] = []
            n = 0
            for h in children.values():
                if buckets is None:
                    buckets = tuple(h.buckets)
                    counts = [0] * (len(h.buckets) + 1)
                if tuple(h.buckets) != buckets:
                    continue
                for i, c in enumerate(h.counts):
                    counts[i] += c
                n += h.n
            return buckets or (), counts, n

    def histogram_quantile(self, name: str, q: float) -> Optional[float]:
        """Interpolated quantile over one series aggregated across label
        children; None when the series has no observations."""
        buckets, counts, n = self.histogram_totals(name)
        if n == 0 or not buckets:
            return None
        h = Histogram(buckets=buckets)
        h.counts = list(counts)
        h.n = n
        return h.quantile(q)

    def get(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        with self._lock:
            if name in self.counters:
                if name in self.gauges:
                    raise ValueError(
                        f"metric {name!r} exists as both counter and gauge;"
                        " read it via .counters / .gauges explicitly"
                    )
                return self.counters[name].get(_lk(labels), 0.0)
            return self.gauges.get(name, {}).get(_lk(labels), 0.0)

    def expose(self) -> str:
        """Prometheus text exposition format (serve with Content-Type
        ``text/plain; version=0.0.4``). HELP text comes from the frozen
        names allowlist (metrics/names.py HELP_TEXT)."""
        from kueue_tpu.metrics.names import help_for

        out: List[str] = []
        with self._lock:
            for name, series in sorted(self.counters.items()):
                out.append(f"# HELP kueue_{name} {help_for(name)}")
                out.append(f"# TYPE kueue_{name} counter")
                for lk, v in sorted(series.items()):
                    out.append(f"kueue_{name}{_fmt(lk)} {v}")
            for name, series in sorted(self.gauges.items()):
                out.append(f"# HELP kueue_{name} {help_for(name)}")
                out.append(f"# TYPE kueue_{name} gauge")
                for lk, v in sorted(series.items()):
                    out.append(f"kueue_{name}{_fmt(lk)} {v}")
            for name, series in sorted(self.histograms.items()):
                out.append(f"# HELP kueue_{name} {help_for(name)}")
                out.append(f"# TYPE kueue_{name} histogram")
                for lk, h in sorted(series.items()):
                    acc = 0
                    for b, c in zip(h.buckets, h.counts):
                        acc += c
                        out.append(
                            f'kueue_{name}_bucket{_fmt(lk, ("le", str(b)))}'
                            f" {acc}"
                        )
                    out.append(
                        f'kueue_{name}_bucket{_fmt(lk, ("le", "+Inf"))} {h.n}'
                    )
                    out.append(f"kueue_{name}_sum{_fmt(lk)} {h.total}")
                    out.append(f"kueue_{name}_count{_fmt(lk)} {h.n}")
        return "\n".join(out) + "\n"

    def to_doc(self) -> dict:
        """JSON-ready snapshot of every series — the machine-readable
        sibling of :meth:`expose` (``/metrics.json``). Histograms export
        count/sum plus interpolated p50/p99."""
        def _labels(lk: LabelKey) -> Dict[str, str]:
            return dict(lk)

        def _q(h: Histogram, q: float):
            v = h.quantile(q)
            # +Inf (observation beyond the last bucket bound) is not
            # valid strict JSON; clients read null as "off the scale".
            return v if v == v and v not in (float("inf"),) else None

        with self._lock:
            return {
                "counters": {
                    name: [
                        {"labels": _labels(lk), "value": v}
                        for lk, v in sorted(series.items())
                    ]
                    for name, series in sorted(self.counters.items())
                },
                "gauges": {
                    name: [
                        {"labels": _labels(lk), "value": v}
                        for lk, v in sorted(series.items())
                    ]
                    for name, series in sorted(self.gauges.items())
                },
                "histograms": {
                    name: [
                        {
                            "labels": _labels(lk),
                            "count": h.n,
                            "sum": h.total,
                            "p50": _q(h, 0.50),
                            "p99": _q(h, 0.99),
                        }
                        for lk, h in sorted(series.items())
                    ]
                    for name, series in sorted(self.histograms.items())
                },
            }


def _escape_label_value(v: str) -> str:
    """Prometheus text exposition format: label values escape backslash,
    double-quote and line feed (in that order, so the escapes themselves
    survive)."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt(lk: LabelKey, extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(lk)
    if extra:
        items.append(extra)
    if not items:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in items)
    return "{" + inner + "}"
