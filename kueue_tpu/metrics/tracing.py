"""Admission-cycle tracing: lightweight spans + hot-loop counters.

Behavioral surface: the reference treats observability as its own layer
(pkg/metrics with ~50 Prometheus series, structured per-phase scheduler
logs, and the visibility API). This module is the measurement substrate
for the standalone stack: contextvar-scoped nestable spans around the
admission hot loop, a ring-buffered recorder exporting Chrome
``trace_event`` JSON (loadable in Perfetto / chrome://tracing), and
per-span-name duration histograms plus solver counters forwarded into a
:class:`kueue_tpu.metrics.registry.Metrics` sink.

Zero-cost when disabled: ``span()`` returns a shared no-op context
manager and every counter helper returns immediately, so the scheduler
microbench with tracing off stays within noise of the uninstrumented
code. Enable per-run:

    from kueue_tpu.metrics import tracing
    tracing.enable(mgr.metrics)
    mgr.schedule_all()
    json.dump(tracing.export_chrome_trace(), open("trace.json", "w"))

Trace-context propagation: a root span mints a ``trace_id``; the remote
clients inject it into the wire request and ``remote.worker.dispatch``
re-enters it via :func:`trace_context`, so worker-side spans land in the
same logical trace as the caller's.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from kueue_tpu.metrics.registry import Metrics

# Module-level fast flag: hot loops read this attribute directly. Mutate
# only through enable()/disable().
ENABLED = False

_DEFAULT_BUFFER_LEN = 65536

# Current innermost span and current trace id. contextvars give each
# thread (and each task) its own value, so nesting is thread-safe without
# locking the hot path.
_span_var: contextvars.ContextVar[Optional["_Span"]] = contextvars.ContextVar(
    "kueue_tpu_current_span", default=None
)
_trace_var: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "kueue_tpu_trace_id", default=None
)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    """Ring-buffered span recorder with an optional Metrics sink."""

    def __init__(self, buffer_len: int = _DEFAULT_BUFFER_LEN) -> None:
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=buffer_len)
        self.metrics: Optional[Metrics] = None
        # Epoch for Chrome-trace timestamps (perf_counter is monotonic but
        # has an arbitrary zero; export is relative to tracer creation).
        self.epoch = time.perf_counter()
        self.dropped = 0

    # -- recording ---------------------------------------------------------

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(rec)

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    # -- export ------------------------------------------------------------

    def export_chrome_trace(self) -> Dict[str, Any]:
        """Chrome ``trace_event`` JSON (complete "X" events, µs units)."""
        events = []
        pid = os.getpid()
        for rec in self.spans():
            events.append({
                "name": rec["name"],
                "cat": "kueue_tpu",
                "ph": "X",
                "ts": round(rec["ts"] * 1e6, 3),
                "dur": round(rec["dur"] * 1e6, 3),
                "pid": pid,
                "tid": rec["tid"],
                "args": {
                    "trace_id": rec["trace_id"],
                    "parent": rec["parent"],
                    **rec["args"],
                },
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def phase_breakdown(self) -> Dict[str, float]:
        """Total seconds spent per span name (self-inclusive)."""
        out: Dict[str, float] = {}
        for rec in self.spans():
            out[rec["name"]] = out.get(rec["name"], 0.0) + rec["dur"]
        return out


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def enabled() -> bool:
    return ENABLED


def enable(metrics: Optional[Metrics] = None,
           buffer_len: Optional[int] = None) -> Tracer:
    """Turn tracing on. ``metrics`` becomes the sink for span histograms
    and hot-loop counters (pass a Manager's registry so the series show
    up on its ``/metrics`` exposition); omitted, the tracer keeps its own
    registry so counters are never silently dropped."""
    global ENABLED, _tracer
    if buffer_len is not None and buffer_len != _tracer._buf.maxlen:
        _tracer = Tracer(buffer_len)
    _tracer.metrics = metrics if metrics is not None else (
        _tracer.metrics or Metrics()
    )
    ENABLED = True
    return _tracer


def disable() -> None:
    global ENABLED
    ENABLED = False


# ----------------------------------------------------------------------
# spans
# ----------------------------------------------------------------------


class _NoopSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set_arg(self, key: str, value: Any) -> None:
        return None


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "args", "start", "_span_token", "_trace_token",
                 "parent", "trace_id")

    def __init__(self, name: str, args: Dict[str, Any]) -> None:
        self.name = name
        self.args = args

    def set_arg(self, key: str, value: Any) -> None:
        self.args[key] = value

    def __enter__(self) -> "_Span":
        parent = _span_var.get()
        self.parent = parent.name if parent is not None else None
        self._span_token = _span_var.set(self)
        tid = _trace_var.get()
        if tid is None:
            tid = new_trace_id()
            self._trace_token = _trace_var.set(tid)
        else:
            self._trace_token = None
        self.trace_id = tid
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        end = time.perf_counter()
        _span_var.reset(self._span_token)
        if self._trace_token is not None:
            _trace_var.reset(self._trace_token)
        tr = _tracer
        tr.record({
            "name": self.name,
            "ts": self.start - tr.epoch,
            "dur": end - self.start,
            "tid": threading.get_ident(),
            "trace_id": self.trace_id,
            "parent": self.parent,
            "args": self.args,
        })
        m = tr.metrics
        if m is not None:
            m.observe("trace_span_duration_seconds", end - self.start,
                      {"span": self.name})


def span(name: str, **args: Any):
    """Context manager for one named span. No-op unless tracing is on."""
    if not ENABLED:
        return _NOOP
    return _Span(name, args)


def current_trace_id() -> Optional[str]:
    return _trace_var.get()


class _TraceContext:
    """Re-enter a caller's trace id (cross-boundary extraction side)."""

    __slots__ = ("trace_id", "_token")

    def __init__(self, trace_id: Optional[str]) -> None:
        self.trace_id = trace_id

    def __enter__(self) -> "_TraceContext":
        self._token = _trace_var.set(self.trace_id)
        return self

    def __exit__(self, *exc) -> None:
        _trace_var.reset(self._token)


def trace_context(trace_id: Optional[str]) -> _TraceContext:
    return _TraceContext(trace_id)


# ----------------------------------------------------------------------
# hot-loop counter helpers (forward to the sink only when enabled)
# ----------------------------------------------------------------------


def inc(name: str, labels: Optional[Dict[str, str]] = None,
        value: float = 1.0) -> None:
    if not ENABLED:
        return
    m = _tracer.metrics
    if m is not None:
        m.inc(name, labels, value)


def observe(name: str, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
    if not ENABLED:
        return
    m = _tracer.metrics
    if m is not None:
        m.observe(name, value, labels)


def set_gauge(name: str, value: float,
              labels: Optional[Dict[str, str]] = None) -> None:
    if not ENABLED:
        return
    m = _tracer.metrics
    if m is not None:
        m.set_gauge(name, value, labels)


def export_chrome_trace() -> Dict[str, Any]:
    return _tracer.export_chrome_trace()


def phase_breakdown() -> Dict[str, float]:
    return _tracer.phase_breakdown()


# ----------------------------------------------------------------------
# JAX solver observability
# ----------------------------------------------------------------------


def instrument_jit(fn, kernel: str):
    """Wrap a jitted callable with compile-cache hit/miss counters and
    device-vs-trace wall time histograms.

    A call that grows the jit cache paid tracing+compilation
    (``solver_trace_seconds``); a steady-state call is dispatch+device
    time (``solver_device_seconds``; dispatch may be async, so this is a
    lower bound unless the caller blocks on the result). Disabled tracing
    adds a single flag check per call."""

    def wrapped(*args, **kwargs):
        if not ENABLED:
            return fn(*args, **kwargs)
        size_fn = getattr(fn, "_cache_size", None)
        before = size_fn() if callable(size_fn) else -1
        t0 = time.perf_counter()
        with span("solver/" + kernel):
            out = fn(*args, **kwargs)
        wall = time.perf_counter() - t0
        after = size_fn() if callable(size_fn) else -1
        miss = before >= 0 and after > before
        inc("solver_jit_cache_total",
            {"kernel": kernel, "event": "miss" if miss else "hit"})
        observe("solver_trace_seconds" if miss else "solver_device_seconds",
                wall, {"kernel": kernel})
        return out

    wrapped.__wrapped__ = fn
    wrapped.__name__ = getattr(fn, "__name__", kernel)
    return wrapped
