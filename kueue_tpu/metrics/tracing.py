"""Admission-cycle tracing: lightweight spans + hot-loop counters.

Behavioral surface: the reference treats observability as its own layer
(pkg/metrics with ~50 Prometheus series, structured per-phase scheduler
logs, and the visibility API). This module is the measurement substrate
for the standalone stack: contextvar-scoped nestable spans around the
admission hot loop, a ring-buffered recorder exporting Chrome
``trace_event`` JSON (loadable in Perfetto / chrome://tracing), and
per-span-name duration histograms plus solver counters forwarded into a
:class:`kueue_tpu.metrics.registry.Metrics` sink.

Zero-cost when disabled: ``span()`` returns a shared no-op context
manager and every counter helper returns immediately, so the scheduler
microbench with tracing off stays within noise of the uninstrumented
code. Enable per-run:

    from kueue_tpu.metrics import tracing
    tracing.enable(mgr.metrics)
    mgr.schedule_all()
    json.dump(tracing.export_chrome_trace(), open("trace.json", "w"))

Trace-context propagation: a root span mints a ``trace_id``; the remote
clients inject it into the wire request and ``remote.worker.dispatch``
re-enters it via :func:`trace_context`, so worker-side spans land in the
same logical trace as the caller's.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from kueue_tpu.metrics.registry import Metrics

# Module-level fast flag: hot loops read this attribute directly. Mutate
# only through enable()/disable().
ENABLED = False

_DEFAULT_BUFFER_LEN = 65536

# Current innermost span and current trace id. contextvars give each
# thread (and each task) its own value, so nesting is thread-safe without
# locking the hot path.
_span_var: contextvars.ContextVar[Optional["_Span"]] = contextvars.ContextVar(
    "kueue_tpu_current_span", default=None
)
_trace_var: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "kueue_tpu_trace_id", default=None
)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class Tracer:
    """Ring-buffered span recorder with an optional Metrics sink."""

    def __init__(self, buffer_len: int = _DEFAULT_BUFFER_LEN) -> None:
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=buffer_len)
        self.metrics: Optional[Metrics] = None
        # Epoch for Chrome-trace timestamps (perf_counter is monotonic but
        # has an arbitrary zero; export is relative to tracer creation).
        self.epoch = time.perf_counter()
        self.dropped = 0

    # -- recording ---------------------------------------------------------

    def record(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(rec)

    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    # -- export ------------------------------------------------------------

    def export_chrome_trace(self) -> Dict[str, Any]:
        """Chrome ``trace_event`` JSON (complete "X" events, µs units).

        Spans ingested from remote workers (``ingest_remote_spans``)
        carry a ``worker`` key and render as their own process lane: each
        distinct worker gets a synthetic pid plus a ``process_name``
        metadata event, so the merged client+worker timeline reads as one
        trace with per-worker swimlanes."""
        events: List[Dict[str, Any]] = []
        pid = os.getpid()
        worker_pids: Dict[str, int] = {}
        for rec in self.spans():
            worker = rec.get("worker")
            if worker is None:
                ev_pid = pid
            else:
                ev_pid = worker_pids.get(worker)
                if ev_pid is None:
                    # Deterministic synthetic lane ids, far from real pids.
                    ev_pid = 1_000_000 + len(worker_pids)
                    worker_pids[worker] = ev_pid
            args = {
                "trace_id": rec["trace_id"],
                "parent": rec["parent"],
                **rec["args"],
            }
            if worker is not None:
                args["worker"] = worker
                if "clock_offset_s" in rec:
                    args["clock_offset_s"] = rec["clock_offset_s"]
            events.append({
                "name": rec["name"],
                "cat": "kueue_tpu",
                "ph": "X",
                "ts": round(rec["ts"] * 1e6, 3),
                "dur": round(rec["dur"] * 1e6, 3),
                "pid": ev_pid,
                "tid": rec["tid"],
                "args": args,
            })
        meta = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": "client"},
        }]
        for worker, wpid in sorted(worker_pids.items(), key=lambda kv: kv[1]):
            meta.append({
                "name": "process_name", "ph": "M", "pid": wpid, "tid": 0,
                "args": {"name": f"worker:{worker}"},
            })
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def spans_for_trace(self, trace_id: str,
                        limit: int = 200) -> List[Dict[str, Any]]:
        """The newest ``limit`` spans recorded under ``trace_id``,
        oldest first — the worker-side fan-in query."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            for rec in reversed(self._buf):
                if rec.get("trace_id") == trace_id:
                    out.append(rec)
                    if len(out) >= limit:
                        break
        out.reverse()
        return out

    def phase_breakdown(self) -> Dict[str, float]:
        """Total seconds spent per span name (self-inclusive)."""
        out: Dict[str, float] = {}
        for rec in self.spans():
            out[rec["name"]] = out.get(rec["name"], 0.0) + rec["dur"]
        return out


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def enabled() -> bool:
    return ENABLED


def enable(metrics: Optional[Metrics] = None,
           buffer_len: Optional[int] = None) -> Tracer:
    """Turn tracing on. ``metrics`` becomes the sink for span histograms
    and hot-loop counters (pass a Manager's registry so the series show
    up on its ``/metrics`` exposition); omitted, the tracer keeps its own
    registry so counters are never silently dropped."""
    global ENABLED, _tracer
    if buffer_len is not None and buffer_len != _tracer._buf.maxlen:
        _tracer = Tracer(buffer_len)
    _tracer.metrics = metrics if metrics is not None else (
        _tracer.metrics or Metrics()
    )
    ENABLED = True
    return _tracer


def disable() -> None:
    global ENABLED
    ENABLED = False


# ----------------------------------------------------------------------
# spans
# ----------------------------------------------------------------------


class _NoopSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        return None

    def set_arg(self, key: str, value: Any) -> None:
        return None


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "args", "start", "_span_token", "_trace_token",
                 "parent", "trace_id")

    def __init__(self, name: str, args: Dict[str, Any]) -> None:
        self.name = name
        self.args = args

    def set_arg(self, key: str, value: Any) -> None:
        self.args[key] = value

    def __enter__(self) -> "_Span":
        parent = _span_var.get()
        self.parent = parent.name if parent is not None else None
        self._span_token = _span_var.set(self)
        tid = _trace_var.get()
        if tid is None:
            tid = new_trace_id()
            self._trace_token = _trace_var.set(tid)
        else:
            self._trace_token = None
        self.trace_id = tid
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        end = time.perf_counter()
        _span_var.reset(self._span_token)
        if self._trace_token is not None:
            _trace_var.reset(self._trace_token)
        tr = _tracer
        tr.record({
            "name": self.name,
            "ts": self.start - tr.epoch,
            "dur": end - self.start,
            "tid": threading.get_ident(),
            "trace_id": self.trace_id,
            "parent": self.parent,
            "args": self.args,
        })
        m = tr.metrics
        if m is not None:
            m.observe("trace_span_duration_seconds", end - self.start,
                      {"span": self.name})


def span(name: str, **args: Any):
    """Context manager for one named span. No-op unless tracing is on."""
    if not ENABLED:
        return _NOOP
    return _Span(name, args)


def record_complete_span(name: str, duration_s: float,
                         **args: Any) -> None:
    """Record a span retroactively: an interval of ``duration_s`` that
    ends *now*. For latencies measured outside a ``with span()`` block —
    e.g. the service loop learns a workload's submit→admit wait only at
    admission time, long after the interval started. No-op unless
    tracing is on; renders on the Chrome-trace timeline like any other
    complete event."""
    if not ENABLED:
        return
    tr = _tracer
    end = time.perf_counter() - tr.epoch
    tr.record({
        "name": name,
        "ts": end - duration_s,
        "dur": duration_s,
        "tid": threading.get_ident(),
        "trace_id": _trace_var.get(),
        "parent": None,
        "args": args,
    })


def current_trace_id() -> Optional[str]:
    return _trace_var.get()


class _TraceContext:
    """Re-enter a caller's trace id (cross-boundary extraction side)."""

    __slots__ = ("trace_id", "_token")

    def __init__(self, trace_id: Optional[str]) -> None:
        self.trace_id = trace_id

    def __enter__(self) -> "_TraceContext":
        self._token = _trace_var.set(self.trace_id)
        return self

    def __exit__(self, *exc) -> None:
        _trace_var.reset(self._token)


def trace_context(trace_id: Optional[str]) -> _TraceContext:
    return _TraceContext(trace_id)


# ----------------------------------------------------------------------
# hot-loop counter helpers (forward to the sink only when enabled)
# ----------------------------------------------------------------------


def inc(name: str, labels: Optional[Dict[str, str]] = None,
        value: float = 1.0) -> None:
    if not ENABLED:
        return
    m = _tracer.metrics
    if m is not None:
        m.inc(name, labels, value)


def observe(name: str, value: float,
            labels: Optional[Dict[str, str]] = None) -> None:
    if not ENABLED:
        return
    m = _tracer.metrics
    if m is not None:
        m.observe(name, value, labels)


def set_gauge(name: str, value: float,
              labels: Optional[Dict[str, str]] = None) -> None:
    if not ENABLED:
        return
    m = _tracer.metrics
    if m is not None:
        m.set_gauge(name, value, labels)


def export_chrome_trace() -> Dict[str, Any]:
    return _tracer.export_chrome_trace()


def phase_breakdown() -> Dict[str, float]:
    return _tracer.phase_breakdown()


# ----------------------------------------------------------------------
# remote trace fan-in (remote/worker.py response side, remote clients
# ingest side) — workers already re-enter the caller's trace id; these
# helpers ship the finished worker spans back in the RPC response so the
# client's Chrome-trace export renders one merged timeline.
# ----------------------------------------------------------------------

#: Hard cap on spans shipped per RPC response. The fan-in is best-effort
#: observability riding on the op response — it must stay far below any
#: transport deadline/payload concern, so only the newest spans of the
#: trace travel and everything beyond the cap is dropped silently.
MAX_REMOTE_SPANS = 200

#: Per-span wire fields. args are stringified and truncated so a caller
#: storing a large object in span args cannot balloon the response.
_REMOTE_ARG_MAX = 256


def attach_remote_spans(resp: Dict[str, Any], trace_id: Optional[str],
                        limit: int = MAX_REMOTE_SPANS) -> None:
    """Worker side: attach this trace's finished spans plus a clock
    sample to an RPC response (in place, best-effort). No-op when tracing
    is off or the caller sent no trace id."""
    if not ENABLED or not trace_id:
        return
    tr = _tracer
    spans = []
    for rec in tr.spans_for_trace(trace_id, limit=limit):
        args = {}
        for k, v in (rec.get("args") or {}).items():
            if isinstance(v, (int, float, bool)) or v is None:
                args[k] = v
            else:
                args[k] = str(v)[:_REMOTE_ARG_MAX]
        spans.append({
            "name": rec["name"],
            "ts": rec["ts"],
            "dur": rec["dur"],
            "tid": rec["tid"],
            "parent": rec.get("parent"),
            "args": args,
        })
    resp["spans"] = spans
    # Worker clock sample on the same relative clock as the span ts
    # values — the client estimates the epoch offset from it.
    resp["worker_now"] = time.perf_counter() - tr.epoch


def ingest_remote_spans(resp: Dict[str, Any], worker: str,
                        t_send: float, t_recv: float,
                        trace_id: Optional[str] = None) -> int:
    """Client side: pop the worker spans off an RPC response and record
    them into the local tracer on the client's clock.

    Clock-skew estimate (NTP-style midpoint): the worker sampled its
    clock (``worker_now``) between the client's ``t_send`` and
    ``t_recv`` (client-epoch-relative perf_counter values); assuming
    symmetric transport latency the worker sample corresponds to the
    midpoint, so ``offset = (t_send + t_recv)/2 - worker_now`` maps
    worker timestamps onto the client timeline. The offset is annotated
    on every ingested span as ``clock_offset_s``. Returns the number of
    spans ingested."""
    spans = resp.pop("spans", None)
    worker_now = resp.pop("worker_now", None)
    if not ENABLED or not spans or worker_now is None:
        return 0
    offset = (t_send + t_recv) / 2.0 - float(worker_now)
    tr = _tracer
    n = 0
    for s in spans[:MAX_REMOTE_SPANS]:
        try:
            tr.record({
                "name": s["name"],
                "ts": float(s["ts"]) + offset,
                "dur": float(s["dur"]),
                "tid": s.get("tid", 0),
                "trace_id": trace_id,
                "parent": s.get("parent"),
                "args": dict(s.get("args") or {}),
                "worker": worker,
                "clock_offset_s": round(offset, 9),
            })
            n += 1
        except (KeyError, TypeError, ValueError):
            continue  # best-effort: a malformed span is dropped, not fatal
    if n:
        inc("remote_spans_ingested_total", {"worker": worker}, value=n)
    return n


# ----------------------------------------------------------------------
# JAX solver observability
# ----------------------------------------------------------------------


def instrument_jit(fn, kernel: str):
    """Wrap a jitted callable with compile-cache hit/miss counters and
    device-vs-trace wall time histograms.

    A call that grows the jit cache paid tracing+compilation
    (``solver_trace_seconds``); a steady-state call is dispatch+device
    time (``solver_device_seconds``; dispatch may be async, so this is a
    lower bound unless the caller blocks on the result). Disabled tracing
    adds a single flag check per call."""

    def wrapped(*args, **kwargs):
        if not ENABLED:
            return fn(*args, **kwargs)
        size_fn = getattr(fn, "_cache_size", None)
        before = size_fn() if callable(size_fn) else -1
        t0 = time.perf_counter()
        with span("solver/" + kernel):
            out = fn(*args, **kwargs)
        wall = time.perf_counter() - t0
        after = size_fn() if callable(size_fn) else -1
        miss = before >= 0 and after > before
        inc("solver_jit_cache_total",
            {"kernel": kernel, "event": "miss" if miss else "hit"})
        observe("solver_trace_seconds" if miss else "solver_device_seconds",
                wall, {"kernel": kernel})
        return out

    wrapped.__wrapped__ = fn
    wrapped.__name__ = getattr(fn, "__name__", kernel)
    return wrapped
