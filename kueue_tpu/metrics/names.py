"""Frozen allowlist of every metric series name this codebase may emit.

``tools/check_metrics_names.py`` statically verifies that each
``metrics.inc / observe / set_gauge`` (and ``tracing.inc / observe /
set_gauge``) call site uses a literal name from this set — a typo'd name
would otherwise silently fork a series and dashboards would read zeros
forever. Adding a metric means adding it here AND to
``docs/observability.md``.

Names are exposed with the ``kueue_`` prefix by
:meth:`kueue_tpu.metrics.registry.Metrics.expose`; entries here are the
unprefixed registry names. Reference counterparts (pkg/metrics/metrics.go)
are listed in docs/observability.md.
"""

from __future__ import annotations

# Lifecycle / quota series carried over from the reference pkg/metrics.
REFERENCE_SERIES = frozenset({
    "admission_attempt_duration_seconds",
    "admission_attempts_total",
    "admission_checks_wait_time_seconds",
    "admission_cycle_preemption_skips",
    "admission_wait_time_seconds",
    "admitted_active_workloads",
    "admitted_workloads_total",
    "build_info",
    "cluster_queue_borrowing_limit",
    "cluster_queue_info",
    "cluster_queue_lending_limit",
    "cluster_queue_nominal_quota",
    "cluster_queue_resource_usage",
    "cluster_queue_status",
    "cluster_queue_weighted_share",
    "cohort_info",
    "cohort_subtree_admitted_active_workloads",
    "cohort_subtree_admitted_workloads_total",
    "cohort_subtree_quota",
    "cohort_subtree_resource_reservations",
    "cohort_weighted_share",
    "evicted_workloads_once_total",
    "evicted_workloads_total",
    "finished_workloads_total",
    "local_queue_admitted_workloads",
    "local_queue_pending_workloads",
    "multikueue_dispatches_total",
    "pending_workloads",
    "pods_ready_to_evicted_time_seconds",
    "preempted_workloads_total",
    "provisioning_requests_failed_total",
    "provisioning_requests_provisioned_total",
    "quota_reserved_wait_time_seconds",
    "quota_reserved_workloads_total",
    "reclaimed_pods_total",
    "reserving_active_workloads",
    "scheduler_nomination_duration_seconds",
    "scheduler_snapshot_duration_seconds",
    "second_pass_assignments_total",
    "tas_node_replacement_failures_total",
    "tas_node_replacements_total",
    "workloads_created_total",
    "workloads_finished_total",
})

# Tracing / hot-loop series introduced by metrics/tracing.py and the
# admission-path instrumentation (spans, queue latencies, JAX solver
# observability, remote-boundary propagation).
TRACING_SERIES = frozenset({
    "trace_span_duration_seconds",
    "scheduler_admission_cycle_duration_seconds",
    "scheduler_admission_cycle_stage_seconds",
    "scheduler_admission_cycle_entries",
    "queue_heads_duration_seconds",
    "queue_heads_popped_total",
    "queue_requeue_latency_seconds",
    "queue_requeue_total",
    "flavor_assignment_total",
    "preemption_search_total",
    "preemption_search_candidates",
    "preemption_search_targets",
    "tas_placement_total",
    "fair_preemption_rounds_total",
    "solver_jit_cache_total",
    "solver_device_seconds",
    "solver_trace_seconds",
    "solver_batch_size",
    "solver_padding_waste_pct",
    "solver_drs_cache_total",
    "solver_encode_seconds",
    "solver_arena_cycles_total",
    "solver_arena_dirty_rows",
    "solver_overlap_occupancy_pct",
    "solver_overlap_host_seconds",
    "remote_calls_total",
    "remote_call_duration_seconds",
    "remote_spans_ingested_total",
    # Fault containment (models/driver.py, utils/breaker.py, remote/).
    "solver_fallback_cycles_total",
    "solver_fixedpoint_rounds",
    "solver_slot_conflict_rounds",
    "solver_breaker_state",
    "solver_plane_validation_failures_total",
    "remote_deadline_exceeded_total",
    # What-if forecasting (whatif/engine.py).
    "whatif_rollout_seconds",
    "whatif_scenarios_total",
    "whatif_fallback_total",
    # Cold start / compile cache (perf/compile_cache.py, driver prewarm).
    "solver_compile_seconds",
    "solver_compile_cache_hits_total",
    "solver_compile_cache_misses_total",
    "solver_prewarm_state",
    # Pipelined admission cycles (models/driver.py + models/arena.py):
    # speculative next-cycle encode overlapped with device dispatch.
    "solver_pipeline_cycles_total",
    "solver_pipeline_abort_total",
    "solver_pipeline_reused_rows",
    "solver_pipeline_speculate_seconds",
    # Tiled streaming admission (models/driver.py _schedule_tiled):
    # past-the-flagship cycles streamed through a bounded device arena
    # in fixed-width W-tiles.
    "solver_tile_cycles_total",
    "solver_tiles_per_cycle",
    "solver_tile_width",
    "solver_tile_fallback_total",
    # Columnar workload plane (cache/columns.py + models/encode.py):
    # struct-of-arrays cold encode. Gauges describe the store the last
    # columnar cycle gathered from; the counter counts cycles that fell
    # back to the row-wise oracle because the backlog was ragged.
    "solver_encode_columns_rows",
    "solver_encode_columns_filled",
    "solver_encode_columns_generation",
    "solver_encode_columns_fallback_total",
})

# Observability layer series (obs/): flight recorder + SLO engine.
OBS_SERIES = frozenset({
    "obs_recorder_cycles_total",
    "slo_burn_rate",
    "slo_budget_remaining",
    "slo_objective_value",
    "slo_healthy",
})

# Cost attribution + on-demand profiling (obs/costs.py).
COST_SERIES = frozenset({
    "solver_cost_dispatch_total",
    "solver_cost_device_seconds_total",
    "padding_waste_lane_fraction",
    "profile_captures_total",
    "profile_state",
})

# Streaming service loop (obs/service.py): ingestion, backpressure,
# queue-age watermarks, per-workload latency spans, loop liveness.
SERVICE_SERIES = frozenset({
    "service_ingest_lag_seconds",
    "service_ingest_queue_depth",
    "service_ingest_ops_total",
    "service_backpressure_total",
    "service_loop_iterations_total",
    "service_loop_errors_total",
    "service_loop_stalled",
    "service_cycle_staleness_seconds",
    "service_queue_depth",
    "service_oldest_pending_age_seconds",
    "service_admission_wait_p99_seconds",
    "service_submit_to_nominate_seconds",
    "service_submit_to_admit_seconds",
})

# Joint multi-cluster placement (fleet/ + controllers/multikueue.py):
# the batched fleet dispatch, per-lane applies, and the remote status
# mirror's breaker-tolerant retry path.
FLEET_SERIES = frozenset({
    "fleet_dispatches_total",
    "fleet_dispatch_seconds",
    "fleet_candidates",
    "fleet_lanes",
    "fleet_placements_total",
    "fleet_preemptions_total",
    "fleet_apply_failures_total",
    "fleet_lane_unavailable_total",
    "multikueue_remote_sync_retries_total",
})

# Warm failover / HA replication (controllers/ha.py + docs/failover.md):
# the primary's crash-consistent replication stream, the warm standby's
# tail/apply loop, and takeover outcomes.
HA_SERIES = frozenset({
    "ha_role",
    "ha_checkpoint_writes_total",
    "ha_checkpoint_bytes_total",
    "ha_replication_errors_total",
    "ha_replication_skipped_total",
    "ha_replication_lag_records",
    "ha_events_applied_total",
    "ha_fingerprint_mismatch_total",
    "failover_takeovers_total",
    "failover_takeover_seconds",
    "failover_replayed_records",
    "failover_truncated_bytes",
})

# Multi-tenant read plane (readplane/): snapshot publishing, query
# coalescing, tenant fairness/cost accounting, batch containment.
READPLANE_SERIES = frozenset({
    "readplane_queries_total",
    "readplane_batches_total",
    "readplane_dispatch_tiles_total",
    "readplane_lanes_per_batch",
    "readplane_query_seconds",
    "readplane_queue_depth",
    "readplane_rejected_total",
    "readplane_deferred_total",
    "readplane_batch_failures_total",
    "readplane_breaker_state",
    "readplane_tenant_lanes_total",
    "readplane_snapshot_generation",
    "readplane_snapshot_staleness_seconds",
    "readplane_publish_seconds",
    "readplane_publish_errors_total",
})

METRIC_NAMES = (
    REFERENCE_SERIES | TRACING_SERIES | OBS_SERIES | COST_SERIES
    | SERVICE_SERIES | FLEET_SERIES | HA_SERIES | READPLANE_SERIES
)

# HELP text for the Prometheus exposition (registry.Metrics.expose).
# Series without an explicit entry fall back to a docs pointer; every key
# here MUST be in METRIC_NAMES (tools/check_metrics_names.py enforces it).
HELP_TEXT = {
    "solver_cost_dispatch_total":
        "Device dispatches per solver entry point and shape bucket",
    "solver_cost_device_seconds_total":
        "Device wall seconds attributed per solver entry point and bucket",
    "padding_waste_lane_fraction":
        "Wasted-lane fraction per entry point and padded axis "
        "(1 - real/padded)",
    "profile_captures_total":
        "jax.profiler capture lifecycle events (start/stop/error)",
    "profile_state":
        "Profiler state: 0 idle, 1 capturing, 2 failed, 3 breaker open",
    "solver_device_seconds":
        "Blocking device dispatch+readback wall time per kernel",
    "solver_fixedpoint_rounds":
        "Rounds the fixed-point admission kernel took to decide a cycle",
    "solver_slot_conflict_rounds":
        "Conflict-scan rounds the batched TAS slot pass ran in a cycle "
        "(0 = all slots settled in the first vectorized placement)",
    "solver_batch_size": "W padding bucket used by the admission cycle",
    "solver_padding_waste_pct":
        "Padded-minus-real head rows as a percentage of the bucket",
    "obs_recorder_cycles_total":
        "Cycle records captured by the flight recorder, by path",
    "solver_pipeline_cycles_total":
        "Pipelined-cycle speculation outcomes, by path "
        "(staged/consumed)",
    "solver_pipeline_abort_total":
        "Speculative encodes abandoned before consumption, by reason",
    "solver_pipeline_reused_rows":
        "W rows patched in from the speculation buffer per consumed cycle",
    "solver_pipeline_speculate_seconds":
        "Host wall time spent staging the next cycle's speculative encode "
        "inside the device-dispatch overlap window",
    "solver_tile_cycles_total":
        "Admission cycles dispatched in tiles, by mode (auto/fixed)",
    "solver_tiles_per_cycle":
        "W-tiles the last tiled cycle streamed through the device",
    "solver_tile_width":
        "Tile width (rows) the last tiled cycle packed against",
    "solver_tile_fallback_total":
        "Tiles rerouted through the host-exact path by containment, "
        "by reason (settled tiles stay applied)",
    "trace_span_duration_seconds": "Span durations by span name",
    "remote_calls_total": "Remote worker calls by op/transport/outcome",
    "remote_call_duration_seconds":
        "Remote worker call latency by op and transport",
    "whatif_rollout_seconds": "What-if batched rollout wall time",
    "remote_spans_ingested_total":
        "Worker spans merged into the client trace, by worker lane",
    "service_ingest_lag_seconds":
        "Time an ingested op waited between post and apply",
    "service_ingest_queue_depth": "Ops waiting in the ingest queue",
    "service_ingest_ops_total": "Ops applied by the service loop, by kind",
    "service_backpressure_total":
        "Posts rejected because the ingest queue was full",
    "service_loop_iterations_total": "Service-loop iterations completed",
    "service_loop_errors_total":
        "Contained exceptions in the service loop or its telemetry stage",
    "service_loop_stalled":
        "1 when cycle staleness exceeds the stall threshold, else 0",
    "service_cycle_staleness_seconds":
        "Seconds since the last completed loop iteration",
    "service_queue_depth": "Pending workloads per ClusterQueue watermark",
    "service_oldest_pending_age_seconds":
        "Age of the oldest pending workload per ClusterQueue",
    "service_admission_wait_p99_seconds":
        "p99 of submit-to-admit wait across the service's lifetime",
    "service_submit_to_nominate_seconds":
        "Submit to first scheduler nomination per workload",
    "service_submit_to_admit_seconds":
        "Submit to admission per workload (the admission wait span)",
    "fleet_dispatches_total":
        "Joint fleet placement solves, by path (device/host)",
    "fleet_dispatch_seconds":
        "Wall time of one joint fleet solve (encode+solve, pre-apply)",
    "fleet_candidates": "Pending candidates in the last joint solve",
    "fleet_lanes": "Reachable cluster lanes in the last joint solve",
    "fleet_placements_total":
        "Workloads placed by the fleet dispatcher, by cluster",
    "fleet_preemptions_total":
        "Remote victims preempted by fleet placements, by cluster",
    "fleet_apply_failures_total":
        "Cluster-lane applies that failed and left placements PENDING",
    "fleet_lane_unavailable_total":
        "Unreachable worker lanes skipped by the fleet encoder",
    "multikueue_remote_sync_retries_total":
        "Remote status mirrors deferred behind backoff because the "
        "worker transport was unreachable",
    "ha_role": "Replica role: 1 leading, 0 following",
    "ha_checkpoint_writes_total":
        "Replication-stream writes completed by the primary",
    "ha_checkpoint_bytes_total":
        "Bytes appended to the replication stream by the primary",
    "ha_replication_errors_total":
        "Contained HA replication failures, by fault point",
    "ha_replication_skipped_total":
        "Replication steps skipped while the HA breaker was open",
    "ha_replication_lag_records":
        "Scanned stream records the standby has not applied yet",
    "ha_events_applied_total":
        "Cache workload events the standby applied from the stream",
    "ha_fingerprint_mismatch_total":
        "Step fingerprints that disagreed with the standby's state",
    "failover_takeovers_total": "Standby promotions completed",
    "failover_takeover_seconds":
        "Promotion wall time: final replay + torn-tail cut + lease grab",
    "failover_replayed_records":
        "Stream records replayed during the last promotion",
    "failover_truncated_bytes":
        "Torn trailing bytes cut from the stream at promotion",
    "readplane_queries_total":
        "Read-plane queries submitted, by kind "
        "(eta/preview/sweep/drain_matrix/starve_search)",
    "readplane_batches_total":
        "Coalescing windows dispatched by the read plane",
    "readplane_dispatch_tiles_total":
        "K-tiles dispatched across all coalesced batches",
    "readplane_lanes_per_batch":
        "Scenario lanes packed into the last coalesced batch",
    "readplane_query_seconds":
        "Read-plane query latency, submit to resolved answer",
    "readplane_queue_depth": "Queries waiting in the coalescer queue",
    "readplane_rejected_total":
        "Queries rejected because the coalescer queue was full",
    "readplane_deferred_total":
        "Queries deferred to a later window by the per-tenant lane cap",
    "readplane_batch_failures_total":
        "Coalesced batches that failed; only that window's queries err",
    "readplane_breaker_state":
        "Read-plane breaker: 0 closed, 1 open, 2 half-open",
    "readplane_tenant_lanes_total":
        "Scenario lanes dispatched per tenant (cost attribution)",
    "readplane_snapshot_generation":
        "Generation of the newest published read snapshot",
    "readplane_snapshot_staleness_seconds":
        "Age of the pinned snapshot at batch dispatch time",
    "readplane_publish_seconds":
        "Wall time to capture one read snapshot at a cycle boundary",
    "readplane_publish_errors_total":
        "Contained snapshot-capture failures in the publish hook",
}

_HELP_FALLBACK = "kueue_tpu series; see docs/observability.md"


def help_for(name: str) -> str:
    return HELP_TEXT.get(name, _HELP_FALLBACK)
