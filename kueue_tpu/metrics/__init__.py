from kueue_tpu.metrics.names import METRIC_NAMES
from kueue_tpu.metrics.registry import Histogram, Metrics

__all__ = ["Histogram", "Metrics", "METRIC_NAMES"]
