"""Saturating quota arithmetic and flavor-resource keys.

TPU-native rebuild of the reference's quota math (reference:
pkg/resources/amount.go, pkg/resources/resources.go). The reference wraps
int64 in an `Amount` struct whose arithmetic saturates instead of wrapping,
with math.MaxInt64 as the "Unlimited" sentinel.

Design deviation (deliberate): we use ``UNLIMITED = 2**62`` as the sentinel
and clamp all quota arithmetic to ``[-UNLIMITED, UNLIMITED]``. This keeps the
same observable semantics for any realistic quota (real quotas are far below
2**62) while guaranteeing that the *device* solver — which carries quota as
int64 JAX arrays — can add any two in-range values without int64 overflow
(2 * 2**62 < 2**63). Host oracle and TPU kernels therefore share one exact
integer semantics, which the differential tests rely on.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, NamedTuple, Tuple

# "Effectively infinite" quota sentinel. See module docstring.
UNLIMITED: int = 1 << 62
_MIN: int = -UNLIMITED


def clamp(v: int) -> int:
    """Clamp an arbitrary int into the representable quota range."""
    if v >= UNLIMITED:
        return UNLIMITED
    if v <= _MIN:
        return _MIN
    return v


def is_unlimited(v: int) -> bool:
    return v >= UNLIMITED


def sat_add(a: int, b: int) -> int:
    """Saturating a + b; Unlimited propagates (reference amount.go Add)."""
    return clamp(a + b)


def sat_sub(a: int, b: int) -> int:
    """Saturating a - b; Unlimited minuend stays Unlimited
    (reference amount.go Sub)."""
    if is_unlimited(a):
        return UNLIMITED
    return clamp(a - b)


class FlavorResource(NamedTuple):
    """Key of a (ResourceFlavor, resource-name) cell
    (reference pkg/resources/resource.go FlavorResource)."""

    flavor: str
    resource: str


# FlavorResourceQuantities in the reference: map[FlavorResource]Amount.
FlavorResourceQuantities = Dict[FlavorResource, int]


def frq_add(dst: FlavorResourceQuantities, src: Mapping[FlavorResource, int]) -> None:
    for fr, v in src.items():
        dst[fr] = sat_add(dst.get(fr, 0), v)


def frq_sub(dst: FlavorResourceQuantities, src: Mapping[FlavorResource, int]) -> None:
    for fr, v in src.items():
        dst[fr] = sat_sub(dst.get(fr, 0), v)


def frq_clone(src: Mapping[FlavorResource, int]) -> FlavorResourceQuantities:
    return dict(src)


# Canonical resource names (subset of corev1 the scheduler treats specially).
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"


def resource_requests_total(
    per_pod: Mapping[str, int], count: int
) -> Dict[str, int]:
    """Total requests of a podset: per-pod requests scaled by pod count
    (reference pkg/workload TotalRequests semantics)."""
    return {name: clamp(v * count) for name, v in per_pod.items()}
