"""WorkloadInfo: scheduling-time wrapper around a Workload.

Behavioral port surface: reference pkg/workload/workload.go:82-1576 (Info,
TotalRequests, usage) and pkg/workload condition helpers. Holds totalized
podset requests, the owning ClusterQueue, and the last flavor-assignment
state used by flavor fungibility (NextFlavorToTry).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kueue_tpu.api.constants import (
    COND_ADMITTED,
    COND_EVICTED,
    COND_FINISHED,
    COND_QUOTA_RESERVED,
    CheckState,
)
from kueue_tpu.api.types import Condition, PodSet, Workload
from kueue_tpu.core.resources import (
    FlavorResource,
    FlavorResourceQuantities,
    frq_add,
    resource_requests_total,
)


@dataclass
class PodSetResources:
    """Totalized requests of one podset (reference workload.go
    PodSetResources)."""

    name: str
    requests: Dict[str, int]  # resource -> total (count * per-pod)
    count: int
    flavors: Dict[str, str] = field(default_factory=dict)  # resource -> flavor

    def scaled_to(self, count: int) -> "PodSetResources":
        if self.count == count or self.count == 0:
            return self
        per_pod = {r: v // self.count for r, v in self.requests.items()}
        return PodSetResources(
            name=self.name,
            requests={r: v * count for r, v in per_pod.items()},
            count=count,
            flavors=dict(self.flavors),
        )


@dataclass
class AssignmentClusterQueueState:
    """LastAssignment (reference workload.go AssignmentClusterQueueState):
    remembers the flavor index where the last attempt stopped, per podset
    resource, so fungibility resumes from the next flavor."""

    last_tried_flavor_idx: List[Dict[str, int]] = field(default_factory=list)
    cluster_queue_generation: int = 0

    def next_flavor_to_try(self, ps_idx: int, resource: str) -> int:
        if ps_idx >= len(self.last_tried_flavor_idx):
            return 0
        return self.last_tried_flavor_idx[ps_idx].get(resource, -1) + 1


class WorkloadInfo:
    """reference workload.Info."""

    def __init__(self, wl: Workload, cluster_queue: str = "") -> None:
        self.obj = wl
        self.cluster_queue = cluster_queue
        self.total_requests: List[PodSetResources] = [
            PodSetResources(
                name=ps.name,
                requests=resource_requests_total(ps.requests, ps.count),
                count=ps.count,
            )
            for ps in wl.pod_sets
        ]
        self.last_assignment: Optional[AssignmentClusterQueueState] = None
        # LocalQueue fair-sharing usage (AdmissionFairSharing); None = off.
        self.local_queue_fs_usage: Optional[float] = None

    @property
    def key(self) -> str:
        return self.obj.key

    def priority(self) -> int:
        return effective_priority(self.obj)

    def usage(self) -> FlavorResourceQuantities:
        """Quota usage keyed by (flavor, resource), derived from the podset
        assignments stored in total_requests[...].flavors. Reclaimable pods
        (reference workload_types.go:874 ReclaimablePod) reduce a podset's
        accounted usage: pods that already finished release their share of
        the gang's quota early."""
        reclaimable = self.obj.status.reclaimable_pods
        out: FlavorResourceQuantities = {}
        for ps in self.total_requests:
            reclaimed = reclaimable.get(ps.name, 0) if reclaimable else 0
            effective = ps
            if reclaimed > 0 and ps.count > 0:
                effective = ps.scaled_to(max(0, ps.count - reclaimed))
            frq_add(
                out,
                {
                    FlavorResource(flv, res): effective.requests.get(res, 0)
                    for res, flv in ps.flavors.items()
                },
            )
        return out

    def tas_usage(self):
        """Topology usage: flavor -> leaf domain id -> per-resource totals,
        derived from the admission's TopologyAssignments (reference
        workload usage.go TAS part)."""
        out: Dict[str, Dict[str, Dict[str, int]]] = {}
        adm = self.obj.status.admission
        if adm is None:
            return out
        for i, psa in enumerate(adm.pod_set_assignments):
            ta = psa.topology_assignment
            if ta is None or i >= len(self.obj.pod_sets):
                continue
            per_pod = self.obj.pod_sets[i].requests
            # The TAS flavor for this podset: any assigned flavor works
            # since one flavor serves the whole podset on the TAS path.
            flavors = set(psa.flavors.values())
            for flavor in flavors:
                dst_f = out.setdefault(flavor, {})
                for values, count in ta.domains:
                    leaf_id = "/".join(values)
                    dst = dst_f.setdefault(leaf_id, {})
                    for res, v in per_pod.items():
                        dst[res] = dst.get(res, 0) + v * count
        return out

    def sync_assignment_from_admission(self) -> None:
        """Populate total_requests flavors/counts from status.admission (used
        when re-building caches from persisted state)."""
        adm = self.obj.status.admission
        if adm is None:
            return
        by_name = {psa.name: psa for psa in adm.pod_set_assignments}
        for ps in self.total_requests:
            psa = by_name.get(ps.name)
            if psa is None:
                continue
            if psa.count and psa.count != ps.count:
                scaled = ps.scaled_to(psa.count)
                ps.requests = scaled.requests
                ps.count = psa.count
            ps.flavors = dict(psa.flavors)

    def clone(self) -> "WorkloadInfo":
        info = WorkloadInfo(self.obj, self.cluster_queue)
        info.total_requests = [
            PodSetResources(
                name=ps.name,
                requests=dict(ps.requests),
                count=ps.count,
                flavors=dict(ps.flavors),
            )
            for ps in self.total_requests
        ]
        info.last_assignment = self.last_assignment
        info.local_queue_fs_usage = self.local_queue_fs_usage
        return info


# ---- condition helpers (reference pkg/workload condition functions) ------


PRIORITY_BOOST_ANNOTATION = "kueue.x-k8s.io/priority-boost"


def effective_priority(wl: Workload) -> int:
    """Base priority adjusted by the priority-boost annotation behind the
    PriorityBoost gate (reference pkg/util/priority/priority.go:64-86,
    KEP-7990): invalid values fall back to the base priority (the webhook
    rejects them at admission; this is defense in depth)."""
    from kueue_tpu.utils import features

    if not features.enabled("PriorityBoost"):
        return wl.priority
    raw = wl.annotations.get(PRIORITY_BOOST_ANNOTATION)
    if not raw:
        return wl.priority
    try:
        return wl.priority + int(raw)
    except ValueError:
        return wl.priority


def get_condition(wl: Workload, cond_type: str) -> Optional[Condition]:
    for c in wl.status.conditions:
        if c.type == cond_type:
            return c
    return None


def set_condition(
    wl: Workload, cond_type: str, status: bool, reason: str = "",
    message: str = "", now: float = 0.0,
) -> None:
    cond = get_condition(wl, cond_type)
    if cond is None:
        wl.status.conditions.append(
            Condition(cond_type, status, reason, message, now)
        )
    else:
        if cond.status != status:
            cond.last_transition_time = now
        cond.status = status
        cond.reason = reason
        cond.message = message


def has_quota_reservation(wl: Workload) -> bool:
    cond = get_condition(wl, COND_QUOTA_RESERVED)
    return cond is not None and cond.status


def is_admitted(wl: Workload) -> bool:
    cond = get_condition(wl, COND_ADMITTED)
    return cond is not None and cond.status


def is_evicted(wl: Workload) -> bool:
    cond = get_condition(wl, COND_EVICTED)
    return cond is not None and cond.status


def is_finished(wl: Workload) -> bool:
    cond = get_condition(wl, COND_FINISHED)
    return cond is not None and cond.status


def is_active(wl: Workload) -> bool:
    return wl.active and not is_finished(wl)


def quota_reservation_time(wl: Workload, now: float) -> float:
    cond = get_condition(wl, COND_QUOTA_RESERVED)
    if cond is not None and cond.status:
        return cond.last_transition_time
    return now


def all_checks_ready(wl: Workload) -> bool:
    return all(
        acs.state == CheckState.READY for acs in wl.status.admission_checks
    )


def has_topology_assignments_pending(wl: Workload) -> bool:
    """reference workload.go:911 HasTopologyAssignmentsPending: any podset
    assignment with a delayed topology request and no assignment yet.
    Gates the Admitted condition and triggers the second scheduling pass."""
    if wl.status.admission is None:
        return False
    return any(
        psa.delayed_topology_request and psa.topology_assignment is None
        for psa in wl.status.admission.pod_set_assignments
    )


def queue_order_timestamp(wl: Workload, eviction_ordering: bool = True) -> float:
    """GetQueueOrderTimestamp (reference pkg/workload/workload.go): the
    eviction transition time when present (and eviction ordering is on),
    else creation time."""
    if eviction_ordering:
        cond = get_condition(wl, COND_EVICTED)
        if cond is not None and cond.status:
            return cond.last_transition_time
    return wl.creation_time
