"""Struct-of-arrays workload column store: the columnar truth source for
the cycle encoders.

``WorkloadColumns`` keeps every encode-relevant per-workload fact in
NumPy slabs (one row per workload, grow-by-doubling like the arena's
admitted store) so a cold or full encode becomes column gathers
(``np.take`` / fancy indexing) instead of an O(W) Python row walk:

- scalar slabs: CQ vocab id, priority, timestamp, quota-reservation and
  preemption-gate flags, pod count, flavor-resume index;
- a fixed-width request table (``REQ_WIDTH`` resource-vocab/value pairs
  per row — the dense analog of slot 0's request dict; rows needing
  more stay row-wise, the ragged-overflow contract);
- an eligibility slab over a store-level flavor vocabulary (the
  per-(workload, flavor) taints/affinity verdict, allowed-flavor label
  already folded in).

Rows are filled lazily by ``gather`` (and in bulk by ``warm``) with the
exact per-row logic of the row-wise oracle, then reused across cycles,
tiles, arena deltas, speculation and failover restores. A row is valid
for a snapshot iff

- the head is the *same* ``WorkloadInfo`` object the row was filled
  from (the queue manager builds a fresh ``WorkloadInfo`` on every spec
  update, so object identity subsumes spec generations; the store holds
  a strong reference, so ``id`` reuse cannot alias),
- the snapshot's ``quota_generation`` matches (flavor vocab, CQ
  membership, eligibility and resume validity are all quota-keyed),
- ``id(info.last_assignment)`` matches (every writer installs a fresh
  assignment object), and
- no cache workload event dirtied the key since the fill
  (``note_event`` — quota-reservation flips and evictions mutate the
  workload object in place, which identity alone cannot see).

The *dense class* a row can represent columnar-ly is deliberately the
same class the arena's ``_build_w`` handles: single assignment slot on
resource group 0, no topology request, no partial-admission reduction,
at most ``REQ_WIDTH`` request entries. For such rows the stored
``compat`` verdict is context-free: ``_device_compatible`` only reads
``preempt``/``fair_sharing``/``delayed``/TAS state on topology or
partial rows, which are excluded from the class. Any head outside the
class makes ``gather`` return ``None`` and the cycle takes the
row-wise oracle unchanged.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence

import numpy as np

from kueue_tpu.core.workload_info import (
    has_quota_reservation,
    queue_order_timestamp,
)

# Fixed request-table width: rows whose slot-0 request dict has more
# entries are ragged and stay on the row-wise oracle. Real workloads
# request a handful of resources (cpu/memory/accelerator + extended).
REQ_WIDTH = 8


class GatherView(NamedTuple):
    """One cycle's resolved head set, as store coordinates.

    ``device_idx``/``fallback_idx`` partition ``range(len(heads))`` in
    head order (the oracle's classification order); ``rows`` are the
    store rows of the device heads, aligned with ``device_idx``.
    """

    rows: np.ndarray          # i64[M] store rows, device heads in order
    device_idx: np.ndarray    # i64[M] positions into heads
    fallback_idx: np.ndarray  # i64[H-M] positions into heads
    filled: int               # rows (re)filled by this gather


class WorkloadColumns:
    """Incrementally maintained struct-of-arrays workload store."""

    def __init__(self, cap: int = 1024) -> None:
        cap = max(16, int(cap))
        self._cap = cap
        self._index: Dict[str, int] = {}
        self._free: List[int] = []
        self._next = 0
        # Bumped on every fill/invalidate: callers key component caches
        # and fingerprints off it (docs/observability.md,
        # solver_encode_columns_generation).
        self.generation = 0
        self.filled_total = 0
        self._axis_cache = None
        # Vocabularies (store-level; per-encode maps translate to the
        # cycle's node/flavor/resource axes).
        self._cq_vid: Dict[str, int] = {}
        self._cq_names: List[str] = []
        self._res_vid: Dict[str, int] = {}
        self._res_names: List[str] = []
        self._flavor_vid: Dict[str, int] = {}
        self._flavor_names: List[str] = []
        # Row slabs.
        self.info = np.empty(cap, dtype=object)     # strong refs
        self.qgen = np.full(cap, -1, dtype=np.int64)
        self.la_id = np.zeros(cap, dtype=np.int64)
        self.dirty = np.zeros(cap, dtype=bool)
        self.dense = np.zeros(cap, dtype=bool)
        self.compat = np.zeros(cap, dtype=bool)
        self.cq = np.zeros(cap, dtype=np.int32)
        self.priority = np.zeros(cap, dtype=np.int64)
        self.timestamp = np.zeros(cap, dtype=np.float64)
        self.quota_reserved = np.zeros(cap, dtype=bool)
        self.gates = np.zeros(cap, dtype=bool)
        self.count = np.ones(cap, dtype=np.int64)
        self.start_flavor = np.zeros(cap, dtype=np.int32)
        self.req_vid = np.full((cap, REQ_WIDTH), -1, dtype=np.int32)
        self.req_val = np.zeros((cap, REQ_WIDTH), dtype=np.int64)
        self.elig = np.zeros((cap, 0), dtype=bool)

    # -- slab plumbing -----------------------------------------------------

    def __len__(self) -> int:
        return len(self._index)

    def _grow(self) -> None:
        old = self._cap
        new = old * 2
        self.info = np.concatenate(
            [self.info, np.empty(old, dtype=object)]
        )
        self.qgen = np.concatenate(
            [self.qgen, np.full(old, -1, dtype=np.int64)]
        )
        for name in ("la_id", "cq", "priority", "count"):
            col = getattr(self, name)
            setattr(self, name, np.concatenate(
                [col, np.zeros(old, dtype=col.dtype)]
            ))
        for name in ("dirty", "dense", "compat", "quota_reserved", "gates"):
            col = getattr(self, name)
            setattr(self, name, np.concatenate(
                [col, np.zeros(old, dtype=bool)]
            ))
        self.timestamp = np.concatenate(
            [self.timestamp, np.zeros(old, dtype=np.float64)]
        )
        self.start_flavor = np.concatenate(
            [self.start_flavor, np.zeros(old, dtype=np.int32)]
        )
        self.req_vid = np.concatenate(
            [self.req_vid, np.full((old, REQ_WIDTH), -1, dtype=np.int32)]
        )
        self.req_val = np.concatenate(
            [self.req_val, np.zeros((old, REQ_WIDTH), dtype=np.int64)]
        )
        self.elig = np.concatenate(
            [self.elig, np.zeros((old, self.elig.shape[1]), dtype=bool)]
        )
        self._cap = new

    def _alloc(self, key: str) -> int:
        row = self._index.get(key)
        if row is not None:
            return row
        if self._free:
            row = self._free.pop()
        else:
            if self._next >= self._cap:
                self._grow()
            row = self._next
            self._next += 1
        self._index[key] = row
        return row

    def _intern(self, vid: Dict[str, int], names: List[str],
                name: str) -> int:
        v = vid.get(name)
        if v is None:
            v = len(names)
            vid[name] = v
            names.append(name)
        return v

    def _intern_flavor(self, name: str) -> int:
        v = self._flavor_vid.get(name)
        if v is None:
            v = len(self._flavor_names)
            self._flavor_vid[name] = v
            self._flavor_names.append(name)
            self.elig = np.concatenate(
                [self.elig, np.zeros((self._cap, 1), dtype=bool)], axis=1
            )
        return v

    # -- event-log application --------------------------------------------

    def note_event(self, kind: int, key: str) -> None:
        """One cache workload event (``Cache._record_workload_event``):
        the workload object mutated in place (quota-reservation flip,
        eviction, elastic reaccount), which the identity check cannot
        see — mark the key's row for refill."""
        row = self._index.get(key)
        if row is not None:
            self.dirty[row] = True
            self.generation += 1

    def drop(self, key: str) -> None:
        row = self._index.pop(key, None)
        if row is not None:
            self.info[row] = None
            self.qgen[row] = -1
            self._free.append(row)
            self.generation += 1

    # -- row fill (the per-row oracle; shared with the row-wise encoder) ---

    def _quota_flavor_axis(self, snapshot) -> Dict[str, int]:
        """The cycle flavor axis (flavor name -> column), rebuilt the
        exact way ``ops.tree_encode.encode_tree`` builds
        ``tidx.flavor_of``: pre-order quota-tree traversal, first
        occurrence wins. Memoized per quota generation — the axis is a
        pure function of the quota tree."""
        qgen = getattr(snapshot, "quota_generation", None)
        cached = self._axis_cache
        if cached is not None and cached[0] == qgen:
            return cached[1]
        flavor_of: Dict[str, int] = {}

        def collect(node) -> None:
            for fr in node.quotas:
                if fr.flavor not in flavor_of:
                    flavor_of[fr.flavor] = len(flavor_of)
            for child in node.children:
                collect(child)

        for root in snapshot.roots:
            collect(root)
        self._axis_cache = (qgen, flavor_of)
        return flavor_of

    def fill_row(self, info, snapshot, resource_flavors) -> int:
        """(Re)fill ``info``'s row from the snapshot with the exact
        per-row logic of the row-wise oracle; returns the row index.
        This is per-workload Python by design — the ragged fallback the
        column plane is built from, run once per (workload, quota
        generation) instead of once per cycle."""
        from kueue_tpu.models.encode import (
            _device_compatible,
            _workload_slots,
        )
        from kueue_tpu.scheduler.flavorassigner import FlavorAssigner

        row = self._alloc(info.key)
        self.info[row] = info
        self.la_id[row] = id(info.last_assignment)
        self.qgen[row] = int(getattr(snapshot, "quota_generation", 0))
        self.dirty[row] = False
        self.generation += 1
        self.filled_total += 1

        self.priority[row] = info.priority()
        self.timestamp[row] = queue_order_timestamp(info.obj)
        self.quota_reserved[row] = has_quota_reservation(info.obj)
        self.gates[row] = bool(info.obj.preemption_gates)
        self.count[row] = info.obj.pod_sets[0].count
        self.cq[row] = self._intern(
            self._cq_vid, self._cq_names, info.cluster_queue
        )

        # Dense-class membership: topology or partial rows have
        # context-dependent compatibility (preempt/fair/delayed/TAS) and
        # stay row-wise; everything below is context-free.
        if any(
            ps.topology_request is not None
            or (ps.min_count is not None and ps.min_count < ps.count)
            for ps in info.obj.pod_sets
        ):
            self.dense[row] = False
            self.compat[row] = False
            return row

        cqs = snapshot.cluster_queues.get(info.cluster_queue)
        slots = _workload_slots(info, cqs) if cqs is not None else None
        compat = _device_compatible(
            info, snapshot, slots, frozenset(), False, True, False
        )
        if not compat:
            # Host-fallback row: dense (the verdict is all the encoder
            # needs), no field payload.
            self.dense[row] = True
            self.compat[row] = False
            return row
        if len(slots) > 1 or slots[0].rg_idx != 0 \
                or len(slots[0].requests) > REQ_WIDTH:
            # Device-compatible but outside the columnar class (slot
            # layout / ragged-wide request dict): the whole cycle must
            # take the row-wise path to build slot planes.
            self.dense[row] = False
            self.compat[row] = True
            return row
        self.dense[row] = True
        self.compat[row] = True

        self.req_vid[row] = -1
        self.req_val[row] = 0
        for k, (res, v) in enumerate(slots[0].requests.items()):
            self.req_vid[row, k] = self._intern(
                self._res_vid, self._res_names, res
            )
            self.req_val[row, k] = v

        # Taints/affinity eligibility, identical to the oracle incl. its
        # per-WorkloadInfo cache (shared, so verify mode never computes
        # the matcher twice) and the allowed-resource-flavor mask. The
        # cached erows row is shaped on the cycle flavor axis
        # (tidx.flavor_of — quota-tree pre-order), reproduced here so
        # the shared cache stays coherent between both fill paths.
        gen = cqs.allocatable_generation
        flavor_of = self._quota_flavor_axis(snapshot)
        f = max(len(flavor_of), 1)
        cached = getattr(info, "_elig_cache", None)
        if cached is not None and cached[0] == gen \
                and cached[1].shape == (len(slots), f):
            erows = cached[1]
        else:
            assigner = FlavorAssigner(info, cqs, resource_flavors)
            erows = np.zeros((len(slots), f), dtype=bool)
            for si, sl in enumerate(slots):
                pod_sets = [info.obj.pod_sets[j] for j in sl.ps_ids]
                for fname, fi in flavor_of.items():
                    ok, _ = assigner._check_flavor_for_podsets(
                        fname, pod_sets
                    )
                    erows[si, fi] = ok
            info._elig_cache = (gen, erows)
        allowed = info.obj.labels.get(
            "kueue.x-k8s.io/allowed-resource-flavor"
        )
        er = erows[0]
        if allowed is not None:
            amask = np.zeros(f, dtype=bool)
            ai = flavor_of.get(allowed)
            if ai is not None:
                amask[ai] = True
            er = er & amask
        for fname, fi in flavor_of.items():
            # Intern first: it may widen ``self.elig``, so it must run
            # before the subscript binds the slab.
            col = self._intern_flavor(fname)
            self.elig[row, col] = er[fi]

        resume = info.last_assignment is not None and (
            gen <= info.last_assignment.cluster_queue_generation
        )
        self.start_flavor[row] = (
            info.last_assignment.next_flavor_to_try(
                slots[0].ps_ids[0], slots[0].trigger_res
            ) if resume else 0
        )
        return row

    # -- cycle resolution --------------------------------------------------

    def gather(self, heads: Sequence, snapshot,
               resource_flavors) -> Optional[GatherView]:
        """Resolve one cycle's heads against the store: reuse valid rows,
        refill invalid ones, and return the columnar view — or ``None``
        when any head is outside the dense class (the cycle then takes
        the row-wise oracle). The loop here is the thin per-head residue
        (a dict lookup and three comparisons); all field construction is
        amortized into ``fill_row``."""
        qgen = getattr(snapshot, "quota_generation", None)
        if qgen is None:
            return None
        n = len(heads)
        rows = np.empty(n, dtype=np.int64)
        compat = np.empty(n, dtype=bool)
        filled = 0
        index = self._index
        for i, info in enumerate(heads):
            row = index.get(info.key)
            if (row is None or self.info[row] is not info
                    or self.qgen[row] != qgen or self.dirty[row]
                    or self.la_id[row] != id(info.last_assignment)):
                row = self.fill_row(info, snapshot, resource_flavors)
                filled += 1
            if not self.dense[row]:
                return None
            rows[i] = row
            compat[i] = self.compat[row]
        device_idx = np.flatnonzero(compat)
        return GatherView(
            rows=rows[device_idx],
            device_idx=device_idx,
            fallback_idx=np.flatnonzero(~compat),
            filled=filled,
        )

    def warm(self, heads: Sequence, snapshot, resource_flavors) -> int:
        """Bulk (re)fill — one vectorized-downstream pass used by the
        failover restore and by speculation staging; returns the number
        of rows filled."""
        view = self.gather(heads, snapshot, resource_flavors)
        if view is not None:
            return view.filled
        # Mixed backlog: fill what is fillable without demanding the
        # dense class cycle-wide.
        qgen = getattr(snapshot, "quota_generation", None)
        if qgen is None:
            return 0
        filled = 0
        for info in heads:
            row = self._index.get(info.key)
            if (row is None or self.info[row] is not info
                    or self.qgen[row] != qgen or self.dirty[row]
                    or self.la_id[row] != id(info.last_assignment)):
                self.fill_row(info, snapshot, resource_flavors)
                filled += 1
        return filled

    # -- columnar assembly -------------------------------------------------

    def assemble(self, rows: np.ndarray, node_of: Dict[str, int],
                 flavor_of: Dict[str, int], resource_of: Dict[str, int],
                 out: Dict[str, np.ndarray]) -> None:
        """Scatter the gathered rows into the cycle's W-arrays: per-axis
        vocabulary translation tables (O(vocab)), then one gather or
        scatter per column. ``out`` maps canonical field names to the
        preallocated padded arrays; optional fields (``w_count`` /
        ``w_min_count``) are filled when present."""
        m = len(rows)
        if m == 0:
            return
        node_of_vid = np.full(
            len(self._cq_names), -1, dtype=np.int32
        )
        for name, vid in self._cq_vid.items():
            ni = node_of.get(name)
            if ni is not None:
                node_of_vid[vid] = ni
        out["w_cq"][:m] = node_of_vid[self.cq[rows]]
        out["w_active"][:m] = True
        out["w_priority"][:m] = self.priority[rows]
        out["w_timestamp"][:m] = self.timestamp[rows]
        out["w_quota_reserved"][:m] = self.quota_reserved[rows]
        out["w_gates"][:m] = self.gates[rows]
        out["w_start_flavor"][:m] = self.start_flavor[rows]
        if "w_count" in out:
            out["w_count"][:m] = self.count[rows]
        if "w_min_count" in out:
            out["w_min_count"][:m] = self.count[rows]

        # Requests: store resource vocab -> cycle resource axis; the
        # sentinel -1 vid lands on the extra -1 slot so unmapped and
        # empty entries drop out together.
        res_axis = np.full(len(self._res_names) + 1, -1, dtype=np.int64)
        for name, vid in self._res_vid.items():
            ri = resource_of.get(name)
            if ri is not None:
                res_axis[vid] = ri
        cyc = res_axis[self.req_vid[rows]]
        rr, cc = np.nonzero(cyc >= 0)
        out["w_req"][rr, cyc[rr, cc]] = self.req_val[rows][rr, cc]

        # Eligibility: cycle flavor axis -> store vocab column, with a
        # sentinel all-False column for flavors the store never saw.
        fv = len(self._flavor_names)
        cols = np.full(out["w_elig"].shape[1], fv, dtype=np.int64)
        for name, fi in flavor_of.items():
            vid = self._flavor_vid.get(name)
            if vid is not None:
                cols[fi] = vid
        eg = np.concatenate(
            [self.elig[rows], np.zeros((m, 1), dtype=bool)], axis=1
        )
        out["w_elig"][:m] = eg[:, cols]

    def rank_arrays(self, heads: Sequence):
        """(priority, timestamp) per head for tile planning: column
        reads for rows whose identity still matches, per-head attribute
        access only for the misses (no fill — planning must not pay the
        eligibility matcher)."""
        n = len(heads)
        prio = np.empty(n, dtype=np.int64)
        ts = np.empty(n, dtype=np.float64)
        index = self._index
        for i, info in enumerate(heads):
            row = index.get(info.key)
            if row is not None and self.info[row] is info \
                    and not self.dirty[row]:
                prio[i] = self.priority[row]
                ts[i] = self.timestamp[row]
            else:
                prio[i] = info.priority()
                ts[i] = queue_order_timestamp(info.obj)
        return prio, ts

    def stats(self) -> Dict[str, int]:
        return {
            "rows": len(self._index),
            "capacity": self._cap,
            "generation": self.generation,
            "filled_total": self.filled_total,
            "flavors": len(self._flavor_names),
            "resources": len(self._res_names),
            "cluster_queues": len(self._cq_names),
        }
