"""Authoritative in-memory state of admitted usage.

Behavioral surface: reference pkg/cache/scheduler/cache.go — the live store
of ClusterQueues/Cohorts/ResourceFlavors/AdmissionChecks and admitted
workloads, with assume/forget semantics for optimistic admission, and the
per-cycle Snapshot() constructor.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from kueue_tpu.api.constants import StopPolicy
from kueue_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LocalQueue,
    ResourceFlavor,
    Topology,
    Workload,
)
from kueue_tpu.cache.snapshot import (
    ClusterQueueSnapshot,
    Snapshot,
    build_quota_tree,
    has_cycle,
)
from kueue_tpu.cache.resource_node import update_tree
from kueue_tpu.core.workload_info import WorkloadInfo
from kueue_tpu.tas.snapshot import Node, TASFlavorSnapshot


class CursorLost(Exception):
    """A workload-event cursor points into a trimmed (dropped) range of
    the event log. Tailers must fall back to a full snapshot instead of
    applying a gapped stream (replaying past a gap would silently lose
    the trimmed mutations)."""

    def __init__(self, cursor: int, base: int, end: int) -> None:
        super().__init__(
            f"event cursor {cursor} outside live log window "
            f"[{base}, {end}]"
        )
        self.cursor = cursor
        self.base = base
        self.end = end


class Cache:
    """reference cache.go:144."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.cluster_queues: Dict[str, ClusterQueue] = {}
        self.cohorts: Dict[str, Cohort] = {}
        self.resource_flavors: Dict[str, ResourceFlavor] = {}
        self.admission_checks: Dict[str, AdmissionCheck] = {}
        self.topologies: Dict[str, Topology] = {}
        self.local_queues: Dict[str, LocalQueue] = {}
        self.nodes: Dict[str, Node] = {}
        self.namespaces: Dict[str, object] = {}
        # Pod-spec request derivation inputs (utils/limitrange.py):
        # LimitRanges by "ns/name", RuntimeClasses by name.
        self.limit_ranges: Dict[str, object] = {}
        self.runtime_classes: Dict[str, object] = {}
        # DRA inventory (kueue_tpu.dra.ResourceSlice) by name.
        self.resource_slices: Dict[str, object] = {}
        # DeviceClassMappings used to fold slice devices into TAS leaf
        # capacity (set by the Manager from configuration).
        self.device_class_mappings: list = []
        # Usage by pods outside kueue's management, per (flavor, leaf
        # domain) (reference tas_non_tas_pod_cache.go).
        self.non_tas_usage: Dict[str, Dict[str, Dict[str, int]]] = {}
        # Admitted (or assumed) workloads, keyed by "ns/name".
        self.workloads: Dict[str, WorkloadInfo] = {}
        self.assumed: Set[str] = set()
        self.generation = 0
        # Bumped on every workload-set mutation (add/assume/forget/
        # delete/reaccount): lets the encoder reuse the admitted-state
        # arrays across cycles when nothing changed.
        self.workload_generation = 0
        # Fine-grained generations (docs/perf.md): ``generation`` stays the
        # union bump for compatibility, but consumers that only depend on
        # one input family key off these so unrelated mutations stop
        # invalidating their caches.
        # CQ / cohort / resource-flavor changes: the quota tree, per-CQ
        # policy and flavor-eligibility inputs.
        self.quota_generation = 0
        # Node / topology / resource-slice changes: TAS capacity only.
        self.node_generation = 0
        # Effective admitted-set/usage mutations (every recorded workload
        # event bumps it; a no-op delete does not).
        self.admitted_generation = 0
        # Workload event log consumed by the incremental cycle encoder
        # (models/arena.py): (kind, key, cq, usage items, priority, uid,
        # info). kind is +1 (added to the live tree) / -1 (removed).
        self._workload_events: list = []
        self._workload_event_base = 0
        # Count of cap-trims applied to the event log; tailers holding a
        # cursor into a trimmed range get CursorLost and must resync.
        self.workload_event_trims = 0
        # Columnar workload plane (cache/columns.py): struct-of-arrays
        # per-workload encode inputs, invalidated through the workload
        # event log and shared with every snapshot. The encoders gather
        # from it instead of re-walking rows in Python.
        from kueue_tpu.cache.columns import WorkloadColumns

        self.workload_columns = WorkloadColumns()
        # Structure cache for TAS snapshots: keyed by the generations the
        # template actually depends on (quota + node inputs).
        self._tas_templates: Dict[str, tuple] = {}
        # Live quota tree with incrementally maintained usage (reference
        # cache.go keeps usage live; Snapshot() only clones usage maps).
        self._live_nodes: Optional[Dict[str, object]] = None
        self._live_generation = -1
        self._cq_workloads: Dict[str, Dict[str, WorkloadInfo]] = {}

    # -- spec management ----------------------------------------------------

    def add_or_update_cluster_queue(self, cq: ClusterQueue) -> None:
        with self._lock:
            self.cluster_queues[cq.name] = cq
            self.generation += 1
            self.quota_generation += 1

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            self.cluster_queues.pop(name, None)
            self.generation += 1
            self.quota_generation += 1

    def add_or_update_cohort(self, cohort: Cohort) -> None:
        with self._lock:
            self.cohorts[cohort.name] = cohort
            self.generation += 1
            self.quota_generation += 1

    def delete_cohort(self, name: str) -> None:
        with self._lock:
            self.cohorts.pop(name, None)
            self.generation += 1
            self.quota_generation += 1

    def add_or_update_resource_flavor(self, rf: ResourceFlavor) -> None:
        with self._lock:
            self.resource_flavors[rf.name] = rf
            self.generation += 1
            self.quota_generation += 1

    def delete_resource_flavor(self, name: str) -> None:
        with self._lock:
            self.resource_flavors.pop(name, None)
            self.generation += 1
            self.quota_generation += 1

    def add_or_update_admission_check(self, ac: AdmissionCheck) -> None:
        with self._lock:
            self.admission_checks[ac.name] = ac

    def add_or_update_topology(self, topo: Topology) -> None:
        with self._lock:
            self.topologies[topo.name] = topo
            # TAS structure templates depend on the topology spec; without
            # this bump a re-applied Topology kept serving stale templates.
            self.node_generation += 1

    def add_or_update_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq

    def delete_local_queue(self, key: str) -> None:
        with self._lock:
            self.local_queues.pop(key, None)

    def add_or_update_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self.generation += 1
            self.node_generation += 1

    def delete_node(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)
            self.generation += 1
            self.node_generation += 1

    def add_or_update_resource_slice(self, rs) -> None:
        """DRA inventory (kueue_tpu.dra.ResourceSlice); slices feed charge
        computation and TAS leaf capacity, so spec generation bumps."""
        with self._lock:
            self.resource_slices[rs.name] = rs
            self.generation += 1
            self.node_generation += 1

    def delete_resource_slice(self, name: str) -> None:
        with self._lock:
            self.resource_slices.pop(name, None)
            self.generation += 1
            self.node_generation += 1

    # -- workload lifecycle -------------------------------------------------

    # Bound on the workload event log; when exceeded the older half is
    # trimmed (consumers detect the gap through the base counter and fall
    # back to a full re-encode).
    _EVENT_LOG_CAP = 100_000

    def _record_workload_event(self, kind: int, key: str, cq: str,
                               items: tuple, info: WorkloadInfo) -> None:
        """Append one effective admitted-set mutation for incremental
        consumers (models/arena.py). kind is +1 add / -1 remove; ``items``
        is the usage at event time (the workload object is mutable, so it
        must be captured here, not at drain time)."""
        self._workload_events.append(
            (kind, key, cq, items, info.priority(), info.obj.uid, info)
        )
        # The same event stream drives columnar invalidation: these are
        # exactly the in-place workload mutations (quota-reservation
        # flips, evictions, elastic reaccounts) that object identity
        # cannot detect.
        self.workload_columns.note_event(kind, key)
        if len(self._workload_events) > self._EVENT_LOG_CAP:
            drop = len(self._workload_events) // 2
            del self._workload_events[:drop]
            self._workload_event_base += drop
            self.workload_event_trims += 1
        self.admitted_generation += 1

    def _live_add(self, info: WorkloadInfo) -> None:
        # Caller must have run _ensure_live() BEFORE storing the workload
        # in self.workloads: the rebuild replays self.workloads, so adding
        # first would double-count this workload's usage.
        node = self._live_nodes.get(info.cluster_queue)
        items = tuple(info.usage().items())
        if node is not None:
            for fr, v in items:
                node.add_usage(fr, v)
        self._cq_workloads.setdefault(info.cluster_queue, {})[info.key] = info
        self._record_workload_event(
            1, info.key, info.cluster_queue, items, info
        )

    def _live_remove(self, key: str) -> None:
        old = self.workloads.get(key)
        if old is None or self._live_nodes is None:
            return
        node = self._live_nodes.get(old.cluster_queue)
        items = tuple(old.usage().items())
        if node is not None:
            for fr, v in items:
                node.remove_usage(fr, v)
        self._cq_workloads.get(old.cluster_queue, {}).pop(key, None)
        self._record_workload_event(-1, key, old.cluster_queue, items, old)

    def add_or_update_workload(self, info: WorkloadInfo) -> None:
        with self._lock:
            self._ensure_live()
            self._live_remove(info.key)
            self.workloads[info.key] = info
            self.assumed.discard(info.key)
            self._live_add(info)
            self.workload_generation += 1

    def assume_workload(self, info: WorkloadInfo) -> None:
        """Optimistic admission before the status write lands
        (reference cache.go AssumeWorkload)."""
        with self._lock:
            self._ensure_live()
            self._live_remove(info.key)
            self.workloads[info.key] = info
            self.assumed.add(info.key)
            self._live_add(info)
            self.workload_generation += 1

    def forget_workload(self, key: str) -> None:
        with self._lock:
            if key in self.assumed:
                self._live_remove(key)
                self.assumed.discard(key)
                self.workloads.pop(key, None)
                self.workload_generation += 1

    def delete_workload(self, key: str) -> None:
        with self._lock:
            self._live_remove(key)
            self.workloads.pop(key, None)
            self.assumed.discard(key)
            # Release the columnar row (and its strong WorkloadInfo ref)
            # even when the workload never reached the live tree — the
            # event hook only sees live-set mutations.
            self.workload_columns.drop(key)
            self.workload_generation += 1

    def reaccount_workload(self, key: str, mutate) -> None:
        """Atomically re-account a stored workload whose usage is about to
        change: remove the old usage from the live tree, apply ``mutate``,
        then add the new usage. Needed because usage is derived from the
        (shared, mutable) workload object."""
        with self._lock:
            info = self.workloads.get(key)
            if info is None:
                mutate()
                return
            self._ensure_live()
            self._live_remove(key)
            mutate()
            self._live_add(info)
            self.workload_generation += 1

    def is_added(self, key: str) -> bool:
        with self._lock:
            return key in self.workloads

    # -- CQ activity --------------------------------------------------------

    def cluster_queue_active(self, cq: ClusterQueue) -> bool:
        """A CQ is inactive when stopped or referencing missing flavors /
        inactive admission checks (reference clusterqueue.go
        updateQueueStatus)."""
        if cq.stop_policy != StopPolicy.NONE:
            return False
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                if fq.name not in self.resource_flavors:
                    return False
        for ac_name in cq.admission_checks:
            ac = self.admission_checks.get(ac_name)
            if ac is None or not ac.active:
                return False
        return True

    # -- snapshot -----------------------------------------------------------

    def _ensure_live(self) -> None:
        """(Re)build the live quota tree when specs changed, replaying
        admitted usage once; all later workload events update it
        incrementally."""
        # Keyed on quota_generation: the quota tree is built from cohorts
        # and CQs only, so node/flavor-unrelated spec bumps must not force
        # a rebuild (a rebuild also reorders _cq_workloads, which the
        # incremental encoder relies on staying stable between quota
        # changes).
        if self._live_nodes is not None and \
                self._live_generation == self.quota_generation:
            return
        nodes = build_quota_tree(
            self.cohorts.values(), self.cluster_queues.values()
        )
        if has_cycle(nodes):
            raise ValueError("cohort hierarchy has a cycle")
        for node in nodes.values():
            if node.parent is None:
                update_tree(node)
        self._live_nodes = nodes
        self._live_generation = self.quota_generation
        self._cq_workloads = {}
        for info in self.workloads.values():
            node = nodes.get(info.cluster_queue)
            if node is not None:
                for fr, v in info.usage().items():
                    node.add_usage(fr, v)
                self._cq_workloads.setdefault(
                    info.cluster_queue, {}
                )[info.key] = info

    def _clone_live_tree(self) -> Dict[str, object]:
        """Copy-on-cycle clone: structure, quotas and subtree quotas are
        shared; usage dicts are copied (the scheduler's transaction state).
        reference resource_node.go Clone()."""
        from kueue_tpu.cache.resource_node import QuotaNode

        clones: Dict[str, QuotaNode] = {}
        for name, node in self._live_nodes.items():
            c = QuotaNode.__new__(QuotaNode)
            c.name = node.name
            c.is_cq = node.is_cq
            c.parent = None
            c.children = []
            c.quotas = node.quotas  # shared (immutable between gens)
            c.subtree_quota = node.subtree_quota  # shared
            c.usage = dict(node.usage)  # the mutable transaction state
            c.usage_gen = 0
            c.fair_weight = node.fair_weight
            clones[name] = c
        for name, node in self._live_nodes.items():
            if node.parent is not None:
                clones[name].parent = clones[node.parent.name]
                clones[node.parent.name].children.append(clones[name])
        return clones

    def snapshot(self) -> Snapshot:
        """reference snapshot.go:161: copy-on-cycle scheduling view."""
        with self._lock:
            self._ensure_live()
            snap = Snapshot()
            snap.generation = self.generation
            snap.workload_columns = self.workload_columns
            snap.quota_generation = self.quota_generation
            snap.node_generation = self.node_generation
            snap.admitted_generation = self.admitted_generation
            snap.workload_generation = self.workload_generation
            snap.resource_flavors = dict(self.resource_flavors)
            nodes = self._clone_live_tree()
            snap.roots = [n for n in nodes.values() if n.parent is None]
            for name, cq in self.cluster_queues.items():
                cqs = ClusterQueueSnapshot(cq, nodes[name])
                # Flavor eligibility / assignment-resume caches depend on
                # quota inputs only; an unrelated node add must not expire
                # every workload's last assignment.
                cqs.allocatable_generation = self.quota_generation
                cqs.workloads = dict(self._cq_workloads.get(name, {}))
                snap.cluster_queues[name] = cqs
                if not self.cluster_queue_active(cq):
                    snap.inactive_cluster_queues.add(name)
            for name, node in nodes.items():
                if not node.is_cq:
                    snap.cohorts[name] = node
            # Per-flavor topology snapshots (reference tas_flavor.go). The
            # domain tree + capacity arrays are immutable between node or
            # topology changes, so they're cached and shared per cycle.
            # DRA: ResourceSlices whose pool names a node add the mapped
            # logical-resource device counts to that node's TAS capacity
            # (kueue_tpu.dra.node_device_counts).
            tas_nodes = self.nodes
            if self.resource_slices and self.device_class_mappings:
                from kueue_tpu.dra import node_device_counts

                counts = node_device_counts(
                    list(self.resource_slices.values()),
                    self.device_class_mappings,
                )
                if counts:
                    tas_nodes = {}
                    for name2, node in self.nodes.items():
                        extra = counts.get(name2)
                        if extra:
                            node = Node(
                                name=node.name, labels=dict(node.labels),
                                capacity=dict(node.capacity),
                                taints=list(node.taints), ready=node.ready,
                            )
                            for r2, v2 in extra.items():
                                node.capacity[r2] = (
                                    node.capacity.get(r2, 0) + v2
                                )
                        tas_nodes[name2] = node
            for name, rf in self.resource_flavors.items():
                if rf.topology_name and rf.topology_name in self.topologies:
                    cached = self._tas_templates.get(name)
                    # The template reads the topology spec, the node set
                    # (+ DRA slices) and the flavor's taints/tolerations —
                    # exactly the quota + node generations.
                    tas_key = (self.quota_generation, self.node_generation)
                    if cached is None or cached[0] != tas_key:
                        template = TASFlavorSnapshot(
                            self.topologies[rf.topology_name],
                            tas_nodes.values(),
                            flavor_taints=rf.node_taints,
                            flavor_tolerations=rf.tolerations,
                        )
                        self._tas_templates[name] = (tas_key, template)
                    else:
                        template = cached[1]
                    tas = template.share_structure()
                    tas.usage = {
                        k: dict(v)
                        for k, v in self.non_tas_usage.get(name, {}).items()
                    }
                    snap.tas_flavors[name] = tas
            # Usage is already in the cloned tree; only TAS usage needs a
            # replay into the per-cycle TAS snapshots.
            if snap.tas_flavors:
                for info in self.workloads.values():
                    for flavor, leaf_usage in info.tas_usage().items():
                        tas = snap.tas_flavors.get(flavor)
                        if tas is not None:
                            for leaf_id, reqs in leaf_usage.items():
                                tas.add_usage(leaf_id, reqs)
            return snap

    def snapshot_with_workload_events(self, cursor: int):
        """Snapshot plus the workload events recorded since ``cursor``,
        under ONE lock hold so the event replay lands exactly on the
        snapshot state. Returns ``(snapshot, events, new_cursor)``;
        ``events`` is None when the log was trimmed past the cursor (a
        gap — the consumer must re-encode from the snapshot)."""
        with self._lock:
            base = self._workload_event_base
            end = base + len(self._workload_events)
            if cursor < base or cursor > end:
                events = None
            else:
                events = list(self._workload_events[cursor - base:])
            return self.snapshot(), events, end

    def workload_events_since(self, cursor: int):
        """Events recorded since ``cursor`` without a snapshot (the tail
        path for replication streams). Returns ``(events, new_cursor)``.

        Raises :class:`CursorLost` when the cap-trim dropped entries the
        cursor still points at — the stream has a gap, so the tailer must
        resync from a full snapshot rather than apply what remains.
        (``snapshot_with_workload_events`` keeps its legacy ``events is
        None`` convention for the arena encoder, which always has the
        snapshot in hand to re-encode from.)"""
        with self._lock:
            base = self._workload_event_base
            end = base + len(self._workload_events)
            if cursor < base or cursor > end:
                raise CursorLost(cursor, base, end)
            return list(self._workload_events[cursor - base:]), end
