"""Authoritative in-memory state of admitted usage.

Behavioral surface: reference pkg/cache/scheduler/cache.go — the live store
of ClusterQueues/Cohorts/ResourceFlavors/AdmissionChecks and admitted
workloads, with assume/forget semantics for optimistic admission, and the
per-cycle Snapshot() constructor.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from kueue_tpu.api.constants import StopPolicy
from kueue_tpu.api.types import (
    AdmissionCheck,
    ClusterQueue,
    Cohort,
    LocalQueue,
    ResourceFlavor,
    Topology,
    Workload,
)
from kueue_tpu.cache.snapshot import (
    ClusterQueueSnapshot,
    Snapshot,
    build_quota_tree,
    has_cycle,
)
from kueue_tpu.cache.resource_node import update_tree
from kueue_tpu.core.workload_info import WorkloadInfo
from kueue_tpu.tas.snapshot import Node, TASFlavorSnapshot


class Cache:
    """reference cache.go:144."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.cluster_queues: Dict[str, ClusterQueue] = {}
        self.cohorts: Dict[str, Cohort] = {}
        self.resource_flavors: Dict[str, ResourceFlavor] = {}
        self.admission_checks: Dict[str, AdmissionCheck] = {}
        self.topologies: Dict[str, Topology] = {}
        self.local_queues: Dict[str, LocalQueue] = {}
        self.nodes: Dict[str, Node] = {}
        # Usage by pods outside kueue's management, per (flavor, leaf
        # domain) (reference tas_non_tas_pod_cache.go).
        self.non_tas_usage: Dict[str, Dict[str, Dict[str, int]]] = {}
        # Admitted (or assumed) workloads, keyed by "ns/name".
        self.workloads: Dict[str, WorkloadInfo] = {}
        self.assumed: Set[str] = set()
        self.generation = 0
        # Structure cache for TAS snapshots: (generation, template).
        self._tas_templates: Dict[str, tuple] = {}

    # -- spec management ----------------------------------------------------

    def add_or_update_cluster_queue(self, cq: ClusterQueue) -> None:
        with self._lock:
            self.cluster_queues[cq.name] = cq
            self.generation += 1

    def delete_cluster_queue(self, name: str) -> None:
        with self._lock:
            self.cluster_queues.pop(name, None)
            self.generation += 1

    def add_or_update_cohort(self, cohort: Cohort) -> None:
        with self._lock:
            self.cohorts[cohort.name] = cohort
            self.generation += 1

    def delete_cohort(self, name: str) -> None:
        with self._lock:
            self.cohorts.pop(name, None)
            self.generation += 1

    def add_or_update_resource_flavor(self, rf: ResourceFlavor) -> None:
        with self._lock:
            self.resource_flavors[rf.name] = rf
            self.generation += 1

    def delete_resource_flavor(self, name: str) -> None:
        with self._lock:
            self.resource_flavors.pop(name, None)
            self.generation += 1

    def add_or_update_admission_check(self, ac: AdmissionCheck) -> None:
        with self._lock:
            self.admission_checks[ac.name] = ac

    def add_or_update_topology(self, topo: Topology) -> None:
        with self._lock:
            self.topologies[topo.name] = topo

    def add_or_update_local_queue(self, lq: LocalQueue) -> None:
        with self._lock:
            self.local_queues[lq.key] = lq

    def add_or_update_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self.generation += 1

    def delete_node(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)
            self.generation += 1

    # -- workload lifecycle -------------------------------------------------

    def add_or_update_workload(self, info: WorkloadInfo) -> None:
        with self._lock:
            self.workloads[info.key] = info
            self.assumed.discard(info.key)

    def assume_workload(self, info: WorkloadInfo) -> None:
        """Optimistic admission before the status write lands
        (reference cache.go AssumeWorkload)."""
        with self._lock:
            self.workloads[info.key] = info
            self.assumed.add(info.key)

    def forget_workload(self, key: str) -> None:
        with self._lock:
            if key in self.assumed:
                self.assumed.discard(key)
                self.workloads.pop(key, None)

    def delete_workload(self, key: str) -> None:
        with self._lock:
            self.workloads.pop(key, None)
            self.assumed.discard(key)

    def is_added(self, key: str) -> bool:
        with self._lock:
            return key in self.workloads

    # -- CQ activity --------------------------------------------------------

    def cluster_queue_active(self, cq: ClusterQueue) -> bool:
        """A CQ is inactive when stopped or referencing missing flavors /
        inactive admission checks (reference clusterqueue.go
        updateQueueStatus)."""
        if cq.stop_policy != StopPolicy.NONE:
            return False
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                if fq.name not in self.resource_flavors:
                    return False
        for ac_name in cq.admission_checks:
            ac = self.admission_checks.get(ac_name)
            if ac is None or not ac.active:
                return False
        return True

    # -- snapshot -----------------------------------------------------------

    def snapshot(self) -> Snapshot:
        """reference snapshot.go:161: copy-on-cycle scheduling view."""
        with self._lock:
            snap = Snapshot()
            snap.resource_flavors = dict(self.resource_flavors)
            nodes = build_quota_tree(
                self.cohorts.values(), self.cluster_queues.values()
            )
            if has_cycle(nodes):
                raise ValueError("cohort hierarchy has a cycle")
            roots = [n for n in nodes.values() if n.parent is None]
            for root in roots:
                update_tree(root)
            snap.roots = roots
            for name, cq in self.cluster_queues.items():
                cqs = ClusterQueueSnapshot(cq, nodes[name])
                cqs.allocatable_generation = self.generation
                snap.cluster_queues[name] = cqs
                if not self.cluster_queue_active(cq):
                    snap.inactive_cluster_queues.add(name)
            for name, node in nodes.items():
                if not node.is_cq:
                    snap.cohorts[name] = node
            # Per-flavor topology snapshots (reference tas_flavor.go). The
            # domain tree + capacity arrays are immutable between node or
            # topology changes, so they're cached and shared per cycle.
            for name, rf in self.resource_flavors.items():
                if rf.topology_name and rf.topology_name in self.topologies:
                    cached = self._tas_templates.get(name)
                    if cached is None or cached[0] != self.generation:
                        template = TASFlavorSnapshot(
                            self.topologies[rf.topology_name],
                            self.nodes.values(),
                            flavor_taints=rf.node_taints,
                            flavor_tolerations=rf.tolerations,
                        )
                        self._tas_templates[name] = (self.generation, template)
                    else:
                        template = cached[1]
                    tas = template.share_structure()
                    tas.usage = {
                        k: dict(v)
                        for k, v in self.non_tas_usage.get(name, {}).items()
                    }
                    snap.tas_flavors[name] = tas
            for info in self.workloads.values():
                if info.cluster_queue in snap.cluster_queues:
                    snap.add_workload(info.clone())
            return snap
