"""Hierarchical quota engine — host-exact semantics.

This is the behavioral re-derivation of the reference's resourceNode math
(reference: pkg/cache/scheduler/resource_node.go). Every function here has a
vectorized twin in ``kueue_tpu/ops/quota_ops.py`` operating on padded
[node, flavor, resource] int64 tensors; the property tests in
``tests/test_quota_oracle.py`` pin the two implementations to each other.

Semantics (per FlavorResource cell, all saturating int arithmetic):

- ``subtree_quota`` = own nominal + Σ_children (child.subtree_quota −
  child.local_quota)                      (resource_node.go:190-227)
- ``local_quota``   = max(0, subtree_quota − lending_limit) when a lending
  limit is set, else 0                    (resource_node.go:67)
- ``usage`` at a cohort = Σ_children max(0, child.usage − child.local_quota)
- ``available``     = recursive up-tree with borrowing-limit clamp
                                          (resource_node.go:106-122)
- ``potential_available`` = max capacity assuming zero usage
                                          (resource_node.go:129-140)
- ``add_usage`` / ``remove_usage`` bubble the part of the delta exceeding
  local quota to the parent               (resource_node.go:144-165)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from kueue_tpu.core.resources import (
    FlavorResource,
    FlavorResourceQuantities,
    UNLIMITED,
    sat_add,
    sat_sub,
)


@dataclass
class QuotaCell:
    """Quota of one node for one FlavorResource."""

    nominal: int = 0
    borrowing_limit: Optional[int] = None  # None = unlimited borrowing
    lending_limit: Optional[int] = None  # None = lend everything


class QuotaNode:
    """One node of the quota tree (a ClusterQueue leaf or a Cohort)."""

    def __init__(self, name: str, is_cq: bool = False) -> None:
        self.name = name
        self.is_cq = is_cq
        self.parent: Optional["QuotaNode"] = None
        self.children: List["QuotaNode"] = []
        self.quotas: Dict[FlavorResource, QuotaCell] = {}
        self.subtree_quota: FlavorResourceQuantities = {}
        self.usage: FlavorResourceQuantities = {}
        self.fair_weight: float = 1.0
        # Usage generation: bumped by every REAL usage mutation (not by
        # simulate/revert pairs, which pass bump=False). DRS of a node is
        # a pure function of (node.usage, static quota config), so DRS
        # caches key their validity on this counter — the fair-sharing
        # tournament's incremental cache depends on it.
        self.usage_gen: int = 0

    # -- navigation ---------------------------------------------------------

    def has_parent(self) -> bool:
        return self.parent is not None

    def root(self) -> "QuotaNode":
        node = self
        while node.parent is not None:
            node = node.parent
        return node

    def path_self_to_root(self) -> Iterator["QuotaNode"]:
        node: Optional[QuotaNode] = self
        while node is not None:
            yield node
            node = node.parent

    # -- cell accessors -----------------------------------------------------

    def local_quota(self, fr: FlavorResource) -> int:
        cell = self.quotas.get(fr)
        if cell is None or cell.lending_limit is None:
            return 0
        return max(0, sat_sub(self.subtree_quota.get(fr, 0), cell.lending_limit))

    def local_available(self, fr: FlavorResource) -> int:
        return max(0, sat_sub(self.local_quota(fr), self.usage.get(fr, 0)))

    def available(self, fr: FlavorResource) -> int:
        """Remaining capacity for this node, honoring borrowing limits.
        May be negative under overadmission (resource_node.go:106)."""
        if self.parent is None:
            return sat_sub(self.subtree_quota.get(fr, 0), self.usage.get(fr, 0))
        parent_available = self.parent.available(fr)
        cell = self.quotas.get(fr)
        if cell is not None and cell.borrowing_limit is not None:
            lq = self.local_quota(fr)
            stored_in_parent = sat_sub(self.subtree_quota.get(fr, 0), lq)
            used_in_parent = max(0, sat_sub(self.usage.get(fr, 0), lq))
            with_max = sat_add(
                sat_sub(stored_in_parent, used_in_parent), cell.borrowing_limit
            )
            parent_available = min(with_max, parent_available)
        return sat_add(self.local_available(fr), parent_available)

    def potential_available(self, fr: FlavorResource) -> int:
        """Max capacity available assuming no usage
        (resource_node.go:129)."""
        if self.parent is None:
            return self.subtree_quota.get(fr, 0)
        avail = sat_add(self.local_quota(fr), self.parent.potential_available(fr))
        cell = self.quotas.get(fr)
        if cell is not None and cell.borrowing_limit is not None:
            max_with_borrowing = sat_add(
                self.subtree_quota.get(fr, 0), cell.borrowing_limit
            )
            avail = min(max_with_borrowing, avail)
        return avail

    # -- usage mutation -----------------------------------------------------

    def add_usage(self, fr: FlavorResource, val: int,
                  bump: bool = True) -> None:
        """resource_node.go:144. Negative val is not allowed here; use
        remove_usage (their bubbling rules differ). ``bump=False`` is for
        simulate/revert pairs whose net usage change is zero — they must
        not advance ``usage_gen`` or every DRS cache keyed on it would be
        spuriously invalidated."""
        local_avail = self.local_available(fr)
        self.usage[fr] = sat_add(self.usage.get(fr, 0), val)
        if bump:
            self.usage_gen += 1
        if self.parent is not None and val > local_avail:
            self.parent.add_usage(fr, sat_sub(val, local_avail), bump)

    def remove_usage(self, fr: FlavorResource, val: int,
                     bump: bool = True) -> None:
        """resource_node.go:156."""
        stored_in_parent = sat_sub(self.usage.get(fr, 0), self.local_quota(fr))
        self.usage[fr] = sat_sub(self.usage.get(fr, 0), val)
        if bump:
            self.usage_gen += 1
        if stored_in_parent <= 0 or self.parent is None:
            return
        self.parent.remove_usage(fr, min(val, stored_in_parent), bump)

    # -- fit predicates -----------------------------------------------------

    def borrowing_with(self, fr: FlavorResource, val: int) -> bool:
        """Would usage+val exceed this node's subtree quota?"""
        return sat_add(self.usage.get(fr, 0), val) > self.subtree_quota.get(fr, 0)

    def quantities_fit_in_quota(
        self, requests: FlavorResourceQuantities
    ) -> Tuple[bool, FlavorResourceQuantities]:
        """resource_node.go:233: fit at this node + requests remaining past
        the node's local quota (to be retried on the parent)."""
        fits = True
        remaining: FlavorResourceQuantities = {}
        for fr, v in requests.items():
            if self.subtree_quota.get(fr, 0) < sat_add(self.usage.get(fr, 0), v):
                fits = False
            remaining[fr] = max(0, sat_sub(v, self.local_available(fr)))
        return fits, remaining

    def is_within_nominal_in(self, frs) -> bool:
        """resource_node.go:247."""
        return all(
            self.subtree_quota.get(fr, 0) >= self.usage.get(fr, 0) for fr in frs
        )

    def height(self) -> int:
        """Distance to the furthest leaf; a childless node has height 0
        (reference hierarchical_preemption.go getNodeHeight)."""
        h = min(len(self.children), 1)
        for child in self.children:
            if not child.is_cq:
                h = max(h, child.height() + 1)
        return h


def update_tree(root: QuotaNode) -> None:
    """Recompute subtree_quota bottom-up and cohort usage roll-ups
    (resource_node.go:190-227). CQ usage is preserved; cohort usage is
    re-derived from children."""
    _update_node(root)


def _update_node(node: QuotaNode) -> None:
    node.subtree_quota = {fr: cell.nominal for fr, cell in node.quotas.items()}
    if not node.is_cq:
        node.usage = {}
    for child in node.children:
        _update_node(child)
        # accumulateFromChild (resource_node.go:217)
        for fr, child_quota in child.subtree_quota.items():
            delta = sat_sub(child_quota, child.local_quota(fr))
            node.subtree_quota[fr] = sat_add(node.subtree_quota.get(fr, 0), delta)
        for fr, child_usage in child.usage.items():
            delta = max(0, sat_sub(child_usage, child.local_quota(fr)))
            node.usage[fr] = sat_add(node.usage.get(fr, 0), delta)


def find_height_of_lowest_subtree_that_fits(
    cq: QuotaNode, fr: FlavorResource, val: int
) -> Tuple[int, bool]:
    """Borrow "distance": height of the lowest cohort subtree that can absorb
    val of fr (reference hierarchical_preemption.go:221). Returns
    (height, subtree_is_proper) where the second value reports whether the
    found subtree is smaller than the whole hierarchy — i.e. reclaim may be
    possible higher up."""
    if not cq.borrowing_with(fr, val) or not cq.has_parent():
        return 0, cq.has_parent()
    remaining = sat_sub(val, cq.local_available(fr))
    node = cq.parent
    while node is not None:
        if not node.borrowing_with(fr, remaining):
            return node.height(), node.has_parent()
        remaining = sat_sub(remaining, node.local_available(fr))
        node = node.parent
    assert cq.parent is not None
    return cq.parent.root().height(), False


def calculate_lendable(node: QuotaNode) -> Dict[str, int]:
    """Aggregate potential capacity per resource name across all flavors,
    evaluated at ``node`` (reference fair_sharing.go:186).

    potentialAvailable is usage-independent, so the result is constant for
    a given quota configuration; it is memoized on the node (snapshot
    clones are rebuilt whenever quotas change)."""
    cached = getattr(node, "_lendable_cache", None)
    if cached is not None:
        return cached
    root = node.root()
    lendable: Dict[str, int] = {}
    for fr in root.subtree_quota:
        lendable[fr.resource] = sat_add(
            lendable.get(fr.resource, 0), node.potential_available(fr)
        )
    node._lendable_cache = lendable
    return lendable


@dataclass
class DRS:
    """Dominant resource share (reference fair_sharing.go:43)."""

    fair_weight: float = 1.0
    unweighted_ratio: float = 0.0
    dominant_resource: str = ""
    borrowing: bool = False
    borrowed_frs: List[FlavorResource] = field(default_factory=list)
    _pws: Optional[float] = None  # memoized precise_weighted_share

    def is_zero(self) -> bool:
        return self.unweighted_ratio == 0

    def precise_weighted_share(self) -> float:
        if self._pws is None:
            if self.is_zero():
                self._pws = 0.0
            elif self.fair_weight == 0:
                self._pws = float("inf")
            else:
                self._pws = self.unweighted_ratio / self.fair_weight
        return self._pws

    def zero_weight_borrows(self) -> bool:
        return self.fair_weight == 0 and not self.is_zero()

    def is_borrowing_on(self, requested: FlavorResourceQuantities) -> bool:
        return any(requested.get(fr, 0) > 0 for fr in self.borrowed_frs)


def negative_drs() -> DRS:
    return DRS(unweighted_ratio=-1.0)


def compare_drs(a: DRS, b: DRS) -> int:
    """Lower wins for scheduling, higher wins for preemption
    (fair_sharing.go:112)."""
    a_zwb, b_zwb = a.zero_weight_borrows(), b.zero_weight_borrows()
    if a_zwb and b_zwb:
        return _cmp(a.unweighted_ratio, b.unweighted_ratio)
    if a_zwb:
        return 1
    if b_zwb:
        return -1
    return _cmp(a.precise_weighted_share(), b.precise_weighted_share())


def _cmp(a: float, b: float) -> int:
    return (a > b) - (a < b)


def dominant_resource_share(
    node: QuotaNode, wl_req: FlavorResourceQuantities
) -> DRS:
    """share = max over resources of (borrowed-above-subtree-quota × 1000 /
    lendable-at-parent), ÷ weight (reference fair_sharing.go:149)."""
    drs = DRS(fair_weight=node.fair_weight)
    if not node.has_parent():
        return drs

    borrowing: Dict[str, int] = {}
    borrowed_frs: List[FlavorResource] = []
    for fr, quota in node.subtree_quota.items():
        amount_borrowed = sat_sub(
            sat_add(wl_req.get(fr, 0), node.usage.get(fr, 0)), quota
        )
        if amount_borrowed > 0:
            borrowing[fr.resource] = sat_add(
                borrowing.get(fr.resource, 0), amount_borrowed
            )
            borrowed_frs.append(fr)
    if not borrowing:
        return drs
    drs.borrowing = True
    drs.borrowed_frs = borrowed_frs

    assert node.parent is not None
    lendable = calculate_lendable(node.parent)
    for r_name, borrowed in borrowing.items():
        lr = lendable.get(r_name, 0)
        if lr > 0:
            ratio = float(borrowed) * 1000.0 / float(lr)
            if ratio > drs.unweighted_ratio or (
                ratio == drs.unweighted_ratio
                and r_name < drs.dominant_resource
            ):
                drs.unweighted_ratio = ratio
                drs.dominant_resource = r_name
    return drs
