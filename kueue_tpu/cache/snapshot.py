"""Scheduling snapshot: copy-on-cycle view of admitted usage.

Behavioral surface: reference pkg/cache/scheduler/snapshot.go and
clusterqueue_snapshot.go. The snapshot owns a QuotaNode tree (exact
hierarchical quota math) plus per-CQ workload maps; AddWorkload /
RemoveWorkload / SimulateWorkloadRemoval are the scheduler's transaction
primitives for preemption simulation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Set

from kueue_tpu.api.types import ClusterQueue, Cohort, ResourceFlavor, ResourceQuota
from kueue_tpu.cache.resource_node import (
    DRS,
    QuotaCell,
    QuotaNode,
    dominant_resource_share,
    update_tree,
)
from kueue_tpu.core.resources import FlavorResource, FlavorResourceQuantities
from kueue_tpu.core.workload_info import WorkloadInfo


class ClusterQueueSnapshot:
    """reference clusterqueue_snapshot.go."""

    def __init__(self, spec: ClusterQueue, node: QuotaNode) -> None:
        self.spec = spec
        self.node = node
        self.workloads: Dict[str, WorkloadInfo] = {}
        self.allocatable_generation = 0

    # -- identity / topology ------------------------------------------------

    @property
    def name(self) -> str:
        return self.spec.name

    def has_parent(self) -> bool:
        return self.node.parent is not None

    def parent(self) -> Optional[QuotaNode]:
        return self.node.parent

    def path_parent_to_root(self) -> Iterator[QuotaNode]:
        node = self.node.parent
        while node is not None:
            yield node
            node = node.parent

    # -- quota math (delegates to the exact QuotaNode engine) ---------------

    def available(self, fr: FlavorResource) -> int:
        return self.node.available(fr)

    def potential_available(self, fr: FlavorResource) -> int:
        return self.node.potential_available(fr)

    def borrowing_with(self, fr: FlavorResource, val: int) -> bool:
        return self.node.borrowing_with(fr, val)

    def borrowing(self, fr: FlavorResource) -> bool:
        return self.node.borrowing_with(fr, 0)

    def quota_for(self, fr: FlavorResource) -> QuotaCell:
        return self.node.quotas.get(fr, QuotaCell())

    def rg_by_resource(self, resource: str):
        for rg in self.spec.resource_groups:
            if resource in rg.covered_resources:
                return rg
        return None

    def usage_for(self, fr: FlavorResource) -> int:
        return self.node.usage.get(fr, 0)

    def add_usage(self, usage: FlavorResourceQuantities,
                  bump: bool = True) -> None:
        for fr, v in usage.items():
            self.node.add_usage(fr, v, bump)

    def remove_usage(self, usage: FlavorResourceQuantities,
                     bump: bool = True) -> None:
        for fr, v in usage.items():
            self.node.remove_usage(fr, v, bump)

    def simulate_usage_addition(self, usage: FlavorResourceQuantities) -> Callable[[], None]:
        """Temporary what-if mutation: reverted by the returned closure,
        so it leaves ``usage_gen`` untouched (net change is zero)."""
        self.add_usage(usage, bump=False)
        return lambda: self.remove_usage(usage, bump=False)

    def simulate_usage_removal(self, usage: FlavorResourceQuantities) -> Callable[[], None]:
        self.remove_usage(usage, bump=False)
        return lambda: self.add_usage(usage, bump=False)

    def fits(self, usage: FlavorResourceQuantities) -> bool:
        return all(v <= self.available(fr) for fr, v in usage.items())

    def dominant_resource_share(
        self, wl_req: Optional[FlavorResourceQuantities] = None
    ) -> DRS:
        return dominant_resource_share(self.node, wl_req or {})


class Snapshot:
    """reference snapshot.go:161. Built fresh each scheduling cycle."""

    def __init__(self) -> None:
        self.cluster_queues: Dict[str, ClusterQueueSnapshot] = {}
        self.cohorts: Dict[str, QuotaNode] = {}
        self.resource_flavors: Dict[str, ResourceFlavor] = {}
        self.roots: List[QuotaNode] = []
        self.inactive_cluster_queues: Set[str] = set()
        # flavor name -> TASFlavorSnapshot (reference tas_flavor_snapshot.go)
        self.tas_flavors: Dict[str, object] = {}
        # Columnar workload plane (cache/columns.py) shared by reference
        # from the owning Cache; None for synthetically built snapshots,
        # which then take the row-wise encode path.
        self.workload_columns: Optional[object] = None

    def cluster_queue(self, name: str) -> ClusterQueueSnapshot:
        return self.cluster_queues[name]

    def cqs_under_root(self, root) -> List[ClusterQueueSnapshot]:
        """CQs grouped by cohort-tree root, memoized for the snapshot's
        lifetime (tree structure is fixed within a cycle): preemption
        candidate discovery is root-scoped (preemption.go:592) and must
        not rescan every CQ in the fleet per preemptor."""
        by_root = getattr(self, "_cqs_by_root", None)
        if by_root is None:
            by_root = {}
            for cq in self.cluster_queues.values():
                by_root.setdefault(id(cq.node.root()), []).append(cq)
            self._cqs_by_root = by_root
        return by_root.get(id(root), [])

    def cq_by_node(self) -> Dict[str, "ClusterQueueSnapshot"]:
        """Node-name -> CQ snapshot, memoized per snapshot lifetime (the
        other structural memo beside cqs_under_root): candidate
        collection resolves tree leaves back to CQ snapshots per
        preemptor and must not rebuild an O(fleet) map each time."""
        memo = getattr(self, "_cq_by_node", None)
        if memo is None:
            memo = {c.node.name: c for c in self.cluster_queues.values()}
            self._cq_by_node = memo
        return memo

    def add_workload(self, info: WorkloadInfo, bump: bool = True) -> None:
        cq = self.cluster_queues[info.cluster_queue]
        cq.workloads[info.key] = info
        cq.add_usage(info.usage(), bump)
        for flavor, leaf_usage in info.tas_usage().items():
            tas = self.tas_flavors.get(flavor)
            if tas is not None:
                for leaf_id, reqs in leaf_usage.items():
                    tas.add_usage(leaf_id, reqs)

    def remove_workload(self, info: WorkloadInfo, bump: bool = True) -> None:
        cq = self.cluster_queues[info.cluster_queue]
        cq.workloads.pop(info.key, None)
        cq.remove_usage(info.usage(), bump)
        for flavor, leaf_usage in info.tas_usage().items():
            tas = self.tas_flavors.get(flavor)
            if tas is not None:
                for leaf_id, reqs in leaf_usage.items():
                    tas.remove_usage(leaf_id, reqs)

    def simulate_workload_removal(
        self, infos: Iterable[WorkloadInfo]
    ) -> Callable[[], None]:
        """reference snapshot.go:77 — the preemption oracle's transaction.
        Gen-neutral (bump=False): the simulate/revert pair nets to zero
        usage, so it must not invalidate ``usage_gen``-keyed DRS caches."""
        infos = list(infos)
        for info in infos:
            self.remove_workload(info, bump=False)

        def revert() -> None:
            for info in infos:
                self.add_workload(info, bump=False)

        return revert


def build_quota_tree(
    cohorts: Iterable[Cohort], cluster_queues: Iterable[ClusterQueue]
) -> Dict[str, QuotaNode]:
    """Construct QuotaNodes for the cohort forest + CQ leaves, link parents,
    and fill quota cells from the specs. Returns name->node (CQs and cohorts
    share the namespace the same way the reference hierarchy.Manager does)."""
    nodes: Dict[str, QuotaNode] = {}

    def cohort_node(name: str) -> QuotaNode:
        if name not in nodes:
            nodes[name] = QuotaNode(name)
        return nodes[name]

    for cohort in cohorts:
        node = cohort_node(cohort.name)
        for fq in cohort.quotas:
            for res, q in fq.resources.items():
                node.quotas[FlavorResource(fq.name, res)] = QuotaCell(
                    q.nominal, q.borrowing_limit, q.lending_limit
                )
        if cohort.fair_sharing is not None:
            # nil weight defaults to 1 (reference FairSharing.Weight
            # *Quantity, fair_sharing.go dominantResourceShare).
            node.fair_weight = (
                1.0 if cohort.fair_sharing.weight is None
                else cohort.fair_sharing.weight
            )
        if cohort.parent:
            parent = cohort_node(cohort.parent)
            node.parent = parent
            parent.children.append(node)

    for cq in cluster_queues:
        node = QuotaNode(cq.name, is_cq=True)
        nodes[cq.name] = node
        for rg in cq.resource_groups:
            for fq in rg.flavors:
                for res, q in fq.resources.items():
                    node.quotas[FlavorResource(fq.name, res)] = QuotaCell(
                        q.nominal, q.borrowing_limit, q.lending_limit
                    )
        if cq.fair_sharing is not None:
            node.fair_weight = (
                1.0 if cq.fair_sharing.weight is None
                else cq.fair_sharing.weight
            )
        if cq.cohort:
            parent = cohort_node(cq.cohort)
            node.parent = parent
            parent.children.append(node)

    return nodes


def has_cycle(nodes: Dict[str, QuotaNode]) -> bool:
    """Cycle detection over parent pointers (reference
    pkg/cache/hierarchy/cycle.go)."""
    for start in nodes.values():
        seen = set()
        node: Optional[QuotaNode] = start
        while node is not None:
            if id(node) in seen:
                return True
            seen.add(id(node))
            node = node.parent
    return False
