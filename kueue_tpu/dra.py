"""DRA: ResourceSlice capacity model.

Behavioral surface: reference pkg/dra/{resourceslice_cache,mapper,counters,
capacity}.go — DeviceClassMappings may carry *sources* that derive the
quota charge of a device request from driver-published ResourceSlices
instead of whole-device counting:

  * counter source: charge = max over matching devices of the named
    counter's consumption, times the requested device count
    (counters.go:328 computeCounterCharges);
  * capacity source: charge = max over matching devices of the named
    capacity dimension (explicit claim request taking precedence), times
    the count (capacity.go computeCapacityCharge);
  * no sources: whole-device counting (one logical unit per device).

Device selection is the idiomatic analog of the reference's CEL device
selectors: a flat attribute-equality match on the device's published
attributes. Insufficient matching devices is a cluster-state error
(retryable in the reference; surfaced as a ValueError here).

ResourceSlices whose ``pool`` names a fleet Node also feed that node's TAS
leaf capacity (the reference counts DRA devices into TAS leaf domains via
the node's slices).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Device:
    """One device in a ResourceSlice (reference resourcev1.Device)."""

    name: str
    attributes: Dict[str, object] = field(default_factory=dict)
    capacity: Dict[str, int] = field(default_factory=dict)
    # Flattened consumesCounters: counter name -> consumption.
    counters: Dict[str, int] = field(default_factory=dict)


@dataclass
class ResourceSlice:
    """reference resourcev1.ResourceSlice (driver-published inventory)."""

    name: str
    driver: str = ""
    pool: str = ""  # commonly the node name
    devices: List[Device] = field(default_factory=list)


@dataclass
class CounterSource:
    """reference configuration DeviceClassMapping counter source."""

    driver: str
    name: str  # counter name
    selector: Dict[str, object] = field(default_factory=dict)


@dataclass
class CapacitySource:
    """reference configuration DeviceClassMapping capacity source."""

    driver: str
    resource_name: str  # capacity dimension on the device
    selector: Dict[str, object] = field(default_factory=dict)


def _device_matches(dev: Device, selector: Dict[str, object]) -> bool:
    return all(dev.attributes.get(k) == v for k, v in selector.items())


def match_devices(
    slices: List[ResourceSlice], driver: str, selector: Dict[str, object]
) -> List[Device]:
    """matchDevicesForSource: list the driver's slices, filter devices by
    the selector."""
    out: List[Device] = []
    for s in slices:
        if driver and s.driver != driver:
            continue
        for dev in s.devices:
            if _device_matches(dev, selector):
                out.append(dev)
    return out


def counter_charge(
    slices: List[ResourceSlice], src: CounterSource, count: int
) -> int:
    """computeCounterCharges (counters.go:328): max matching-device counter
    consumption x count; insufficient devices or no counter entry raise."""
    matched = match_devices(slices, src.driver, src.selector)
    if len(matched) < count:
        raise ValueError(
            f"insufficient matching devices for counter driver "
            f"{src.driver!r}: {len(matched)} device(s) match but "
            f"{count} requested"
        )
    best: Optional[int] = None
    for dev in matched:
        v = dev.counters.get(src.name)
        if v is not None and (best is None or v > best):
            best = v
    if best is None:
        raise ValueError(
            f"matched devices have no consumesCounters entry for counter "
            f"{src.name!r}"
        )
    return max(best, 0) * count


def capacity_charge(
    slices: List[ResourceSlice], src: CapacitySource, count: int,
    explicit_request: Optional[int] = None,
) -> int:
    """computeCapacityCharge (capacity.go): max matching-device capacity in
    the named dimension (explicit claim request wins when given) x count."""
    matched = match_devices(slices, src.driver, src.selector)
    if len(matched) < count:
        raise ValueError(
            f"insufficient matching devices for capacity driver "
            f"{src.driver!r}: {len(matched)} device(s) match but "
            f"{count} requested"
        )
    best: Optional[int] = None
    for dev in matched:
        cap = dev.capacity.get(src.resource_name)
        if cap is None:
            continue
        v = explicit_request if explicit_request is not None else cap
        if best is None or v > best:
            best = v
    if best is None:
        raise ValueError(
            f"matched devices have no capacity dimension "
            f"{src.resource_name!r}"
        )
    return max(best, 0) * count


def charges_for_request(
    slices: List[ResourceSlice], mapping, count: int
) -> int:
    """Quota charge of one device-class request under a mapping
    (mapper.go + counters.go + capacity.go). Whole-device counting when the
    mapping has no sources."""
    sources = getattr(mapping, "sources", None) or []
    if not sources:
        return count
    total = 0
    for src in sources:
        if isinstance(src, CounterSource):
            total += counter_charge(slices, src, count)
        else:
            total += capacity_charge(slices, src, count)
    return total


def node_device_counts(
    slices: List[ResourceSlice], mappings
) -> Dict[str, Dict[str, int]]:
    """Per-node logical-resource device counts: slices whose pool names a
    node contribute one unit per mapped device (TAS leaf capacity feed)."""
    by_class: Dict[str, object] = {}
    for m in mappings:
        for dc in m.device_class_names:
            by_class.setdefault(dc, m)
    out: Dict[str, Dict[str, int]] = {}
    for s in slices:
        if not s.pool:
            continue
        for dev in s.devices:
            dc = dev.attributes.get("deviceClass")
            m = by_class.get(dc) if dc else None
            if m is None:
                continue
            dst = out.setdefault(s.pool, {})
            dst[m.name] = dst.get(m.name, 0) + 1
    return out
