"""DeviceScheduler: the scheduling loop with the batched TPU cycle.

Drives the same control plane as kueue_tpu.scheduler.Scheduler (same cache,
queues, eviction lifecycle) but executes each cycle's nomination + admission
with the compiled batched kernel (kueue_tpu/models/batch_scheduler.py).
Workloads outside the dense fast path — or needing the preemption oracle —
fall back to the host-exact path within the same loop.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from kueue_tpu.api.constants import (
    COND_ADMITTED,
    COND_QUOTA_RESERVED,
    CheckState,
    RequeueReason,
)
from kueue_tpu.api.types import Admission, AdmissionCheckState, PodSetAssignment
from kueue_tpu.cache.cache import Cache
from kueue_tpu.core.workload_info import (
    AssignmentClusterQueueState,
    WorkloadInfo,
    set_condition,
)
from kueue_tpu.metrics import tracing
from kueue_tpu.models import batch_scheduler, buckets
from kueue_tpu.models.arena import CycleArena, TileCarry
from kueue_tpu.models.encode import encode_cycle, plan_tiles, plane_nbytes
from kueue_tpu.obs import costs
from kueue_tpu.obs import recorder as flight
from kueue_tpu.perf import compile_cache
from kueue_tpu.queue.manager import QueueManager
from kueue_tpu.scheduler.scheduler import CycleResult, Scheduler
from kueue_tpu.utils import faults
from kueue_tpu.utils.breaker import CircuitBreaker


class PlaneValidationError(ValueError):
    """A device readback plane failed a cheap structural invariant.

    Raised by :meth:`DeviceScheduler._validate_planes` BEFORE any
    admission from the cycle is applied, so a corrupted readback (the
    threat model of ``faults.DEVICE_READBACK`` corrupt rules) can never
    mutate the cache — the cycle reroutes through the host-exact path.
    """

    def __init__(self, check: str, detail: str = "") -> None:
        self.check = check
        super().__init__(f"plane validation failed [{check}]: {detail}")


class FixedpointRoundsError(RuntimeError):
    """The fixed-point kernel hit ``max_rounds`` before every head
    decided.

    The kernel's bounds are conservative while undecided, so a truncated
    run could leave heads stuck in their initial "undecided" outcome
    (OUT_NOFIT plane values that the full run would have admitted).
    Raised by :meth:`DeviceScheduler._read_planes` BEFORE any admission
    from the cycle is applied; the containment path reroutes the whole
    cycle through the host-exact scheduler
    (``solver_fallback_cycles_total{reason="fixedpoint_rounds"}``).
    """


class DeviceScheduler:
    """Hybrid device/host scheduler."""

    # Cycles the head count must fit the next-smaller padding bucket
    # before the W axis actually shrinks (see _pick_bucket).
    _SHRINK_PATIENCE = 4

    # tile_width="auto" thresholds: cycles at or below _TILE_AUTO_MIN
    # heads keep the monolithic dispatch (the measured regime up to the
    # 50k flagship); past it the head set streams through the device in
    # _TILE_AUTO_WIDTH-row tiles, bounding the materialized w_*/s_*
    # planes regardless of backlog width (see _schedule_tiled and
    # docs/perf.md "Scaling beyond 50k").
    _TILE_AUTO_MIN = 65536
    _TILE_AUTO_WIDTH = 8192

    def __init__(
        self,
        cache: Cache,
        queues: QueueManager,
        fair_sharing: bool = False,
        clock: Callable[[], float] = time.monotonic,
        use_arena: bool = True,
        verify_arena: bool = False,
        containment: bool = True,
        breaker_threshold: int = 3,
        breaker_backoff_s: float = 1.0,
        breaker_max_backoff_s: float = 60.0,
        device_kernel: str = "scan",
        fixedpoint_max_rounds: int = 64,
        auto_cpu_kernel: str = "scan",
        pipeline_cycles: str = "off",
        pipeline_patch_limit: int = 64,
        tile_width="auto",
    ) -> None:
        self.cache = cache
        self.queues = queues
        self.fair_sharing = fair_sharing
        self.clock = clock
        # Host-exact scheduler reused for fallback entries and for the
        # eviction lifecycle.
        self.host = Scheduler(cache, queues, fair_sharing=fair_sharing,
                              clock=clock)
        self.device_time_s = 0.0
        self.cycles = 0
        # Admission-kernel selection (see docs/perf.md coverage matrix):
        #   "scan"       — grouped-preempt scan always (the safe default);
        #   "fixedpoint" — pure fixed-point rounds whenever exact
        #                  (preemption-needing trees defer to the host);
        #   "auto"       — widest exact kernel per cycle: pure fixed-point
        #                  when no tree can preempt, the fixed-point +
        #                  residual-scan hybrid otherwise, the scan for
        #                  shapes the fixed-point kernel does not cover
        #                  (multislot / TAS / partial). Fair sharing always
        #                  uses its own tournament kernel.
        if device_kernel not in ("scan", "fixedpoint", "auto"):
            raise ValueError(
                f"device_kernel must be scan|fixedpoint|auto, "
                f"got {device_kernel!r}"
            )
        self.device_kernel = device_kernel
        self.fixedpoint_max_rounds = int(fixedpoint_max_rounds)
        # Per-platform "auto" preference: on CPU the sequential scan is
        # measured faster than fixed-point rounds (scanfloor ledger:
        # fp_speedup 0.42x on the single-core box), so auto keeps the
        # scan there unless the cycle's scan bound is long or this
        # override forces the fixed point (see _fp_auto_ok).
        if auto_cpu_kernel not in ("scan", "fixedpoint"):
            raise ValueError(
                f"auto_cpu_kernel must be scan|fixedpoint, "
                f"got {auto_cpu_kernel!r}"
            )
        self.auto_cpu_kernel = auto_cpu_kernel
        # (reason, s_resid) of the most recent auto-kernel decision —
        # suffixed onto the flight-recorder kernel field.
        self._auto_choice: Tuple[str, int] = ("", 0)
        # Rounds the most recent fixed-point dispatch took (None when the
        # last cycle used a scan kernel) — cost-ledger lane + diagnostics.
        self._last_fp_rounds: Optional[int] = None
        # Conflict rounds the last batched TAS slot pass ran (None when
        # the cycle carried no multi-podset TAS planes) — suffixed onto
        # the flight-recorder kernel field as [slot-fp]/[slot-scan:r].
        self._last_slot_rounds: Optional[int] = None
        # Incremental cycle encoding: device-resident snapshot arena with
        # row-level delta updates (models/arena.py). verify_arena re-encodes
        # from scratch every incremental cycle and asserts bit-identity.
        self._arena = (
            CycleArena(cache, fair_sharing=fair_sharing, verify=verify_arena)
            if use_arena else None
        )
        # Incremental encode component cache (shared with the arena when
        # enabled): admitted-state tensors reused across cycles while the
        # relevant generations are unchanged.
        self._adm_cache: Dict = (
            self._arena.component_cache if self._arena is not None else {}
        )
        # Padding-bucket hysteresis state (the unified ladder from
        # models/buckets.py — the same rungs the whatif engine pads to,
        # so both paths share one executable per logical shape).
        self._w_ladder = buckets.BucketLadder(
            patience=self._SHRINK_PATIENCE
        )
        # Separate ladder for tiled cycles: tile widths are near-constant
        # (the planner packs to ``tile_width``) but the last tile of a
        # cycle is ragged — without hysteresis every cycle's tail tile
        # oscillated executables across a shrink/grow churn window
        # (tiles previously bypassed the ladder entirely and bucketed
        # exactly).
        self._tile_ladder = buckets.BucketLadder(
            patience=self._SHRINK_PATIENCE
        )
        # Fault containment: device-path exceptions and invalid readback
        # planes route the cycle through the host-exact path instead of
        # crashing the loop or applying a wrong admission; K consecutive
        # device failures trip the breaker to all-host scheduling with
        # exponential-backoff re-probes (utils/breaker.py). The arena is
        # invalidated on every device failure — stale device state after
        # a failure must force a full re-capture.
        self.containment = containment
        self._breaker = CircuitBreaker(
            threshold=breaker_threshold,
            backoff_s=breaker_backoff_s,
            max_backoff_s=breaker_max_backoff_s,
            clock=clock,
        )
        self.fault_fallback_cycles = 0
        self.last_fault: Optional[Tuple[str, str]] = None
        # Pipelined admission cycles: while cycle N executes on device,
        # speculatively stage cycle N+1's host encode from the pre-apply
        # state (arena.begin_speculation) inside the overlap window; the
        # next encode patches in the dirty rows the apply produced. Apply
        # stays FIFO-at-boundary, so results are bit-identical to the
        # serialized loop. "auto" stays off for call-per-cycle usage and
        # is switched on by the service loop (set_pipeline).
        if pipeline_cycles not in ("on", "off", "auto"):
            raise ValueError(
                f"pipeline_cycles must be on|off|auto, "
                f"got {pipeline_cycles!r}"
            )
        if pipeline_cycles == "on" and self._arena is None:
            raise ValueError(
                "pipeline_cycles='on' requires the arena (use_arena=True)"
            )
        self.pipeline_cycles = pipeline_cycles
        self.pipeline_patch_limit = int(pipeline_patch_limit)
        self._pipeline_on = pipeline_cycles == "on"
        self._pipeline_skip_next = False
        self.pipeline_speculated = 0
        self.pipeline_overlap_s = 0.0
        if self._arena is not None:
            self._arena.pipeline_patch_limit = self.pipeline_patch_limit
        # Tiled streaming admission: past-the-flagship cycles stream the
        # head set through the device in fixed-width W-tiles instead of
        # one monolithic plane (see _schedule_tiled). "auto" tiles only
        # above _TILE_AUTO_MIN heads; "off" never tiles; an explicit int
        # tiles whenever the head count exceeds it.
        if tile_width not in ("auto", "off"):
            try:
                # Through str() so bools ("True") and non-integral floats
                # ("2.5") are rejected rather than silently coerced.
                tile_width = int(str(tile_width))
            except (TypeError, ValueError):
                raise ValueError(
                    f"tile_width must be auto|off|positive int, "
                    f"got {tile_width!r}"
                )
            if tile_width <= 0:
                raise ValueError(
                    f"tile_width must be auto|off|positive int, "
                    f"got {tile_width!r}"
                )
        self.tile_width = tile_width
        # Live during a tiled cycle (plane accounting hook); the last
        # completed tiled cycle's carry stays readable for diagnostics
        # and the bench probe.
        self._tile_carry: Optional[TileCarry] = None
        self._last_tile_carry: Optional[TileCarry] = None
        # Optional what-if engine refreshed in spare time (attach_whatif).
        self._whatif = None
        self._whatif_interval_s = 30.0

    # ------------------------------------------------------------------

    @property
    def breaker_state(self) -> float:
        """The device-path breaker as a gauge (0 closed / 1 half-open /
        2 open) — surfaced in the service loop's ``health()`` document
        and ``/healthz`` so liveness probes see host-fallback mode."""
        return self._breaker.gauge_value

    def health(self) -> dict:
        """Lock-free device-path health summary for liveness probes."""
        fault = self.last_fault
        doc = {
            "breakerState": self._breaker.gauge_value,
            "faultFallbackCycles": self.fault_fallback_cycles,
            "lastFault": list(fault) if fault is not None else None,
        }
        if self.pipeline_cycles != "off":
            doc["pipeline"] = self.pipeline_health()
        return doc

    def set_pipeline(self, enabled: bool) -> None:
        """Resolve ``pipelineCycles: auto``: the service loop enables the
        pipeline when it starts driving sustained cycles (call-per-cycle
        usage stays serialized); explicit "on"/"off" are unaffected."""
        if self.pipeline_cycles == "auto":
            self._pipeline_on = bool(enabled) and self._arena is not None

    def pipeline_backpressure_hint(self, quota_ops_pending: bool) -> None:
        """Service-loop backpressure interaction: when the drained ingest
        batch holds quota-affecting ops, the next speculation would be a
        guaranteed quota-generation abort — skip staging it instead of
        burning the overlap window."""
        if quota_ops_pending:
            self._pipeline_skip_next = True

    def pipeline_health(self) -> dict:
        """Lock-free pipeline summary for service health and the bench."""
        st = (
            dict(self._arena.pipeline_stats)
            if self._arena is not None else {}
        )
        aborts = {
            k.split(":", 1)[1]: v for k, v in st.items()
            if k.startswith("abort:")
        }
        dev = self.device_time_s
        occ = (
            100.0 * min(self.pipeline_overlap_s, dev) / dev
            if dev > 0 else 0.0
        )
        return {
            "mode": self.pipeline_cycles,
            "enabled": self._pipeline_on,
            "speculated": st.get("staged", 0),
            "consumed": st.get("consumed", 0),
            "reusedRows": st.get("reused_rows", 0),
            "aborts": aborts,
            "abortTotal": sum(aborts.values()),
            "overlapS": round(self.pipeline_overlap_s, 6),
            "overlapOccupancyPct": round(occ, 3),
        }

    @property
    def use_fixedpoint(self) -> bool:
        """Legacy boolean view of :attr:`device_kernel` (pre-config-layer
        API): True when a fixed-point mode is selected."""
        return self.device_kernel in ("fixedpoint", "auto")

    @use_fixedpoint.setter
    def use_fixedpoint(self, value: bool) -> None:
        self.device_kernel = "fixedpoint" if value else "scan"

    # ------------------------------------------------------------------

    def attach_whatif(self, engine, refresh_interval_s: float = 30.0):
        """Attach a WhatIfEngine (whatif/engine.py) whose cached base ETA
        forecast is refreshed opportunistically between admission cycles:
        only when a cycle finds no heads (the loop is quiescent), at most
        every ``refresh_interval_s``. Forecast faults never reach the
        admission loop — the engine contains them behind its own breaker."""
        self._whatif = engine
        self._whatif_interval_s = refresh_interval_s
        return engine

    def prewarm(self, max_heads: int = 16, background: bool = False,
                aot: bool = True):
        """Compile the admission-cycle entry points for every W bucket
        of the ladder covering ``max_heads`` (models/buckets.py), so the
        first real cycles hit warm executables instead of multi-second
        jits. Encoding the live snapshot with zero heads reproduces the
        exact compile shape of a real cycle at each bucket (padding rows
        are inert); with a persistent compile cache configured
        (perf/compile_cache.configure) the compiles also land on disk
        for the next process, and ``aot=True`` additionally serializes
        standalone executables into the AOT store.

        ``background=True`` runs the warmup in a daemon thread and
        returns it; admission cycles proceed meanwhile (a cycle that
        races ahead of the warmup just compiles its own shape first).
        Synchronous calls return ``{bucket: seconds}``; failures set
        ``solver_prewarm_state`` to 3 and are contained (a broken warmup
        must never stop the service from admitting)."""
        if background:
            import threading

            t = threading.Thread(
                target=self._prewarm_sync, args=(max_heads, aot),
                name="kueue-tpu-prewarm", daemon=True,
            )
            t.start()
            return t
        return self._prewarm_sync(max_heads, aot)

    def _prewarm_fp_wanted(self) -> bool:
        """Whether prewarm should compile the fixed-point entries: skip
        warms that "auto" would never dispatch on this backend (CPU
        prefers the scan unless overridden; the long-scan escape hatch
        compiles on first use like any bucket growth)."""
        if self.device_kernel == "fixedpoint":
            return True
        if self.device_kernel != "auto":
            return False
        return (
            jax.default_backend() != "cpu"
            or self.auto_cpu_kernel == "fixedpoint"
        )

    def _tile_prewarm_bucket(self, max_heads: int, rungs) -> Optional[int]:
        """Bucket of the tiled prewarm rung, or None when the ladder
        already covers it. Tiles dispatch at ``bucket_for(tile rows)``,
        which the max_heads ladder may not include: an explicit tile
        width always warms its own bucket; "auto" warms the
        ``_TILE_AUTO_WIDTH`` bucket only when the caller declares a
        ``max_heads`` past the auto threshold (warming an 8192-row shape
        for services that never tile would waste minutes of compile)."""
        if self.tile_width == "off":
            return None
        if self.tile_width == "auto":
            if max_heads <= self._TILE_AUTO_MIN:
                return None
            b = buckets.bucket_for(self._TILE_AUTO_WIDTH)
        else:
            b = buckets.bucket_for(int(self.tile_width))
        return None if b in rungs else b

    def _synth_slot_heads(self, snapshot):
        """Synthetic multi-podset TAS heads for the slot-pass prewarm
        rung. A zero-head encode carries no per-slot TAS planes, so the
        batched slot pass's compile shapes can never warm from the live
        snapshot alone; a two-podset gang against the first TAS-covered
        CQ lights up encode's slot layout at the floor S bucket — the
        shape every live small-slot-count cycle dispatches."""
        from kueue_tpu.api.types import PodSet, TopologyRequest, Workload
        from kueue_tpu.core.workload_info import WorkloadInfo

        for cq in snapshot.cluster_queues.values():
            for rg in cq.spec.resource_groups:
                for fq in rg.flavors:
                    tas = snapshot.tas_flavors.get(fq.name)
                    if tas is None or not rg.covered_resources:
                        continue
                    res = rg.covered_resources[0]
                    level = tas.level_keys[-1]
                    wl = Workload(
                        name="__prewarm_slot__",
                        pod_sets=[
                            PodSet(
                                name=f"ps{i}", count=1,
                                requests={res: 1},
                                topology_request=TopologyRequest(
                                    required_level=level
                                ),
                            )
                            for i in range(2)
                        ],
                    )
                    return [WorkloadInfo(wl, cq.name)]
        return []

    def _prewarm_sync(self, max_heads: int, aot: bool):
        if tracing.ENABLED:
            tracing.set_gauge("solver_prewarm_state", 1)  # running
        timings: Dict[int, float] = {}
        try:
            snapshot = self.cache.snapshot()
            if self.fair_sharing:
                from kueue_tpu.models.fair_kernel import (
                    fair_cycle_preempt_for,
                )

                # Upper-bound the tournament depth from the snapshot
                # itself (every CQ under a root could hold a head);
                # encode's per-cycle bound never exceeds it.
                roots: Dict[int, int] = {}
                for cqs in snapshot.cluster_queues.values():
                    rid = id(cqs.node.root())
                    roots[rid] = roots.get(rid, 0) + 1
                s_bound = buckets.pow2_bucket(
                    max(roots.values(), default=1), floor=4
                )
            rungs = list(buckets.ladder(max_heads))
            tile_b = self._tile_prewarm_bucket(max_heads, rungs)
            if tile_b is not None:
                rungs.append(tile_b)
            for bucket in rungs:
                arrays, idx = encode_cycle(
                    snapshot, [], snapshot.resource_flavors,
                    w_pad=bucket, fair_sharing=self.fair_sharing,
                    preempt=True,
                    fair_strategies=self.host.preemptor.fair_strategies,
                )
                if self.fair_sharing:
                    timings[bucket] = compile_cache.prewarm_entry(
                        "cycle_fair_preempt",
                        fair_cycle_preempt_for(s_bound),
                        (arrays, idx.admitted_arrays),
                        static=("s_max", s_bound), aot=aot,
                    )
                    if self._prewarm_fp_wanted():
                        from kueue_tpu.models.fair_fixedpoint import (
                            fair_fixedpoint_cycle_for,
                        )

                        timings[bucket] += compile_cache.prewarm_entry(
                            "cycle_fair_fixedpoint",
                            fair_fixedpoint_cycle_for(s_bound),
                            (arrays, idx.admitted_arrays),
                            static=("s_max", s_bound), aot=aot,
                        )
                else:
                    timings[bucket] = compile_cache.prewarm_entry(
                        "cycle_grouped_preempt",
                        batch_scheduler.cycle_grouped_preempt,
                        (arrays, idx.group_arrays, idx.admitted_arrays),
                        aot=aot,
                    )
                    if self._prewarm_fp_wanted():
                        max_r = self.fixedpoint_max_rounds
                        timings[bucket] += compile_cache.prewarm_entry(
                            "cycle_fixedpoint",
                            batch_scheduler.fixedpoint_cycle_for(max_r),
                            (arrays, idx.group_arrays),
                            static=("rounds", max_r), aot=aot,
                        )
                    if self.device_kernel == "auto" \
                            and self._prewarm_fp_wanted():
                        # Hybrid: warm the residual ladder's floor rung —
                        # the common case (few preemptors per tree); deeper
                        # residuals compile on first use like any bucket
                        # growth.
                        s_b = 4
                        timings[bucket] += compile_cache.prewarm_entry(
                            "cycle_fixedpoint_hybrid",
                            batch_scheduler.fixedpoint_cycle_preempt_for(
                                s_b, max_r
                            ),
                            (arrays, idx.group_arrays, idx.admitted_arrays),
                            static=("s_resid", s_b, "rounds", max_r),
                            aot=aot,
                        )
            if tile_b is not None:
                # Name the tiled rung: the ladder rungs stay keyed by
                # bucket int, the tile-width rung (a shape the ladder
                # does not cover) is keyed "tiled" so callers and the
                # zero-compile pins can assert it warmed.
                timings["tiled"] = timings.pop(tile_b)
            if snapshot.tas_flavors:
                # Slot-pass rung: warm the batched TAS slot-placement
                # shapes with synthetic multi-podset heads (the zero-head
                # encodes above never produce the s_tas planes the pass
                # compiles against).
                slot_heads = self._synth_slot_heads(snapshot)
                if slot_heads:
                    w_b = buckets.ladder(1)[0]
                    arrays, idx = encode_cycle(
                        snapshot, slot_heads, snapshot.resource_flavors,
                        w_pad=w_b, fair_sharing=self.fair_sharing,
                        preempt=True,
                        fair_strategies=(
                            self.host.preemptor.fair_strategies
                        ),
                    )
                    if getattr(arrays, "s_tas", None) is not None:
                        if self.fair_sharing:
                            timings["slot"] = compile_cache.prewarm_entry(
                                "cycle_fair_preempt",
                                fair_cycle_preempt_for(s_bound),
                                (arrays, idx.admitted_arrays),
                                static=("s_max", s_bound), aot=aot,
                            )
                        else:
                            timings["slot"] = compile_cache.prewarm_entry(
                                "cycle_grouped_preempt",
                                batch_scheduler.cycle_grouped_preempt,
                                (arrays, idx.group_arrays,
                                 idx.admitted_arrays),
                                aot=aot,
                            )
            if tracing.ENABLED:
                tracing.set_gauge("solver_prewarm_state", 2)  # done
        except Exception as exc:
            self.last_fault = ("prewarm_error", repr(exc))
            if tracing.ENABLED:
                tracing.set_gauge("solver_prewarm_state", 3)  # failed
        return timings

    def schedule(self) -> CycleResult:
        self.cycles += 1
        start = self.clock()
        result = CycleResult()
        heads = self.queues.heads()
        result.head_keys = frozenset(h.key for h in heads)
        if not heads:
            if self._whatif is not None:
                self._whatif.maybe_refresh(self._whatif_interval_s)
            result.duration_s = self.clock() - start
            return result
        width = self._resolve_tile_width(len(heads))
        if width is not None:
            return self._schedule_tiled(list(heads), width, start, result)
        return self._schedule_heads(list(heads), start, result)

    def _resolve_tile_width(self, n_heads: int) -> Optional[int]:
        """Tile width for this cycle, or None for a monolithic dispatch.

        ``tile_width`` is "off" (never tile), "auto" (tile at
        ``_TILE_AUTO_WIDTH`` once the head count passes
        ``_TILE_AUTO_MIN`` — cycles at or below the 50k flagship keep the
        monolithic path and its measured behavior), or an explicit
        positive int (tile whenever the head count exceeds it)."""
        tw = self.tile_width
        if tw == "off":
            return None
        if tw == "auto":
            if n_heads > self._TILE_AUTO_MIN:
                return self._TILE_AUTO_WIDTH
            return None
        return int(tw) if n_heads > int(tw) else None

    def _schedule_tiled(self, heads: List[WorkloadInfo], width: int,
                        start: float, result: CycleResult) -> CycleResult:
        """Stream one cycle's heads through the device in W-tiles.

        Tiles pack whole cohort trees (encode.plan_tiles): trees are
        quota-independent and the kernels solve them independently, so a
        tile's device outcomes match the monolithic cycle's row for row.
        Trees sharing a device-encoded TAS flavor are fused into one tile
        — topology capacity is physical state shared across trees.
        The cross-tile carry is the arena itself: tile k's applies land
        as cache events, and tile k+1's ``take_snapshot`` drains them
        into row deltas, so tile k+1 encodes against tile k's post-apply
        usage and admitted set without re-capturing untouched rows.
        Per-tile containment: a faulted tile reroutes through the
        host-exact path (same as a faulted monolithic cycle) without
        invalidating settled tiles — their applies already landed."""
        try:
            if faults.ENABLED:
                faults.fire(faults.CACHE_SNAPSHOT)
            if self._arena is not None:
                snapshot = self._arena.take_snapshot()
            else:
                snapshot = self.cache.snapshot()
        except Exception as exc:
            if not self._containable(exc):
                raise
            return self._contain_cycle(
                result, heads, "snapshot_error", exc, start
            )
        tiles = plan_tiles(heads, width, snapshot)
        carry = TileCarry(width=width, tiles=len(tiles))
        self._tile_carry = carry
        self._last_tile_carry = carry
        if tracing.ENABLED:
            tracing.inc("solver_tile_cycles_total", {
                "mode": "auto" if self.tile_width == "auto" else "fixed",
            })
            tracing.set_gauge("solver_tile_width", width)
            tracing.set_gauge("solver_tiles_per_cycle", len(tiles))
        try:
            for k, tile_heads in enumerate(tiles):
                faults_before = self.fault_fallback_cycles
                self._schedule_heads(
                    tile_heads, start, result,
                    # Ladder-observed (shrink hysteresis), not an exact
                    # bucket: ragged tail tiles must not oscillate
                    # executables across a churn window.
                    bucket=self._tile_ladder.observe(len(tile_heads)),
                    tile=(k + 1, len(tiles)),
                    # Tile 0 solves against the planning snapshot; later
                    # tiles re-snapshot to drain the prior tile's applies.
                    snapshot=snapshot if k == 0 else None,
                )
                faulted = self.fault_fallback_cycles > faults_before
                carry.note_tile(len(tile_heads), faulted=faulted)
                if faulted and tracing.ENABLED:
                    tracing.inc("solver_tile_fallback_total", {
                        "reason": (
                            self.last_fault[0]
                            if self.last_fault is not None else "unknown"
                        ),
                    })
        finally:
            self._tile_carry = None
        result.duration_s = self.clock() - start
        return result

    def _schedule_heads(
        self,
        heads: List[WorkloadInfo],
        start: float,
        result: CycleResult,
        bucket: Optional[int] = None,
        tile: Optional[Tuple[int, int]] = None,
        snapshot=None,
    ) -> CycleResult:
        """One dispatch of the batched cycle over ``heads``, mutating the
        shared ``result``: the monolithic cycle calls this once with the
        full head set; the tiled mode calls it once per tile with an
        explicit bucket and a ``(k, n)`` tile tag. This is the single
        kernel dispatch site tools/check_kernel_gates.py pins — both
        modes funnel through the gate chain below."""
        if tracing.ENABLED:
            tracing.set_gauge(
                "solver_breaker_state", self._breaker.gauge_value
            )
        if not self._breaker.allow():
            # Breaker open: all-host cycle, no device work at all. The
            # arena was invalidated when the breaker tripped, so the
            # half-open probe that eventually re-enters the device path
            # re-captures from scratch.
            if tracing.ENABLED:
                tracing.inc("solver_fallback_cycles_total",
                            {"reason": "breaker_open"})
            self._merge_result(result, self._host_process(list(heads)))
            result.duration_s = self.clock() - start
            if flight.ENABLED:
                flight.capture_cycle(
                    cycle=self.cycles, ts=self.clock(), heads=len(heads),
                    bucket=0, path="breaker_open",
                    generations=(self.cache.generation,
                                 self.cache.workload_generation),
                    arena=self._arena is not None,
                    breaker_state=self._breaker.gauge_value,
                    fallback_reason="breaker_open",
                    result=result, duration_s=result.duration_s,
                )
            return result

        if snapshot is None:
            try:
                if faults.ENABLED:
                    faults.fire(faults.CACHE_SNAPSHOT)
                if self._arena is not None:
                    # Snapshot + event drain under one cache lock hold.
                    snapshot = self._arena.take_snapshot()
                else:
                    snapshot = self.cache.snapshot()
            except Exception as exc:
                if not self._containable(exc):
                    raise
                return self._contain_cycle(
                    result, heads, "snapshot_error", exc, start
                )
        if bucket is None:
            bucket = self._pick_bucket(len(heads))
        # Flight-recorder scratch: generation fingerprint pinned at
        # snapshot time (apply bumps the live counters), stage timings
        # filled in as the cycle progresses. None when recording is off —
        # the disabled path allocates nothing.
        rec_t = None
        if flight.ENABLED:
            rec_t = {
                "gen": (self.cache.generation,
                        self.cache.workload_generation),
                "t0": self.clock(),
            }
        if tracing.ENABLED:
            # Report the bucket actually used (hysteresis holds included)
            # so padding waste stays honest on the shrink path.
            tracing.set_gauge("solver_batch_size", bucket)
            tracing.set_gauge(
                "solver_padding_waste_pct",
                100.0 * (bucket - len(heads)) / bucket,
            )
        delay_fn = (
            lambda cqs, info: self.host._delay_tas(cqs, info)
            or self.host._has_multikueue_check(cqs)
        )
        try:
            if self._arena is not None:
                arrays, idx = self._arena.encode(
                    snapshot, heads, snapshot.resource_flavors, w_pad=bucket,
                    preempt=True, delay_tas_fn=delay_fn,
                    fair_strategies=self.host.preemptor.fair_strategies,
                )
            else:
                arrays, idx = encode_cycle(
                    snapshot, heads, snapshot.resource_flavors, w_pad=bucket,
                    fair_sharing=self.fair_sharing, preempt=True,
                    delay_tas_fn=delay_fn,
                    fair_strategies=self.host.preemptor.fair_strategies,
                    admitted_cache=self._adm_cache,
                    admitted_key=(
                        self.cache.generation, self.cache.workload_generation,
                        self.fair_sharing,
                    ),
                )
        except Exception as exc:
            if not self._containable(exc):
                raise
            return self._contain_cycle(
                result, heads, "encode_error", exc, start
            )
        if rec_t is not None:
            rec_t["encode_s"] = self.clock() - rec_t.pop("t0")
        if self._tile_carry is not None:
            # The memory story of tiling: what the tile actually
            # materialized, vs the monolithic plane the full head set
            # would have needed (bench --probe tiled's headline).
            self._tile_carry.note_plane(plane_nbytes(arrays))

        # Trees with an encode-fallback entry route through the host
        # wholesale (device rows included, see the discard comment below),
        # and that routing does not depend on device outcomes — so they can
        # be host-processed while the device solve runs, in the window
        # before the first blocking readback. Trees are quota-independent,
        # so their host admissions cannot change other trees' device
        # results.
        def _root_id(cq_name: str):
            cqs = snapshot.cluster_queues.get(cq_name)
            return id(cqs.node.root()) if cqs is not None else None

        pre_roots = set()
        for info in idx.host_fallback:
            pre_roots.add(_root_id(info.cluster_queue))
        pre_roots.discard(None)

        host_entries: List[WorkloadInfo] = []
        if not idx.workloads:
            host_entries = list(idx.host_fallback)

        fault: Optional[Tuple[str, Exception]] = None
        planes = None
        entry = "cycle_grouped_preempt"
        self._auto_choice = ("", 0)
        if idx.workloads:
            t0 = self.clock()
            out = None
            try:
                if faults.ENABLED:
                    faults.fire(faults.SOLVER_DISPATCH)
                # Default kernel: forest-grouped scan with on-device
                # classical preemption. Fair sharing swaps in the DRS
                # tournament kernel. The fixed-point kernel is exact for
                # every shape except multislot / TAS / partial (lending
                # limits included); "auto" adds the hybrid for cycles
                # needing device preemption. The gate conditions below are
                # pinned against each kernel factory's docstring markers
                # by tools/check_kernel_gates.py.
                if self.fair_sharing \
                        and self.device_kernel in ("fixedpoint", "auto") \
                        and self._fair_fp_auto_ok(arrays, idx):
                    from kueue_tpu.models.fair_fixedpoint import (
                        fair_fixedpoint_cycle_for,
                    )

                    entry = "cycle_fair_fixedpoint"
                    with tracing.span("device/cycle_fair_fixedpoint",
                                      batch=bucket):
                        out = compile_cache.dispatch(
                            "cycle_fair_fixedpoint",
                            fair_fixedpoint_cycle_for(idx.fair_s_bound),
                            arrays, idx.admitted_arrays,
                            static=("s_max", idx.fair_s_bound),
                        )
                elif self.fair_sharing:
                    from kueue_tpu.models.fair_kernel import (
                        fair_cycle_preempt_for,
                    )

                    entry = "cycle_fair_preempt"
                    with tracing.span("device/cycle_fair_preempt",
                                      batch=bucket):
                        out = compile_cache.dispatch(
                            "cycle_fair_preempt",
                            fair_cycle_preempt_for(idx.fair_s_bound),
                            arrays, idx.admitted_arrays,
                            static=("s_max", idx.fair_s_bound),
                        )
                elif self.device_kernel in ("fixedpoint", "auto") \
                        and not idx.has_partial \
                        and arrays.tas_topo is None \
                        and self._fp_auto_ok(arrays, idx):
                    max_r = self.fixedpoint_max_rounds
                    # Residual scan bound: 0 when no tree needs the
                    # sequential steps this cycle (pure fixed-point is
                    # then exact). Preempt-capable trees count only in
                    # "auto" mode — strict "fixedpoint" trades them to
                    # the host path as before — but slot-layout trees
                    # count in both (the pure rounds read only legacy
                    # planes). Computed by _fp_auto_ok alongside the
                    # platform preference.
                    s_resid = self._auto_choice[1]
                    if s_resid > 0:
                        entry = "cycle_fixedpoint_hybrid"
                        s_b = buckets.pow2_bucket(s_resid, floor=4)
                        with tracing.span("device/cycle_fixedpoint_hybrid",
                                          batch=bucket):
                            out = compile_cache.dispatch(
                                "cycle_fixedpoint_hybrid",
                                batch_scheduler.fixedpoint_cycle_preempt_for(
                                    s_b, max_r
                                ),
                                arrays, idx.group_arrays,
                                idx.admitted_arrays,
                                static=("s_resid", s_b, "rounds", max_r),
                            )
                    else:
                        entry = "cycle_fixedpoint"
                        with tracing.span("device/cycle_fixedpoint",
                                          batch=bucket):
                            out = compile_cache.dispatch(
                                "cycle_fixedpoint",
                                batch_scheduler.fixedpoint_cycle_for(max_r),
                                arrays, idx.group_arrays,
                                static=("rounds", max_r),
                            )
                else:
                    with tracing.span("device/cycle_grouped_preempt",
                                      batch=bucket):
                        out = compile_cache.dispatch(
                            "cycle_grouped_preempt",
                            batch_scheduler.cycle_grouped_preempt,
                            arrays, idx.group_arrays, idx.admitted_arrays,
                        )
            except Exception as exc:
                if not self._containable(exc):
                    raise
                fault = ("dispatch_error", exc)
            if rec_t is not None:
                rec_t["dispatch_s"] = self.clock() - t0
            # Overlap window: the kernel call above only dispatched — run
            # the pre-discarded trees' host work before the first blocking
            # read so it executes while the device solves. These host
            # results are exact and stand even if the readback below
            # fails (trees are quota-independent).
            host_dt = 0.0
            pre_entries = list(idx.host_fallback)
            if pre_roots:
                pre_entries.extend(
                    info for info in idx.workloads
                    if self._in_discarded(info, snapshot, pre_roots)
                )
            pre_done = False
            if fault is None and pre_entries:
                th0 = self.clock()
                self._merge_result(result, self._host_process(pre_entries))
                host_dt = self.clock() - th0
                pre_done = True
                if rec_t is not None:
                    rec_t["overlap_host_s"] = host_dt
            if self._pipeline_on and tile is None and fault is None:
                # Pipeline stage: while the device still solves cycle N,
                # stage cycle N+1's speculative encode from the pre-apply
                # state. Contained — a staging failure aborts only the
                # speculation, never the cycle.
                spec_dt = self._speculate_next(snapshot, heads, bucket)
                host_dt += spec_dt
                if rec_t is not None and spec_dt:
                    rec_t["speculate_s"] = spec_dt
            planes = None
            if fault is None:
                try:
                    # Blocking readback + invariant validation + TAS
                    # decode; validation runs BEFORE any admission is
                    # applied, so a corrupted plane cannot reach the cache.
                    if rec_t is not None:
                        rec_t["t_rb"] = self.clock()
                    planes = self._read_planes(out, idx)
                    if rec_t is not None:
                        rec_t["readback_s"] = (
                            self.clock() - rec_t.pop("t_rb")
                        )
                except PlaneValidationError as exc:
                    if tracing.ENABLED:
                        tracing.inc(
                            "solver_plane_validation_failures_total",
                            {"check": exc.check},
                        )
                    if not self.containment:
                        raise
                    fault = ("plane_validation", exc)
                except FixedpointRoundsError as exc:
                    if not self.containment:
                        raise
                    fault = ("fixedpoint_rounds", exc)
                except Exception as exc:
                    if not self._containable(exc):
                        raise
                    fault = ("readback_error", exc)
            if fault is not None:
                self._record_device_failure(fault[0], fault[1])
                if pre_done:
                    # The fallback trees were already host-processed in
                    # the overlap window; reprocessing would double-apply
                    # their admissions. Everything else reroutes.
                    host_entries.extend(
                        info for info in idx.workloads
                        if not (pre_roots and self._in_discarded(
                            info, snapshot, pre_roots))
                    )
                else:
                    host_entries.extend(idx.host_fallback)
                    host_entries.extend(idx.workloads)

        if idx.workloads and fault is None:
            self._breaker.record_success()
            (outcome, chosen, tried, s_flavor, s_pmode, s_tried, partial,
             victims, variants, tas_assignments, leader_tas,
             slot_tas) = planes
            dt = self.clock() - t0
            self.device_time_s += dt
            if costs.ENABLED:
                # Attribute the exact wall time booked into
                # device_time_s, so ledger sums reconcile against the
                # driver's own totals; W lanes: real heads vs the padded
                # bucket the executable actually ran.
                lanes = {"W": (len(heads), bucket)}
                if self._last_fp_rounds is not None:
                    # Rounds lane: real rounds taken vs the compiled
                    # round budget — the fixed-point analogue of padding
                    # waste (unused while_loop headroom).
                    lanes["rounds"] = (
                        self._last_fp_rounds, self.fixedpoint_max_rounds
                    )
                costs.charge(entry, bucket, dt, lanes=lanes)
            if tracing.ENABLED:
                tracing.observe("solver_device_seconds", dt,
                                {"kernel": "batch_cycle"})
                tracing.observe("solver_overlap_host_seconds", host_dt)
                tracing.set_gauge(
                    "solver_overlap_occupancy_pct",
                    100.0 * min(host_dt, dt) / dt if dt > 0 else 0.0,
                )

            # In-cycle interleaving is per cohort tree: entries of one
            # tree contend for the same quota in admission order, and a
            # host-fallback entry (encode fallback or OUT_NEEDS_HOST) may
            # precede device-resolved entries in that order — or need to
            # see a device preemptor's transient in-cycle usage
            # (scheduler.go:561 adds usage for PREEMPTING entries too).
            # The device scan skips deferred entries entirely, so the
            # tree's device ordering is incomplete: discard the whole
            # tree's device outcomes and route it through the host
            # (host-exact within the tree; trees are quota-independent,
            # so other trees' device outcomes stay valid). Cycles with
            # zero fallbacks — the production configs — discard nothing.
            # Fallback trees (pre_roots) were already host-processed in
            # the overlap window; OUT_NEEDS_HOST rows discovered on
            # readback discard their tree into the post-readback batch.
            discarded_roots = set(pre_roots)
            for i, info in enumerate(idx.workloads):
                if outcome[i] == batch_scheduler.OUT_NEEDS_HOST:
                    discarded_roots.add(_root_id(info.cluster_queue))
            discarded_roots.discard(None)

            for i, info in enumerate(idx.workloads):
                oc = outcome[i]
                slots_i = idx.slots[i] if idx.slots else None
                multi = slots_i is not None and len(slots_i) > 1
                if pre_roots and \
                        self._in_discarded(info, snapshot, pre_roots):
                    continue  # handled in the overlap window
                if discarded_roots and \
                        self._in_discarded(info, snapshot, discarded_roots):
                    host_entries.append(info)
                    continue
                if oc == batch_scheduler.OUT_ADMITTED:
                    delayed_i = bool(
                        idx.delayed_tas and idx.delayed_tas[i]
                    )
                    from kueue_tpu.scheduler.flavorassigner import (
                        is_lws_group,
                    )

                    lws_group = (
                        not multi and is_lws_group(info.obj.pod_sets)
                    )
                    if multi or i in slot_tas:
                        # i in slot_tas covers single-slot off-RG0 TAS
                        # entries: encoded per-slot, decoded per-slot —
                        # they must not fall into the single-psa applier
                        # (which would drop their TopologyAssignment).
                        self._apply_admission_slots(
                            info, slots_i, s_flavor[i], s_tried[i], idx,
                            snapshot, delayed_tas=delayed_i,
                            tas_by_pid=slot_tas.get(i),
                        )
                    elif lws_group:
                        # Keyed on the GROUP SHAPE, not on decode output:
                        # a delayed first pass or a placement without a
                        # leader take must still emit BOTH podsets'
                        # assignments (the host always does).
                        self._apply_admission_lws(
                            info, idx.flavors[chosen[i]], int(tried[i]),
                            snapshot, tas_assignments.get(i),
                            leader_tas.get(i), delayed_tas=delayed_i,
                        )
                    else:
                        self._apply_admission(
                            info, idx.flavors[chosen[i]], int(tried[i]),
                            snapshot,
                            topology_assignment=tas_assignments.get(i),
                            reduced_count=(
                                int(partial[i])
                                if partial is not None and partial[i] >= 0
                                else None
                            ),
                            delayed_tas=delayed_i,
                        )
                    result.admitted.append(info.key)
                elif oc == batch_scheduler.OUT_PREEMPTING:
                    self._apply_preempting(
                        info, victims[i], variants[i], idx, int(tried[i]),
                        snapshot, result,
                        slots=slots_i if multi else None,
                        s_pmode_row=s_pmode[i] if multi else None,
                        s_tried_row=s_tried[i] if multi else None,
                    )
                elif oc == batch_scheduler.OUT_NEEDS_HOST:
                    host_entries.append(info)
                else:
                    self._apply_requeue(
                        info, int(oc), int(tried[i]), snapshot,
                        slots=slots_i if multi else None,
                        s_pmode_row=s_pmode[i] if multi else None,
                        s_tried_row=s_tried[i] if multi else None,
                    )
                    result.skipped.append(info.key)

        # Host-exact path for fallback + preemption entries, in one go.
        if host_entries:
            host_result = self._host_process(host_entries)
            result.admitted.extend(host_result.admitted)
            result.preempted.extend(host_result.preempted)
            result.preempting.extend(host_result.preempting)
            result.skipped.extend(host_result.skipped)
            result.inadmissible.extend(host_result.inadmissible)

        if self._pipeline_on:
            # Apply boundary passed: report every key this cycle mutated
            # so staged speculation rows for them are patched, not reused.
            self._pipeline_note_applied(result)

        result.duration_s = self.clock() - start
        if flight.ENABLED:
            flight.capture_cycle(
                cycle=self.cycles, ts=self.clock(), heads=len(heads),
                bucket=bucket,
                path=(
                    "fallback" if fault is not None
                    else "device" if planes is not None else "host"
                ),
                generations=(
                    rec_t["gen"] if rec_t is not None
                    else (self.cache.generation,
                          self.cache.workload_generation)
                ),
                arena=self._arena is not None,
                breaker_state=self._breaker.gauge_value,
                fallback_reason=fault[0] if fault is not None else None,
                timings=rec_t, result=result,
                duration_s=result.duration_s,
                idx=idx, planes=planes,
                kernel=(
                    (
                        entry + (
                            f"[{self._auto_choice[0]}]"
                            if self._auto_choice[0] else ""
                        ) + (
                            # Which slot path decided the cycle: one
                            # vectorized pass ([slot-fp]) or the bounded
                            # conflict scan with its round count.
                            "[slot-fp]" if self._last_slot_rounds == 0
                            else f"[slot-scan:{self._last_slot_rounds}]"
                            if self._last_slot_rounds is not None else ""
                        )
                        if planes is not None else ""
                    ) + (
                        f"[tile {tile[0]}/{tile[1]}]"
                        if tile is not None else ""
                    )
                ),
            )
        return result

    def schedule_all(self, max_cycles: int = 100000) -> int:
        cycles = 0
        prev_heads = None
        while cycles < max_cycles:
            result = self.schedule()
            cycles += 1
            if result.admitted or result.preempted:
                prev_heads = None
                continue
            if not result.head_keys or result.head_keys == prev_heads:
                break
            prev_heads = result.head_keys
        return cycles

    # ------------------------------------------------------------------

    def _pick_bucket(self, n_heads: int) -> int:
        """W padding bucket (models/buckets.py ladder) with shrink
        hysteresis. Growth is immediate (the cycle must fit); shrinking
        one rung requires the head count to fit the next-smaller bucket
        for _SHRINK_PATIENCE consecutive cycles — a count oscillating
        across a bucket boundary would otherwise recompile the cycle
        program every cycle."""
        return self._w_ladder.observe(n_heads)

    @staticmethod
    def _in_discarded(info, snapshot, discarded_roots) -> bool:
        cqs = snapshot.cluster_queues.get(info.cluster_queue)
        return cqs is not None and id(cqs.node.root()) in discarded_roots

    @staticmethod
    def _residual_scan_bound(arrays, idx, with_preempt: bool = True,
                             with_slots: bool = True) -> int:
        """Upper bound on the residual scan length the hybrid kernel
        needs for THIS cycle, host-side from already-resident encode
        arrays (no device sync).

        Two classes of cohort tree need the residual scan's sequential
        step semantics. (1) Preemption: a tree can only produce a
        P_PREEMPT_OK nomination when it has an active head on a CQ whose
        policies allow preemption at all (``~never_preempts``) AND at
        least one admitted workload to victimize. (2) Slot layout: a
        tree holding an active multi-slot / off-RG0 head
        (``~w_simple_slot``) — the fixed-point rounds read only the
        legacy single-plane fields, so those trees settle in the
        residual even without admitted workloads. The per-tree
        active-head maximum over qualifying trees bounds the sequential
        steps exactly like ``s_max`` bounds the full scan. Returns 0
        when no tree qualifies — the pure fixed-point kernel is then
        exact (preemption-needing entries would have deferred to the
        host anyway)."""
        w_cq = np.asarray(arrays.w_cq)
        act = np.asarray(arrays.w_active)
        if not act.any():
            return 0
        flat_to_group = np.asarray(idx.group_arrays.flat_to_group)
        g_w = flat_to_group[w_cq]
        n_g = int(flat_to_group.max()) + 1
        resid = np.zeros(n_g, dtype=bool)
        if with_preempt and idx.admitted:
            never = np.asarray(arrays.never_preempts)
            can_pre = act & ~never[w_cq]
            adm_active = np.asarray(idx.admitted_arrays.active)
            if can_pre.any() and adm_active.any():
                adm_groups = np.unique(
                    flat_to_group[
                        np.asarray(idx.admitted_arrays.cq)[adm_active]
                    ]
                )
                adm_mask = np.zeros(n_g, dtype=bool)
                adm_mask[adm_groups] = True
                resid[np.unique(g_w[can_pre & adm_mask[g_w]])] = True
        if with_slots and arrays.s_req is not None:
            simple = (
                np.asarray(arrays.w_simple_slot)
                if arrays.w_simple_slot is not None
                else np.zeros_like(act)
            )
            hard = act & ~simple
            if hard.any():
                resid[np.unique(g_w[hard])] = True
        if not resid.any():
            return 0
        counts = np.bincount(g_w[act], minlength=n_g)
        return int(counts[resid].max())

    # Scan-depth threshold above which CPU "auto" still takes the fixed
    # point: past this many sequential per-tree steps the parallel rounds
    # win even on a single core (the scanfloor probe tracks the floor).
    _CPU_FP_SCAN_BOUND = 64

    def _fp_auto_ok(self, arrays, idx) -> bool:
        """Per-platform kernel preference for the exact fixed-point shape
        gate (the conjunct before this one establishes exactness).

        Strict "fixedpoint" keeps the legacy behavior. "auto" prefers the
        fixed point on accelerator backends (parallel rounds beat the
        sequential scan), but on CPU the scan is measured faster
        (scanfloor ledger: fp_speedup 0.42x on the single-core box), so
        auto keeps the scan there unless the cycle's full scan bound
        exceeds ``_CPU_FP_SCAN_BOUND`` or ``auto_cpu_kernel`` forces the
        fixed point. The decision reason and the residual scan bound land
        in ``self._auto_choice`` (flight-recorder kernel suffix)."""
        if self.device_kernel != "auto":
            # Strict "fixedpoint" still needs the hybrid's residual scan
            # for slot-layout trees (the pure rounds read only the
            # legacy planes), so carry the slot-only bound.
            self._auto_choice = ("", self._residual_scan_bound(
                arrays, idx, with_preempt=False))
            return True
        s_resid = self._residual_scan_bound(arrays, idx)
        if jax.default_backend() != "cpu":
            self._auto_choice = ("auto-accel", s_resid)
            return True
        if self.auto_cpu_kernel == "fixedpoint":
            self._auto_choice = ("auto-cpu-fp", s_resid)
            return True
        if self._full_scan_bound(arrays, idx) > self._CPU_FP_SCAN_BOUND:
            self._auto_choice = ("auto-cpu-long-scan", s_resid)
            return True
        self._auto_choice = ("auto-cpu-scan", s_resid)
        return False

    def _fair_fp_auto_ok(self, arrays, idx) -> bool:
        """Platform preference for the fair fixed-point rounds, the
        mirror of :meth:`_fp_auto_ok` for fair-sharing cycles. The fair
        kernel carries its own residual scan internally (trees the
        rounds can't settle fall back to scan steps inside the jit), so
        only the decision reason lands in ``self._auto_choice`` — the
        bound stays 0.

        Same CPU story as the non-fair shape: "auto" keeps the DRS
        tournament scan on CPU unless the cycle's scan bound
        (``idx.fair_s_bound``) exceeds ``_CPU_FP_SCAN_BOUND`` or
        ``auto_cpu_kernel`` forces the fixed point."""
        if self.device_kernel != "auto":
            self._auto_choice = ("", 0)
            return True
        if jax.default_backend() != "cpu":
            self._auto_choice = ("auto-accel", 0)
            return True
        if self.auto_cpu_kernel == "fixedpoint":
            self._auto_choice = ("auto-cpu-fp", 0)
            return True
        if idx.fair_s_bound > self._CPU_FP_SCAN_BOUND:
            self._auto_choice = ("auto-cpu-long-scan", 0)
            return True
        self._auto_choice = ("auto-cpu-scan", 0)
        return False

    @staticmethod
    def _full_scan_bound(arrays, idx) -> int:
        """Sequential steps the grouped scan needs this cycle: the
        per-tree active-head maximum over ALL trees (the scan's s_max
        analogue), host-side from already-resident encode arrays."""
        act = np.asarray(arrays.w_active)
        if not act.any():
            return 0
        flat_to_group = np.asarray(idx.group_arrays.flat_to_group)
        g_w = flat_to_group[np.asarray(arrays.w_cq)[act]]
        return int(np.bincount(g_w).max())

    # -- pipelined cycles ----------------------------------------------------

    def _speculate_next(self, snapshot, heads, bucket: int) -> float:
        """Stage cycle N+1's speculative encode inside the device overlap
        window. Returns the host seconds spent (booked as pipeline
        overlap). Contained: any failure aborts only the speculation."""
        if self._arena is None:
            return 0.0
        if self._pipeline_skip_next:
            self._pipeline_skip_next = False
            return 0.0
        t0 = self.clock()
        try:
            staged = self._arena.begin_speculation(
                snapshot, heads, snapshot.resource_flavors, w_pad=bucket
            )
        except AssertionError:
            raise
        except Exception:
            self._arena._pipe_abort("speculate-error")
            staged = False
        dt = self.clock() - t0
        if staged:
            self.pipeline_speculated += 1
            self.pipeline_overlap_s += dt
            if tracing.ENABLED:
                tracing.observe("solver_pipeline_speculate_seconds", dt)
        return dt

    def _pipeline_note_applied(self, result: CycleResult) -> None:
        """Report the apply boundary's mutated keys (every processed head
        plus preemption victims) to the arena's staged buffers."""
        if self._arena is None:
            return
        keys = set(result.head_keys)
        keys.update(result.preempted)
        self._arena.note_applied(keys)

    # -- fault containment ---------------------------------------------------

    def _containable(self, exc: Exception) -> bool:
        """Verification failures (arena verify mode, kernel asserts) must
        surface — masking them behind the host fallback would hide exactly
        the bugs the differential layers exist to catch."""
        return self.containment and not isinstance(exc, AssertionError)

    @staticmethod
    def _merge_result(result: CycleResult, other: CycleResult) -> None:
        result.admitted.extend(other.admitted)
        result.preempted.extend(other.preempted)
        result.preempting.extend(other.preempting)
        result.skipped.extend(other.skipped)
        result.inadmissible.extend(other.inadmissible)

    def _record_device_failure(self, reason: str, exc: Exception) -> None:
        """Book one contained device failure: breaker accounting, arena
        invalidation (stale device state must force a full re-capture),
        and the fallback metric series."""
        self.fault_fallback_cycles += 1
        self.last_fault = (reason, repr(exc))
        if self._arena is not None:
            self._arena.invalidate(reason)
        self._breaker.record_failure()
        if tracing.ENABLED:
            tracing.inc("solver_fallback_cycles_total", {"reason": reason})
            tracing.set_gauge(
                "solver_breaker_state", self._breaker.gauge_value
            )

    def _contain_cycle(self, result: CycleResult, heads, reason: str,
                       exc: Exception, start: float) -> CycleResult:
        """Containment for failures before any device work consumed cache
        state (snapshot / encode): the whole cycle runs host-exact."""
        self._record_device_failure(reason, exc)
        self._merge_result(result, self._host_process(list(heads)))
        result.duration_s = self.clock() - start
        if flight.ENABLED:
            flight.capture_cycle(
                cycle=self.cycles, ts=self.clock(), heads=len(heads),
                bucket=0, path="contained",
                generations=(self.cache.generation,
                             self.cache.workload_generation),
                arena=self._arena is not None,
                breaker_state=self._breaker.gauge_value,
                fallback_reason=reason,
                result=result, duration_s=result.duration_s,
            )
        return result

    def _read_planes(self, out, idx):
        """Blocking device->host readback of every plane the apply loop
        consumes, validated against cheap structural invariants before the
        caller applies a single admission. Also the hook point for
        readback fault injection (``faults.DEVICE_READBACK``: raise/delay
        rules fire before the first transfer, corrupt rules rewrite
        individual planes)."""
        if faults.ENABLED:
            faults.fire(faults.DEVICE_READBACK)
        # Convergence gate first: a fixed-point run that exhausted its
        # round budget has undefined undecided rows, so nothing from the
        # cycle may apply. Observe the rounds histogram either way —
        # exhaustion is exactly when the operator needs the data point.
        if out.converged is not None:
            rounds = int(np.asarray(out.fp_rounds))
            self._last_fp_rounds = rounds
            if tracing.ENABLED:
                tracing.observe("solver_fixedpoint_rounds", float(rounds))
            if not bool(np.asarray(out.converged)):
                raise FixedpointRoundsError(
                    f"fixed-point kernel undecided after {rounds} rounds "
                    f"(max_rounds={self.fixedpoint_max_rounds})"
                )
        else:
            self._last_fp_rounds = None
        # Slot-pass conflict telemetry: how many bounded conflict-scan
        # rounds the batched TAS slot placement ran (0 = every slot
        # settled in the first vectorized pass). No error case — the
        # bound is structural (< S), never a budget.
        if getattr(out, "slot_rounds", None) is not None:
            srounds = int(np.asarray(out.slot_rounds))
            self._last_slot_rounds = srounds
            if tracing.ENABLED:
                tracing.observe(
                    "solver_slot_conflict_rounds", float(srounds)
                )
        else:
            self._last_slot_rounds = None
        outcome = np.asarray(out.outcome)  # first blocking read
        chosen = np.asarray(out.chosen_flavor)
        tried = np.asarray(out.tried_flavor_idx)
        s_flavor = (
            np.asarray(out.s_flavor)
            if out.s_flavor is not None else None
        )
        s_pmode = (
            np.asarray(out.s_pmode)
            if out.s_pmode is not None else None
        )
        s_tried = (
            np.asarray(out.s_tried)
            if out.s_tried is not None else None
        )
        if faults.ENABLED:
            outcome = faults.corrupt_plane(
                faults.DEVICE_READBACK, "outcome", outcome)
            chosen = faults.corrupt_plane(
                faults.DEVICE_READBACK, "chosen", chosen)
            tried = faults.corrupt_plane(
                faults.DEVICE_READBACK, "tried", tried)
            s_flavor = faults.corrupt_plane(
                faults.DEVICE_READBACK, "s_flavor", s_flavor)
        # Secondary planes are only copied off device when some row
        # outcome actually consumes them (the victim matrix is the
        # largest readback of the cycle). A corrupted outcome plane
        # steers these reads exactly like a real one would.
        any_admit = bool(
            (outcome == batch_scheduler.OUT_ADMITTED).any()
        )
        any_preempt = bool(
            (outcome == batch_scheduler.OUT_PREEMPTING).any()
        )
        partial = (
            np.asarray(out.partial_count)
            if out.partial_count is not None and any_admit else None
        )
        victims = (
            np.asarray(out.victims)
            if out.victims is not None and any_preempt else None
        )
        variants = (
            np.asarray(out.victim_variant)
            if out.victim_variant is not None and any_preempt else None
        )
        if faults.ENABLED:
            partial = faults.corrupt_plane(
                faults.DEVICE_READBACK, "partial", partial)
            victims = faults.corrupt_plane(
                faults.DEVICE_READBACK, "victims", victims)
            variants = faults.corrupt_plane(
                faults.DEVICE_READBACK, "variants", variants)
        self._validate_planes(
            outcome, chosen, tried, partial, victims, variants,
            s_flavor, idx,
        )
        # Admitted TAS entries: the placement kernel emits its own
        # per-leaf takes (CycleOutputs.tas_takes), so domains decode
        # directly in O(assignments) — no host placement replay.
        (tas_assignments, leader_tas,
         slot_tas) = self._decode_tas_assignments(out, outcome, chosen, idx)
        return (outcome, chosen, tried, s_flavor, s_pmode, s_tried,
                partial, victims, variants, tas_assignments, leader_tas,
                slot_tas)

    def _validate_planes(self, outcome, chosen, tried, partial, victims,
                         variants, s_flavor, idx) -> None:
        """Cheap invariants every readback must satisfy before admissions
        apply: index bounds on every value the apply loop will index with,
        outcome/variant domains, admitted-row partial-count sanity, no
        NaN. O(W) host work on planes already resident — the threat model
        is a trashed or truncated readback buffer, not a semantically
        plausible wrong answer (that class is covered by the differential
        suites and the arena verify mode)."""
        w = len(idx.workloads)
        n_flavors = len(idx.flavors)
        n_adm = len(idx.admitted)
        for name, plane in (("outcome", outcome), ("chosen", chosen),
                            ("tried", tried), ("partial", partial),
                            ("victims", victims), ("variants", variants),
                            ("s_flavor", s_flavor)):
            if plane is not None and \
                    np.issubdtype(plane.dtype, np.floating) and \
                    np.isnan(plane).any():
                raise PlaneValidationError("nan", f"{name} contains NaN")
        if outcome.shape[0] < w:
            raise PlaneValidationError(
                "shape", f"outcome rows {outcome.shape[0]} < {w}")
        oc = outcome[:w]
        if ((oc < batch_scheduler.OUT_NOFIT)
                | (oc > batch_scheduler.OUT_SHADOWED)).any():
            raise PlaneValidationError(
                "outcome-domain",
                f"values outside [{batch_scheduler.OUT_NOFIT}, "
                f"{batch_scheduler.OUT_SHADOWED}]",
            )
        tr = tried[:w]
        if ((tr < -1) | (tr > n_flavors)).any():
            raise PlaneValidationError(
                "tried-bounds", f"values outside [-1, {n_flavors}]")
        admitted_rows = np.flatnonzero(oc == batch_scheduler.OUT_ADMITTED)
        preempt_rows = np.flatnonzero(oc == batch_scheduler.OUT_PREEMPTING)
        ch = chosen[:w]
        for i in admitted_rows:
            if not (0 <= ch[i] < n_flavors):
                raise PlaneValidationError(
                    "flavor-bounds",
                    f"row {i}: chosen {ch[i]} outside [0, {n_flavors})",
                )
            slots_i = idx.slots[i] if idx.slots else None
            if s_flavor is not None and slots_i is not None:
                for si in range(min(len(slots_i), s_flavor.shape[1])):
                    sf = s_flavor[i, si]
                    if not (0 <= sf < n_flavors):
                        raise PlaneValidationError(
                            "slot-flavor-bounds",
                            f"row {i} slot {si}: {sf} outside "
                            f"[0, {n_flavors})",
                        )
            if partial is not None and partial[i] != -1:
                count = idx.workloads[i].total_requests[0].count
                if not (0 < partial[i] <= count):
                    raise PlaneValidationError(
                        "partial-range",
                        f"row {i}: partial count {partial[i]} outside "
                        f"(0, {count}]",
                    )
        if len(preempt_rows):
            if victims is None:
                raise PlaneValidationError(
                    "victims-missing", "preempting rows without a victim "
                    "plane")
            for i in preempt_rows:
                marks = np.flatnonzero(victims[i])
                if marks.size == 0:
                    raise PlaneValidationError(
                        "victims-empty", f"preempting row {i} designates "
                        "no victims")
                if int(marks.max()) >= n_adm:
                    raise PlaneValidationError(
                        "victim-bounds",
                        f"row {i}: victim index {int(marks.max())} >= "
                        f"{n_adm} admitted rows",
                    )
                if variants is not None:
                    var = variants[i][marks]
                    if ((var < 0) | (var > 6)).any():
                        raise PlaneValidationError(
                            "variant-domain",
                            f"row {i}: victim variants outside [0, 6]",
                        )

    def _host_process(self, infos: List[WorkloadInfo]) -> CycleResult:
        """Run the host-exact pipeline on specific workloads by temporarily
        feeding them as the only heads."""
        result = CycleResult()
        snapshot = self.cache.snapshot()
        entries, inadmissible = self.host._nominate(infos, snapshot)
        iterator = self.host._make_iterator(entries, snapshot)
        from kueue_tpu.scheduler.preemption import PreemptedWorkloads
        from kueue_tpu.scheduler.scheduler import EntryStatus

        preempted = PreemptedWorkloads()
        skipped: Dict[str, int] = {}
        for e in iterator:
            self.host._process_entry(e, snapshot, preempted, skipped, result)
        for e in entries:
            if e.status == EntryStatus.ASSUMED:
                result.admitted.append(e.info.key)
            elif e.status == EntryStatus.PREEMPTING:
                result.preempting.append(e.info.key)
                # Mirror Scheduler.schedule: the preemptor stays pinned at
                # the head while its victims' evictions land.
                e.requeue_reason = RequeueReason.PENDING_PREEMPTION
                self.host._requeue_and_update(e)
            elif e.status != EntryStatus.EVICTED:
                result.skipped.append(e.info.key)
                self.host._requeue_and_update(e)
        for e in inadmissible:
            result.inadmissible.append(e.info.key)
            self.host._requeue_and_update(e)
        return result

    def _decode_tas_assignments(self, out, outcome, chosen, idx):
        """Decode device-TAS admissions straight from the placement
        kernel's per-leaf takes: map each nonzero leaf (device leaf order)
        through the encode permutation to the host leaf's level values.
        O(assignments) — the host placement engine is not invoked."""
        from kueue_tpu.api.types import TopologyAssignment

        if not idx.tas_flavor_names or out.tas_takes is None:
            return {}, {}, {}
        takes = np.asarray(out.tas_takes)
        ltakes = (
            np.asarray(out.tas_leader_takes)
            if out.tas_leader_takes is not None else None
        )
        stakes = (
            np.asarray(out.s_tas_takes)
            if out.s_tas_takes is not None else None
        )
        s_flavors = (
            np.asarray(out.s_flavor) if out.s_flavor is not None else None
        )
        row_of = {name: t for t, name in enumerate(idx.tas_flavor_names)}

        def _domains_of(t, row):
            tas = idx.tas_snapshots[t]
            perm = idx.tas_leaf_perm[t]
            li = len(tas.level_keys) - 1 if tas.lowest_is_node else 0
            domains = []
            for j in np.flatnonzero(row[: len(perm)]):
                leaf = tas.leaves[perm[int(j)]]
                domains.append(
                    (tuple(leaf.level_values[li:]), int(row[j]))
                )
            return TopologyAssignment(
                levels=list(tas.level_keys[li:]), domains=domains
            )

        assignments = {}
        leader_assignments = {}
        slot_assignments = {}
        for i, info in enumerate(idx.workloads):
            if outcome[i] != batch_scheduler.OUT_ADMITTED:
                continue
            if idx.delayed_tas and idx.delayed_tas[i]:
                continue  # quota-only first pass: second pass places
            # Generic multi-podset TAS: per-slot takes decode to one TA
            # per TAS podset (singleton groups).
            if (
                stakes is not None and idx.slots
                and i < len(idx.slots) and idx.slots[i] is not None
                and stakes[i].any()
            ):
                by_pid = {}
                for si, sl in enumerate(idx.slots[i]):
                    if si >= stakes.shape[1] or not stakes[i, si].any():
                        continue
                    fidx = int(s_flavors[i, si])
                    t = row_of.get(idx.flavors[fidx]) \
                        if 0 <= fidx < len(idx.flavors) else None
                    if t is None:
                        continue
                    by_pid[sl.ps_ids[0]] = _domains_of(t, stakes[i, si])
                if by_pid:
                    slot_assignments[i] = by_pid
                continue
            if info.obj.pod_sets[0].topology_request is None:
                continue
            t = row_of.get(idx.flavors[chosen[i]])
            if t is None:
                continue
            # buildAssignment semantics (tas_flavor_snapshot.py:1175 /
            # reference :1663): node-level topologies emit hostname-only
            # domains; device leaf order is level_values-sorted, matching
            # the host's domain sort.
            assignments[i] = _domains_of(t, takes[i])
            if ltakes is not None and ltakes[i].any():
                leader_assignments[i] = _domains_of(t, ltakes[i])
        return assignments, leader_assignments, slot_assignments

    def _apply_admission(
        self, info: WorkloadInfo, flavor: str, tried_idx: int, snapshot,
        topology_assignment=None, reduced_count=None, delayed_tas=False,
    ) -> None:
        ps = info.total_requests[0]
        if reduced_count is not None and reduced_count != ps.count:
            # Partial admission: replace the tracked totals with the
            # scaled copy (host analog: Scheduler._admit's ps.scaled_to).
            # Mutating the existing PodSetResources in place would leak
            # the reduction to any other holder of the object if the
            # admission were rolled back.
            ps = ps.scaled_to(reduced_count)
            info.total_requests[0] = ps
        flavors = {res: flavor for res, v in ps.requests.items()}
        psas = [
            PodSetAssignment(
                name=ps.name,
                flavors=dict(flavors),
                resource_usage=dict(ps.requests),
                count=ps.count,
                topology_assignment=topology_assignment,
                # Delayed placement (tas_flavorassigner.go:106): the
                # manager's second pass assigns topology later.
                delayed_topology_request=bool(
                    delayed_tas
                    and info.obj.pod_sets[0].topology_request
                    is not None
                ),
            )
        ]
        ps.flavors = dict(flavors)
        self._finish_admission(
            info, psas, [{r: tried_idx for r in ps.requests}], snapshot
        )

    def _finish_admission(self, info, psas, tried_state, snapshot) -> None:
        """Shared admission tail for every applier: status, conditions,
        requeue state, admission checks, cache assume (host analog:
        Scheduler._admit, reference scheduler.go:561)."""
        now = self.clock()
        cqs = snapshot.cluster_queues[info.cluster_queue]
        wl = info.obj
        wl.status.admission = Admission(
            cluster_queue=info.cluster_queue, pod_set_assignments=psas
        )
        set_condition(wl, COND_QUOTA_RESERVED, True, "QuotaReserved",
                      f"Quota reserved in ClusterQueue {cqs.name}", now)
        info.last_assignment = AssignmentClusterQueueState(
            last_tried_flavor_idx=tried_state,
            cluster_queue_generation=cqs.allocatable_generation,
        )
        checks = cqs.spec.admission_checks
        if checks:
            wl.status.admission_checks = [
                AdmissionCheckState(name=c, state=CheckState.PENDING)
                for c in checks
            ]
        else:
            set_condition(wl, COND_ADMITTED, True, "Admitted",
                          "The workload is admitted", now)
        self.cache.assume_workload(info)

    def _apply_admission_lws(
        self, info: WorkloadInfo, flavor: str, tried_idx: int, snapshot,
        worker_ta, leader_ta, delayed_tas=False,
    ) -> None:
        """LWS leader-group admission decode: the two grouped podsets
        place as one request — the worker podset carries the placement
        TA, the leader podset the leader leaf one-hot
        (flavorassigner.update_for_tas, tas_flavor_snapshot.go:725).
        With ``delayed_tas`` both podsets admit quota-only with
        delayed_topology_request set (the second pass places)."""
        from kueue_tpu.scheduler.flavorassigner import (
            find_leader_and_workers,
        )

        leader_pid, worker_pid = find_leader_and_workers(
            info.obj.pod_sets, [0, 1]
        )
        psas = []
        tried_state = []
        for pid, ps in enumerate(info.total_requests):
            psas.append(PodSetAssignment(
                name=ps.name,
                flavors={res: flavor for res in ps.requests},
                resource_usage=dict(ps.requests),
                count=ps.count,
                topology_assignment=(
                    None if delayed_tas
                    else (worker_ta if pid == worker_pid else leader_ta)
                ),
                delayed_topology_request=delayed_tas,
            ))
            ps.flavors = {res: flavor for res in ps.requests}
            tried_state.append({r: tried_idx for r in ps.requests})
        self._finish_admission(info, psas, tried_state, snapshot)

    def _apply_admission_slots(
        self, info: WorkloadInfo, slots, flavor_row, tried_row, idx,
        snapshot, delayed_tas=False, tas_by_pid=None,
    ) -> None:
        """Multi-podset / multi-resource-group admission decode: one
        PodSetAssignment per podset with per-resource flavors recovered
        from the slot results (host analog: Scheduler._admit over
        assignment.pod_sets, reference scheduler.go:561)."""
        flavors_by_ps = [dict() for _ in info.total_requests]
        tried_by_ps = [dict() for _ in info.total_requests]
        for si, sl in enumerate(slots):
            fname = idx.flavors[int(flavor_row[si])]
            for pid in sl.ps_ids:
                for res in info.total_requests[pid].requests:
                    if res in sl.requests:
                        flavors_by_ps[pid][res] = fname
                        tried_by_ps[pid][res] = int(tried_row[si])
        psas = []
        for pid, ps in enumerate(info.total_requests):
            psas.append(
                PodSetAssignment(
                    name=ps.name,
                    flavors=dict(flavors_by_ps[pid]),
                    resource_usage=dict(ps.requests),
                    count=ps.count,
                    topology_assignment=(
                        tas_by_pid.get(pid) if tas_by_pid else None
                    ),
                    delayed_topology_request=bool(
                        delayed_tas
                        and pid < len(info.obj.pod_sets)
                        and info.obj.pod_sets[pid].topology_request
                        is not None
                    ),
                )
            )
            ps.flavors = dict(flavors_by_ps[pid])
        self._finish_admission(info, psas, tried_by_ps, snapshot)

    @staticmethod
    def _slot_tried_state(info, slots, pmode_row, tried_row):
        """Rebuild the host's partial last_tried_flavor_idx for a requeued
        multi-slot entry: one dict per podset of every processed group, up
        to and including the group whose slot failed (the assigner
        early-returns there — flavorassigner.go:296); resources of failed
        or unevaluated slots are absent (next_flavor_to_try -> 0)."""
        out = []
        i = 0
        n = len(slots)
        stop = False
        while i < n and not stop:
            ids = slots[i].ps_ids
            group = []
            j = i
            while j < n and slots[j].ps_ids == ids:
                group.append(j)
                j += 1
            rec: dict = {}
            for sj in group:
                if pmode_row[sj] == batch_scheduler.P_NOFIT:
                    # The host drops the whole group's flavors on failure
                    # (flavorassigner.go:757), so nothing is recorded for
                    # any of its resources.
                    rec = {}
                    stop = True
                    break
                for res in slots[sj].requests:
                    rec[res] = int(tried_row[sj])
            for pid in ids:
                out.append({
                    res: rec[res]
                    for res in info.total_requests[pid].requests
                    if res in rec
                })
            i = j
        return out

    def _apply_preempting(
        self,
        info: WorkloadInfo,
        victim_row: np.ndarray,
        variant_row: np.ndarray,
        idx,
        tried_idx: int,
        snapshot,
        result: CycleResult,
        slots=None,
        s_pmode_row=None,
        s_tried_row=None,
    ) -> None:
        """Issue the device-designated preemptions and requeue the
        preemptor (host analog: scheduler.go _issue_preemptions +
        _requeue_and_update for a PREEMPTING entry)."""
        from kueue_tpu.api.constants import (
            EVICTED_BY_PREEMPTION,
            IN_CLUSTER_QUEUE_REASON,
            IN_COHORT_FAIR_SHARING_REASON,
            IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
            IN_COHORT_RECLAMATION_REASON,
        )

        reasons = {
            1: IN_CLUSTER_QUEUE_REASON,
            2: IN_COHORT_RECLAMATION_REASON,
            3: IN_COHORT_RECLAMATION_REASON,
            4: IN_COHORT_RECLAIM_WHILE_BORROWING_REASON,
            # Fair-sharing tournament variants (fair_preempt_kernel).
            5: IN_COHORT_FAIR_SHARING_REASON,
            6: IN_COHORT_RECLAMATION_REASON,
        }
        for a in np.flatnonzero(victim_row):
            victim = idx.admitted[a]
            self.host.evict_fn(
                victim, EVICTED_BY_PREEMPTION,
                reasons.get(int(variant_row[a]), IN_COHORT_RECLAMATION_REASON),
            )
            result.preempted.append(victim.key)
        result.preempting.append(info.key)
        cqs = snapshot.cluster_queues[info.cluster_queue]
        ps = info.total_requests[0]
        if slots is not None:
            tried_state = self._slot_tried_state(
                info, slots, s_pmode_row, s_tried_row
            )
        else:
            tried_state = [{r: tried_idx for r in ps.requests}]
        info.last_assignment = AssignmentClusterQueueState(
            last_tried_flavor_idx=tried_state,
            cluster_queue_generation=cqs.allocatable_generation,
        )
        self.queues.requeue_workload(
            info, RequeueReason.PENDING_PREEMPTION
        )

    def _apply_requeue(
        self, info: WorkloadInfo, outcome: int, tried_idx: int, snapshot,
        slots=None, s_pmode_row=None, s_tried_row=None,
    ) -> None:
        cqs = snapshot.cluster_queues[info.cluster_queue]
        ps = info.total_requests[0]
        if slots is not None:
            info.last_assignment = AssignmentClusterQueueState(
                last_tried_flavor_idx=self._slot_tried_state(
                    info, slots, s_pmode_row, s_tried_row
                ),
                cluster_queue_generation=cqs.allocatable_generation,
            )
        else:
            info.last_assignment = AssignmentClusterQueueState(
                last_tried_flavor_idx=[{r: tried_idx for r in ps.requests}],
                cluster_queue_generation=cqs.allocatable_generation,
            )
        reason = {
            batch_scheduler.OUT_NOFIT: RequeueReason.NO_FIT,
            batch_scheduler.OUT_NO_CANDIDATES:
                RequeueReason.PREEMPTION_NO_CANDIDATES,
            batch_scheduler.OUT_FIT_SKIPPED:
                RequeueReason.FAILED_AFTER_NOMINATION,
            # A shadowed fair-tournament entry was nominated but never
            # evaluated; the host upgrades its GENERIC reason to
            # FAILED_AFTER_NOMINATION (scheduler._requeue_and_update), which
            # re-heaps immediately instead of parking it inadmissible.
            batch_scheduler.OUT_SHADOWED:
                RequeueReason.FAILED_AFTER_NOMINATION,
        }.get(outcome, RequeueReason.GENERIC)
        self.queues.requeue_workload(info, reason)
        now = self.clock()
        set_condition(info.obj, COND_QUOTA_RESERVED, False, "Pending",
                      "Workload didn't fit", now)
