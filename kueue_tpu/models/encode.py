"""Encode a scheduling cycle into dense device tensors.

Host-side, runs once per cycle: takes the Snapshot's quota tree plus a batch
of pending workloads and produces the padded arrays consumed by the batched
cycle kernel (kueue_tpu/models/batch_scheduler.py).

Device-compatible workloads are the dense common case the TPU path handles:
single podset, all requested resources covered by one resource group of the
CQ. Anything else (multi-podset with heterogeneous flavors, multiple
resource groups, TAS, partial admission) goes through the host-exact path —
the encoder reports them in ``host_fallback``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from kueue_tpu.api.constants import (
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
)
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.core.workload_info import WorkloadInfo, has_quota_reservation
from kueue_tpu.ops.quota_ops import QuotaTreeArrays
from kueue_tpu.ops.tree_encode import GroupLayout, TreeIndex, encode_tree
from kueue_tpu.core.workload_info import queue_order_timestamp


class CycleArrays(NamedTuple):
    """Inputs of one batched scheduling cycle. W/F/R/N are padded axes."""

    # -- tree/topology (static between spec changes) --
    tree: QuotaTreeArrays
    usage: jnp.ndarray  # i64[N,F,R] cycle-start usage
    # -- per-CQ policy --
    flavor_at: jnp.ndarray  # i32[N,K] global flavor id per preference slot
    n_flavors: jnp.ndarray  # i32[N]
    covered: jnp.ndarray  # bool[N,R] resource covered by the CQ's group
    when_can_borrow_try_next: jnp.ndarray  # bool[N]
    when_can_preempt_try_next: jnp.ndarray  # bool[N]
    pref_preempt_over_borrow: jnp.ndarray  # bool[N]
    can_preempt_while_borrowing: jnp.ndarray  # bool[N]
    never_preempts: jnp.ndarray  # bool[N] oracle deterministically NoCandidates
    can_always_reclaim: jnp.ndarray  # bool[N] reclaimWithinCohort == Any
    # Preemption-candidate prefilter (resolves NoCandidates on device):
    # admitted usage bucketed by workload priority rank, and policy codes
    # (0=Never, 1=LowerPriority, 2=LowerOrNewerEqual superset, 3=Any).
    usage_by_prio: jnp.ndarray  # i64[N,F,R,B] per-CQ admitted usage
    prio_cuts: jnp.ndarray  # i64[B] bucket upper bounds (sorted distinct)
    prefilter_valid: jnp.ndarray  # bool[] whether buckets cover all prios
    policy_within: jnp.ndarray  # i32[N]
    policy_reclaim: jnp.ndarray  # i32[N]
    nominal_cq: jnp.ndarray  # i64[N,F,R] (= tree.nominal; alias for clarity)
    # -- per-workload --
    w_cq: jnp.ndarray  # i32[W] CQ node index
    w_req: jnp.ndarray  # i64[W,R]
    w_elig: jnp.ndarray  # bool[W,F] flavor passes taints/affinity
    w_active: jnp.ndarray  # bool[W] (padding = False)
    w_priority: jnp.ndarray  # i64[W]
    w_timestamp: jnp.ndarray  # f64[W]
    w_quota_reserved: jnp.ndarray  # bool[W] second-pass entries first
    w_start_flavor: jnp.ndarray  # i32[W] NextFlavorToTry resume index
    # -- device preemption (None when the preempt path is not encoded) --
    # borrowWithinCohort policy code (0=Never, 1=LowerPriority) + threshold.
    bwc_policy: Optional[jnp.ndarray] = None  # i32[N]
    bwc_threshold: Optional[jnp.ndarray] = None  # i64[N]
    bwc_has_threshold: Optional[jnp.ndarray] = None  # bool[N]
    # CQ is in a flat no-lending-limit tree whose admitted set is fully
    # device-representable: classical victim search can run on device.
    preempt_simple: Optional[jnp.ndarray] = None  # bool[N]
    w_has_gates: Optional[jnp.ndarray] = None  # bool[W] preemptionGates open


@dataclass
class CycleIndex:
    """Host bookkeeping to decode device results."""

    tree_index: TreeIndex
    workloads: List[WorkloadInfo] = field(default_factory=list)
    host_fallback: List[WorkloadInfo] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    flavors: List[str] = field(default_factory=list)
    group_arrays: object = None  # batch_scheduler.GroupArrays
    # Admitted candidates row order (device preemption victim decode).
    admitted: List[WorkloadInfo] = field(default_factory=list)
    admitted_arrays: object = None  # preempt_kernel.AdmittedArrays


def _round_up(n: int, m: int) -> int:
    return max(m, ((n + m - 1) // m) * m)


def encode_cycle(
    snapshot: Snapshot,
    heads: Sequence[WorkloadInfo],
    resource_flavors: Dict[str, object],
    w_pad: int = 0,
    fair_sharing: bool = False,
    preempt: bool = False,
) -> Tuple[CycleArrays, CycleIndex]:
    """Build CycleArrays from the host snapshot + pending heads.

    With ``preempt=True`` also encodes the admitted-candidate arrays and
    per-CQ preemption policy fields consumed by the device victim-selection
    kernel (models/preempt_kernel.py); the resulting CycleArrays must then
    be paired with the AdmittedArrays returned via ``encode_admitted``."""
    tree, tidx, usage, is_cq = encode_tree(snapshot.roots)
    n = tree.n_nodes
    f = tree.nominal.shape[1]
    r = tree.nominal.shape[2]

    # subtree_quota and cohort usage roll-ups arrive pre-computed from the
    # host tree (exact); no device round-trip during encoding.
    usage_full = usage

    idx = CycleIndex(
        tree_index=tidx,
        resources=list(tidx.resources),
        flavors=list(tidx.flavors),
    )

    # Per-CQ policy arrays.
    flavor_at = np.zeros((n, max(f, 1)), dtype=np.int32)
    n_flavors = np.zeros(n, dtype=np.int32)
    covered = np.zeros((n, r), dtype=bool)
    borrow_try_next = np.zeros(n, dtype=bool)
    preempt_try_next = np.zeros(n, dtype=bool)
    pref_pob = np.zeros(n, dtype=bool)
    cpwb = np.zeros(n, dtype=bool)
    never_preempts = np.zeros(n, dtype=bool)
    can_always_reclaim = np.zeros(n, dtype=bool)
    policy_within = np.zeros(n, dtype=np.int32)
    policy_reclaim = np.zeros(n, dtype=np.int32)
    bwc_policy = np.zeros(n, dtype=np.int32)
    bwc_threshold = np.zeros(n, dtype=np.int64)
    bwc_has_threshold = np.zeros(n, dtype=bool)

    single_rg_cq: Dict[str, bool] = {}
    for name, cqs in snapshot.cluster_queues.items():
        ni = tidx.node_of[name]
        spec = cqs.spec
        single_rg_cq[name] = len(spec.resource_groups) == 1
        if not spec.resource_groups:
            continue
        rg = spec.resource_groups[0]
        flist = [fq.name for fq in rg.flavors if fq.name in tidx.flavor_of]
        n_flavors[ni] = len(flist)
        for k, fname in enumerate(flist):
            flavor_at[ni, k] = tidx.flavor_of[fname]
        for res in rg.covered_resources:
            if res in tidx.resource_of:
                covered[ni, tidx.resource_of[res]] = True
        fung = spec.flavor_fungibility
        borrow_try_next[ni] = (
            fung.when_can_borrow == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
        )
        preempt_try_next[ni] = (
            fung.when_can_preempt == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
        )
        pref_pob[ni] = (
            fung.preference
            == FlavorFungibilityPreference.PREEMPTION_OVER_BORROWING
        )
        from kueue_tpu.api.constants import (
            BorrowWithinCohortPolicy,
            PreemptionPolicy,
        )

        p = spec.preemption
        cpwb[ni] = (
            p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER
        ) or (
            fair_sharing
            and p.reclaim_within_cohort != PreemptionPolicy.NEVER
        )
        never_preempts[ni] = (
            p.within_cluster_queue == PreemptionPolicy.NEVER
            and p.reclaim_within_cohort == PreemptionPolicy.NEVER
        )
        can_always_reclaim[ni] = (
            p.reclaim_within_cohort == PreemptionPolicy.ANY
        )
        _pol = {
            PreemptionPolicy.NEVER: 0,
            PreemptionPolicy.LOWER_PRIORITY: 1,
            PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY: 2,
            PreemptionPolicy.ANY: 3,
        }
        policy_within[ni] = _pol[p.within_cluster_queue]
        policy_reclaim[ni] = _pol[p.reclaim_within_cohort]
        bwc_policy[ni] = (
            0 if p.borrow_within_cohort.policy == BorrowWithinCohortPolicy.NEVER
            else 1
        )
        thr = p.borrow_within_cohort.max_priority_threshold
        bwc_has_threshold[ni] = thr is not None
        bwc_threshold[ni] = thr if thr is not None else 0

    # Admitted usage bucketed by priority rank (preemption prefilter).
    B = 8
    admitted_prios = sorted({
        info.priority()
        for cqs in snapshot.cluster_queues.values()
        for info in cqs.workloads.values()
    })
    prefilter_valid = np.asarray(len(admitted_prios) <= B)
    prio_cuts = np.full(B, np.iinfo(np.int64).max // 2, dtype=np.int64)
    prio_rank = {}
    if prefilter_valid:
        for rank_i, pv in enumerate(admitted_prios):
            prio_cuts[rank_i] = pv
            prio_rank[pv] = rank_i
    usage_by_prio = np.zeros((n, f, r, B), dtype=np.int64)
    if prefilter_valid:
        for cq_name2, cqs2 in snapshot.cluster_queues.items():
            ni2 = tidx.node_of[cq_name2]
            for info in cqs2.workloads.values():
                b = prio_rank.get(info.priority(), B - 1)
                for fr2, v2 in info.usage().items():
                    fi2 = tidx.flavor_of.get(fr2.flavor)
                    ri2 = tidx.resource_of.get(fr2.resource)
                    if fi2 is not None and ri2 is not None:
                        usage_by_prio[ni2, fi2, ri2, b] += v2

    # Workload arrays.
    device_wls: List[WorkloadInfo] = []
    for info in heads:
        if _device_compatible(info, snapshot, single_rg_cq):
            device_wls.append(info)
        else:
            idx.host_fallback.append(info)

    w = _round_up(len(device_wls), 8) if w_pad == 0 else w_pad
    w_cq = np.zeros(w, dtype=np.int32)
    w_req = np.zeros((w, r), dtype=np.int64)
    w_elig = np.zeros((w, f), dtype=bool)
    w_active = np.zeros(w, dtype=bool)
    w_priority = np.zeros(w, dtype=np.int64)
    w_timestamp = np.zeros(w, dtype=np.float64)
    w_qr = np.zeros(w, dtype=bool)
    w_start = np.zeros(w, dtype=np.int32)
    w_gates = np.zeros(w, dtype=bool)

    from kueue_tpu.scheduler.flavorassigner import FlavorAssigner

    for i, info in enumerate(device_wls):
        idx.workloads.append(info)
        cqs = snapshot.cluster_queues[info.cluster_queue]
        ni = tidx.node_of[info.cluster_queue]
        w_cq[i] = ni
        w_active[i] = True
        w_priority[i] = info.priority()
        w_timestamp[i] = queue_order_timestamp(info.obj)
        w_qr[i] = has_quota_reservation(info.obj)
        w_gates[i] = bool(info.obj.preemption_gates)
        ps = info.total_requests[0]
        for res, v in ps.requests.items():
            if res in tidx.resource_of:
                w_req[i, tidx.resource_of[res]] = v
        # Taints/affinity eligibility per flavor (host-side; reuses the exact
        # assigner's check).
        assigner = FlavorAssigner(info, cqs, resource_flavors)
        pod_sets = [info.obj.pod_sets[0]]
        for fname, fi in tidx.flavor_of.items():
            ok, _ = assigner._check_flavor_for_podsets(fname, pod_sets)
            w_elig[i, fi] = ok
        if info.last_assignment is not None and (
            cqs.allocatable_generation
            <= info.last_assignment.cluster_queue_generation
        ):
            # Resume keys exist only for resources the workload requests
            # (single resource group -> same index for all of them).
            res_keys = [r for r in ps.requests if r in tidx.resource_of]
            res0 = res_keys[0] if res_keys else ""
            w_start[i] = info.last_assignment.next_flavor_to_try(0, res0)

    layout = GroupLayout(np.asarray(tree.parent), np.asarray(tree.active))
    from kueue_tpu.models.batch_scheduler import GroupArrays

    idx.group_arrays = GroupArrays(*layout.as_jax())

    preempt_fields: Dict[str, object] = {}
    if preempt:
        preempt_simple = _encode_admitted(
            snapshot, tidx, tree, idx, fair_sharing
        )
        preempt_fields = dict(
            bwc_policy=jnp.asarray(bwc_policy),
            bwc_threshold=jnp.asarray(bwc_threshold),
            bwc_has_threshold=jnp.asarray(bwc_has_threshold),
            preempt_simple=jnp.asarray(preempt_simple),
            w_has_gates=jnp.asarray(w_gates),
        )

    arrays = CycleArrays(
        tree=tree,
        usage=usage_full,
        flavor_at=jnp.asarray(flavor_at),
        n_flavors=jnp.asarray(n_flavors),
        covered=jnp.asarray(covered),
        when_can_borrow_try_next=jnp.asarray(borrow_try_next),
        when_can_preempt_try_next=jnp.asarray(preempt_try_next),
        pref_preempt_over_borrow=jnp.asarray(pref_pob),
        can_preempt_while_borrowing=jnp.asarray(cpwb),
        never_preempts=jnp.asarray(never_preempts),
        can_always_reclaim=jnp.asarray(can_always_reclaim),
        usage_by_prio=jnp.asarray(usage_by_prio),
        prio_cuts=jnp.asarray(prio_cuts),
        prefilter_valid=jnp.asarray(prefilter_valid),
        policy_within=jnp.asarray(policy_within),
        policy_reclaim=jnp.asarray(policy_reclaim),
        nominal_cq=tree.nominal,
        w_cq=jnp.asarray(w_cq),
        w_req=jnp.asarray(w_req),
        w_elig=jnp.asarray(w_elig),
        w_active=jnp.asarray(w_active),
        w_priority=jnp.asarray(w_priority),
        w_timestamp=jnp.asarray(w_timestamp),
        w_quota_reserved=jnp.asarray(w_qr),
        w_start_flavor=jnp.asarray(w_start),
        **preempt_fields,
    )
    return arrays, idx


def _encode_admitted(snapshot, tidx, tree, idx, fair_sharing) -> np.ndarray:
    """Build the admitted-candidate arrays (preempt_kernel.AdmittedArrays)
    and the per-CQ ``preempt_simple`` flag.

    A CQ's entries may use device victim selection only when the whole
    cohort tree is "simple": flat (root's children are all CQs, matching the
    single-LCA classical search), free of lending limits (usage bubbles
    fully so removal math is closed-form), fair sharing off, and every
    admitted workload's usage maps onto the encoded [F, R] cells."""
    from kueue_tpu.core.workload_info import (
        is_evicted,
        quota_reservation_time,
    )
    from kueue_tpu.models.preempt_kernel import AdmittedArrays

    n = tree.n_nodes
    parent = np.asarray(tree.parent)
    is_cq_node = np.zeros(n, dtype=bool)
    for name in snapshot.cluster_queues:
        is_cq_node[tidx.node_of[name]] = True
    root_of = np.arange(n)
    for _ in range(8):
        root_of = np.where(parent[root_of] >= 0, parent[root_of], root_of)

    has_lend = np.asarray(tree.has_lend_limit).any(axis=(1, 2))  # [N]
    # Per root: flat (no nested cohorts) and lend-limit free.
    root_ok = np.ones(n, dtype=bool)
    for node in range(n):
        if not np.asarray(tree.active)[node]:
            continue
        r = root_of[node]
        if has_lend[node]:
            root_ok[r] = False
        if node != r and not is_cq_node[node]:
            root_ok[r] = False  # nested cohort -> not flat

    infos = []
    for cqs2 in snapshot.cluster_queues.values():
        infos.extend(cqs2.workloads.values())
    a = max(8, _round_up(len(infos), 8))
    f = tree.nominal.shape[1]
    r = tree.nominal.shape[2]
    a_cq = np.zeros(a, dtype=np.int32)
    a_usage = np.zeros((a, f, r), dtype=np.int64)
    a_prio = np.zeros(a, dtype=np.int64)
    a_ts = np.zeros(a, dtype=np.float64)
    a_qr = np.zeros(a, dtype=np.float64)
    a_evicted = np.zeros(a, dtype=bool)
    a_active = np.zeros(a, dtype=bool)

    uids = sorted(info.obj.uid for info in infos)
    uid_rank_of = {u: i for i, u in enumerate(uids)}
    a_uid = np.zeros(a, dtype=np.int32)

    for i, info in enumerate(infos):
        ni = tidx.node_of[info.cluster_queue]
        a_cq[i] = ni
        a_active[i] = True
        a_prio[i] = info.priority()
        a_ts[i] = queue_order_timestamp(info.obj)
        a_qr[i] = quota_reservation_time(info.obj, 0.0)
        a_evicted[i] = is_evicted(info.obj)
        a_uid[i] = uid_rank_of[info.obj.uid]
        idx.admitted.append(info)
        for fr2, v2 in info.usage().items():
            fi2 = tidx.flavor_of.get(fr2.flavor)
            ri2 = tidx.resource_of.get(fr2.resource)
            if fi2 is None or ri2 is None:
                # Unmappable usage: the victim-removal math would be wrong
                # for this tree; keep it on the host path.
                root_ok[root_of[ni]] = False
            else:
                a_usage[i, fi2, ri2] = v2

    preempt_simple = np.zeros(n, dtype=bool)
    if not fair_sharing:
        for name in snapshot.cluster_queues:
            ni = tidx.node_of[name]
            preempt_simple[ni] = root_ok[root_of[ni]]

    idx.admitted_arrays = AdmittedArrays(
        cq=jnp.asarray(a_cq),
        usage=jnp.asarray(a_usage),
        prio=jnp.asarray(a_prio),
        ts=jnp.asarray(a_ts),
        qr_time=jnp.asarray(a_qr),
        evicted=jnp.asarray(a_evicted),
        active=jnp.asarray(a_active),
        uid_rank=jnp.asarray(a_uid),
    )
    return preempt_simple


def _device_compatible(
    info: WorkloadInfo, snapshot: Snapshot, single_rg_cq: Dict[str, bool]
) -> bool:
    if info.cluster_queue not in snapshot.cluster_queues:
        return False
    if not single_rg_cq.get(info.cluster_queue, False):
        return False
    if len(info.total_requests) != 1:
        return False
    ps = info.obj.pod_sets[0]
    if ps.min_count is not None and ps.min_count < ps.count:
        return False  # partial admission -> host path
    if ps.topology_request is not None:
        return False  # TAS -> host path (device TAS kernel comes separately)
    cqs = snapshot.cluster_queues[info.cluster_queue]
    rg = cqs.spec.resource_groups[0]
    return all(
        res in rg.covered_resources
        for res, v in info.total_requests[0].requests.items()
        if v > 0
    )
