"""Encode a scheduling cycle into dense device tensors.

Host-side, runs once per cycle: takes the Snapshot's quota tree plus a batch
of pending workloads and produces the padded arrays consumed by the batched
cycle kernel (kueue_tpu/models/batch_scheduler.py).

Device-compatible workloads are the dense common case the TPU path handles:
single podset, all requested resources covered by one resource group of the
CQ. Anything else (multi-podset with heterogeneous flavors, multiple
resource groups, TAS, partial admission) goes through the host-exact path —
the encoder reports them in ``host_fallback``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from kueue_tpu.api.constants import (
    FlavorFungibilityPolicy,
    FlavorFungibilityPreference,
)
from kueue_tpu.cache.snapshot import Snapshot
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.core.workload_info import WorkloadInfo, has_quota_reservation
from kueue_tpu.metrics import tracing
from kueue_tpu.models import buckets
from kueue_tpu.ops.quota_ops import QuotaTreeArrays
from kueue_tpu.ops.tree_encode import GroupLayout, TreeIndex, encode_tree
from kueue_tpu.core.workload_info import queue_order_timestamp

# Columnar encode mode (cache/columns.py): "on" gathers the W plane
# from the struct-of-arrays store with the row-wise path as fallback;
# "off" forces the row-wise oracle everywhere; "verify" runs both and
# compares field-for-field every columnar cycle. Env override for
# probes/tests; set_columns_mode for in-process switching.
_COLUMNS_MODE = os.environ.get("KUEUE_TPU_ENCODE_COLUMNS", "on")


def columns_mode() -> str:
    return _COLUMNS_MODE


def set_columns_mode(mode: str) -> None:
    global _COLUMNS_MODE
    if mode not in ("on", "off", "verify"):
        raise ValueError(f"unknown encode columns mode: {mode!r}")
    _COLUMNS_MODE = mode


class CycleArrays(NamedTuple):
    """Inputs of one batched scheduling cycle. W/F/R/N are padded axes."""

    # -- tree/topology (static between spec changes) --
    tree: QuotaTreeArrays
    usage: jnp.ndarray  # i64[N,F,R] cycle-start usage
    # -- per-CQ policy --
    flavor_at: jnp.ndarray  # i32[N,K] global flavor id per preference slot
    n_flavors: jnp.ndarray  # i32[N]
    covered: jnp.ndarray  # bool[N,R] resource covered by the CQ's group
    when_can_borrow_try_next: jnp.ndarray  # bool[N]
    when_can_preempt_try_next: jnp.ndarray  # bool[N]
    pref_preempt_over_borrow: jnp.ndarray  # bool[N]
    can_preempt_while_borrowing: jnp.ndarray  # bool[N]
    never_preempts: jnp.ndarray  # bool[N] oracle deterministically NoCandidates
    can_always_reclaim: jnp.ndarray  # bool[N] reclaimWithinCohort == Any
    # Preemption-candidate prefilter (resolves NoCandidates on device):
    # admitted usage bucketed by workload priority rank, and policy codes
    # (0=Never, 1=LowerPriority, 2=LowerOrNewerEqual superset, 3=Any).
    usage_by_prio: jnp.ndarray  # i64[N,F,R,B] per-CQ admitted usage
    prio_cuts: jnp.ndarray  # i64[B] bucket upper bounds (sorted distinct)
    prefilter_valid: jnp.ndarray  # bool[] whether buckets cover all prios
    policy_within: jnp.ndarray  # i32[N]
    policy_reclaim: jnp.ndarray  # i32[N]
    nominal_cq: jnp.ndarray  # i64[N,F,R] (= tree.nominal; alias for clarity)
    # -- per-workload --
    w_cq: jnp.ndarray  # i32[W] CQ node index
    w_req: jnp.ndarray  # i64[W,R]
    w_elig: jnp.ndarray  # bool[W,F] flavor passes taints/affinity
    w_active: jnp.ndarray  # bool[W] (padding = False)
    w_priority: jnp.ndarray  # i64[W]
    w_timestamp: jnp.ndarray  # f64[W]
    w_quota_reserved: jnp.ndarray  # bool[W] second-pass entries first
    w_start_flavor: jnp.ndarray  # i32[W] NextFlavorToTry resume index
    # Host-precomputed (priority desc, timestamp, submission) sort rank:
    # lets admission_order run one composite sort instead of five.
    w_order_rank: Optional[jnp.ndarray] = None  # i32[W] unique per row
    # -- multi-slot assignment (None when every device workload is one
    # (podset-group x resource-group) slot on its CQ's first resource
    # group — the dense legacy layout). A slot mirrors one
    # _find_flavor_for_podsets call (flavorassigner.go:946): its own
    # request vector, flavor list, eligibility row and resume index.
    # Slots are ordered exactly as the host evaluates them (podset-group
    # order, then resource groups by first triggering resource); slot 0
    # of a single-slot workload equals the legacy fields above.
    s_req: Optional[jnp.ndarray] = None  # i64[W,S,R]
    s_elig: Optional[jnp.ndarray] = None  # bool[W,S,F]
    s_flavor_at: Optional[jnp.ndarray] = None  # i32[W,S,K]
    s_n_flavors: Optional[jnp.ndarray] = None  # i32[W,S]
    s_start: Optional[jnp.ndarray] = None  # i32[W,S]
    s_valid: Optional[jnp.ndarray] = None  # bool[W,S]
    # Single slot on resource-group 0: the per-entry device preemption /
    # partial kernels (which read the legacy fields) remain applicable.
    w_simple_slot: Optional[jnp.ndarray] = None  # bool[W]
    # -- device preemption (None when the preempt path is not encoded) --
    # borrowWithinCohort policy code (0=Never, 1=LowerPriority) + threshold.
    bwc_policy: Optional[jnp.ndarray] = None  # i32[N]
    bwc_threshold: Optional[jnp.ndarray] = None  # i64[N]
    bwc_has_threshold: Optional[jnp.ndarray] = None  # bool[N]
    # CQ is in a flat no-lending-limit tree whose admitted set is fully
    # device-representable: classical victim search can run on device.
    preempt_simple: Optional[jnp.ndarray] = None  # bool[N]
    # CQ is in a *nested* no-lending-limit tree with device-representable
    # admitted usage: the hierarchical victim-search kernel applies.
    preempt_hier: Optional[jnp.ndarray] = None  # bool[N]
    # CQ's tree has fully device-representable admitted TAS usage: the
    # victim search may run its tas_fits probe on device for TAS entries.
    preempt_tas_ok: Optional[jnp.ndarray] = None  # bool[N]
    # -- partial admission (None when no device partial entry this cycle;
    # PodSetReducer class: single podset, non-TAS; never-preempts CQs
    # probe pure-fit, preempting CQs probe the victim-search kernel in
    # preempt cycles) --
    w_req_pp: Optional[jnp.ndarray] = None  # i64[W,R] per-pod requests
    w_count: Optional[jnp.ndarray] = None  # i64[W] requested pod count
    w_min_count: Optional[jnp.ndarray] = None  # i64[W]
    w_partial: Optional[jnp.ndarray] = None  # bool[W] reducible entry
    w_has_gates: Optional[jnp.ndarray] = None  # bool[W] preemptionGates open
    # -- device TAS (None when no TAS flavor is device-encoded) --
    tas_topo: Optional[object] = None  # ops.tas_place.TASDeviceTopo
    tas_usage0: Optional[jnp.ndarray] = None  # i64[T, D, R+1] cycle-start
    tas_of_flavor: Optional[jnp.ndarray] = None  # i32[F] -> T row (-1 none)
    w_tas: Optional[jnp.ndarray] = None  # bool[W] TAS entry on device path
    w_tas_req: Optional[jnp.ndarray] = None  # i64[W, R+1] incl. implicit pods
    w_tas_usage_req: Optional[jnp.ndarray] = None  # i64[W, R+1] usage deltas
    w_tas_count: Optional[jnp.ndarray] = None  # i64[W]
    w_tas_slice_size: Optional[jnp.ndarray] = None  # i64[W]
    w_tas_req_level: Optional[jnp.ndarray] = None  # i32[W, T] (-1 missing)
    w_tas_slice_level: Optional[jnp.ndarray] = None  # i32[W, T]
    # Multi-layer slice units per level (all-ones without inner layers).
    w_tas_sizes: Optional[jnp.ndarray] = None  # i64[W, T, LMAX]
    w_tas_required: Optional[jnp.ndarray] = None  # bool[W]
    w_tas_unconstrained: Optional[jnp.ndarray] = None  # bool[W]
    w_tas_invalid: Optional[jnp.ndarray] = None  # bool[W] always-infeasible
    # Balanced placement requested (tr.balanced or the global gate); None
    # when no entry this cycle is balanced, so the common program never
    # compiles the subset-enumeration pipeline.
    w_tas_balanced: Optional[jnp.ndarray] = None  # bool[W]
    # Per-entry filtered leaf capacity (selector/taint matching; None when
    # no entry this cycle needs node filtering): i64[W, D, R+1] rows are
    # meaningful where w_tas_has_cap; other entries use the topology cap.
    w_tas_cap: Optional[jnp.ndarray] = None
    w_tas_has_cap: Optional[jnp.ndarray] = None  # bool[W]
    # -- LWS leader group (None when no leader-group entry this cycle):
    # a two-podset group places as ONE request — the worker podset's
    # count/per-pod requests fill the w_tas_* fields above; the leader's
    # fit vector (requests + one pod slot, flavorassigner OnePodRequest)
    # and usage vector ride along, and the placement kernel emits the
    # leader leaf one-hot (ops/tas_place.place leader planes).
    w_tas_leader_req: Optional[jnp.ndarray] = None  # i64[W,R+1]
    w_tas_leader_usage_req: Optional[jnp.ndarray] = None  # i64[W,R+1]
    w_tas_has_leader: Optional[jnp.ndarray] = None  # bool[W]
    # -- per-slot TAS (generic multi-podset / multi-RG TAS entries; None
    # when every TAS entry this cycle is single-slot or an LWS pair).
    # Each TAS slot is a singleton podset group placing on its own
    # chosen flavor's topology, sequentially in slot order with
    # assumed-usage threading (the host's ``assumed`` dict in
    # flavorassigner.update_for_tas). Entries here do NOT set w_tas —
    # the legacy per-entry fields drive single-slot/LWS entries and the
    # two paths coexist in one cycle.
    s_tas: Optional[jnp.ndarray] = None  # bool[W,S]
    s_tas_req: Optional[jnp.ndarray] = None  # i64[W,S,R+1]
    s_tas_usage_req: Optional[jnp.ndarray] = None  # i64[W,S,R+1]
    s_tas_count: Optional[jnp.ndarray] = None  # i64[W,S]
    s_tas_slice_size: Optional[jnp.ndarray] = None  # i64[W,S]
    s_tas_req_level: Optional[jnp.ndarray] = None  # i32[W,S,T]
    s_tas_slice_level: Optional[jnp.ndarray] = None  # i32[W,S,T]
    s_tas_sizes: Optional[jnp.ndarray] = None  # i64[W,S,T,LMAX]
    s_tas_required: Optional[jnp.ndarray] = None  # bool[W,S]
    s_tas_unconstrained: Optional[jnp.ndarray] = None  # bool[W,S]
    # -- fair sharing (None unless the fair tournament kernel is in use) --
    node_weight: Optional[jnp.ndarray] = None  # f64[N] FairSharing weight
    node_is_cq: Optional[jnp.ndarray] = None  # bool[N]
    fair_pwn: Optional[jnp.ndarray] = None  # bool[] PreemptWithinNominal gate
    fair_strat0: Optional[jnp.ndarray] = None  # i32[] 0=S2a-first, 1=S2b
    fair_has_s2: Optional[jnp.ndarray] = None  # bool[] second strategy on
    # CQ's tree is lend-limit free with fully mappable admitted usage: the
    # fair preemption tournament can run on device.
    fair_preempt_ok: Optional[jnp.ndarray] = None  # bool[N]


@dataclass
class CycleIndex:
    """Host bookkeeping to decode device results."""

    tree_index: TreeIndex
    workloads: List[WorkloadInfo] = field(default_factory=list)
    host_fallback: List[WorkloadInfo] = field(default_factory=list)
    resources: List[str] = field(default_factory=list)
    flavors: List[str] = field(default_factory=list)
    group_arrays: object = None  # batch_scheduler.GroupArrays
    # Admitted candidates row order (device preemption victim decode).
    admitted: List[WorkloadInfo] = field(default_factory=list)
    admitted_arrays: object = None  # preempt_kernel.AdmittedArrays
    # Device-TAS decode state: per-T host snapshots + device-leaf order.
    tas_flavor_names: List[str] = field(default_factory=list)
    tas_snapshots: List[object] = field(default_factory=list)
    tas_leaf_perm: List[List[int]] = field(default_factory=list)
    tas_pad_shape: Tuple[int, int] = (0, 0)  # (D, R+1) padded axes
    has_partial: bool = False  # any reducible (partial-admission) entry
    # Multi-slot decode state: per device workload, the ordered slot list
    # from _workload_slots (None entries for trivially-single workloads
    # when the cycle is in legacy layout).
    slots: List[object] = field(default_factory=list)
    n_slots: int = 1  # padded S axis (1 = legacy layout, no slot fields)
    # Delayed topology placement (tas_flavorassigner.go:106): entries
    # admitted quota-only on device; the driver marks every TAS podset's
    # delayed_topology_request and the manager's second pass places.
    delayed_tas: List[bool] = field(default_factory=list)
    # Exact step bound for the fair tournament scan: at most one entry
    # per CQ participates (last-entry shadowing), and each scan step
    # resolves one winner per cohort root, so a root needs at most
    # #participating-CQs steps. Power-of-two bucketed for compile reuse.
    fair_s_bound: int = 0


def _round_up(n: int, m: int) -> int:
    return max(m, ((n + m - 1) // m) * m)


def encode_cycle(
    snapshot: Snapshot,
    heads: Sequence[WorkloadInfo],
    resource_flavors: Dict[str, object],
    w_pad: int = 0,
    fair_sharing: bool = False,
    preempt: bool = False,
    delay_tas_fn=None,
    fair_strategies: Optional[Sequence[str]] = None,
    admitted_cache: Optional[dict] = None,
    admitted_key=None,
    device_put: bool = True,
) -> Tuple[CycleArrays, CycleIndex]:
    """Build CycleArrays from the host snapshot + pending heads.

    With ``preempt=True`` also encodes the admitted-candidate arrays and
    per-CQ preemption policy fields consumed by the device victim-selection
    kernel (models/preempt_kernel.py); the resulting CycleArrays must then
    be paired with the AdmittedArrays returned via ``encode_admitted``.

    ``admitted_cache``/``admitted_key``: incremental encode of the
    admitted state. The per-admitted-workload arrays (usage_by_prio,
    AdmittedArrays incl. TAS rows, per-CQ preemption-eligibility flags)
    depend only on the spec + workload generations; when the key matches
    the previous cycle's, the cached (already on-device) tensors are
    reused — O(admitted) python work and the host->device transfer both
    drop out of the steady-state cycle (the reference cache is
    incremental by construction, cache.go:775).

    The cache is keyed per component: ``admitted_key`` may be a dict
    ``{"prio": key, "adm": key}`` so the priority buckets and the
    admitted-candidate arrays invalidate independently (the arena passes
    fine-grained cache generations); a plain hashable keys both
    components together (legacy callers). Entries are stored as
    ``admitted_cache[component] = (key, tensors)``.

    ``device_put=False`` returns host-side arrays and skips the batched
    transfer — the arena handles residency itself."""
    tree, tidx, usage, is_cq = encode_tree(snapshot.roots)
    n = tree.n_nodes
    f = tree.nominal.shape[1]
    r = tree.nominal.shape[2]

    # subtree_quota and cohort usage roll-ups arrive pre-computed from the
    # host tree (exact); no device round-trip during encoding.
    usage_full = usage

    idx = CycleIndex(
        tree_index=tidx,
        resources=list(tidx.resources),
        flavors=list(tidx.flavors),
    )

    # Per-CQ policy arrays.
    flavor_at = np.zeros((n, max(f, 1)), dtype=np.int32)
    n_flavors = np.zeros(n, dtype=np.int32)
    covered = np.zeros((n, r), dtype=bool)
    borrow_try_next = np.zeros(n, dtype=bool)
    preempt_try_next = np.zeros(n, dtype=bool)
    pref_pob = np.zeros(n, dtype=bool)
    cpwb = np.zeros(n, dtype=bool)
    never_preempts = np.zeros(n, dtype=bool)
    can_always_reclaim = np.zeros(n, dtype=bool)
    policy_within = np.zeros(n, dtype=np.int32)
    policy_reclaim = np.zeros(n, dtype=np.int32)
    bwc_policy = np.zeros(n, dtype=np.int32)
    bwc_threshold = np.zeros(n, dtype=np.int64)
    bwc_has_threshold = np.zeros(n, dtype=bool)

    for name, cqs in snapshot.cluster_queues.items():
        ni = tidx.node_of[name]
        spec = cqs.spec
        if not spec.resource_groups:
            continue
        rg = spec.resource_groups[0]
        flist = [fq.name for fq in rg.flavors if fq.name in tidx.flavor_of]
        n_flavors[ni] = len(flist)
        for k, fname in enumerate(flist):
            flavor_at[ni, k] = tidx.flavor_of[fname]
        for res in rg.covered_resources:
            if res in tidx.resource_of:
                covered[ni, tidx.resource_of[res]] = True
        fung = spec.flavor_fungibility
        borrow_try_next[ni] = (
            fung.when_can_borrow == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
        )
        preempt_try_next[ni] = (
            fung.when_can_preempt == FlavorFungibilityPolicy.TRY_NEXT_FLAVOR
        )
        pref_pob[ni] = (
            fung.preference
            == FlavorFungibilityPreference.PREEMPTION_OVER_BORROWING
        )
        from kueue_tpu.api.constants import (
            BorrowWithinCohortPolicy,
            PreemptionPolicy,
        )

        p = spec.preemption
        cpwb[ni] = (
            p.borrow_within_cohort.policy != BorrowWithinCohortPolicy.NEVER
        ) or (
            fair_sharing
            and p.reclaim_within_cohort != PreemptionPolicy.NEVER
        )
        never_preempts[ni] = (
            p.within_cluster_queue == PreemptionPolicy.NEVER
            and p.reclaim_within_cohort == PreemptionPolicy.NEVER
        )
        can_always_reclaim[ni] = (
            p.reclaim_within_cohort == PreemptionPolicy.ANY
        )
        _pol = {
            PreemptionPolicy.NEVER: 0,
            PreemptionPolicy.LOWER_PRIORITY: 1,
            PreemptionPolicy.LOWER_OR_NEWER_EQUAL_PRIORITY: 2,
            PreemptionPolicy.ANY: 3,
        }
        policy_within[ni] = _pol[p.within_cluster_queue]
        policy_reclaim[ni] = _pol[p.reclaim_within_cohort]
        bwc_policy[ni] = (
            0 if p.borrow_within_cohort.policy == BorrowWithinCohortPolicy.NEVER
            else 1
        )
        thr = p.borrow_within_cohort.max_priority_threshold
        bwc_has_threshold[ni] = thr is not None
        bwc_threshold[ni] = thr if thr is not None else 0

    if admitted_cache is not None and admitted_key is not None:
        comp_keys = (
            admitted_key if isinstance(admitted_key, dict)
            else {"prio": admitted_key, "adm": admitted_key}
        )
    else:
        comp_keys = None

    def _component_cached(component: str):
        if comp_keys is None:
            return None
        entry = admitted_cache.get(component)
        if entry is not None and entry[0] == comp_keys[component]:
            return entry[1]
        return None

    # Admitted usage bucketed by priority rank (preemption prefilter).
    prio_cached = _component_cached("prio")
    if prio_cached is not None:
        usage_by_prio, prio_cuts, prefilter_valid = prio_cached
    else:
        B = 8
        admitted_prios = sorted({
            info.priority()
            for cqs in snapshot.cluster_queues.values()
            for info in cqs.workloads.values()
        })
        prefilter_valid = np.asarray(len(admitted_prios) <= B)
        prio_cuts = np.full(B, np.iinfo(np.int64).max // 2, dtype=np.int64)
        prio_rank = {}
        if prefilter_valid:
            for rank_i, pv in enumerate(admitted_prios):
                prio_cuts[rank_i] = pv
                prio_rank[pv] = rank_i
        usage_by_prio = np.zeros((n, f, r, B), dtype=np.int64)
        if prefilter_valid:
            for cq_name2, cqs2 in snapshot.cluster_queues.items():
                ni2 = tidx.node_of[cq_name2]
                for info in cqs2.workloads.values():
                    b = prio_rank.get(info.priority(), B - 1)
                    for fr2, v2 in info.usage().items():
                        fi2 = tidx.flavor_of.get(fr2.flavor)
                        ri2 = tidx.resource_of.get(fr2.resource)
                        if fi2 is not None and ri2 is not None:
                            usage_by_prio[ni2, fi2, ri2, b] += v2

    # Device-encodable TAS flavors: topology present and every usage key
    # mappable onto the cycle resource axis (else the device free-capacity
    # math would diverge — those flavors' TAS entries stay on the host).
    tas_device_flavors: List[str] = []
    if preempt:
        for fname, tas in snapshot.tas_flavors.items():
            ok = len(tas.level_keys) <= 8 and tas.level_keys
            for leaf_usage in tas.usage.values():
                for res in leaf_usage:
                    if res not in tidx.resource_of and res != "pods":
                        ok = False
            if ok:
                tas_device_flavors.append(fname)

    # Fair x TAS: a TAS flavor shared by more than one cohort tree would
    # let same-step tournament winners of different trees race on shared
    # topology state; those entries take the host path (the driver then
    # routes their whole tree through the host for exact interleaving).
    # Lending limits need no gate: the fair kernel's availability walk and
    # clamped bubbling are exact for partially-lent trees.
    fair_tas_single: Dict[str, bool] = {}
    root_of_cq: Dict[str, int] = {}
    if fair_sharing:
        roots_of_flavor: Dict[str, set] = {}
        for cq_name2, cqs2 in snapshot.cluster_queues.items():
            rid = id(cqs2.node.root())
            root_of_cq[cq_name2] = rid
            for rg2 in cqs2.spec.resource_groups:
                for fq2 in rg2.flavors:
                    if fq2.name in snapshot.tas_flavors:
                        roots_of_flavor.setdefault(fq2.name, set()).add(rid)
        fair_tas_single = {
            name: len(roots) == 1
            for name, roots in roots_of_flavor.items()
        }

    # Workload arrays: columnar fast path first (cache/columns.py) —
    # classification and every W column resolved from the struct-of-
    # arrays store when the cycle carries no fair/TAS context and the
    # backlog is in the dense class; any ragged head (slot layout,
    # topology, partial reduction, over-wide request dict) drops the
    # whole cycle to the row-wise oracle (_classify_heads/_fill_w_rows),
    # which stays the reference path and the verify-mode differential.
    store = getattr(snapshot, "workload_columns", None)
    col_view = None
    if (_COLUMNS_MODE != "off" and store is not None and heads
            and not fair_sharing and not snapshot.tas_flavors):
        col_view = store.gather(heads, snapshot, resource_flavors)
        if tracing.ENABLED:
            if col_view is None:
                tracing.inc("solver_encode_columns_fallback_total",
                            {"reason": "ragged"})
            else:
                tracing.set_gauge(
                    "solver_encode_columns_rows",
                    float(len(col_view.rows)),
                )
                tracing.set_gauge(
                    "solver_encode_columns_filled",
                    float(col_view.filled),
                )
                tracing.set_gauge(
                    "solver_encode_columns_generation",
                    float(store.generation),
                )
    if col_view is not None:
        device_wls = [heads[j] for j in col_view.device_idx]
        wl_slots = None
        idx.workloads = device_wls
        idx.host_fallback = [heads[j] for j in col_view.fallback_idx]
        idx.delayed_tas = [False] * len(device_wls)
        need_slots = False
        s_n = 1
    else:
        device_wls, wl_slots, need_slots, s_n = _classify_heads(
            snapshot, heads, idx, fair_sharing, preempt, delay_tas_fn,
            tas_device_flavors, fair_tas_single, root_of_cq,
        )

    # Unified compile bucket (models/buckets.py, min 16): the W axis
    # shrinks cycle over cycle as entries admit, and an exact-size pad
    # would recompile every kernel per cycle; bucketing reuses one
    # compiled program across cycles (and across same-bucket scenarios
    # in one process — driver and whatif paths share the same ladder).
    # Padding rows are inert (w_active=False), identical to the old %8
    # rows.
    if w_pad == 0:
        w = buckets.bucket_for(len(device_wls))
    else:
        w = w_pad
    w_cq = np.zeros(w, dtype=np.int32)
    w_req = np.zeros((w, r), dtype=np.int64)
    w_elig = np.zeros((w, f), dtype=bool)
    w_active = np.zeros(w, dtype=bool)
    w_priority = np.zeros(w, dtype=np.int64)
    w_timestamp = np.zeros(w, dtype=np.float64)
    w_qr = np.zeros(w, dtype=bool)
    w_start = np.zeros(w, dtype=np.int32)
    w_gates = np.zeros(w, dtype=bool)
    w_pp = np.zeros((w, r), dtype=np.int64)
    w_cnt = np.ones(w, dtype=np.int64)
    w_minc = np.ones(w, dtype=np.int64)
    w_part = np.zeros(w, dtype=bool)

    from kueue_tpu.utils import features as _feat

    partial_on = _feat.enabled("PartialAdmission") and not fair_sharing

    k_n = max(f, 1)
    if need_slots:
        s_req = np.zeros((w, s_n, r), dtype=np.int64)
        s_elig = np.zeros((w, s_n, f), dtype=bool)
        s_flavor_at = np.zeros((w, s_n, k_n), dtype=np.int32)
        s_nf = np.zeros((w, s_n), dtype=np.int32)
        s_start_arr = np.zeros((w, s_n), dtype=np.int32)
        s_valid = np.zeros((w, s_n), dtype=bool)
        w_simple = np.zeros(w, dtype=bool)

    cols = dict(
        w_cq=w_cq, w_req=w_req, w_elig=w_elig, w_active=w_active,
        w_priority=w_priority, w_timestamp=w_timestamp, w_qr=w_qr,
        w_start=w_start, w_gates=w_gates, w_pp=w_pp, w_cnt=w_cnt,
        w_minc=w_minc, w_part=w_part,
    )
    if col_view is not None:
        # Columnar W plane: vocabulary translation tables plus one
        # gather/scatter per column (cache/columns.py assemble) — the
        # per-row Python walk the store amortizes away.
        store.assemble(
            col_view.rows, tidx.node_of, tidx.flavor_of, tidx.resource_of,
            {
                "w_cq": w_cq, "w_active": w_active,
                "w_priority": w_priority, "w_timestamp": w_timestamp,
                "w_quota_reserved": w_qr, "w_gates": w_gates,
                "w_start_flavor": w_start, "w_req": w_req,
                "w_elig": w_elig, "w_count": w_cnt, "w_min_count": w_minc,
            },
        )
        if _COLUMNS_MODE == "verify":
            _verify_columns(
                snapshot, heads, tidx, resource_flavors, partial_on,
                fair_sharing, preempt, delay_tas_fn, tas_device_flavors,
                fair_tas_single, root_of_cq, device_wls,
                idx.host_fallback, cols,
            )
    else:
        _fill_w_rows(
            device_wls, wl_slots, snapshot, tidx, resource_flavors,
            partial_on, need_slots, idx, cols,
            dict(
                s_req=s_req, s_elig=s_elig, s_flavor_at=s_flavor_at,
                s_nf=s_nf, s_start_arr=s_start_arr, s_valid=s_valid,
                w_simple=w_simple,
            ) if need_slots else None,
        )

    partial_fields: Dict[str, object] = {}
    if w_part.any():
        idx.has_partial = True
        partial_fields = dict(
            w_req_pp=w_pp, w_count=w_cnt, w_min_count=w_minc,
            w_partial=w_part,
        )

    slot_fields: Dict[str, object] = {}
    if need_slots:
        idx.slots = wl_slots
        idx.n_slots = s_n
        slot_fields = dict(
            s_req=s_req, s_elig=s_elig, s_flavor_at=s_flavor_at,
            s_n_flavors=s_nf, s_start=s_start_arr, s_valid=s_valid,
            w_simple_slot=w_simple,
        )

    preempt_fields: Dict[str, object] = {}
    root_merge = None
    fair_node_ok = None
    if preempt:
        # TAS encoding first: _encode_admitted reuses its snapshots/leaf
        # permutations to express admitted workloads' TAS usage on the
        # device topologies (victim-release modeling in the preempt
        # kernel's tas_fits probe).
        if tas_device_flavors:
            tas_fields, root_merge = _encode_tas(
                snapshot, tidx, idx, device_wls, w, tas_device_flavors,
                np.asarray(tree.parent),
            )
            preempt_fields.update(tas_fields)
        adm_comp = _component_cached("adm")
        if adm_comp is not None:
            (adm_list, adm_arrays, preempt_simple, preempt_hier,
             fair_node_ok, preempt_tas_ok) = adm_comp
            idx.admitted = list(adm_list)
            idx.admitted_arrays = adm_arrays
        else:
            preempt_simple, preempt_hier, fair_node_ok, preempt_tas_ok = \
                _encode_admitted(snapshot, tidx, tree, idx, fair_sharing)
        preempt_fields.update(
            bwc_policy=np.asarray(bwc_policy),
            bwc_threshold=np.asarray(bwc_threshold),
            bwc_has_threshold=np.asarray(bwc_has_threshold),
            preempt_simple=np.asarray(preempt_simple),
            w_has_gates=np.asarray(w_gates),
        )
        if tas_device_flavors:
            preempt_fields["preempt_tas_ok"] = np.asarray(preempt_tas_ok)
        if preempt_hier.any():
            # Omitted (None) when no nested lend-free tree exists, so the
            # common flat-only cycle compiles without the hier kernel.
            preempt_fields["preempt_hier"] = np.asarray(preempt_hier)
    if fair_sharing:
        from kueue_tpu.utils import features as _features

        node_weight = np.ones(n, dtype=np.float64)
        for i, nd in enumerate(tidx.nodes):
            node_weight[i] = nd.fair_weight
        strategies = list(
            fair_strategies
            or ["LessThanOrEqualToFinalShare", "LessThanInitialShare"]
        )
        preempt_fields["node_weight"] = np.asarray(node_weight)
        preempt_fields["node_is_cq"] = np.asarray(np.asarray(is_cq))
        preempt_fields["fair_pwn"] = np.asarray(
            _features.enabled("FairSharingPreemptWithinNominal")
        )
        preempt_fields["fair_strat0"] = np.asarray(
            np.int32(0 if strategies[0] == "LessThanOrEqualToFinalShare"
                     else 1)
        )
        preempt_fields["fair_has_s2"] = np.asarray(len(strategies) > 1)
        if fair_node_ok is not None:
            preempt_fields["fair_preempt_ok"] = np.asarray(fair_node_ok)

    # Cohort trees sharing a device TAS flavor are merged into one scan
    # group: their entries consume the same topology state, so the grouped
    # scan must serialize them (quota trees alone are independent).
    layout = GroupLayout(
        np.asarray(tree.parent), np.asarray(tree.active),
        root_merge=root_merge,
    )
    from kueue_tpu.models.batch_scheduler import GroupArrays

    idx.group_arrays = GroupArrays(*layout.as_numpy())

    arrays = CycleArrays(
        tree=tree,
        usage=usage_full,
        flavor_at=np.asarray(flavor_at),
        n_flavors=np.asarray(n_flavors),
        covered=np.asarray(covered),
        when_can_borrow_try_next=np.asarray(borrow_try_next),
        when_can_preempt_try_next=np.asarray(preempt_try_next),
        pref_preempt_over_borrow=np.asarray(pref_pob),
        can_preempt_while_borrowing=np.asarray(cpwb),
        never_preempts=np.asarray(never_preempts),
        can_always_reclaim=np.asarray(can_always_reclaim),
        # May be cached on-device tensors (incremental encode) — pass
        # through untouched; device_put is a no-op for resident arrays.
        usage_by_prio=usage_by_prio,
        prio_cuts=prio_cuts,
        prefilter_valid=prefilter_valid,
        policy_within=np.asarray(policy_within),
        policy_reclaim=np.asarray(policy_reclaim),
        nominal_cq=tree.nominal,
        w_cq=np.asarray(w_cq),
        w_req=np.asarray(w_req),
        w_elig=np.asarray(w_elig),
        w_active=np.asarray(w_active),
        w_priority=np.asarray(w_priority),
        w_timestamp=np.asarray(w_timestamp),
        w_quota_reserved=np.asarray(w_qr),
        w_start_flavor=np.asarray(w_start),
        w_order_rank=np.asarray(_order_rank(w_priority, w_timestamp)),
        **partial_fields,
        **slot_fields,
        **preempt_fields,
    )
    # ONE batched host->device transfer for every encoded tensor: over a
    # remote device transport (axon tunnel: 20-65 ms per dispatch),
    # per-field jnp.asarray costs a round trip each — ~50 fields made the
    # encode transfer-bound (2.2 s at the 15k-workload baseline).
    if device_put:
        arrays, idx.group_arrays, idx.admitted_arrays = jax.device_put(
            (arrays, idx.group_arrays, idx.admitted_arrays)
        )
    if comp_keys is not None:
        admitted_cache["prio"] = (
            comp_keys["prio"],
            (arrays.usage_by_prio, arrays.prio_cuts, arrays.prefilter_valid),
        )
        if preempt:
            admitted_cache["adm"] = (
                comp_keys["adm"],
                (list(idx.admitted), idx.admitted_arrays, preempt_simple,
                 preempt_hier, fair_node_ok, preempt_tas_ok),
            )
    return arrays, idx


def _classify_heads(
    snapshot, heads, idx, fair_sharing, preempt, delay_tas_fn,
    tas_device_flavors, fair_tas_single, root_of_cq,
):
    """Row-wise head classification — the oracle the columnar gather is
    verified against, and the only classifier for fair/TAS/ragged
    cycles. Per-workload Python by design (the allowlisted fallback in
    tools/check_encode_columns.py). Returns ``(device_wls, wl_slots,
    need_slots, s_n)``; mutates ``idx`` (fallbacks, delayed flags, fair
    scan bound) exactly as the pre-columnar encoder did."""
    device_wls: List[WorkloadInfo] = []
    wl_slots: List[List[AssignSlot]] = []
    for info in heads:
        slots = (
            _workload_slots(info, snapshot.cluster_queues[info.cluster_queue])
            if info.cluster_queue in snapshot.cluster_queues else None
        )
        fair_host = False
        if fair_sharing and info.cluster_queue in snapshot.cluster_queues:
            if any(
                ps2.topology_request is not None
                for ps2 in info.obj.pod_sets
            ):
                # The tournament's placement threading is only race-free
                # when every TAS flavor the entry might land on is
                # reachable from a single cohort root (fair_tas_single).
                # The check spans exactly the resource groups the entry's
                # slots assign from (an off-RG0 single podset places on
                # ITS group's flavors, not RG0's); uncovered entries
                # (slots=None) never reach the device path, but check all
                # groups anyway so fair_host never under-approximates.
                rgs0 = snapshot.cluster_queues[
                    info.cluster_queue
                ].spec.resource_groups
                if slots is not None:
                    rg_ids = sorted({sl.rg_idx for sl in slots})
                    rgs_chk = [rgs0[i] for i in rg_ids if i < len(rgs0)]
                else:
                    rgs_chk = rgs0
                tas_names = [
                    fq.name
                    for rg0 in rgs_chk
                    for fq in rg0.flavors
                    if fq.name in snapshot.tas_flavors
                ]
                fair_host = not tas_names or not all(
                    fair_tas_single.get(nm, False) for nm in tas_names
                )
        delayed = bool(
            delay_tas_fn is not None
            and info.cluster_queue in snapshot.cluster_queues
            and any(
                ps.topology_request is not None
                for ps in info.obj.pod_sets
            )
            and delay_tas_fn(
                snapshot.cluster_queues[info.cluster_queue], info
            )
        )
        if not fair_host and _device_compatible(
                info, snapshot, slots,
                set(tas_device_flavors), delayed,
                preempt, fair_sharing):
            device_wls.append(info)
            wl_slots.append(slots)
            idx.delayed_tas.append(delayed)
        else:
            idx.host_fallback.append(info)

    if fair_sharing:
        # Steps the tournament scan actually needs (see CycleIndex):
        # max over cohort roots of the number of device CQs with >=1
        # entry under that root.
        cqs_of_root: Dict[int, set] = {}
        for info in device_wls:
            # root_of_cq covers every snapshot CQ, and _device_compatible
            # guarantees device entries' CQs are in the snapshot.
            cqs_of_root.setdefault(
                root_of_cq[info.cluster_queue], set()
            ).add(info.cluster_queue)
        bound = max((len(s) for s in cqs_of_root.values()), default=1)
        idx.fair_s_bound = buckets.pow2_bucket(bound, floor=4)

    # Layout: the dense legacy (single-slot, first-RG) layout compiles the
    # existing kernels unchanged; any multi-podset or off-RG0 entry
    # switches the cycle to the slot layout (padded S axis, slot fields).
    need_slots = any(
        len(sl) > 1 or sl[0].rg_idx != 0 for sl in wl_slots
    )
    s_n = 1
    if need_slots:
        # Power-of-two compile bucket for the slot axis.
        s_n = buckets.pow2_bucket(max(len(sl) for sl in wl_slots))
    return device_wls, wl_slots, need_slots, s_n


def _fill_w_rows(
    device_wls, wl_slots, snapshot, tidx, resource_flavors, partial_on,
    need_slots, idx, cols, slot_cols,
):
    """Row-wise W fill — the oracle the columnar plane is bit-compared
    against (verify mode and the randomized differentials), and the only
    fill for ragged cycles (slot layouts, partial admission, fair/TAS
    context). Per-workload Python by design; appends each device row to
    ``idx.workloads`` exactly as the pre-columnar encoder did."""
    from kueue_tpu.scheduler.flavorassigner import FlavorAssigner

    w_cq = cols["w_cq"]
    w_req = cols["w_req"]
    w_elig = cols["w_elig"]
    w_active = cols["w_active"]
    w_priority = cols["w_priority"]
    w_timestamp = cols["w_timestamp"]
    w_qr = cols["w_qr"]
    w_start = cols["w_start"]
    w_gates = cols["w_gates"]
    w_pp = cols["w_pp"]
    w_cnt = cols["w_cnt"]
    w_minc = cols["w_minc"]
    w_part = cols["w_part"]
    f = w_elig.shape[1]
    if need_slots:
        s_req = slot_cols["s_req"]
        s_elig = slot_cols["s_elig"]
        s_flavor_at = slot_cols["s_flavor_at"]
        s_nf = slot_cols["s_nf"]
        s_start_arr = slot_cols["s_start_arr"]
        s_valid = slot_cols["s_valid"]
        w_simple = slot_cols["w_simple"]

    for i, info in enumerate(device_wls):
        idx.workloads.append(info)
        slots = wl_slots[i]
        cqs = snapshot.cluster_queues[info.cluster_queue]
        w_cq[i] = tidx.node_of[info.cluster_queue]
        w_active[i] = True
        w_priority[i] = info.priority()
        w_timestamp[i] = queue_order_timestamp(info.obj)
        w_qr[i] = has_quota_reservation(info.obj)
        w_gates[i] = bool(info.obj.preemption_gates)
        ps0 = info.obj.pod_sets[0]
        w_cnt[i] = ps0.count
        w_minc[i] = ps0.count
        # Legacy request vector = slot 0 (equals total_requests[0] for
        # single-slot first-RG workloads; the per-entry preemption and
        # partial-admission kernels only apply to those — w_simple_slot).
        for res, v in slots[0].requests.items():
            if res in tidx.resource_of:
                w_req[i, tidx.resource_of[res]] = v
        if (partial_on and ps0.min_count is not None
                and ps0.min_count < ps0.count):
            # Reducible entry (vetted by _device_compatible: single
            # podset, non-TAS, exact per-pod totals; preempting CQs
            # allowed in preempt cycles — the search probes the
            # victim-search kernel).
            w_part[i] = True
            w_minc[i] = ps0.min_count
            for res, v in ps0.requests.items():
                if res in tidx.resource_of:
                    w_pp[i, tidx.resource_of[res]] = v
        # Taints/affinity eligibility per flavor and slot (host-side;
        # reuses the exact assigner's check). The verdict depends only on
        # flavor specs and the slot's podsets, so it is cached on the
        # WorkloadInfo keyed by the cache spec generation — a requeued
        # workload re-encodes in O(S*F) array copy instead of re-running
        # the matcher every cycle.
        gen = cqs.allocatable_generation
        cached = getattr(info, "_elig_cache", None)
        if cached is not None and cached[0] == gen \
                and cached[1].shape == (len(slots), f):
            erows = cached[1]
        else:
            assigner = FlavorAssigner(info, cqs, resource_flavors)
            erows = np.zeros((len(slots), f), dtype=bool)
            for si, sl in enumerate(slots):
                pod_sets = [info.obj.pod_sets[j] for j in sl.ps_ids]
                for fname, fi in tidx.flavor_of.items():
                    ok, _ = assigner._check_flavor_for_podsets(
                        fname, pod_sets
                    )
                    erows[si, fi] = ok
            info._elig_cache = (gen, erows)
        allowed = info.obj.labels.get(
            "kueue.x-k8s.io/allowed-resource-flavor"
        )
        if allowed is not None:
            # ConcurrentAdmission variants race one flavor each: the host
            # scan skips every other flavor (flavorassigner.go:981
            # semantics); masking eligibility is the identical device
            # behavior (skipped and NoFit flavors both advance the scan).
            amask = np.zeros(f, dtype=bool)
            ai = tidx.flavor_of.get(allowed)
            if ai is not None:
                amask[ai] = True
            erows = erows & amask[None, :]
        w_elig[i] = erows[0]
        resume = info.last_assignment is not None and (
            cqs.allocatable_generation
            <= info.last_assignment.cluster_queue_generation
        )
        if resume:
            # Per-slot resume key: the resource that opens the slot's RG
            # search (first in sorted group-request order), exactly the
            # host's res_name at flavorassigner.go:425.
            w_start[i] = info.last_assignment.next_flavor_to_try(
                slots[0].ps_ids[0], slots[0].trigger_res
            )
        if need_slots:
            w_simple[i] = len(slots) == 1 and slots[0].rg_idx == 0
            for si, sl in enumerate(slots):
                s_valid[i, si] = True
                rg_s = cqs.spec.resource_groups[sl.rg_idx]
                flist = [
                    fq.name for fq in rg_s.flavors
                    if fq.name in tidx.flavor_of
                ]
                s_nf[i, si] = len(flist)
                for k2, fname in enumerate(flist):
                    s_flavor_at[i, si, k2] = tidx.flavor_of[fname]
                for res, v in sl.requests.items():
                    if res in tidx.resource_of:
                        s_req[i, si, tidx.resource_of[res]] = v
                s_elig[i, si] = erows[si]
                if resume:
                    s_start_arr[i, si] = (
                        info.last_assignment.next_flavor_to_try(
                            sl.ps_ids[0], sl.trigger_res
                        )
                    )


def _verify_columns(
    snapshot, heads, tidx, resource_flavors, partial_on, fair_sharing,
    preempt, delay_tas_fn, tas_device_flavors, fair_tas_single,
    root_of_cq, device_wls, host_fallback, cols,
):
    """Verify-mode differential: re-run the row-wise oracle on the same
    cycle and require the columnar partition and every W column to be
    bit-identical. Raises AssertionError on any divergence."""
    ref_idx = CycleIndex(
        tree_index=tidx,
        resources=list(tidx.resources),
        flavors=list(tidx.flavors),
    )
    ref_wls, ref_slots, ref_need_slots, _ = _classify_heads(
        snapshot, heads, ref_idx, fair_sharing, preempt, delay_tas_fn,
        tas_device_flavors, fair_tas_single, root_of_cq,
    )
    if ref_need_slots:
        raise AssertionError(
            "columns/oracle divergence: oracle classified a slot-layout "
            "cycle the gather accepted as dense"
        )
    if [id(x) for x in ref_wls] != [id(x) for x in device_wls]:
        raise AssertionError(
            "columns/oracle divergence: device partition mismatch"
        )
    if [id(x) for x in ref_idx.host_fallback] \
            != [id(x) for x in host_fallback]:
        raise AssertionError(
            "columns/oracle divergence: host-fallback partition mismatch"
        )
    ref_cols = {
        k: (np.ones_like(v) if k in ("w_cnt", "w_minc")
            else np.zeros_like(v))
        for k, v in cols.items()
    }
    _fill_w_rows(
        ref_wls, ref_slots, snapshot, tidx, resource_flavors, partial_on,
        False, ref_idx, ref_cols, None,
    )
    for k in cols:
        if not np.array_equal(cols[k], ref_cols[k]):
            raise AssertionError(
                f"columns/oracle divergence on {k}"
            )


def _order_rank(priority: np.ndarray, timestamp: np.ndarray) -> np.ndarray:
    """Rank of each row under (priority desc, timestamp asc, submission
    asc) — the static part of the classical iterator's key, precomputed on
    host so the device sorts once."""
    order = np.lexsort((timestamp, -priority))
    rank = np.zeros(priority.shape[0], np.int32)
    rank[order] = np.arange(priority.shape[0], dtype=np.int32)
    return rank


def _encode_tas(
    snapshot, tidx, idx, device_wls, w, flavor_names, parent_arr
) -> Tuple[Dict[str, object], Dict[int, int]]:
    """Encode device-TAS arrays: padded topologies, cycle-start leaf usage,
    per-workload placement requests, and the root-merge map for scan
    grouping."""
    from kueue_tpu.ops.tas_place import encode_device_topos

    topo, tas_snaps, leaf_perm = encode_device_topos(
        snapshot.tas_flavors, flavor_names, tidx.resource_of
    )
    idx.tas_flavor_names = list(flavor_names)
    idx.tas_snapshots = tas_snaps
    idx.tas_leaf_perm = leaf_perm
    t_n = max(len(flavor_names), 1)
    d_n = topo.leaf_cap.shape[1]
    r1 = topo.leaf_cap.shape[2]  # cycle resources + implicit pods column
    r_cy = r1 - 1
    idx.tas_pad_shape = (d_n, r1)

    usage0 = np.zeros((t_n, d_n, r1), np.int64)
    for t, tas in enumerate(tas_snaps):
        inv = {hi: j for j, hi in enumerate(leaf_perm[t])}
        for leaf_id, used in tas.usage.items():
            hi = tas._leaf_index.get(tas._canonical_leaf_id(leaf_id))
            if hi is None:
                continue
            j = inv[hi]
            for res, v in used.items():
                ci = tidx.resource_of.get(res)
                if ci is not None:
                    usage0[t, j, ci] += v
                if res == "pods":
                    # Mirror into the implicit-pods column so unrequested
                    # pod-count bounds see explicit pods consumption too.
                    usage0[t, j, r_cy] += v

    f_n = max(len(tidx.flavors), 1)
    tas_of_flavor = np.full(f_n, -1, np.int32)
    for t, name in enumerate(flavor_names):
        fi = tidx.flavor_of.get(name)
        if fi is not None:
            tas_of_flavor[fi] = t

    w_tas = np.zeros(w, bool)
    w_tas_req = np.zeros((w, r1), np.int64)
    w_tas_usage_req = np.zeros((w, r1), np.int64)  # per-pod usage deltas
    w_tas_count = np.zeros(w, np.int64)
    w_tas_slice_size = np.ones(w, np.int64)
    w_tas_req_level = np.full((w, t_n), -1, np.int32)
    w_tas_slice_level = np.full((w, t_n), -1, np.int32)
    from kueue_tpu.ops.tas_place import LMAX as _LMAX

    w_tas_sizes = np.ones((w, t_n, _LMAX), np.int64)
    w_tas_required = np.zeros(w, bool)
    w_tas_uncon = np.zeros(w, bool)
    w_tas_invalid = np.zeros(w, bool)
    w_tas_bal = np.zeros(w, bool)
    # Per-entry filtered leaf capacity (host _matching_capacity analog):
    # required whenever the fleet has tainted nodes or the entry carries a
    # node selector / tolerations — capacity must come only from nodes the
    # entry's pods can land on. Built lazily; None when nobody needs it.
    w_tas_cap = None
    w_tas_has_cap = None
    fleet_tainted = [tas.has_tainted_nodes for tas in tas_snaps]
    row_of_flavor = {name: t for t, name in enumerate(flavor_names)}
    from kueue_tpu.utils import features as _bfeat

    bal_gate_on = _bfeat.enabled("TASBalancedPlacement")

    w_tas_leader_req = None
    w_tas_leader_usage = None
    w_tas_has_leader = None

    # Generic multi-podset / multi-RG TAS entries take the per-slot
    # path below; the legacy per-entry loop must not claim them.
    _slots_list = idx.slots if idx.slots else None
    _multi_tas_set = set()
    if _slots_list is not None:
        from kueue_tpu.scheduler.flavorassigner import is_lws_group             as _is_lws

        for _i, _info in enumerate(device_wls):
            if _i >= len(_slots_list) or _slots_list[_i] is None:
                continue
            _sl = _slots_list[_i]
            if not (len(_sl) > 1 or _sl[0].rg_idx != 0):
                continue
            if _is_lws(_info.obj.pod_sets):
                continue
            if idx.delayed_tas and idx.delayed_tas[_i]:
                continue
            if any(ps.topology_request is not None
                   for ps in _info.obj.pod_sets):
                _multi_tas_set.add(_i)

    def _fill_request_rows(ps, tr, set_vec, set_scalar, set_level,
                           set_sizes):
        """Per-request fill for the per-slot TAS rows: request/usage
        vectors, slice config and per-topology level/size rows. Same
        rules as the legacy per-entry loop below (which keeps its
        long-validated inline copy) — change BOTH when the level/size
        derivation changes."""
        for res, v in ps.requests.items():
            ci = tidx.resource_of.get(res)
            if ci is not None:
                set_vec("req", ci, v)
                set_vec("usage", ci, v)
        pods_req = ps.requests.get("pods", 0)
        set_vec("req", r_cy, 0 if pods_req > 0 else 1)
        set_vec("usage", r_cy, pods_req)
        required = tr.required_level is not None
        uncon = tr.unconstrained or (
            tr.required_level is None and tr.preferred_level is None
        )
        level_key = tr.required_level or tr.preferred_level
        has_slice = tr.slice_required_level is not None
        ssz = (tr.slice_size or 1) if has_slice else 1
        set_scalar("count", ps.count)
        set_scalar("ssz", ssz)
        set_scalar("required", required)
        set_scalar("uncon", uncon)
        invalid = bool(ssz > 0 and ps.count % ssz != 0)
        for t, tas in enumerate(tas_snaps):
            keys = tas.level_keys
            lk = level_key if level_key is not None else (
                keys[-1] if keys else None
            )
            if lk not in keys:
                continue
            rl = keys.index(lk)
            if has_slice:
                if tr.slice_required_level not in keys:
                    continue
                sl = keys.index(tr.slice_required_level)
            else:
                sl = len(keys) - 1
            if rl > sl:
                continue
            layers_ok = True
            if getattr(tr, "slice_layers", None):
                from kueue_tpu.utils import features as _lfeat

                if not _lfeat.enabled("TASMultiLayerTopology"):
                    layers_ok = False
                prev_idx2, prev_size2 = sl, ssz
                for layer_level, layer_size in tr.slice_layers:
                    if layer_level not in keys:
                        layers_ok = False
                        break
                    li2 = keys.index(layer_level)
                    if (li2 <= prev_idx2 or layer_size <= 0
                            or prev_size2 % layer_size != 0):
                        layers_ok = False
                        break
                    set_sizes(t, prev_idx2 + 1, li2 + 1, layer_size)
                    prev_idx2, prev_size2 = li2, layer_size
            if not layers_ok:
                set_sizes(t, 0, _LMAX, 1)
                continue
            set_level(t, rl, sl)
        return invalid

    for i, info in enumerate(device_wls):
        if i in _multi_tas_set:
            continue
        pods = info.obj.pod_sets
        ps = pods[0]
        leader_ps = None
        from kueue_tpu.scheduler.flavorassigner import (
            find_leader_and_workers,
            is_lws_group,
        )

        if is_lws_group(pods):
            li_, wi_ = find_leader_and_workers(pods, [0, 1])
            leader_ps, ps = pods[li_], pods[wi_]
        tr = ps.topology_request
        if tr is None:
            continue
        if idx.delayed_tas and idx.delayed_tas[i]:
            # Quota-only first pass: no topology tensors; the second
            # pass places after provisioning (scheduler.go:840-884).
            continue
        w_tas[i] = True
        w_tas_count[i] = ps.count
        for res, v in ps.requests.items():
            ci = tidx.resource_of.get(res)
            if ci is not None:
                w_tas_req[i, ci] = v
                w_tas_usage_req[i, ci] = v
        if leader_ps is not None:
            if w_tas_leader_req is None:
                w_tas_leader_req = np.zeros((w, r1), np.int64)
                w_tas_leader_usage = np.zeros((w, r1), np.int64)
                w_tas_has_leader = np.zeros(w, bool)
            w_tas_has_leader[i] = True
            for res, v in leader_ps.requests.items():
                ci = tidx.resource_of.get(res)
                if ci is not None:
                    w_tas_leader_req[i, ci] = v
                    w_tas_leader_usage[i, ci] = v
            # Fit vector: the leader occupies one pod slot on top of any
            # explicit pods request (OnePodRequest, flavorassigner :965);
            # usage adds only the explicit resources (_add_tas_usage).
            lp = leader_ps.requests.get("pods", 0)
            w_tas_leader_req[i, r_cy] = lp + 1
            w_tas_leader_usage[i, r_cy] = lp
        pods_req = ps.requests.get("pods", 0)
        # Fit vector: implicit 1-pod bound unless pods explicitly requested.
        # Usage vector: only explicit pods consumption mirrors into the
        # implicit column (add_usage adds requested resources only).
        w_tas_req[i, r_cy] = 0 if pods_req > 0 else 1
        w_tas_usage_req[i, r_cy] = pods_req

        required = tr.required_level is not None
        uncon = tr.unconstrained or (
            tr.required_level is None and tr.preferred_level is None
        )
        level_key = tr.required_level or tr.preferred_level
        has_slice = tr.slice_required_level is not None
        ssz = (tr.slice_size or 1) if has_slice else 1
        w_tas_slice_size[i] = ssz
        w_tas_required[i] = required
        w_tas_uncon[i] = uncon
        w_tas_bal[i] = (
            (tr.balanced or bal_gate_on) and not required and not uncon
        )
        if ssz > 0 and ps.count % ssz != 0:
            w_tas_invalid[i] = True
        for t, tas in enumerate(tas_snaps):
            keys = tas.level_keys
            lk = level_key if level_key is not None else (
                keys[-1] if keys else None
            )
            if lk not in keys:
                continue  # stays -1: infeasible on this flavor
            rl = keys.index(lk)
            if has_slice:
                if tr.slice_required_level not in keys:
                    continue
                sl = keys.index(tr.slice_required_level)
            else:
                sl = len(keys) - 1
            if rl > sl:
                continue  # host rejects: slice level above podset level
            # Multi-layer slice sizes (buildSliceSizeAtLevel): each inner
            # layer must be strictly deeper and divide the outer size;
            # intermediate levels inherit the inner layer's size. A bad
            # layer config is infeasible on this flavor (the host returns
            # a reason), so the levels stay -1.
            layers_ok = True
            if getattr(tr, "slice_layers", None):
                from kueue_tpu.utils import features as _lfeat

                if not _lfeat.enabled("TASMultiLayerTopology"):
                    layers_ok = False
                prev_idx2, prev_size2 = sl, ssz
                for layer_level, layer_size in tr.slice_layers:
                    if layer_level not in keys:
                        layers_ok = False
                        break
                    li2 = keys.index(layer_level)
                    if (li2 <= prev_idx2 or layer_size <= 0
                            or prev_size2 % layer_size != 0):
                        layers_ok = False
                        break
                    w_tas_sizes[i, t, prev_idx2 + 1:li2 + 1] = layer_size
                    prev_idx2, prev_size2 = li2, layer_size
            if not layers_ok:
                w_tas_sizes[i, t, :] = 1
                continue
            w_tas_req_level[i, t] = rl
            w_tas_slice_level[i, t] = sl

        # Only topologies reachable through the entry's OWN CQ flavors:
        # w_tas_req_level is filled for every snapshot whose level keys
        # match, but the runtime row comes from tas_of_flavor of the CQ's
        # resource group — a foreign topology's cap row would be wrong.
        cq_spec = snapshot.cluster_queues[info.cluster_queue].spec
        cq_rows = {
            row_of_flavor[fq.name]
            for rg2 in cq_spec.resource_groups[:1]
            for fq in rg2.flavors
            if fq.name in row_of_flavor
        }
        need_filter = [
            t for t in sorted(cq_rows)
            if w_tas_req_level[i, t] >= 0
            and (fleet_tainted[t] or ps.node_selector or ps.tolerations)
        ]
        if need_filter:
            # Exactly one mappable topology per filtered entry (the
            # _device_compatible multi-flavor gate guarantees it), so one
            # [D, R+1] row in that topology's device leaf order is exact.
            if w_tas_cap is None:
                w_tas_cap = np.zeros((w, d_n, r1), np.int64)
                w_tas_has_cap = np.zeros(w, bool)
            from kueue_tpu.tas.snapshot import PlacementRequest

            req_obj = PlacementRequest(
                count=ps.count,
                single_pod_requests=dict(ps.requests),
                node_selector=dict(ps.node_selector),
                tolerations=list(ps.tolerations),
            )
            t = need_filter[0]
            tas = tas_snaps[t]
            inv = {hi: j for j, hi in enumerate(leaf_perm[t])}
            cap = tas._matching_capacity(req_obj)  # [leaves, host R]
            row = np.zeros((d_n, r1), np.int64)
            row[:, r_cy] = 1 << 60  # implicit-pods column default
            for hi, j in inv.items():
                for res, ri in tas._res_index.items():
                    ci = tidx.resource_of.get(res)
                    if ci is not None:
                        row[j, ci] = cap[hi, ri]
                    if res == "pods":
                        row[j, r_cy] = cap[hi, ri]
            w_tas_cap[i] = row
            w_tas_has_cap[i] = True

    # Root merging: union roots of CQs sharing a device TAS flavor.
    n = parent_arr.shape[0]
    root_of = np.arange(n)
    for _ in range(9):
        root_of = np.where(
            parent_arr[root_of] >= 0, parent_arr[root_of], root_of
        )
    uf: Dict[int, int] = {}

    def find(x):
        while uf.get(x, x) != x:
            uf[x] = uf.get(uf[x], uf[x])
            x = uf[x]
        return x

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            uf[max(ra, rb)] = min(ra, rb)

    flavor_anchor: Dict[str, int] = {}
    for cq_name, cqs2 in snapshot.cluster_queues.items():
        ni = tidx.node_of[cq_name]
        for rg in cqs2.spec.resource_groups:
            for fq in rg.flavors:
                if fq.name in flavor_names:
                    anchor = flavor_anchor.get(fq.name)
                    if anchor is None:
                        flavor_anchor[fq.name] = int(root_of[ni])
                    else:
                        union(anchor, int(root_of[ni]))
    root_merge = {int(r): find(int(r)) for r in set(root_of.tolist())}

    fields = dict(
        tas_topo=topo,
        tas_usage0=np.asarray(usage0),
        tas_of_flavor=np.asarray(tas_of_flavor),
        w_tas=np.asarray(w_tas),
        w_tas_req=np.asarray(w_tas_req),
        w_tas_usage_req=np.asarray(w_tas_usage_req),
        w_tas_count=np.asarray(w_tas_count),
        w_tas_slice_size=np.asarray(w_tas_slice_size),
        w_tas_req_level=np.asarray(w_tas_req_level),
        w_tas_slice_level=np.asarray(w_tas_slice_level),
        w_tas_sizes=np.asarray(w_tas_sizes),
        w_tas_required=np.asarray(w_tas_required),
        w_tas_unconstrained=np.asarray(w_tas_uncon),
        w_tas_invalid=np.asarray(w_tas_invalid),
    )
    if w_tas_bal.any():
        fields["w_tas_balanced"] = np.asarray(w_tas_bal)
    if w_tas_cap is not None:
        fields["w_tas_cap"] = w_tas_cap
        fields["w_tas_has_cap"] = w_tas_has_cap
    if w_tas_has_leader is not None:
        fields["w_tas_leader_req"] = np.asarray(w_tas_leader_req)
        fields["w_tas_leader_usage_req"] = np.asarray(w_tas_leader_usage)
        fields["w_tas_has_leader"] = np.asarray(w_tas_has_leader)

    # Per-slot TAS rows for generic multi-podset / multi-RG TAS entries
    # (singleton podset groups only — the compat gate enforces it).
    # These entries keep w_tas False; the grouped scan runs the per-slot
    # sequential placement path for them alongside the legacy path.
    multi_rows = sorted(_multi_tas_set)
    if multi_rows:
        slots_list = idx.slots
        if slots_list:
            s_n2 = idx.n_slots
            s_tas = np.zeros((w, s_n2), bool)
            s_req_v = np.zeros((w, s_n2, r1), np.int64)
            s_usage_v = np.zeros((w, s_n2, r1), np.int64)
            s_count = np.zeros((w, s_n2), np.int64)
            s_ssz = np.ones((w, s_n2), np.int64)
            s_rl = np.full((w, s_n2, t_n), -1, np.int32)
            s_sl = np.full((w, s_n2, t_n), -1, np.int32)
            s_sizes = np.ones((w, s_n2, t_n, _LMAX), np.int64)
            s_required = np.zeros((w, s_n2), bool)
            s_uncon = np.zeros((w, s_n2), bool)
            for i in multi_rows:
                for si, sl_u in enumerate(slots_list[i]):
                    ps = device_wls[i].obj.pod_sets[sl_u.ps_ids[0]]
                    tr = ps.topology_request
                    if tr is None:
                        continue

                    def set_vec(kind, ci, v, i=i, si=si):
                        (s_req_v if kind == "req" else s_usage_v)[
                            i, si, ci
                        ] = v

                    def set_scalar(kind, v, i=i, si=si):
                        if kind == "count":
                            s_count[i, si] = v
                        elif kind == "ssz":
                            s_ssz[i, si] = v
                        elif kind == "required":
                            s_required[i, si] = v
                        elif kind == "uncon":
                            s_uncon[i, si] = v

                    def set_level(t, rl, sl2, i=i, si=si):
                        s_rl[i, si, t] = rl
                        s_sl[i, si, t] = sl2

                    def set_sizes(t, lo, hi, v, i=i, si=si):
                        s_sizes[i, si, t, lo:hi] = v

                    invalid = _fill_request_rows(
                        ps, tr, set_vec, set_scalar, set_level, set_sizes
                    )
                    if invalid:
                        w_tas_invalid[i] = True
                    s_tas[i, si] = True
            fields["s_tas"] = s_tas
            fields["s_tas_req"] = s_req_v
            fields["s_tas_usage_req"] = s_usage_v
            fields["s_tas_count"] = s_count
            fields["s_tas_slice_size"] = s_ssz
            fields["s_tas_req_level"] = s_rl
            fields["s_tas_slice_level"] = s_sl
            fields["s_tas_sizes"] = s_sizes
            fields["s_tas_required"] = s_required
            fields["s_tas_unconstrained"] = s_uncon
            fields["w_tas_invalid"] = np.asarray(w_tas_invalid)
    return fields, root_merge


def _encode_admitted(snapshot, tidx, tree, idx, fair_sharing):
    """Build the admitted-candidate arrays (preempt_kernel.AdmittedArrays),
    the per-CQ classical ``preempt_simple`` flag and the fair-tournament
    ``fair_node_ok`` flag.

    Classical device victim selection needs a "simple" tree: flat (root's
    children are all CQs, matching the single-LCA classical search), free
    of lending limits (usage bubbles fully so removal math is closed-form),
    fair sharing off, and every admitted workload's usage mappable onto the
    encoded [F, R] cells. The fair tournament kernel handles nested trees,
    so its flag drops only the flatness requirement."""
    from kueue_tpu.core.workload_info import (
        is_evicted,
        quota_reservation_time,
    )
    from kueue_tpu.models.preempt_kernel import AdmittedArrays

    from kueue_tpu.ops.quota_ops import MAX_DEPTH

    n = tree.n_nodes
    parent = np.asarray(tree.parent)
    is_cq_node = np.zeros(n, dtype=bool)
    for name in snapshot.cluster_queues:
        is_cq_node[tidx.node_of[name]] = True
    root_of = np.arange(n)
    for _ in range(MAX_DEPTH):
        root_of = np.where(parent[root_of] >= 0, parent[root_of], root_of)

    has_lend = np.asarray(tree.has_lend_limit).any(axis=(1, 2))  # [N]
    # Per root: flat (no nested cohorts) and lend-limit free; the fair
    # variant skips the flatness requirement.
    root_ok = np.ones(n, dtype=bool)
    root_fair_ok = np.ones(n, dtype=bool)
    for node in range(n):
        if not np.asarray(tree.active)[node]:
            continue
        r = root_of[node]
        if has_lend[node]:
            root_ok[r] = False
            root_fair_ok[r] = False
        if node != r and not is_cq_node[node]:
            root_ok[r] = False  # nested cohort -> not flat

    infos = []
    for cqs2 in snapshot.cluster_queues.values():
        infos.extend(cqs2.workloads.values())
    a = max(8, _round_up(len(infos), 8))
    f = tree.nominal.shape[1]
    r = tree.nominal.shape[2]
    a_cq = np.zeros(a, dtype=np.int32)
    a_usage = np.zeros((a, f, r), dtype=np.int64)
    a_prio = np.zeros(a, dtype=np.int64)
    a_ts = np.zeros(a, dtype=np.float64)
    a_qr = np.zeros(a, dtype=np.float64)
    a_evicted = np.zeros(a, dtype=bool)
    a_active = np.zeros(a, dtype=bool)

    uids = sorted(info.obj.uid for info in infos)
    uid_rank_of = {u: i for i, u in enumerate(uids)}
    a_uid = np.zeros(a, dtype=np.int32)

    # Admitted TAS usage on device topologies (victim release modeling in
    # the preempt kernel's tas_fits probe). Axis layout matches tas_usage0
    # ([T, D, R+1], same leaf permutation, same implicit-pods mirroring).
    t_n = len(idx.tas_flavor_names)
    tas_root_ok = np.ones(n, dtype=bool)
    a_tas_t = np.full(a, -1, dtype=np.int32)
    a_tas_usage = None
    tas_row_of = {name: t for t, name in enumerate(idx.tas_flavor_names)}
    if t_n:
        d_n, r1 = idx.tas_pad_shape
        a_tas_usage = np.zeros((a, d_n, r1), np.int64)

    for i, info in enumerate(infos):
        ni = tidx.node_of[info.cluster_queue]
        a_cq[i] = ni
        a_active[i] = True
        a_prio[i] = info.priority()
        a_ts[i] = queue_order_timestamp(info.obj)
        a_qr[i] = quota_reservation_time(info.obj, 0.0)
        a_evicted[i] = is_evicted(info.obj)
        a_uid[i] = uid_rank_of[info.obj.uid]
        idx.admitted.append(info)
        for fr2, v2 in info.usage().items():
            fi2 = tidx.flavor_of.get(fr2.flavor)
            ri2 = tidx.resource_of.get(fr2.resource)
            if fi2 is None or ri2 is None:
                # Unmappable usage: the victim-removal math would be wrong
                # for this tree; keep it on the host path.
                root_ok[root_of[ni]] = False
                root_fair_ok[root_of[ni]] = False
            else:
                a_usage[i, fi2, ri2] = v2
        if t_n:
            rows = [
                tas_row_of[f] for f in info.tas_usage() if f in tas_row_of
            ]
            if len(rows) > 1:
                # Multi-topology victims: release modeling out of scope.
                tas_root_ok[root_of[ni]] = False
            elif rows:
                t = rows[0]
                tas = idx.tas_snapshots[t]
                inv = {
                    hi: j for j, hi in enumerate(idx.tas_leaf_perm[t])
                }
                a_tas_t[i] = t
                flavor = idx.tas_flavor_names[t]
                for leaf_id, used in info.tas_usage()[flavor].items():
                    hi = tas._leaf_index.get(
                        tas._canonical_leaf_id(leaf_id)
                    )
                    j = inv.get(hi) if hi is not None else None
                    if j is None:
                        tas_root_ok[root_of[ni]] = False
                        continue
                    for res, v in used.items():
                        ci = tidx.resource_of.get(res)
                        if ci is not None:
                            a_tas_usage[i, j, ci] += v
                        if res == "pods":
                            a_tas_usage[i, j, r1 - 1] += v

    preempt_simple = np.zeros(n, dtype=bool)
    preempt_hier = np.zeros(n, dtype=bool)
    fair_node_ok = np.zeros(n, dtype=bool)
    if not fair_sharing:
        for name in snapshot.cluster_queues:
            ni = tidx.node_of[name]
            preempt_simple[ni] = root_ok[root_of[ni]]
            # Nested lend-free trees take the hierarchical kernel.
            preempt_hier[ni] = (
                root_fair_ok[root_of[ni]] and not root_ok[root_of[ni]]
            )
    else:
        for name in snapshot.cluster_queues:
            ni = tidx.node_of[name]
            fair_node_ok[ni] = root_fair_ok[root_of[ni]]

    preempt_tas_ok = np.zeros(n, dtype=bool)
    for name in snapshot.cluster_queues:
        ni = tidx.node_of[name]
        preempt_tas_ok[ni] = tas_root_ok[root_of[ni]]

    idx.admitted_arrays = AdmittedArrays(
        cq=np.asarray(a_cq),
        usage=np.asarray(a_usage),
        prio=np.asarray(a_prio),
        ts=np.asarray(a_ts),
        qr_time=np.asarray(a_qr),
        evicted=np.asarray(a_evicted),
        active=np.asarray(a_active),
        uid_rank=np.asarray(a_uid),
        tas_t=np.asarray(a_tas_t) if t_n else None,
        tas_usage=np.asarray(a_tas_usage) if t_n else None,
    )
    return preempt_simple, preempt_hier, fair_node_ok, preempt_tas_ok


@dataclass
class AssignSlot:
    """One (podset-group x resource-group) flavor-search unit, mirroring a
    single _find_flavor_for_podsets call (flavorassigner.go:712+946)."""

    ps_ids: List[int]
    rg_idx: int
    requests: Dict[str, int]
    trigger_res: str  # the sorted-order resource that opens the RG search


# Hard cap on the padded slot axis; wider workloads take the host path.
MAX_SLOTS = 16


def _workload_slots(info: WorkloadInfo, cqs) -> Optional[List[AssignSlot]]:
    """Mirror FlavorAssigner._assign_flavors grouping: podset groups in
    first-appearance order, then resource groups in the order their first
    resource appears in sorted(group_requests). Returns None when any
    positive request has no resource group, or a resource is covered by
    more than one group (ambiguous first-match semantics) — host path."""
    res_rg: Dict[str, int] = {}
    for gi, rg in enumerate(cqs.spec.resource_groups):
        for res in rg.covered_resources:
            if res in res_rg:
                return None  # overlapping coverage: keep host semantics
            res_rg[res] = gi

    groups: Dict[str, List[int]] = {}
    order: List[str] = []
    for i, _ps in enumerate(info.total_requests):
        key = str(i)
        tr = info.obj.pod_sets[i].topology_request
        if tr is not None and tr.podset_group_name:
            key = tr.podset_group_name
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(i)

    slots: List[AssignSlot] = []
    for key in order:
        ps_ids = groups[key]
        group_requests: Dict[str, int] = {}
        for i in ps_ids:
            for res, v in info.total_requests[i].requests.items():
                group_requests[res] = group_requests.get(res, 0) + v
        by_rg: Dict[int, AssignSlot] = {}
        rg_order: List[int] = []
        for res in sorted(group_requests):
            gi = res_rg.get(res)
            if gi is None:
                if group_requests[res] == 0:
                    continue
                return None  # uncovered positive request: host path
            if gi not in by_rg:
                by_rg[gi] = AssignSlot(
                    ps_ids=ps_ids, rg_idx=gi, requests={}, trigger_res=res
                )
                rg_order.append(gi)
            by_rg[gi].requests[res] = group_requests[res]
        slots.extend(by_rg[gi] for gi in rg_order)
    return slots


def _balanced_widths_ok(tas, tr) -> bool:
    """Device balanced placement enumerates optimal-domain-set DP inputs
    as 2^BMAX subsets (ops/tas_balanced.py); an entry is device-eligible
    only when every DP input on this topology fits in BMAX domains: the
    widest sibling group at the requested level (DP over the pruned
    group, reference selectOptimalDomainSetToFit :82) and, when the
    request sits above the slice level, the widest set of
    requested-level+1 descendants under one group (the placement DP runs
    over children of the selected set, :293)."""
    from kueue_tpu.ops.tas_balanced import BMAX as _BMAX

    keys = tas.level_keys
    if tr.preferred_level not in keys:
        return True  # flavor infeasible for the entry: never placed here
    rl = keys.index(tr.preferred_level)
    if tr.slice_required_level is not None:
        if tr.slice_required_level not in keys:
            return True
        sl = keys.index(tr.slice_required_level)
    else:
        sl = len(keys) - 1
    if rl > sl:
        return True

    def _max_group(level: int, hops_up: int) -> int:
        counts: Dict[int, int] = {}
        for d in tas.domains_per_level[level]:
            anc = d
            for _ in range(hops_up):
                anc = anc.parent
            counts[id(anc)] = counts.get(id(anc), 0) + 1
        return max(counts.values(), default=0)

    if rl == 0:
        gw = len(tas.domains_per_level[0])
    else:
        gw = _max_group(rl, 1)
    if gw > _BMAX:
        return False
    if rl < sl:
        if rl == 0:
            g2 = len(tas.domains_per_level[1])
        else:
            g2 = _max_group(rl + 1, 2)
        if g2 > _BMAX:
            return False
    return True


def _device_compatible(
    info: WorkloadInfo,
    snapshot: Snapshot,
    slots: Optional[List[AssignSlot]],
    tas_device_flavors: set = frozenset(),
    delayed: bool = False,
    preempt: bool = False,
    fair_sharing: bool = False,
) -> bool:
    if info.cluster_queue not in snapshot.cluster_queues:
        return False
    if slots is None or not slots or len(slots) > MAX_SLOTS:
        return False
    multi_slot = len(slots) > 1 or slots[0].rg_idx != 0
    if delayed:
        # Delayed topology placement (tas_flavorassigner.go:106): the
        # first pass is pure quota admission — the entry rides the normal
        # (slot) machinery with no topology tensors; the driver marks
        # delayed_topology_request and the manager's second pass places.
        # Partial-admission TAS still stays host (gated below).
        pass
    elif any(
        ps.topology_request is not None for ps in info.obj.pod_sets
    ) and (len(info.obj.pod_sets) != 1 or multi_slot):
        # LWS leader group on device: two podsets sharing a
        # podset_group_name place as ONE request with the smaller-count
        # member as the leader (flavorassigner.update_for_tas /
        # reference tas_flavor_snapshot.go:725) — the placement kernel
        # carries the leader planes. Other multi-podset TAS shapes stay
        # on the host for now.
        if not preempt:
            return False
        from kueue_tpu.scheduler.flavorassigner import is_lws_group

        singleton = (
            slots is not None
            and all(len(sl.ps_ids) == 1 for sl in slots)
        )
        if fair_sharing:
            # Fair tournament: per-slot TAS placement runs in the fair
            # scan for singleton-group slots; the LWS leader planes are
            # not in that kernel — LWS pairs stay host under fair.
            if not singleton:
                return False
        elif not (
            (not multi_slot and is_lws_group(info.obj.pod_sets))
            or singleton
        ):
            # LWS pair (one two-podset group) or singleton groups only;
            # groups-of-2 mixed with other podsets stay host.
            return False
        cqs0 = snapshot.cluster_queues[info.cluster_queue]
        from kueue_tpu.utils import features as _mbfeat

        bal_gate = _mbfeat.enabled("TASBalancedPlacement")
        for ps2 in info.obj.pod_sets:
            tr2 = ps2.topology_request
            if tr2 is None:
                continue
            # Balanced placement stays single-podset on device.
            if tr2.balanced or (
                bal_gate
                and tr2.required_level is None
                and tr2.preferred_level is not None
                and not tr2.unconstrained
            ):
                return False
            # Node-filtered capacity (selector/tolerations) is encoded
            # as a single worker-shaped row — keep filtered groups host.
            if ps2.node_selector or ps2.tolerations:
                return False
        # Every topology flavor of the group's RG must be encoded and
        # untainted (no per-entry capacity filter rows for groups).
        for sl in slots:
            rg2 = cqs0.spec.resource_groups[sl.rg_idx]
            for fq in rg2.flavors:
                tas2 = snapshot.tas_flavors.get(fq.name)
                if tas2 is None:
                    continue
                if fq.name not in tas_device_flavors:
                    return False
                if tas2.has_tainted_nodes:
                    return False
    ps = info.obj.pod_sets[0]
    cqs = snapshot.cluster_queues[info.cluster_queue]
    if any(
        p.min_count is not None and p.min_count < p.count
        for p in info.obj.pod_sets
    ) and (len(info.obj.pod_sets) != 1 or multi_slot):
        # Device PodSetReducer handles the single-podset class only.
        return False
    if ps.min_count is not None and ps.min_count < ps.count:
        # Partial admission (PodSetReducer): the device search handles the
        # single-podset class under the PartialAdmission gate. On
        # never-preempts CQs the probe predicate is pure FIT; on
        # preempting CQs (preempt cycles only) each probe consults the
        # flat victim-search kernel (reference scheduler.go:803), with
        # oracle-dependent probes marking the entry host-bound
        # dynamically. With the feature off there is no search anywhere,
        # so the entry is an ordinary full-count entry.
        from kueue_tpu.api.constants import PreemptionPolicy
        from kueue_tpu.utils import features as _features

        if _features.enabled("PartialAdmission"):
            p = cqs.spec.preemption
            never = (
                p.within_cluster_queue == PreemptionPolicy.NEVER
                and p.reclaim_within_cohort == PreemptionPolicy.NEVER
            )
            if fair_sharing or ps.topology_request is not None:
                return False
            if not never and not preempt:
                return False
            # The search scales per-pod requests; totals must be the
            # plain per-pod x count product (no reclaimed-pods skew).
            tot = info.total_requests[0]
            if tot.count != ps.count or any(
                tot.requests.get(res, 0) != v * ps.count
                for res, v in ps.requests.items()
            ):
                return False
            # The device binary search is bounded by
            # batch_scheduler._PARTIAL_STEPS (22) probe halvings; a wider
            # reduction range could not converge — host path.
            if ps.count - ps.min_count >= (1 << 22):
                return False
    if ps.topology_request is not None and not delayed:
        tr = ps.topology_request
        if not preempt:
            return False
        # Device TAS class: no delayed placement (multi-layer slices run
        # on device via per-level units; balanced placement runs on
        # device when the optimal-domain-set DP widths fit the subset
        # enumeration — see _balanced_widths_ok).
        from kueue_tpu.utils import features as _bfeat2

        balanced_applies = (
            (tr.balanced or _bfeat2.enabled("TASBalancedPlacement"))
            and tr.required_level is None
            and tr.preferred_level is not None
            and not tr.unconstrained
        )
        if balanced_applies:
            # Inner slice layers would flow through the prune/refill with
            # the host's (reference-exact) non-rounded fillInCountsHelper
            # — keep balanced x multi-layer on the host.
            if getattr(tr, "slice_layers", None):
                return False
            for fq in cqs.spec.resource_groups[0].flavors:
                tas2 = snapshot.tas_flavors.get(fq.name)
                if tas2 is not None and not _balanced_widths_ok(tas2, tr):
                    return False
        # Every topology-backed flavor of the CQ must be device-encoded.
        rg0 = cqs.spec.resource_groups[0]
        tas_flavor_count = 0
        any_tainted = False
        for fq in rg0.flavors:
            if fq.name in snapshot.tas_flavors:
                if fq.name not in tas_device_flavors:
                    return False
                tas_flavor_count += 1
                any_tainted = any_tainted or \
                    snapshot.tas_flavors[fq.name].has_tainted_nodes
        # Node-filtered capacity (selector/tolerations/tainted fleet) is
        # encoded as ONE per-entry leaf-capacity row, which is exact only
        # when a single topology can host the entry.
        if (ps.node_selector or ps.tolerations or any_tainted) \
                and tas_flavor_count > 1:
            return False
    # Coverage is guaranteed by the slot computation (None on any
    # uncovered positive request).
    return True


# ---------------------------------------------------------------------------
# Tiled streaming admission (models/driver.py _schedule_tiled): the
# tile-view encoder is encode_cycle itself called per tile — only the
# tile's w_*/s_* planes are ever materialized — plus the planner below,
# which decides which heads may share a tile without changing results.


def plane_nbytes(arrays) -> int:
    """Total bytes of the materialized cycle planes (host or device).

    Sums ``nbytes`` over every array leaf of ``arrays`` — the number the
    tiled mode bounds: a W-tile's planes instead of the full backlog's.
    Non-array leaves (e.g. an unregistered topology handle) count zero.
    """
    total = 0
    for leaf in jax.tree_util.tree_leaves(arrays):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


def plan_tiles(
    heads: Sequence[WorkloadInfo],
    tile_width: int,
    snapshot: Snapshot,
) -> List[List[WorkloadInfo]]:
    """Pack pending heads into W-tiles without splitting a solve-coupled
    group across tile boundaries.

    Tiling is exact because the batched kernels solve cohort trees
    independently — quota never crosses a root — so a tile holding whole
    trees reproduces the monolithic cycle's per-row outcomes. Two
    couplings survive the root split and are fused here:

    - TAS topology capacity is physical state shared by every tree whose
      CQs cover the same device-encoded TAS flavor: trees sharing one
      are unioned into a single group, so their gangs place against one
      consistent topology plane instead of racing across tiles.
    - Heads whose CQ is missing from the snapshot ride as singletons
      (they host-fallback inside their tile either way).

    Groups are ordered by their best head's queue rank
    ``(-priority, timestamp)`` — the order the monolithic cycle would
    consider them — and greedily packed up to ``tile_width`` rows. A
    group wider than the tile gets its own oversized tile: correctness
    over the bound; the peak plane becomes ``max(tile_width bucket,
    widest-group bucket)``, which docs/perf.md calls out.
    """
    if not heads:
        return []
    groups, roots, prio, ts, wkeys = _tile_head_views(heads, snapshot)

    # Group order = the order the monolithic cycle would first consider
    # any member: rank heads once, vectorized ((-priority, timestamp,
    # key) via one lexsort over column views), then take each group at
    # its best member's position. Members keep submission order within
    # the group (the dict preserved head order).
    order = np.lexsort((wkeys, ts, -prio))
    seen = set()
    ordered: List[List[WorkloadInfo]] = []
    for j in order:
        root = roots[j]
        if root not in seen:
            seen.add(root)
            ordered.append(groups[root])

    tiles: List[List[WorkloadInfo]] = []
    cur: List[WorkloadInfo] = []
    for group in ordered:
        if cur and len(cur) + len(group) > tile_width:
            tiles.append(cur)
            cur = []
        cur.extend(group)
        if len(cur) >= tile_width:
            tiles.append(cur)
            cur = []
    if cur:
        tiles.append(cur)
    return tiles


def _tile_head_views(heads: Sequence[WorkloadInfo], snapshot: Snapshot):
    """Per-head tile-planning views: fused-group membership and rank
    columns. The per-head residue is one dict lookup each — cohort-root
    and TAS fusion are resolved once per distinct CQ (O(#CQs) union-find,
    not O(heads) tree walks), and rank columns come from the workload
    column store when attached (``rank_arrays``), falling back to
    per-head attribute reads (the allowlisted row-wise path)."""
    parent: Dict[object, object] = {}

    def find(x):
        r = x
        while parent[r] != r:
            r = parent[r]
        while parent[x] != r:
            parent[x], x = r, parent[x]
        return r

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra != rb:
            parent[rb] = ra

    cq_key: Dict[str, object] = {}

    def key_of_cq(cq_name: str):
        key = cq_key.get(cq_name)
        if key is None:
            cqs = snapshot.cluster_queues[cq_name]
            key = ("root", id(cqs.node.root()))
            parent.setdefault(key, key)
            if snapshot.tas_flavors:
                for rg in cqs.spec.resource_groups:
                    for fq in rg.flavors:
                        if fq.name in snapshot.tas_flavors:
                            fkey = ("tas", fq.name)
                            parent.setdefault(fkey, fkey)
                            union(key, fkey)
            cq_key[cq_name] = key
        return key

    keys: List[object] = []
    for i, info in enumerate(heads):
        if info.cluster_queue in snapshot.cluster_queues:
            keys.append(key_of_cq(info.cluster_queue))
        else:
            key = ("solo", i)
            parent.setdefault(key, key)
            keys.append(key)

    groups: Dict[object, List[WorkloadInfo]] = {}
    roots: List[object] = []
    for info, key in zip(heads, keys):
        root = find(key)
        roots.append(root)
        groups.setdefault(root, []).append(info)

    store = getattr(snapshot, "workload_columns", None)
    if store is not None and _COLUMNS_MODE != "off":
        prio, ts = store.rank_arrays(heads)
    else:
        n = len(heads)
        prio = np.fromiter(
            (h.priority() for h in heads), dtype=np.int64, count=n
        )
        ts = np.fromiter(
            (queue_order_timestamp(h.obj) for h in heads),
            dtype=np.float64, count=n,
        )
    wkeys = np.array([h.key for h in heads])
    return groups, roots, prio, ts, wkeys
