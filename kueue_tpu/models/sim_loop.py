"""On-device multi-cycle simulation loop.

The whole benchmark-style workload lifecycle — repeated scheduling cycles
with virtual-time execution (admitted workloads complete after their
runtime, releasing capacity) — as ONE compiled XLA program: a while_loop
whose body runs the batched cycle, applies admissions, and advances the
virtual clock to the next completion when stuck.

This removes per-cycle host round-trips entirely (the remote-device
dispatch latency otherwise dominates: ~1 s per call through a device
tunnel vs one call total here). Decision semantics per cycle are identical
to models/batch_scheduler.cycle_grouped in full-batch mode; usage after
completions is recomputed from the running set via the exact subtree
roll-up (replay-from-zero equals incremental bubbling for non-negative
adds).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from kueue_tpu.models import batch_scheduler as bs
from kueue_tpu.models.batch_scheduler import GroupArrays
from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.ops import quota_ops

_T_INF = jnp.int64(1) << 60


class SimOutputs(NamedTuple):
    admitted_at: jnp.ndarray  # i64[W] virtual ms (-1 = never admitted)
    completed_at: jnp.ndarray  # i64[W] virtual ms (-1 = never)
    rounds: jnp.ndarray  # i32 scheduling rounds executed
    final_vclock: jnp.ndarray  # i64 virtual ms when the simulation settled


def make_sim_loop(s_max: int, max_rounds: int = 100000,
                  kernel: str = "grouped",
                  n_levels: int = quota_ops.MAX_DEPTH + 1,
                  interpret: bool = False, mesh=None):
    """Build the jittable simulator. ``s_max`` is the per-tree admission
    scan depth (see admit_scan_grouped). ``kernel`` selects the per-round
    admission pass: "grouped" (the sequential per-tree scan),
    "fixedpoint" (monotone-bounds rounds — usually far fewer device steps
    per cycle; exact only for lending-limit-free trees, which the caller
    must check), "pallas" (the whole per-tree scan as one Pallas
    kernel with VMEM-resident state — exact only when
    ``pallas_scan.fits_int32`` holds for the cycle arrays, which the
    caller must check; ``interpret`` runs it in interpreter mode
    off-TPU), or "fair" (the DRS tournament admission — requires the
    fair fields on CycleArrays; per round each CQ is represented by its
    last pending entry, mirroring the per-CQ-heads cycle semantics)."""
    assert kernel in ("grouped", "fixedpoint", "pallas", "fair")

    def simulate(
        arrays: CycleArrays, ga: GroupArrays, runtime_ms: jnp.ndarray
    ) -> SimOutputs:
        w_n = arrays.w_cq.shape[0]
        tree = arrays.tree
        f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
        f_onehot = jnp.arange(f_n)

        cell_mask_full = (
            (arrays.w_req[:, None, :] > 0)
            & arrays.covered[arrays.w_cq][:, None, :]
        )  # [W,1->F broadcast later, R] per chosen flavor at admit time

        base_usage = arrays.usage
        # Leaf detection: a CQ is a node no other active node points to.
        is_parent = jnp.zeros(tree.n_nodes, bool).at[
            jnp.where(tree.parent >= 0, tree.parent, 0)
        ].set(tree.parent >= 0, mode="drop")
        is_parent = jnp.zeros(tree.n_nodes, bool).at[tree.parent].max(
            (tree.parent >= 0), mode="drop"
        )
        is_cq_node = tree.active & ~is_parent
        base_cq_usage = jnp.where(is_cq_node[:, None, None], base_usage, 0)

        def recompute_usage(running, chosen_flavor):
            """usage = exact roll-up of (base CQ usage + running deltas);
            replay-from-zero equals incremental bubbling for positive
            adds."""
            cmask = (
                (f_onehot[None, :, None] == chosen_flavor[:, None, None])
                & cell_mask_full
            )
            delta = jnp.where(cmask, arrays.w_req[:, None, :], 0).astype(
                jnp.int64
            )
            delta = jnp.where(running[:, None, None], delta, 0)
            cq_add = jnp.zeros_like(base_usage).at[arrays.w_cq].add(
                delta, mode="drop"
            )
            _subtree, usage = quota_ops.compute_subtree(
                tree, base_cq_usage + cq_add, is_cq_node
            )
            return usage

        def body(state):
            (pending, running, admitted_at, completed_at, chosen_flavor,
             vclock, rounds, _progress) = state

            usage = recompute_usage(running, chosen_flavor)
            a = arrays._replace(w_active=pending, usage=usage)
            nom = bs.nominate(a, usage, n_levels=n_levels)
            if kernel == "fair":
                from kueue_tpu.models.fair_kernel import fair_admit_scan

                # The tournament orders entries itself (dynamic DRS keys).
                (_u, admit, _pre, _shadowed, _part, _step,
                 _tk, _stk) = fair_admit_scan(
                    a, nom, usage, s_max
                )
            elif kernel == "fixedpoint":
                order = bs.admission_order(a, nom)
                _u, admit, _r = bs.admit_fixedpoint(
                    a, ga, nom, usage, order, n_levels=n_levels
                )
            elif kernel == "pallas":
                from kueue_tpu.models.pallas_scan import pallas_admit_scan

                order = bs.admission_order(a, nom)
                _u, admit, _pre = pallas_admit_scan(
                    a, ga, nom, usage, order, s_max, n_levels=n_levels,
                    interpret=interpret,
                )
            else:
                order = bs.admission_order(a, nom)
                _u, admit, _pre, _tk, _ltk, _stk = bs.admit_scan_grouped(
                    a, ga, nom, usage, order, s_max, n_levels=n_levels,
                    mesh=mesh,
                )

            newly = admit & pending
            any_admit = jnp.any(newly)
            pending = pending & ~newly
            running = running | newly
            admitted_at = jnp.where(newly, vclock, admitted_at)
            chosen_flavor = jnp.where(
                newly, nom.chosen_flavor, chosen_flavor
            )
            completes = jnp.where(
                running & (completed_at < 0),
                admitted_at + runtime_ms,
                _T_INF,
            )

            # When no admissions: advance to the earliest completion.
            next_t = jnp.min(completes)
            can_advance = next_t < _T_INF
            do_advance = (~any_admit) & can_advance
            new_vclock = jnp.where(do_advance, next_t, vclock)
            finishing = do_advance & running & (completes <= new_vclock)
            completed_at = jnp.where(finishing, new_vclock, completed_at)
            running = running & ~finishing

            progress = any_admit | jnp.any(finishing)
            return (pending, running, admitted_at, completed_at,
                    chosen_flavor, new_vclock, rounds + 1, progress)

        def cond(state):
            (pending, running, _aa, _ca, _cf, _vc, rounds, progress) = state
            return progress & (rounds < max_rounds) & jnp.any(pending)

        init = (
            arrays.w_active,  # pending
            jnp.zeros(w_n, bool),  # running
            jnp.full(w_n, -1, jnp.int64),  # admitted_at
            jnp.full(w_n, -1, jnp.int64),  # completed_at
            jnp.full(w_n, -1, jnp.int32),  # chosen flavor
            jnp.int64(0),  # vclock
            jnp.int32(0),  # rounds
            jnp.bool_(True),  # progress
        )
        (pending, running, admitted_at, completed_at, chosen, vclock,
         rounds, _p) = jax.lax.while_loop(cond, body, init)
        # Drain: anything still running completes at its scheduled time.
        final_completes = jnp.where(
            running, admitted_at + runtime_ms, completed_at
        )
        final_vclock = jnp.maximum(vclock, jnp.max(jnp.where(
            final_completes > 0, final_completes, 0
        )))
        return SimOutputs(
            admitted_at=admitted_at,
            completed_at=final_completes,
            rounds=rounds,
            final_vclock=final_vclock,
        )

    return simulate
