"""On-device multi-cycle simulation loop.

The whole benchmark-style workload lifecycle — repeated scheduling cycles
with virtual-time execution (admitted workloads complete after their
runtime, releasing capacity) — as ONE compiled XLA program: a while_loop
whose body runs the batched cycle, applies admissions, and advances the
virtual clock to the next completion when stuck.

This removes per-cycle host round-trips entirely (the remote-device
dispatch latency otherwise dominates: ~1 s per call through a device
tunnel vs one call total here). Decision semantics per cycle are identical
to models/batch_scheduler.cycle_grouped in full-batch mode; usage after
completions is recomputed from the running set via the exact subtree
roll-up (replay-from-zero equals incremental bubbling for non-negative
adds).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from kueue_tpu.models import batch_scheduler as bs
from kueue_tpu.models.batch_scheduler import GroupArrays
from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.ops import quota_ops

_T_INF = jnp.int64(1) << 60


class SimInit(NamedTuple):
    """Optional initial lifecycle state for :func:`make_sim_loop`.

    The default start (every active entry pending, nothing running) models
    an empty cluster. A forecast over a *live* snapshot instead seeds the
    currently admitted workloads as already-running rows: ``running`` rows
    must carry ``admitted_at <= 0`` (their virtual admission time, usually
    0 = "now") and a valid ``chosen_flavor`` so the usage roll-up re-adds
    their consumption; their remaining runtime goes in ``runtime_ms``.
    ``pending`` and ``running`` must be disjoint."""

    pending: jnp.ndarray  # bool[W]
    running: jnp.ndarray  # bool[W]
    admitted_at: jnp.ndarray  # i64[W] (-1 for pending rows)
    chosen_flavor: jnp.ndarray  # i32[W] (-1 for pending rows)


class SimOutputs(NamedTuple):
    admitted_at: jnp.ndarray  # i64[W] virtual ms (-1 = never admitted)
    completed_at: jnp.ndarray  # i64[W] virtual ms (-1 = never)
    rounds: jnp.ndarray  # i32 scheduling rounds executed
    final_vclock: jnp.ndarray  # i64 virtual ms when the simulation settled
    chosen_flavor: jnp.ndarray = None  # i32[W] flavor at admission (-1)


def make_sim_loop(s_max: int, max_rounds: int = 100000,
                  kernel: str = "grouped",
                  n_levels: int = quota_ops.MAX_DEPTH + 1,
                  interpret: bool = False, mesh=None,
                  per_cq_heads: bool = False):
    """Build the jittable simulator. ``s_max`` is the per-tree admission
    scan depth (see admit_scan_grouped). ``kernel`` selects the per-round
    admission pass: "grouped" (the sequential per-tree scan),
    "fixedpoint" (monotone-bounds rounds — usually far fewer device steps
    per cycle; exact for every tree shape including lending limits, but
    resolves no preemptions — preempt-needing entries stay pending),
    "pallas" (the whole per-tree scan as one Pallas
    kernel with VMEM-resident state — exact only when
    ``pallas_scan.fits_int32`` holds for the cycle arrays, which the
    caller must check; ``interpret`` runs it in interpreter mode
    off-TPU), "fair" (the DRS tournament admission — requires the
    fair fields on CycleArrays; per round each CQ is represented by its
    last pending entry, mirroring the per-CQ-heads cycle semantics), or
    "fair_fixedpoint" (the same tournament as parallel monotone-bounds
    rounds with a residual scan for unsettled trees — bit-identical
    planes to "fair", usually far fewer device steps).

    ``per_cq_heads`` switches each round from the maximal full-batch pass
    (every pending entry competes at once) to the live scheduler's exact
    cycle shape: one head per ClusterQueue — the pending entry with the
    lowest host-precomputed ``w_order_rank`` — competes per round, and a
    head that fails is staged *inadmissible* (out of contention, so the
    CQ's next entry gets a try) until the next completion requeues it,
    mirroring ``QueueManager.heads()`` + the inadmissible staging. The
    full-batch default admits a strictly priority-ordered set, which can
    differ under cohort contention: a low-priority head of a quiet CQ is
    admitted by the real scheduler before a higher-priority entry buried
    deeper in a busy CQ's queue. Forecasters that must be bit-identical
    to stepping the real scheduler (whatif/) run with this on; the
    benchmark lifecycle probes keep the cheaper full-batch rounds."""
    assert kernel in (
        "grouped", "fixedpoint", "pallas", "fair", "fair_fixedpoint"
    )
    _RANK_INF = jnp.int32(1) << 30

    def simulate(
        arrays: CycleArrays, ga: GroupArrays, runtime_ms: jnp.ndarray,
        init: Optional[SimInit] = None,
    ) -> SimOutputs:
        w_n = arrays.w_cq.shape[0]
        tree = arrays.tree
        f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
        f_onehot = jnp.arange(f_n)

        cell_mask_full = (
            (arrays.w_req[:, None, :] > 0)
            & arrays.covered[arrays.w_cq][:, None, :]
        )  # [W,1->F broadcast later, R] per chosen flavor at admit time

        base_usage = arrays.usage
        # Leaf detection: a CQ is a node no other active node points to.
        is_parent = jnp.zeros(tree.n_nodes, bool).at[
            jnp.where(tree.parent >= 0, tree.parent, 0)
        ].set(tree.parent >= 0, mode="drop")
        is_parent = jnp.zeros(tree.n_nodes, bool).at[tree.parent].max(
            (tree.parent >= 0), mode="drop"
        )
        is_cq_node = tree.active & ~is_parent
        base_cq_usage = jnp.where(is_cq_node[:, None, None], base_usage, 0)

        def recompute_usage(running, chosen_flavor):
            """usage = exact roll-up of (base CQ usage + running deltas);
            replay-from-zero equals incremental bubbling for positive
            adds."""
            cmask = (
                (f_onehot[None, :, None] == chosen_flavor[:, None, None])
                & cell_mask_full
            )
            delta = jnp.where(cmask, arrays.w_req[:, None, :], 0).astype(
                jnp.int64
            )
            delta = jnp.where(running[:, None, None], delta, 0)
            cq_add = jnp.zeros_like(base_usage).at[arrays.w_cq].add(
                delta, mode="drop"
            )
            _subtree, usage = quota_ops.compute_subtree(
                tree, base_cq_usage + cq_add, is_cq_node
            )
            return usage

        if per_cq_heads:
            assert arrays.w_order_rank is not None, (
                "per_cq_heads needs the host-precomputed w_order_rank"
            )

        def body(state):
            (pending, blocked, running, admitted_at, completed_at,
             chosen_flavor, vclock, rounds, _progress) = state

            usage = recompute_usage(running, chosen_flavor)
            if per_cq_heads:
                # One head per CQ: the eligible (pending, not staged
                # inadmissible) row with the lowest order rank. Ranks are
                # a permutation, so exactly one row per CQ wins.
                eligible = pending & ~blocked
                key = jnp.where(
                    eligible, arrays.w_order_rank.astype(jnp.int32),
                    _RANK_INF,
                )
                cq_min = jnp.full(
                    (tree.n_nodes,), _RANK_INF, jnp.int32
                ).at[arrays.w_cq].min(key, mode="drop")
                active = eligible & (key == cq_min[arrays.w_cq])
            else:
                active = pending
            a = arrays._replace(w_active=active, usage=usage)
            nom = bs.nominate(a, usage, n_levels=n_levels)
            if kernel == "fair":
                from kueue_tpu.models.fair_kernel import fair_admit_scan

                # The tournament orders entries itself (dynamic DRS keys).
                admit = fair_admit_scan(a, nom, usage, s_max).admitted
            elif kernel == "fair_fixedpoint":
                from kueue_tpu.models.fair_fixedpoint import (
                    fair_admit_fixedpoint,
                )

                admit = fair_admit_fixedpoint(
                    a, nom, usage, s_max
                ).res.admitted
            elif kernel == "fixedpoint":
                order = bs.admission_order(a, nom)
                _u, admit, _r, _conv = bs.admit_fixedpoint(
                    a, ga, nom, usage, order, n_levels=n_levels
                )
            elif kernel == "pallas":
                from kueue_tpu.models.pallas_scan import pallas_admit_scan

                order = bs.admission_order(a, nom)
                _u, admit, _pre = pallas_admit_scan(
                    a, ga, nom, usage, order, s_max, n_levels=n_levels,
                    interpret=interpret,
                )
            else:
                order = bs.admission_order(a, nom)
                admit = bs.admit_scan_grouped(
                    a, ga, nom, usage, order, s_max, n_levels=n_levels,
                    mesh=mesh,
                ).admitted

            newly = admit & active
            any_admit = jnp.any(newly)
            pending = pending & ~newly
            running = running | newly
            admitted_at = jnp.where(newly, vclock, admitted_at)
            chosen_flavor = jnp.where(
                newly, nom.chosen_flavor, chosen_flavor
            )
            if per_cq_heads:
                # A failed head is staged until the next capacity event;
                # staging IS scheduling progress (the CQ's next entry
                # gets the following round). Advance the clock only once
                # every eligible entry has had its try this instant.
                failed = active & ~newly
                blocked = blocked | failed
                stalled = ~jnp.any(pending & ~blocked)
                sched_progress = any_admit | jnp.any(failed)
            else:
                stalled = ~any_admit
                sched_progress = any_admit
            completes = jnp.where(
                running & (completed_at < 0),
                admitted_at + runtime_ms,
                _T_INF,
            )

            # When stuck at this instant: advance to the earliest
            # completion (a capacity event, which also requeues the
            # staged inadmissible set).
            next_t = jnp.min(completes)
            can_advance = next_t < _T_INF
            do_advance = (~any_admit) & stalled & can_advance
            new_vclock = jnp.where(do_advance, next_t, vclock)
            finishing = do_advance & running & (completes <= new_vclock)
            completed_at = jnp.where(finishing, new_vclock, completed_at)
            running = running & ~finishing
            blocked = blocked & ~do_advance

            progress = sched_progress | jnp.any(finishing)
            return (pending, blocked, running, admitted_at, completed_at,
                    chosen_flavor, new_vclock, rounds + 1, progress)

        def cond(state):
            (pending, _bl, running, _aa, _ca, _cf, _vc, rounds,
             progress) = state
            return progress & (rounds < max_rounds) & jnp.any(pending)

        if init is None:
            pending0 = arrays.w_active
            running0 = jnp.zeros(w_n, bool)
            admitted_at0 = jnp.full(w_n, -1, jnp.int64)
            chosen0 = jnp.full(w_n, -1, jnp.int32)
        else:
            pending0 = init.pending
            running0 = init.running
            admitted_at0 = init.admitted_at.astype(jnp.int64)
            chosen0 = init.chosen_flavor.astype(jnp.int32)
        state0 = (
            pending0,
            jnp.zeros(w_n, bool),  # blocked (inadmissible staging)
            running0,
            admitted_at0,
            jnp.full(w_n, -1, jnp.int64),  # completed_at
            chosen0,
            jnp.int64(0),  # vclock
            jnp.int32(0),  # rounds
            jnp.bool_(True),  # progress
        )
        (pending, _blocked, running, admitted_at, completed_at, chosen,
         vclock, rounds, _p) = jax.lax.while_loop(cond, body, state0)
        # Drain: anything still running completes at its scheduled time.
        final_completes = jnp.where(
            running, admitted_at + runtime_ms, completed_at
        )
        final_vclock = jnp.maximum(vclock, jnp.max(jnp.where(
            final_completes > 0, final_completes, 0
        )))
        return SimOutputs(
            admitted_at=admitted_at,
            completed_at=final_completes,
            rounds=rounds,
            final_vclock=final_vclock,
            chosen_flavor=chosen,
        )

    return simulate
