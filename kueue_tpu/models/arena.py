"""Device-resident incremental cycle encoding (the CycleArena).

``encode_cycle`` rebuilds every dense tensor from the snapshot each cycle,
even though successive cycles differ only in the rows touched by the last
cycle's admissions and preemptions. The arena keeps the encoded tensors
resident on device across cycles and reconciles them with row-level deltas:

- the cache appends a workload event log (``Cache._record_workload_event``)
  for every effective admitted-set mutation; the arena drains it atomically
  with the snapshot (``Cache.snapshot_with_workload_events``),
- host-side numpy mirrors of every dynamic tensor family are updated from
  the events (O(events) python work plus C-level numpy gathers),
- dirty rows are found by mirror comparison and applied on device by a
  small jitted ``.at[idx].set`` scatter per family, fed by ONE batched
  ``device_put`` of the delta payload.

Families and their delta sources:

- node family   — ``usage[N,F,R]`` rows of event CQs + their ancestors,
                  re-read from the snapshot tree (exactly what
                  ``encode_tree`` reads); ``usage_by_prio[N,F,R,B]`` by
                  integer event arithmetic (commutative, exact).
- A family      — the AdmittedArrays columns. Per-CQ insertion-ordered
                  slot dicts replay the cache's ``_cq_workloads``
                  semantics (pop on remove, append on add) so the flat
                  row order is bit-identical to the from-scratch concat;
                  per-row values live in a slab store and the mirrors are
                  rebuilt by a numpy gather.
- W family      — per-head rows, recomputed exactly like the from-scratch
                  loop (it is O(heads) by nature) and diffed row-wise.
- flag family   — ``preempt_simple`` / ``preempt_hier``, recomputed from
                  static per-root topology facts and an event-maintained
                  unmappable-usage counter per root.

Everything static under the quota generation (tree, per-CQ policy, group
arrays, bwc_*) is reused as-is from the committed device arrays.

Any condition the incremental path does not model (TAS flavors, fair
sharing, slot layout, partial admission, topology-requesting heads, a
quota-structure change, an event-log gap, a priority-cut change, a
``preempt_hier`` presence flip) falls back to the from-scratch
``encode_cycle`` — which re-captures the arena, so the next steady cycle
is incremental again. The differential guarantee is strict: arena-built
arrays are bit-identical to from-scratch encode (``verify=True`` asserts
it after every incremental cycle; tests/test_arena_differential.py drives
randomized mutation sequences through it).

Pipelined speculation (PR 10): while cycle N executes on device, the
pipelined driver stages cycle N+1's W build from the pre-apply state into
one of two generation-tagged staging buffers (:meth:`begin_speculation`,
ping-ponged per cycle). The apply boundary reports the keys it mutated
(:meth:`note_applied`); the next incremental encode consumes the buffer,
reusing rows whose inputs provably did not change and recomputing the
dirty rest — or abandons it entirely (``solver_pipeline_abort_total`` by
reason) on a quota-generation flip, bucket change, oversized delta set,
arena invalidation, or an injected ``pipeline.patch`` fault. Abandonment
always means a fresh row compute, never a stale one, so pipelined encodes
stay bit-identical to the serialized loop by construction.
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from kueue_tpu.core.workload_info import (
    WorkloadInfo,
    has_quota_reservation,
    is_evicted,
    queue_order_timestamp,
    quota_reservation_time,
)
from kueue_tpu.metrics import tracing
from kueue_tpu.models.encode import (
    CycleArrays,
    CycleIndex,
    _device_compatible,
    _order_rank,
    _round_up,
    _workload_slots,
    encode_cycle,
)
from kueue_tpu.ops.quota_ops import MAX_DEPTH
from kueue_tpu.utils import faults

_B = 8  # priority-bucket axis, mirrors encode_cycle's B


class _Fallback(Exception):
    """Raised by the incremental path when the cycle needs a full encode."""


@jax.jit
def _scatter_rows(cols, idx_, rows):
    """Apply one family's dirty rows: cols[k][idx] = rows[k]."""
    return {k: cols[k].at[idx_].set(rows[k]) for k in rows}


def _pad_bucket(idx_: np.ndarray, rows: Dict[str, np.ndarray]):
    """Pad the dirty-row count to a power of two so the jitted scatter
    compiles one program per bucket. Padding repeats the last (index, row)
    pair — an idempotent same-value set."""
    k = len(idx_)
    b = 1 << max(k - 1, 0).bit_length()
    if b == k:
        return idx_, rows
    pad = b - k
    idx2 = np.concatenate([idx_, np.repeat(idx_[-1:], pad)])
    rows2 = {
        c: np.concatenate([v, np.repeat(v[-1:], pad, axis=0)])
        for c, v in rows.items()
    }
    return idx2, rows2


class _AdmittedStore:
    """Slab store of per-admitted-workload row values, keyed by slot id.

    The flat A-family mirrors are rebuilt each event cycle by a numpy
    gather over the slot order, so the python work stays O(events)."""

    def __init__(self, f: int, r: int) -> None:
        self.f = f
        self.r = r
        self.cap = 0
        self.free: List[int] = []
        self.next = 0
        self.cq = np.zeros(0, dtype=np.int32)
        self.prio = np.zeros(0, dtype=np.int64)
        self.ts = np.zeros(0, dtype=np.float64)
        self.qr = np.zeros(0, dtype=np.float64)
        self.evicted = np.zeros(0, dtype=bool)
        self.uid = np.zeros(0, dtype=object)
        self.info = np.zeros(0, dtype=object)

    def _grow(self, need: int) -> None:
        cap = max(64, self.cap * 2, need)
        for name in ("cq", "prio", "ts", "qr", "evicted", "uid", "info"):
            old = getattr(self, name)
            new = np.zeros(cap, dtype=old.dtype)
            new[: self.cap] = old
            setattr(self, name, new)
        if self.cap:
            usage = np.zeros((cap, self.f, self.r), dtype=np.int64)
            usage[: self.cap] = self.usage
            self.usage = usage
        else:
            self.usage = np.zeros((cap, self.f, self.r), dtype=np.int64)
        self.cap = cap

    def alloc(self) -> int:
        if self.free:
            return self.free.pop()
        if self.next >= self.cap:
            self._grow(self.next + 1)
        slot = self.next
        self.next += 1
        return slot

    def release(self, slot: int) -> None:
        self.free.append(slot)

    def set_row(self, slot, cq_i, info, items, prio, uid,
                flavor_of, resource_of) -> int:
        """Fill one slab row; returns the count of unmappable usage keys
        (the per-root counter feeding preempt_simple/preempt_hier)."""
        self.cq[slot] = cq_i
        self.prio[slot] = prio
        self.ts[slot] = queue_order_timestamp(info.obj)
        self.qr[slot] = quota_reservation_time(info.obj, 0.0)
        self.evicted[slot] = is_evicted(info.obj)
        self.uid[slot] = uid
        self.info[slot] = info
        row = self.usage[slot]
        row[:] = 0
        unmap = 0
        for fr, v in items:
            fi = flavor_of.get(fr.flavor)
            ri = resource_of.get(fr.resource)
            if fi is None or ri is None:
                unmap += 1
            else:
                row[fi, ri] = v
        return unmap


class CycleArena:
    """Persistent device-resident encode state for one DeviceScheduler."""

    def __init__(self, cache, fair_sharing: bool = False,
                 verify: bool = False) -> None:
        self.cache = cache
        self.fair_sharing = fair_sharing
        self.verify = verify
        # Component cache consumed by encode_cycle on the full path
        # ({"prio": (key, tensors), "adm": (key, tensors)}).
        self.component_cache: dict = {}
        self._cursor = 0
        self._pending_events: Optional[list] = None
        self._committed = False
        # Rolling per-cycle stats (tests pin the perf contract on these).
        self.last_stats: Dict[str, object] = {}
        # Pipelined-cycle speculation: two generation-tagged staging
        # buffers ping-ponged per cycle (see begin_speculation).
        self._spec_bufs: List[Optional[dict]] = [None, None]
        self._spec_flip = 0
        self.pipeline_patch_limit = 64
        self.pipeline_stats: Counter = Counter()

    # -- snapshot pairing ---------------------------------------------------

    def take_snapshot(self):
        """Snapshot + event drain under one cache lock hold, so the mirror
        replay exactly matches the snapshot state."""
        snap, events, cursor = self.cache.snapshot_with_workload_events(
            self._cursor
        )
        self._pending_events = events  # None = gap -> full encode
        self._cursor = cursor
        return snap

    def invalidate(self, reason: str = "") -> None:
        """Drop every piece of committed device state so the next encode
        runs the full from-scratch path (``_gate`` answers "cold").

        Called by the DeviceScheduler's fault containment after any
        contained device failure: a dispatch that died mid-flight, or a
        readback that failed validation, may have left the device-resident
        tensors (or the component cache's admitted/prio tensors keyed by
        generation) in an unknown state — a delta applied on top would
        silently poison every later cycle. Pending events are dropped too;
        the cursor is left alone so the next ``take_snapshot`` drains the
        log normally and the full re-capture re-commits from it.
        """
        self._committed = False
        self._pending_events = None
        # The component cache holds device tensors reused by the full
        # encode path under generation keys; after a fault those keys can
        # no longer be trusted to imply valid tensors.
        self.component_cache.clear()
        # Speculation buffers ride on the committed mirrors: a breaker
        # trip or contained fault invalidates them exactly like the arena.
        if any(b is not None for b in self._spec_bufs):
            self._spec_bufs = [None, None]
            self._pipe_abort("invalidated")
        self.last_stats = {"path": "invalidated", "reason": reason}

    # -- pipelined speculation ----------------------------------------------

    def _pipe_abort(self, reason: str) -> None:
        self.pipeline_stats["abort:" + reason] += 1
        tracing.inc(
            "solver_pipeline_abort_total", labels={"reason": reason}
        )

    def begin_speculation(self, snapshot, heads: Sequence[WorkloadInfo],
                          resource_flavors, w_pad: int = 0) -> bool:
        """Stage cycle N+1's W build from cycle N's *pre-apply* state.

        Called by the pipelined driver inside the device-dispatch overlap
        window: while the device solves cycle N, the host runs the same
        per-head W computation the next encode would (warming each head's
        generation-keyed ``_elig_cache`` — the expensive FlavorAssigner
        work — and materialising the row values) into one of two
        generation-tagged staging buffers, ping-ponged per cycle. The
        buffer is consumed by the next ``_incremental`` encode, which
        patches in the dirty rows the apply boundary produced
        (:meth:`note_applied`) and reuses the rest; any validity mismatch
        abandons the buffer and the encode recomputes from live state, so
        results are bit-identical to the unpipelined loop by construction.

        Returns True when a buffer was staged.
        """
        if not self._committed or self.fair_sharing:
            self._pipe_abort("not-committed")
            return False
        if getattr(snapshot, "quota_generation", None) != self._quota_gen:
            self._pipe_abort("quota-gen")
            return False
        try:
            device_wls, _fallbacks, mw = self._build_w(
                snapshot, heads, resource_flavors, w_pad
            )
        except _Fallback:
            self._pipe_abort("shape")
            return False
        buf = {
            "quota_gen": self._quota_gen,
            "w": int(mw["w_cq"].shape[0]),
            "rows": {info.key: i for i, info in enumerate(device_wls)},
            "info_id": {info.key: id(info) for info in device_wls},
            "cq_gen": {
                name: cqs.allocatable_generation
                for name, cqs in snapshot.cluster_queues.items()
            },
            "mw": mw,
            "touched": set(),
        }
        slot = self._spec_flip
        self._spec_flip ^= 1
        self._spec_bufs[slot] = buf
        self.pipeline_stats["staged"] += 1
        tracing.inc(
            "solver_pipeline_cycles_total", labels={"path": "staged"}
        )
        return True

    def note_applied(self, keys) -> None:
        """Mark workload keys mutated at the apply boundary (processed
        heads, preemption victims): their staged rows are dirty and will
        be recomputed — the "patch" half of patch-after-speculate."""
        for buf in self._spec_bufs:
            if buf is not None:
                buf["touched"].update(keys)

    def _take_speculation(self) -> Optional[dict]:
        """Pop the most recently staged buffer; both slots are cleared
        (the older buffer describes a cycle that already happened)."""
        newest = self._spec_flip ^ 1
        out = None
        for j in (newest, self._spec_flip):
            buf, self._spec_bufs[j] = self._spec_bufs[j], None
            if out is None and buf is not None:
                out = buf
        return out

    def _spec_plan(self, spec: dict, device_wls, snapshot,
                   w: int) -> Optional[Dict[int, int]]:
        """Map live head positions to reusable staged rows, or None when
        the speculation must be abandoned (counted by reason). A row is
        reusable only if its key was staged, untouched since, backed by
        the same WorkloadInfo object, and its CQ's allocatable generation
        is unchanged — everything the row value is a function of."""
        try:
            if faults.ENABLED:
                faults.fire(faults.PIPELINE_PATCH)
        except AssertionError:
            raise
        except Exception:
            self._pipe_abort("fault")
            return None
        if spec["quota_gen"] != self._quota_gen:
            self._pipe_abort("quota-gen")
            return None
        if spec["w"] != w:
            self._pipe_abort("bucket")
            return None
        rows = spec["rows"]
        ids = spec["info_id"]
        touched = spec["touched"]
        cq_gen = spec["cq_gen"]
        plan: Dict[int, int] = {}
        for i, info in enumerate(device_wls):
            k = info.key
            j = rows.get(k)
            if j is None or k in touched or ids.get(k) != id(info):
                continue
            cqs = snapshot.cluster_queues.get(info.cluster_queue)
            if cqs is None or cq_gen.get(info.cluster_queue) \
                    != cqs.allocatable_generation:
                continue
            plan[i] = j
        if len(device_wls) - len(plan) > self.pipeline_patch_limit:
            self._pipe_abort("delta-threshold")
            return None
        self.pipeline_stats["consumed"] += 1
        self.pipeline_stats["reused_rows"] += len(plan)
        tracing.inc(
            "solver_pipeline_cycles_total", labels={"path": "consumed"}
        )
        tracing.observe("solver_pipeline_reused_rows", float(len(plan)))
        return plan

    # -- public encode ------------------------------------------------------

    def encode(self, snapshot, heads: Sequence[WorkloadInfo],
               resource_flavors, w_pad: int = 0, preempt: bool = True,
               delay_tas_fn=None, fair_strategies=None):
        t0 = time.perf_counter()
        events = self._pending_events
        self._pending_events = None
        reason = self._gate(snapshot, heads, preempt, events)
        out = None
        if reason is None:
            try:
                out = self._incremental(
                    snapshot, heads, resource_flavors, w_pad, delay_tas_fn,
                    events,
                )
            except _Fallback as exc:
                reason = str(exc)
        if out is None:
            out = self._capture(
                snapshot, heads, resource_flavors, w_pad, preempt,
                delay_tas_fn, fair_strategies,
            )
            self.last_stats = {"path": "full", "reason": reason}
        dt = time.perf_counter() - t0
        self.last_stats["encode_s"] = dt
        path = self.last_stats["path"]
        tracing.observe("solver_encode_seconds", dt, labels={"path": path})
        tracing.inc(
            "solver_arena_cycles_total",
            labels={"path": path, "reason": reason or "ok"},
        )
        if path == "incremental":
            for axis in ("workload", "admitted", "node"):
                tracing.observe(
                    "solver_arena_dirty_rows",
                    float(self.last_stats.get("dirty_" + axis, 0)),
                    labels={"axis": axis},
                )
        if self.verify and path == "incremental":
            self._verify(out, snapshot, heads, resource_flavors, w_pad,
                         preempt, delay_tas_fn, fair_strategies)
        return out

    # -- gating -------------------------------------------------------------

    def _gate(self, snapshot, heads, preempt, events) -> Optional[str]:
        if self.fair_sharing:
            return "fair"
        if not preempt:
            return "no-preempt"
        if snapshot.tas_flavors:
            return "tas"
        if not self._committed:
            return "cold"
        if getattr(snapshot, "quota_generation", None) != self._quota_gen:
            return "quota-gen"
        if events is None:
            return "event-gap"
        for info in heads:
            for ps in info.obj.pod_sets:
                if ps.topology_request is not None:
                    return "topology-head"
        from kueue_tpu.utils import features as _feat

        if _feat.enabled("PartialAdmission"):
            for info in heads:
                if any(
                    ps.min_count is not None and ps.min_count < ps.count
                    for ps in info.obj.pod_sets
                ):
                    return "partial"
        return None

    # -- full path ----------------------------------------------------------

    def _component_keys(self, snapshot) -> dict:
        qg = getattr(snapshot, "quota_generation", None)
        ag = getattr(snapshot, "admitted_generation", None)
        if snapshot.tas_flavors:
            # TAS rows depend on topology snapshots and every workload's
            # TAS usage: stay exactly as conservative as the legacy key.
            adm = (qg, getattr(snapshot, "node_generation", None),
                   getattr(snapshot, "workload_generation", None),
                   self.fair_sharing, "tas")
        else:
            adm = (qg, ag, self.fair_sharing)
        return {"prio": (qg, ag), "adm": adm}

    def _capture(self, snapshot, heads, resource_flavors, w_pad, preempt,
                 delay_tas_fn, fair_strategies):
        arrays, idx = encode_cycle(
            snapshot, heads, resource_flavors, w_pad=w_pad,
            fair_sharing=self.fair_sharing, preempt=preempt,
            delay_tas_fn=delay_tas_fn, fair_strategies=fair_strategies,
            admitted_cache=self.component_cache,
            admitted_key=self._component_keys(snapshot),
            device_put=False,
        )
        dev_arrays, dev_groups, dev_adm = jax.device_put(
            (arrays, idx.group_arrays, idx.admitted_arrays)
        )
        # Keep the component cache on-device so later full encodes (and
        # non-arena callers sharing the cache) pass resident tensors through.
        keys = self._component_keys(snapshot)
        self.component_cache["prio"] = (
            keys["prio"],
            (dev_arrays.usage_by_prio, dev_arrays.prio_cuts,
             dev_arrays.prefilter_valid),
        )
        if preempt and "adm" in self.component_cache:
            k, (adm_list, _old, simple, hier, fair_ok, tas_ok) = (
                self.component_cache["adm"]
            )
            self.component_cache["adm"] = (
                k, (adm_list, dev_adm, simple, hier, fair_ok, tas_ok)
            )
        idx.group_arrays = dev_groups
        idx.admitted_arrays = dev_adm
        self._committed = False
        if (preempt and not self.fair_sharing and not snapshot.tas_flavors
                and not idx.has_partial and idx.n_slots == 1
                and idx.admitted_arrays is not None):
            self._capture_state(snapshot, arrays, idx, dev_arrays, dev_adm,
                                dev_groups)
        return dev_arrays, idx

    def _capture_state(self, snapshot, arrays, idx, dev_arrays, dev_adm,
                       dev_groups) -> None:
        tidx = idx.tree_index
        self._tidx = tidx
        self._node_of = dict(tidx.node_of)
        self._flavor_of = dict(tidx.flavor_of)
        self._resource_of = dict(tidx.resource_of)
        self._node_names = [nd.name for nd in tidx.nodes]
        self._cq_names = list(snapshot.cluster_queues.keys())
        self._quota_gen = getattr(snapshot, "quota_generation", None)
        tree = dev_arrays.tree
        n = int(tree.parent.shape[0])
        self._n = n
        self._f = int(tree.nominal.shape[1])
        self._r = int(tree.nominal.shape[2])
        self._parent = np.asarray(tree.parent)
        # Static per-root facts (replicates _encode_admitted's topology
        # scan; only the unmappable-usage term is dynamic).
        active = np.asarray(tree.active)
        has_lend = np.asarray(tree.has_lend_limit).any(axis=(1, 2))
        is_cq_node = np.zeros(n, dtype=bool)
        for name in snapshot.cluster_queues:
            is_cq_node[self._node_of[name]] = True
        root_of = np.arange(n)
        for _ in range(MAX_DEPTH):
            root_of = np.where(
                self._parent[root_of] >= 0, self._parent[root_of], root_of
            )
        self._root_of = root_of
        static_ok = np.ones(n, dtype=bool)
        static_fair_ok = np.ones(n, dtype=bool)
        for node in range(n):
            if not active[node]:
                continue
            rt = root_of[node]
            if has_lend[node]:
                static_ok[rt] = False
                static_fair_ok[rt] = False
            if node != rt and not is_cq_node[node]:
                static_ok[rt] = False
        self._root_static_ok = static_ok
        self._root_static_fair_ok = static_fair_ok
        self._cq_node_idx = np.asarray(
            [self._node_of[name] for name in self._cq_names], dtype=np.int64
        )
        # Dynamic admitted state: slab store + per-CQ slot order + per-root
        # unmappable-usage counters + priority census + uid order.
        adm = idx.admitted
        a = int(np.asarray(dev_adm.cq).shape[0])
        self._a = a
        store = _AdmittedStore(self._f, self._r)
        self._store = store
        self._order: Dict[str, Dict[str, int]] = {}
        self._root_unmap = np.zeros(n, dtype=np.int64)
        prio_counter: Counter = Counter()
        for i, info in enumerate(adm):
            slot = store.alloc()
            unmap = store.set_row(
                slot, self._node_of[info.cluster_queue], info,
                tuple(info.usage().items()), info.priority(), info.obj.uid,
                self._flavor_of, self._resource_of,
            )
            self._order.setdefault(info.cluster_queue, {})[info.key] = slot
            self._root_unmap[root_of[self._node_of[info.cluster_queue]]] += \
                unmap
            prio_counter[int(info.priority())] += 1
        self._prio_counter = prio_counter
        self._uid_sorted = np.array(
            sorted(info.obj.uid for info in adm), dtype=object
        )
        self._admitted_list = list(adm)
        # Host numpy mirrors of every dynamic tensor family.
        asnp = lambda x: np.array(np.asarray(x))  # writable host copy
        self._m_usage = asnp(arrays.usage)
        self._m_ubp = asnp(arrays.usage_by_prio)
        self._m_cuts = asnp(arrays.prio_cuts)
        self._prefilter_valid_b = bool(np.asarray(arrays.prefilter_valid))
        self._prio_rank = {}
        if self._prefilter_valid_b:
            for rank_i, pv in enumerate(sorted(prio_counter)):
                self._prio_rank[pv] = rank_i
        self._mw = {
            "w_cq": asnp(arrays.w_cq),
            "w_req": asnp(arrays.w_req),
            "w_elig": asnp(arrays.w_elig),
            "w_active": asnp(arrays.w_active),
            "w_priority": asnp(arrays.w_priority),
            "w_timestamp": asnp(arrays.w_timestamp),
            "w_quota_reserved": asnp(arrays.w_quota_reserved),
            "w_start_flavor": asnp(arrays.w_start_flavor),
            "w_order_rank": asnp(arrays.w_order_rank),
            "w_has_gates": asnp(arrays.w_has_gates),
        }
        self._w = int(self._mw["w_cq"].shape[0])
        self._ma = {
            "cq": asnp(dev_adm.cq),
            "usage": asnp(dev_adm.usage),
            "prio": asnp(dev_adm.prio),
            "ts": asnp(dev_adm.ts),
            "qr_time": asnp(dev_adm.qr_time),
            "evicted": asnp(dev_adm.evicted),
            "active": asnp(dev_adm.active),
            "uid_rank": asnp(dev_adm.uid_rank),
        }
        self._m_simple = asnp(arrays.preempt_simple)
        self._has_hier = arrays.preempt_hier is not None
        self._m_hier = (
            asnp(arrays.preempt_hier) if self._has_hier
            else np.zeros(n, dtype=bool)
        )
        self._tas_ok_np = (
            np.asarray(arrays.preempt_tas_ok)
            if arrays.preempt_tas_ok is not None else None
        )
        self._dev_arrays = dev_arrays
        self._dev_adm = dev_adm
        self._dev_groups = dev_groups
        self._committed = True

    # -- incremental path ---------------------------------------------------

    def _incremental(self, snapshot, heads, resource_flavors, w_pad,
                     delay_tas_fn, events):
        if faults.ENABLED:
            faults.fire(faults.ARENA_DELTA_APPLY)
        n, f, r = self._n, self._f, self._r
        stats: Dict[str, object] = {"path": "incremental",
                                    "events": len(events)}
        # 1. Replay workload events into the admitted state.
        dirty_nodes: set = set()
        touched_roots = False
        adm_dirty = bool(events)
        for kind, key, cq, items, prio, uid, info in events:
            cq_i = self._node_of.get(cq)
            d = self._order.setdefault(cq, {})
            if cq_i is None:
                # CQ outside the encoded snapshot: from-scratch encode
                # skips these rows too; keep only the order bookkeeping
                # (slot -1) so a later remove pairs up.
                if kind > 0:
                    d[key] = -1
                else:
                    d.pop(key, None)
                continue
            if kind > 0:
                slot = self._store.alloc()
                unmap = self._store.set_row(
                    slot, cq_i, info, items, prio, uid,
                    self._flavor_of, self._resource_of,
                )
                d[key] = slot
                self._prio_counter[int(prio)] += 1
                self._uid_insert(uid)
                sign = 1
            else:
                slot = d.pop(key, None)
                if slot is None or slot < 0:
                    continue
                unmap = 0
                for fr, _v in items:
                    if (self._flavor_of.get(fr.flavor) is None
                            or self._resource_of.get(fr.resource) is None):
                        unmap += 1
                self._store.release(slot)
                c = self._prio_counter
                c[int(prio)] -= 1
                if c[int(prio)] <= 0:
                    del c[int(prio)]
                self._uid_remove(uid)
                sign = -1
            if unmap:
                self._root_unmap[self._root_of[cq_i]] += sign * unmap
                touched_roots = True
            if self._prefilter_valid_b:
                b = self._prio_rank.get(int(prio), _B - 1)
                for fr, v in items:
                    fi = self._flavor_of.get(fr.flavor)
                    ri = self._resource_of.get(fr.resource)
                    if fi is not None and ri is not None:
                        self._m_ubp[cq_i, fi, ri, b] += sign * v
            walk = cq_i
            while walk >= 0:
                dirty_nodes.add(int(walk))
                walk = self._parent[walk]
        # 2. Priority census must still match the committed buckets.
        prios = sorted(self._prio_counter)
        valid = len(prios) <= _B
        if valid != self._prefilter_valid_b:
            raise _Fallback("prio-validity")
        if valid:
            cuts = np.full(_B, np.iinfo(np.int64).max // 2, dtype=np.int64)
            cuts[: len(prios)] = prios
            if not np.array_equal(cuts, self._m_cuts):
                raise _Fallback("prio-cuts")

        payload_np: List[object] = []
        apply_plan: List[Tuple] = []

        # 3. Node family: re-read dirty usage rows from the snapshot tree
        # (the same dicts encode_tree reads).
        if dirty_nodes:
            node_idx = np.asarray(sorted(dirty_nodes), dtype=np.int64)
            rows = np.zeros((len(node_idx), f, r), dtype=np.int64)
            for j, ni in enumerate(node_idx):
                name = self._node_names[ni]
                cqs = snapshot.cluster_queues.get(name)
                node = cqs.node if cqs is not None else snapshot.cohorts[name]
                row = rows[j]
                for fr, v in node.usage.items():
                    row[self._flavor_of[fr.flavor],
                        self._resource_of[fr.resource]] = v
            self._m_usage[node_idx] = rows
            u_idx, u_rows = _pad_bucket(node_idx, {"usage": rows})
            apply_plan.append(("node", u_idx, u_rows))
            if self._prefilter_valid_b:
                ubp_rows = self._m_ubp[node_idx]
                p_idx, p_rows = _pad_bucket(
                    node_idx, {"usage_by_prio": ubp_rows}
                )
                apply_plan.append(("prio", p_idx, p_rows))
        stats["dirty_node"] = len(dirty_nodes)

        # 4. A family: rebuild the flat admitted order + mirrors by gather.
        a_update = None
        if adm_dirty:
            slots_list: List[int] = []
            for name in self._cq_names:
                d = self._order.get(name)
                if d:
                    slots_list.extend(d.values())
            cnt = len(slots_list)
            a_new = max(8, _round_up(cnt, 8))
            slots_flat = np.asarray(slots_list, dtype=np.int64)
            st = self._store
            new_ma = {
                k: np.zeros((a_new,) + tail, dtype=dt)
                for k, tail, dt in (
                    ("cq", (), np.int32), ("usage", (f, r), np.int64),
                    ("prio", (), np.int64), ("ts", (), np.float64),
                    ("qr_time", (), np.float64), ("evicted", (), bool),
                    ("active", (), bool), ("uid_rank", (), np.int32),
                )
            }
            if cnt:
                new_ma["cq"][:cnt] = st.cq[slots_flat]
                new_ma["usage"][:cnt] = st.usage[slots_flat]
                new_ma["prio"][:cnt] = st.prio[slots_flat]
                new_ma["ts"][:cnt] = st.ts[slots_flat]
                new_ma["qr_time"][:cnt] = st.qr[slots_flat]
                new_ma["evicted"][:cnt] = st.evicted[slots_flat]
                new_ma["active"][:cnt] = True
                new_ma["uid_rank"][:cnt] = np.searchsorted(
                    self._uid_sorted, st.uid[slots_flat]
                ).astype(np.int32)
            self._admitted_list = (
                list(st.info[slots_flat]) if cnt else []
            )
            if a_new != self._a:
                self._a = a_new
                a_update = ("full", new_ma)
                stats["dirty_admitted"] = cnt
            else:
                dirty = np.zeros(a_new, dtype=bool)
                for k2, v in new_ma.items():
                    old = self._ma[k2]
                    neq = v != old
                    if neq.ndim > 1:
                        neq = neq.any(axis=tuple(range(1, neq.ndim)))
                    dirty |= neq
                didx = np.flatnonzero(dirty)
                stats["dirty_admitted"] = int(len(didx))
                if len(didx):
                    a_update = (
                        "scatter", didx,
                        {k2: v[didx] for k2, v in new_ma.items()},
                    )
            self._ma = new_ma
        else:
            stats["dirty_admitted"] = 0
        if a_update is not None and a_update[0] == "scatter":
            a_idx, a_rows = _pad_bucket(a_update[1], a_update[2])
            apply_plan.append(("adm", a_idx, a_rows))

        # 5. Flag family (preempt_simple / preempt_hier).
        flags_put = None
        if touched_roots:
            ok_dyn = self._root_static_ok & (self._root_unmap == 0)
            fair_dyn = self._root_static_fair_ok & (self._root_unmap == 0)
            simple = np.zeros(n, dtype=bool)
            hier = np.zeros(n, dtype=bool)
            cq_i = self._cq_node_idx
            simple[cq_i] = ok_dyn[self._root_of[cq_i]]
            hier[cq_i] = fair_dyn[self._root_of[cq_i]] & ~ok_dyn[
                self._root_of[cq_i]
            ]
            if bool(hier.any()) != self._has_hier:
                raise _Fallback("hier-toggle")
            if (not np.array_equal(simple, self._m_simple)
                    or not np.array_equal(hier, self._m_hier)):
                flags_put = (simple, hier)
                self._m_simple = simple
                self._m_hier = hier

        # 6. W family: per-head rows (inherently O(heads)), diffed. A
        # staged speculation buffer (pipelined driver) patches in clean
        # rows here; dirty rows are recomputed exactly as without it.
        device_wls, fallbacks, new_mw = self._build_w(
            snapshot, heads, resource_flavors, w_pad,
            spec=self._take_speculation(),
        )
        stats["rows_recomputed"] = len(device_wls)
        w_new = int(new_mw["w_cq"].shape[0])
        w_update = None
        if w_new != self._w:
            self._w = w_new
            w_update = ("full", new_mw)
            stats["dirty_workload"] = len(device_wls)
        else:
            dirty = np.zeros(w_new, dtype=bool)
            for k2, v in new_mw.items():
                old = self._mw[k2]
                neq = v != old
                if neq.ndim > 1:
                    neq = neq.any(axis=tuple(range(1, neq.ndim)))
                dirty |= neq
            didx = np.flatnonzero(dirty)
            stats["dirty_workload"] = int(len(didx))
            if len(didx):
                w_update = (
                    "scatter", *_pad_bucket(
                        didx, {k2: v[didx] for k2, v in new_mw.items()}
                    ),
                )
        self._mw = new_mw
        if w_update is not None and w_update[0] == "scatter":
            apply_plan.append(("wl", w_update[1], w_update[2]))

        # 7. ONE batched transfer of the whole delta payload, then one
        # jitted scatter per dirty family; resized families re-put whole.
        plan_fams = [fam for fam, _, _ in apply_plan]
        puts = {"plan": [(idx_, rows) for _, idx_, rows in apply_plan]}
        if a_update is not None and a_update[0] == "full":
            puts["a_full"] = a_update[1]
        if w_update is not None and w_update[0] == "full":
            puts["w_full"] = w_update[1]
        if flags_put is not None:
            puts["flags"] = flags_put
        if len(puts) > 1 or puts["plan"]:
            puts = jax.device_put(puts)

        dev = self._dev_arrays
        dev_adm = self._dev_adm
        fam_cols = {
            "node": {"usage": dev.usage},
            "prio": {"usage_by_prio": dev.usage_by_prio},
            "adm": {
                "cq": dev_adm.cq, "usage": dev_adm.usage,
                "prio": dev_adm.prio, "ts": dev_adm.ts,
                "qr_time": dev_adm.qr_time, "evicted": dev_adm.evicted,
                "active": dev_adm.active, "uid_rank": dev_adm.uid_rank,
            },
            "wl": {
                "w_cq": dev.w_cq, "w_req": dev.w_req,
                "w_elig": dev.w_elig, "w_active": dev.w_active,
                "w_priority": dev.w_priority,
                "w_timestamp": dev.w_timestamp,
                "w_quota_reserved": dev.w_quota_reserved,
                "w_start_flavor": dev.w_start_flavor,
                "w_order_rank": dev.w_order_rank,
                "w_has_gates": dev.w_has_gates,
            },
        }
        updated: Dict[str, Dict[str, jnp.ndarray]] = {}
        for fam, (idx_, rows) in zip(plan_fams, puts["plan"]):
            updated[fam] = _scatter_rows(fam_cols[fam], idx_, rows)
        if "a_full" in puts:
            updated["adm"] = puts["a_full"]
        if "w_full" in puts:
            updated["wl"] = puts["w_full"]

        repl: Dict[str, object] = {}
        if "node" in updated:
            repl["usage"] = updated["node"]["usage"]
        if "prio" in updated:
            repl["usage_by_prio"] = updated["prio"]["usage_by_prio"]
        if "wl" in updated:
            wl = updated["wl"]
            repl.update(
                w_cq=wl["w_cq"], w_req=wl["w_req"], w_elig=wl["w_elig"],
                w_active=wl["w_active"], w_priority=wl["w_priority"],
                w_timestamp=wl["w_timestamp"],
                w_quota_reserved=wl["w_quota_reserved"],
                w_start_flavor=wl["w_start_flavor"],
                w_order_rank=wl["w_order_rank"],
                w_has_gates=wl["w_has_gates"],
            )
        if "flags" in puts:
            repl["preempt_simple"] = puts["flags"][0]
            if self._has_hier:
                repl["preempt_hier"] = puts["flags"][1]
        if "adm" in updated:
            ad = updated["adm"]
            from kueue_tpu.models.preempt_kernel import AdmittedArrays

            dev_adm = AdmittedArrays(
                cq=ad["cq"], usage=ad["usage"], prio=ad["prio"],
                ts=ad["ts"], qr_time=ad["qr_time"], evicted=ad["evicted"],
                active=ad["active"], uid_rank=ad["uid_rank"],
                tas_t=None, tas_usage=None,
            )
            self._dev_adm = dev_adm
        arrays = dev._replace(**repl) if repl else dev
        self._dev_arrays = arrays

        idx = CycleIndex(
            tree_index=self._tidx,
            resources=list(self._tidx.resources),
            flavors=list(self._tidx.flavors),
        )
        idx.workloads = device_wls
        idx.host_fallback = fallbacks
        idx.delayed_tas = [False] * len(device_wls)
        idx.group_arrays = self._dev_groups
        idx.admitted = list(self._admitted_list)
        idx.admitted_arrays = self._dev_adm
        self.last_stats = stats
        # Refresh the component cache so a later full encode with the same
        # admitted state reuses the arena-updated tensors.
        keys = self._component_keys(snapshot)
        self.component_cache["prio"] = (
            keys["prio"],
            (arrays.usage_by_prio, arrays.prio_cuts, arrays.prefilter_valid),
        )
        self.component_cache["adm"] = (
            keys["adm"],
            (list(self._admitted_list), self._dev_adm,
             np.array(self._m_simple), np.array(self._m_hier), None,
             self._tas_ok_np),
        )
        return arrays, idx

    # -- uid order maintenance ---------------------------------------------

    def _uid_insert(self, uid) -> None:
        pos = int(np.searchsorted(self._uid_sorted, uid))
        self._uid_sorted = np.insert(self._uid_sorted, pos, uid)

    def _uid_remove(self, uid) -> None:
        pos = int(np.searchsorted(self._uid_sorted, uid))
        if pos < len(self._uid_sorted) and self._uid_sorted[pos] == uid:
            self._uid_sorted = np.delete(self._uid_sorted, pos)

    # -- W family (replicates the encode_cycle head loop, dense case) -------

    def _build_w(self, snapshot, heads, resource_flavors, w_pad,
                 spec=None):
        """Dispatch: columnar W build off the cache's struct-of-arrays
        store when attached and the backlog is dense (cache/columns.py);
        the row-wise oracle (``_build_w_rows``) otherwise. Both are
        bit-identical by construction (and compared in verify mode);
        speculation staging rides whichever path runs, warming store
        rows during the device overlap."""
        from kueue_tpu.models.encode import columns_mode

        store = getattr(snapshot, "workload_columns", None)
        view = None
        if store is not None and columns_mode() != "off":
            view = store.gather(heads, snapshot, resource_flavors)
        if view is None:
            return self._build_w_rows(
                snapshot, heads, resource_flavors, w_pad, spec
            )
        device_wls = [heads[j] for j in view.device_idx]
        fallbacks = [heads[j] for j in view.fallback_idx]
        if w_pad == 0:
            w = max(16, 1 << max(len(device_wls) - 1, 0).bit_length())
        else:
            w = w_pad
        f, r = self._f, self._r
        mw = {
            "w_cq": np.zeros(w, dtype=np.int32),
            "w_req": np.zeros((w, r), dtype=np.int64),
            "w_elig": np.zeros((w, f), dtype=bool),
            "w_active": np.zeros(w, dtype=bool),
            "w_priority": np.zeros(w, dtype=np.int64),
            "w_timestamp": np.zeros(w, dtype=np.float64),
            "w_quota_reserved": np.zeros(w, dtype=bool),
            "w_start_flavor": np.zeros(w, dtype=np.int32),
            "w_has_gates": np.zeros(w, dtype=bool),
        }
        if spec is not None:
            # Keep the speculation-consumption contract (fault point,
            # abort taxonomy, consumed/reused_rows accounting) exactly as
            # the row-wise path: the plan's values are not needed — a
            # columnar recompute of a validated staged row is the same
            # bits — but its bookkeeping is part of the pipeline's
            # observable behavior.
            self._spec_plan(spec, device_wls, snapshot, w)
        store.assemble(
            view.rows, self._node_of, self._flavor_of, self._resource_of,
            {
                "w_cq": mw["w_cq"], "w_active": mw["w_active"],
                "w_priority": mw["w_priority"],
                "w_timestamp": mw["w_timestamp"],
                "w_quota_reserved": mw["w_quota_reserved"],
                "w_gates": mw["w_has_gates"],
                "w_start_flavor": mw["w_start_flavor"],
                "w_req": mw["w_req"], "w_elig": mw["w_elig"],
            },
        )
        mw["w_order_rank"] = _order_rank(
            mw["w_priority"], mw["w_timestamp"]
        )
        if columns_mode() == "verify":
            self._verify_build_w(
                snapshot, heads, resource_flavors, w_pad,
                device_wls, fallbacks, mw
            )
        return device_wls, fallbacks, mw

    def _verify_build_w(self, snapshot, heads, resource_flavors, w_pad,
                        device_wls, fallbacks, mw):
        """Verify-mode oracle comparison for the columnar W build."""
        ref_wls, ref_fallbacks, ref_mw = self._build_w_rows(
            snapshot, heads, resource_flavors, w_pad, None
        )
        if [id(x) for x in ref_wls] != [id(x) for x in device_wls] \
                or [id(x) for x in ref_fallbacks] \
                != [id(x) for x in fallbacks]:
            raise AssertionError(
                "columns/oracle divergence: arena partition mismatch"
            )
        for col, v in ref_mw.items():
            if not np.array_equal(mw[col], v):
                raise AssertionError(
                    f"columns/oracle divergence on arena {col}"
                )

    def _build_w_rows(self, snapshot, heads, resource_flavors, w_pad,
                      spec=None):
        """Row-wise W build — the oracle the columnar path is compared
        against, and the fallback for ragged backlogs. Per-workload
        Python by design (allowlisted in check_encode_columns)."""
        from kueue_tpu.scheduler.flavorassigner import FlavorAssigner

        f, r = self._f, self._r
        device_wls: List[WorkloadInfo] = []
        wl_slots: List[list] = []
        fallbacks: List[WorkloadInfo] = []
        for info in heads:
            slots = (
                _workload_slots(
                    info, snapshot.cluster_queues[info.cluster_queue]
                )
                if info.cluster_queue in snapshot.cluster_queues else None
            )
            if _device_compatible(info, snapshot, slots, frozenset(), False,
                                  True, False):
                device_wls.append(info)
                wl_slots.append(slots)
            else:
                fallbacks.append(info)
        if any(len(sl) > 1 or sl[0].rg_idx != 0 for sl in wl_slots):
            raise _Fallback("slots")
        if w_pad == 0:
            w = max(16, 1 << max(len(device_wls) - 1, 0).bit_length())
        else:
            w = w_pad
        mw = {
            "w_cq": np.zeros(w, dtype=np.int32),
            "w_req": np.zeros((w, r), dtype=np.int64),
            "w_elig": np.zeros((w, f), dtype=bool),
            "w_active": np.zeros(w, dtype=bool),
            "w_priority": np.zeros(w, dtype=np.int64),
            "w_timestamp": np.zeros(w, dtype=np.float64),
            "w_quota_reserved": np.zeros(w, dtype=bool),
            "w_start_flavor": np.zeros(w, dtype=np.int32),
            "w_has_gates": np.zeros(w, dtype=bool),
        }
        plan = (
            self._spec_plan(spec, device_wls, snapshot, w)
            if spec is not None else None
        )
        for i, info in enumerate(device_wls):
            if plan is not None:
                j = plan.get(i)
                if j is not None:
                    for col, v in spec["mw"].items():
                        if col != "w_order_rank":
                            mw[col][i] = v[j]
                    continue
            slots = wl_slots[i]
            cqs = snapshot.cluster_queues[info.cluster_queue]
            ps0 = info.obj.pod_sets[0]
            if ps0.min_count is not None and ps0.min_count < ps0.count:
                from kueue_tpu.utils import features as _feat

                if _feat.enabled("PartialAdmission"):
                    raise _Fallback("partial")
            mw["w_cq"][i] = self._node_of[info.cluster_queue]
            mw["w_active"][i] = True
            mw["w_priority"][i] = info.priority()
            mw["w_timestamp"][i] = queue_order_timestamp(info.obj)
            mw["w_quota_reserved"][i] = has_quota_reservation(info.obj)
            mw["w_has_gates"][i] = bool(info.obj.preemption_gates)
            for res, v in slots[0].requests.items():
                if res in self._resource_of:
                    mw["w_req"][i, self._resource_of[res]] = v
            gen = cqs.allocatable_generation
            cached = getattr(info, "_elig_cache", None)
            if cached is not None and cached[0] == gen \
                    and cached[1].shape == (len(slots), f):
                erows = cached[1]
            else:
                assigner = FlavorAssigner(info, cqs, resource_flavors)
                erows = np.zeros((len(slots), f), dtype=bool)
                for si, sl in enumerate(slots):
                    pod_sets = [info.obj.pod_sets[j] for j in sl.ps_ids]
                    for fname, fi in self._flavor_of.items():
                        ok, _ = assigner._check_flavor_for_podsets(
                            fname, pod_sets
                        )
                        erows[si, fi] = ok
                info._elig_cache = (gen, erows)
            allowed = info.obj.labels.get(
                "kueue.x-k8s.io/allowed-resource-flavor"
            )
            if allowed is not None:
                amask = np.zeros(f, dtype=bool)
                ai = self._flavor_of.get(allowed)
                if ai is not None:
                    amask[ai] = True
                erows = erows & amask[None, :]
            mw["w_elig"][i] = erows[0]
            resume = info.last_assignment is not None and (
                cqs.allocatable_generation
                <= info.last_assignment.cluster_queue_generation
            )
            if resume:
                mw["w_start_flavor"][i] = (
                    info.last_assignment.next_flavor_to_try(
                        slots[0].ps_ids[0], slots[0].trigger_res
                    )
                )
        mw["w_order_rank"] = _order_rank(
            mw["w_priority"], mw["w_timestamp"]
        )
        return device_wls, fallbacks, mw

    # -- differential verification ------------------------------------------

    def _verify(self, out, snapshot, heads, resource_flavors, w_pad,
                preempt, delay_tas_fn, fair_strategies) -> None:
        arrays, idx = out
        ref_arrays, ref_idx = encode_cycle(
            snapshot, heads, resource_flavors, w_pad=w_pad,
            fair_sharing=self.fair_sharing, preempt=preempt,
            delay_tas_fn=delay_tas_fn, fair_strategies=fair_strategies,
            device_put=False,
        )
        assert_cycle_equal(arrays, idx, ref_arrays, ref_idx)


class TileCarry:
    """Cross-tile bookkeeping for one tiled admission cycle
    (models/driver.py ``_schedule_tiled``).

    The quota/admitted carry itself is the arena: tile k's applies land
    as cache events, and tile k+1's ``take_snapshot`` drains them into
    row deltas — tile k+1 therefore encodes against tile k's post-apply
    usage and admitted set without a full re-capture. What this object
    carries is the *accounting* of that stream: rows solved, tiles
    faulted into the host path, and the peak plane bytes any single tile
    materialized (the memory bound tiling exists to enforce — see
    ``bench.py --probe tiled``'s ``tiled_peak_plane_mb`` headline).
    """

    def __init__(self, width: int, tiles: int) -> None:
        self.width = int(width)
        self.tiles = int(tiles)
        self.tiles_done = 0
        self.rows = 0
        self.faulted_tiles = 0
        self.peak_plane_bytes = 0

    def note_plane(self, nbytes: int) -> None:
        """Record one tile's materialized plane size (driver hook,
        called right after the tile's encode)."""
        if nbytes > self.peak_plane_bytes:
            self.peak_plane_bytes = int(nbytes)

    def note_tile(self, rows: int, faulted: bool = False) -> None:
        self.tiles_done += 1
        self.rows += int(rows)
        if faulted:
            self.faulted_tiles += 1

    def stats(self) -> dict:
        return {
            "width": self.width,
            "tiles": self.tiles,
            "tiles_done": self.tiles_done,
            "rows": self.rows,
            "faulted_tiles": self.faulted_tiles,
            "peak_plane_bytes": self.peak_plane_bytes,
        }


def _field_equal(name: str, a, b) -> None:
    if a is None or b is None:
        assert a is None and b is None, (
            f"{name}: presence differs (incremental "
            f"{'set' if a is not None else 'None'}, reference "
            f"{'set' if b is not None else 'None'})"
        )
        return
    an, bn = np.asarray(a), np.asarray(b)
    assert an.dtype == bn.dtype, f"{name}: dtype {an.dtype} != {bn.dtype}"
    assert an.shape == bn.shape, f"{name}: shape {an.shape} != {bn.shape}"
    assert np.array_equal(an, bn), (
        f"{name}: values differ at rows "
        f"{np.argwhere((an != bn).reshape(an.shape[0], -1).any(axis=-1) if an.ndim else an != bn)[:8].tolist()}"
    )


def assert_cycle_equal(arrays: CycleArrays, idx: CycleIndex,
                       ref_arrays: CycleArrays, ref_idx: CycleIndex) -> None:
    """Assert the arena-built cycle is bit-identical to from-scratch."""
    for fname in type(ref_arrays.tree)._fields:
        _field_equal(
            "tree." + fname,
            getattr(arrays.tree, fname), getattr(ref_arrays.tree, fname),
        )
    for fname in CycleArrays._fields:
        if fname == "tree":
            continue
        a = getattr(arrays, fname)
        b = getattr(ref_arrays, fname)
        if fname == "tas_topo":
            continue
        _field_equal(fname, a, b)
    aa, bb = idx.admitted_arrays, ref_idx.admitted_arrays
    assert (aa is None) == (bb is None), "admitted_arrays presence differs"
    if aa is not None:
        for fname in aa._fields:
            _field_equal(
                "admitted." + fname, getattr(aa, fname), getattr(bb, fname)
            )
    assert [i.key for i in idx.workloads] == \
        [i.key for i in ref_idx.workloads], "device workload order differs"
    assert [i.key for i in idx.host_fallback] == \
        [i.key for i in ref_idx.host_fallback], "host fallback differs"
    assert [i.key for i in idx.admitted] == \
        [i.key for i in ref_idx.admitted], "admitted row order differs"
    assert idx.delayed_tas == ref_idx.delayed_tas, "delayed flags differ"
    assert idx.has_partial == ref_idx.has_partial
    assert idx.n_slots == ref_idx.n_slots
    assert idx.fair_s_bound == ref_idx.fair_s_bound
    assert idx.flavors == ref_idx.flavors
    assert idx.resources == ref_idx.resources
