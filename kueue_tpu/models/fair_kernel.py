"""Device-side fair-sharing admission: the DRS tournament as a scan.

Tensor reformulation of the reference's fair-sharing iterator
(pkg/scheduler/fair_sharing_iterator.go + pkg/cache/scheduler/fair_sharing.go
dominantResourceShare/CompareDRS): each scheduling step recomputes every
remaining entry's DominantResourceShare at each ancestor of its ClusterQueue
(with the entry's nominated usage simulated in), runs the hierarchical
tournament (champions bubble from the leaves to the root, compared at each
cohort by the DRS of the child on the entry's path, tie-broken by priority
then queue timestamp), and processes the per-tree winner with the usual
fit-or-skip admission body.

Exactness preconditions (the encoder gates entries accordingly —
models/encode.py):
  * at most one tournament entry per CQ — the host iterator keys entries
    by CQ and keeps only the LAST nominated one (fair_sharing_iterator
    semantics); earlier same-CQ entries are reported OUT_SHADOWED and
    requeued unprocessed, exactly like the host's untouched entries;
  * entries needing a preemption oracle the device cannot resolve stay on
    the host path; the driver discards device outcomes for any tree
    containing one (or any encode host-fallback entry) and routes that
    whole tree through the host so tournament interleaving stays exact
    per tree;
  * TAS entries are device-eligible when their topology flavor is used by
    a single cohort tree (winners of different trees in the same step
    would otherwise race on shared topology state).

Lending limits are exact: the DRS simulation adds the workload's usage
unclamped at every ancestor (reference fair_sharing.go:149 adds wlReq in
full), while fit checks run the same availability walk as the grouped
admission scan and winner usage bubbles with local-availability clamping
(resource_node.go:144) — so partially-lent trees evolve identically to
the host cache.

The tournament is independent per cohort tree, so every step processes one
winner per tree simultaneously on the flat usage state — no grouped layout
needed.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kueue_tpu.models.batch_scheduler import (
    CycleOutputs,
    NominateResult,
    OUT_ADMITTED,
    OUT_FIT_SKIPPED,
    OUT_NEEDS_HOST,
    OUT_NO_CANDIDATES,
    OUT_NOFIT,
    OUT_PREEMPTING,
    OUT_SHADOWED,
    P_FIT,
    P_NO_CANDIDATES,
    P_NOFIT,
    P_PREEMPT_OK,
    P_PREEMPT_RAW,
    apply_tas_nominate_hook,
    nominate,
)
from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.models.fair_preempt_kernel import fair_preempt_targets
from kueue_tpu.models import slot_tas as _slot_tas
from kueue_tpu.ops import quota_ops
from kueue_tpu.ops.quota_ops import MAX_DEPTH, sat_add, sat_sub

_INF64 = jnp.int64(1) << 61
_F64_INF = jnp.float64(jnp.inf)


class FairScanResult(NamedTuple):
    """Result of :func:`fair_admit_scan` (a pytree — flows through
    jit/scan unchanged; fields formerly threaded as a positional
    8-tuple)."""

    usage: jnp.ndarray  # [N,F,R] final usage after the tournament
    admitted: jnp.ndarray  # bool[W]
    preempting: jnp.ndarray  # bool[W]
    shadowed: jnp.ndarray  # bool[W] lost to a same-CQ earlier entry
    participated: jnp.ndarray  # bool[W] decided within s_max steps
    win_step: jnp.ndarray  # i32[W] tournament step won at (-1 = lost)
    tas_takes: jnp.ndarray  # i32[W,D] or None
    s_tas_takes: jnp.ndarray  # i32[W,S,D] or None
    slot_rounds: jnp.ndarray = None  # i32[] max conflict rounds, or None


def _fair_ctx(
    arrays: CycleArrays,
    nom: NominateResult,
    adm=None,
    targets=None,
):
    """Build the shared tournament context: participant compaction, all
    per-chain statics, the DRS key/tournament functions and the per-step
    scan ``body``, plus slot-normalized views (an explicit S axis, S=1
    for legacy single-plane cycles) of the fit/apply tensors that the
    fixed-point rounds analysis (models/fair_fixedpoint.py) reuses.
    Returned as a namespace so :func:`fair_admit_scan` and
    ``fair_admit_fixedpoint`` run the exact same step semantics."""
    tree = arrays.tree
    w_n = arrays.w_cq.shape[0]
    n = tree.n_nodes
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
    w_iota = jnp.arange(w_n, dtype=jnp.int32)

    parent = jnp.where(tree.parent < 0, jnp.arange(n), tree.parent)

    root_of = jnp.arange(n)
    for _ in range(MAX_DEPTH):
        root_of = parent[root_of]

    with_preempt = targets is not None
    with_tas = getattr(arrays, "tas_topo", None) is not None
    if with_preempt:
        # Victim usage at CQ d reduces availability at every ancestor;
        # victims only exist in lend-limit-free trees (fair_preempt_ok),
        # where full subtraction is exact; entries of other trees never
        # have victims on their chains.
        on_chain_adm = quota_ops.ancestor_matrix(tree)[:, adm.cq]  # [N, A]
        usage_by_f = jnp.swapaxes(adm.usage, 0, 1)  # [F,A,R]

    # Static DRS ingredients.
    sq = tree.subtree_quota
    pot_all = quota_ops.potential_available_all(tree)  # [N,F,R]
    lendable = jnp.sum(pot_all, axis=1).astype(jnp.float64)  # [N,R]
    weight = arrays.node_weight  # f64[N]
    # Per-plane walk statics (hoisted; availability honors lending limits
    # exactly like admit_scan_grouped).
    lq_all = quota_ops.local_quota(tree)  # [N,F,R]

    # Tournament membership: the LAST active entry of each CQ (host dict
    # semantics); earlier ones are shadowed.
    last_of_cq = (
        jnp.full(n, -1, jnp.int32)
        .at[arrays.w_cq]
        .max(jnp.where(arrays.w_active, w_iota, -1), mode="drop")
    )
    shadowed = arrays.w_active & (last_of_cq[arrays.w_cq] != w_iota)
    part = arrays.w_active & ~shadowed

    # ---- participant compaction ------------------------------------------
    # At most one entry per CQ ever participates in a scan (last-entry
    # shadowing above is static), so every per-step tensor lives on the
    # NODE axis [n] — one slot per CQ, cohort/root slots inert. At the
    # 50k x 2,000-CQ flagship that is ~25x narrower than the padded W
    # axis; the whole scan body (DRS keys, tournament, fit walk, TAS
    # placement) scales with participants, not entries. Results scatter
    # back to [W] once, after the scan. In the opposite regime (drained
    # queue: W bucket 16 << n) this widens keys/fit tensors to [n], but
    # the tournament's [n]-wide scatters dominated that regime before
    # the compaction too, and the s_max bound shrinks with participants
    # — the absolute per-scan cost there stays microseconds.
    p_e = last_of_cq  # [n] participant entry index (-1 none)
    p_has = p_e >= 0
    pe = jnp.clip(p_e, 0, w_n - 1)
    n_iota = jnp.arange(n, dtype=jnp.int32)

    # A participant slot's chain is the ancestor chain of its OWN node —
    # built directly on the node axis (no [W]-wide intermediates).
    chain_cols = [n_iota]
    for _ in range(MAX_DEPTH):
        chain_cols.append(parent[chain_cols[-1]].astype(jnp.int32))
    chains_c = jnp.stack(chain_cols, axis=1)  # [n, D+1]
    # Walk-repeat semantics (position at/past root): matches the grouped
    # admission scan's is_repeat, so the availability walk and bubbling
    # treat the root layer exactly once.
    walk_rep_c = chains_c == jnp.concatenate(
        [chains_c[:, 1:], chains_c[:, -1:]], axis=1
    )  # [n, D+1]
    root_c = root_of  # [n]
    own_cq_c = n_iota
    depth_c = tree.depth
    prio_c = arrays.w_priority[pe]
    ts_c = arrays.w_timestamp[pe]
    pm_c = nom.best_pmode[pe]
    deferred_c = nom.needs_host[pe]
    borrowing_c = nom.best_borrow[pe] > 0
    chosen_c = nom.chosen_flavor[pe]
    fe_c = jnp.clip(chosen_c, 0, f_n - 1)
    fe_col_c = fe_c[:, None]
    req_c = arrays.w_req[pe]
    # Slot layout (multi-podset / multi-resource-group entries present):
    # an entry touches up to S flavor planes, one per assigned slot.
    # Fit/apply/DRS use per-plane totals aggregated across same-flavor
    # slots (``agg``), applied once per distinct plane (``dedup``) — the
    # host sees the summed FlavorResource usage map. Single-slot cycles
    # keep the legacy single-plane tensors so the tuned compiled program
    # is unchanged.
    with_slots = arrays.s_req is not None and nom.s_flavor is not None
    if with_slots:
        s_ax = arrays.s_req.shape[1]
        fs_c = nom.s_flavor[pe]  # [n,S]
        act_c = (
            arrays.s_valid[pe] & (fs_c >= 0)
            & (nom.s_pmode[pe] != P_NOFIT)
        )
        fes_c = jnp.clip(fs_c, 0, f_n - 1)
        sreq_c = arrays.s_req[pe]  # [n,S,R]
        # NOTE: no ``covered`` mask here — covered[] describes the FIRST
        # resource group only (legacy single-plane layout); slots span
        # all RGs and _workload_slots guarantees coverage (None on any
        # uncovered positive request).
        cell_s = (sreq_c > 0) & act_c[..., None]  # [n,S,R]
        req_m = jnp.where(cell_s, sreq_c, 0).astype(jnp.int64)
        samef = (
            (fes_c[:, :, None] == fes_c[:, None, :])
            & act_c[:, :, None] & act_c[:, None, :]
        )  # [n,S,S]
        agg_c = jnp.einsum(
            "nst,ntr->nsr", samef.astype(jnp.int64), req_m
        )  # [n,S,R] per-plane totals
        dedup_c = (
            jnp.argmax(samef, axis=2).astype(jnp.int32)
            == jnp.arange(s_ax, dtype=jnp.int32)[None, :]
        ) & act_c  # [n,S] first slot of each distinct plane
        ch_sl = chains_c[:, None, :]  # [n,1,L] -> broadcast with [n,S,1]
        fe_sl = fes_c[:, :, None]
        lq_s = lq_all[ch_sl, fe_sl]  # [n,S,L,R]
        sub_s = sq[ch_sl, fe_sl]
        bl_s = tree.borrow_limit[ch_sl, fe_sl]
        hbl_s = tree.has_borrow_limit[ch_sl, fe_sl]
        nominal_s = tree.nominal[own_cq_c[:, None], fes_c]  # [n,S,R]
    # All fit/apply math lives on the entry's chosen flavor plane.
    cell_c = (
        (chosen_c >= 0)[:, None]
        & (req_c > 0)
        & arrays.covered[own_cq_c]
    )  # [n,R]
    delta_c = jnp.where(cell_c, req_c, 0).astype(jnp.int64)
    # Plane statics along each participant's chain [n,D+1,R].
    lq_c = lq_all[chains_c, fe_col_c]
    sub_c = sq[chains_c, fe_col_c]
    bl_c = tree.borrow_limit[chains_c, fe_col_c]
    hbl_c = tree.has_borrow_limit[chains_c, fe_col_c]
    nominal_c = tree.nominal[own_cq_c, fe_c]  # [n,R]
    reclaim_c = arrays.can_always_reclaim[own_cq_c]
    # The nominated usage simulated into the DRS (assignment.usage): the
    # request vector on the chosen flavor. Entries with no chosen flavor
    # (NoFit everywhere) simulate nothing, like the host's empty usage.
    sim_req_c = jnp.where(
        (chosen_c >= 0)[:, None] & (req_c > 0), req_c, 0
    )  # [n,R]

    if with_preempt:
        victims_c = targets.victims[pe]  # [n,A]
        chain_sub_c = on_chain_adm[chains_c]  # [n,D+1,A]
        au_c = usage_by_f[fe_c]  # [n,A,R]

    if with_tas:
        from kueue_tpu.ops import tas_place as _tas_place

        t_of_c = jnp.where(
            chosen_c >= 0, arrays.tas_of_flavor[fe_c], -1
        )
        t_idx_c = jnp.clip(t_of_c, 0, arrays.tas_usage0.shape[0] - 1)
        rl_c = arrays.w_tas_req_level[pe, t_idx_c]
        sl_c = arrays.w_tas_slice_level[pe, t_idx_c]
        cap_c = _tas_place.entry_leaf_cap(arrays, t_idx_c, w=pe)
        sizes_c = arrays.w_tas_sizes[pe, t_idx_c]
        w_tas_c = arrays.w_tas[pe]
        tas_req_c = arrays.w_tas_req[pe]
        tas_count_c = arrays.w_tas_count[pe]
        tas_ss_c = arrays.w_tas_slice_size[pe]
        tas_required_c = arrays.w_tas_required[pe]
        tas_uncon_c = arrays.w_tas_unconstrained[pe]
        tas_usage_req_c = arrays.w_tas_usage_req[pe]
        tas_bal_c = (
            arrays.w_tas_balanced[pe]
            if arrays.w_tas_balanced is not None else None
        )

    # Generic multi-podset TAS (slot-layout entries with per-slot
    # topology requests): one batched slot-placement pass per step
    # (models.slot_tas), mirroring the grouped admission scan
    # (batch_scheduler admit_scan_grouped with_stas) and the host's
    # update_for_tas ``assumed`` dict. The shared context is gathered
    # once onto the participant axis; the body only supplies the
    # per-step do-mask and usage base.
    with_stas = with_tas and with_slots and arrays.s_tas is not None
    if with_stas:
        sctx_s = _slot_tas.slot_ctx(arrays, fs_c, sel=pe)
        stas_c = sctx_s.stas  # [n,S]

    lend_par_c = lendable[parent[chains_c]]  # [n,D+1,R]
    wgt_c = weight[chains_c]  # [n,D+1]

    def keys_for(usage_now):
        """Per-participant DRS key at each chain position [n, D+1]:
        (zwb bool, value f64). Root positions are never compared."""
        u_chain = usage_now[chains_c]  # [n,D+1,F,R]
        sq_chain = sq[chains_c]
        over_base = jnp.maximum(0, u_chain - sq_chain)
        borrowed_base = jnp.sum(over_base, axis=2)  # [n,D+1,R]
        if with_slots:
            # Adjust each DISTINCT assigned plane once with its
            # aggregated simulated usage (the host adds the whole
            # assignment's FlavorResource map, fair_sharing.go:149).
            L_ax = MAX_DEPTH + 1
            ni4 = jnp.arange(n)[:, None, None]
            li4 = jnp.arange(L_ax)[None, :, None]
            fe4 = fes_c[:, None, :]
            u_fe_s = u_chain[ni4, li4, fe4]  # [n,L,S,R]
            sq_fe_s = sq_chain[ni4, li4, fe4]
            over_now = jnp.maximum(0, u_fe_s - sq_fe_s)
            over_sim = jnp.maximum(
                0, u_fe_s + agg_c[:, None, :, :] - sq_fe_s
            )
            adj = jnp.sum(
                jnp.where(
                    dedup_c[:, None, :, None], over_sim - over_now, 0
                ),
                axis=2,
            )
            borrowed = borrowed_base + adj  # [n,D+1,R]
        else:
            # Adjust the chosen-flavor plane for the simulated addition.
            idx_fe = fe_c[:, None, None, None]
            u_fe = jnp.take_along_axis(u_chain, idx_fe, axis=2)[:, :, 0, :]
            sq_fe = jnp.take_along_axis(
                sq_chain, idx_fe, axis=2
            )[:, :, 0, :]
            over_fe_now = jnp.maximum(0, u_fe - sq_fe)
            over_fe_sim = jnp.maximum(
                0, u_fe + sim_req_c[:, None, :] - sq_fe
            )
            borrowed = borrowed_base + over_fe_sim - over_fe_now

        ratio_r = jnp.where(
            (lend_par_c > 0) & (borrowed > 0),
            borrowed.astype(jnp.float64) * 1000.0 / lend_par_c,
            0.0,
        )
        ratio = jnp.max(ratio_r, axis=-1)  # [n,D+1]
        zwb = (wgt_c == 0.0) & (ratio > 0.0)
        val = jnp.where(
            zwb,
            ratio,
            jnp.where(
                ratio == 0.0, 0.0,
                ratio / jnp.where(wgt_c == 0.0, 1.0, wgt_c),
            ),
        )
        # weight==0 && ratio>0 handled by zwb; weight==0 && ratio==0 -> 0.
        return zwb, val

    def tournament(zwb_k, val_k, remaining):
        """champ[node] = CQ slot of the node's winning subtree (-1)."""
        live = p_has & remaining
        champ = jnp.where(live, n_iota, jnp.int32(-1))
        for d in range(MAX_DEPTH, 0, -1):
            has = champ >= 0
            lvl = (tree.depth == d) & has & tree.active
            c = jnp.clip(champ, 0, n - 1)
            j = jnp.clip(depth_c[c] - d, 0, MAX_DEPTH)
            kz = zwb_k[c, j]
            kv = val_k[c, j]
            kp = prio_c[c]
            kt = ts_c[c]
            ke = pe[c]  # host tie-break: queue order = entry index
            p = parent  # [N]

            def scat_min(vals, init, mask):
                return (
                    jnp.full(n, init, vals.dtype)
                    .at[p]
                    .min(jnp.where(mask, vals, init), mode="drop")
                )

            def scat_max(vals, init, mask):
                return (
                    jnp.full(n, init, vals.dtype)
                    .at[p]
                    .max(jnp.where(mask, vals, init), mode="drop")
                )

            bz = scat_min(kz.astype(jnp.int32), jnp.int32(2), lvl)
            m = lvl & (kz.astype(jnp.int32) == bz[p])
            bv = scat_min(kv, _F64_INF, m)
            m = m & (kv == bv[p])
            bp = scat_max(kp, -_INF64, m)
            m = m & (kp == bp[p])
            bt = scat_min(kt, _F64_INF, m)
            m = m & (kt == bt[p])
            be = scat_min(
                jnp.where(m, ke, jnp.int32(w_n)), jnp.int32(w_n), m
            )
            # The winning entry's slot IS its CQ node — a gather on the
            # unique surviving entry index, no further scatter needed.
            new_champ = jnp.where(
                be < w_n,
                arrays.w_cq[jnp.clip(be, 0, w_n - 1)].astype(jnp.int32),
                -1,
            )
            # Write winners into parents one level up; nodes at other
            # depths keep their champions.
            parent_at_lvl = (
                jnp.zeros(n, bool).at[p].max(lvl, mode="drop")
            )
            champ = jnp.where(
                parent_at_lvl & (tree.depth == d - 1), new_champ, champ
            )
        return champ

    def body(carry, step):
        (usage_now, tas_usage, remaining, admitted, preempting_acc,
         designated, win_step, w_takes, s_takes, slot_rounds) = carry
        zwb_k, val_k = keys_for(usage_now)
        champ = tournament(zwb_k, val_k, remaining)
        win = p_has & remaining & (champ[root_c] == n_iota)

        pm = pm_c
        # Chain availability on the entry's chosen plane(s), via the same
        # walk as the grouped admission scan — exact under lending
        # limits. The fit check simulates removal of every designated
        # victim plus the entry's own targets (scheduler fits() ->
        # SimulateWorkloadRemoval).
        L = MAX_DEPTH + 1
        if with_preempt:
            is_pre = win & (pm == P_PREEMPT_OK)
            overlap = is_pre & jnp.any(
                victims_c & designated[None, :], axis=1
            )
            use_vict = designated[None, :] | jnp.where(
                (is_pre & ~overlap)[:, None], victims_c, False
            )  # [n,A]
        else:
            is_pre = jnp.zeros(n, bool)
            overlap = jnp.zeros(n, bool)
        if with_slots:
            u_pl_s = usage_now[ch_sl, fe_sl]  # [n,S,L,R]
            if with_preempt:
                au_s = usage_by_f[fes_c]  # [n,S,A,R]
                rem_s = jnp.einsum(
                    "nda,nsar->nsdr",
                    (use_vict[:, None, :]
                     & chain_sub_c).astype(jnp.int64),
                    au_s,
                )
                u_fit_s = u_pl_s - rem_s
            else:
                u_fit_s = u_pl_s
            l_avail_fit_s = jnp.maximum(0, sat_sub(lq_s, u_fit_s))
            stored_s = sat_sub(sub_s, lq_s)
            uip_s = jnp.maximum(0, sat_sub(u_fit_s, lq_s))
            with_max_s = sat_add(sat_sub(stored_s, uip_s), bl_s)
            avail_s = sat_sub(sub_s[:, :, L - 1], u_fit_s[:, :, L - 1])
            for i in range(L - 2, -1, -1):
                clamped = jnp.where(
                    hbl_s[:, :, i],
                    jnp.minimum(with_max_s[:, :, i], avail_s), avail_s,
                )
                stepped = sat_add(l_avail_fit_s[:, :, i], clamped)
                avail_s = jnp.where(
                    walk_rep_c[:, None, i, None], avail_s, stepped
                )
            fits = jnp.all((agg_c <= avail_s) | ~cell_s, axis=(1, 2))
        else:
            u_pl = usage_now[chains_c, fe_col_c]  # [n,D+1,R]
            if with_preempt:
                rem = jnp.einsum(
                    "wda,war->wdr",
                    (use_vict[:, None, :]
                     & chain_sub_c).astype(jnp.int64),
                    au_c,
                )
                u_fit = u_pl - rem
            else:
                u_fit = u_pl
            l_avail_fit = jnp.maximum(0, sat_sub(lq_c, u_fit))
            stored = sat_sub(sub_c, lq_c)
            used_in_parent = jnp.maximum(0, sat_sub(u_fit, lq_c))
            with_max = sat_add(sat_sub(stored, used_in_parent), bl_c)
            avail = sat_sub(sub_c[:, L - 1], u_fit[:, L - 1])
            for i in range(L - 2, -1, -1):
                clamped = jnp.where(
                    hbl_c[:, i], jnp.minimum(with_max[:, i], avail), avail
                )
                stepped = sat_add(l_avail_fit[:, i], clamped)
                avail = jnp.where(walk_rep_c[:, i, None], avail, stepped)
            fits = jnp.all((delta_c <= avail) | ~cell_c, axis=1)

        deferred = deferred_c
        # TAS placement recheck against the running topology state for
        # winners (scheduler.go:409 updateAssignmentIfNeeded): earlier
        # winners may have taken the domains.
        if with_tas:
            tas_do = (
                win & w_tas_c & (t_of_c >= 0) & (pm == P_FIT)
            )

            def place_one(t, req_v, cnt, ssz, sl_, rl_, rq_, un_, cap_,
                          sz_, bal_=None):
                return _tas_place.place(
                    arrays.tas_topo, t, tas_usage[t], req_v, cnt, ssz,
                    jnp.maximum(sl_, 0), jnp.maximum(rl_, 0), rq_, un_,
                    cap_override=cap_, sizes=sz_, balanced=bal_,
                )

            place_args = (
                t_idx_c, tas_req_c, tas_count_c,
                tas_ss_c, sl_c, rl_c,
                tas_required_c, tas_uncon_c,
                cap_c, sizes_c,
            )
            if tas_bal_c is not None:
                place_args = place_args + (tas_bal_c,)
            tas_feas, tas_take = jax.vmap(place_one)(
                *place_args
            )  # [n], [n, D]
            tas_ok = jnp.where(tas_do, tas_feas, True)
            if with_stas:
                # Batched slot-placement pass on the participant axis,
                # evaluated against the live topology state (commit
                # below re-applies winner deltas on admit, like the
                # grouped scan). fair_tas_single guarantees at most one
                # root reaches a flavor, so concurrent per-root winners
                # never race on a topology row — the accumulator is
                # shared (per_lane=False). Twin of admit_scan_grouped's
                # with_stas block (batch_scheduler.py) — change BOTH
                # when the slot-placement semantics change.
                s_do = (
                    win[:, None] & sctx_s.stas & sctx_s.t_valid
                    & (pm == P_FIT)[:, None]
                )
                sp = _slot_tas.place_slots(
                    arrays.tas_topo, tas_usage, sctx_s, s_do
                )
                slot_rounds = jnp.maximum(slot_rounds, sp.rounds)
                has_stas_c = jnp.any(stas_c, axis=1)
                tas_ok = tas_ok & jnp.where(
                    win & has_stas_c & (pm == P_FIT), sp.ok, True
                )
        else:
            tas_ok = True
            tas_do = None
        admit = win & (pm == P_FIT) & fits & ~deferred & tas_ok
        preempt_ok = is_pre & ~overlap & fits & ~deferred

        # NO_CANDIDATES capacity reserve (scheduler.go:513) at the CQ.
        do_reserve = (
            win
            & (pm == P_NO_CANDIDATES)
            & ~reclaim_c
            & ~deferred
        )
        # Both admitted FIT entries and proceeding preemptors consume
        # their usage (scheduler.go:561 cq.AddUsage runs for either mode).
        take_usage = admit | preempt_ok
        if with_slots:
            u_cq_s = u_pl_s[:, :, 0]  # [n,S,R]
            res_borrow_s = jnp.where(
                hbl_s[:, :, 0],
                jnp.minimum(
                    agg_c,
                    sat_sub(sat_add(nominal_s, bl_s[:, :, 0]), u_cq_s),
                ),
                agg_c,
            )
            res_plain_s = jnp.maximum(
                0, jnp.minimum(agg_c, sat_sub(nominal_s, u_cq_s))
            )
            reserve_s = jnp.where(
                borrowing_c[:, None, None], res_borrow_s, res_plain_s
            )
            reserve_s = jnp.where(cell_s, reserve_s, 0)
            applied_s = jnp.where(
                take_usage[:, None, None], agg_c,
                jnp.where(do_reserve[:, None, None], reserve_s, 0),
            )  # [n,S,R]
            # One application per distinct plane.
            applied_s = jnp.where(dedup_c[..., None], applied_s, 0)
            l_avail_pre_s = jnp.maximum(0, sat_sub(lq_s, u_pl_s))
            deltas_s = jnp.zeros((n, s_ax, L, r_n), dtype=jnp.int64)
            cur = applied_s
            for i in range(L):
                deltas_s = deltas_s.at[:, :, i].set(cur)
                cont = (
                    (~walk_rep_c[:, None, i, None]) if i < L - 1 else False
                )
                cur = jnp.where(
                    cont,
                    jnp.maximum(0, sat_sub(cur, l_avail_pre_s[:, :, i])),
                    0,
                )
            deltas_s = jnp.where(win[:, None, None, None], deltas_s, 0)
            new_usage = quota_ops.sat(
                usage_now.at[ch_sl, fe_sl].add(deltas_s, mode="drop")
            )
        else:
            u_cq_pl = u_pl[:, 0]  # [n,R]
            reserve_borrowing = jnp.where(
                hbl_c[:, 0],
                jnp.minimum(
                    delta_c,
                    sat_sub(sat_add(nominal_c, bl_c[:, 0]), u_cq_pl),
                ),
                delta_c,
            )
            reserve_plain = jnp.maximum(
                0, jnp.minimum(delta_c, sat_sub(nominal_c, u_cq_pl))
            )
            reserve = jnp.where(
                borrowing_c[:, None], reserve_borrowing, reserve_plain
            )
            reserve = jnp.where(cell_c, reserve, 0)
            applied = jnp.where(
                take_usage[:, None], delta_c,
                jnp.where(do_reserve[:, None], reserve, 0),
            )  # [n,R]
            # addUsage bubbling with local-availability clamping
            # (resource_node.go:144) — exact under lending limits;
            # l_avail comes from the pre-update usage.
            l_avail_pre = jnp.maximum(0, sat_sub(lq_c, u_pl))
            deltas = jnp.zeros((n, L, r_n), dtype=jnp.int64)
            cur = applied
            for i in range(L):
                deltas = deltas.at[:, i].set(cur)
                cont = (
                    (~walk_rep_c[:, i, None]) if i < L - 1 else False
                )
                cur = jnp.where(
                    cont, jnp.maximum(0, sat_sub(cur, l_avail_pre[:, i])),
                    0,
                )
            deltas = jnp.where(win[:, None, None], deltas, 0)
            new_usage = quota_ops.sat(
                usage_now.at[chains_c, fe_col_c].add(deltas, mode="drop")
            )
        if with_tas:
            do_take = admit & tas_do
            usage_delta = (
                tas_take[:, :, None]
                * tas_usage_req_c[:, None, :]
            )  # [n, D, R1]
            usage_delta = jnp.where(
                do_take[:, None, None], usage_delta, 0
            )
            tas_usage = tas_usage.at[t_idx_c].add(usage_delta)
            w_takes = w_takes + jnp.where(
                do_take[:, None], tas_take, 0
            ).astype(jnp.int32)
            if with_stas:
                # Batched twin of the per-slot commit (shapes align on
                # the participant axis, so s_takes is a plain add).
                do_c = admit[:, None] & s_do
                tas_usage = _slot_tas.commit_usage(
                    tas_usage, sctx_s, sp.takes, do_c
                )
                s_takes = s_takes + jnp.where(
                    do_c[:, :, None], sp.takes, 0
                ).astype(jnp.int32)
        if with_preempt:
            designated = designated | jnp.any(
                jnp.where(preempt_ok[:, None], victims_c, False),
                axis=0,
            )
        win_step = jnp.where(win, step, win_step)
        return (new_usage, tas_usage, remaining & ~win, admitted | admit,
                preempting_acc | preempt_ok, designated, win_step,
                w_takes, s_takes, slot_rounds), None

    def init(usage0, remaining0=None, admitted0=None, win_step0=None):
        """Scan carry for a tournament starting from ``usage0``.
        ``remaining0``/``admitted0``/``win_step0`` let the fixed-point
        rounds pre-settle trees before the residual scan."""
        designated0 = (
            jnp.zeros(adm.cq.shape[0], bool) if with_preempt
            else jnp.zeros(1, bool)
        )
        tas_usage0 = (
            arrays.tas_usage0 if with_tas else jnp.zeros((1,), jnp.int64)
        )
        takes0 = (
            jnp.zeros((n, arrays.tas_topo.leaf_cap.shape[1]), jnp.int32)
            if with_tas else jnp.zeros((1,), jnp.int32)
        )
        stakes0 = (
            jnp.zeros(
                (n, arrays.s_tas.shape[1],
                 arrays.tas_topo.leaf_cap.shape[1]),
                jnp.int32,
            )
            if with_stas else jnp.zeros((1,), jnp.int32)
        )
        # slot_rounds rides at the END of the carry so the fixed-point
        # driver's positional reads (carry[2] = remaining) stay valid.
        return (
            usage0, tas_usage0,
            jnp.ones(n, bool) if remaining0 is None else remaining0,
            jnp.zeros(n, bool) if admitted0 is None else admitted0,
            jnp.zeros(n, bool), designated0,
            jnp.full(n, -1, jnp.int32) if win_step0 is None else win_step0,
            takes0, stakes0, jnp.zeros((), jnp.int32),
        )

    def scatter(carry) -> FairScanResult:
        """Scatter participant results back onto the entry axis."""
        (final_usage, _tas_u, remaining_c, admitted_c, preempting_c,
         _desig, win_step_c, takes_c, stakes_c, slot_rounds_c) = carry
        idx_w = jnp.where(p_has, pe, jnp.int32(w_n))  # OOB rows drop
        admitted = jnp.zeros(w_n, bool).at[idx_w].set(
            admitted_c & p_has, mode="drop"
        )
        preempting = jnp.zeros(w_n, bool).at[idx_w].set(
            preempting_c & p_has, mode="drop"
        )
        participated = jnp.zeros(w_n, bool).at[idx_w].set(
            p_has & ~remaining_c, mode="drop"
        )
        win_step = jnp.full(w_n, -1, jnp.int32).at[idx_w].set(
            jnp.where(p_has, win_step_c, -1), mode="drop"
        )
        w_takes_f = None
        if with_tas:
            w_takes_f = jnp.zeros(
                (w_n, arrays.tas_topo.leaf_cap.shape[1]), jnp.int32
            ).at[idx_w].set(
                jnp.where(p_has[:, None], takes_c, 0), mode="drop"
            )
        s_takes_f = None
        if with_stas:
            s_takes_f = jnp.zeros(
                (w_n, arrays.s_tas.shape[1],
                 arrays.tas_topo.leaf_cap.shape[1]),
                jnp.int32,
            ).at[idx_w].set(
                jnp.where(p_has[:, None, None], stakes_c, 0), mode="drop"
            )
        return FairScanResult(
            usage=final_usage,
            admitted=admitted,
            preempting=preempting,
            shadowed=shadowed,
            participated=participated,
            win_step=win_step,
            tas_takes=w_takes_f,
            s_tas_takes=s_takes_f,
            slot_rounds=slot_rounds_c if with_stas else None,
        )

    # ---- slot-normalized views (explicit S axis; S=1 legacy) -------------
    # The fixed-point rounds analysis needs the fit walk, the reserve
    # formula and the addUsage bubble on arbitrary per-participant chain
    # usage. These mirror the scan body's two branches exactly — the
    # randomized kernel differentials (tests/test_fair_fixedpoint.py) pin
    # them plane-for-plane against the scan.
    L = MAX_DEPTH + 1
    if with_slots:
        chS = ch_sl  # [n,1,L]
        feS = fe_sl  # [n,S,1]
        cellS, aggS, dedupS, samefS = cell_s, agg_c, dedup_c, samef
        lqS, subS, blS, hblS = lq_s, sub_s, bl_s, hbl_s
        nominalS = nominal_s
    else:
        chS = chains_c[:, None, :]
        feS = fe_c[:, None, None]
        cellS = cell_c[:, None]
        aggS = delta_c[:, None]
        dedupS = jnp.ones((n, 1), bool)
        samefS = jnp.ones((n, 1, 1), bool)
        lqS, subS = lq_c[:, None], sub_c[:, None]
        blS, hblS = bl_c[:, None], hbl_c[:, None]
        nominalS = nominal_c[:, None]
    first_c = jnp.concatenate(
        [jnp.ones((n, 1), bool), ~walk_rep_c[:, :-1]], axis=1
    )  # [n,L] first occurrence of each distinct chain node

    def uS_of(usage0):
        """Per-participant chain usage on the assigned plane(s)."""
        return usage0[chS, feS]  # [n,S,L,R]

    def fits_chain(uS_fit):
        """The scan body's availability walk on explicit [n,S,L,R] chain
        usage (victim-free form — rounds never settle preempt trees)."""
        l_avail_fit = jnp.maximum(0, sat_sub(lqS, uS_fit))
        stored = sat_sub(subS, lqS)
        uip = jnp.maximum(0, sat_sub(uS_fit, lqS))
        with_max = sat_add(sat_sub(stored, uip), blS)
        avail = sat_sub(subS[:, :, L - 1], uS_fit[:, :, L - 1])
        for i in range(L - 2, -1, -1):
            clamped = jnp.where(
                hblS[:, :, i], jnp.minimum(with_max[:, :, i], avail), avail
            )
            stepped = sat_add(l_avail_fit[:, :, i], clamped)
            avail = jnp.where(
                walk_rep_c[:, None, i, None], avail, stepped
            )
        return jnp.all((aggS <= avail) | ~cellS, axis=(1, 2))

    def bubble_chain(appliedS, l_availS):
        """addUsage bubbling of [n,S,R] applications along each chain
        with per-level pre-availability clamping ``l_availS`` [n,S,L,R]
        (zeros = raw, no absorption). Repeat (at/past-root) positions
        get zero, like the scan's delta loop."""
        deltas = jnp.zeros(
            (n, appliedS.shape[1], L, r_n), dtype=jnp.int64
        )
        cur = appliedS
        for i in range(L):
            deltas = deltas.at[:, :, i].set(cur)
            cont = (
                (~walk_rep_c[:, None, i, None]) if i < L - 1 else False
            )
            cur = jnp.where(
                cont, jnp.maximum(0, sat_sub(cur, l_availS[:, :, i])), 0
            )
        return deltas

    # Participants whose step semantics the rounds analysis cannot model
    # order-independently: device-resolved preemptors (sequential
    # designated-victim bookkeeping) and TAS placements (the topology
    # state threads across tournament steps — the batched slot pass
    # removes the per-slot loop WITHIN a step, not the step-to-step
    # dependency). Their whole trees go residual.
    resid_force = jnp.zeros(n, bool)
    if with_preempt:
        resid_force = resid_force | (p_has & (pm_c == P_PREEMPT_OK))
    if with_tas:
        resid_force = resid_force | (p_has & w_tas_c & (t_of_c >= 0))
    if with_stas:
        resid_force = resid_force | (p_has & jnp.any(stas_c, axis=1))

    import types

    return types.SimpleNamespace(
        n=n, w_n=w_n, L=L, r_n=r_n,
        body=body, init=init, scatter=scatter,
        p_has=p_has, pe=pe, root_c=root_c, chains_c=chains_c,
        walk_rep_c=walk_rep_c, first_c=first_c, shadowed=shadowed,
        pm_c=pm_c, deferred_c=deferred_c, reclaim_c=reclaim_c,
        borrowing_c=borrowing_c, resid_force=resid_force,
        with_slots=with_slots, with_tas=with_tas,
        with_preempt=with_preempt, with_stas=with_stas,
        chS=chS, feS=feS, cellS=cellS, aggS=aggS, dedupS=dedupS,
        samefS=samefS, lqS=lqS, subS=subS, blS=blS, hblS=hblS,
        nominalS=nominalS,
        uS_of=uS_of, fits_chain=fits_chain, bubble_chain=bubble_chain,
    )


def fair_admit_scan(
    arrays: CycleArrays,
    nom: NominateResult,
    usage: jnp.ndarray,
    s_max: int,
    adm=None,
    targets=None,
) -> "FairScanResult":
    """Tournament-ordered admission. With ``adm``/``targets`` (device fair
    preemption) winners resolved to P_PREEMPT_OK designate their victims
    with the host's overlap/fit semantics and consume usage like admitted
    entries. Returns a :class:`FairScanResult`."""
    ctx = _fair_ctx(arrays, nom, adm=adm, targets=targets)
    carry, _ = jax.lax.scan(
        ctx.body, ctx.init(usage), jnp.arange(s_max, dtype=jnp.int32)
    )
    return ctx.scatter(carry)


def _fair_finish(arrays, nom, final_usage, admitted, preempting, shadowed,
                 win_step, victims=None, variant=None, tas_takes=None,
                 s_tas_takes=None, converged=None, fp_rounds=None,
                 slot_rounds=None):
    """Assemble CycleOutputs from fair-tournament planes — shared by the
    scan and fixed-point fair cycle factories so both kernels report
    decisions identically."""
    outcome = jnp.where(
            ~arrays.w_active,
            OUT_NOFIT,
            jnp.where(
                nom.needs_host,
                OUT_NEEDS_HOST,
                jnp.where(
                    shadowed,
                    OUT_SHADOWED,
                    jnp.where(
                        admitted,
                        OUT_ADMITTED,
                        jnp.where(
                            preempting,
                            OUT_PREEMPTING,
                            jnp.where(
                                (nom.best_pmode == P_FIT)
                                | (nom.best_pmode == P_PREEMPT_OK),
                                OUT_FIT_SKIPPED,
                                jnp.where(
                                    nom.best_pmode == P_NO_CANDIDATES,
                                    OUT_NO_CANDIDATES,
                                    OUT_NOFIT,
                                ),
                            ),
                        ),
                    ),
                ),
            ),
    ).astype(jnp.int32)
    return CycleOutputs(
        outcome=outcome,
        chosen_flavor=nom.chosen_flavor,
        borrow=nom.best_borrow,
        tried_flavor_idx=nom.tried_flavor_idx,
        usage=final_usage,
        # Diagnostics only: the dynamic tournament order (step each
        # entry won at; losers sink to the end). Domain decode reads
        # tas_takes directly and does not depend on this.
        order=jnp.argsort(
            jnp.where(
                win_step >= 0, win_step.astype(jnp.int64),
                jnp.int64(1) << 40,
            )
            * arrays.w_cq.shape[0]
            + jnp.arange(arrays.w_cq.shape[0], dtype=jnp.int64)
        ).astype(jnp.int32),
        victims=victims,
        victim_variant=variant,
        s_flavor=nom.s_flavor,
        s_pmode=nom.s_pmode,
        s_tried=nom.s_tried,
        tas_takes=tas_takes,
        s_tas_takes=s_tas_takes,
        converged=converged,
        fp_rounds=fp_rounds,
        slot_rounds=slot_rounds,
    )


def _fair_preempt_nominate(arrays: CycleArrays, adm):
    """The fair cycle's nomination front half: nominate, the TAS hook,
    device fair-preemption eligibility and target resolution. Shared by
    the scan and fixed-point fair cycle factories."""
    usage = arrays.usage
    nom = nominate(arrays, usage)
    if arrays.tas_topo is not None:
        nom, _downgrade = apply_tas_nominate_hook(arrays, nom)
    elig = (
        arrays.w_active
        & (nom.best_pmode == P_PREEMPT_RAW)
        & (nom.praw_count == 1)
        & arrays.fair_preempt_ok[arrays.w_cq]
        & ~arrays.w_has_gates
    )
    if arrays.w_tas is not None:
        elig = elig & ~arrays.w_tas
    if arrays.s_tas is not None:
        # Multi-podset TAS entries needing preemption keep the host
        # victim search (same rule as the grouped cycle).
        elig = elig & ~jnp.any(arrays.s_tas, axis=1)
    if arrays.w_simple_slot is not None:
        # The fair victim tournament reads the legacy single-slot
        # fields; a multi-slot entry needing preemption stays
        # needs_host and the driver routes its whole tree through
        # the host (tournament interleaving stays exact per tree).
        elig = elig & arrays.w_simple_slot
    tgt = fair_preempt_targets(
        arrays, adm, nom.chosen_flavor, elig, nom.praw_stop,
        nom.considered,
    )
    nom = nom._replace(
        best_pmode=jnp.where(
            tgt.success, P_PREEMPT_OK,
            jnp.where(tgt.resolved_nc, P_NO_CANDIDATES,
                      nom.best_pmode),
        ),
        best_borrow=jnp.where(
            tgt.resolved, tgt.borrow_after, nom.best_borrow
        ),
        needs_host=nom.needs_host & ~tgt.resolved,
    )
    return nom, tgt


def make_fair_cycle(s_max: int = 0, preempt: bool = False):
    """Jittable fair-sharing cycle: nominate -> DRS tournament scan.

    kernel-entry: cycle_fair_preempt
    gate-requires: self.fair_sharing

    With ``preempt=True`` the cycle takes the AdmittedArrays and resolves
    the fair preemption tournament on device for eligible entries
    (models/fair_preempt_kernel.py) before the admission scan."""

    if not preempt:
        def impl(arrays: CycleArrays) -> CycleOutputs:
            usage = arrays.usage
            nom = nominate(arrays, usage)
            if arrays.tas_topo is not None:
                nom, _downgrade = apply_tas_nominate_hook(arrays, nom)
            s = s_max if s_max > 0 else arrays.w_cq.shape[0]
            res = fair_admit_scan(arrays, nom, usage, s)
            return _fair_finish(arrays, nom, res.usage, res.admitted,
                                res.preempting, res.shadowed, res.win_step,
                                tas_takes=res.tas_takes,
                                s_tas_takes=res.s_tas_takes,
                                slot_rounds=res.slot_rounds)

        return impl

    def impl_preempt(arrays: CycleArrays, adm) -> CycleOutputs:
        usage = arrays.usage
        nom, tgt = _fair_preempt_nominate(arrays, adm)
        s = s_max if s_max > 0 else arrays.w_cq.shape[0]
        res = fair_admit_scan(arrays, nom, usage, s, adm=adm, targets=tgt)
        return _fair_finish(arrays, nom, res.usage, res.admitted,
                            res.preempting, res.shadowed, res.win_step,
                            victims=tgt.victims, variant=tgt.variant,
                            tas_takes=res.tas_takes,
                            s_tas_takes=res.s_tas_takes,
                            slot_rounds=res.slot_rounds)

    return impl_preempt


cycle_fair = jax.jit(make_fair_cycle())
@functools.lru_cache(maxsize=None)
def fair_cycle_preempt_for(s_max: int):
    """Compiled fair cycle for a given (bucketed) tournament step count.

    ``s_max=0`` falls back to the full padded width — always correct but
    wasteful; callers should pass CycleIndex.fair_s_bound (at most one
    entry per CQ participates per scan, so #participating-CQs steps per
    root suffice)."""
    return jax.jit(make_fair_cycle(s_max=s_max, preempt=True))


def cycle_fair_preempt(arrays, adm, s_max: int = 0):
    return fair_cycle_preempt_for(s_max)(arrays, adm)
