"""The single shape-bucket ladder for every compiled solver entry point.

Compiled XLA programs are keyed by their padded argument shapes, so two
call sites that bucket the same logical size differently compile (and
cache, and prewarm) two executables for identical work. Before this
module the driver's ``_pick_bucket`` used an unbounded power-of-two
ladder while the what-if engine capped power-of-two growth at 1024 and
switched to 1024-multiples above it — e.g. 2500 heads padded to 4096 on
the admission path but 3072 on the forecast path, guaranteeing a
duplicate compile of the same cycle program. Every W-axis caller
(driver, encode defaults, whatif/engine, and through them the sim-loop
rollouts) now resolves through :func:`bucket_for`, and the scan-depth /
slot-axis power-of-two buckets resolve through :func:`pow2_bucket`, so
identical logical shapes always share one executable — and
``perf/compile_cache.py`` can prewarm the ladder knowing it covers every
runtime shape.

The ladder itself keeps the what-if engine's memory-conscious shape:
power-of-two rungs up to :data:`LINEAR_CAP`, then multiples of
:data:`LINEAR_STEP`. Above ~1k rows a pow2 pad can waste ~60% of the
batch's memory (vmapped [K, W] forecast planes blow the cache) for no
compile-count win, while below it pow2 keeps the rung count logarithmic.
"""

from __future__ import annotations

from typing import List

# Minimum W-axis bucket: the admission cycle's smallest compiled shape.
FLOOR = 16
# Pow2 rungs up to here; linear LINEAR_STEP-multiples above.
LINEAR_CAP = 1024
LINEAR_STEP = 1024


def pow2_bucket(n: int, floor: int = 1) -> int:
    """Smallest power of two >= max(n, floor). The generic compile
    bucket for scan depths and slot axes (encode's ``fair_s_bound`` uses
    floor=4, the slot S axis floor=1, rollout ``s_max`` floor=8)."""
    return 1 << (max(int(n), floor, 1) - 1).bit_length()


def bucket_for(n: int, floor: int = FLOOR) -> int:
    """The unified W-axis bucket for ``n`` workload rows."""
    n = max(int(n), floor)
    if n <= LINEAR_CAP:
        return pow2_bucket(n)
    return LINEAR_STEP * ((n + LINEAR_STEP - 1) // LINEAR_STEP)


def prev_bucket(bucket: int, floor: int = FLOOR) -> int:
    """The next-smaller rung (shrink step), clamped at ``floor``."""
    if bucket > LINEAR_CAP:
        return bucket - LINEAR_STEP
    return max(floor, bucket // 2)


def ladder(up_to: int, floor: int = FLOOR) -> List[int]:
    """Every rung from ``floor`` up to the one covering ``up_to`` — the
    shape set a prewarm must compile to cover workloads of that size."""
    rungs = [bucket_for(floor, floor)]
    top = bucket_for(up_to, floor)
    while rungs[-1] < top:
        rung = rungs[-1]
        rungs.append(rung * 2 if rung < LINEAR_CAP else rung + LINEAR_STEP)
    return rungs


class BucketLadder:
    """Stateful rung selection with shrink hysteresis.

    Growth is immediate (the cycle must fit); shrinking one rung
    requires the observed size to fit a smaller rung for ``patience``
    consecutive observations — a size oscillating across a rung boundary
    would otherwise recompile the cycle program every cycle. Any
    observation that needs the current rung (or larger) resets the
    streak.
    """

    def __init__(self, floor: int = FLOOR, patience: int = 4) -> None:
        self.floor = floor
        self.patience = patience
        self.value = bucket_for(floor, floor)
        self.streak = 0

    def observe(self, n: int) -> int:
        need = bucket_for(n, self.floor)
        if need >= self.value:
            self.value = need
            self.streak = 0
        else:
            self.streak += 1
            if self.streak >= self.patience:
                self.value = prev_bucket(self.value, self.floor)
                self.streak = 0
        return self.value
