"""Fair-sharing admission as fixed-point rounds + a residual tournament.

The DRS tournament (models/fair_kernel.py) processes one winner per
cohort tree per sequential scan step. BENCH_r05 showed each step costs
~0.2 ms of dispatch latency regardless of width, so the win is
eliminating steps — the same treatment ``admit_fixedpoint`` /
``cycle_fixedpoint_hybrid`` gave the grouped scan (PR 8).

The key observation making rounds possible WITHOUT simulating DRS order:
for trees free of device preemptors and TAS placements, every
participant's *contribution* to usage is order-independent or boundable:

* a FIT participant that does not fit at the cycle's base usage can
  never fit later (usage only grows, the availability walk is monotone
  decreasing in usage) — statically rejected, contributes nothing;
* a NO_CANDIDATES reserve reads only the participant's own-CQ usage
  (scheduler.go:513), and CQ nodes are tournament-exclusive leaves (one
  participant per CQ, no other chain passes through) — the reserve
  amount is static;
* every other participant either applies its aggregate (if it admits)
  or nothing — bounded between zero and a raw no-absorption bubble.

Two passes therefore settle a tree: pass 1 scatters the raw
(absorption-free) bubbles of every potential contributor to get a
per-node usage upper bound; pass 2 re-runs the availability walk and the
addUsage bubble under both the base (lower) and worst-case (upper)
usage. A participant that fits even at the upper bound admits in every
tournament order; one that fails at base never admits. Trees where the
two bounds pin every contributor's bubbled arrival exactly
(``arr_hi == arr_lo``) and leave no participant undecided have an
order-independent final usage — applied in one scatter. Everything else
(genuinely order-dependent contention, device preemptors, TAS) runs the
unmodified sequential tournament, restricted to the unsettled trees and
early-exited once they drain — bit-identical planes to
:func:`fair_admit_scan` by construction, pinned by the randomized
differentials in tests/test_fair_fixedpoint.py.

``converged`` is False when the residual tournament ran out of steps
before draining; the driver contains that as a
``solver_fallback_cycles_total{reason="fixedpoint_rounds"}`` host
fallback before reading any plane.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kueue_tpu.models.batch_scheduler import (
    CycleOutputs,
    NominateResult,
    P_FIT,
    P_NO_CANDIDATES,
    apply_tas_nominate_hook,
    nominate,
)
from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.models.fair_kernel import (
    FairScanResult,
    _fair_ctx,
    _fair_finish,
    _fair_preempt_nominate,
)
from kueue_tpu.ops import quota_ops
from kueue_tpu.ops.quota_ops import sat_add, sat_sub


class FairRoundsResult(NamedTuple):
    """Result of :func:`fair_admit_fixedpoint`."""

    res: FairScanResult
    fp_rounds: jnp.ndarray  # i32 — 2 bound passes + residual steps run
    converged: jnp.ndarray  # bool — residual tournament fully drained


def fair_admit_fixedpoint(
    arrays: CycleArrays,
    nom: NominateResult,
    usage: jnp.ndarray,
    s_max: int,
    adm=None,
    targets=None,
) -> FairRoundsResult:
    """Fair admission via bound rounds + residual tournament.

    Same signature/semantics as :func:`fair_admit_scan` — the planes of
    ``res`` are bit-identical to the scan's at the same ``s_max`` except
    ``win_step``/``participated`` ordering diagnostics (settled
    participants report step 0). ``s_max`` bounds the residual steps; an
    undrained residual reports ``converged=False``.
    """
    ctx = _fair_ctx(arrays, nom, adm=adm, targets=targets)

    # ---- pass 1: classify + raw (no-absorption) usage upper bound --------
    uS_base = ctx.uS_of(usage)  # [n,S,L,R]
    is_fit = ctx.p_has & (ctx.pm_c == P_FIT) & ~ctx.deferred_c
    is_nc = (
        ctx.p_has & (ctx.pm_c == P_NO_CANDIDATES)
        & ~ctx.reclaim_c & ~ctx.deferred_c
    )
    fits_base = ctx.fits_chain(uS_base)
    maybe = is_fit & fits_base  # statically-rejected FIT entries drop out
    hi_set = maybe | is_nc  # every participant that can consume usage

    # NO_CANDIDATES reserve at the CQ — static: no other participant's
    # chain passes the (leaf) CQ node, so own-CQ usage stays at base
    # until the participant itself wins. Mirrors the scan body.
    u_cqS = uS_base[:, :, 0]  # [n,S,R]
    res_borrow = jnp.where(
        ctx.hblS[:, :, 0],
        jnp.minimum(
            ctx.aggS,
            sat_sub(sat_add(ctx.nominalS, ctx.blS[:, :, 0]), u_cqS),
        ),
        ctx.aggS,
    )
    res_plain = jnp.maximum(
        0, jnp.minimum(ctx.aggS, sat_sub(ctx.nominalS, u_cqS))
    )
    reserveS = jnp.where(
        ctx.borrowing_c[:, None, None], res_borrow, res_plain
    )
    reserveS = jnp.where(ctx.cellS, reserveS, 0)

    appliedS = jnp.where(
        maybe[:, None, None], ctx.aggS,
        jnp.where(is_nc[:, None, None], reserveS, 0),
    )  # [n,S,R] worst-case per-participant application
    appliedS = jnp.where(ctx.dedupS[..., None], appliedS, 0)

    zero_l = jnp.zeros(
        (ctx.n, appliedS.shape[1], ctx.L, ctx.r_n), jnp.int64
    )
    raw_deltas = ctx.bubble_chain(appliedS, zero_l)  # applied at each node
    grid_raw = jnp.zeros_like(usage).at[ctx.chS, ctx.feS].add(
        raw_deltas, mode="drop"
    )
    # Own raw contribution at every own-chain node is the plane total
    # (each distinct node receives exactly one scatter of it; same-plane
    # slots share the dedup'd application).
    own_fill = jnp.einsum(
        "nst,ntr->nsr", ctx.samefS.astype(jnp.int64), appliedS
    )
    others_raw = jnp.maximum(
        0, grid_raw[ctx.chS, ctx.feS] - own_fill[:, :, None, :]
    )  # [n,S,L,R] upper bound on other contributors' arrivals

    # ---- pass 2: bounded bubbles + worst-case fit ------------------------
    # Higher usage -> smaller local availability -> less absorbed ->
    # larger arrival upward: l under the hi usage bounds arrivals above,
    # l at base bounds them below.
    u_hiS = sat_add(uS_base, others_raw)
    l_hi = jnp.maximum(0, sat_sub(ctx.lqS, u_hiS))
    l_lo = jnp.maximum(0, sat_sub(ctx.lqS, uS_base))
    arr_hi = ctx.bubble_chain(appliedS, l_hi)
    arr_lo = ctx.bubble_chain(appliedS, l_lo)

    hi_deltas = jnp.where(hi_set[:, None, None, None], arr_hi, 0)
    grid_hi = jnp.zeros_like(usage).at[ctx.chS, ctx.feS].add(
        hi_deltas, mode="drop"
    )
    # Own arrival at each own-chain node: per-plane arrivals summed over
    # same-plane slots, forward-filled so repeat (past-root) positions
    # read the root's own arrival (they alias the root node).
    plane_arr = jnp.einsum(
        "nst,ntlr->nslr", ctx.samefS.astype(jnp.int64), hi_deltas
    )
    own = plane_arr[:, :, 0]
    own_rows = []
    for k in range(ctx.L):
        own = jnp.where(ctx.first_c[:, None, k, None], plane_arr[:, :, k],
                        own)
        own_rows.append(own)
    own_hi_at = jnp.stack(own_rows, axis=2)  # [n,S,L,R]
    others_hi = jnp.maximum(0, grid_hi[ctx.chS, ctx.feS] - own_hi_at)
    fits_worst = ctx.fits_chain(sat_add(uS_base, others_hi))

    admit_b = maybe & fits_worst  # admits in every tournament order
    undec = maybe & ~fits_worst  # genuinely order-dependent -> residual

    # ---- settle trees ----------------------------------------------------
    exact_c = jnp.all(
        (arr_hi == arr_lo) | ~hi_set[:, None, None, None], axis=(1, 2, 3)
    )
    bad = (
        undec
        | ((admit_b | is_nc) & ~exact_c)
        | ctx.resid_force
    )
    tree_bad = jnp.zeros(ctx.n, bool).at[ctx.root_c].max(bad)
    settled_c = ctx.p_has & ~tree_bad[ctx.root_c]

    contrib = (admit_b | is_nc) & settled_c
    settle_deltas = jnp.where(contrib[:, None, None, None], arr_lo, 0)
    # One sat at the end equals the scan's per-step sat: deltas are
    # nonnegative, so the running sums are monotone under the clamp.
    usage1 = quota_ops.sat(
        usage.at[ctx.chS, ctx.feS].add(settle_deltas, mode="drop")
    )

    # ---- residual tournament over the unsettled trees --------------------
    remaining0 = ctx.p_has & ~settled_c
    admitted0 = admit_b & settled_c
    win_step0 = jnp.where(settled_c, jnp.int32(0), jnp.int32(-1))
    carry0 = ctx.init(
        usage1, remaining0=remaining0, admitted0=admitted0,
        win_step0=win_step0,
    )

    def cond_fn(state):
        step, carry = state
        return (step < jnp.int32(s_max)) & jnp.any(carry[2])

    def body_fn(state):
        step, carry = state
        new_carry, _ = ctx.body(carry, step)
        return step + jnp.int32(1), new_carry

    step_f, carry_f = jax.lax.while_loop(
        cond_fn, body_fn, (jnp.int32(0), carry0)
    )
    res = ctx.scatter(carry_f)
    converged = ~jnp.any(carry_f[2])
    fp_rounds = jnp.int32(2) + step_f
    return FairRoundsResult(res=res, fp_rounds=fp_rounds,
                            converged=converged)


def make_fair_fixedpoint_cycle(s_max: int = 0, preempt: bool = True):
    """Jittable fair cycle: nominate -> fixed-point rounds + residual.

    kernel-entry: cycle_fair_fixedpoint
    gate-requires: self.fair_sharing

    Drop-in for :func:`make_fair_cycle` — same nomination front half
    (device fair-preemption resolution included with ``preempt=True``),
    admission via :func:`fair_admit_fixedpoint`, and the shared
    ``_fair_finish`` assembly so both kernels report identically, plus
    the ``converged``/``fp_rounds`` planes the driver's convergence gate
    reads before any other plane."""

    if not preempt:
        def impl(arrays: CycleArrays) -> CycleOutputs:
            usage = arrays.usage
            nom = nominate(arrays, usage)
            if arrays.tas_topo is not None:
                nom, _downgrade = apply_tas_nominate_hook(arrays, nom)
            s = s_max if s_max > 0 else arrays.w_cq.shape[0]
            rr = fair_admit_fixedpoint(arrays, nom, usage, s)
            res = rr.res
            return _fair_finish(arrays, nom, res.usage, res.admitted,
                                res.preempting, res.shadowed, res.win_step,
                                tas_takes=res.tas_takes,
                                s_tas_takes=res.s_tas_takes,
                                converged=rr.converged,
                                fp_rounds=rr.fp_rounds,
                                slot_rounds=res.slot_rounds)

        return impl

    def impl_preempt(arrays: CycleArrays, adm) -> CycleOutputs:
        usage = arrays.usage
        nom, tgt = _fair_preempt_nominate(arrays, adm)
        s = s_max if s_max > 0 else arrays.w_cq.shape[0]
        rr = fair_admit_fixedpoint(arrays, nom, usage, s, adm=adm,
                                   targets=tgt)
        res = rr.res
        return _fair_finish(arrays, nom, res.usage, res.admitted,
                            res.preempting, res.shadowed, res.win_step,
                            victims=tgt.victims, variant=tgt.variant,
                            tas_takes=res.tas_takes,
                            s_tas_takes=res.s_tas_takes,
                            converged=rr.converged,
                            fp_rounds=rr.fp_rounds,
                            slot_rounds=res.slot_rounds)

    return impl_preempt


@functools.lru_cache(maxsize=None)
def fair_fixedpoint_cycle_for(s_max: int):
    """Compiled fixed-point fair cycle for a (bucketed) residual step
    bound — callers pass CycleIndex.fair_s_bound like the scan's
    ``fair_cycle_preempt_for``."""
    return jax.jit(make_fair_fixedpoint_cycle(s_max=s_max, preempt=True))


def cycle_fair_fixedpoint(arrays, adm, s_max: int = 0):
    return fair_fixedpoint_cycle_for(s_max)(arrays, adm)
