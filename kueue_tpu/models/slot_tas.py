"""Batched TAS slot placement: one pass over every (entry, slot) pair.

Generic multi-podset / multi-RG TAS entries carry up to S per-slot
topology requests (encode's ``s_tas*`` planes). The reference threads
them sequentially through ``flavorassigner.update_for_tas``'s
``assumed`` usage dict — and until this module, every kernel mirrored
that with a Python-unrolled ``for si in range(S)`` loop, paying S
placement dispatches (and S traced program copies) per call site.

This module replaces all of those loops with a single batched pass:

* every slot of every lane places at once against the base topology
  usage (``jax.vmap`` over the ``[L, S]`` block — one placement kernel
  launch instead of S);
* the assumed-usage dependency between slots only exists when two
  ``do``-active slots land on the SAME topology row (same flavor →
  same topology). A segment count over ``t_idx`` assigns each slot its
  *conflict rank* — how many earlier active slots share its row;
* rank-0 slots (the common case: distinct flavors → distinct
  topologies) are final after the first pass. Only genuinely
  conflicting slot groups re-place under a bounded
  ``lax.while_loop`` over conflict rank, committing the previous
  rank's feasible deltas before each re-place — the fixed-point
  blueprint of the admission rounds kernels (PR 8/11) applied to the
  slot axis. The loop runs ``max_rank`` times — the largest same-key
  active group minus one, which at every kernel call site is < S:
  per-lane keys cap the group at the lane's S slots, and the shared
  call sites process one lane per row per step (grouping /
  fair_tas_single), so a row never collects slots from two lanes.

Bit-identity with the sequential threading is structural: within a
row group, rank strictly increases with slot order among active slots,
so a slot of rank r places against exactly the feasible deltas of the
r earlier same-row slots — the sequential prefix — and equal-rank
slots of different lanes place concurrently then commit together,
matching the old same-``si`` place-then-scatter semantics. All the
math is integer, so "same inputs" means "same bits"; the randomized
differentials in tests/test_slot_tas.py pin every plane against
:func:`place_slots_reference` (the retired sequential loop, kept here
as the oracle).

Threading scopes (mirrors the two historical loop families):

* ``per_lane=False`` — one assumed-usage accumulator shared across
  lanes, keyed by topology row. Used by the admission-scan bodies
  (batch_scheduler ``admit_scan_grouped``, fair_kernel ``_fair_ctx``),
  where grouping / fair_tas_single guarantees at most one lane per
  step touches a flavor row anyway.
* ``per_lane=True`` — per-(lane, row) accumulator: lanes are isolated
  from each other's simulated takes. Used by the nominate-phase
  feasibility hook (``apply_tas_nominate_hook``), where the host's
  ``assumed`` dict is scoped to one workload.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from kueue_tpu.ops import tas_place as _tas_place


class SlotCtx(NamedTuple):
    """Per-(lane, slot) placement inputs, gathered once per call site.

    ``L`` is the caller's lane axis (scan groups G, fair participants
    n, or raw entries W), ``S`` the encoded slot axis. All call sites
    build it through :func:`slot_ctx` so the gather/clip semantics are
    defined in exactly one place.
    """

    stas: jnp.ndarray  # bool[L,S] slot carries a topology request
    t_of: jnp.ndarray  # i32[L,S] topology of the slot's flavor (-1 none)
    t_valid: jnp.ndarray  # bool[L,S] t_of >= 0
    t_idx: jnp.ndarray  # i32[L,S] t_of clipped to a valid row
    levels_ok: jnp.ndarray  # bool[L,S] req/slice levels exist on t
    req: jnp.ndarray  # i64[L,S,R1] per-pod topology request
    count: jnp.ndarray  # i64[L,S] pods to place
    slice_size: jnp.ndarray  # i64[L,S]
    req_level: jnp.ndarray  # i32[L,S] level on the slot's topology
    slice_level: jnp.ndarray  # i32[L,S]
    required: jnp.ndarray  # bool[L,S]
    unconstrained: jnp.ndarray  # bool[L,S]
    sizes: jnp.ndarray  # i64[L,S,LMAX] per-level domain sizes
    usage_req: jnp.ndarray  # i64[L,S,R1] usage added per placed pod


class SlotPlacement(NamedTuple):
    """Result of :func:`place_slots` / :func:`place_slots_reference`."""

    ok: jnp.ndarray  # bool[L] every active slot feasible
    feas: jnp.ndarray  # bool[L,S] per-slot feasibility (levels included)
    takes: jnp.ndarray  # i64[L,S,D] leaf takes, zeroed outside ``do``
    rounds: jnp.ndarray  # i32[] conflict rounds run (reference: -1)


def slot_ctx(arrays, s_flavor, sel=None) -> SlotCtx:
    """Build the shared slot-placement context.

    ``s_flavor`` is the nominated per-slot flavor on the caller's lane
    axis (``nom.s_flavor`` itself, or a per-step/per-participant gather
    of it). ``sel`` optionally gathers the encoded ``[W, S, ...]`` slot
    planes onto that lane axis (the grouped scan's per-step ``w``, the
    fair kernel's participant ``pe``); ``None`` keeps the raw entry
    axis (the nominate hook).
    """
    g = (lambda x: x[sel]) if sel is not None else (lambda x: x)
    f_n = arrays.tas_of_flavor.shape[0]
    t_rows = arrays.tas_usage0.shape[0]
    t_of = jnp.where(
        s_flavor >= 0,
        arrays.tas_of_flavor[jnp.clip(s_flavor, 0, f_n - 1)],
        -1,
    )
    t_idx = jnp.clip(t_of, 0, t_rows - 1)
    # Per-slot level planes are encoded per topology [.., S, T]; gather
    # each slot's row at its own topology.
    t3 = t_idx[:, :, None]
    req_level = jnp.take_along_axis(
        g(arrays.s_tas_req_level), t3, axis=2
    )[:, :, 0]
    slice_level = jnp.take_along_axis(
        g(arrays.s_tas_slice_level), t3, axis=2
    )[:, :, 0]
    sizes = jnp.take_along_axis(
        g(arrays.s_tas_sizes), t3[:, :, :, None], axis=2
    )[:, :, 0]
    return SlotCtx(
        stas=g(arrays.s_tas),
        t_of=t_of,
        t_valid=t_of >= 0,
        t_idx=t_idx,
        levels_ok=(req_level >= 0) & (slice_level >= 0),
        req=g(arrays.s_tas_req),
        count=g(arrays.s_tas_count),
        slice_size=g(arrays.s_tas_slice_size),
        req_level=req_level,
        slice_level=slice_level,
        required=g(arrays.s_tas_required),
        unconstrained=g(arrays.s_tas_unconstrained),
        sizes=sizes,
        usage_req=g(arrays.s_tas_usage_req),
    )


def _conflict_rank(t_idx, do, t_rows: int, per_lane: bool):
    """Conflict rank per slot: how many ``do``-active slots of strictly
    earlier slot order share its assumed-usage key (topology row, or
    (lane, row) under per-lane threading). Rank 0 slots see no earlier
    simulated takes and are final after one pass."""
    l_n, s_n = t_idx.shape
    s_io = jnp.arange(s_n, dtype=jnp.int32)
    if per_lane:
        same_row = t_idx[:, :, None] == t_idx[:, None, :]
        earlier = s_io[None, :, None] > s_io[None, None, :]
        rank = jnp.sum(
            (same_row & earlier) & do[:, None, :], axis=2,
            dtype=jnp.int32,
        )
    else:
        per_row = jnp.zeros((t_rows, s_n), jnp.int32).at[
            t_idx, s_io[None, :]
        ].add(do.astype(jnp.int32))
        excl = jnp.cumsum(per_row, axis=1) - per_row
        rank = excl[t_idx, s_io[None, :]]
    return jnp.where(do, rank, 0)


def place_slots(topo, base, ctx: SlotCtx, do,
                per_lane: bool = False) -> SlotPlacement:
    """One batched placement pass over every (lane, slot) pair.

    ``base`` is the topology usage state all placements start from
    ([T,D,R1]); ``do`` masks the slots whose feasibility gates the lane
    and whose takes thread into later same-row slots. Masked-out slots
    still place (their feas/takes are ignored and their takes zeroed),
    exactly like the retired unrolled loops.

    Returns feasibility, ``do``-masked takes and the number of conflict
    rounds run (0 = every active slot settled in the first vectorized
    pass; always < S). Commit the takes into the running topology usage
    with :func:`commit_usage`.

    slot-pass-used-by: batch_scheduler.admit_scan_grouped
    slot-pass-used-by: batch_scheduler.apply_tas_nominate_hook
    slot-pass-used-by: fair_kernel._fair_ctx
    """
    l_n, s_n = do.shape
    l_io = jnp.arange(l_n)
    rank = _conflict_rank(ctx.t_idx, do, base.shape[0], per_lane)
    max_rank = jnp.max(rank).astype(jnp.int32)

    def place_one(t, u_row, req_v, cnt, ssz, sl_, rl_, rq_, un_, sz_):
        return _tas_place.place(
            topo, t, u_row, req_v, cnt, ssz,
            jnp.maximum(sl_, 0), jnp.maximum(rl_, 0), rq_, un_,
            sizes=sz_,
        )

    place_block = jax.vmap(jax.vmap(place_one))

    def place_all(acc):
        if per_lane:
            u = base[ctx.t_idx] + acc[l_io[:, None], ctx.t_idx]
        else:
            u = base[ctx.t_idx] + acc[ctx.t_idx]
        feas, take = place_block(
            ctx.t_idx, u, ctx.req, ctx.count, ctx.slice_size,
            ctx.slice_level, ctx.req_level, ctx.required,
            ctx.unconstrained, ctx.sizes,
        )
        return feas & ctx.levels_ok, take

    if per_lane:
        acc0 = jnp.zeros((l_n,) + base.shape, base.dtype)
    else:
        acc0 = jnp.zeros_like(base)
    feas0, take0 = place_all(acc0)

    def cond(state):
        return state[0] <= max_rank

    def body(state):
        r, acc, feas, take = state
        # Commit the previous rank's feasible active deltas, then
        # re-place; only the slots of THIS rank adopt the re-placed
        # result — they now see exactly the sequential prefix of their
        # row group.
        m = do & feas & (rank == r - 1)
        upd = jnp.where(
            m[:, :, None, None],
            take[:, :, :, None] * ctx.usage_req[:, :, None, :],
            0,
        )
        if per_lane:
            acc = acc.at[l_io[:, None], ctx.t_idx].add(upd)
        else:
            acc = acc.at[ctx.t_idx].add(upd)
        nf, nt = place_all(acc)
        sel = rank == r
        feas = jnp.where(sel, nf, feas)
        take = jnp.where(sel[:, :, None], nt, take)
        return (r + jnp.int32(1), acc, feas, take)

    _, _, feas_f, take_f = jax.lax.while_loop(
        cond, body, (jnp.int32(1), acc0, feas0, take0)
    )
    return SlotPlacement(
        ok=jnp.all(jnp.where(do, feas_f, True), axis=1),
        feas=feas_f,
        takes=jnp.where(do[:, :, None], take_f, 0),
        rounds=max_rank,
    )


def commit_usage(tas_usage, ctx: SlotCtx, takes, mask):
    """Scatter the masked slot takes into the running topology usage —
    the commit half of the retired per-slot loops, as one batched
    scatter-add (duplicate rows accumulate, matching the sequential
    per-slot adds)."""
    add = takes[:, :, :, None] * ctx.usage_req[:, :, None, :]
    return tas_usage.at[ctx.t_idx].add(
        jnp.where(mask[:, :, None, None], add, 0)
    )


def place_slots_reference(topo, base, ctx: SlotCtx, do,
                          per_lane: bool = False) -> SlotPlacement:
    """Sequential per-slot placement with assumed-usage threading — the
    retired unrolled loop, verbatim semantics, kept as the differential
    oracle for :func:`place_slots` (tests/test_slot_tas.py). Not called
    by any kernel."""
    l_n, s_n = do.shape
    l_io = jnp.arange(l_n)
    if per_lane:
        extra = jnp.zeros((l_n,) + base.shape, base.dtype)
    else:
        t_sim = base
    ok = jnp.ones(l_n, bool)
    feas_cols, take_cols = [], []

    def place_one(t, u_row, req_v, cnt, ssz, sl_, rl_, rq_, un_, sz_):
        return _tas_place.place(
            topo, t, u_row, req_v, cnt, ssz,
            jnp.maximum(sl_, 0), jnp.maximum(rl_, 0), rq_, un_,
            sizes=sz_,
        )

    for si in range(s_n):
        t_i = ctx.t_idx[:, si]
        if per_lane:
            u = base[t_i] + extra[l_io, t_i]
        else:
            u = t_sim[t_i]
        feas, take = jax.vmap(place_one)(
            t_i, u, ctx.req[:, si], ctx.count[:, si],
            ctx.slice_size[:, si], ctx.slice_level[:, si],
            ctx.req_level[:, si], ctx.required[:, si],
            ctx.unconstrained[:, si], ctx.sizes[:, si],
        )
        feas = feas & ctx.levels_ok[:, si]
        live = do[:, si] & feas
        upd = jnp.where(
            live[:, None, None],
            take[:, :, None] * ctx.usage_req[:, si][:, None, :],
            0,
        )
        if per_lane:
            extra = extra.at[l_io, t_i].add(upd)
        else:
            t_sim = t_sim.at[t_i].add(upd)
        ok = ok & jnp.where(do[:, si], feas, True)
        feas_cols.append(feas)
        take_cols.append(jnp.where(do[:, si, None], take, 0))
    return SlotPlacement(
        ok=ok,
        feas=jnp.stack(feas_cols, axis=1),
        takes=jnp.stack(take_cols, axis=1),
        rounds=jnp.int32(-1),
    )
