"""Device-side classical preemption: vectorized victim selection.

Tensor reformulation of the reference's nomination-phase preemption search
(pkg/scheduler/preemption/preemption.go:281-351 classicalPreemptions +
preemption/classical/{candidate_generator,hierarchical_preemption}.go and
the per-cell oracle preemption_oracle.go SimulatePreemption) for the
*flat-cohort* case: the preemptor's CQ is either standalone or a direct
child of a root cohort whose children are all CQs, with no lending limits
anywhere in the tree (encode_cycle gates this via ``preempt_simple``).

Why this is exact under those restrictions:
  * With no lending limits, usage bubbles fully to every ancestor, so
    removing a victim with usage u at CQ d subtracts u at d and at the
    root — availability after removing a candidate *prefix* is a pair of
    running sums (same-CQ / whole-tree), and remove-until-fit becomes a
    prefix-sum argmax instead of a mutate-check loop.
  * Candidate validity (candidate_generator.go:137: a reclaim candidate is
    skipped once its CQ falls within nominal) is absorbing — removal only
    lowers the CQ's usage — so validity is a per-CQ prefix property,
    computable with segment cumsums.
  * The fill-back minimization pass (preemption.go:338) is a short reverse
    scan over the selected prefix with additive running sums.

Two search granularities run per entry, matching the host exactly:
  * one single-FlavorResource probe per contested cell — the oracle the
    flavor assigner consults (its success and post-removal borrow height
    set the cell's PMode and the assignment's ordering borrow), and
  * the full multi-resource search that yields the actual victim set.

Everything is batched over the pending-workload axis W, the probe axis
(R+1), and the admitted-candidate axis A; the only sequential construct is
the fill-back ``lax.scan`` over A (shared across batches via vmap).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.ops import quota_ops
from kueue_tpu.ops.quota_ops import sat_add, sat_sub

_INF = jnp.int64(1) << 61

# Variant codes (scheduler.preemption.Variant; 0 = not a candidate).
V_WITHIN_CQ = 1
V_HIERARCHICAL_RECLAIM = 2
V_RECLAIM_WITHOUT_BORROWING = 3
V_RECLAIM_WHILE_BORROWING = 4


class AdmittedArrays(NamedTuple):
    """The cycle-start admitted set — the candidate pool (padded axis A)."""

    cq: jnp.ndarray  # i32[A] CQ node index
    usage: jnp.ndarray  # i64[A,F,R] admitted usage per cell
    prio: jnp.ndarray  # i64[A]
    ts: jnp.ndarray  # f64[A] queue-order timestamp
    qr_time: jnp.ndarray  # f64[A] quota-reservation time
    evicted: jnp.ndarray  # bool[A]
    active: jnp.ndarray  # bool[A] (padding = False)
    uid_rank: jnp.ndarray  # i32[A] UID sort rank (final ordering tiebreak)
    # Admitted TAS usage on device topologies (None when no device TAS):
    # removal of victim a releases tas_usage[a] on topology row tas_t[a].
    tas_t: jnp.ndarray = None  # i32[A] topo row (-1 = not TAS / host topo)
    tas_usage: jnp.ndarray = None  # i64[A, D, R+1] per-leaf usage


class PreemptTargets(NamedTuple):
    victims: jnp.ndarray  # bool[W,A] final victim set per preemptor
    variant: jnp.ndarray  # i32[W,A] Variant code per victim (0 = none)
    success: jnp.ndarray  # bool[W] device-resolved Preempt with targets
    resolved_nc: jnp.ndarray  # bool[W] device-resolved, no targets (reserve)
    resolved: jnp.ndarray  # bool[W] = success | resolved_nc
    borrow_after: jnp.ndarray  # i32[W] assignment-order borrow key


class SlotNom(NamedTuple):
    """Per-slot nominate outputs for slot-layout (multi-podset /
    multi-resource-group) cycles — the victim search then runs over
    (slot, resource) cells on each slot's chosen flavor plane, the way
    the host preemptor sees the whole assignment's FlavorResource usage
    (preemption.go:131 GetTargets over assignment.Usage)."""

    s_flavor: jnp.ndarray  # i32[W,S] chosen flavor per slot (-1 none)
    s_on: jnp.ndarray  # bool[W,S] effective assigned slots
    s_is_praw: jnp.ndarray  # bool[W,S] slot stopped in raw-preempt mode
    s_praw_stop: jnp.ndarray  # bool[W,S] slot scan stopped at a praw flavor
    s_considered: jnp.ndarray  # i32[W,S] flavors considered by slot scan


def _seg_excl_prefix(sorted_vals, head):
    """Exclusive prefix sums within segments (head marks segment starts)."""
    c = jnp.cumsum(sorted_vals, axis=0)
    excl = c - sorted_vals
    n = head.shape[0]
    head_idx = jnp.where(head, jnp.arange(n), -1)
    seg_head = jax.lax.associative_scan(jnp.maximum, head_idx)
    return excl - excl[seg_head]


def _seg_incl_cumsum(vals, head):
    """Inclusive prefix sums within segments for a 1-D int array."""
    c = jnp.cumsum(vals)
    n = head.shape[0]
    head_idx = jnp.where(head, jnp.arange(n), -1)
    seg_head = jax.lax.associative_scan(jnp.maximum, head_idx)
    return c - (c - vals)[seg_head]


def preempt_targets(
    arrays: CycleArrays,
    adm: AdmittedArrays,
    chosen_flavor: jnp.ndarray,  # i32[W]
    eligible: jnp.ndarray,  # bool[W] structurally device-resolvable entries
    praw_stop: jnp.ndarray,  # bool[W] fungibility scan stopped at the raw flavor
    considered: jnp.ndarray,  # i32[W] flavors considered by the scan
    slot_nom: SlotNom = None,
) -> PreemptTargets:
    """Victim selection for every eligible entry at once, against the
    cycle-start usage (matching the host's nomination-phase get_targets).

    The search runs over (slot, resource) cells: each slot contributes its
    requests on its own chosen flavor plane, same-flavor slots aggregate
    (the host preemptor sees the summed FlavorResource usage map), and the
    per-cell oracle probes use the slot-accumulated value exactly like the
    host's ``val = assumed + request`` (flavorassigner.go:1213). Legacy
    single-slot cycles pass ``slot_nom=None`` and run with S=1, which is
    definitionally the same search.

    TAS entries (when the encoder's ``preempt_tas_ok`` gate admits them)
    run the same search with the host's tas_fits probe folded in
    (preemption.go:637): victim removal releases per-leaf topology usage,
    and — placement feasibility being monotone in the removal prefix —
    the placement threshold is found by binary search over the ordered
    candidate prefix instead of a per-candidate probe. Device TAS entries
    are single-podset by encoder gate, so the probe stays workload-level.
    """
    tree = arrays.tree
    usage = arrays.usage
    sq = tree.subtree_quota
    avail0 = quota_ops.available_all(tree, usage)

    n = tree.n_nodes
    parent_or_self = jnp.where(tree.parent < 0, jnp.arange(n), tree.parent)
    root_of = jnp.arange(n)
    for _ in range(quota_ops.MAX_DEPTH):
        root_of = parent_or_self[root_of]
    has_par_n = tree.parent >= 0

    a_n = adm.cq.shape[0]
    r_n = tree.nominal.shape[2]
    a_iota = jnp.arange(a_n)

    w_count = arrays.w_cq.shape[0]
    if slot_nom is not None and arrays.s_req is not None:
        sl_f = slot_nom.s_flavor
        sl_req = arrays.s_req
        sl_on = slot_nom.s_on
        sl_praw = slot_nom.s_is_praw
        sl_stop = slot_nom.s_praw_stop
        sl_cons = slot_nom.s_considered
    else:
        sl_f = chosen_flavor[:, None]
        sl_req = arrays.w_req[:, None, :]
        sl_on = jnp.ones((w_count, 1), bool)
        sl_praw = jnp.ones((w_count, 1), bool)
        sl_stop = praw_stop[:, None]
        sl_cons = considered[:, None]
    s_n = sl_req.shape[1]

    with_tas = (
        getattr(arrays, "tas_topo", None) is not None
        and adm.tas_t is not None
    )
    if with_tas:
        from kueue_tpu.ops import tas_place as _tas_place

        w_n = arrays.w_cq.shape[0]
        w_iota = jnp.arange(w_n)
        f_all = arrays.w_elig.shape[1]
        t_of_w = jnp.where(
            chosen_flavor >= 0,
            arrays.tas_of_flavor[jnp.clip(chosen_flavor, 0, f_all - 1)],
            -1,
        )
        t_idx_w = jnp.clip(t_of_w, 0, arrays.tas_usage0.shape[0] - 1)
        cap_w = _tas_place.entry_leaf_cap(arrays, t_idx_w)
        tas_in = dict(
            do_tas=arrays.w_tas & (t_of_w >= 0),
            t_row=t_idx_w,
            t_req=arrays.w_tas_req,
            t_cnt=arrays.w_tas_count,
            t_ssz=arrays.w_tas_slice_size,
            t_sl=jnp.maximum(
                arrays.w_tas_slice_level[w_iota, t_idx_w], 0
            ),
            t_rl=jnp.maximum(arrays.w_tas_req_level[w_iota, t_idx_w], 0),
            t_rq=arrays.w_tas_required,
            t_un=arrays.w_tas_unconstrained,
            t_cap=cap_w,
            t_sz=arrays.w_tas_sizes[w_iota, t_idx_w],
        )
    else:
        zw = jnp.zeros(arrays.w_cq.shape[0], jnp.int64)
        tas_in = dict(
            do_tas=zw.astype(bool), t_row=zw.astype(jnp.int32),
            t_req=zw[:, None], t_cnt=zw, t_ssz=zw,
            t_sl=zw.astype(jnp.int32), t_rl=zw.astype(jnp.int32),
            t_rq=zw.astype(bool), t_un=zw.astype(bool),
            t_cap=zw[:, None, None],
            t_sz=zw[:, None],
        )

    def per_w(c, sf, sreq_own, son, spraw, sstop, scons,
              prio, ts, elig_w,
              do_tas, t_row, t_req, t_cnt, t_ssz, t_sl, t_rl, t_rq, t_un,
              t_cap, t_sz):
        f = jnp.maximum(sf, 0)  # [S]
        on = son & (sf >= 0)
        sreq = jnp.where(on[:, None], sreq_own, 0)  # [S,R]
        # Same-flavor aggregation: the host preemptor's usage map sums
        # podset requests per FlavorResource; duplicate slot planes carry
        # the identical total (harmless duplicate checks).
        samef = (f[:, None] == f[None, :]) & on[:, None] & on[None, :]
        req_tot = jnp.einsum(
            "st,tr->sr", samef.astype(jnp.int64), sreq
        )  # [S,R]
        # Inclusive slot accumulation for the per-cell oracle probes: the
        # host consults the oracle with val = assumed + request, where
        # assumed covers EARLIER slots assigned on the same plane
        # (flavorassigner.go:1213).
        s_iota_ax = jnp.arange(s_n)
        acc_incl = jnp.einsum(
            "st,tr->sr",
            (samef
             & (s_iota_ax[None, :] <= s_iota_ax[:, None])).astype(
                 jnp.int64),
            sreq,
        )  # [S,R]
        full_active = (req_tot > 0) & on[:, None]  # [S,R]
        if s_n == 1:
            # Legacy single-slot layout: requests live on the first
            # resource group, whose coverage ``covered`` describes.
            # Slot layouts span all RGs — coverage is guaranteed by
            # _workload_slots (None on any uncovered positive request),
            # and covered[] would wrongly drop later-RG cells.
            full_active = full_active & arrays.covered[c][None, :]
        contested_full = full_active & (req_tot > avail0[c][f])  # [S,R]
        au = adm.usage[:, f, :]  # [A,S,R]

        same = adm.cq == c
        cross = (root_of[adm.cq] == root_of[c]) & ~same & has_par_n[c]
        lower = prio > adm.prio
        neq = (prio == adm.prio) & (ts < adm.ts)

        def pol_ok(pol):
            return jnp.where(
                pol == 3, jnp.ones_like(lower),
                jnp.where(pol == 2, lower | neq,
                          jnp.where(pol == 1, lower,
                                    jnp.zeros_like(lower))),
            )

        pol_w = arrays.policy_within[c]
        pol_r = arrays.policy_reclaim[c]
        policy_pass = (
            (same & (pol_w != 0) & pol_ok(pol_w))
            | (cross & (pol_r != 0) & pol_ok(pol_r))
        )

        has_par = has_par_n[c]
        root = root_of[c]
        u_c = usage[c, f]  # [R]
        u_root = usage[root, f]
        sq_c = sq[c, f]
        sq_root = sq[root, f]
        t_c = jnp.where(
            has_par,
            jnp.where(tree.has_borrow_limit[c, f],
                      sat_add(sq_c, tree.borrow_limit[c, f]), _INF),
            sq_c,
        )  # [R]

        def search(active_req, contested, req_vec, tas_probe=False):
            """One classical search (preemption.go:296): requests =
            req_vec over active_req [S,R] cells, contested cells needing
            preemption. Returns (success, victims[A]). With ``tas_probe``
            the host's tas_fits placement check gates the stop point and
            the fill-back (preemption.go:637)."""
            uses = jnp.any(contested[None] & (au > 0), axis=(1, 2))
            # Cross-CQ collection gate: candidate CQ not within nominal in
            # the contested cells (hierarchical_preemption.go:176).
            above_nom = jnp.any(
                contested[None]
                & (usage[adm.cq[:, None], f[None, :], :]
                   > sq[adm.cq[:, None], f[None, :], :]),
                axis=(1, 2),
            )
            cand = adm.active & uses & policy_pass & (same | above_nom)

            # Hierarchical advantage: requests fit in the preemptor CQ's
            # own quota (hierarchical_preemption.go:129).
            advantage = jnp.all(
                ~active_req | (sq_c >= sat_add(u_c, req_vec))
            )
            bwc = arrays.bwc_policy[c]
            rwob = (bwc == 0) | (adm.prio >= prio) | (
                arrays.bwc_has_threshold[c]
                & (adm.prio > arrays.bwc_threshold[c])
            )
            variant = jnp.where(
                ~cand, 0,
                jnp.where(same, V_WITHIN_CQ,
                          jnp.where(advantage, V_HIERARCHICAL_RECLAIM,
                                    jnp.where(rwob,
                                              V_RECLAIM_WITHOUT_BORROWING,
                                              V_RECLAIM_WHILE_BORROWING))),
            ).astype(jnp.int32)

            # Global candidate order: evicted-class split then per-class
            # CandidatesOrdering (ordering.go:42). Within a class the
            # evicted / same-CQ key components are uniform, so the
            # concatenation of per-class sorts equals one sort by
            # (class_rank, prio, -qr_time, uid).
            class_rank = (
                jnp.where(same, 2, jnp.where(advantage, 0, 1))
                + jnp.where(adm.evicted, 0, 3)
            )
            ord_ = jnp.lexsort((
                adm.uid_rank, -adm.qr_time, adm.prio, class_rank,
                (~cand).astype(jnp.int32),
            )).astype(jnp.int32)
            pos = jnp.zeros(a_n, jnp.int32).at[ord_].set(
                a_iota.astype(jnp.int32)
            )
            ord2 = jnp.lexsort((pos, adm.cq)).astype(jnp.int32)
            s_cq = adm.cq[ord2]
            head2 = jnp.concatenate(
                [jnp.ones(1, bool), s_cq[1:] != s_cq[:-1]]
            )
            same_g = same[ord_]
            au_g = au[ord_]

            # Attempt plan (preemption.go:312-336).
            has_cross = jnp.any(cand & cross)
            borrow_forbidden = bwc == 0
            under_nom = jnp.all(
                ~contested | (tree.nominal[c, f] > u_c)
            )
            single = ~has_cross | (borrow_forbidden & ~under_nom)
            has_hier = has_cross & advantage
            first_borrow = jnp.where(
                single, True, ~(borrow_forbidden & ~has_hier)
            )
            second_on = ~single

            def fits_with(s_same, s_all, borrow_b):
                """req_vec fits after removing s_same at the CQ / s_all at
                the root (workloadFits, preemption.go:628)."""
                term_c = jnp.where(
                    t_c >= _INF, _INF, sat_sub(t_c, u_c - s_same)
                )
                term_root = sat_sub(sq_root, u_root - s_all)
                avail = jnp.minimum(
                    term_c, jnp.where(has_par, term_root, _INF)
                )
                ok = (req_vec <= avail) | ~active_req
                no_borrow_ok = (
                    (u_c - s_same + req_vec <= sq_c) | ~active_req
                )
                ok = ok & (borrow_b | no_borrow_ok)
                return jnp.all(ok, axis=(-2, -1))

            def attempt(borrow_b):
                elig = cand & ~(
                    borrow_b & (variant == V_RECLAIM_WITHOUT_BORROWING)
                )
                contrib = jnp.where(
                    elig[:, None, None], au, 0
                ).astype(jnp.int64)
                # Per-CQ dynamic validity: naive above-nominal check
                # against the CQ-segment exclusive prefix, folded with a
                # cumulative AND (validity is absorbing).
                excl2 = _seg_excl_prefix(contrib[ord2], head2)  # [A,S,R]
                naive = same[ord2] | jnp.any(
                    contested[None]
                    & (usage[s_cq[:, None], f[None, :], :] - excl2
                       > sq[s_cq[:, None], f[None, :], :]),
                    axis=(1, 2),
                )
                bad = (elig[ord2] & ~naive).astype(jnp.int32)
                valid2 = _seg_incl_cumsum(bad, head2) == 0
                valid = jnp.zeros(a_n, bool).at[ord2].set(valid2)
                removal = elig & valid

                rg = removal[ord_]
                cg = jnp.where(
                    rg[:, None, None], au_g, 0
                ).astype(jnp.int64)
                cum_all = jnp.cumsum(cg, axis=0)
                cum_same = jnp.cumsum(
                    jnp.where(same_g[:, None, None], cg, 0), axis=0
                )
                fits_k = fits_with(cum_same, cum_all, borrow_b)  # [A]

                if tas_probe:
                    # Placement threshold: smallest removal-prefix length
                    # after which the entry places on its topology (the
                    # released victim usage only grows along the prefix,
                    # so feasibility is monotone — binary search). ``pos``
                    # from the enclosing search() is the ord_-position map.
                    pos_of = pos
                    rel_mask = removal & (adm.tas_t == t_row)
                    tas0_row = arrays.tas_usage0[t_row]  # [D,R1]

                    def tas_state(k):
                        wgt = (rel_mask & (pos_of <= k)).astype(jnp.int64)
                        rel = jnp.einsum("a,adr->dr", wgt, adm.tas_usage)
                        return tas0_row - rel

                    def feas(state):
                        return _tas_place.feasible_only(
                            arrays.tas_topo, t_row, state, t_req, t_cnt,
                            t_ssz, t_sl, t_rl, t_rq, t_un,
                            cap_override=t_cap, sizes=t_sz,
                        )

                    def bisect(_, st):
                        lo, hi = st
                        mid = (lo + hi) // 2
                        ok = feas(tas_state(mid))
                        go = lo < hi
                        hi = jnp.where(go & ok, mid, hi)
                        lo = jnp.where(go & ~ok, mid + 1, lo)
                        return lo, hi

                    # Lower bound over k in [-1, a_n-1], sentinel a_n =
                    # never feasible; fori_loop so the placement probe
                    # traces once, not once per bisection step.
                    steps = max(a_n + 1, 1).bit_length() + 1
                    kt, _hi = jax.lax.fori_loop(
                        0, steps, bisect,
                        (jnp.int32(-1), jnp.int32(a_n)),
                    )
                    kt = jnp.where(do_tas, kt, jnp.int32(-1))
                    hit = rg & fits_k & (a_iota >= kt)
                else:
                    hit = rg & fits_k
                success = jnp.any(hit)
                k_star = jnp.argmax(hit).astype(jnp.int32)
                pre = rg & (a_iota <= k_star)

                # Fill-back (preemption.go:338): reverse pass over the
                # prefix targets except the last, restoring any
                # no-longer-needed one.
                s_same0 = cum_same[k_star]
                s_all0 = cum_all[k_star]
                fb_mask = pre & (a_iota < k_star)

                if tas_probe:
                    t_state0 = tas_state(k_star)
                    rel_g = rel_mask[ord_]

                    def fb(carry, xs):
                        s_s, s_a, t_state = carry
                        is_t, c_p, is_same_p, a_p, rel_p = xs
                        t_s = s_s - jnp.where(is_same_p, c_p, 0)
                        t_a = s_a - c_p
                        t_try = t_state + jnp.where(
                            rel_p, adm.tas_usage[a_p], 0
                        )
                        ok = fits_with(t_s, t_a, borrow_b) & (
                            ~do_tas | feas(t_try)
                        )
                        drop = is_t & ok
                        s_s = jnp.where(drop, t_s, s_s)
                        s_a = jnp.where(drop, t_a, s_a)
                        t_state = jnp.where(drop, t_try, t_state)
                        return (s_s, s_a, t_state), drop

                    xs = (fb_mask[::-1], cg[::-1], same_g[::-1],
                          ord_[::-1], rel_g[::-1])
                    _, drops_rev = jax.lax.scan(
                        fb, (s_same0, s_all0, t_state0), xs
                    )
                else:
                    def fb(carry, xs):
                        s_s, s_a = carry
                        is_t, c_p, is_same_p = xs
                        t_s = s_s - jnp.where(is_same_p, c_p, 0)
                        t_a = s_a - c_p
                        drop = is_t & fits_with(t_s, t_a, borrow_b)
                        s_s = jnp.where(drop, t_s, s_s)
                        s_a = jnp.where(drop, t_a, s_a)
                        return (s_s, s_a), drop

                    xs = (fb_mask[::-1], cg[::-1], same_g[::-1])
                    _, drops_rev = jax.lax.scan(fb, (s_same0, s_all0), xs)
                drops = drops_rev[::-1]
                victims_g = pre & ~drops & success
                victims = jnp.zeros(a_n, bool).at[ord_].set(victims_g)
                return success, victims

            ok1, v1 = attempt(first_borrow)
            ok2, v2 = attempt(~first_borrow)
            use2 = ~ok1 & second_on & ok2
            success = ok1 | use2
            victims = jnp.where(success, jnp.where(ok1, v1, v2), False)
            return success, victims, variant

        # Full multi-resource search (with the tas_fits probe for TAS
        # entries) + per-cell oracle probes (quota-only, matching the
        # reference SimulatePreemption). Cells enumerate the (slot,
        # resource) plane; inactive cells run inert searches.
        k_cells = s_n * r_n
        cs = jnp.repeat(jnp.arange(s_n), r_n)  # [K] slot of cell
        cr = jnp.tile(jnp.arange(r_n), s_n)  # [K] resource of cell
        eye_sr = (
            (cs[:, None, None] == jnp.arange(s_n)[None, :, None])
            & (cr[:, None, None] == jnp.arange(r_n)[None, None, :])
        )  # [K,S,R]
        cell_active_p = eye_sr & full_active[None]
        cell_contested_p = eye_sr & contested_full[None]
        cell_req = jnp.where(cell_active_p, acc_incl[None], 0)
        full_success, full_victims, variant = search(
            full_active, contested_full,
            jnp.where(full_active, req_tot, 0),
            tas_probe=with_tas,
        )
        cell_success_k, cell_victims_k, _vc = jax.vmap(search)(
            cell_active_p, cell_contested_p, cell_req
        )  # [K], [K, A]
        cell_success = cell_success_k.reshape(s_n, r_n)

        # Per-cell borrow = the oracle's post-removal height for
        # successful probes, the current height otherwise; FIT cells keep
        # the current height (flavorassigner.go:1213 + oracle).
        root_h = tree.height[root]
        au_cells = jnp.moveaxis(au, 0, -1).reshape(k_cells, a_n)
        rem_same_cell = jnp.einsum(
            "ka,ka->k",
            (cell_victims_k & same[None, :]).astype(jnp.int64),
            au_cells,
        ).reshape(s_n, r_n)  # same-CQ removal per probe at its own cell
        h_pre = jnp.where(
            has_par & (sat_add(u_c, req_tot) > sq_c), root_h, 0
        )  # [S,R]
        h_post = jnp.where(
            has_par & (sat_add(u_c - rem_same_cell, req_tot) > sq_c),
            root_h, 0,
        )
        cell_borrow = jnp.where(
            contested_full,
            jnp.where(cell_success, h_post, h_pre),
            h_pre,
        )
        borrow_after = jnp.max(
            jnp.where(full_active, cell_borrow, 0)
        ).astype(jnp.int32)

        # Flavor-scan consistency, per slot: when the host stopped a
        # slot's fungibility scan at its flavor, it did so because every
        # contested cell's oracle reported preempt-mode; a NoCandidates
        # cell would have continued to later flavors, so such entries must
        # stay on the host path. A single-flavor slot has no later flavor
        # — the choice is forced either way. Non-praw slots (Fit or
        # device-resolved NoCandidates with zero praw flavors seen, per
        # the caller's structural gate) are oracle-independent.
        cells_ok_s = jnp.all(~contested_full | cell_success, axis=1)  # [S]
        slot_ok = (
            ~on | ~spraw | (scons == 1) | (sstop & cells_ok_s)
        )
        resolved = elig_w & jnp.all(slot_ok)
        success = resolved & full_success
        victims = jnp.where(success, full_victims, False)
        resolved_nc = resolved & ~full_success

        return victims, jnp.where(victims, variant, 0), success, \
            resolved_nc, resolved, borrow_after

    victims, variant, success, resolved_nc, resolved, borrow_after = \
        jax.vmap(per_w)(
            arrays.w_cq, sl_f, sl_req, sl_on, sl_praw, sl_stop, sl_cons,
            arrays.w_priority, arrays.w_timestamp, eligible,
            tas_in["do_tas"], tas_in["t_row"], tas_in["t_req"],
            tas_in["t_cnt"], tas_in["t_ssz"], tas_in["t_sl"],
            tas_in["t_rl"], tas_in["t_rq"], tas_in["t_un"],
            tas_in["t_cap"], tas_in["t_sz"],
        )
    return PreemptTargets(victims, variant, success, resolved_nc, resolved,
                          borrow_after)


def hier_targets(
    arrays: CycleArrays,
    adm: AdmittedArrays,
    chosen_flavor: jnp.ndarray,  # i32[W]
    eligible: jnp.ndarray,  # bool[W] structurally device-resolvable entries
    praw_stop: jnp.ndarray,  # bool[W]
    considered: jnp.ndarray,  # i32[W]
) -> PreemptTargets:
    """Victim selection for entries in *nested* (depth > 1) lending-limit-
    free cohort trees — the hierarchical-reclaim generalization of
    ``preempt_targets`` (reference hierarchical_preemption.go:149
    collectCandidatesForHierarchicalReclaim + candidate_generator.go:135
    candidateIsValid + preemption.go:281 classicalPreemptions).

    Differences from the flat kernel:
      * per-candidate LCA with the preemptor and an advantage state that
        evolves along the preemptor's root path (QuantitiesFitInQuota
        walk, resource_node.go:233);
      * candidate collection and in-run validity check the candidate's CQ
        *and every cohort strictly below the LCA* for above-nominal usage;
      * the fit test is a chain-min over all of the preemptor's ancestors;
      * remove-until-fit runs as a lax.scan over the ordered candidate
        axis carrying per-node removed usage (exact sequential semantics —
        cross-CQ removals under shared cohorts interleave, so the flat
        kernel's per-CQ prefix trick does not apply).

    Exactness relies on the encoder's ``preempt_hier`` gate: no lending
    limits anywhere in the tree (usage bubbles fully, so removal at CQ d
    subtracts at every ancestor of d) and fully mappable admitted usage.

    TAS entries (``preempt_tas_ok``) run the same search with the host's
    tas_fits probe folded in (preemption.go:637): the remove-until-fit
    scan carries the topology state alongside the per-node usage, victim
    removal releases per-leaf usage, and both the stop test and the
    fill-back check placement feasibility.
    """
    tree = arrays.tree
    usage = arrays.usage
    sq = tree.subtree_quota
    avail0 = quota_ops.available_all(tree, usage)

    n = tree.n_nodes
    parent_or_self = jnp.where(tree.parent < 0, jnp.arange(n), tree.parent)
    root_of = jnp.arange(n)
    for _ in range(quota_ops.MAX_DEPTH):
        root_of = parent_or_self[root_of]
    has_par_n = tree.parent >= 0
    chain_cols = [jnp.arange(n)]
    for _ in range(quota_ops.MAX_DEPTH):
        chain_cols.append(parent_or_self[chain_cols[-1]])
    chain_table = jnp.stack(chain_cols, axis=1)  # [N, D+1]
    in_sub = quota_ops.ancestor_matrix(tree)  # [b, d]: b ancestor-or-self of d
    lq_all = quota_ops.local_quota(tree)
    height_n = tree.height
    d1 = quota_ops.MAX_DEPTH + 1

    a_n = adm.cq.shape[0]
    r_n = tree.nominal.shape[2]
    a_iota = jnp.arange(a_n)
    cand_chain = chain_table[adm.cq]  # [A, D+1]

    with_tas = (
        getattr(arrays, "tas_topo", None) is not None
        and adm.tas_t is not None
    )
    if with_tas:
        from kueue_tpu.ops import tas_place as _tas_place

        w_n = arrays.w_cq.shape[0]
        w_iota = jnp.arange(w_n)
        f_all = arrays.w_elig.shape[1]
        t_of_w = jnp.where(
            chosen_flavor >= 0,
            arrays.tas_of_flavor[jnp.clip(chosen_flavor, 0, f_all - 1)],
            -1,
        )
        t_idx_w = jnp.clip(t_of_w, 0, arrays.tas_usage0.shape[0] - 1)
        cap_w = _tas_place.entry_leaf_cap(arrays, t_idx_w)
        tas_in = dict(
            do_tas=arrays.w_tas & (t_of_w >= 0),
            t_row=t_idx_w,
            t_req=arrays.w_tas_req,
            t_cnt=arrays.w_tas_count,
            t_ssz=arrays.w_tas_slice_size,
            t_sl=jnp.maximum(
                arrays.w_tas_slice_level[w_iota, t_idx_w], 0
            ),
            t_rl=jnp.maximum(arrays.w_tas_req_level[w_iota, t_idx_w], 0),
            t_rq=arrays.w_tas_required,
            t_un=arrays.w_tas_unconstrained,
            t_cap=cap_w,
            t_sz=arrays.w_tas_sizes[w_iota, t_idx_w],
        )
    else:
        zw = jnp.zeros(arrays.w_cq.shape[0], jnp.int64)
        tas_in = dict(
            do_tas=zw.astype(bool), t_row=zw.astype(jnp.int32),
            t_req=zw[:, None], t_cnt=zw, t_ssz=zw,
            t_sl=zw.astype(jnp.int32), t_rl=zw.astype(jnp.int32),
            t_rq=zw.astype(bool), t_un=zw.astype(bool),
            t_cap=zw[:, None, None],
            t_sz=zw[:, None],
        )

    def per_w(c, f0, req, prio, ts, elig_w, stopped_at_praw, considered,
              do_tas, t_row, t_req, t_cnt, t_ssz, t_sl, t_rl, t_rq, t_un,
              t_cap, t_sz):
        f = jnp.maximum(f0, 0)
        full_active = (req > 0) & arrays.covered[c]  # [R]
        contested_full = full_active & (req > avail0[c, f])  # [R]
        au = adm.usage[:, f, :]  # [A,R]
        u0_f = usage[:, f, :]  # [N,R] cycle-start plane
        sq_f = sq[:, f, :]
        lq_f = lq_all[:, f, :]
        bl_f = tree.borrow_limit[:, f, :]
        has_bl_f = tree.has_borrow_limit[:, f, :]

        same = adm.cq == c
        cross = (root_of[adm.cq] == root_of[c]) & ~same & has_par_n[c]
        lower = prio > adm.prio
        neq = (prio == adm.prio) & (ts < adm.ts)

        def pol_ok(pol):
            return jnp.where(
                pol == 3, jnp.ones_like(lower),
                jnp.where(pol == 2, lower | neq,
                          jnp.where(pol == 1, lower,
                                    jnp.zeros_like(lower))),
            )

        pol_w = arrays.policy_within[c]
        pol_r = arrays.policy_reclaim[c]
        policy_pass = (
            (same & (pol_w != 0) & pol_ok(pol_w))
            | (cross & (pol_r != 0) & pol_ok(pol_r))
        )

        has_par = has_par_n[c]
        chain_c = chain_table[c]  # [D+1]
        is_real_lvl = jnp.concatenate([
            jnp.ones(1, bool), chain_c[1:] != chain_c[:-1]
        ])  # [D+1] first occurrence of each chain node
        # Fit-test constraint term per chain level (lend-free closed form).
        t_chain = jnp.where(
            (tree.parent[chain_c] < 0)[:, None],
            sq_f[chain_c],
            jnp.where(has_bl_f[chain_c],
                      sat_add(sq_f[chain_c], bl_f[chain_c]), _INF),
        )  # [D+1,R]
        u_c0 = u0_f[c]
        sq_c = sq_f[c]

        # LCA of preemptor and each candidate: first chain level (>=1)
        # whose node covers the candidate's CQ.
        anc = in_sub[chain_c][:, adm.cq]  # [D+1, A]
        anc = anc & (jnp.arange(d1) > 0)[:, None]
        lca_lvl = jnp.argmax(anc, axis=0).astype(jnp.int32)  # [A]
        lca_node = chain_c[lca_lvl]
        # Candidate path levels strictly below the LCA (its own CQ apart).
        lvl_of_lca_on_cand = jnp.argmax(
            cand_chain == lca_node[:, None], axis=1
        ).astype(jnp.int32)  # [A]
        cand_real = jnp.concatenate([
            jnp.ones((a_n, 1), bool),
            cand_chain[:, 1:] != cand_chain[:, :-1],
        ], axis=1)
        path_mask = (
            (jnp.arange(d1)[None, :] >= 1)
            & (jnp.arange(d1)[None, :] < lvl_of_lca_on_cand[:, None])
            & cand_real
        )  # [A, D+1]

        def search(active_req, contested, req_vec, tas_probe=False):
            uses = jnp.any(contested[None, :] & (au > 0), axis=1)

            if tas_probe:
                rel_ok = adm.tas_t == t_row  # [A] same-topology victims
                tas0_row = arrays.tas_usage0[t_row]  # [D,R1]

                def tas_feas(state):
                    return _tas_place.feasible_only(
                        arrays.tas_topo, t_row, state, t_req, t_cnt,
                        t_ssz, t_sl, t_rl, t_rq, t_un,
                        cap_override=t_cap, sizes=t_sz,
                    )

            def above_nominal(u_f, nodes):
                """∃ contested cell with usage above subtree quota."""
                return jnp.any(
                    contested & (u_f[nodes] > sq_f[nodes]), axis=-1
                )

            # Advantage state along the preemptor's root path
            # (hierarchical_preemption.go:160-172): candidates found at
            # LCA level i get the state *before* that level's fit update.
            adv = jnp.all(
                ~active_req | (sat_add(u_c0, req_vec) <= sq_c)
            )
            remaining = sat_sub(
                req_vec, jnp.maximum(0, sat_sub(lq_f[c], u_c0))
            )
            adv_at_rows = [adv]  # state entering level 1
            for i in range(1, d1):
                b = chain_c[i]
                fits_i = jnp.all(
                    ~active_req
                    | (sat_add(u0_f[b], remaining) <= sq_f[b])
                )
                adv = adv | (fits_i & is_real_lvl[i])
                if i < d1 - 1:
                    adv_at_rows.append(adv)
                remaining = sat_sub(
                    remaining, jnp.maximum(0, sat_sub(lq_f[b], u0_f[b]))
                )
            adv_at = jnp.stack(adv_at_rows)  # [D] state entering level i+1
            cand_adv = adv_at[jnp.clip(lca_lvl - 1, 0, d1 - 2)]  # [A]

            # Static collection gate: candidate CQ and every cohort
            # strictly below the LCA above nominal at cycle start
            # (collectCandidatesInSubtree skips within-nominal subtrees).
            # all path nodes above nominal <=> count(above) == count(path)
            above0_cnt = jnp.sum(
                path_mask
                & jnp.any(
                    contested[None, None, :]
                    & (u0_f[cand_chain] > sq_f[cand_chain]),
                    axis=-1,
                ),
                axis=1,
            )
            path_ok0 = above0_cnt == jnp.sum(path_mask, axis=1)
            cq_ok0 = above_nominal(u0_f, adm.cq)
            cand = adm.active & uses & policy_pass & (
                same | (path_ok0 & cq_ok0)
            )

            bwc = arrays.bwc_policy[c]
            rwob = (bwc == 0) | (adm.prio >= prio) | (
                arrays.bwc_has_threshold[c]
                & (adm.prio > arrays.bwc_threshold[c])
            )
            variant = jnp.where(
                ~cand, 0,
                jnp.where(same, V_WITHIN_CQ,
                          jnp.where(cand_adv, V_HIERARCHICAL_RECLAIM,
                                    jnp.where(rwob,
                                              V_RECLAIM_WITHOUT_BORROWING,
                                              V_RECLAIM_WHILE_BORROWING))),
            ).astype(jnp.int32)

            class_rank = (
                jnp.where(same, 2, jnp.where(cand_adv, 0, 1))
                + jnp.where(adm.evicted, 0, 3)
            )
            ord_ = jnp.lexsort((
                adm.uid_rank, -adm.qr_time, adm.prio, class_rank,
                (~cand).astype(jnp.int32),
            )).astype(jnp.int32)

            # Attempt plan (preemption.go:308-316).
            has_cross = jnp.any(cand & cross)
            has_hier = jnp.any(cand & cross & cand_adv)
            borrow_forbidden = bwc == 0
            under_nom = jnp.all(
                ~contested | (tree.nominal[c, f] > u_c0)
            )
            single = ~has_cross | (borrow_forbidden & ~under_nom)
            first_borrow = jnp.where(
                single, True, ~(borrow_forbidden & ~has_hier)
            )
            second_on = ~single

            def fits_state(u_f, borrow_b):
                """workloadFits against per-node plane usage u_f [N,R]."""
                term = jnp.where(
                    t_chain >= _INF, _INF, sat_sub(t_chain, u_f[chain_c])
                )  # [D+1,R]
                term = jnp.where(is_real_lvl[:, None], term, _INF)
                avail = jnp.min(term, axis=0)  # [R]
                avail = jnp.where(
                    has_par, avail,
                    sat_sub(sq_c, u_f[c]),
                )
                ok = (req_vec <= avail) | ~active_req
                no_borrow_ok = (
                    (sat_add(u_f[c], req_vec) <= sq_c) | ~active_req
                )
                return jnp.all(ok & (borrow_b | no_borrow_ok))

            def attempt(borrow_b):
                elig = cand & ~(
                    borrow_b & (variant == V_RECLAIM_WITHOUT_BORROWING)
                )
                t_state0 = tas0_row if tas_probe else jnp.zeros((), jnp.int64)

                def fwd(carry, a):
                    u_f, stopped, t_state = carry
                    # Dynamic validity (candidate_generator.go:135):
                    # same-CQ always valid; cross needs CQ + path-to-LCA
                    # above nominal against the running usage.
                    d_cq = adm.cq[a]
                    above_cq = above_nominal(u_f, d_cq)
                    path_above = jnp.any(
                        contested[None, :]
                        & (u_f[cand_chain[a]] > sq_f[cand_chain[a]]),
                        axis=-1,
                    )  # [D+1]
                    path_all = jnp.all(~path_mask[a] | path_above)
                    valid = jnp.where(same[a], True, above_cq & path_all)
                    remove = elig[a] & valid & ~stopped
                    sub = jnp.where(
                        remove, in_sub[:, d_cq], False
                    )[:, None] * au[a][None, :]
                    u_f = u_f - sub
                    hit = remove & fits_state(u_f, borrow_b)
                    if tas_probe:
                        t_state = t_state - jnp.where(
                            remove & rel_ok[a], adm.tas_usage[a], 0
                        )
                        hit = hit & (~do_tas | tas_feas(t_state))
                    return (u_f, stopped | hit, t_state), (remove, hit)

                (u_end, _, t_end), (removed_o, hit_o) = jax.lax.scan(
                    fwd, (u0_f, jnp.bool_(False), t_state0), ord_
                )
                success = jnp.any(hit_o)
                k_star = jnp.argmax(hit_o).astype(jnp.int32)
                pos = jnp.arange(a_n)
                pre = removed_o & (pos <= k_star)

                def fb(carry, xs):
                    u_f, t_state = carry
                    is_t, a = xs
                    u_t = u_f + (
                        jnp.where(is_t, in_sub[:, adm.cq[a]], False)[:, None]
                        * au[a][None, :]
                    )
                    drop = is_t & fits_state(u_t, borrow_b)
                    if tas_probe:
                        t_try = t_state + jnp.where(
                            is_t & rel_ok[a], adm.tas_usage[a], 0
                        )
                        drop = drop & (~do_tas | tas_feas(t_try))
                        t_state = jnp.where(drop, t_try, t_state)
                    u_f = jnp.where(drop, u_t, u_f)
                    return (u_f, t_state), drop

                fb_mask = pre & (pos < k_star)
                (u_fb, _t_fb), drops_rev = jax.lax.scan(
                    fb, (u_end, t_end), (fb_mask[::-1], ord_[::-1])
                )
                drops = drops_rev[::-1]
                victims_o = pre & ~drops & success
                victims = jnp.zeros(a_n, bool).at[ord_].set(victims_o)
                return success, victims

            ok1, v1 = attempt(first_borrow)
            ok2, v2 = attempt(~first_borrow)
            use2 = ~ok1 & second_on & ok2
            success = ok1 | use2
            victims = jnp.where(success, jnp.where(ok1, v1, v2), False)
            return success, victims, variant

        # Full multi-resource search (with the tas_fits probe for TAS
        # entries) + per-cell oracle probes (quota-only, matching the
        # reference SimulatePreemption).
        eye = jnp.eye(r_n, dtype=bool)
        cell_active_p = eye & full_active[None, :]
        cell_contested_p = eye & contested_full[None, :]
        cell_req = jnp.where(cell_active_p, req[None, :], 0)
        full_success, full_victims, variant = search(
            full_active, contested_full,
            jnp.where(full_active, req, 0), tas_probe=with_tas,
        )
        cell_success, cell_victims, _vc = jax.vmap(search)(
            cell_active_p, cell_contested_p, cell_req
        )  # [R], [R, A]

        # Post-removal borrow height per cell: the generalized
        # FindHeightOfLowestSubtreeThatFits walk (lend-free: per-level
        # local available is zero, so `remaining` stays the request).
        def height_walk(u_f_r, val):
            """u_f_r: [D+1] usage along the preemptor chain for one
            resource; val: scalar request."""
            borrowing0 = sat_add(u_f_r[0], val) > sq_c_r
            fits_lvls = (
                (sat_add(u_f_r[1:], val) <= sq_chain_r[1:])
                & is_real_lvl[1:]
            )
            any_fit = jnp.any(fits_lvls)
            first = jnp.argmax(fits_lvls).astype(jnp.int32) + 1
            h_up = jnp.where(
                any_fit, height_n[chain_c[first]],
                height_n[chain_c[quota_ops.MAX_DEPTH]],
            )
            return jnp.where(~borrowing0 | ~has_par, 0, h_up)

        sq_chain_r = None  # bound per-resource below
        sq_c_r = None
        h_pre = jnp.zeros(r_n, jnp.int32)
        h_post = jnp.zeros(r_n, jnp.int32)
        rem_nodes = jnp.einsum(
            "ra,na,as->rns",
            cell_victims.astype(jnp.int64), in_sub[:, adm.cq], au,
        )  # [R, N, R'] removal at every node per cell probe's victim set
        for r in range(r_n):
            sq_chain_r = sq_f[chain_c, r]
            sq_c_r = sq_f[c, r]
            u_pre_chain = u0_f[chain_c, r]
            u_post_chain = u_pre_chain - rem_nodes[r][chain_c, r]
            h_pre = h_pre.at[r].set(height_walk(u_pre_chain, req[r]))
            h_post = h_post.at[r].set(height_walk(u_post_chain, req[r]))
        cell_borrow = jnp.where(
            contested_full,
            jnp.where(cell_success, h_post, h_pre),
            h_pre,
        )
        borrow_after = jnp.max(
            jnp.where(full_active, cell_borrow, 0)
        ).astype(jnp.int32)

        all_cells_ok = jnp.all(~contested_full | cell_success)
        resolved = elig_w & (
            (considered == 1) | (stopped_at_praw & all_cells_ok)
        )
        success = resolved & full_success
        victims = jnp.where(success, full_victims, False)
        resolved_nc = resolved & ~full_success

        return victims, jnp.where(victims, variant, 0), success, \
            resolved_nc, resolved, borrow_after

    victims, variant, success, resolved_nc, resolved, borrow_after = \
        jax.vmap(per_w)(
            arrays.w_cq, chosen_flavor, arrays.w_req, arrays.w_priority,
            arrays.w_timestamp, eligible, praw_stop, considered,
            tas_in["do_tas"], tas_in["t_row"], tas_in["t_req"],
            tas_in["t_cnt"], tas_in["t_ssz"], tas_in["t_sl"],
            tas_in["t_rl"], tas_in["t_rq"], tas_in["t_un"],
            tas_in["t_cap"], tas_in["t_sz"],
        )
    return PreemptTargets(victims, variant, success, resolved_nc, resolved,
                          borrow_after)
