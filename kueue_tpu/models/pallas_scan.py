"""Pallas TPU kernel for the grouped admission scan.

The XLA ``admit_scan_grouped`` (models/batch_scheduler.py) runs the
order-dependent admission loop as a ``lax.scan`` whose per-step tensors are
tiny ([G, L, R] gathers at north-star scale) — the step cost is dominated
by op-dispatch latency, not compute. This module runs the WHOLE scan as a
single Pallas kernel: each grid program owns one cohort tree (group), its
usage state lives in VMEM for the entire bucket, and every step is a
handful of full-lane VPU row operations. No per-step XLA dispatch, no
HBM round-trips between steps.

Semantics are identical to ``admit_scan_grouped`` for the no-preemption,
no-TAS cycle (the reference fast path, scheduler.go:385 processEntry +
resource_node.go available()/addUsage) and are differential-tested against
it (tests/test_pallas_scan.py).

Int32 discipline: the attached TPU backend cannot pass s64 operands
through a pallas custom call (its X64-rewriting pass does not support
``tpu_custom_call``), so the kernel computes in int32 with saturation at
``CAP32`` standing in for quota_ops.CAP. ``fits_int32`` checks — host-side,
once per cycle encode — that every quantity and every worst-case
accumulation stays below CAP32, so the int32 math is bit-equivalent to the
int64 path; callers must fall back to the XLA scan when it returns False
(real kueue quantities are canonical milli-units/bytes and can exceed
2**30 — e.g. 1Gi of memory is 2**30 bytes exactly).

Status (PR 17): RETIRED TO OPT-IN. The BENCH_TPU_LIVE ``RecursionError``
(the Mosaic int64->int32 lowering recursion above) was re-probed against
the post-PR-8/11/15 kernel set; with the sequential scans eliminated,
the fixed-point kernels now carry the mega probe and the Pallas variants
no longer earn their live-hardware risk. The module and its interpret-
mode differential tests stay, but the bench probes only dispatch Pallas
when ``KUEUE_TPU_ENABLE_PALLAS=1`` (``opt_in()``); otherwise the mega
probe routes to the fixed-point/grouped kernels. Decision recorded in
docs/perf.md ("Pallas scan: retired to opt-in").
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from kueue_tpu.models import batch_scheduler as bs
from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.ops import quota_ops

# Saturation cap for the in-kernel int32 quota math — the SAME constant
# the dtype-aware saturation helpers clamp at (quota_ops.CAP32), so the
# fits_int32 gate and the int32 arithmetic can never disagree. (1 << 30)
# - 1 so that CAP32 + CAP32 still fits int32; plays the role of
# quota_ops.CAP (UNLIMITED): sat_sub keeps an unlimited minuend
# unlimited, sat_add clamps, and min(with_max_from_parent, avail)
# degenerates to avail for unlimited borrow limits exactly like the
# int64 path.
CAP32 = int(quota_ops.CAP32)

#: Env flag gating live Pallas dispatch in the bench probes (module
#: docstring "Status"): the interpret-mode differentials always run, but
#: live TPU probes skip the Pallas variants unless this is set to "1".
PALLAS_OPT_IN_ENV = "KUEUE_TPU_ENABLE_PALLAS"


def opt_in() -> bool:
    """Whether live Pallas probe dispatch is explicitly enabled."""
    return os.environ.get(PALLAS_OPT_IN_ENV) == "1"


_META_LOCAL_BITS = 16  # low bits of slot meta = local node id
_META_ADMIT = 1 << 16  # entry is FIT, active, in range, not host-deferred
_META_RESERVE = 1 << 17  # entry reserves (NO_CANDIDATES, can't reclaim)
_META_BORROWING = 1 << 18  # nominated assignment borrows


# int32-typed constants: a bare Python literal is a weak-typed scalar
# that materializes as int64 under x64, and this jaxlib's Mosaic
# lowering recurses forever on any in-kernel int64->int32 convert.
_CAP32_I32 = np.int32(CAP32)
_NCAP32_I32 = np.int32(-CAP32)


def _im3(g):
    """Grid->block index map. The zero coordinates must be int32-typed:
    a bare literal 0 is a weak scalar that lowers to an i64 constant
    under x64, giving every generated transform function an
    (i32, i64, i64) func.return that Mosaic fails to legalize."""
    return (g, np.int32(0), np.int32(0))


def _sat32(v):
    return jnp.clip(v, _NCAP32_I32, _CAP32_I32)


def _sadd(a, b):
    return _sat32(a + b)


def _ssub(a, b):
    """a - b with an Unlimited (CAP32) minuend staying Unlimited."""
    return jnp.where(a >= _CAP32_I32, _CAP32_I32, _sat32(a - b))


def fits_int32(arrays: CycleArrays) -> bool:
    """Host-side gate: True when the int32 kernel is bit-exact for this
    cycle. Checks every encoded quantity and the worst-case usage
    accumulation (initial usage + all pending requests + reserves) against
    CAP32. Call once per encode; on False use the XLA int64 scan."""
    tree = arrays.tree
    finite_max = 0
    for t in (tree.nominal, tree.subtree_quota, arrays.usage):
        finite_max = max(finite_max, int(jnp.max(jnp.abs(t))))
    # Limits are CAP (unlimited) where unset; only set limits must fit.
    for t, has in (
        (tree.borrow_limit, tree.has_borrow_limit),
        (tree.lend_limit, tree.has_lend_limit),
    ):
        set_vals = jnp.where(has, jnp.abs(t), 0)
        finite_max = max(finite_max, int(jnp.max(set_vals)))
    req_sum = int(
        jnp.sum(
            jnp.where(arrays.w_active[:, None], arrays.w_req, 0).max(axis=1)
        )
    )
    if arrays.w_cq.shape[0] and int(jnp.max(arrays.w_req)) >= CAP32:
        return False
    # Local node ids pack into the meta word's low bits; the total node
    # count bounds every per-group local id.
    if arrays.tree.parent.shape[0] >= (1 << _META_LOCAL_BITS):
        return False
    # Priorities must be strictly below INT32_MAX so the int32-cast
    # prefilter keeps its "no bucket" sentinel semantics
    # (batch_scheduler.cast_arrays_i32). k8s priorities are int32 API
    # fields, so this only excludes the literal INT32_MAX.
    if arrays.w_cq.shape[0] and int(
        jnp.max(jnp.abs(arrays.w_priority))
    ) >= (1 << 31) - 1:
        return False
    return finite_max + req_sum < CAP32


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _to_g32(x, ga, pad, g_n, nm, fr, frp):
    """[N,F,R] int64 -> grouped, int32, lane-flattened [G, Nm, FRp]."""
    y = x[ga.node_sel]  # [G,Nm,F,R]
    y = jnp.where(ga.local_valid[..., None, None], y, pad)
    y = _sat32(y).astype(jnp.int32).reshape(g_n, nm, fr)
    if frp > fr:
        y = jnp.pad(y, ((0, 0), (0, 0), (0, frp - fr)))
    return y


def _kernel(n_levels, counts_ref, meta_ref, chain_ref, delta_ref, usage_ref,
            lq_ref, sub_ref, bl_ref, nom_ref, uout_ref, aout_ref):
    """One grid program = one cohort tree's whole admission bucket.

    Refs: counts [1,1,1] SMEM; meta [1,1,S] SMEM (packed local-id +
    flags); chain [1,Nm,L] SMEM; delta [1,S,FRp] VMEM (pre-masked per-slot
    request rows on the chosen flavor's lanes); usage/lq/sub/bl/nom
    [1,Nm,FRp] VMEM; outputs uout [1,Nm,FRp], aout [1,S,1].
    """
    L = n_levels
    uout_ref[:] = usage_ref[:]
    aout_ref[:] = jnp.zeros_like(aout_ref)
    cnt = counts_ref[0, 0, 0]

    def step(s, carry):
        meta = meta_ref[0, 0, s]
        c = meta & ((1 << _META_LOCAL_BITS) - 1)
        admit_el = (meta & _META_ADMIT) != 0
        res_el = (meta & _META_RESERVE) != 0
        borrowing = (meta & _META_BORROWING) != 0
        delta = delta_ref[0, pl.ds(s, 1), :]  # [1, FRp]

        nodes = [chain_ref[0, c, i] for i in range(L)]
        u = [uout_ref[0, pl.ds(nodes[i], 1), :] for i in range(L)]
        lq = [lq_ref[0, pl.ds(nodes[i], 1), :] for i in range(L)]
        sub = [sub_ref[0, pl.ds(nodes[i], 1), :] for i in range(L)]
        bl = [bl_ref[0, pl.ds(nodes[i], 1), :] for i in range(L)]
        # chain pads by repeating the root: rep[i] marks chain[i] being the
        # last real node (chain[i] == chain[i+1]).
        rep = [nodes[i] == nodes[i + 1] for i in range(L - 1)]

        l_avail = [jnp.maximum(0, _ssub(lq[i], u[i])) for i in range(L)]

        # available() down the chain, root first (resource_node.go:106).
        # Unlimited borrow limits saturate with_max at CAP32, making the
        # min() a no-op — no has_borrow_limit branch needed.
        avail = _ssub(sub[L - 1], u[L - 1])
        for i in range(L - 2, -1, -1):
            stored = _ssub(sub[i], lq[i])
            uip = jnp.maximum(0, _ssub(u[i], lq[i]))
            with_max = _sadd(_ssub(stored, uip), bl[i])
            stepped = _sadd(l_avail[i], jnp.minimum(with_max, avail))
            avail = jnp.where(rep[i], avail, stepped)

        # Reduce in int32: this jaxlib's Mosaic lowers a bool jnp.all()
        # scalarization through float64 under x64, which it then rejects.
        ok32 = ((delta <= avail) | (delta == 0)).astype(jnp.int32)
        fits = jnp.min(ok32) > 0
        admit = admit_el & fits

        # reserveCapacityForUnreclaimablePreempt (scheduler.go:513).
        nomr = nom_ref[0, pl.ds(c, 1), :]
        res_b = jnp.minimum(delta, _ssub(_sadd(nomr, bl[0]), u[0]))
        res_p = jnp.maximum(0, jnp.minimum(delta, _ssub(nomr, u[0])))
        reserve = jnp.where(borrowing, res_b, res_p)
        reserve = jnp.where(delta > 0, reserve, np.int32(0))

        applied = jnp.where(
            admit, delta, jnp.where(res_el, reserve, jnp.zeros_like(delta))
        )

        # addUsage bubbling (resource_node.go:144): level i+1 receives the
        # part of level i's delta exceeding its pre-update local
        # availability. Stores are guarded so a repeated root row is only
        # written once (u[] rows were loaded pre-update).
        cur = applied
        real = None
        for i in range(L):
            d_i = cur
            new_row = u[i] + d_i
            if i == 0:
                uout_ref[0, pl.ds(nodes[0], 1), :] = new_row
                real = jnp.bool_(True)
            else:
                real = real & ~rep[i - 1]

                @pl.when(real)
                def _(new_row=new_row, node=nodes[i]):
                    uout_ref[0, pl.ds(node, 1), :] = new_row

            if i < L - 1:
                cur = jnp.where(
                    rep[i],
                    jnp.zeros_like(cur),
                    jnp.maximum(0, _ssub(cur, l_avail[i])),
                )

        # int32 literals: under x64 a weak-int where() yields int64, and
        # this jaxlib's Mosaic lowering recurses forever on an in-kernel
        # int64->int32 convert (no 64-bit trunci rule).
        aout_ref[0, pl.ds(s, 1), :] = jnp.where(
            admit, jnp.int32(1), jnp.int32(0)
        ).reshape(1, 1)
        return carry

    jax.lax.fori_loop(np.int32(0), cnt, step, np.int32(0))


def pallas_admit_scan(
    arrays: CycleArrays,
    ga: bs.GroupArrays,
    nom: bs.NominateResult,
    usage: jnp.ndarray,
    order: jnp.ndarray,
    s_max: int,
    n_levels: int = quota_ops.MAX_DEPTH + 1,
    interpret: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Drop-in for ``admit_scan_grouped`` (no-preempt, no-TAS, int32-safe
    cycles only — see ``fits_int32``). Returns (final_usage int64,
    admitted bool[W], preempting bool[W] all-False)."""
    tree = arrays.tree
    w_n = arrays.w_cq.shape[0]
    g_n, nm = ga.node_sel.shape
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
    L = n_levels
    fr = f_n * r_n
    frp = _round_up(fr, 128)
    S = s_max

    # --- XLA-side prep: grouped static tensors (int32 lane rows) ---
    gargs = (ga, 0, g_n, nm, fr, frp)
    lq_g = _to_g32(quota_ops.local_quota(tree), ga, 0, g_n, nm, fr, frp)
    sub_g = _to_g32(tree.subtree_quota, ga, 0, g_n, nm, fr, frp)
    bl_g = _to_g32(tree.borrow_limit, ga, quota_ops.CAP, g_n, nm, fr, frp)
    nom_g = _to_g32(tree.nominal, ga, 0, g_n, nm, fr, frp)
    usage_g = _to_g32(usage, ga, 0, g_n, nm, fr, frp)

    # --- slot bucketing (same one-sort layout as admit_scan_grouped) ---
    # int32 (group, rank) keys when they fit: the sort is bandwidth-bound,
    # so halving the key width matters at north-star scale.
    kdt = jnp.int32 if (g_n + 1) * (w_n + 1) < (1 << 31) else jnp.int64
    rank = jnp.zeros(w_n, dtype=kdt).at[order].set(
        jnp.arange(w_n, dtype=kdt)
    )
    g_w = ga.flat_to_group[arrays.w_cq].astype(kdt)
    sort_key = jnp.where(
        arrays.w_active, g_w * w_n + rank, kdt(g_n) * w_n + w_n
    )
    grouped_order = jnp.argsort(sort_key).astype(jnp.int32)
    counts = jnp.zeros(g_n, dtype=jnp.int32).at[
        ga.flat_to_group[arrays.w_cq]
    ].add(arrays.w_active.astype(jnp.int32), mode="drop")
    starts = jnp.cumsum(counts) - counts

    slot_idx = starts[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    wslot = grouped_order[jnp.clip(slot_idx, 0, w_n - 1)]  # [G,S]
    in_range = jnp.arange(S)[None, :] < counts[:, None]

    c_w = arrays.w_cq[wslot]  # [G,S]
    c_local = ga.flat_to_local[c_w].astype(jnp.int32)
    f = nom.chosen_flavor[wslot]
    pm = nom.best_pmode[wslot]
    valid = in_range & arrays.w_active[wslot]
    deferred = nom.needs_host[wslot]
    admit_el = valid & (pm == bs.P_FIT) & ~deferred
    res_el = (
        valid
        & (pm == bs.P_NO_CANDIDATES)
        & ~arrays.can_always_reclaim[c_w]
        & ~deferred
    )
    borrowing = nom.best_borrow[wslot] > 0
    meta = (
        c_local
        | jnp.where(admit_el, _META_ADMIT, 0)
        | jnp.where(res_el, _META_RESERVE, 0)
        | jnp.where(borrowing, _META_BORROWING, 0)
    ).astype(jnp.int32)

    req = arrays.w_req[wslot]  # [G,S,R] i64
    cell = (f[..., None] >= 0) & (req > 0) & arrays.covered[c_w]
    delta_fr = jnp.where(
        (jnp.arange(f_n, dtype=jnp.int32)[None, None, :, None]
         == f[..., None, None])
        & cell[:, :, None, :],
        req[:, :, None, :],
        0,
    )  # [G,S,F,R]
    delta = _sat32(delta_fr).astype(jnp.int32).reshape(g_n, S, fr)
    if frp > fr:
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, frp - fr)))

    chain_l = ga.chain_local[:, :, :L].astype(jnp.int32)  # [G,Nm,L]
    counts2 = counts.reshape(g_n, 1, 1)
    meta3 = meta.reshape(g_n, 1, S)

    out_usage, out_admit = pl.pallas_call(
        functools.partial(_kernel, L),
        grid=(g_n,),
        in_specs=[
            pl.BlockSpec((1, 1, 1), _im3,
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, S), _im3,
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, nm, L), _im3,
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, S, frp), _im3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nm, frp), _im3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nm, frp), _im3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nm, frp), _im3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nm, frp), _im3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, nm, frp), _im3,
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, nm, frp), _im3,
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, S, 1), _im3,
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g_n, nm, frp), jnp.int32),
            jax.ShapeDtypeStruct((g_n, S, 1), jnp.int32),
        ],
        interpret=interpret,
    )(counts2, meta3, chain_l, delta, usage_g, lq_g, sub_g, bl_g, nom_g)

    admit_slots = out_admit[..., 0] != 0  # [G,S]
    w_out = jnp.where(admit_slots & in_range, wslot, w_n)
    admitted = jnp.zeros(w_n + 1, dtype=bool).at[w_out.ravel()].max(
        admit_slots.ravel(), mode="drop"
    )[:w_n]

    final_g = out_usage[:, :, :fr].astype(jnp.int64).reshape(
        g_n, nm, f_n, r_n
    )
    final_usage = final_g[ga.flat_to_group, ga.flat_to_local]
    final_usage = jnp.where(
        tree.active[:, None, None], final_usage, usage
    )
    preempting = jnp.zeros(w_n, dtype=bool)
    return final_usage, admitted, preempting


def make_pallas_cycle(s_max: int, n_levels: int = quota_ops.MAX_DEPTH + 1,
                      interpret: bool = False, i32: bool = False):
    """Jittable no-preempt cycle with the Pallas admission scan. Same
    contract as ``bs.make_grouped_cycle(s_max, preempt=False)``; callers
    gate on ``fits_int32(arrays)``.

    ``i32=True`` additionally runs the nominate/order phases on
    int32-cast quota tensors (bs.cast_arrays_i32) — exact under the same
    fits_int32 gate and half the HBM traffic of the [W,F,R]-wide phase."""

    def impl(arrays: CycleArrays, ga: bs.GroupArrays) -> bs.CycleOutputs:
        if i32:
            arrays = bs.cast_arrays_i32(arrays)
        usage = arrays.usage
        nom = bs.nominate(arrays, usage, n_levels=n_levels)
        order = bs.admission_order(arrays, nom)
        final_usage, admitted, preempting = pallas_admit_scan(
            arrays, ga, nom, usage, order, s_max, n_levels=n_levels,
            interpret=interpret,
        )
        outcome = jnp.where(
            ~arrays.w_active,
            bs.OUT_NOFIT,
            jnp.where(
                nom.needs_host,
                bs.OUT_NEEDS_HOST,
                jnp.where(
                    admitted,
                    bs.OUT_ADMITTED,
                    jnp.where(
                        nom.best_pmode == bs.P_FIT,
                        bs.OUT_FIT_SKIPPED,
                        jnp.where(
                            nom.best_pmode == bs.P_NO_CANDIDATES,
                            bs.OUT_NO_CANDIDATES,
                            bs.OUT_NOFIT,
                        ),
                    ),
                ),
            ),
        ).astype(jnp.int32)
        return bs.CycleOutputs(
            outcome=outcome,
            chosen_flavor=nom.chosen_flavor,
            borrow=nom.best_borrow,
            tried_flavor_idx=nom.tried_flavor_idx,
            usage=final_usage,
            order=order,
        )

    return impl
