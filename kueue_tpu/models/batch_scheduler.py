"""The batched scheduling-cycle kernel — the TPU hot loop.

This reformulates the reference's per-workload scheduling cycle
(pkg/scheduler/scheduler.go:300 + flavorassigner.go findFlavorForPodSets) as
one compiled XLA program over dense (workload x flavor x resource) tensors:

  1. ``nominate``: flavor assignment for ALL workloads at once — per-cell
     Fit/Preempt/NoFit modes, borrow heights (cohort-subtree walk), flavor
     fungibility stop rules and preference scores, fully vectorized.
  2. ``admission order``: the classical iterator's sort (fewest borrows,
     priority, FIFO) as a lexsort.
  3. ``admit scan``: the order-dependent part — earlier entries consume
     capacity — as a lax.scan whose body does a MAX_DEPTH-bounded
     ancestor-chain walk (gathers + one scatter-add) instead of the
     reference's pointer-chasing tree mutation.

Exactness: decisions are bit-identical to the host-exact scheduler for all
device-compatible workloads on CQs that cannot preempt (the oracle outcome
is then deterministic). Workloads needing a preemption oracle are flagged
``needs_host`` and handled by the host path. Integer quota math is exact
int64 end to end.

Outcome codes returned per workload:
  0 = NOFIT (requeue), 1 = NO_CANDIDATES (requeue, capacity reserved),
  2 = NEEDS_HOST (preemption path), 3 = FIT_SKIPPED (lost the race in-cycle),
  4 = ADMITTED.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.models import slot_tas as _slot_tas
from kueue_tpu.ops import quota_ops
from kueue_tpu.ops.quota_ops import (
    CAP,
    MAX_DEPTH,
    QuotaTreeArrays,
    ancestor_chain,
    sat_add,
    sat_sub,
)

# preemptionMode codes (match kueue_tpu.scheduler.flavorassigner.PMode).
P_NOFIT = 0
P_NO_CANDIDATES = 1
P_PREEMPT_RAW = 2  # preemption possible; oracle outcome unknown on device
P_PREEMPT_OK = 3  # device-resolved preemption with a victim set
P_FIT = 4

OUT_NOFIT = 0
OUT_NO_CANDIDATES = 1
OUT_NEEDS_HOST = 2
OUT_FIT_SKIPPED = 3
OUT_ADMITTED = 4
OUT_PREEMPTING = 5  # victims designated; entry waits for their eviction
OUT_SHADOWED = 6  # fair tournament: a later same-CQ entry displaced this one

_BIG = jnp.int64(1) << 40
_NEG_INF = -(jnp.int64(1) << 60)


class NominateResult(NamedTuple):
    chosen_flavor: jnp.ndarray  # i32[W] global flavor id (-1 none)
    best_pmode: jnp.ndarray  # i32[W]
    best_borrow: jnp.ndarray  # i32[W]
    needs_host: jnp.ndarray  # bool[W]
    tried_flavor_idx: jnp.ndarray  # i32[W] (-1 = wrapped)
    # Device-preemption eligibility signals (see models/preempt_kernel.py):
    praw_count: jnp.ndarray  # i32[W] flavors seen with raw preempt mode
    praw_stop: jnp.ndarray  # bool[W] scan stopped at a raw-preempt flavor
    considered: jnp.ndarray  # i32[W] flavors considered by the scan
    # Per-slot results (multi-podset / multi-resource-group cycles only;
    # None on the dense legacy layout). Slot order matches host
    # evaluation order — see encode._workload_slots.
    s_flavor: jnp.ndarray = None  # i32[W,S] chosen flavor per slot
    s_pmode: jnp.ndarray = None  # i32[W,S]
    s_borrow: jnp.ndarray = None  # i32[W,S]
    s_tried: jnp.ndarray = None  # i32[W,S] (-1 = wrapped)
    # Per-slot preemption-eligibility signals (device victim search):
    s_praw_count: jnp.ndarray = None  # i32[W,S] praw flavors seen by slot
    s_praw_stop: jnp.ndarray = None  # bool[W,S] slot stopped at praw flavor
    s_considered: jnp.ndarray = None  # i32[W,S] flavors considered by slot


class CycleOutputs(NamedTuple):
    outcome: jnp.ndarray  # i32[W]
    chosen_flavor: jnp.ndarray  # i32[W]
    borrow: jnp.ndarray  # i32[W]
    tried_flavor_idx: jnp.ndarray  # i32[W]
    usage: jnp.ndarray  # i64[N,F,R] post-cycle
    order: jnp.ndarray  # i32[W] processing order (diagnostics)
    # Device-preemption outputs (None on the no-preempt kernels).
    victims: jnp.ndarray = None  # bool[W,A] victim set of OUT_PREEMPTING rows
    victim_variant: jnp.ndarray = None  # i32[W,A] preemption reason codes
    # Partial admission: reduced pod count (-1 = full count / not found).
    partial_count: jnp.ndarray = None  # i64[W]
    # Per-slot decode outputs (slot-layout cycles only).
    s_flavor: jnp.ndarray = None  # i32[W,S]
    s_pmode: jnp.ndarray = None  # i32[W,S]
    s_tried: jnp.ndarray = None  # i32[W,S]
    # Device-TAS decode: pods placed per leaf domain (device leaf order)
    # for each admitted TAS entry — the placement kernel's own takes, so
    # the driver maps them straight to TopologyAssignment domains instead
    # of replaying the host placement engine (None when no TAS).
    tas_takes: jnp.ndarray = None  # i32[W,D]
    # LWS leader leaf one-hot per admitted leader-group entry (None when
    # no leader-group entry this cycle).
    tas_leader_takes: jnp.ndarray = None  # i32[W,D]
    # Per-slot takes for generic multi-podset TAS entries (None when no
    # such entry this cycle).
    s_tas_takes: jnp.ndarray = None  # i32[W,S,D]
    # Fixed-point kernels only: did the bounds iteration settle every
    # entry within the rounds cap, and how many rounds it took. None on
    # the scan kernels (the driver treats None as trivially converged).
    converged: jnp.ndarray = None  # bool[] scalar
    fp_rounds: jnp.ndarray = None  # i32[] scalar
    # Max TAS slot-placement conflict rounds across scan steps (None when
    # the cycle carries no multi-podset TAS planes). 0 = every slot
    # settled in the batched pass's first placement ([slot-fp] suffix).
    slot_rounds: jnp.ndarray = None  # i32[] scalar


def _pref_score(pmode, borrow, pref_preempt_over_borrow):
    """Granular-mode preference as a single i64 score; higher = preferred
    (flavorassigner.go isPreferred). NOFIT is absolute bottom."""
    bob = pmode * _BIG - borrow
    pob = -borrow * _BIG + pmode
    score = jnp.where(pref_preempt_over_borrow, pob, bob)
    return jnp.where(pmode == P_NOFIT, _NEG_INF, score)


_SNEG32 = jnp.int32(-(1 << 30))
_I32_MAX = jnp.int32((1 << 31) - 1)


def cast_arrays_i32(arrays: CycleArrays) -> CycleArrays:
    """Quota tensors to int32 with CAP->CAP32 saturation semantics.

    Exactness gate: ``pallas_scan.fits_int32(arrays)`` (every quantity and
    worst-case accumulation below CAP32, priorities below INT32_MAX).
    Halves the HBM traffic of the [W,F,R]-wide nominate phase and the
    sort-key widths — the cycle-dominant cost at north-star scale is
    bandwidth, not FLOPs. Only the no-preempt/no-TAS class uses this
    (the pallas cycle); preemption/TAS kernels keep int64 inputs."""
    tree = arrays.tree

    def sat32(x):
        return jnp.clip(x, -quota_ops.CAP32, quota_ops.CAP32).astype(
            jnp.int32
        )

    def lim32(x, has):
        return jnp.where(
            has, sat32(x), quota_ops.CAP32
        ).astype(jnp.int32)

    tree32 = tree._replace(
        nominal=sat32(tree.nominal),
        subtree_quota=sat32(tree.subtree_quota),
        borrow_limit=lim32(tree.borrow_limit, tree.has_borrow_limit),
        lend_limit=lim32(tree.lend_limit, tree.has_lend_limit),
    )
    rep = dict(
        tree=tree32,
        usage=sat32(arrays.usage),
        nominal_cq=sat32(arrays.nominal_cq),
        w_req=sat32(arrays.w_req),
        usage_by_prio=sat32(arrays.usage_by_prio),
        # INT32_MAX keeps the "no bucket" sentinel semantics: fits_int32
        # guarantees every real priority is strictly below it.
        prio_cuts=jnp.minimum(arrays.prio_cuts, _I32_MAX).astype(jnp.int32),
        w_priority=arrays.w_priority.astype(jnp.int32),
    )
    if getattr(arrays, "s_req", None) is not None:
        rep["s_req"] = sat32(arrays.s_req)
    return arrays._replace(**rep)


def _policy_exists(pol, mincut, anyb, prio):
    """Preemption-candidate existence per policy code (0=Never,
    1=LowerPriority, 2=LowerOrNewerEqual superset, 3=Any). pol: i32[W];
    mincut/anyb: [W,F,R]; prio: i64[W]."""
    p = pol[:, None, None]
    return jnp.where(
        p == 3, anyb,
        jnp.where(
            p == 2, mincut <= prio[:, None, None],
            jnp.where(p == 1, mincut < prio[:, None, None], False),
        ),
    )


def _fungibility_scan(rep_pmode, rep_borrow, pob_w, f_k, n_fl, start,
                      preempt_try_next, borrow_try_next):
    """First-stop/argmax fungibility scan over the [W,K] preference axis
    (flavorassigner.go:1142 shouldTryNextFlavor + the strictly-preferred
    best keep). Shared by the legacy and slot nominate paths — any rule
    change lands in both automatically. Returns
    (b_f, b_pm, b_bw, att, praw_n, praw_stop, n_cons).

    Per-workload fancy-index gathers (``x[w_iota, f_k]``) lower to scalar
    gathers on TPU and dominated the cycle (~18 ms each at 50k); the
    [W,F]->[W,K] permutation is instead one onehot contraction of a
    packed (pmode, borrow) payload, and the per-row scalar picks are
    K-onehot masked reductions — elementwise + reduce only."""
    w_n, k_n = f_k.shape
    f_n = rep_pmode.shape[1]
    k_iota = jnp.arange(k_n, dtype=jnp.int32)
    pos_valid = (
        (k_iota[None, :] < n_fl[:, None])
        & (k_iota[None, :] >= start[:, None])
    )
    # pmode <= 4 and borrow <= MAX_DEPTH (8) pack into 7 bits.
    payload = (rep_pmode * 16 + rep_borrow).astype(jnp.int32)  # [W,F]
    oh_f = f_k[:, :, None] == jnp.arange(f_n, dtype=f_k.dtype)[None, None, :]
    pay_k = jnp.sum(jnp.where(oh_f, payload[:, None, :], 0), axis=2)
    pm_k = pay_k // 16
    bw_k = pay_k % 16
    sc_k = jnp.where(pob_w[:, None], -bw_k * 16 + pm_k, pm_k * 16 - bw_k)
    sc_k = jnp.where(pm_k == P_NOFIT, _SNEG32, sc_k).astype(jnp.int32)
    should_try_next = (
        (pm_k == P_NOFIT)
        | (pm_k == P_NO_CANDIDATES)
        | ((pm_k == P_PREEMPT_RAW) & preempt_try_next[:, None])
        | ((bw_k > 0) & borrow_try_next[:, None])
    )
    stop_k = pos_valid & ~should_try_next
    any_stop = jnp.any(stop_k, axis=1)
    kstop = jnp.where(
        any_stop, jnp.argmax(stop_k, axis=1).astype(jnp.int32),
        jnp.int32(k_n),
    )
    considered = pos_valid & (k_iota[None, :] <= kstop[:, None])
    n_cons = jnp.sum(considered, axis=1).astype(jnp.int32)
    att = jnp.max(
        jnp.where(considered, k_iota[None, :], -1), axis=1
    ).astype(jnp.int32)
    is_praw_k = considered & (pm_k == P_PREEMPT_RAW)
    praw_n = jnp.sum(is_praw_k, axis=1).astype(jnp.int32)
    kstop_c = jnp.clip(kstop, 0, k_n - 1)
    oh_stop = k_iota[None, :] == kstop_c[:, None]
    pm_stop = jnp.sum(jnp.where(oh_stop, pm_k, 0), axis=1)
    praw_stop = any_stop & (pm_stop == P_PREEMPT_RAW)

    # Best-scoring considered flavor, first occurrence winning ties (the
    # host scan's strict-> update); a stop takes its own flavor outright.
    sc_masked = jnp.where(considered, sc_k, _SNEG32)
    k_best = jnp.argmax(sc_masked, axis=1).astype(jnp.int32)
    none_considered = ~jnp.any(considered & (sc_k > _SNEG32), axis=1)
    k_take = jnp.where(any_stop, kstop_c, jnp.clip(k_best, 0, k_n - 1))
    oh_take = k_iota[None, :] == k_take[:, None]

    def pick(v):
        return jnp.sum(jnp.where(oh_take, v, 0), axis=1)

    miss = none_considered & ~any_stop
    b_f = jnp.where(miss, -1, pick(f_k)).astype(jnp.int32)
    b_pm = jnp.where(miss, P_NOFIT, pick(pm_k)).astype(jnp.int32)
    b_bw = jnp.where(miss, 0, pick(bw_k)).astype(jnp.int32)
    return b_f, b_pm, b_bw, att, praw_n, praw_stop, n_cons


def _prefilter_aggregates(arrays: CycleArrays, usage: jnp.ndarray):
    """Preemption-candidate prefilter aggregates, once per cycle [N,F,R]:
    the minimum priority cut among buckets with same-CQ admitted usage
    (resolves policy thresholds by comparison) and the equivalent over
    "borrowing CQs elsewhere in this tree" counts. A sound subset of
    reference preemption_oracle.go outcomes; any possible candidate
    still routes to the host path."""
    tree = arrays.tree
    parent_or_self = jnp.where(
        tree.parent < 0, jnp.arange(tree.n_nodes), tree.parent
    )
    root_of = jnp.arange(tree.n_nodes)
    for _ in range(MAX_DEPTH):
        root_of = parent_or_self[root_of]
    cq_borrowing = usage > tree.subtree_quota  # [N,F,R] not-within-nominal
    contrib = (
        cq_borrowing[..., None] & (arrays.usage_by_prio > 0)
    )  # [N,F,R,B]
    tree_count = jnp.zeros_like(contrib, dtype=jnp.int32).at[root_of].add(
        contrib.astype(jnp.int32), mode="drop"
    )  # indexed by root node id
    cuts = arrays.prio_cuts  # i64[B] sorted ascending (i32 in cast mode)
    # "No bucket" sentinel: must exceed every real priority; dtype-max
    # keeps the comparison in the cuts dtype (no silent i64 promotion on
    # the [W,F,R]-wide gathers in the int32-cast mode).
    _PINF = jnp.asarray(jnp.iinfo(cuts.dtype).max, cuts.dtype)
    has_same = arrays.usage_by_prio > 0  # [N,F,R,B]
    same_mincut = jnp.min(
        jnp.where(has_same, cuts, _PINF), axis=-1
    )  # [N,F,R]
    same_any = jnp.any(has_same, axis=-1)
    has_other = (tree_count[root_of] - contrib.astype(jnp.int32)) > 0
    other_mincut = jnp.min(jnp.where(has_other, cuts, _PINF), axis=-1)
    other_any = jnp.any(has_other, axis=-1)
    return same_mincut, same_any, other_mincut, other_any


def nominate(arrays: CycleArrays, usage: jnp.ndarray,
             n_levels: int = MAX_DEPTH + 1) -> NominateResult:
    """Vectorized flavor assignment for every workload against the
    cycle-start usage (reference scheduler.go:629 nominate +
    flavorassigner.go:946 findFlavorForPodSets).

    Flat [W,·] formulation: the per-workload fungibility scan is a
    first-stop/argmax computation over the [W,K] preference axis, the
    preemption-candidate prefilter reads per-cell minimum-priority-cut
    aggregates precomputed once per cycle, and preference scores are small
    int32 keys — no inner lax.scan and no [W,F,R,B] temporaries.

    Slot-layout cycles (multi-podset / multi-resource-group entries
    present) dispatch to the slot-sequential variant."""
    if arrays.s_req is not None:
        return _nominate_slots(arrays, usage, n_levels)
    tree = arrays.tree
    w_n = arrays.w_cq.shape[0]
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
    avail_all = quota_ops.available_all(tree, usage)  # [N,F,R]
    pot_all = quota_ops.potential_available_all(tree)  # [N,F,R]
    w_iota = jnp.arange(w_n)

    same_mincut, same_any, other_mincut, other_any = _prefilter_aggregates(
        arrays, usage
    )

    # ---- per-cell modes/heights, [W,F,R] ----------------------------------
    c = arrays.w_cq
    req = arrays.w_req  # i64[W,R]
    prio = arrays.w_priority
    req_cell = jnp.broadcast_to(req[:, None, :], (w_n, f_n, r_n))
    cell_active = (req[:, None, :] > 0) & arrays.covered[c][:, None, :]

    height, proper = jax.vmap(
        lambda cc, rq: quota_ops.borrow_height(
            tree, usage, cc, rq, n_levels=n_levels
        )
    )(c, req_cell)

    no_fit = req_cell > pot_all[c]
    fit = req_cell <= avail_all[c]
    preempt_gate = (
        (arrays.nominal_cq[c] >= req_cell)
        | proper
        | arrays.can_preempt_while_borrowing[c][:, None, None]
    )
    pmode_cell = jnp.where(
        fit,
        P_FIT,
        jnp.where(
            no_fit, P_NOFIT,
            jnp.where(preempt_gate, P_PREEMPT_RAW, P_NOFIT),
        ),
    ).astype(jnp.int32)
    # CQs that can never find preemption targets resolve the oracle on
    # device: NoCandidates, borrow from the no-preemption fit search.
    pmode_cell = jnp.where(
        (pmode_cell == P_PREEMPT_RAW)
        & arrays.never_preempts[c][:, None, None],
        P_NO_CANDIDATES,
        pmode_cell,
    )

    same_exists = _policy_exists(arrays.policy_within[c], same_mincut[c],
                                 same_any[c], prio)
    cross_exists = _policy_exists(arrays.policy_reclaim[c],
                                  other_mincut[c], other_any[c], prio)
    no_candidates = arrays.prefilter_valid & ~(same_exists | cross_exists)
    pmode_cell = jnp.where(
        (pmode_cell == P_PREEMPT_RAW) & no_candidates,
        P_NO_CANDIDATES,
        pmode_cell,
    )
    borrow_cell = height.astype(jnp.int32)

    # ---- representative (worst) cell per flavor, small-int scores --------
    # Lexicographic (mode, borrow) preference as an int32 key: borrow
    # heights are bounded by MAX_DEPTH, so 16 separates the components.
    _SNEG = jnp.int32(-(1 << 30))
    pob = arrays.pref_preempt_over_borrow[c][:, None, None]

    def score_of(pm, bw):
        s = jnp.where(pob, -bw * 16 + pm, pm * 16 - bw)
        return jnp.where(pm == P_NOFIT, _SNEG, s).astype(jnp.int32)

    score_cell = score_of(pmode_cell, borrow_cell)
    best_inactive = jnp.where(pob, jnp.int32(P_FIT), jnp.int32(P_FIT * 16))
    score_cell = jnp.where(cell_active, score_cell,
                           jnp.broadcast_to(best_inactive, score_cell.shape))
    rep_idx = jnp.argmin(score_cell, axis=2)  # [W,F] worst resource
    # Extract the argmin cell's (pmode, borrow) with an R-onehot masked
    # reduction — the [W,F]-indexed gather lowers to 1.6M scalar gathers
    # on TPU (~20 ms at 50k); the onehot is fused elementwise.
    oh_r = (
        jnp.arange(r_n, dtype=jnp.int32)[None, None, :]
        == rep_idx[..., None]
    )
    rep_pmode = jnp.sum(jnp.where(oh_r, pmode_cell, 0), axis=2)
    rep_borrow = jnp.sum(jnp.where(oh_r, borrow_cell, 0), axis=2)
    # A flavor failing taints/affinity is NOFIT outright
    # (checkFlavorForPodSets precedes the quota loop).
    rep_pmode = jnp.where(arrays.w_elig, rep_pmode, P_NOFIT)
    rep_borrow = jnp.where(arrays.w_elig, rep_borrow, 0)

    # ---- fungibility scan as first-stop/argmax over [W,K] ----------------
    b_f, b_pm, b_bw, att, praw_n, praw_stop, n_cons = _fungibility_scan(
        rep_pmode, rep_borrow, arrays.pref_preempt_over_borrow[c],
        arrays.flavor_at[c],
        arrays.n_flavors[c], arrays.w_start_flavor,
        arrays.when_can_preempt_try_next[c],
        arrays.when_can_borrow_try_next[c],
    )
    seen_praw = praw_n > 0
    needs_host = (seen_praw | (b_pm == P_PREEMPT_RAW)) & arrays.w_active
    tried = jnp.where(att == arrays.n_flavors[c] - 1, -1, att)
    b_pm = jnp.where(arrays.w_active, b_pm, P_NOFIT)
    return NominateResult(b_f, b_pm.astype(jnp.int32),
                          b_bw, needs_host, tried,
                          praw_n, praw_stop, n_cons)


def _nominate_slots(arrays: CycleArrays, usage: jnp.ndarray,
                    n_levels: int = MAX_DEPTH + 1) -> NominateResult:
    """Slot-sequential flavor assignment (flavorassigner.go:712 Assign over
    podset groups x resource groups): each slot runs the same vectorized
    fungibility scan as the legacy path, with earlier slots' assigned
    usage folded into the requested value per cell — the host's
    assignment.usage accumulation, where _fits_resource_quota checks
    ``val = assumed + request`` (flavorassigner.go:1213). Slot order
    matches host evaluation order, so the early-return on a failed group
    is modeled by the ``done`` prefix; the workload-level mode is the
    min over processed slots (Assignment.RepresentativeMode) and the
    borrow is the max over assigned flavors (flavorassigner.go:901)."""
    tree = arrays.tree
    w_n = arrays.w_cq.shape[0]
    s_n = arrays.s_req.shape[1]
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
    avail_all = quota_ops.available_all(tree, usage)  # [N,F,R]
    pot_all = quota_ops.potential_available_all(tree)  # [N,F,R]
    w_iota = jnp.arange(w_n)
    f_iota = jnp.arange(f_n)
    c = arrays.w_cq
    prio = arrays.w_priority

    same_mincut, same_any, other_mincut, other_any = _prefilter_aggregates(
        arrays, usage
    )

    same_exists = _policy_exists(arrays.policy_within[c], same_mincut[c],
                                 same_any[c], prio)
    cross_exists = _policy_exists(arrays.policy_reclaim[c],
                                  other_mincut[c], other_any[c], prio)
    no_candidates = arrays.prefilter_valid & ~(same_exists | cross_exists)

    pob3 = arrays.pref_preempt_over_borrow[c][:, None, None]
    cpwb3 = arrays.can_preempt_while_borrowing[c][:, None, None]
    nevp3 = arrays.never_preempts[c][:, None, None]
    _SNEG = _SNEG32

    def score_of(pm, bw):
        sc = jnp.where(pob3, -bw * 16 + pm, pm * 16 - bw)
        return jnp.where(pm == P_NOFIT, _SNEG, sc).astype(jnp.int32)

    acc = jnp.zeros((w_n, f_n, r_n), dtype=jnp.int64)
    outs = []
    for s in range(s_n):
        req = arrays.s_req[:, s]  # [W,R]
        val = req[:, None, :] + acc  # [W,F,R]
        height, proper = jax.vmap(
            lambda cc, rq: quota_ops.borrow_height(
                tree, usage, cc, rq, n_levels=n_levels
            )
        )(c, val)
        no_fit = val > pot_all[c]
        fit = val <= avail_all[c]
        preempt_gate = (arrays.nominal_cq[c] >= val) | proper | cpwb3
        pmode_cell = jnp.where(
            fit, P_FIT,
            jnp.where(no_fit, P_NOFIT,
                      jnp.where(preempt_gate, P_PREEMPT_RAW, P_NOFIT)),
        ).astype(jnp.int32)
        pmode_cell = jnp.where(
            (pmode_cell == P_PREEMPT_RAW) & nevp3,
            P_NO_CANDIDATES, pmode_cell,
        )
        pmode_cell = jnp.where(
            (pmode_cell == P_PREEMPT_RAW) & no_candidates,
            P_NO_CANDIDATES, pmode_cell,
        )
        borrow_cell = height.astype(jnp.int32)

        score_cell = score_of(pmode_cell, borrow_cell)
        best_inactive = jnp.where(
            pob3, jnp.int32(P_FIT), jnp.int32(P_FIT * 16)
        )
        cell3 = jnp.broadcast_to(req[:, None, :] > 0, score_cell.shape)
        score_cell = jnp.where(
            cell3, score_cell,
            jnp.broadcast_to(best_inactive, score_cell.shape),
        )
        rep_idx = jnp.argmin(score_cell, axis=2)  # [W,F] worst resource
        oh_r = (
            jnp.arange(r_n, dtype=jnp.int32)[None, None, :]
            == rep_idx[..., None]
        )
        rep_pmode = jnp.sum(jnp.where(oh_r, pmode_cell, 0), axis=2)
        rep_borrow = jnp.sum(jnp.where(oh_r, borrow_cell, 0), axis=2)
        elig = arrays.s_elig[:, s]
        rep_pmode = jnp.where(elig, rep_pmode, P_NOFIT)
        rep_borrow = jnp.where(elig, rep_borrow, 0)

        # Fungibility scan over the slot's own flavor list.
        b_f, b_pm, b_bw, att, praw_n, praw_stop, n_cons = \
            _fungibility_scan(
                rep_pmode, rep_borrow,
                arrays.pref_preempt_over_borrow[c],
                arrays.s_flavor_at[:, s], arrays.s_n_flavors[:, s],
                arrays.s_start[:, s],
                arrays.when_can_preempt_try_next[c],
                arrays.when_can_borrow_try_next[c],
            )
        tried = jnp.where(
            att == arrays.s_n_flavors[:, s] - 1, -1, att
        ).astype(jnp.int32)

        # Accumulate the slot's assigned usage onto its chosen plane: the
        # host appends psa.flavors usage for any mode above NoFit
        # (flavorassigner.go:901 _append).
        take = arrays.s_valid[:, s] & (b_pm != P_NOFIT) & (b_f >= 0)
        onehot = (
            (f_iota[None, :, None]
             == jnp.clip(b_f, 0, f_n - 1)[:, None, None])
            & (req[:, None, :] > 0)
            & take[:, None, None]
        )
        acc = acc + jnp.where(onehot, req[:, None, :], 0)
        outs.append((b_f, b_pm, b_bw, tried, praw_n, praw_stop, n_cons))

    s_f = jnp.stack([o[0] for o in outs], axis=1)
    s_pm = jnp.stack([o[1] for o in outs], axis=1)
    s_bw = jnp.stack([o[2] for o in outs], axis=1)
    s_tried = jnp.stack([o[3] for o in outs], axis=1)
    s_praw_n = jnp.stack([o[4] for o in outs], axis=1)
    s_praw_stop = jnp.stack([o[5] for o in outs], axis=1)
    s_cons = jnp.stack([o[6] for o in outs], axis=1)

    sv = arrays.s_valid
    # done[s]: every earlier valid slot assigned — the host early-returns
    # on a failed group, so later slots are never evaluated.
    ok_slot = ~sv | (s_pm != P_NOFIT)
    done = jnp.cumprod(
        jnp.concatenate(
            [jnp.ones((w_n, 1), dtype=jnp.int32),
             ok_slot[:, :-1].astype(jnp.int32)], axis=1
        ), axis=1
    ).astype(bool)
    eff = sv & done
    wl_nofit = jnp.any(eff & (s_pm == P_NOFIT), axis=1)
    any_praw = jnp.any(eff & (s_pm == P_PREEMPT_RAW), axis=1)
    any_nc = jnp.any(eff & (s_pm == P_NO_CANDIDATES), axis=1)
    best_pmode = jnp.where(
        wl_nofit, P_NOFIT,
        jnp.where(any_praw, P_PREEMPT_RAW,
                  jnp.where(any_nc, P_NO_CANDIDATES, P_FIT)),
    ).astype(jnp.int32)
    best_pmode = jnp.where(arrays.w_active, best_pmode, P_NOFIT)
    assigned = eff & (s_pm != P_NOFIT)
    best_borrow = jnp.max(
        jnp.where(assigned, s_bw, 0), axis=1
    ).astype(jnp.int32)
    seen_praw = jnp.any(eff & (s_praw_n > 0), axis=1)
    needs_host = (seen_praw | any_praw) & arrays.w_active

    return NominateResult(
        chosen_flavor=s_f[:, 0],
        best_pmode=best_pmode,
        best_borrow=best_borrow,
        needs_host=needs_host,
        tried_flavor_idx=s_tried[:, 0],
        praw_count=s_praw_n[:, 0],
        praw_stop=s_praw_stop[:, 0],
        considered=s_cons[:, 0],
        s_flavor=s_f,
        s_pmode=jnp.where(eff, s_pm, P_NOFIT).astype(jnp.int32),
        s_borrow=s_bw,
        s_tried=s_tried,
        s_praw_count=s_praw_n,
        s_praw_stop=s_praw_stop,
        s_considered=s_cons,
    )


# Static probe-step bound for the partial-admission binary search: the
# search space is [0, count - min_count]; 22 halvings cover 4M pods.
_PARTIAL_STEPS = 22


def structural_elig(arrays: CycleArrays, nm: NominateResult, base_core):
    """Oracle-independence of the fungibility choice, shared by the
    cycle's full-count preemption resolution (make_grouped_cycle
    impl_preempt) and the partial-admission probes (partial_search):
    the scan must have stopped at exactly one raw-preempt flavor — per
    slot in slot-layout cycles (a preempting slot saw exactly one praw
    flavor, a non-preempting slot saw none) — so the victim kernel's
    verdict cannot change the flavor choice. Returns (base_elig,
    slot_nom) with slot_nom None outside slot-layout cycles."""
    from kueue_tpu.models.preempt_kernel import SlotNom

    slot_nom = None
    if arrays.s_req is not None and nm.s_flavor is not None:
        eff_s = arrays.s_valid & (nm.s_pmode != P_NOFIT)
        s_is_praw = eff_s & (nm.s_pmode == P_PREEMPT_RAW)
        slot_gate = jnp.where(
            s_is_praw,
            nm.s_praw_count == 1,
            ~eff_s | (nm.s_praw_count == 0),
        )
        base_elig = base_core & jnp.all(slot_gate, axis=1)
        slot_nom = SlotNom(
            s_flavor=nm.s_flavor,
            s_on=eff_s & (nm.s_flavor >= 0),
            s_is_praw=s_is_praw,
            s_praw_stop=nm.s_praw_stop,
            s_considered=nm.s_considered,
        )
    else:
        base_elig = base_core & (nm.praw_count == 1)
    return base_elig, slot_nom


def partial_search(
    arrays: CycleArrays, usage: jnp.ndarray, nom: NominateResult,
    n_levels: int = MAX_DEPTH + 1, adm=None, targets=None,
) -> Tuple[NominateResult, jnp.ndarray, jnp.ndarray, object]:
    """Device PodSetReducer (reference flavorassigner/podset_reducer.go:67
    + the host's Scheduler._search_partial): for every reducible entry
    whose full-count assignment is not Fit (nor resolved Preempt),
    binary-search the smallest reduction whose assignment passes,
    replicating the host's exact probe sequence (sort.Search semantics —
    same midpoints, same final lo-probe, so results agree even off the
    monotone happy path).

    A probe passes when its mode is Fit, or — in preempt cycles
    (``adm``/``targets`` given, reference scheduler.go:803 reducer
    fits()) — when it is a device-resolvable Preempt with a non-empty
    victim set from the flat victim-search kernel. A probe the kernels
    cannot decide (oracle-dependent fungibility, non-simple tree, gated
    entry) marks the WHOLE entry host-bound: the host then re-runs the
    full search, and the driver's whole-tree discard keeps the cycle
    exact. Each probe re-runs the full vectorized ``nominate`` on scaled
    per-pod requests (flavor choice may change with the count, exactly
    like the host re-running assign()).

    Returns (updated nominate result, updated w_req, partial_count[W]
    with -1 for full-count entries, merged PreemptTargets or None).
    """
    delta = arrays.w_count - arrays.w_min_count
    widened = (
        adm is not None
        and targets is not None
        and arrays.preempt_simple is not None
    )
    searching = (
        arrays.w_partial
        & arrays.w_active
        & (nom.best_pmode != P_FIT)
        & ~nom.needs_host
        & (delta > 0)
    )
    if widened:
        # Full-count Preempt already resolved with targets: the reference
        # reducer never runs (scheduler.go:795 returns before it).
        searching = searching & (nom.best_pmode != P_PREEMPT_OK)

    from kueue_tpu.models.preempt_kernel import (
        PreemptTargets,
        preempt_targets,
    )

    w_n = arrays.w_cq.shape[0]
    a_n = adm.cq.shape[0] if widened else 1

    def probe(count_probe):
        req_p = jnp.where(
            searching[:, None],
            arrays.w_req_pp * count_probe[:, None],
            arrays.w_req,
        )
        arr2 = arrays._replace(w_req=req_p)
        if arrays.s_req is not None:
            # Slot-layout cycles: nominate reads s_req; partial entries
            # are single-slot (slot 0 mirrors w_req by construction).
            arr2 = arr2._replace(s_req=arrays.s_req.at[:, 0].set(req_p))
        return arr2, nominate(arr2, usage, n_levels=n_levels)

    def probe_verdict(go, arr2, nm):
        """(ok, unres, borrow, victims, variant) for one probe, under the
        same structural-eligibility rules as the cycle's full-count
        resolution (make_grouped_cycle impl_preempt — change BOTH when
        the eligibility rules change; the probe copy omits only the
        w_tas / preempt_hier arms, which the encoder gates off for
        partial entries)."""
        fit = go & (nm.best_pmode == P_FIT) & ~nm.needs_host
        if not widened:
            # No preempt widening: a probe whose nominate verdict depends
            # on the host oracle is unresolved, exactly as on the widened
            # path below — reporting it as a plain failure would silently
            # shrink the entry instead of routing it to the host.
            return fit, go & nm.needs_host, nm.best_borrow, None, None
        praw = nm.best_pmode == P_PREEMPT_RAW
        base_core = go & praw & ~arrays.w_has_gates
        base_elig, slot_nom = structural_elig(arrays, nm, base_core)
        # Partial entries are non-TAS by encoder gate; the flat kernel
        # covers simple trees only (probes on nested trees stay host).
        elig = base_elig & arrays.preempt_simple[arrays.w_cq]
        zero_t = PreemptTargets(
            victims=jnp.zeros((w_n, a_n), bool),
            variant=jnp.zeros((w_n, a_n), jnp.int32),
            success=jnp.zeros(w_n, bool),
            resolved_nc=jnp.zeros(w_n, bool),
            resolved=jnp.zeros(w_n, bool),
            borrow_after=jnp.zeros(w_n, jnp.int32),
        )
        tgt_p = jax.lax.cond(
            jnp.any(elig),
            lambda: preempt_targets(
                arr2, adm, nm.chosen_flavor, elig, nm.praw_stop,
                nm.considered, slot_nom=slot_nom,
            ),
            lambda: zero_t,
        )
        pre_ok = elig & tgt_p.success
        # Resolvable probes: oracle-independent nominate, or a
        # kernel-resolved preempt verdict (success OR definite
        # no-candidates). Anything else needs the host's oracle.
        resolved_probe = ~nm.needs_host | (elig & tgt_p.resolved)
        unres = go & ~resolved_probe
        ok = fit | pre_ok
        borrow = jnp.where(pre_ok, tgt_p.borrow_after, nm.best_borrow)
        return ok, unres, borrow, \
            jnp.where(pre_ok[:, None], tgt_p.victims, False), \
            jnp.where(pre_ok[:, None], tgt_p.variant, 0)

    def step(carry, _):
        lo, hi, best, bf, bb, bt, bad, bpre, bvict, bvar = carry
        go = searching & (lo < hi)
        mid = (lo + hi) // 2
        # Probe only while some lane is still searching; converged
        # iterations of the fixed-length scan skip the nominate pass
        # (its results would be fully masked by ``go`` anyway).
        arr2, nm = jax.lax.cond(
            jnp.any(go),
            lambda: probe(arrays.w_count - mid),
            lambda: (arrays, nom),
        )
        ok, unres, borrow, vict, var = probe_verdict(go, arr2, nm)
        bad = bad | unres
        best = jnp.where(ok, mid, best)
        bf = jnp.where(ok, nm.chosen_flavor, bf)
        bb = jnp.where(ok, borrow, bb)
        bt = jnp.where(ok, nm.tried_flavor_idx, bt)
        if widened:
            # won-by-preempt iff this passing probe carried victims (a
            # fit-passing probe's victim row is zeroed in probe_verdict).
            pre_win = ok & jnp.any(vict, axis=1)
            bpre = jnp.where(ok, pre_win, bpre)
            bvict = jnp.where(ok[:, None], vict, bvict)
            bvar = jnp.where(ok[:, None], var, bvar)
        hi = jnp.where(ok, mid, hi)
        lo = jnp.where(go & ~ok, mid + 1, lo)
        return (lo, hi, best, bf, bb, bt, bad, bpre, bvict, bvar), None

    init = (
        jnp.zeros_like(delta), delta, jnp.full_like(delta, -1),
        nom.chosen_flavor, nom.best_borrow, nom.tried_flavor_idx,
        jnp.zeros(w_n, bool), jnp.zeros(w_n, bool),
        jnp.zeros((w_n, a_n), bool), jnp.zeros((w_n, a_n), jnp.int32),
    )
    (lo, _hi, best, bf, bb, bt, bad, bpre, bvict, bvar), _ = jax.lax.scan(
        step, init, None, length=_PARTIAL_STEPS
    )

    # sort.Search tail: nothing found inside the loop -> one last probe
    # at lo (== hi after convergence).
    need_final = searching & (best < 0) & (lo <= delta)
    arr2, nm = jax.lax.cond(
        jnp.any(need_final),
        lambda: probe(
            jnp.where(need_final, arrays.w_count - lo, arrays.w_count)
        ),
        lambda: (arrays, nom),
    )
    ok_f, unres_f, borrow_f, vict_f, var_f = probe_verdict(
        need_final, arr2, nm
    )
    bad = bad | unres_f
    best = jnp.where(ok_f, lo, best)
    bf = jnp.where(ok_f, nm.chosen_flavor, bf)
    bb = jnp.where(ok_f, borrow_f, bb)
    bt = jnp.where(ok_f, nm.tried_flavor_idx, bt)
    if widened:
        pre_win_f = ok_f & jnp.any(vict_f, axis=1)
        bpre = jnp.where(ok_f, pre_win_f, bpre)
        bvict = jnp.where(ok_f[:, None], vict_f, bvict)
        bvar = jnp.where(ok_f[:, None], var_f, bvar)

    found = searching & (best >= 0) & ~bad
    new_count = arrays.w_count - jnp.maximum(best, 0)
    new_req = jnp.where(
        found[:, None], arrays.w_req_pp * new_count[:, None], arrays.w_req
    )
    nom2 = nom._replace(
        chosen_flavor=jnp.where(found, bf, nom.chosen_flavor),
        best_pmode=jnp.where(
            found,
            jnp.where(found & bpre, P_PREEMPT_OK, P_FIT)
            if widened else P_FIT,
            nom.best_pmode,
        ),
        best_borrow=jnp.where(found, bb, nom.best_borrow),
        tried_flavor_idx=jnp.where(found, bt, nom.tried_flavor_idx),
        needs_host=nom.needs_host | (searching & bad),
    )
    tgt2 = None
    if widened:
        pre_m = found & bpre
        tgt2 = PreemptTargets(
            victims=jnp.where(pre_m[:, None], bvict, targets.victims),
            variant=jnp.where(pre_m[:, None], bvar, targets.variant),
            success=targets.success | pre_m,
            resolved_nc=targets.resolved_nc & ~pre_m,
            resolved=targets.resolved | pre_m,
            borrow_after=jnp.where(
                pre_m, bb.astype(targets.borrow_after.dtype),
                targets.borrow_after,
            ),
        )
    if nom.s_flavor is not None:
        # Mirror the reduction into slot 0 (partial entries are
        # single-slot) so the slot-layout admission scan sees it.
        pm0 = (
            jnp.where(found & bpre, P_PREEMPT_OK, P_FIT)
            if widened else P_FIT
        )
        nom2 = nom2._replace(
            s_flavor=nom.s_flavor.at[:, 0].set(
                jnp.where(found, bf, nom.s_flavor[:, 0])
            ),
            s_pmode=nom.s_pmode.at[:, 0].set(
                jnp.where(found, pm0, nom.s_pmode[:, 0])
            ),
            s_tried=nom.s_tried.at[:, 0].set(
                jnp.where(found, bt, nom.s_tried[:, 0])
            ),
        )
    partial_count = jnp.where(found, new_count, jnp.int64(-1))
    return nom2, new_req, partial_count, tgt2


def admission_order(arrays: CycleArrays, nom: NominateResult) -> jnp.ndarray:
    """Classical iterator sort (scheduler.go:1005): quota-reserved first,
    fewest borrows, highest priority, FIFO timestamp. Inactive entries sink
    to the end."""
    w = arrays.w_cq.shape[0]
    borrows = jnp.where(nom.best_pmode > P_NOFIT, nom.best_borrow, 0)
    if getattr(arrays, "w_order_rank", None) is not None:
        # Host-precomputed (priority desc, timestamp, submission) rank:
        # fold the dynamic keys on top into ONE composite key and sort
        # once instead of five stable passes. Keys are unique (the rank
        # is a permutation), so an unstable sort is exact.
        if w <= (1 << 25):
            # int32 composite: rank(25) | borrows(4) | reserved | active.
            # Borrow heights are tree heights <= MAX_DEPTH=8, so 4 bits
            # are exact; an int32 sort is ~2x the int64 sort's speed on
            # TPU (the sort is bandwidth-bound on (key, index) pairs).
            key32 = (
                (~arrays.w_active).astype(jnp.int32) * jnp.int32(1 << 30)
                + (~arrays.w_quota_reserved).astype(jnp.int32)
                * jnp.int32(1 << 29)
                + jnp.clip(borrows, 0, 15).astype(jnp.int32)
                * jnp.int32(1 << 25)
                + arrays.w_order_rank.astype(jnp.int32)
            )
            return jnp.argsort(key32).astype(jnp.int32)
        key = (
            (~arrays.w_active).astype(jnp.int64) * (jnp.int64(1) << 40)
            + (~arrays.w_quota_reserved).astype(jnp.int64)
            * (jnp.int64(1) << 39)
            + jnp.clip(borrows, 0, 127).astype(jnp.int64)
            * (jnp.int64(1) << 32)
            + arrays.w_order_rank.astype(jnp.int64)
        )
        return jnp.argsort(key).astype(jnp.int32)
    # Least-significant key first; each pass is a stable argsort applied on
    # top of the previous permutation (equivalent to lexsort, but compiles
    # to simple single-key sorts). Submission-index tiebreak is implicit in
    # stability.
    perm = jnp.arange(w, dtype=jnp.int32)
    for key in (
        arrays.w_timestamp,
        -arrays.w_priority,
        borrows.astype(jnp.int64),
        (~arrays.w_quota_reserved).astype(jnp.int32),
        (~arrays.w_active).astype(jnp.int32),
    ):
        perm = perm[jnp.argsort(key[perm], stable=True)]
    return perm.astype(jnp.int32)


def admit_scan(
    arrays: CycleArrays, nom: NominateResult, usage: jnp.ndarray,
    order: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sequential admission in sorted order (the order-dependent core of
    processEntry, scheduler.go:385): each FIT entry re-checks the fit
    against running usage, then consumes capacity; NO_CANDIDATES entries
    reserve clipped capacity (scheduler.go:513).

    Per-step work is restricted to the entry's MAX_DEPTH ancestor chain —
    gather [D+1,F,R] rows, walk, one scatter back — so a step touches
    ~D*F*R elements, not the whole [N,F,R] state. All usage-independent
    quantities (local quota, subtree quota, limits, chains) are hoisted out
    of the scan."""
    tree = arrays.tree
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
    f_onehot = jnp.arange(f_n)

    # Hoisted invariants (usage-independent).
    lq_all = quota_ops.local_quota(tree)  # [N,F,R]
    parent = jnp.where(tree.parent < 0, jnp.arange(tree.n_nodes), tree.parent)
    chain_cols = [jnp.arange(tree.n_nodes)]
    for _ in range(MAX_DEPTH):
        chain_cols.append(parent[chain_cols[-1]])
    chain_table = jnp.stack(chain_cols, axis=1)  # [N, D+1]

    def body(usage, w):
        c = arrays.w_cq[w]
        f = nom.chosen_flavor[w]
        pm = nom.best_pmode[w]
        active = arrays.w_active[w]
        cell_mask = (
            (f_onehot[:, None] == f)
            & (arrays.w_req[w][None, :] > 0)
            & arrays.covered[c][None, :]
        )
        delta = jnp.where(cell_mask, arrays.w_req[w][None, :], 0).astype(
            jnp.int64
        )

        chain = chain_table[c]  # [D+1]
        u = usage[chain]  # [D+1,F,R]
        lq = lq_all[chain]
        subtree = tree.subtree_quota[chain]
        bl = tree.borrow_limit[chain]
        has_bl = tree.has_borrow_limit[chain]
        # chain[i] == chain[i+1] marks padding repeats past the root.
        nxt = jnp.concatenate([chain[1:], chain[-1:]])
        is_repeat = chain == nxt

        l_avail = jnp.maximum(0, sat_sub(lq, u))
        stored = sat_sub(subtree, lq)
        used_in_parent = jnp.maximum(0, sat_sub(u, lq))
        with_max = sat_add(sat_sub(stored, used_in_parent), bl)

        # available() down the chain, root first (resource_node.go:106).
        avail = sat_sub(subtree[MAX_DEPTH], u[MAX_DEPTH])
        for i in range(MAX_DEPTH - 1, -1, -1):
            clamped = jnp.where(has_bl[i], jnp.minimum(with_max[i], avail),
                                avail)
            stepped = sat_add(l_avail[i], clamped)
            avail = jnp.where(is_repeat[i], avail, stepped)

        fits = jnp.all((delta <= avail) | ~cell_mask)
        deferred = nom.needs_host[w]  # host path decides; don't touch usage
        admit = active & (pm == P_FIT) & fits & ~deferred

        # reserveCapacityForUnreclaimablePreempt for NO_CANDIDATES entries.
        borrowing = nom.best_borrow[w] > 0
        nominal_c = tree.nominal[c]
        reserve_borrowing = jnp.where(
            has_bl[0],
            jnp.minimum(delta, sat_sub(sat_add(nominal_c, bl[0]), u[0])),
            delta,
        )
        reserve_plain = jnp.maximum(
            0, jnp.minimum(delta, sat_sub(nominal_c, u[0]))
        )
        reserve = jnp.where(borrowing, reserve_borrowing, reserve_plain)
        reserve = jnp.where(cell_mask, reserve, 0)
        do_reserve = (
            active
            & (pm == P_NO_CANDIDATES)
            & ~arrays.can_always_reclaim[c]
            & ~deferred
        )

        applied = jnp.where(admit, delta, jnp.where(do_reserve, reserve, 0))
        # addUsage bubbling along the chain (resource_node.go:144): each
        # level receives the part of the previous level's delta exceeding
        # its (pre-update) local availability; repeats past root get zero.
        deltas = jnp.zeros((MAX_DEPTH + 1, f_n, r_n), dtype=jnp.int64)
        cur = applied
        for i in range(MAX_DEPTH + 1):
            deltas = deltas.at[i].set(cur)
            cont = ~is_repeat[i] if i < MAX_DEPTH else jnp.bool_(False)
            cur = jnp.where(cont, jnp.maximum(0, sat_sub(cur, l_avail[i])), 0)
        new_usage = quota_ops.sat(usage.at[chain].add(deltas, mode="drop"))
        return new_usage, admit

    final_usage, admitted_in_order = jax.lax.scan(body, usage, order,
                                                  unroll=4)
    admitted = jnp.zeros(arrays.w_cq.shape[0], dtype=bool)
    admitted = admitted.at[order].set(admitted_in_order)
    return final_usage, admitted


def cycle_impl(arrays: CycleArrays) -> CycleOutputs:
    """One full batched scheduling cycle (unjitted; see ``cycle``)."""
    usage = arrays.usage
    nom = nominate(arrays, usage)
    order = admission_order(arrays, nom)
    final_usage, admitted = admit_scan(arrays, nom, usage, order)

    outcome = jnp.where(
        ~arrays.w_active,
        OUT_NOFIT,
        jnp.where(
            nom.needs_host,
            OUT_NEEDS_HOST,
            jnp.where(
                admitted,
                OUT_ADMITTED,
                jnp.where(
                    nom.best_pmode == P_FIT,
                    OUT_FIT_SKIPPED,
                    jnp.where(
                        nom.best_pmode == P_NO_CANDIDATES,
                        OUT_NO_CANDIDATES,
                        OUT_NOFIT,
                    ),
                ),
            ),
        ),
    ).astype(jnp.int32)
    return CycleOutputs(
        outcome=outcome,
        chosen_flavor=nom.chosen_flavor,
        borrow=nom.best_borrow,
        tried_flavor_idx=nom.tried_flavor_idx,
        usage=final_usage,
        order=order,
    )


# Jitted entry point: one compiled XLA program per (W, N, F, R) shape bucket.
cycle = jax.jit(cycle_impl)


class GroupArrays(NamedTuple):
    """Device-side forest layout (see ops.tree_encode.GroupLayout)."""

    flat_to_group: jnp.ndarray  # i32[N]
    flat_to_local: jnp.ndarray  # i32[N]
    node_sel: jnp.ndarray  # i32[G,Nm] flat node per slot
    local_valid: jnp.ndarray  # bool[G,Nm]
    chain_local: jnp.ndarray  # i32[G,Nm,D+1] local-id ancestor chains


class AdmitScanResult(NamedTuple):
    """Result of :func:`admit_scan_grouped` (a pytree — flows through
    jit/scan unchanged; fields formerly threaded as a positional
    6-tuple)."""

    usage: jnp.ndarray  # [N,F,R] final usage after reservations
    admitted: jnp.ndarray  # bool[W]
    preempting: jnp.ndarray  # bool[W] reserved-pending-preemption
    tas_takes: jnp.ndarray  # i32[W,D] or None — pods per leaf domain
    tas_leader_takes: jnp.ndarray  # i32[W,D] or None
    s_tas_takes: jnp.ndarray  # i32[W,S,D] or None
    slot_rounds: jnp.ndarray = None  # i32[] max conflict rounds, or None


def admit_scan_grouped(
    arrays: CycleArrays,
    ga: GroupArrays,
    nom: NominateResult,
    usage: jnp.ndarray,
    order: jnp.ndarray,
    s_max: int,
    adm=None,
    targets=None,
    unroll: int = 2,
    n_levels: int = MAX_DEPTH + 1,
    mesh=None,
) -> "AdmitScanResult":
    """Forest-parallel admission scan.

    With ``mesh`` the scan shards over the GROUP axis instead of
    replicating: cohort forests are independent by construction, so each
    device scans its own groups against its shard of the per-group usage
    state, and the only collectives are the nominate-output all-gather
    before the scan and the tiny admitted/usage merge after it — the
    per-step state never crosses devices (VERDICT r3 weak #4: the
    replicated sequential scan was the multi-chip bottleneck).

    ``n_levels`` statically bounds the ancestor-chain walk (callers pass
    the forest's true max depth + 1; levels past the root are repeats and
    carry no information, so truncating them shrinks every per-step
    tensor).

    Cohort trees share no quota cells, so sequential consistency is only
    required *within* a tree. Entries are bucketed per tree (group) in
    global admission order; the scan runs over per-group slots with the body
    vectorized across all G groups — scan length max-entries-per-group
    instead of W. Entries beyond ``s_max`` slots in one group are left
    undecided this cycle (requeued; exactness needs s_max >= max bucket).

    With ``adm``/``targets`` (device preemption), the scan additionally
    tracks the designated-victim set: every fit check simulates removal of
    all victims designated so far plus the entry's own targets (the host's
    scheduler.go fits()), P_PREEMPT_OK entries with non-overlapping targets
    reserve their usage and designate their victims, and overlapping ones
    are skipped (scheduler.go:385 _process_entry).

    Returns an :class:`AdmitScanResult` (final usage, admitted/preempting
    masks, and the per-leaf-domain TAS take planes decoded by the driver
    into TopologyAssignments).
    """
    tree = arrays.tree
    w_n = arrays.w_cq.shape[0]
    g_n, nm = ga.node_sel.shape
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
    f_onehot = jnp.arange(f_n)
    g_iota = jnp.arange(g_n)
    with_preempt = targets is not None
    with_tas = getattr(arrays, "tas_topo", None) is not None
    with_slots = getattr(arrays, "s_req", None) is not None
    with_leader = (
        with_tas and getattr(arrays, "w_tas_leader_req", None) is not None
    )
    with_stas = (
        with_tas and getattr(arrays, "s_tas", None) is not None
    )

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as _P

        _rep_sh = NamedSharding(mesh, _P())

        def rep(x):
            """Replicate: the all-gather point for W-sharded nominate
            outputs the per-group gathers need locally."""
            return jax.lax.with_sharding_constraint(x, _rep_sh)

        def gsh(x):
            """Shard the leading (group) axis over the mesh."""
            spec = _P(*(("w",) + (None,) * (x.ndim - 1)))
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, spec)
            )

        nom = jax.tree_util.tree_map(
            lambda x: rep(x) if hasattr(x, "ndim") else x, nom
        )
        order = rep(order)
        usage = rep(usage)
    else:
        rep = gsh = lambda x: x

    if with_tas:
        from kueue_tpu.ops import tas_place as _tas_place

        t_n = arrays.tas_usage0.shape[0]
        f_all = arrays.w_elig.shape[1]
        w_iota_all = jnp.arange(w_n)

    if with_preempt:
        a_n = adm.cq.shape[0]
        usage_by_f = jnp.swapaxes(adm.usage, 0, 1)  # [F,A,R]
        # in_sub[b, d]: node b lies on d's ancestor chain (victim usage at
        # CQ d reduces availability at every such b; full subtraction is
        # exact because preempt-eligible trees have no lending limits).
        in_sub = quota_ops.ancestor_matrix(tree)

    # Grouped static tensors [G,Nm,F,R] (usage-independent, hoisted).
    def to_g(x, pad):
        y = x[ga.node_sel]
        return jnp.where(ga.local_valid[..., None, None], y, pad)

    lq_g = gsh(to_g(quota_ops.local_quota(tree), 0))
    subtree_g = gsh(to_g(tree.subtree_quota, 0))
    bl_g = gsh(to_g(tree.borrow_limit, CAP))
    has_bl_g = gsh(to_g(tree.has_borrow_limit, False))
    nominal_g = gsh(to_g(tree.nominal, 0))
    usage_g = gsh(to_g(usage, 0))

    # Entries bucketed by (group, admission rank) with one stable argsort.
    rank = jnp.zeros(w_n, dtype=jnp.int64).at[order].set(
        jnp.arange(w_n, dtype=jnp.int64)
    )
    g_w = ga.flat_to_group[arrays.w_cq].astype(jnp.int64)
    sort_key = jnp.where(
        arrays.w_active, g_w * w_n + rank, jnp.int64(w_n) * w_n + w_n
    )
    grouped_order = rep(jnp.argsort(sort_key).astype(jnp.int32))
    counts = gsh(jnp.zeros(g_n, dtype=jnp.int32).at[
        ga.flat_to_group[arrays.w_cq]
    ].add(arrays.w_active.astype(jnp.int32), mode="drop"))
    starts = gsh(jnp.cumsum(counts) - counts)  # exclusive

    # chain repeats mark root padding (local chain mirrors flat semantics).
    chain_next = jnp.concatenate(
        [ga.chain_local[..., 1:], ga.chain_local[..., -1:]], axis=-1
    )
    chain_is_repeat = gsh(ga.chain_local == chain_next)  # [G,Nm,D+1]

    def body(carry, s):
        (usage_g, designated, tas_usage, w_takes, w_ltakes,
         w_stakes, slot_rounds) = carry
        pos = starts + s
        in_range = s < counts
        # Per-step gathers pull from REPLICATED [W]/[N] sources with a
        # G-sharded index, so every result is pinned to the group shard —
        # no per-step cross-device traffic.
        w = gsh(grouped_order[jnp.clip(pos, 0, w_n - 1)])  # [G]
        c = gsh(arrays.w_cq[w])
        valid = in_range & gsh(arrays.w_active[w])
        f = gsh(nom.chosen_flavor[w])
        pm = gsh(nom.best_pmode[w])
        c_local = gsh(ga.flat_to_local[c])
        chain = gsh(ga.chain_local[g_iota, c_local][:, :n_levels])  # [G,L]
        is_repeat = chain_is_repeat[g_iota, c_local][:, :n_levels]

        gi = g_iota[:, None]
        if with_preempt:
            my_vict = targets.victims[w]  # [G,A]
            preempting = valid & (pm == P_PREEMPT_OK)
            overlap = preempting & jnp.any(
                my_vict & designated[None, :], axis=1
            )
            use_vict = designated[None, :] | jnp.where(
                (preempting & ~overlap)[:, None], my_vict, False
            )  # [G,A]
            chain_flat = ga.node_sel[gi, chain]  # [G,D+1] flat node ids
            vict_masks = []
            for i in range(n_levels):
                on_chain = in_sub[chain_flat[:, i]][:, adm.cq]  # [G,A]
                vict_masks.append(
                    (use_vict & on_chain).astype(jnp.int64)
                )
        else:
            my_vict = None
            preempting = jnp.zeros(g_n, bool)
            overlap = jnp.zeros(g_n, bool)

        if with_slots:
            # Slot-layout step: the entry touches up to S flavor planes
            # (one per assigned slot). Joint fit and usage application use
            # per-plane totals aggregated across same-flavor slots — the
            # host checks and adds assignment.usage per FlavorResource
            # (scheduler.go fits / cq.AddUsage) — applied once per
            # distinct plane (``dedup``). Kept as a separate branch (not
            # S=1-unified with the legacy path below) so the tuned legacy
            # compiled program stays byte-identical; changes to the
            # availability walk / reserve semantics must land in BOTH
            # branches — the differential suites cover each layout.
            s_ax = arrays.s_req.shape[1]
            f_s = nom.s_flavor[w]  # [G,S]
            req_s_raw = arrays.s_req[w]  # [G,S,R]
            act_s = (
                arrays.s_valid[w] & (f_s >= 0)
                & (nom.s_pmode[w] != P_NOFIT)
            )  # [G,S]
            fcl_s = jnp.clip(f_s, 0, f_n - 1)
            cell_s = (req_s_raw > 0) & act_s[..., None]  # [G,S,R]
            req_m = jnp.where(cell_s, req_s_raw, 0).astype(jnp.int64)
            same = (
                (fcl_s[:, :, None] == fcl_s[:, None, :])
                & act_s[:, :, None] & act_s[:, None, :]
            )  # [G,S,S]
            agg = jnp.einsum(
                "gst,gtr->gsr", same.astype(jnp.int64), req_m
            )  # [G,S,R] per-plane totals
            first_idx = jnp.argmax(same, axis=2).astype(jnp.int32)
            dedup = (
                first_idx == jnp.arange(s_ax, dtype=jnp.int32)[None, :]
            ) & act_s  # [G,S] first slot of each distinct plane

            gi3 = g_iota[:, None, None]
            ch3 = chain[:, None, :]
            fg3 = fcl_s[:, :, None]
            u = usage_g[gi3, ch3, fg3]  # [G,S,L,R]
            lq = lq_g[gi3, ch3, fg3]
            subtree = subtree_g[gi3, ch3, fg3]
            bl = bl_g[gi3, ch3, fg3]
            has_bl = has_bl_g[gi3, ch3, fg3]
            l_avail = jnp.maximum(0, sat_sub(lq, u))
            stored = sat_sub(subtree, lq)
            if with_preempt:
                au_f = usage_by_f[fcl_s]  # [G,S,A,R]
                rem = jnp.stack(
                    [
                        jnp.einsum("ga,gsar->gsr", vict_masks[i], au_f)
                        for i in range(n_levels)
                    ],
                    axis=2,
                )  # [G,S,L,R]
                u_fit = u - rem
            else:
                u_fit = u
            l_avail_fit = jnp.maximum(0, sat_sub(lq, u_fit))
            used_in_parent_fit = jnp.maximum(0, sat_sub(u_fit, lq))
            with_max_fit = sat_add(sat_sub(stored, used_in_parent_fit), bl)
            avail = sat_sub(
                subtree[:, :, n_levels - 1], u_fit[:, :, n_levels - 1]
            )
            for i in range(n_levels - 2, -1, -1):
                clamped = jnp.where(
                    has_bl[:, :, i],
                    jnp.minimum(with_max_fit[:, :, i], avail), avail,
                )
                stepped = sat_add(l_avail_fit[:, :, i], clamped)
                avail = jnp.where(
                    is_repeat[:, None, i, None], avail, stepped
                )
            fits = jnp.all((agg <= avail) | ~cell_s, axis=(1, 2))  # [G]
        else:
            req = arrays.w_req[w]  # [G,R]
            # All of a step's quota math lives on the entry's single
            # chosen flavor plane — gather [G,D+1,R] slices instead of
            # [G,D+1,F,R].
            fcl = jnp.clip(f, 0, f_n - 1)
            cell_mask = (
                (f[:, None] >= 0) & (req > 0) & arrays.covered[c]
            )  # [G,R]
            delta = jnp.where(cell_mask, req, 0).astype(jnp.int64)

            fg = fcl[:, None]
            u = usage_g[gi, chain, fg]  # [G,D+1,R]
            lq = lq_g[gi, chain, fg]
            subtree = subtree_g[gi, chain, fg]
            bl = bl_g[gi, chain, fg]
            has_bl = has_bl_g[gi, chain, fg]

            l_avail = jnp.maximum(0, sat_sub(lq, u))
            stored = sat_sub(subtree, lq)

            # Victim-adjusted usage for the availability walk: simulate
            # the removal of every designated victim plus this entry's own
            # targets (scheduler.go fits() -> SimulateWorkloadRemoval).
            # Only the entry's flavor plane matters — its cells are all on
            # flavor f.
            if with_preempt:
                au_f = usage_by_f[fcl]  # [G,A,R]
                rem = jnp.stack(
                    [
                        jnp.einsum("ga,gar->gr", vict_masks[i], au_f)
                        for i in range(n_levels)
                    ],
                    axis=1,
                )  # [G,D+1,R]
                u_fit = u - rem
            else:
                u_fit = u

            l_avail_fit = jnp.maximum(0, sat_sub(lq, u_fit))
            used_in_parent_fit = jnp.maximum(0, sat_sub(u_fit, lq))
            with_max_fit = sat_add(sat_sub(stored, used_in_parent_fit), bl)
            avail = sat_sub(subtree[:, n_levels - 1], u_fit[:, n_levels - 1])
            for i in range(n_levels - 2, -1, -1):
                clamped = jnp.where(
                    has_bl[:, i], jnp.minimum(with_max_fit[:, i], avail),
                    avail,
                )
                stepped = sat_add(l_avail_fit[:, i], clamped)
                avail = jnp.where(is_repeat[:, i, None], avail, stepped)

            fits = jnp.all((delta <= avail) | ~cell_mask, axis=1)  # [G]
        deferred = nom.needs_host[w]

        # TAS placement recheck against the running topology state
        # (scheduler.go:409 updateAssignmentIfNeeded): earlier entries may
        # have taken the domains; infeasible-now entries are skipped.
        if with_tas:
            t_of_g = jnp.where(
                f >= 0, arrays.tas_of_flavor[jnp.clip(f, 0, f_all - 1)], -1
            )
            tas_do = valid & arrays.w_tas[w] & (t_of_g >= 0) & (pm == P_FIT)
            t_idx_g = jnp.clip(t_of_g, 0, tas_usage.shape[0] - 1)
            rl_g = arrays.w_tas_req_level[w, t_idx_g]
            sl_g = arrays.w_tas_slice_level[w, t_idx_g]

            bal_all = arrays.w_tas_balanced

            def place_one(t, req_v, cnt, ssz, sl_, rl_, rq_, un_, cap_,
                          sz_, bal_=None, leader_req_=None,
                          has_leader_=None):
                return _tas_place.place(
                    arrays.tas_topo, t, tas_usage[t], req_v, cnt, ssz,
                    jnp.maximum(sl_, 0), jnp.maximum(rl_, 0), rq_, un_,
                    cap_override=cap_, sizes=sz_, balanced=bal_,
                    leader_req=leader_req_, has_leader=has_leader_,
                )

            cap_g = _tas_place.entry_leaf_cap(arrays, t_idx_g, w=w)
            sizes_g = arrays.w_tas_sizes[w, t_idx_g]
            place_args = (
                t_idx_g, arrays.w_tas_req[w], arrays.w_tas_count[w],
                arrays.w_tas_slice_size[w], sl_g, rl_g,
                arrays.w_tas_required[w], arrays.w_tas_unconstrained[w],
                cap_g, sizes_g,
            )
            if bal_all is not None:
                place_args = place_args + (bal_all[w],)
            if with_leader:
                # LWS groups: leader planes through the placement kernel
                # (reference tas_flavor_snapshot.go:725); entries without
                # a leader pass has_leader=False and place identically to
                # the plain kernel.
                out_p = jax.vmap(
                    lambda lr, hl, *a: place_one(
                        *a, leader_req_=lr, has_leader_=hl
                    ),
                    in_axes=(0, 0) + (0,) * len(place_args),
                )(arrays.w_tas_leader_req[w],
                  arrays.w_tas_has_leader[w], *place_args)
                tas_feas, tas_take, tas_ltake = out_p
            else:
                tas_feas, tas_take = jax.vmap(place_one)(
                    *place_args
                )  # [G], [G, D]
                tas_ltake = None
            tas_ok = jnp.where(tas_do, tas_feas, True)
            if with_stas:
                # Generic multi-podset / multi-RG TAS: every slot of
                # every group lane places in ONE batched pass
                # (models.slot_tas). The reference's sequential
                # assumed-usage threading (flavorassigner.update_for_tas's
                # ``assumed`` dict) is recovered by the pass's bounded
                # conflict scan — slots on distinct topology rows settle
                # in the first vectorized placement; only same-row slot
                # groups iterate, by conflict rank. The accumulator is
                # shared across lanes (per_lane=False): trees sharing a
                # flavor are merged into one group, so at most one entry
                # per step touches a flavor row.
                s_ax2 = arrays.s_tas.shape[1]
                sctx = _slot_tas.slot_ctx(arrays, nom.s_flavor[w], sel=w)
                s_do = (
                    valid[:, None] & sctx.stas & sctx.t_valid
                    & (pm == P_FIT)[:, None]
                )
                sp = _slot_tas.place_slots(
                    arrays.tas_topo, tas_usage, sctx, s_do
                )
                slot_rounds = jnp.maximum(slot_rounds, sp.rounds)
                has_stas_g = jnp.any(sctx.stas, axis=1)
                tas_ok = tas_ok & jnp.where(
                    valid & has_stas_g & (pm == P_FIT), sp.ok, True
                )
        else:
            tas_ok = True
            tas_do = None

        admit = valid & (pm == P_FIT) & fits & ~deferred & tas_ok
        preempt_ok = preempting & ~overlap & fits & ~deferred

        borrowing = nom.best_borrow[w] > 0
        do_reserve = (
            valid
            & (pm == P_NO_CANDIDATES)
            & ~arrays.can_always_reclaim[c]
            & ~deferred
        )
        # Both admitted FIT entries and proceeding preemptors consume their
        # usage (scheduler.go:561 cq.AddUsage runs for either mode).
        take_usage = admit | preempt_ok
        if with_slots:
            nom_c = nominal_g[
                g_iota[:, None], c_local[:, None], fcl_s
            ]  # [G,S,R]
            pcell = agg > 0  # plane-union cells (assignment.usage keys)
            reserve_borrowing = jnp.where(
                has_bl[:, :, 0],
                jnp.minimum(
                    agg, sat_sub(sat_add(nom_c, bl[:, :, 0]), u[:, :, 0])
                ),
                agg,
            )
            reserve_plain = jnp.maximum(
                0, jnp.minimum(agg, sat_sub(nom_c, u[:, :, 0]))
            )
            reserve = jnp.where(
                borrowing[:, None, None], reserve_borrowing, reserve_plain
            )
            reserve = jnp.where(pcell, reserve, 0)
            applied = jnp.where(
                (take_usage[:, None] & dedup)[:, :, None],
                agg,
                jnp.where(
                    (do_reserve[:, None] & dedup)[:, :, None], reserve, 0
                ),
            )  # [G,S,R]
            deltas = jnp.zeros(
                (g_n, s_ax, n_levels, r_n), dtype=jnp.int64
            )
            cur = applied
            for i in range(n_levels):
                deltas = deltas.at[:, :, i].set(cur)
                cont = (
                    (~is_repeat[:, None, i, None])
                    if i < n_levels - 1 else False
                )
                cur = jnp.where(
                    cont, jnp.maximum(0, sat_sub(cur, l_avail[:, :, i])), 0
                )
            new_usage_g = usage_g.at[gi3, ch3, fg3].add(
                deltas, mode="drop"
            )
        else:
            nom_c = nominal_g[g_iota, c_local, fcl]  # [G,R]
            reserve_borrowing = jnp.where(
                has_bl[:, 0],
                jnp.minimum(
                    delta, sat_sub(sat_add(nom_c, bl[:, 0]), u[:, 0])
                ),
                delta,
            )
            reserve_plain = jnp.maximum(
                0, jnp.minimum(delta, sat_sub(nom_c, u[:, 0]))
            )
            reserve = jnp.where(
                borrowing[:, None], reserve_borrowing, reserve_plain
            )
            reserve = jnp.where(cell_mask, reserve, 0)
            applied = jnp.where(
                take_usage[:, None],
                delta,
                jnp.where(do_reserve[:, None], reserve, 0),
            )
            deltas = jnp.zeros((g_n, n_levels, r_n), dtype=jnp.int64)
            cur = applied
            for i in range(n_levels):
                deltas = deltas.at[:, i].set(cur)
                cont = (
                    (~is_repeat[:, i, None]) if i < n_levels - 1 else False
                )
                cur = jnp.where(
                    cont, jnp.maximum(0, sat_sub(cur, l_avail[:, i])), 0
                )
            # Plain scatter-add on the flavor plane: usage stays far below
            # the saturation cap (it is bounded by the sum of admitted
            # requests), so no full-array sat() pass is needed per step.
            # Chain repeats past the root carry zero deltas, so duplicate
            # indices are benign.
            new_usage_g = usage_g.at[gi, chain, fg].add(deltas, mode="drop")
        if with_preempt:
            designated = designated | jnp.any(
                jnp.where(preempt_ok[:, None], my_vict, False), axis=0
            )
        if with_tas:
            # Consume topology capacity for admitted TAS entries. Trees
            # sharing a flavor are merged into one scan group, so at most
            # one entry per step touches a given flavor row.
            do_take = admit & tas_do
            usage_delta = (
                tas_take[:, :, None]
                * arrays.w_tas_usage_req[w][:, None, :]
            )  # [G, D, R1]
            if with_leader:
                # The leader pod's explicit resources land on its leaf
                # (host _add_tas_usage adds every podset's TA usage).
                lmask = arrays.w_tas_has_leader[w]
                usage_delta = usage_delta + jnp.where(
                    lmask[:, None, None],
                    tas_ltake[:, :, None].astype(jnp.int64)
                    * arrays.w_tas_leader_usage_req[w][:, None, :],
                    0,
                )
            usage_delta = jnp.where(
                do_take[:, None, None], usage_delta, 0
            )
            tas_usage = tas_usage.at[t_idx_g].add(usage_delta)
            # Record the entry's own leaf takes for the driver's direct
            # domain decode (row w_n is the trash row for non-TAS steps).
            w_takes = w_takes.at[jnp.where(do_take, w, w_n)].add(
                jnp.where(do_take[:, None], tas_take, 0).astype(jnp.int32),
                mode="drop",
            )
            if with_leader:
                w_ltakes = w_ltakes.at[jnp.where(do_take, w, w_n)].add(
                    jnp.where(
                        do_take[:, None] & lmask[:, None], tas_ltake, False
                    ).astype(jnp.int32),
                    mode="drop",
                )
            if with_stas:
                # Batched twin of the per-slot commit: one scatter-add
                # over every (lane, slot) pair (duplicate topology rows
                # accumulate, matching the sequential per-slot adds).
                do_c = admit[:, None] & s_do
                tas_usage = _slot_tas.commit_usage(
                    tas_usage, sctx, sp.takes, do_c
                )
                w_stakes = w_stakes.at[
                    jnp.where(do_c, w[:, None], w_n),
                    jnp.arange(s_ax2)[None, :],
                ].add(
                    jnp.where(do_c[:, :, None], sp.takes, 0)
                    .astype(jnp.int32),
                    mode="drop",
                )
        w_out = jnp.where(admit | preempt_ok, w, w_n)  # w_n = dropped
        return (new_usage_g, designated, tas_usage, w_takes, w_ltakes,
                w_stakes, slot_rounds), (w_out, admit, preempt_ok)

    designated0 = (
        jnp.zeros(a_n, bool) if with_preempt else jnp.zeros(1, bool)
    )
    tas_usage0 = (
        arrays.tas_usage0 if with_tas else jnp.zeros((1,), jnp.int64)
    )
    takes0 = (
        jnp.zeros((w_n + 1, arrays.tas_topo.leaf_cap.shape[1]), jnp.int32)
        if with_tas else jnp.zeros((1,), jnp.int32)
    )
    ltakes0 = (
        jnp.zeros((w_n + 1, arrays.tas_topo.leaf_cap.shape[1]), jnp.int32)
        if with_leader else jnp.zeros((1,), jnp.int32)
    )
    stakes0 = (
        jnp.zeros(
            (w_n + 1, arrays.s_tas.shape[1],
             arrays.tas_topo.leaf_cap.shape[1]),
            jnp.int32,
        )
        if with_stas else jnp.zeros((1,), jnp.int32)
    )
    slot_rounds0 = jnp.zeros((), jnp.int32)
    (final_usage_g, _designated, _tas_u, w_takes_f, w_ltakes_f,
     w_stakes_f, slot_rounds_f), (w_mat, admit_mat, pre_mat) = jax.lax.scan(
        body, (usage_g, designated0, tas_usage0, takes0, ltakes0,
               stakes0, slot_rounds0),
        jnp.arange(s_max), unroll=unroll,
    )
    admitted = rep(jnp.zeros(w_n + 1, dtype=bool).at[w_mat.ravel()].max(
        admit_mat.ravel(), mode="drop"
    )[:w_n])
    preempting_out = rep(
        jnp.zeros(w_n + 1, dtype=bool).at[w_mat.ravel()].max(
            pre_mat.ravel(), mode="drop"
        )[:w_n]
    )
    # Back to flat node layout.
    final_usage = rep(final_usage_g[ga.flat_to_group, ga.flat_to_local])
    final_usage = jnp.where(
        tree.active[:, None, None], final_usage, usage
    )
    tas_takes = w_takes_f[:w_n] if with_tas else None
    tas_leader_takes = w_ltakes_f[:w_n] if with_leader else None
    s_tas_takes = w_stakes_f[:w_n] if with_stas else None
    return AdmitScanResult(
        usage=final_usage,
        admitted=admitted,
        preempting=preempting_out,
        tas_takes=tas_takes,
        tas_leader_takes=tas_leader_takes,
        s_tas_takes=s_tas_takes,
        slot_rounds=slot_rounds_f if with_stas else None,
    )


def apply_tas_nominate_hook(arrays: CycleArrays, nom: NominateResult):
    """Device TAS hook (flavorassigner.go:796-835 order): feasibility of
    the chosen flavor's topology placement downgrades Fit->Preempt;
    preempt-mode entries that cannot place even on an empty fleet demote
    to NoFit; surviving preempt-mode TAS entries need the host's
    TAS-aware victim search. Shared by the classical grouped cycle and
    the fair tournament cycle. Returns (updated nom, downgrade mask)."""
    from kueue_tpu.ops import tas_place

    w_n = arrays.w_cq.shape[0]
    w_iota = jnp.arange(w_n)
    f_n = arrays.w_elig.shape[1]
    chosen_c = jnp.clip(nom.chosen_flavor, 0, f_n - 1)
    t_of = jnp.where(
        nom.chosen_flavor >= 0, arrays.tas_of_flavor[chosen_c], -1
    )
    tas_entry = arrays.w_tas & arrays.w_active & (t_of >= 0)
    t_idx = jnp.clip(t_of, 0, arrays.tas_usage0.shape[0] - 1)
    rl = arrays.w_tas_req_level[w_iota, t_idx]
    sl = arrays.w_tas_slice_level[w_iota, t_idx]

    with_leader = arrays.w_tas_leader_req is not None

    def feas(usage_all, t, req, count, ssz, sl_, rl_, rq_, un_, cap_, sz_,
             lr_=None, hl_=None):
        return tas_place.feasible_only(
            arrays.tas_topo, t, usage_all[t], req, count, ssz,
            jnp.maximum(sl_, 0), jnp.maximum(rl_, 0), rq_, un_,
            cap_override=cap_, sizes=sz_, leader_req=lr_, has_leader=hl_,
        )

    # Per-entry filtered leaf capacity (node selector / taint matching)
    # replaces the topology's static capacity where set.
    cap_all = tas_place.entry_leaf_cap(arrays, t_idx)
    sizes_all = arrays.w_tas_sizes[w_iota, t_idx]
    feas_args = (
        t_idx, arrays.w_tas_req, arrays.w_tas_count,
        arrays.w_tas_slice_size, sl, rl, arrays.w_tas_required,
        arrays.w_tas_unconstrained, cap_all, sizes_all,
    )
    if with_leader:
        # LWS groups: feasibility must include the leader pod (the host's
        # find_topology_assignment places worker and leader together).
        feas_args = feas_args + (
            arrays.w_tas_leader_req, arrays.w_tas_has_leader,
        )
    n_in = len(feas_args)
    feas_now = jax.vmap(feas, in_axes=(None,) + (0,) * n_in)(
        arrays.tas_usage0, *feas_args
    )
    feas_empty = jax.vmap(feas, in_axes=(None,) + (0,) * n_in)(
        jnp.zeros_like(arrays.tas_usage0), *feas_args
    )
    ok_levels = (rl >= 0) & (sl >= 0) & ~arrays.w_tas_invalid
    feas_now = feas_now & ok_levels
    feas_empty = feas_empty & ok_levels

    pm0 = nom.best_pmode
    downgrade = tas_entry & (pm0 == P_FIT) & ~feas_now
    # A downgraded entry on a CQ that can never find preemption targets
    # resolves on device: the host's get_targets trivially returns none
    # and the entry takes the reserve path.
    pm1 = jnp.where(
        downgrade,
        jnp.where(arrays.never_preempts[arrays.w_cq],
                  P_NO_CANDIDATES, P_PREEMPT_RAW),
        pm0,
    )
    pre_mode = tas_entry & (
        (pm1 == P_PREEMPT_RAW) | (pm1 == P_NO_CANDIDATES)
    )
    pm2 = jnp.where(pre_mode & ~feas_empty, P_NOFIT, pm1)
    needs_host2 = jnp.where(
        tas_entry, pm2 == P_PREEMPT_RAW, nom.needs_host
    )

    if getattr(arrays, "s_tas", None) is not None:
        # Generic multi-podset TAS entries: batched per-slot feasibility
        # (models.slot_tas) with per-ENTRY assumed-usage threading — the
        # host's ``assumed`` dict is scoped to one workload's
        # update_for_tas call, so entries must not see each other's
        # simulated takes (per_lane=True). The [W,T,D,R] accumulator is
        # affordable because this branch only compiles when a
        # multi-podset TAS entry exists (small TAS cycles; the flagship
        # configs have none); a compact multi-TAS row index is the
        # round-5 refinement if W-wide TAS cycles appear.
        sctx = _slot_tas.slot_ctx(arrays, nom.s_flavor)
        s_do = sctx.stas & sctx.t_valid

        def slot_feas(usage_all):
            return _slot_tas.place_slots(
                arrays.tas_topo, usage_all, sctx, s_do, per_lane=True
            ).ok

        stas_entry = (
            jnp.any(arrays.s_tas, axis=1) & arrays.w_active
        )
        sfeas_now = slot_feas(arrays.tas_usage0) & ~arrays.w_tas_invalid
        sfeas_empty = slot_feas(
            jnp.zeros_like(arrays.tas_usage0)
        ) & ~arrays.w_tas_invalid
        sdown = stas_entry & (pm2 == P_FIT) & ~sfeas_now
        pm3 = jnp.where(
            sdown,
            jnp.where(arrays.never_preempts[arrays.w_cq],
                      P_NO_CANDIDATES, P_PREEMPT_RAW),
            pm2,
        )
        spre = stas_entry & (
            (pm3 == P_PREEMPT_RAW) | (pm3 == P_NO_CANDIDATES)
        )
        pm2 = jnp.where(spre & ~sfeas_empty, P_NOFIT, pm3)
        needs_host2 = jnp.where(
            stas_entry, pm2 == P_PREEMPT_RAW, needs_host2
        )
        downgrade = downgrade | sdown
    return nom._replace(best_pmode=pm2, needs_host=needs_host2), downgrade


def _finish_outputs(arrays, nom, final_usage, admitted, preempting, order,
                    victims=None, variant=None, partial_count=None,
                    tas_takes=None, tas_leader_takes=None, s_tas_takes=None,
                    converged=None, fp_rounds=None, slot_rounds=None):
    """Decode the admission planes into the per-workload outcome nest and
    assemble CycleOutputs — shared by the scan, fixed-point and hybrid
    cycle factories so every kernel reports decisions identically."""
    outcome = jnp.where(
        ~arrays.w_active,
        OUT_NOFIT,
        jnp.where(
            nom.needs_host,
            OUT_NEEDS_HOST,
            jnp.where(
                admitted,
                OUT_ADMITTED,
                jnp.where(
                    preempting,
                    OUT_PREEMPTING,
                    jnp.where(
                        nom.best_pmode == P_FIT,
                        OUT_FIT_SKIPPED,
                        jnp.where(
                            nom.best_pmode == P_PREEMPT_OK,
                            OUT_FIT_SKIPPED,
                            jnp.where(
                                nom.best_pmode == P_NO_CANDIDATES,
                                OUT_NO_CANDIDATES,
                                OUT_NOFIT,
                            ),
                        ),
                    ),
                ),
            ),
        ),
    ).astype(jnp.int32)
    return CycleOutputs(
        outcome=outcome,
        chosen_flavor=nom.chosen_flavor,
        borrow=nom.best_borrow,
        tried_flavor_idx=nom.tried_flavor_idx,
        usage=final_usage,
        order=order,
        victims=victims,
        victim_variant=variant,
        partial_count=partial_count,
        s_flavor=nom.s_flavor,
        s_pmode=nom.s_pmode,
        s_tried=nom.s_tried,
        tas_takes=tas_takes,
        tas_leader_takes=tas_leader_takes,
        s_tas_takes=s_tas_takes,
        converged=converged,
        fp_rounds=fp_rounds,
        slot_rounds=slot_rounds,
    )


def _resolve_preempt_nominate(arrays, adm, nom):
    """The device-preemption front half shared by the grouped-preempt and
    fixed-point-hybrid cycles: structural eligibility, the flat and
    hierarchical victim-search kernels, and the nominate overrides for
    device-resolved entries. Returns the patched NominateResult plus the
    target planes (victims/variant/success/resolved...)."""
    from kueue_tpu.models.preempt_kernel import preempt_targets

    downgrade = None
    if arrays.tas_topo is not None:
        nom, downgrade = apply_tas_nominate_hook(arrays, nom)

    # Structural eligibility for on-device oracle resolution: the
    # fungibility scan's choice must be independent of the oracle
    # outcome. Slot-layout cycles gate per slot: a preempting slot
    # saw exactly one raw-preempt flavor (its stop is forced), and a
    # non-preempting slot saw none (its choice never consulted the
    # oracle); any other shape defers to the host, because a
    # different oracle verdict would change that slot's flavor and
    # every later slot's accumulated usage.
    base_core = (
        arrays.w_active
        & (nom.best_pmode == P_PREEMPT_RAW)
        & ~arrays.w_has_gates
    )
    base_elig, slot_nom = structural_elig(arrays, nom, base_core)
    if arrays.w_tas is not None:
        # TAS entries may use the kernels' tas_fits-aware searches
        # (flat and hierarchical) when the tree's admitted TAS usage
        # is device-representable and the preempt mode came from
        # nominate (a Fit->Preempt TAS downgrade re-enters the host
        # fungibility scan instead).
        tas_allowed = jnp.zeros_like(base_elig)
        if (arrays.tas_topo is not None
                and arrays.preempt_tas_ok is not None):
            tas_allowed = (
                arrays.w_tas
                & arrays.preempt_tas_ok[arrays.w_cq]
                & ~downgrade
            )
            if arrays.w_tas_has_leader is not None:
                # Leader-group entries keep the host's TAS-aware
                # victim search (the kernels' tas_fits probe has no
                # leader planes).
                tas_allowed = tas_allowed & ~arrays.w_tas_has_leader
        base_elig = base_elig & (~arrays.w_tas | tas_allowed)
    if getattr(arrays, "s_tas", None) is not None:
        # Generic multi-podset TAS entries needing preemption keep
        # the host victim search (per-slot tas_fits probes are not
        # in the kernels); the whole-tree discard keeps the cycle
        # exact.
        base_elig = base_elig & ~jnp.any(arrays.s_tas, axis=1)
    # The hierarchical kernel still reads the legacy single-slot
    # fields; multi-slot / off-RG0 entries on nested trees defer to
    # the host preemptor (the flat kernel is slot-aware).
    base_hier = base_elig
    if arrays.w_simple_slot is not None:
        base_hier = base_hier & arrays.w_simple_slot
    elig = base_elig & arrays.preempt_simple[arrays.w_cq]
    tgt = preempt_targets(
        arrays, adm, nom.chosen_flavor, elig, nom.praw_stop,
        nom.considered, slot_nom=slot_nom,
    )
    if arrays.preempt_hier is not None:
        # Nested lend-free trees: hierarchical victim-search kernel
        # (models/preempt_kernel.hier_targets); the encoder omits the
        # field entirely when no such tree exists this cycle.
        from kueue_tpu.models.preempt_kernel import hier_targets

        elig_h = base_hier & arrays.preempt_hier[arrays.w_cq]
        tgt_h = hier_targets(
            arrays, adm, nom.chosen_flavor, elig_h, nom.praw_stop,
            nom.considered,
        )
        hm = elig_h
        tgt = tgt.__class__(
            victims=jnp.where(hm[:, None], tgt_h.victims, tgt.victims),
            variant=jnp.where(hm[:, None], tgt_h.variant, tgt.variant),
            success=jnp.where(hm, tgt_h.success, tgt.success),
            resolved_nc=jnp.where(
                hm, tgt_h.resolved_nc, tgt.resolved_nc
            ),
            resolved=jnp.where(hm, tgt_h.resolved, tgt.resolved),
            borrow_after=jnp.where(
                hm, tgt_h.borrow_after, tgt.borrow_after
            ),
        )
    nom = nom._replace(
        best_pmode=jnp.where(
            tgt.success, P_PREEMPT_OK,
            jnp.where(tgt.resolved_nc, P_NO_CANDIDATES,
                      nom.best_pmode),
        ),
        best_borrow=jnp.where(
            tgt.resolved, tgt.borrow_after, nom.best_borrow
        ),
        needs_host=nom.needs_host & ~tgt.resolved,
    )
    return nom, tgt


def make_grouped_cycle(s_max: int = 0, preempt: bool = False,
                       unroll: int = 2, n_levels: int = MAX_DEPTH + 1,
                       mesh=None):
    """Build a jittable grouped cycle; s_max=0 means exact (W slots).

    kernel-entry: cycle_grouped_preempt

    (No gate-requires markers: the grouped-preempt scan is the driver's
    unconditional default — exact for every device-compatible cycle
    shape.)

    With ``preempt=True`` the cycle takes a third AdmittedArrays argument
    and resolves classical preemption on device for eligible entries
    (models/preempt_kernel.py): the oracle + full victim search run in the
    nomination phase against cycle-start usage (matching scheduler.go:629),
    resolved entries get exact pmodes/borrows for the admission order, and
    the scan designates victims with overlap/fit semantics."""

    finish = _finish_outputs

    def apply_partial(arrays, nom, adm=None, targets=None):
        nom, new_req, partial_count, tgt2 = partial_search(
            arrays, arrays.usage, nom, n_levels=n_levels,
            adm=adm, targets=targets,
        )
        arrays = arrays._replace(w_req=new_req)
        if arrays.s_req is not None:
            arrays = arrays._replace(
                s_req=arrays.s_req.at[:, 0].set(new_req)
            )
        return arrays, nom, partial_count, tgt2

    if not preempt:
        def impl(arrays: CycleArrays, ga: GroupArrays) -> CycleOutputs:
            usage = arrays.usage
            nom = nominate(arrays, usage, n_levels=n_levels)
            partial_count = None
            if arrays.w_partial is not None:
                arrays, nom, partial_count, _ = apply_partial(arrays, nom)
            order = admission_order(arrays, nom)
            s = s_max if s_max > 0 else arrays.w_cq.shape[0]
            res = admit_scan_grouped(
                arrays, ga, nom, usage, order, s, unroll=unroll,
                n_levels=n_levels, mesh=mesh,
            )
            return finish(arrays, nom, res.usage, res.admitted,
                          res.preempting, order,
                          partial_count=partial_count,
                          tas_takes=res.tas_takes,
                          tas_leader_takes=res.tas_leader_takes,
                          s_tas_takes=res.s_tas_takes,
                          slot_rounds=res.slot_rounds)

        return impl

    def impl_preempt(arrays: CycleArrays, ga: GroupArrays,
                     adm) -> CycleOutputs:
        usage = arrays.usage
        nom = nominate(arrays, usage, n_levels=n_levels)
        nom, tgt = _resolve_preempt_nominate(arrays, adm, nom)
        partial_count = None
        if arrays.w_partial is not None:
            # The search runs after the full-count preemption resolution
            # (reference scheduler.go:803: the reducer only runs when the
            # full assignment is neither Fit nor Preempt-with-targets);
            # its probes consult the flat victim-search kernel, and a
            # winning preempt probe's victims replace the entry's targets.
            arrays, nom, partial_count, tgt2 = apply_partial(
                arrays, nom, adm=adm, targets=tgt
            )
            if tgt2 is not None:
                tgt = tgt2
        order = admission_order(arrays, nom)
        s = s_max if s_max > 0 else arrays.w_cq.shape[0]
        res = admit_scan_grouped(
            arrays, ga, nom, usage, order, s, adm=adm, targets=tgt,
            unroll=unroll, n_levels=n_levels, mesh=mesh,
        )
        return finish(arrays, nom, res.usage, res.admitted,
                      res.preempting, order,
                      victims=tgt.victims, variant=tgt.variant,
                      partial_count=partial_count,
                      tas_takes=res.tas_takes,
                      tas_leader_takes=res.tas_leader_takes,
                      s_tas_takes=res.s_tas_takes,
                      slot_rounds=res.slot_rounds)

    return impl_preempt


cycle_grouped = jax.jit(make_grouped_cycle())
cycle_grouped_preempt = jax.jit(make_grouped_cycle(preempt=True))


# ---------------------------------------------------------------------------
# Fixed-point admission
# ---------------------------------------------------------------------------
#
# The grouped scan's per-tree bookkeeping (node-local quota absorption on
# the way up, the root-first availability walk on the way down —
# resource_node.go:67 localQuota / hierarchical available()) is a pure
# function of the base usage plus the admission-order prefix of earlier
# entries' contributions at every chain node. Those prefixes are
# segmented exclusive prefix sums per (node, flavor) — so greedy
# admission becomes a monotone-bounds fixed point instead of a
# sequential scan:
#   * an entry that fits even when ALL undecided earlier entries are
#     counted (over-estimate) is definitely admitted;
#   * an entry that cannot fit even when NO undecided earlier entry is
#     counted (under-estimate) is definitely rejected;
#   * the first undecided entry of each cohort tree always has an exact
#     prefix, so every round decides at least one entry per tree.
# Monotonicity survives lending limits because every walk quantity
# (node-local absorption, stored+borrow clamp, root slack) is monotone
# non-increasing in the contribution vector, and the bubbled arrival of
# a contribution at an ancestor is monotone non-decreasing in it.
# Expected rounds: a handful; worst case max-entries-per-tree.
#
# Chain levels are keyed by ABSOLUTE tree depth (root = depth 0,
# quota_ops convention), not by per-entry chain position: two CQs of
# different depths sharing an interior cohort must land that cohort in
# the same prefix segment or its usage is undercounted.

_INF64 = (jnp.int64(1) << 61)


def _cumsum0(x):
    """Axis-0 cumulative sum as an explicit Hillis-Steele shift-add ladder
    (log2(n) elementwise adds). The native jnp.cumsum lowering for int64
    on TPU emits a u32-pair reduce-window whose scoped-vmem scratch
    overflows the 16M limit at 50k-long axes; plain shifted adds lower to
    simple fusions with no scratch at all."""
    n = x.shape[0]
    if n <= 1024:
        return jnp.cumsum(x, axis=0)
    pad_cfg = [(0, 0)] * (x.ndim - 1)
    k = 1
    while k < n:
        shifted = jnp.pad(x, [(k, 0)] + pad_cfg)[:n]
        x = x + shifted
        k *= 2
    return x


def _seg_excl_prefix(sorted_vals, head):
    """Exclusive prefix sums within segments. sorted_vals: [W,...] in sorted
    order; head: bool[W] marking segment starts. Returns [W,...].

    The per-position segment base is recovered by scattering each head's
    global prefix into its segment slot (segment ids = cumsum(head)-1)
    and gathering back — no cumulative-max scan needed."""
    c = _cumsum0(sorted_vals)
    excl = c - sorted_vals  # global exclusive prefix
    w = head.shape[0]
    seg_ids = _cumsum0(head.astype(jnp.int32)) - 1
    head_b = head.reshape((w,) + (1,) * (sorted_vals.ndim - 1))
    base = jnp.zeros_like(excl).at[seg_ids].add(
        jnp.where(head_b, excl, 0), mode="drop"
    )
    return excl - base[seg_ids]


def _vmem_barrier(x):
    """optimization_barrier with a registered vmap rule. The primitive
    ships without one (NotImplementedError: Batching rule for
    'optimization_barrier'), which broke vmapping admit_fixedpoint from
    the what-if engine's batched rollout. The barrier is semantically the
    identity, so batching it is just binding it on the batched operands
    with the batch dims passed through."""
    return jax.lax.optimization_barrier(x)


def _register_barrier_batching() -> None:
    try:
        from jax._src.lax.lax import optimization_barrier_p
        from jax.interpreters import batching
    except ImportError:  # pragma: no cover - jax internals moved
        return
    if optimization_barrier_p in batching.primitive_batchers:
        return

    def _rule(args, dims):
        return optimization_barrier_p.bind(*args), dims

    batching.primitive_batchers[optimization_barrier_p] = _rule


_register_barrier_batching()


def admit_fixedpoint(
    arrays: CycleArrays,
    ga: GroupArrays,
    nom: NominateResult,
    usage: jnp.ndarray,
    order: jnp.ndarray,
    max_rounds: int = 64,
    n_levels: int = MAX_DEPTH + 1,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Order-exact admission equivalent to admit_scan_grouped (including
    lending-limit trees), computed in O(rounds) fully-vectorized passes.

    Returns ``(final_usage, admitted, rounds, converged)`` — ``converged``
    is False when the rounds cap expired with entries still undecided, in
    which case the planes are NOT exact and the caller must discard the
    cycle (driver: contained host fallback)."""
    tree = arrays.tree
    w_n = arrays.w_cq.shape[0]
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]

    # Static per-cycle quantities -------------------------------------------
    rank = jnp.zeros(w_n, dtype=jnp.int64).at[order].set(
        jnp.arange(w_n, dtype=jnp.int64)
    )
    parent = jnp.where(tree.parent < 0, jnp.arange(tree.n_nodes), tree.parent)
    chain_cols = [arrays.w_cq.astype(jnp.int32)]
    for _ in range(n_levels - 1):
        chain_cols.append(parent[chain_cols[-1]].astype(jnp.int32))
    chains = jnp.stack(chain_cols, axis=1)  # [W, L] CQ-first node ids

    # Depth-aligned chains: column k holds the entry's ancestor at
    # ABSOLUTE tree depth k (root first), so a shared interior cohort
    # lands in one prefix segment no matter how deep each CQ under it
    # sits. Columns past the CQ's own depth are off-chain (masked).
    depth_w = tree.depth[arrays.w_cq].astype(jnp.int32)  # [W]
    k_iota = jnp.arange(n_levels, dtype=jnp.int32)
    al_idx = jnp.clip(depth_w[:, None] - k_iota[None, :], 0, n_levels - 1)
    aligned = jnp.take_along_axis(chains, al_idx, axis=1)  # [W,L]
    on_chain = k_iota[None, :] <= depth_w[:, None]  # [W,L]

    # Every entry reads and writes a single flavor plane, so all per-entry
    # tensors are [W,R] plane slices and the per-depth segments are keyed
    # by (node, flavor) — a factor-F cut in the per-round data volume.
    fcl = jnp.clip(nom.chosen_flavor, 0, f_n - 1)
    cell_mask = (
        (nom.chosen_flavor[:, None] >= 0)
        & (arrays.w_req > 0)
        & arrays.covered[arrays.w_cq]
    )  # [W,R]
    delta = jnp.where(cell_mask, arrays.w_req, 0).astype(jnp.int64)

    deferred = nom.needs_host
    is_fit = arrays.w_active & (nom.best_pmode == P_FIT) & ~deferred
    is_nc = (
        arrays.w_active
        & (nom.best_pmode == P_NO_CANDIDATES)
        & ~arrays.can_always_reclaim[arrays.w_cq]
        & ~deferred
    )
    borrowing = nom.best_borrow > 0
    nominal_c = tree.nominal[arrays.w_cq, fcl]  # [W,R]
    has_bl_c = tree.has_borrow_limit[arrays.w_cq, fcl]
    bl_c = tree.borrow_limit[arrays.w_cq, fcl]

    # Per-depth flavor-plane slices of the scan's node terms, [W,L,R].
    fcol = fcl[:, None]
    u0_al = usage[aligned, fcol]
    lq_al = quota_ops.local_quota(tree)[aligned, fcol]
    subtree_al = tree.subtree_quota[aligned, fcol]
    bl_al = tree.borrow_limit[aligned, fcol]
    has_bl_al = tree.has_borrow_limit[aligned, fcol]
    stored_al = sat_sub(subtree_al, lq_al)

    # Per-depth sorted orders (static): entries sorted by ((depth-k node,
    # flavor), rank) — contributions within a segment share the plane.
    perms = []
    heads = []
    inv_perms = []
    for k in range(n_levels):
        seg_id = aligned[:, k].astype(jnp.int64) * f_n + fcl
        key = seg_id * (w_n + 1) + rank
        perm = jnp.argsort(key)
        seg_sorted = seg_id[perm]
        head = jnp.concatenate([
            jnp.ones(1, bool), seg_sorted[1:] != seg_sorted[:-1]
        ])
        inv = jnp.zeros(w_n, dtype=jnp.int32).at[perm].set(
            jnp.arange(w_n, dtype=jnp.int32)
        )
        perms.append(perm)
        heads.append(head)
        inv_perms.append(inv)

    def bubble(contrib):
        """Deepest-first absorption pass mirroring the scan's usage
        bubbling: each entry's contribution enters at its CQ depth, the
        node-local quota headroom (computed against base usage plus the
        admission-rank-exclusive prefix of earlier arrivals) absorbs what
        it can, and the remainder arrives at the parent depth. Returns
        (u_cols: per-depth [W,R] step-time usage, pre_cq [W,R] the
        earlier-arrivals prefix at the entry's own CQ, arrive_cols:
        per-depth [W,R] amount arriving — the node's usage growth)."""
        cur = jnp.zeros_like(contrib)
        pre_cq = jnp.zeros_like(contrib)
        u_cols = [None] * n_levels
        arrive_cols = [None] * n_levels
        for k in range(n_levels - 1, -1, -1):
            at_cq = (depth_w == k)[:, None]
            cur = cur + jnp.where(at_cq, contrib, 0)
            arrive_cols[k] = cur
            perm, head, inv = perms[k], heads[k], inv_perms[k]
            pre = _seg_excl_prefix(cur[perm], head)[inv]
            pre_cq = jnp.where(at_cq, pre, pre_cq)
            u_k = u0_al[:, k] + pre
            u_cols[k] = u_k
            if k > 0:
                # resource_node.go:67 localQuota absorption; entries
                # shallower than k carry cur == 0 here, so their lanes
                # are inert. The barrier keeps XLA from fusing every
                # depth's segmented prefix into one kernel, whose
                # combined scoped buffers overflow the TPU's 16M vmem
                # scratch limit.
                l_avail = jnp.maximum(0, sat_sub(lq_al[:, k], u_k))
                cur = _vmem_barrier(jnp.maximum(0, sat_sub(cur, l_avail)))
        return u_cols, pre_cq, arrive_cols

    def chain_avail(contrib):
        """Availability at every entry's CQ given assumed per-entry plane
        contributions [W,R] — the scan's root-first walk (local
        availability + borrow-clamped parent headroom per node) evaluated
        against the bubbled step-time usage. Returns (avail [W,R],
        pre_cq [W,R])."""
        u_cols, pre_cq, _arrive = bubble(contrib)
        avail = sat_sub(subtree_al[:, 0], u_cols[0])  # root slack
        for k in range(1, n_levels):
            u_k = u_cols[k]
            l_avail = jnp.maximum(0, sat_sub(lq_al[:, k], u_k))
            used_in_parent = jnp.maximum(0, sat_sub(u_k, lq_al[:, k]))
            with_max = sat_add(
                sat_sub(stored_al[:, k], used_in_parent), bl_al[:, k]
            )
            clamped = jnp.where(
                has_bl_al[:, k], jnp.minimum(with_max, avail), avail
            )
            stepped = sat_add(l_avail, clamped)
            avail = _vmem_barrier(
                jnp.where(on_chain[:, k][:, None], stepped, avail)
            )
        return avail, pre_cq  # [W,R] each

    def body(state):
        admitted, rejected, reserved, decided, changed, rounds = state
        undecided = ~decided

        contrib_lo = jnp.where(admitted[:, None], delta, 0) + reserved
        maybe = undecided & (is_fit | is_nc)
        contrib_hi = contrib_lo + jnp.where(maybe[:, None], delta, 0)

        avail_lo, pre_cq_hi = chain_avail(contrib_hi)  # worst case
        avail_hi, pre_cq_lo = chain_avail(contrib_lo)  # best case
        exact = jnp.all(avail_lo == avail_hi, axis=1)

        fits_worst = jnp.all((delta <= avail_lo) | ~cell_mask, axis=1)
        fits_best = jnp.all((delta <= avail_hi) | ~cell_mask, axis=1)

        new_admit = undecided & is_fit & fits_worst
        new_reject = undecided & is_fit & ~fits_best
        # Exact prefixes decide anything (covers first-undecided-per-tree).
        new_admit = new_admit | (undecided & is_fit & exact & fits_best)
        new_reject = new_reject | (undecided & is_fit & exact & ~fits_best)

        # NO_CANDIDATES reserves finalize once the prefix AT THE CQ NODE is
        # exact (the clipped amount needs the true usage there —
        # scheduler.go:738 quotaResourcesToReserve). avail equality is not
        # enough: the min can coincide while the CQ-level prefix differs.
        exact0 = jnp.all(pre_cq_lo == pre_cq_hi, axis=1)
        nc_final = undecided & is_nc & exact0
        u_c = usage[arrays.w_cq, fcl] + pre_cq_lo
        reserve_borrowing = jnp.where(
            has_bl_c,
            jnp.minimum(delta, sat_sub(sat_add(nominal_c, bl_c), u_c)),
            delta,
        )
        reserve_plain = jnp.maximum(
            0, jnp.minimum(delta, sat_sub(nominal_c, u_c))
        )
        res_amt = jnp.where(
            borrowing[:, None], reserve_borrowing, reserve_plain
        )
        res_amt = jnp.where(cell_mask, res_amt, 0)
        reserved = jnp.where(nc_final[:, None], res_amt, reserved)

        newly = new_admit | new_reject | nc_final
        admitted = admitted | new_admit
        rejected = rejected | new_reject
        decided = decided | newly | (undecided & ~is_fit & ~is_nc)
        return (admitted, rejected, reserved, decided, jnp.any(newly),
                rounds + 1)

    def cond(state):
        _adm, _rej, _res, decided, changed, rounds = state
        return changed & (rounds < max_rounds) & ~jnp.all(decided)

    init = (
        jnp.zeros(w_n, bool),
        jnp.zeros(w_n, bool),
        jnp.zeros((w_n, r_n), jnp.int64),
        ~(is_fit | is_nc),  # everything else is decided from the start
        jnp.bool_(True),
        jnp.int32(0),
    )
    admitted, _rej, reserved, decided, _chg, rounds = jax.lax.while_loop(
        cond, body, init
    )
    converged = jnp.all(decided)

    # Final usage: base + finalized contributions bubbled through the
    # lending-limit absorption — the amount ARRIVING at each depth is what
    # that node's usage grows by (the scan stores exactly its deltas).
    contrib = jnp.where(admitted[:, None], delta, 0) + reserved
    _u, _pre, arrive_cols = bubble(contrib)
    final_usage = usage
    for k in range(n_levels):
        arrive = jnp.where(on_chain[:, k][:, None], arrive_cols[k], 0)
        add_k = jnp.zeros_like(usage).at[aligned[:, k], fcl].add(
            arrive, mode="drop"
        )
        final_usage = quota_ops.sat(final_usage + add_k)
    return final_usage, admitted, rounds, converged


def make_fixedpoint_cycle(max_rounds: int = 64,
                          n_levels: int = MAX_DEPTH + 1):
    """Grouped-cycle equivalent using the fixed-point admission pass.

    kernel-entry: cycle_fixedpoint
    gate-requires: not idx.has_partial
    gate-requires: arrays.tas_topo is None

    Exact for every cycle meeting the preconditions above — including
    lending-limit trees — provided the loop converges (the CycleOutputs
    ``converged`` flag is checked by the driver; non-convergence triggers
    a contained host fallback). Entries whose resolution needs the
    preemption oracle stay ``needs_host`` and their trees fall back to
    the host path, exactly as with the grouped scan's deferred entries;
    the hybrid cycle below settles those on device instead."""

    def impl(arrays: CycleArrays, ga: GroupArrays) -> CycleOutputs:
        usage = arrays.usage
        nom = nominate(arrays, usage, n_levels=n_levels)
        order = admission_order(arrays, nom)
        final_usage, admitted, rounds, converged = admit_fixedpoint(
            arrays, ga, nom, usage, order, max_rounds, n_levels=n_levels
        )
        preempting = jnp.zeros_like(admitted)
        return _finish_outputs(
            arrays, nom, final_usage, admitted, preempting, order,
            converged=converged, fp_rounds=rounds,
        )

    return impl


def make_hybrid_preempt_cycle(s_resid: int, max_rounds: int = 64,
                              unroll: int = 2,
                              n_levels: int = MAX_DEPTH + 1):
    """Fixed-point admission with a short residual preemption scan.

    kernel-entry: cycle_fixedpoint_hybrid
    gate-requires: not idx.has_partial
    gate-requires: arrays.tas_topo is None

    The preemption front half (oracle + victim search) runs exactly as in
    the grouped-preempt cycle; then cohort trees are routed by quota
    independence: a tree holding at least one device-resolved preemptor
    (P_PREEMPT_OK) — or, on slot-layout cycles, any active head that is
    not a simple single-slot entry (``~w_simple_slot``: the fixed-point
    pass reads only the legacy single-plane fields) — needs the scan's
    sequential step semantics, every other tree's admissions settle in
    the fixed-point rounds. The residual scan runs with ``s_resid`` slots
    per group — the driver computes a host-side bound (max active heads
    among trees that can preempt or carry multi-slot heads) so the
    residual is exact; victims and quota cells never cross trees, so the
    two partitions compose bit-identically to ``cycle_grouped_preempt``."""
    if s_resid < 1:
        raise ValueError("s_resid must be >= 1 (use cycle_fixedpoint "
                         "when no tree can preempt)")

    def impl(arrays: CycleArrays, ga: GroupArrays, adm) -> CycleOutputs:
        usage = arrays.usage
        nom = nominate(arrays, usage, n_levels=n_levels)
        nom, tgt = _resolve_preempt_nominate(arrays, adm, nom)
        order = admission_order(arrays, nom)

        g_n = ga.node_sel.shape[0]
        g_w = ga.flat_to_group[arrays.w_cq]
        resid_w = arrays.w_active & (nom.best_pmode == P_PREEMPT_OK)
        if arrays.s_req is not None:
            # Multi-slot (or off-RG0) heads need the scan's per-slot
            # placement; their whole trees go residual so tournament
            # interleaving stays exact per tree. Simple single-slot
            # entries are faithfully described by the legacy planes the
            # fixed-point pass reads.
            if arrays.w_simple_slot is not None:
                resid_w = resid_w | (
                    arrays.w_active & ~arrays.w_simple_slot
                )
            else:
                resid_w = resid_w | arrays.w_active
        g_resid = jnp.zeros(g_n, bool).at[g_w].max(resid_w, mode="drop")
        in_resid = g_resid[g_w] & arrays.w_active

        fp_usage, fp_admit, rounds, converged = admit_fixedpoint(
            arrays._replace(w_active=arrays.w_active & ~in_resid),
            ga, nom, usage, order, max_rounds, n_levels=n_levels,
        )
        res = admit_scan_grouped(
            arrays._replace(w_active=in_resid), ga, nom, usage, order,
            s_resid, adm=adm, targets=tgt, unroll=unroll,
            n_levels=n_levels,
        )
        # Cohort trees share no quota cells, so each partition's usage
        # delta touches only its own trees' planes: the merge is additive.
        final_usage = quota_ops.sat(fp_usage + (res.usage - usage))
        admitted = fp_admit | res.admitted
        return _finish_outputs(
            arrays, nom, final_usage, admitted, res.preempting, order,
            victims=tgt.victims, variant=tgt.variant,
            converged=converged, fp_rounds=rounds,
        )

    return impl


cycle_fixedpoint = jax.jit(make_fixedpoint_cycle())


@functools.lru_cache(maxsize=None)
def fixedpoint_cycle_for(max_rounds: int = 64):
    """Jitted pure fixed-point cycle for a rounds cap (shared across
    dispatch + prewarm so each cap compiles once per shape)."""
    if max_rounds == 64:
        return cycle_fixedpoint
    return jax.jit(make_fixedpoint_cycle(max_rounds=max_rounds))


@functools.lru_cache(maxsize=None)
def fixedpoint_cycle_preempt_for(s_resid: int, max_rounds: int = 64):
    """Jitted hybrid cycle for a residual-scan bound (the driver buckets
    the bound on the pow2 ladder so executables are reused)."""
    return jax.jit(
        make_hybrid_preempt_cycle(s_resid, max_rounds=max_rounds)
    )
