"""Device-side fair-sharing preemption: the DRS victim tournament.

Tensor reformulation of the reference's fair preemption search
(pkg/scheduler/preemption/preemption.go:362-548 fairPreemptions +
preemption/fairsharing/{strategy,ordering,target,least_common_ancestor}.go),
mirrored host-side by kueue_tpu/scheduler/fair_preemption.py.

Per eligible preemptor entry the kernel runs the exact sequential search as
a bounded ``lax.while_loop`` (the tournament is inherently a data-dependent
greedy — each removal changes every DominantResourceShare — so the
sequential structure is kept and the per-step *math* is vectorized):

  * candidates: within-CQ by policy + cross-CQ from borrowing CQs, ordered
    by CandidatesOrdering (evicted first, other-CQ first, priority,
    quota-reservation time, UID);
  * strategy S1: descend from the root to the highest-DRS ClusterQueue with
    remaining candidates (cohorts pruned when not borrowing and off the
    preemptor's path), compare DRS at the almost-least-common-ancestors,
    apply LessThanOrEqualToFinalShare / LessThanInitialShare, remove until
    the preemptor fits; failures go to the retry list;
  * strategy S2 (rule S2-b) over the retries, one candidate per CQ;
  * fill-back minimization replaying the host's list semantics.

Like the classical kernel (models/preempt_kernel.py), a probe axis runs the
single-FlavorResource oracle searches the flavor assigner consults
(preemption_oracle.go SimulatePreemption) alongside the full multi-resource
search, so cell preemption modes and post-removal borrow heights are exact.

Exactness preconditions (encoder-gated): no lending limits in the tree
(usage bubbles fully; availability is the chain min), admitted usage fully
mappable onto the [F, R] cells, single-praw-flavor entries with
oracle-independent flavor choice, no TAS, no preemption gates.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kueue_tpu.models.encode import CycleArrays
from kueue_tpu.models.preempt_kernel import AdmittedArrays, PreemptTargets
from kueue_tpu.ops import quota_ops
from kueue_tpu.ops.quota_ops import MAX_DEPTH, sat_add, sat_sub

_INF64 = jnp.int64(1) << 61
_NEG = -(jnp.int64(1) << 60)
_FINF = jnp.float64(jnp.inf)

# Variant codes surfaced to the driver (reason mapping):
FV_WITHIN_CQ = 1  # InClusterQueueReason
FV_FAIR_SHARING = 5  # InCohortFairSharingReason
FV_RECLAMATION = 6  # InCohortReclamationReason (preemptor within nominal)

STRAT_S2A = 0  # LessThanOrEqualToFinalShare
STRAT_S2B = 1  # LessThanInitialShare


def _drs_key_at(usage_node, sq_node, lend_par, wgt):
    """DRS comparison key of one node: (borrowing, zwb, val).
    usage_node/sq_node: [F,R]; lend_par: f64[R]; wgt: f64 scalar."""
    borrowed = jnp.sum(
        jnp.maximum(0, usage_node - sq_node), axis=0
    ).astype(jnp.float64)  # [R]
    ratio = jnp.max(
        jnp.where((lend_par > 0) & (borrowed > 0),
                  borrowed * 1000.0 / lend_par, 0.0)
    )
    borrowing = jnp.any(borrowed > 0)
    zwb = (wgt == 0.0) & (ratio > 0.0)
    val = jnp.where(
        zwb, ratio,
        jnp.where(ratio == 0.0, 0.0,
                  ratio / jnp.where(wgt == 0.0, 1.0, wgt)),
    )
    return borrowing, zwb, val


def _key_gt(z1, v1, z2, v2):
    """compare_drs(a, b) > 0 (a preferred for preemption)."""
    return jnp.where(
        z1 & z2, v1 > v2, jnp.where(z1, True, jnp.where(z2, False, v1 > v2))
    )


def _key_ge(z1, v1, z2, v2):
    return jnp.where(
        z1 & z2, v1 >= v2,
        jnp.where(z1, True, jnp.where(z2, False, v1 >= v2)),
    )


def _key_le(z1, v1, z2, v2):
    return ~_key_gt(z1, v1, z2, v2)


def _key_lt(z1, v1, z2, v2):
    return ~_key_ge(z1, v1, z2, v2)


def fair_preempt_targets(
    arrays: CycleArrays,
    adm: AdmittedArrays,
    chosen_flavor: jnp.ndarray,  # i32[W]
    eligible: jnp.ndarray,  # bool[W]
    praw_stop: jnp.ndarray,  # bool[W]
    considered: jnp.ndarray,  # i32[W]
) -> PreemptTargets:
    tree = arrays.tree
    usage0 = arrays.usage
    sq = tree.subtree_quota
    n = tree.n_nodes
    f_n, r_n = tree.nominal.shape[1], tree.nominal.shape[2]
    a_n = adm.cq.shape[0]
    a_iota = jnp.arange(a_n)
    n_iota = jnp.arange(n)

    parent = jnp.where(tree.parent < 0, n_iota, tree.parent)
    chain_cols = [n_iota.astype(jnp.int32)]
    for _ in range(MAX_DEPTH):
        chain_cols.append(parent[chain_cols[-1]].astype(jnp.int32))
    chain_n = jnp.stack(chain_cols, axis=1)  # [N, D+1]
    root_of = chain_n[:, MAX_DEPTH]
    has_par_n = tree.parent >= 0

    # in_sub[b, d]: b on d's root path (usage at b includes d's subtree).
    in_sub = quota_ops.ancestor_matrix(tree)

    pot_all = quota_ops.potential_available_all(tree)
    lendable = jnp.sum(pot_all, axis=1).astype(jnp.float64)  # [N,R]
    weight = arrays.node_weight
    is_cq = arrays.node_is_cq
    avail0 = quota_ops.available_all(tree, usage0)
    # T_b for chain-min availability (no lending limits precondition).
    t_node = jnp.where(
        (tree.parent < 0)[:, None, None],
        sq,
        jnp.where(
            tree.has_borrow_limit, sat_add(sq, tree.borrow_limit), _INF64
        ),
    )
    pwn_gate = arrays.fair_pwn  # FairSharingPreemptWithinNominal enabled
    strat0 = arrays.fair_strat0
    has_s2 = arrays.fair_has_s2

    adm_usage_full = adm.usage  # [A,F,R]

    def per_w(c, f0, req_full, prio, ts, elig_w, stopped_at_praw, consid):
        f = jnp.maximum(f0, 0)
        full_active = (req_full > 0) & arrays.covered[c]  # [R]
        contested_full = full_active & (req_full > avail0[c, f])
        au = adm_usage_full[:, f, :]  # [A,R]

        same = adm.cq == c
        same_root = root_of[adm.cq] == root_of[c]
        cross = same_root & ~same & has_par_n[c]
        lower = prio > adm.prio
        neq = (prio == adm.prio) & (ts < adm.ts)

        def pol_ok(pol):
            return jnp.where(
                pol == 3, jnp.ones_like(lower),
                jnp.where(pol == 2, lower | neq,
                          jnp.where(pol == 1, lower,
                                    jnp.zeros_like(lower))),
            )

        pol_w = arrays.policy_within[c]
        pol_r = arrays.policy_reclaim[c]

        on_path_c = in_sub[:, c]  # [N] ancestors-or-self of c
        chain_c = chain_n[c]  # [D+1]
        chain_c_repeat = jnp.concatenate(
            [jnp.zeros(1, bool), chain_c[1:] == chain_c[:-1]]
        )

        # Almost-LCA nodes per candidate CQ (static): first chain position
        # of d that lies on c's path is the LCA; one below on each side.
        def alcas(d):
            chain_d = chain_n[d]
            on = on_path_c[chain_d]  # [D+1]
            j_lca = jnp.argmax(on)  # first True
            tgt = chain_d[jnp.maximum(j_lca - 1, 0)]
            lca = chain_d[j_lca]
            pre_pos = jnp.argmax(chain_c == lca)
            pre = chain_c[jnp.maximum(pre_pos - 1, 0)]
            return pre.astype(jnp.int32), tgt.astype(jnp.int32)

        pre_alca_of, tgt_alca_of = jax.vmap(alcas)(n_iota)  # [N], [N]

        def search(active_req, contested, req_vec):
            """One fair search. Returns (success, victims[A], variant[A],
            borrow_after i32)."""
            uses = jnp.any(contested[None, :] & (au > 0), axis=1)
            cq_borrow = jnp.any(
                contested[None, :]
                & (usage0[adm.cq, f, :] > sq[adm.cq, f, :]),
                axis=1,
            )
            cand = adm.active & uses & (
                (same & (pol_w != 0) & pol_ok(pol_w))
                | (cross & (pol_r != 0) & pol_ok(pol_r) & cq_borrow)
            )

            # CandidatesOrdering rank (static per search).
            rank_pos = jnp.lexsort((
                adm.uid_rank, -adm.qr_time, adm.prio,
                same.astype(jnp.int32), (~adm.evicted).astype(jnp.int32),
                (~cand).astype(jnp.int32),
            ))
            rank = jnp.zeros(a_n, jnp.int32).at[rank_pos].set(
                a_iota.astype(jnp.int32)
            )
            rank = jnp.where(cand, rank, jnp.int32(a_n))

            # Simulated preemptor usage on c's path (full bubble).
            add_cell = jnp.zeros((f_n, r_n), jnp.int64).at[f].set(
                jnp.where(active_req, req_vec, 0)
            )
            sim_add = jnp.where(
                on_path_c[:, None, None], add_cell[None, :, :], 0
            )

            pwn = pwn_gate & ~jnp.any(
                contested & (usage0[c, f] + add_cell[f] > sq[c, f])
            )

            def usage_now_fn(removed):
                rem = jnp.einsum(
                    "na,afr->nfr",
                    (removed[None, :] & in_sub[:, adm.cq]).astype(
                        jnp.int64
                    ),
                    adm_usage_full,
                )
                return usage0 + sim_add - rem

            def drs_all(usage_now):
                """Per-node DRS keys [N]: (borrowing, zwb, val)."""
                borrowed = jnp.sum(
                    jnp.maximum(0, usage_now - sq), axis=1
                ).astype(jnp.float64)  # [N,R]
                lend_par = lendable[parent]  # [N,R]
                ratio = jnp.max(
                    jnp.where((lend_par > 0) & (borrowed > 0),
                              borrowed * 1000.0 / lend_par, 0.0),
                    axis=1,
                )
                borrowing = jnp.any(borrowed > 0, axis=1)
                # Root nodes have no parent: DRS is the zero default.
                ratio = jnp.where(has_par_n, ratio, 0.0)
                borrowing = borrowing & has_par_n
                zwb = (weight == 0.0) & (ratio > 0.0)
                val = jnp.where(
                    zwb, ratio,
                    jnp.where(ratio == 0.0, 0.0,
                              ratio / jnp.where(weight == 0.0, 1.0,
                                                weight)),
                )
                return borrowing, zwb, val

            def fits_removed(removed):
                """workloadFitsForFairSharing: incoming usage removed."""
                u = usage_now_fn(removed) - sim_add
                slack = jnp.where(
                    t_node[chain_c] >= _INF64, _INF64,
                    sat_sub(t_node[chain_c], u[chain_c]),
                )  # [D+1,F,R]
                slack = jnp.where(
                    chain_c_repeat[:, None, None], _INF64, slack
                )
                avail = jnp.min(slack, axis=0)  # [F,R]
                return jnp.all(
                    (req_vec <= avail[f]) | ~active_req
                )

            def drs_at(usage_now, node):
                return _drs_key_at(
                    usage_now[node], sq[node], lendable[parent[node]],
                    weight[node],
                )

            # ---------------- S1 + S2 while_loop ----------------
            # phase: 0 = S1 descend/pop, 1 = S2, 2 = done.
            def cond(st):
                # Step cap: every step consumes a candidate or transitions
                # phase; 4A+16 is a safety net far above any real search.
                return (st["phase"] < 2) & (st["step"] < 4 * a_n + 16)

            def body(st):
                removed = st["removed"]
                consumed = st["consumed"]
                usage_now = usage_now_fn(removed)
                in_s2 = st["phase"] == 1
                pool_retry = jnp.where(in_s2, st["retry"],
                                       jnp.ones(a_n, bool))
                b_all, z_all, v_all = drs_all(usage_now)
                pool = cand & ~consumed & pool_retry
                head_rank = jnp.full(n, jnp.int32(a_n)).at[adm.cq].min(
                    jnp.where(pool, rank, jnp.int32(a_n)), mode="drop"
                )
                alive_cq = is_cq & (head_rank < a_n) & (
                    b_all | (n_iota == c)
                ) & ~(in_s2 & st["s2_dropped"])
                sub_alive = alive_cq
                for d in range(MAX_DEPTH, 0, -1):
                    lvl = (tree.depth == d) & tree.active
                    par_alive = jnp.zeros(n, bool).at[parent].max(
                        jnp.where(lvl, sub_alive, False), mode="drop"
                    )
                    coh = (tree.depth == d - 1) & ~is_cq
                    sub_alive = jnp.where(
                        coh, par_alive & (b_all | on_path_c), sub_alive
                    )

                sticky = st["sticky"]
                need_descent = sticky < 0
                # Sticky CQ may have exhausted its candidates.
                sticky_has = jnp.where(
                    sticky >= 0,
                    head_rank[jnp.maximum(sticky, 0)] < a_n,
                    False,
                )
                need_descent = need_descent | ~sticky_has

                def best(mask, tie_last):
                    any_ = jnp.any(mask)
                    best_z = jnp.any(mask & z_all)
                    m1 = mask & (z_all == best_z)
                    best_v = jnp.max(jnp.where(m1, v_all, -_FINF))
                    m2 = m1 & (v_all == best_v)
                    if tie_last:
                        pick = jnp.max(jnp.where(m2, n_iota, -1))
                    else:
                        best_r = jnp.min(
                            jnp.where(m2, head_rank, jnp.int32(a_n))
                        )
                        m3 = m2 & (head_rank == best_r)
                        pick = jnp.max(jnp.where(m3, n_iota, -1))
                    return any_, pick.astype(jnp.int32), best_z, best_v

                def do_descend(_):
                    root = root_of[c]

                    def desc_body(state):
                        cur, tgt, done = state
                        children = (parent == cur) & (n_iota != cur) & \
                            tree.active
                        cq_any, cq_pick, cq_z, cq_v = best(
                            children & alive_cq, False
                        )
                        co_any, co_pick, co_z, co_v = best(
                            children & ~is_cq & sub_alive, True
                        )
                        go_coh = co_any & (
                            ~cq_any | _key_ge(co_z, co_v, cq_z, cq_v)
                        )
                        new_tgt = jnp.where(
                            go_coh, -1,
                            jnp.where(cq_any, cq_pick, -1),
                        )
                        return (
                            jnp.where(go_coh, co_pick, cur),
                            new_tgt,
                            ~go_coh,
                        )

                    cur0 = root.astype(jnp.int32)
                    tgt0 = jnp.where(
                        is_cq[root] & alive_cq[root], root, -1
                    ).astype(jnp.int32)
                    done0 = is_cq[root] | ~sub_alive[root]
                    state = (cur0, tgt0, done0)
                    for _ in range(MAX_DEPTH + 1):
                        cur, tgt, done = state
                        nc, nt, nd = desc_body((cur, tgt, done))
                        state = (
                            jnp.where(done, cur, nc),
                            jnp.where(done, tgt, nt),
                            done | nd,
                        )
                    return state[1]

                new_target = jax.lax.cond(
                    need_descent, do_descend, lambda _: sticky,
                    operand=None,
                )
                no_target = new_target < 0

                # Visit-start DRS keys (stored when (re)entering a CQ).
                entering = need_descent & ~no_target
                pre_node = pre_alca_of[jnp.maximum(new_target, 0)]
                tgt_node = tgt_alca_of[jnp.maximum(new_target, 0)]
                pz, pv = st["pre_z"], st["pre_v"]
                toz, tov = st["tgold_z"], st["tgold_v"]
                _, ez, ev = drs_at(usage_now, pre_node)
                _, etz, etv = drs_at(usage_now, tgt_node)
                pz = jnp.where(entering, ez, pz)
                pv = jnp.where(entering, ev, pv)
                toz = jnp.where(entering, etz, toz)
                tov = jnp.where(entering, etv, tov)

                # Pop the lowest-rank candidate of the target CQ.
                r_t = head_rank[jnp.maximum(new_target, 0)]
                have = (new_target >= 0) & (r_t < a_n)
                ac = jnp.argmax(rank == r_t).astype(jnp.int32)
                ac = jnp.where(have, ac, 0)
                a_same = same[ac]

                # Strategy evaluation (cross-CQ, not pwn, S1 only).
                u_tgt_after = usage_now[tgt_node] - adm_usage_full[ac]
                _, tnz, tnv = _drs_key_at(
                    u_tgt_after, sq[tgt_node],
                    lendable[parent[tgt_node]], weight[tgt_node],
                )
                s2a_pass = _key_le(pz, pv, tnz, tnv)
                s2b_pass = _key_lt(pz, pv, toz, tov)
                strat_pass = jnp.where(strat0 == STRAT_S2A,
                                       s2a_pass, s2b_pass)
                # S2 rule is always LessThanInitialShare with FRESH keys.
                s2_pass = _key_lt(ez, ev, etz, etv)

                uncond = a_same | (pwn & ~in_s2)
                take = have & jnp.where(
                    in_s2, s2_pass, uncond | strat_pass
                )
                variant_a = jnp.where(
                    a_same, FV_WITHIN_CQ,
                    jnp.where(pwn & ~in_s2, FV_RECLAMATION,
                              FV_FAIR_SHARING),
                )

                removed2 = removed.at[ac].set(
                    removed[ac] | take, mode="drop"
                )
                consumed2 = consumed.at[ac].set(
                    consumed[ac] | have, mode="drop"
                )
                retry2 = st["retry"].at[ac].set(
                    st["retry"][ac] | (have & ~take & ~in_s2),
                    mode="drop",
                )
                order2 = jnp.where(
                    (a_iota == ac) & take & (st["rm_step"][ac] < 0),
                    st["step"], st["rm_step"],
                )
                var2 = jnp.where(
                    (a_iota == ac) & take, variant_a, st["variant"]
                )
                s2_dropped2 = jnp.where(
                    in_s2 & ~no_target,
                    st["s2_dropped"].at[jnp.maximum(new_target, 0)].set(
                        True, mode="drop"
                    ),
                    st["s2_dropped"],
                )

                fit_now = take & fits_removed(removed2)

                # Next sticky: removals re-pick the CQ (host break/continue);
                # strategy failures stay on the CQ (inner while); S2 always
                # re-picks (drop_queue after one pop).
                sticky2 = jnp.where(
                    in_s2 | take | no_target | ~have,
                    jnp.int32(-1),
                    new_target,
                )

                # Phase transitions.
                start_s2 = (~in_s2) & no_target & has_s2 & \
                    jnp.any(st["retry"] & ~removed2)
                # Reset consumed for S2 over the retry set.
                consumed3 = jnp.where(
                    start_s2, consumed2 & ~st["retry"], consumed2
                )
                phase2 = jnp.where(
                    fit_now, 2,
                    jnp.where(
                        start_s2, 1,
                        jnp.where(no_target & ~start_s2, 2, st["phase"]),
                    ),
                ).astype(jnp.int32)

                return {
                    "phase": phase2,
                    "sticky": sticky2,
                    "removed": removed2,
                    "consumed": consumed3,
                    "retry": retry2,
                    "s2_dropped": s2_dropped2,
                    "rm_step": order2,
                    "variant": var2,
                    "pre_z": pz, "pre_v": pv,
                    "tgold_z": toz, "tgold_v": tov,
                    "fit": st["fit"] | fit_now,
                    "step": st["step"] + 1,
                }

            init = {
                "phase": jnp.int32(0),
                "sticky": jnp.int32(-1),
                "removed": jnp.zeros(a_n, bool),
                "consumed": jnp.zeros(a_n, bool),
                "retry": jnp.zeros(a_n, bool),
                "s2_dropped": jnp.zeros(n, bool),
                "rm_step": jnp.full(a_n, -1, jnp.int32),
                "variant": jnp.zeros(a_n, jnp.int32),
                "pre_z": jnp.bool_(False), "pre_v": jnp.float64(0.0),
                "tgold_z": jnp.bool_(False), "tgold_v": jnp.float64(0.0),
                "fit": jnp.bool_(False),
                "step": jnp.int32(0),
            }
            st = jax.lax.while_loop(cond, body, init)
            success = st["fit"]
            removed = st["removed"] & success

            # Fill-back (host list semantics: targets in removal order,
            # last element escapes examination, dropped slots receive the
            # current last element).
            t_count = jnp.sum(removed.astype(jnp.int32))
            slot_of = jnp.where(removed, st["rm_step"], jnp.int32(1 << 30))
            slot_order = jnp.argsort(slot_of).astype(jnp.int32)  # [A]

            # At examination of list position i the host always sees the
            # ORIGINAL i-th removed target (swaps only ever write to
            # already-examined higher positions), so iterating original
            # slots T-2..0 and skipping dropped ones is exact; the last
            # slot (i == t_count-1) is never examined.
            def fb_step(kept, i):
                idx = slot_order[i]
                alive = kept[idx] & (i < t_count - 1)
                test = kept.at[idx].set(False)
                ok = alive & fits_removed(test)
                return jnp.where(ok, test, kept), None

            idxs = jnp.arange(a_n - 2, -1, -1)
            kept, _ = jax.lax.scan(fb_step, removed, idxs)
            victims = kept & success

            # Post-removal borrow height (oracle borrow_after /
            # find_height_of_lowest_subtree_that_fits, lend-free form).
            rem_final = jnp.einsum(
                "na,afr->nfr",
                (victims[None, :] & in_sub[:, adm.cq]).astype(jnp.int64),
                adm_usage_full,
            )
            u_after = usage0 - rem_final

            def borrow_height(u_state):
                val_cell = jnp.where(active_req, req_vec, 0)  # [R]
                fits_j = jnp.all(
                    (u_state[chain_c, f] + val_cell[None, :]
                     <= sq[chain_c, f]) | ~active_req[None, :],
                    axis=1,
                )  # [D+1]
                h = tree.height[chain_c]
                first = jnp.argmax(fits_j)
                any_fit = jnp.any(fits_j)
                root_h = tree.height[root_of[c]]
                return jnp.where(
                    any_fit, h[first], root_h
                ).astype(jnp.int32)

            borrow_after = jnp.where(
                success, borrow_height(u_after), borrow_height(usage0)
            )
            return success, victims, jnp.where(victims, st["variant"], 0), \
                borrow_after

        # Probe axis: slot 0 = full search; slot 1+r = per-cell oracle.
        eye = jnp.eye(r_n, dtype=bool)
        probe_active = jnp.concatenate(
            [full_active[None, :], eye & full_active[None, :]]
        )
        probe_contested = jnp.concatenate(
            [contested_full[None, :], eye & contested_full[None, :]]
        )
        probe_req = jnp.where(probe_active, req_full[None, :], 0)
        succ_p, vict_p, var_p, borrow_p = jax.vmap(search)(
            probe_active, probe_contested, probe_req
        )
        full_success = succ_p[0]
        full_victims = vict_p[0]
        variant = var_p[0]
        cell_success = succ_p[1:]  # [R]

        all_cells_ok = jnp.all(~contested_full | cell_success)
        resolved = elig_w & (
            (consid == 1) | (stopped_at_praw & all_cells_ok)
        )
        success = resolved & full_success
        victims = jnp.where(success, full_victims, False)
        resolved_nc = resolved & ~full_success
        # Per-cell assignment borrow: the single-cell probes return the
        # oracle's post-removal height for contested cells and the plain
        # lowest-fitting-subtree height for fit cells; the assignment's
        # ordering borrow is the max across active cells.
        borrow_after = jnp.max(
            jnp.where(full_active, borrow_p[1:], 0)
        ).astype(jnp.int32)
        return victims, jnp.where(victims, variant, 0), success, \
            resolved_nc, resolved, borrow_after

    victims, variant, success, resolved_nc, resolved, borrow_after = \
        jax.vmap(per_w)(
            arrays.w_cq, chosen_flavor, arrays.w_req, arrays.w_priority,
            arrays.w_timestamp, eligible, praw_stop, considered,
        )
    return PreemptTargets(victims, variant, success, resolved_nc, resolved,
                          borrow_after)
