"""Fair-sharing (DRF) preemption.

Behavioral surface: reference pkg/scheduler/preemption/preemption.go:362-548
and preemption/fairsharing/{strategy,ordering,target,least_common_ancestor}.go.

The tournament walks the cohort tree from the root, repeatedly descending to
the child (Cohort or CQ) with the highest DominantResourceShare that still
has candidates, and applies strategy rules S2-a (LessThanOrEqualToFinalShare)
and S2-b (LessThanInitialShare) between the almost-least-common-ancestors of
the preemptor and the target.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from kueue_tpu.api.constants import (
    IN_CLUSTER_QUEUE_REASON,
    IN_COHORT_FAIR_SHARING_REASON,
    IN_COHORT_RECLAMATION_REASON,
    PreemptionPolicy,
)
from kueue_tpu.cache.resource_node import (
    DRS,
    compare_drs,
    dominant_resource_share,
    negative_drs,
    QuotaNode,
)
from kueue_tpu.cache.snapshot import ClusterQueueSnapshot
from kueue_tpu.core.resources import FlavorResource
from kueue_tpu.core.workload_info import WorkloadInfo
from kueue_tpu.metrics import tracing
from kueue_tpu.utils import features

# Imported lazily by preemption.py to avoid a cycle; keep the import local.


def _strategy_s2a(preemptor_new: DRS, target_old: DRS, target_new: DRS) -> bool:
    """LessThanOrEqualToFinalShare (strategy.go)."""
    return compare_drs(preemptor_new, target_new) <= 0


def _strategy_s2b(preemptor_new: DRS, target_old: DRS, target_new: DRS) -> bool:
    """LessThanInitialShare (strategy.go)."""
    return compare_drs(preemptor_new, target_old) < 0


STRATEGIES: Dict[str, Callable[[DRS, DRS, DRS], bool]] = {
    "LessThanOrEqualToFinalShare": _strategy_s2a,
    "LessThanInitialShare": _strategy_s2b,
}


def fair_preemptions(ctx, strategies: List[str]):
    """reference preemption.go:495 fairPreemptions. ``ctx`` is a
    kueue_tpu.scheduler.preemption.PreemptionCtx."""
    from kueue_tpu.scheduler.preemption import (
        Target,
        candidates_ordering_key,
        satisfies_preemption_policy,
        workload_uses_frs,
    )

    cq = ctx.preemptor_cq
    candidates = _find_candidates(ctx, satisfies_preemption_policy,
                                  workload_uses_frs)
    if tracing.ENABLED:
        tracing.observe("preemption_search_candidates", len(candidates))
    if not candidates:
        return []
    candidates.sort(
        key=lambda c: candidates_ordering_key(c, cq.name, ctx.now)
    )

    # DRS values must include the incoming workload.
    revert_sim = cq.simulate_usage_addition(ctx.requests)
    try:
        fits, targets, retry = _run_first_strategy(
            ctx, candidates, STRATEGIES[strategies[0]], Target,
            candidates_ordering_key,
        )
        if tracing.ENABLED:
            tracing.inc("fair_preemption_rounds_total",
                        {"strategy": strategies[0]})
        if not fits and len(strategies) > 1:
            fits, targets = _run_second_strategy(ctx, retry, targets, Target,
                                                 candidates_ordering_key)
            if tracing.ENABLED:
                tracing.inc("fair_preemption_rounds_total",
                            {"strategy": strategies[1]})
    finally:
        revert_sim()

    if not fits:
        for t in targets:
            ctx.snapshot.add_workload(t.info)
        return []
    targets = _fill_back_fair(ctx, targets)
    for t in targets:
        ctx.snapshot.add_workload(t.info)
    return targets


def _find_candidates(ctx, satisfies_policy, uses_frs) -> List[WorkloadInfo]:
    """reference preemption.go:592 findCandidates."""
    cq = ctx.preemptor_cq
    out: List[WorkloadInfo] = []
    p = cq.spec.preemption
    if p.within_cluster_queue != PreemptionPolicy.NEVER:
        for wl in cq.workloads.values():
            if satisfies_policy(ctx.preemptor, wl, p.within_cluster_queue) and \
                    uses_frs(wl, ctx.frs_need_preemption):
                out.append(wl)
    if cq.has_parent() and p.reclaim_within_cohort != PreemptionPolicy.NEVER:
        root = cq.node.root()
        for other in ctx.snapshot.cqs_under_root(root):
            if other.name == cq.name:
                continue
            if not _cq_is_borrowing(other, ctx.frs_need_preemption):
                continue
            for wl in other.workloads.values():
                if satisfies_policy(ctx.preemptor, wl, p.reclaim_within_cohort) \
                        and uses_frs(wl, ctx.frs_need_preemption):
                    out.append(wl)
    return out


def _cq_is_borrowing(
    cq: ClusterQueueSnapshot, frs: Set[FlavorResource]
) -> bool:
    return cq.has_parent() and any(cq.borrowing(fr) for fr in frs)


class _DRSCache:
    """Memoizes dominant_resource_share per node between usage mutations:
    the tournament re-reads shares of untouched subtrees on every descent
    (ordering.go nextTarget), which dominates the fair path's cost."""

    def __init__(self) -> None:
        self._cache: Dict[int, DRS] = {}

    def get(self, node) -> DRS:
        hit = self._cache.get(id(node))
        if hit is None:
            if tracing.ENABLED:
                tracing.inc("solver_drs_cache_total", {"event": "miss"})
            hit = dominant_resource_share(node, {})
            self._cache[id(node)] = hit
        elif tracing.ENABLED:
            tracing.inc("solver_drs_cache_total", {"event": "hit"})
        return hit

    def invalidate(self) -> None:
        self._cache.clear()

    def invalidate_path(self, cq: ClusterQueueSnapshot) -> None:
        """A workload removal/addition on ``cq`` only mutates usage on
        its CQ→root path; DRS of every other node is untouched (it reads
        only the node's own usage plus static quota config)."""
        self._cache.pop(id(cq.node), None)
        for anc in cq.path_parent_to_root():
            self._cache.pop(id(anc), None)


class _Ordering:
    """TargetClusterQueueOrdering (ordering.go)."""

    def __init__(self, ctx, candidates: List[WorkloadInfo], ordering_key,
                 drs_cache: Optional[_DRSCache] = None):
        self.ctx = ctx
        self.preemptor_cq: ClusterQueueSnapshot = ctx.preemptor_cq
        # The key is a pure function of (workload, preemptor CQ, now) —
        # all fixed for this ordering's lifetime — so memoize it: the
        # tie-break in _next_target recomputes it per comparison.
        key_memo: Dict[str, object] = {}

        def memo_key(wl, cq_name, now):
            k = key_memo.get(wl.key)
            if k is None:
                k = ordering_key(wl, cq_name, now)
                key_memo[wl.key] = k
            return k

        self.ordering_key = memo_key
        self.drs = drs_cache or _DRSCache()
        self.preemptor_ancestors = set(
            id(n) for n in self.preemptor_cq.path_parent_to_root()
        )
        self.cq_to_targets: Dict[str, List[WorkloadInfo]] = {}
        for c in candidates:
            self.cq_to_targets.setdefault(c.cluster_queue, []).append(c)
        self.pruned_cqs: Set[str] = set()
        self.pruned_cohorts: Set[int] = set()

    def iterate(self):
        if not self.preemptor_cq.has_parent():
            while (
                self.preemptor_cq.name not in self.pruned_cqs
                and self.has_workload(self.preemptor_cq.name)
            ):
                yield self.preemptor_cq
            return
        root = self.preemptor_cq.node.root()
        while id(root) not in self.pruned_cohorts:
            target = self._next_target(root)
            if target is not None:
                yield target

    def has_workload(self, cq_name: str) -> bool:
        return bool(self.cq_to_targets.get(cq_name))

    def pop_workload(self, cq_name: str) -> WorkloadInfo:
        return self.cq_to_targets[cq_name].pop(0)

    def drop_queue(self, cq_name: str) -> None:
        self.pruned_cqs.add(cq_name)

    def _next_target(self, cohort: QuotaNode) -> Optional[ClusterQueueSnapshot]:
        """ordering.go nextTarget: descend to highest-DRS child."""
        cqs = self.ctx.snapshot.cluster_queues
        highest_cq: Optional[ClusterQueueSnapshot] = None
        highest_cq_drs = negative_drs()
        for child in cohort.children:
            if not child.is_cq:
                continue
            cq = cqs[child.name]
            if cq.name in self.pruned_cqs:
                continue
            drs = self.drs.get(child)
            if (not drs.borrowing and cq is not self.preemptor_cq) or \
                    not self.has_workload(cq.name):
                self.pruned_cqs.add(cq.name)
            elif compare_drs(drs, highest_cq_drs) == 0:
                new_wl = self.cq_to_targets[cq.name][0]
                cur_wl = self.cq_to_targets[highest_cq.name][0]
                if self.ordering_key(new_wl, self.preemptor_cq.name,
                                     self.ctx.now) < \
                        self.ordering_key(cur_wl, self.preemptor_cq.name,
                                          self.ctx.now):
                    highest_cq = cq
            elif compare_drs(drs, highest_cq_drs) > 0:
                highest_cq_drs = drs
                highest_cq = cq

        highest_cohort: Optional[QuotaNode] = None
        highest_cohort_drs = negative_drs()
        for child in cohort.children:
            if child.is_cq or id(child) in self.pruned_cohorts:
                continue
            drs = self.drs.get(child)
            on_path = id(child) in self.preemptor_ancestors
            if not drs.borrowing and not on_path:
                self.pruned_cohorts.add(id(child))
            elif compare_drs(drs, highest_cohort_drs) >= 0:
                highest_cohort_drs = drs
                highest_cohort = child

        if highest_cohort is None and highest_cq is None:
            self.pruned_cohorts.add(id(cohort))
            return None
        if compare_drs(highest_cohort_drs, highest_cq_drs) >= 0 and \
                highest_cohort is not None:
            return self._next_target(highest_cohort)
        return highest_cq


def _almost_lcas(ctx, target_cq: ClusterQueueSnapshot,
                 preemptor_ancestors: Set[int]) -> Tuple[QuotaNode, QuotaNode]:
    """least_common_ancestor.go: the two nodes just below the LCA."""
    lca = None
    for anc in target_cq.path_parent_to_root():
        if id(anc) in preemptor_ancestors:
            lca = anc
            break
    assert lca is not None, "no common ancestor"

    def almost(cq: ClusterQueueSnapshot) -> QuotaNode:
        a: QuotaNode = cq.node
        for anc in cq.path_parent_to_root():
            if anc is lca:
                return a
            a = anc
        raise AssertionError("no almostLCA")

    return almost(ctx.preemptor_cq), almost(target_cq)


def _workload_fits_fair(ctx) -> bool:
    """workloadFitsForFairSharing (preemption.go:649): the incoming usage was
    simulated in, so remove it for the fit check."""
    cq = ctx.preemptor_cq
    revert = cq.simulate_usage_removal(ctx.requests)
    try:
        for fr, v in ctx.requests.items():
            if v > cq.available(fr):
                return False
        if ctx.tas_fits is not None:
            return ctx.tas_fits()
        return True
    finally:
        revert()


def _run_first_strategy(ctx, candidates, strategy, Target, ordering_key):
    """reference preemption.go:381 runFirstFsStrategy."""
    ordering = _Ordering(ctx, candidates, ordering_key)
    targets: List = []
    retry: List[WorkloadInfo] = []
    drs = ordering.drs

    preemptor_within_nominal = (
        features.enabled("FairSharingPreemptWithinNominal")
        and _queue_within_nominal(ctx)
    )
    for cand_cq in ordering.iterate():
        if cand_cq is ctx.preemptor_cq:
            wl = ordering.pop_workload(cand_cq.name)
            ctx.snapshot.remove_workload(wl)
            drs.invalidate_path(cand_cq)
            targets.append(Target(wl, IN_CLUSTER_QUEUE_REASON))
            if _workload_fits_fair(ctx):
                return True, targets, retry
            continue

        if preemptor_within_nominal:
            wl = ordering.pop_workload(cand_cq.name)
            ctx.snapshot.remove_workload(wl)
            drs.invalidate_path(cand_cq)
            targets.append(Target(wl, IN_COHORT_RECLAMATION_REASON))
            if _workload_fits_fair(ctx):
                return True, targets, retry
            continue

        pre_alca, tgt_alca = _almost_lcas(
            ctx, cand_cq, ordering.preemptor_ancestors
        )
        preemptor_new = drs.get(pre_alca)
        target_old = drs.get(tgt_alca)
        removal_memo: Dict = {}
        while ordering.has_workload(cand_cq.name):
            wl = ordering.pop_workload(cand_cq.name)
            # Same-profile candidates (identical usage) yield the same
            # share-after-removal; memoize within this CQ visit.
            mkey = (id(tgt_alca), tuple(sorted(wl.usage().items())))
            target_new = removal_memo.get(mkey)
            if target_new is None:
                revert = cand_cq.simulate_usage_removal(wl.usage())
                target_new = dominant_resource_share(tgt_alca, {})
                revert()
                removal_memo[mkey] = target_new
            if strategy(preemptor_new, target_old, target_new):
                ctx.snapshot.remove_workload(wl)
                drs.invalidate_path(cand_cq)
                targets.append(Target(wl, IN_COHORT_FAIR_SHARING_REASON))
                if _workload_fits_fair(ctx):
                    return True, targets, retry
                break  # re-pick CQ: shares changed
            retry.append(wl)
    return False, targets, retry


def _run_second_strategy(ctx, retry_candidates, targets, Target, ordering_key):
    """reference preemption.go:460 runSecondFsStrategy (rule S2-b)."""
    ordering = _Ordering(ctx, retry_candidates, ordering_key)
    for cand_cq in ordering.iterate():
        pre_alca, tgt_alca = _almost_lcas(
            ctx, cand_cq, ordering.preemptor_ancestors
        )
        preemptor_new = dominant_resource_share(pre_alca, {})
        target_old = dominant_resource_share(tgt_alca, {})
        wl = ordering.pop_workload(cand_cq.name)
        if _strategy_s2b(preemptor_new, target_old, DRS()):
            ctx.snapshot.remove_workload(wl)
            ordering.drs.invalidate_path(cand_cq)
            targets.append(Target(wl, IN_COHORT_FAIR_SHARING_REASON))
            if _workload_fits_fair(ctx):
                return True, targets
        ordering.drop_queue(cand_cq.name)
    return False, targets


def _fill_back_fair(ctx, targets):
    """fillBackWorkloads with allowBorrowing=True. Runs after the incoming
    usage simulation was reverted, so it uses the plain fit check
    (reference preemption.go:539 calls fillBackWorkloads -> workloadFits)."""

    def plain_fits() -> bool:
        for fr, v in ctx.requests.items():
            if v > ctx.preemptor_cq.available(fr):
                return False
        if ctx.tas_fits is not None:
            return ctx.tas_fits()
        return True

    i = len(targets) - 2
    while i >= 0:
        ctx.snapshot.add_workload(targets[i].info)
        if plain_fits():
            targets[i] = targets[-1]
            targets.pop()
        else:
            ctx.snapshot.remove_workload(targets[i].info)
        i -= 1
    return targets


def _queue_within_nominal(ctx) -> bool:
    """preemption.go:673: usage at or below nominal for contested frs."""
    return not any(
        ctx.preemptor_cq.borrowing(fr) for fr in ctx.frs_need_preemption
    )
